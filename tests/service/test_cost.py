"""Per-tenant cost accounting: the _CostTracker fold (task-seconds, store
bytes, retry draw), stats_snapshot cost rows, the tenant_cost_* telemetry
series, /metrics exposition, the top COST panel, and the ~zero-cost
contract for cache hits."""

from __future__ import annotations

import time

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu import top
from cubed_tpu.observability.export import prometheus_text
from cubed_tpu.observability.timeseries import (
    TelemetrySampler,
    TimeSeriesStore,
)
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.service import ComputeService

AN = np.arange(64, dtype=np.float64).reshape(8, 8)


@pytest.fixture
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


def _build(spec, k):
    def kernel(x, _k=float(k)):
        return x + _k

    a = ct.from_array(AN, chunks=(4, 4), spec=spec)
    return ct.map_blocks(kernel, a, dtype=np.float64)


def _sleepy_build(spec, k, delay=0.05):
    def kernel(x, _k=float(k), _d=delay):
        time.sleep(_d)
        return x + _k

    a = ct.from_array(AN, chunks=(4, 4), spec=spec)
    return ct.map_blocks(kernel, a, dtype=np.float64)


def test_request_and_tenant_cost_fold(spec):
    with ComputeService(
        executor=AsyncPythonDagExecutor(), tenants={"gold": 2.0},
        plan_cache=False, result_cache=False,
    ) as svc:
        h = svc.submit(_sleepy_build(spec, 1.0), tenant="gold")
        np.testing.assert_array_equal(h.result(120), AN + 1.0)
        cost = h.cost
        assert cost is not None
        # 4 chunks x 50ms sleep, measured where the tasks ran
        assert cost["task_seconds"] >= 4 * 0.05 * 0.8
        assert cost["bytes_written"] >= AN.nbytes
        assert cost["retries"] == 0
        row = svc.stats_snapshot()["tenants"]["gold"]["cost"]
        assert row["task_seconds"] == pytest.approx(
            cost["task_seconds"], abs=1e-6
        )
        assert row["bytes_written"] == cost["bytes_written"]


def test_cost_accumulates_per_tenant_and_isolates(spec):
    with ComputeService(
        executor=AsyncPythonDagExecutor(),
        tenants={"gold": 2.0, "free": 1.0},
        plan_cache=False, result_cache=False,
    ) as svc:
        for i in range(2):
            h = svc.submit(_build(spec, float(i)), tenant="gold")
            np.testing.assert_array_equal(h.result(120), AN + float(i))
        snap = svc.stats_snapshot()["tenants"]
        assert snap["gold"]["cost"]["task_seconds"] > 0
        assert snap["gold"]["cost"]["bytes_written"] >= 2 * AN.nbytes
        # the free tenant never ran anything: zero cost
        assert snap["free"]["cost"]["task_seconds"] == 0
        assert snap["free"]["cost"]["bytes_written"] == 0


def test_result_cache_hit_costs_nothing(spec):
    with ComputeService(
        executor=AsyncPythonDagExecutor(), tenants={"gold": 2.0},
    ) as svc:
        arr = _build(spec, 7.0)
        h1 = svc.submit(arr, tenant="gold")
        np.testing.assert_array_equal(h1.result(120), AN + 7.0)
        spent = svc.stats_snapshot()["tenants"]["gold"]["cost"]
        h2 = svc.submit(_build(spec, 7.0), tenant="gold")
        np.testing.assert_array_equal(h2.result(120), AN + 7.0)
        assert h2.result_cache_hit
        assert h2.cost is None  # a cached answer consumed ~nothing
        after = svc.stats_snapshot()["tenants"]["gold"]["cost"]
        assert after == spent  # the tenant's bill did not move


def test_failed_request_still_folds_cost(spec):
    def boom(x):
        raise ValueError("kernel exploded")

    a = ct.from_array(AN, chunks=(4, 4), spec=spec)
    bad = ct.map_blocks(boom, a, dtype=np.float64)
    with ComputeService(
        executor=AsyncPythonDagExecutor(retries=0),
        tenants={"gold": 2.0}, plan_cache=False, result_cache=False,
    ) as svc:
        h = svc.submit(bad, tenant="gold")
        with pytest.raises(ValueError):
            h.result(120)
        # the fleet's time was spent either way: the fold happened
        assert h.cost is not None
        row = svc.stats_snapshot()["tenants"]["gold"]["cost"]
        assert row is not None


def test_sampler_records_tenant_cost_series_and_metrics(spec):
    with ComputeService(
        executor=AsyncPythonDagExecutor(), tenants={"gold": 2.0},
        plan_cache=False, result_cache=False,
    ) as svc:
        h = svc.submit(_sleepy_build(spec, 1.0), tenant="gold")
        np.testing.assert_array_equal(h.result(120), AN + 1.0)
        store = TimeSeriesStore()
        TelemetrySampler(store).sample_once()
        labels = {"tenant": "gold"}
        secs = store.latest("tenant_cost_task_seconds", labels=labels)
        assert secs is not None and secs > 0
        assert store.latest(
            "tenant_cost_bytes_written", labels=labels
        ) >= AN.nbytes
        assert store.latest("tenant_cost_retries", labels=labels) == 0
        text = prometheus_text(store=store)
        assert (
            'cubed_tpu_tenant_cost_task_seconds{tenant="gold"}' in text
        )
        assert (
            'cubed_tpu_tenant_cost_bytes_written{tenant="gold"}' in text
        )


def test_top_cost_panel_renders(spec):
    with ComputeService(
        executor=AsyncPythonDagExecutor(), tenants={"gold": 2.0},
        plan_cache=False, result_cache=False,
    ) as svc:
        h = svc.submit(_build(spec, 1.0), tenant="gold")
        np.testing.assert_array_equal(h.result(120), AN + 1.0)
        frame = top.render({
            "ts": time.time(), "fleet": {}, "metrics": {},
            "service": svc.stats_snapshot(), "computes": [], "alerts": [],
        })
    assert "COST" in frame
    assert "TASK-SEC" in frame
    assert "gold" in frame
