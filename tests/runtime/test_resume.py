"""Resume semantics: chunk-granular skips, checksum-trustworthy restarts,
plan introspection consistency (``num_tasks``/``max_projected_mem`` under
``resume=True`` match what executors actually run), corrupt-metadata
tolerance, and interaction with speculative backups.
"""

from __future__ import annotations

import glob
import os

import numpy as np

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor

from ..utils import TaskCounter


def _output_store(tmp_path) -> str:
    """The single materialized store of a one-op plan under tmp_path."""
    stores = sorted(
        os.path.dirname(p) for p in glob.glob(f"{tmp_path}/*/*.zarr/.zarray")
    )
    assert len(stores) == 1, stores
    return stores[0]


def _chunk_files(store: str) -> list[str]:
    return sorted(
        n
        for n in os.listdir(store)
        if not n.startswith(".")
        and not n.endswith(".tmp")
        and all(p.lstrip("-").isdigit() for p in n.split("."))
    )


def _flip_byte(path: str, offset: int = 0) -> None:
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        data[offset] ^= 0xFF
        f.seek(0)
        f.write(data)


def test_resume_is_chunk_granular(spec, tmp_path):
    """Resuming an op with 24/25 valid chunks re-runs 1 task, not 25."""
    an = np.arange(100.0).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    np.testing.assert_array_equal(b.compute(optimize_graph=False), an + 1.0)
    store = _output_store(spec.work_dir)
    os.unlink(os.path.join(store, "3.3"))

    before = get_registry().snapshot()
    counter = TaskCounter()
    res = b.compute(optimize_graph=False, resume=True, callbacks=[counter])
    np.testing.assert_array_equal(res, an + 1.0)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("tasks_skipped_resume") == 24
    # create-arrays (1 task) + exactly the one missing-chunk task
    assert counter.value == 2


def test_resume_distrusts_corrupt_chunk(spec):
    """A bit-flipped chunk fails its checksum: resume quarantines it and
    re-runs exactly its producing task — existence is not integrity."""
    an = np.arange(100.0).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    b.compute(optimize_graph=False)
    store = _output_store(spec.work_dir)
    _flip_byte(os.path.join(store, "0.1"), offset=4)

    before = get_registry().snapshot()
    res = b.compute(optimize_graph=False, resume=True)
    np.testing.assert_array_equal(res, an + 1.0)  # bitwise-repaired
    delta = get_registry().snapshot_delta(before)
    assert delta.get("chunks_corrupt_detected") == 1
    assert delta.get("chunks_quarantined") == 1
    assert delta.get("tasks_skipped_resume") == 24
    assert [n for n in os.listdir(store) if n.startswith("0.1.quarantine.")]


def test_num_tasks_resume_matches_executed_tasks(spec):
    """Plan introspection under resume agrees with what executors run:
    ``num_tasks(resume=True)`` counts create-arrays plus only the pending
    chunk tasks, and the resumed compute fires exactly that many."""
    an = np.arange(64.0).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 16 chunks
    b = xp.add(a, 1.0)
    plan = b.plan
    full = plan.num_tasks(optimize_graph=False)
    b.compute(optimize_graph=False)
    store = _output_store(spec.work_dir)
    for name in ("0.0", "1.2", "3.3"):
        os.unlink(os.path.join(store, name))

    pending = plan.num_tasks(optimize_graph=False, resume=True)
    assert pending == full - 13  # 16 - 3 pending chunk tasks were skipped
    counter = TaskCounter()
    b.compute(optimize_graph=False, resume=True, callbacks=[counter])
    assert counter.value == pending


def test_num_tasks_resume_complete_plan(spec):
    an = np.arange(16.0).reshape(4, 4)
    b = xp.add(ct.from_array(an, chunks=(2, 2), spec=spec), 1.0)
    plan = b.plan
    b.compute(optimize_graph=False)
    # fully valid: only the (idempotent) create-arrays op remains
    assert plan.num_tasks(optimize_graph=False, resume=True) == 1


def test_max_projected_mem_resume_consistent(spec):
    """An op whose outputs are fully valid drops out of the projected-mem
    scan, exactly as the executors skip it; a partially-valid op stays."""
    an = np.arange(64.0).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    plan = b.plan
    full_mem = plan.max_projected_mem(optimize_graph=False)
    assert full_mem > 0
    b.compute(optimize_graph=False)
    assert plan.max_projected_mem(optimize_graph=False, resume=True) == 0
    store = _output_store(spec.work_dir)
    os.unlink(os.path.join(store, "0.0"))
    # one missing chunk: the op is pending again, with its full footprint
    assert plan.max_projected_mem(optimize_graph=False, resume=True) == full_mem


def test_resume_tolerates_corrupt_zarray(spec):
    """Regression: a corrupt/truncated .zarray used to crash the resume
    scan (only FileNotFoundError was caught). Now the op is treated as
    not-computed, the metadata is recreated, and the compute succeeds."""
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    b.compute(optimize_graph=False)
    store = _output_store(spec.work_dir)
    with open(os.path.join(store, ".zarray"), "wb") as f:
        f.write(b'{"zarr_format": 2, "shape": [6,')  # truncated JSON

    res = b.compute(optimize_graph=False, resume=True)
    np.testing.assert_array_equal(res, an + 1.0)
    assert [n for n in os.listdir(store) if n.startswith(".zarray.quarantine.")]


def test_resume_tolerates_corrupt_manifest(spec):
    """Garbage manifest JSON demotes its chunks to untrusted (they re-run)
    without crashing the scan or poisoning the result."""
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    b.compute(optimize_graph=False)
    store = _output_store(spec.work_dir)
    shard = next(n for n in os.listdir(store) if n.startswith(".manifest-"))
    with open(os.path.join(store, shard), "wb") as f:
        f.write(b"\xff\xfenot json")

    before = get_registry().snapshot()
    res = b.compute(optimize_graph=False, resume=True)
    np.testing.assert_array_equal(res, an + 1.0)
    delta = get_registry().snapshot_delta(before)
    # nothing trustworthy -> every chunk task re-ran
    assert delta.get("tasks_skipped_resume", 0) == 0


def test_resume_integrity_off_is_existence_only(spec):
    """``integrity="off"`` restores the pre-integrity resume: no byte
    verification, no quarantining — a present-but-corrupt chunk is trusted
    (the documented trade of turning the feature off)."""
    from cubed_tpu.storage import integrity

    an = np.arange(100.0).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    b.compute(optimize_graph=False)
    store = _output_store(spec.work_dir)
    _flip_byte(os.path.join(store, "0.1"), offset=4)

    before = get_registry().snapshot()
    with integrity.scoped("off"):
        b.compute(optimize_graph=False, resume=True)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("chunks_verified", 0) == 0
    assert delta.get("chunks_corrupt_detected", 0) == 0
    assert delta.get("chunks_quarantined", 0) == 0
    # all chunks "present" -> the whole op is skipped (create-arrays only)
    assert delta.get("tasks_started") == 1
    assert not [n for n in os.listdir(store) if "quarantine" in n]


def test_plan_introspection_is_metrics_silent(spec):
    """num_tasks/max_projected_mem(resume=True) must not skew the
    execution counters (chunks_verified etc.) they'd otherwise double."""
    an = np.arange(36.0).reshape(6, 6)
    b = xp.add(ct.from_array(an, chunks=(2, 2), spec=spec), 1.0)
    b.compute(optimize_graph=False)
    before = get_registry().snapshot()
    b.plan.num_tasks(optimize_graph=False, resume=True)
    b.plan.max_projected_mem(optimize_graph=False, resume=True)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("chunks_verified", 0) == 0
    assert delta.get("tasks_skipped_resume", 0) == 0


def test_resume_with_speculative_backups(spec):
    """Chunk-granular resume composes with speculative backups: duplicate
    twins re-writing identical bytes keep manifests consistent and the
    resumed result bitwise-correct."""
    an = np.arange(100.0).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    ex = AsyncPythonDagExecutor(use_backups=True)
    b.compute(optimize_graph=False, executor=ex)
    store = _output_store(spec.work_dir)
    for name in _chunk_files(store)[:5]:
        os.unlink(os.path.join(store, name))

    before = get_registry().snapshot()
    res = b.compute(optimize_graph=False, resume=True, executor=ex)
    np.testing.assert_array_equal(res, an + 1.0)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("tasks_skipped_resume") == 20


def test_multioutput_resume_skips_per_task(spec):
    """Multi-output ops skip a task only when EVERY output array holds a
    valid chunk for it; losing one side output's chunk re-runs exactly that
    task (not the whole op, not zero tasks)."""
    from cubed_tpu.core.ops import general_blockwise
    from cubed_tpu.runtime.executors.python import PythonDagExecutor

    an = np.arange(12, dtype=np.float64)
    a = ct.from_array(an, chunks=(4,), spec=spec)

    def two(chunk):
        return chunk + 1.0, (chunk * 2.0).astype(np.float64)

    def block_function(out_key):
        return ((a.name, *out_key[1:]),)

    p, d = general_blockwise(
        two, block_function, a,
        shape=a.shape, dtype=[a.dtype, np.dtype(np.float64)],
        chunks=a.chunks, op_name="two_out",
    )
    ex = PythonDagExecutor()
    np.testing.assert_array_equal(np.asarray(p.compute(executor=ex)), an + 1.0)
    np.testing.assert_array_equal(np.asarray(d.compute(executor=ex)), an * 2.0)
    # drop ONE chunk of the SECONDARY output: that task alone re-runs
    os.unlink(os.path.join(str(d.zarray_maybe_lazy.store), "1"))
    before = get_registry().snapshot()
    np.testing.assert_array_equal(
        np.asarray(d.compute(executor=ex, resume=True)), an * 2.0
    )
    delta = get_registry().snapshot_delta(before)
    assert delta.get("tasks_skipped_resume") == 2  # 3 tasks, 1 pending
