"""Distributed (multi-host control plane) executor tests.

Exercises the real network path end to end: a TCP coordinator in this
process, worker subprocesses connecting over localhost, chunk data through
the shared store — the single-host simulation of the reference's fleet
executors (SURVEY §2.4), plus the fault-tolerance contract (worker loss →
resubmission, duplicate results dropped).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.runtime.distributed import (
    Coordinator,
    NoWorkersError,
    WorkerLostError,
)
from cubed_tpu.runtime.executors.distributed import (
    DistributedDagExecutor,
    _worker_env,
)

from ..utils import TaskCounter


@pytest.fixture()
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


@pytest.fixture()
def fleet():
    ex = DistributedDagExecutor(n_local_workers=2, worker_threads=2)
    try:
        yield ex
    finally:
        ex.close()


def test_distributed_end_to_end(spec, fleet):
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    b = ct.from_array(an, chunks=(4, 4), spec=spec)
    counter = TaskCounter()
    result = xp.sum(xp.add(a, b)).compute(executor=fleet, callbacks=[counter])
    assert np.allclose(float(result), (an + an).sum())
    assert counter.value > 0


def test_distributed_fused_closures(spec, fleet):
    # optimizer-fused closures are the hardest payloads to ship
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    r = xp.mean(xp.add(xp.multiply(a, 2.0), a))
    result = r.compute(executor=fleet)
    assert np.allclose(float(result), (an * 2.0 + an).mean())


def test_distributed_reused_across_computes_and_blob_cache(spec, fleet):
    an = np.ones((8, 8), dtype=np.float64)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    r1 = float(xp.sum(a).compute(executor=fleet))
    sent_after_first = fleet._coordinator.stats["blobs_sent"]
    # same fleet serves a second plan; new ops ship new blobs, but each
    # (op, worker) pair ships its blob at most once
    r2 = float(xp.sum(xp.add(a, a)).compute(executor=fleet))
    assert r1 == an.sum() and r2 == 2 * an.sum()
    stats = fleet._coordinator.stats
    assert stats["tasks_sent"] >= stats["blobs_sent"]
    assert sent_after_first >= 1
    # a blob is sent at most once per (op, worker): with 2 workers each op
    # contributes at most 2 blob sends even though it has many tasks
    assert stats["blobs_sent"] <= 2 * stats["tasks_sent"]


def test_distributed_generation_parallelism(spec):
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    with DistributedDagExecutor(
        n_local_workers=2, compute_arrays_in_parallel=True
    ) as ex:
        a = ct.from_array(an, chunks=(4, 4), spec=spec)
        b = ct.from_array(2 * an, chunks=(4, 4), spec=spec)
        result = xp.sum(xp.add(a, b)).compute(executor=ex)
    assert np.allclose(float(result), (an + 2 * an).sum())


def test_distributed_survives_worker_kill(spec):
    """SIGKILL one of the workers mid-plan: its in-flight tasks fail with
    WorkerLostError, map_unordered resubmits to the survivor, and the result
    is still correct (idempotent whole-chunk writes)."""
    ex = DistributedDagExecutor(n_local_workers=2, retries=3)
    try:
        ex._ensure_fleet()
        an = np.arange(400, dtype=np.float64).reshape(20, 20)
        a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 100 tasks per op

        victim = ex._procs[0]
        killer_fired = {}

        class KillOnFirstTask:
            def on_compute_start(self, event):
                pass

            def on_operation_start(self, event):
                pass

            def on_compute_end(self, event):
                pass

            def on_task_end(self, event):
                if not killer_fired:
                    killer_fired["t"] = time.time()
                    os.kill(victim.pid, signal.SIGKILL)

        result = xp.sum(xp.add(a, a)).compute(
            executor=ex, callbacks=[KillOnFirstTask()]
        )
        assert np.allclose(float(result), 2 * an.sum())
        assert killer_fired, "kill callback never fired"
        assert ex._coordinator.n_workers == 1
    finally:
        ex.close()


def test_distributed_all_workers_dead_raises(spec):
    ex = DistributedDagExecutor(n_local_workers=1, retries=1)
    try:
        ex._ensure_fleet()
        for p in ex._procs:
            os.kill(p.pid, signal.SIGKILL)
        deadline = time.time() + 10
        while ex._coordinator.n_workers > 0 and time.time() < deadline:
            time.sleep(0.05)
        an = np.ones((4, 4), dtype=np.float64)
        a = ct.from_array(an, chunks=(2, 2), spec=spec)
        with pytest.raises((NoWorkersError, WorkerLostError)):
            xp.sum(a).compute(executor=ex)
    finally:
        ex.close()


def test_distributed_remote_exception_propagates(spec, fleet):
    from cubed_tpu.runtime.distributed import RemoteTaskError

    a = ct.from_array(np.ones((4, 4)), chunks=(2, 2), spec=spec)

    def boom(x):
        raise ValueError("task failed on purpose")

    r = ct.map_blocks(boom, a, dtype=np.float64)
    with pytest.raises(RemoteTaskError, match="task failed on purpose"):
        r.compute(executor=fleet, retries=0)


def _raise_on_load():
    raise ModuleNotFoundError("dependency missing on worker host")


class _UnloadableOnWorker:
    """Pickles fine, explodes when deserialized — models client/worker
    environment skew (a closure dependency missing on the worker)."""

    def __reduce__(self):
        return (_raise_on_load, ())


def test_distributed_undeserializable_blob_fails_task_not_worker(spec, fleet):
    """An op blob that can't be deserialized on the worker must surface as a
    task error (RemoteTaskError with the real traceback), not kill the
    worker process and cascade into WorkerLostError/NoWorkersError."""
    from cubed_tpu.runtime.distributed import RemoteTaskError

    a = ct.from_array(np.ones((4, 4)), chunks=(2, 2), spec=spec)
    poison = _UnloadableOnWorker()

    def needs_missing_dep(x):
        return x + (0.0 if poison is None else 0.0)

    r = ct.map_blocks(needs_missing_dep, a, dtype=np.float64)
    with pytest.raises(RemoteTaskError, match="dependency missing"):
        r.compute(executor=fleet, retries=0)
    # the fleet survived: both workers still serve tasks
    assert fleet._coordinator.n_workers == 2
    ok = float(xp.sum(a).compute(executor=fleet))
    assert ok == 16.0


def test_distributed_by_name(tmp_path):
    """Registry path: Spec(executor_name='distributed') builds the fleet."""
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        executor_name="distributed",
        executor_options=dict(n_local_workers=2),
    )
    a = ct.from_array(np.ones((6, 6)), chunks=(3, 3), spec=spec)
    try:
        assert float(xp.sum(a).compute()) == 36.0
    finally:
        spec.executor.close()


def test_distributed_out_of_band_worker(spec):
    """The real multi-host path: a fixed listen address and a worker started
    by hand (as it would be on another host), no local spawning."""
    ex = DistributedDagExecutor(
        listen="127.0.0.1:0", n_local_workers=0, min_workers=1,
        worker_start_timeout=30,
    )
    proc = None
    try:
        # _ensure_fleet binds, then blocks until min_workers join; run it on
        # a thread and start the worker once the bound address is known
        import threading

        err = {}

        def start():
            try:
                ex._ensure_fleet()
            except Exception as e:  # pragma: no cover - surfaced below
                err["e"] = e

        t = threading.Thread(target=start)
        t.start()
        deadline = time.time() + 15
        while ex.coordinator_address is None and time.time() < deadline:
            time.sleep(0.05)
        assert ex.coordinator_address is not None
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "cubed_tpu.runtime.worker",
                ex.coordinator_address, "--threads", "2", "--name", "hostB",
            ],
            env=_worker_env(),
        )
        t.join(timeout=30)
        assert not err, err
        an = np.arange(36, dtype=np.float64).reshape(6, 6)
        a = ct.from_array(an, chunks=(3, 3), spec=spec)
        result = xp.sum(xp.multiply(a, 3.0)).compute(executor=ex)
        assert np.allclose(float(result), 3 * an.sum())
    finally:
        ex.close()
        if proc is not None:
            proc.wait(timeout=10)


class _FleetFaultTask:
    """Picklable fault-injection task for the fabric (shared harness)."""

    def __init__(self, path, timing_map):
        self.path = path
        self.timing_map = timing_map

    def __call__(self, i):
        from .utils import deterministic_failure

        return deterministic_failure(self.path, self.timing_map, i)


def test_distributed_task_timeout_reroutes(tmp_path):
    """A task stuck on a hung worker times out at the coordinator and the
    retry succeeds (fresh invocation returns immediately); the worker that
    kept timing out is evicted as hung."""
    from cubed_tpu.runtime.distributed import TaskTimeoutError  # noqa: F401
    from cubed_tpu.runtime.executors.python_async import map_unordered

    path = tmp_path / "counts"
    path.mkdir()
    # input 0: first invocation sleeps 60s (far past the timeout), second
    # invocation (the rerouted retry) succeeds immediately. The timeout must
    # sit above the worker's first-task cold cost (decoding the blob imports
    # this test module and with it jax) — the started-ack protects against
    # hang-eviction during cold start, but attempts still burn.
    timing_map = {0: [60000]}
    ex = DistributedDagExecutor(
        n_local_workers=2, task_timeout=8.0, retries=2, use_backups=False,
    )
    try:
        coord = ex._ensure_fleet()
        map_unordered(
            _CoordPool(coord),
            _FleetFaultTask(str(path), timing_map),
            list(range(3)),
            retries=2,
            use_backups=False,
        )
        from .utils import read_int_from_file

        assert read_int_from_file(str(path / "0")) == 2  # timed out once
        assert coord.stats["task_timeouts"] >= 1
    finally:
        ex.close()


class _CoordPool:
    def __init__(self, coordinator):
        self.coordinator = coordinator

    def submit(self, stats_wrapper, function, task_input, **kwargs):
        return self.coordinator.submit(stats_wrapper, function, task_input, **kwargs)


@pytest.mark.slow
def test_distributed_hung_threads_avoided(tmp_path):
    """Started-task timeouts leave ghost threads; routing counts them so
    retries land on workers with free capacity and the map completes.

    Slow-marked (~26 s of real timeout waits on one core); the default
    suite keeps test_distributed_task_timeout_reroutes as the
    timeout-path coverage."""
    from cubed_tpu.runtime.executors.python_async import map_unordered

    path = tmp_path / "counts"
    path.mkdir()
    # two poisoned inputs: each sleeps forever on first invocation
    timing_map = {0: [60000], 1: [60000]}
    ex = DistributedDagExecutor(
        n_local_workers=2, worker_threads=2, task_timeout=1.0, retries=3,
        use_backups=False,
    )
    try:
        coord = ex._ensure_fleet()
        map_unordered(
            _CoordPool(coord),
            _FleetFaultTask(str(path), timing_map),
            list(range(6)),
            retries=3,
            use_backups=False,
        )
        # all 6 inputs completed despite two hung tasks
        from .utils import read_int_from_file

        total = sum(read_int_from_file(str(path / str(i))) for i in range(6))
        assert total >= 8  # 6 firsts + 2 retries
        assert coord.stats["task_timeouts"] >= 2
    finally:
        ex.close()


@pytest.mark.slow
def test_distributed_hung_worker_evicted(tmp_path):
    """A worker whose started tasks keep timing out is dropped as hung; with
    no survivors the plan fails loudly instead of spinning.

    Slow-marked (~21 s of real timeout waits on one core); default-suite
    timeout coverage lives in test_distributed_task_timeout_reroutes."""
    from cubed_tpu.runtime.distributed import (
        NoWorkersError,
        TaskTimeoutError,
        WorkerLostError,
    )
    from cubed_tpu.runtime.executors.python_async import map_unordered

    path = tmp_path / "counts"
    path.mkdir()
    timing_map = {0: [120000, 120000, 120000, 120000],
                  1: [120000, 120000, 120000, 120000]}
    ex = DistributedDagExecutor(
        n_local_workers=1, worker_threads=2, task_timeout=8.0, retries=3,
        use_backups=False,
    )
    try:
        coord = ex._ensure_fleet()
        with pytest.raises((TaskTimeoutError, WorkerLostError, NoWorkersError)):
            map_unordered(
                _CoordPool(coord),
                _FleetFaultTask(str(path), timing_map),
                list(range(2)),
                retries=3,
                use_backups=False,
            )
        deadline = time.time() + 10
        while coord.n_workers > 0 and time.time() < deadline:
            time.sleep(0.1)
        assert coord.n_workers == 0  # evicted as hung
    finally:
        ex.close()


def test_distributed_resume_after_fleet_failure(spec):
    """Checkpoint/resume across fleet restarts: a plan that dies mid-way
    (all workers killed) resumes on a FRESH fleet, skipping ops whose
    persistent targets are fully initialized — the multi-host recovery
    story (docs/multihost.md 'Resume / checkpoint')."""
    an = np.arange(144, dtype=np.float64).reshape(12, 12)
    a = ct.from_array(an, chunks=(3, 3), spec=spec)
    b = xp.add(a, 1.0)
    c = xp.add(b, 1.0)

    ex1 = DistributedDagExecutor(n_local_workers=1, retries=0)
    kill_state = {}

    class KillFleetMidway:
        def on_task_end(self, event):
            kill_state["seen"] = kill_state.get("seen", 0) + 1
            # event layout: 2 create-arrays + 16 op-b tasks end at event 18;
            # firing at 20 (two op-c tasks in) guarantees b's target is
            # FULLY initialized and therefore resumable
            if kill_state["seen"] == 20:
                for p in ex1._procs:
                    os.kill(p.pid, signal.SIGKILL)

    try:
        with pytest.raises(Exception):
            c.compute(
                executor=ex1, callbacks=[KillFleetMidway()],
                optimize_graph=False,
            )
    finally:
        ex1.close()
    assert kill_state.get("seen", 0) >= 20

    # fresh fleet; resume skips whatever already hit the shared store
    counter = TaskCounter()
    with DistributedDagExecutor(n_local_workers=2) as ex2:
        result = c.compute(
            executor=ex2, callbacks=[counter], optimize_graph=False,
            resume=True,
        )
    np.testing.assert_array_equal(result, an + 2.0)
    # op b (16 tasks) must have been skipped: fewer events than a full run
    assert counter.value < 32, counter.value


def test_distributed_blob_eviction_self_heals(spec, monkeypatch):
    """With the worker's decoded-blob LRU capped at 1, every new op evicts
    the previous one; the ``blob_dropped`` notification makes the
    coordinator re-ship bytes, so plans reusing earlier ops still succeed
    (the bounded caches are invisible to correctness)."""
    monkeypatch.setenv("CUBED_TPU_WORKER_BLOB_CAP", "1")
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    with DistributedDagExecutor(n_local_workers=1, worker_threads=1) as ex:
        a = ct.from_array(an, chunks=(4, 4), spec=spec)
        r1 = float(xp.sum(a).compute(executor=ex))
        # distinct ops across several plans cycle the cap-1 cache hard
        r2 = float(xp.sum(xp.add(a, 1.0)).compute(executor=ex))
        r3 = float(xp.mean(xp.multiply(a, 2.0)).compute(executor=ex))
        # and the first plan's shape again, after its blobs were evicted
        r4 = float(xp.sum(a).compute(executor=ex))
    assert r1 == r4 == an.sum()
    assert r2 == (an + 1.0).sum()
    assert np.isclose(r3, (an * 2.0).mean())


from ..utils import SlowAdd as _SlowAdd  # noqa: E402


def test_distributed_graceful_drain_requeues_free(spec, tmp_path):
    """Graceful scale-down contract: draining a worker mid-compute never
    loses a completed chunk (the result stays bitwise-correct), abandoned
    in-flight/queued tasks requeue WITHOUT drawing the user-visible retry
    budget, and the drain is observable in ``stats_snapshot()`` and in the
    exported trace."""
    import json

    from cubed_tpu.observability import get_registry
    from cubed_tpu.observability.collect import TraceCollector

    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    ex = DistributedDagExecutor(n_local_workers=2)
    before = get_registry().snapshot()
    try:
        coord = ex._ensure_fleet()
        a = ct.from_array(an, chunks=(4, 4), spec=spec)  # 16 slow tasks
        r = ct.map_blocks(_SlowAdd(0.4), a, dtype=np.float64)

        seen = {"n": 0}

        class DrainMidOp:
            def on_task_end(self, event):
                seen["n"] += 1
                if seen["n"] == 3:  # create-array + 2 slow tasks: mid-op
                    coord.request_drain(
                        "local-0", grace_s=0.05, reason="scale_down"
                    )

        collector = TraceCollector(trace_dir=str(tmp_path))
        result = r.compute(executor=ex, callbacks=[DrainMidOp(), collector])
        np.testing.assert_array_equal(result, an + 1.0)  # nothing lost

        snap = coord.stats_snapshot()
        assert snap["drains_completed"] == 1, snap
        assert snap["tasks_abandoned_on_drain"] >= 1, snap
        assert snap["workers_lost"] == 0, snap  # a drain is not a loss
        row = snap["workers"]["local-0"]
        assert row["drained"] is True and "drained" in row["reason"], row
        delta = get_registry().snapshot_delta(before)
        # abandoned tasks rerouted free: requeues, not budget-drawing retries
        assert delta.get("worker_loss_requeues", 0) >= 1, delta
        assert delta.get("task_retries", 0) == 0, delta
        assert delta.get("drains_completed", 0) == 1, delta
        # ...and the drain decisions landed in the exported merged trace
        with open(collector.trace_path) as f:
            trace = f.read()
        assert "worker_drain_requested" in trace
        assert "worker_drained" in trace
        json.loads(trace)  # still a valid Perfetto/Chrome trace
    finally:
        ex.close()


def test_wait_for_workers_races_late_autoscaler_worker():
    """``wait_for_workers`` blocking on the joined-condition must be woken
    by workers the AUTOSCALER spawns (not only by the executor's initial
    spawn loop) — the late-arrival race a backfill always creates."""
    import threading as _threading

    from cubed_tpu.runtime.autoscale import (
        Autoscaler,
        AutoscalePolicy,
        WorkerFactory,
    )
    from cubed_tpu.runtime.distributed import run_worker

    coord = Coordinator("127.0.0.1", 0)
    host, port = coord.address

    class ThreadWorkerFactory(WorkerFactory):
        """In-process workers over the real socket path (fast: no
        subprocess boot); SIGTERM spot semantics are simply absent off the
        main thread, which run_worker tolerates."""

        def __init__(self):
            self.n = 0

        def start_worker(self):
            name = f"t-{self.n}"
            self.n += 1
            _threading.Thread(
                target=run_worker, args=(f"{host}:{port}",),
                kwargs=dict(nthreads=1, name=name), daemon=True,
            ).start()
            return name

        def stop_worker(self, name):
            pass

    scaler = Autoscaler(
        coord, factory=ThreadWorkerFactory(),
        policy=AutoscalePolicy(min_workers=2, max_workers=2, interval_s=0.05),
        initial_workers=2,
    )
    try:
        scaler.start()  # begins backfilling toward desired=2 immediately
        coord.wait_for_workers(2, timeout=30)  # woken by the late arrivals
        assert coord.n_workers == 2
        assert scaler.stats["workers_scaled_up"] == 2
        # the registered workers settle the pending-spawn bookkeeping: no
        # further spawns on subsequent ticks
        time.sleep(0.3)
        assert scaler.stats["workers_scaled_up"] == 2
    finally:
        scaler.stop()
        coord.close()


def test_close_during_drain_and_exit_probe_after_replacement(spec):
    """Satellite: ``close()`` while a drain is in progress leaves no
    orphaned local worker subprocess, and ``_procs`` bookkeeping stays
    exit-probe-correct after the autoscaler replaces a crashed worker."""
    from cubed_tpu.runtime.autoscale import AutoscalePolicy

    ex = DistributedDagExecutor(
        n_local_workers=2,
        autoscale_policy=AutoscalePolicy(
            min_workers=2, max_workers=3, interval_s=0.1,
            idle_rounds_before_down=10**6, cooldown_down_s=3600,
        ),
    )
    try:
        coord = ex._ensure_fleet()
        # crash local-0: the autoscaler must backfill local-2
        os.kill(ex._procs[0].pid, signal.SIGKILL)
        deadline = time.time() + 30
        while time.time() < deadline:
            names = {
                n for n, row in coord.stats_snapshot()["workers"].items()
                if row.get("alive")
            }
            if "local-2" in names and coord.n_workers >= 2:
                break
            time.sleep(0.1)
        assert "local-2" in names, names
        # exit-probe-correct after the replacement: local-<i> is _procs[i]
        assert len(ex._procs) == 3
        assert ex._local_worker_exitcode("local-0") == -signal.SIGKILL
        assert ex._local_worker_exitcode("local-2") is None  # still running
        # put a slow task in flight, then drain with a grace far longer
        # than close() is willing to wait
        fut = coord.submit(None, _SlowAdd(5.0), 1.0)
        time.sleep(0.3)
        assert coord.request_drain("local-1", grace_s=60.0, reason="scale_down")
        procs = list(ex._procs)
    finally:
        ex.close()
    assert ex._procs == []
    deadline = time.time() + 15
    while time.time() < deadline and any(p.poll() is None for p in procs):
        time.sleep(0.1)
    codes = [p.poll() for p in procs]
    assert all(c is not None for c in codes), codes  # nothing orphaned


def test_no_workers_error_is_actionable():
    """Satellite: zero-worker submits and worker-wait timeouts carry real
    diagnostics — address, counts seen, timeout used, and a how-to hint —
    instead of bare errors."""
    coord = Coordinator("127.0.0.1", 0)
    try:
        with pytest.raises(NoWorkersError) as ei:
            coord.submit(None, lambda x: x, 0)
        msg = str(ei.value)
        host, port = coord.address
        assert "no live workers" in msg
        assert f"{host}:{port}" in msg
        assert "cubed_tpu.runtime.worker" in msg  # the how-to hint
        assert "no worker ever connected" in msg  # ever-joined count seen

        with pytest.raises(TimeoutError) as ei2:
            coord.wait_for_workers(2, timeout=0.2)
        m2 = str(ei2.value)
        assert "0 of 2" in m2  # workers seen vs wanted
        assert "0.2" in m2  # the timeout used
        assert "0 ever joined" in m2
        assert "cubed_tpu.runtime.worker" in m2
    finally:
        coord.close()


def test_compute_with_zero_workers_fails_fast(spec):
    """min_workers=0 sails past the startup wait; the compute itself must
    fail fast with a clear diagnostic rather than mid-plan."""
    ex = DistributedDagExecutor(
        listen="127.0.0.1:0", n_local_workers=0, min_workers=0,
    )
    try:
        a = ct.from_array(np.ones((4, 4)), chunks=(2, 2), spec=spec)
        with pytest.raises(NoWorkersError, match="zero live workers"):
            xp.sum(a).compute(executor=ex)
    finally:
        ex.close()


def test_last_worker_drained_submit_waits_for_backfill():
    """Regression for the last-worker race: (a) ``grace_s=0`` is a
    legitimate 'abandon immediately' — the worker must not substitute its
    default drain grace and sit out the in-flight task; (b) with an
    autoscaler-armed ``backfill_grace_s``, a submit that finds the fleet
    momentarily empty waits for the replacement to register instead of
    failing the compute with ``NoWorkersError``."""
    import threading as _threading

    from cubed_tpu.runtime.distributed import WorkerDrainedError, run_worker

    coord = Coordinator("127.0.0.1", 0)
    host, port = coord.address

    def start_worker(name):
        _threading.Thread(
            target=run_worker, args=(f"{host}:{port}",),
            kwargs=dict(nthreads=1, name=name, drain_grace_s=10.0),
            daemon=True,
        ).start()

    try:
        start_worker("w-0")
        coord.wait_for_workers(1, timeout=30)
        coord.backfill_grace_s = 10.0  # what Autoscaler.start() arms

        # (a) catch a slow task in flight, drain with grace_s=0: it must be
        # abandoned immediately, not after the worker's 10s default grace
        # (nor after the 2s the task itself would take to finish)
        fut = coord.submit(None, _SlowAdd(2.0), 1.0)
        time.sleep(0.5)  # let the worker pull the task into flight
        t0 = time.monotonic()
        assert coord.request_drain("w-0", grace_s=0.0, reason="scale_down")
        with pytest.raises(WorkerDrainedError):
            fut.result(timeout=5)
        assert time.monotonic() - t0 < 1.5  # abandoned, not waited out
        # the drain completed cleanly and the fleet is now empty
        deadline = time.time() + 10
        while time.time() < deadline and coord.n_workers > 0:
            time.sleep(0.02)
        snap = coord.stats_snapshot()
        assert snap["drains_completed"] == 1, snap
        assert coord.n_workers == 0

        # (b) submit against the empty fleet from a thread; it must block
        # on the backfill grace, then land on the late replacement
        fut2_box = {}

        def _submit():
            fut2_box["fut"] = coord.submit(None, _SlowAdd(0.0), 41.0)

        t = _threading.Thread(target=_submit, daemon=True)
        t.start()
        time.sleep(0.3)  # let submit() reach the backfill wait
        start_worker("w-1")  # the autoscaler's replacement registers late
        t.join(timeout=30)
        assert not t.is_alive()
        result, _stats = fut2_box["fut"].result(timeout=30)
        assert result == 42.0
    finally:
        coord.backfill_grace_s = 0.0
        coord.close()


def test_all_draining_fleet_submit_waits_for_replacement():
    """Regression: when EVERY live worker is draining (a coordinated spot
    reclaim of the whole fleet) and the autoscaler has armed
    ``backfill_grace_s``, submit must wait for a non-draining replacement
    instead of routing to a drainer — that path is an instant
    abandon→requeue ping-pong that exhausts the free requeue allowance in
    milliseconds, far faster than any replacement can boot."""
    import threading as _threading

    from cubed_tpu.runtime.distributed import run_worker

    coord = Coordinator("127.0.0.1", 0)
    host, port = coord.address

    def start_worker(name):
        _threading.Thread(
            target=run_worker, args=(f"{host}:{port}",),
            kwargs=dict(nthreads=1, name=name, drain_grace_s=10.0),
            daemon=True,
        ).start()

    try:
        start_worker("w-0")
        coord.wait_for_workers(1, timeout=30)
        coord.backfill_grace_s = 10.0  # what Autoscaler.start() arms

        # keep the drain window open: an in-flight slow task means w-0
        # stays alive-and-draining instead of reporting drained instantly
        fut = coord.submit(None, _SlowAdd(3.0), 1.0)
        time.sleep(0.5)
        assert coord.request_drain("w-0", grace_s=30.0, reason="scale_down")

        box = {}

        def _submit():
            box["fut"] = coord.submit(None, _SlowAdd(0.0), 41.0)

        t = _threading.Thread(target=_submit, daemon=True)
        t.start()
        time.sleep(0.5)
        assert t.is_alive()  # blocked waiting, NOT handed to the drainer
        start_worker("w-1")  # the backfill replacement registers
        t.join(timeout=30)
        assert not t.is_alive()
        result, _stats = box["fut"].result(timeout=30)
        assert result == 42.0
        # the drainer finished its in-flight task inside the grace window
        r0, _ = fut.result(timeout=30)
        assert r0 == 2.0
    finally:
        coord.backfill_grace_s = 0.0
        coord.close()


# ----------------------------------------------------------------------
# heartbeat metrics-delta shipping (live telemetry, observability PR)
# ----------------------------------------------------------------------


def test_heartbeat_metrics_delta_is_bounded_numeric_and_nonzero():
    from cubed_tpu.observability.metrics import MetricsRegistry
    from cubed_tpu.runtime.distributed import (
        HEARTBEAT_DELTA_MAX_KEYS,
        heartbeat_metrics_delta,
    )

    reg = MetricsRegistry()
    reg.counter("worker_tasks_executed").inc(3)
    reg.counter("untouched").inc(0)
    reg.gauge("peer_cache_bytes").set(123)
    reg.histogram("op_wall_clock_s").observe(0.5)
    delta, snap = heartbeat_metrics_delta(reg, {})
    assert delta["worker_tasks_executed"] == 3
    # gauges are windowed away by snapshot_delta — but NOT silently: the
    # drop is counted and the counter ships on the NEXT heartbeat (the
    # bookkeeping lands after the delta's own snapshot), so a fleet gauge
    # can never vanish without a trace (the satellite fix this PR carries)
    assert "peer_cache_bytes" not in delta
    # histogram summaries (dicts) and zero increments stay off the wire
    assert "op_wall_clock_s" not in delta and "untouched" not in delta
    delta2, _ = heartbeat_metrics_delta(reg, snap)
    assert delta2 is not None
    assert delta2.get("gauges_dropped_in_delta", 0) >= 1
    assert set(delta2) <= {"gauges_dropped_in_delta"}
    # the key cap holds whatever the metric namespace grows to
    for i in range(2 * HEARTBEAT_DELTA_MAX_KEYS):
        reg.counter(f"m{i:04d}").inc()
    delta3, _ = heartbeat_metrics_delta(reg, snap)
    payload_keys = [
        k for k in delta3 if k != "heartbeat_delta_keys_dropped"
    ]
    assert len(payload_keys) <= HEARTBEAT_DELTA_MAX_KEYS
    assert delta3["heartbeat_delta_keys_dropped"] > 0


def test_fleet_heartbeats_fold_worker_metrics_into_coordinator(tmp_path):
    """End to end: worker subprocesses count task executions in their own
    registries, heartbeats ship the deltas, and the coordinator's
    per-worker + fleet-wide accumulators carry them (what the telemetry
    sampler and `cubed_tpu.top` read)."""
    from cubed_tpu.observability.metrics import get_registry

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    an = np.arange(64.0).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    r = ct.map_blocks(_inc_one, a, dtype=np.float64)
    reg = get_registry()
    before = reg.snapshot()
    ex = DistributedDagExecutor(n_local_workers=2)
    try:
        ex._ensure_fleet()
        result = np.asarray(r.compute(executor=ex))
        np.testing.assert_array_equal(result, an + 1.0)
        # wait for the next heartbeat round to deliver the final deltas
        deadline = time.monotonic() + 15
        total = 0
        while time.monotonic() < deadline:
            snap = ex._coordinator.stats_snapshot()
            total = (snap.get("fleet_metrics") or {}).get(
                "worker_tasks_executed", 0
            )
            if total >= 17:  # 16 map tasks + create-arrays
                break
            time.sleep(0.2)
        assert total >= 17, snap.get("fleet_metrics")
        workers = snap["workers"]
        per_worker = [
            (w.get("metrics") or {}).get("worker_tasks_executed", 0)
            for w in workers.values() if w.get("alive")
        ]
        assert sum(per_worker) == total
        assert all(v > 0 for v in per_worker)  # both workers reported
    finally:
        ex.close()
    # the coordinator counted the delta frames it folded
    delta = reg.snapshot_delta(before)
    assert delta.get("heartbeat_metric_deltas", 0) >= 2


def _inc_one(x):
    return x + 1.0
