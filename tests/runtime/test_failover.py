"""Live coordinator failover: control-plane snapshot, epoch fencing,
fleet re-adoption, and the chaos proofs.

A crashed coordinator is replaced by a successor pointed at the same
``control_dir``: it comes up as the next epoch, re-adopts the recorded
fleet (workers re-attach through their session tokens and replay their
unacked outboxes), fences stale-epoch frames, and re-issues only
genuinely lost assignments — the running fleet survives the control
plane's death (runtime/distributed.py + runtime/journal.py ControlLog).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.observability import get_registry
from cubed_tpu.runtime.distributed import (
    Coordinator,
    WorkerLostError,
    _give_up_message,
    frame_bytes,
    recv_frame,
    run_worker,
    send_frame,
)
from cubed_tpu.runtime.journal import (
    ControlLog,
    control_log_path,
    load_control,
    load_journal,
    read_rendezvous,
    rendezvous_path,
    write_rendezvous,
)


# ----------------------------------------------------------------------
# control log + rendezvous (runtime/journal.py)
# ----------------------------------------------------------------------


def test_control_log_roundtrip_and_torn_tail(tmp_path):
    d = str(tmp_path / "ctrl")
    log = ControlLog(d)
    log.record_epoch(0, ("127.0.0.1", 4000))
    log.record_worker("w0", "tok0", 2, peer_addr=("10.0.0.1", 9000),
                      address=("10.0.0.1", 50001), pid=1234)
    log.record_worker("w1", "tok1", 1)
    log.record_dispatch(7, ("op-a", "0.0"), "w0")
    log.record_dispatch(8, ("op-a", "0.1"), "w1")
    log.record_dispatch(9, ("op-a", "1.0"), "w0")
    log.record_done(8)
    log.record_chunk_locations("w0", [("store", "a/0.0", 64)])
    log.record_worker_gone("w1")
    log.record_decision(0, {"kind": "worker_disconnected", "worker": "w0"})
    log.close()

    # a torn tail (partial line) and garbage cost only themselves
    with open(control_log_path(d), "ab") as f:
        f.write(b'{"kind": "dispatch", "task_')

    prior = load_control(control_log_path(d))
    assert prior["epoch"] == 0
    assert prior["addr"] == ["127.0.0.1", 4000]
    # w1 is gone: its registration AND its in-flight dispatch fold away
    assert set(prior["workers"]) == {"w0"}
    assert prior["workers"]["w0"]["token"] == "tok0"
    assert prior["workers"]["w0"]["pid"] == 1234
    assert set(prior["inflight"]) == {7, 9}  # 8 done, w1's 8 gone anyway
    assert prior["inflight"][7]["tag"] == ["op-a", "0.0"]
    assert prior["chunk_locations"][0]["key"] == "a/0.0"
    assert prior["decisions"][-1]["decision"] == "worker_disconnected"
    assert prior["bad_lines"] == 1

    # a fresh directory folds to epoch -1 (NOT a successor)
    fresh = load_control(control_log_path(str(tmp_path / "nope")))
    assert fresh["epoch"] == -1 and not fresh["workers"]


def test_rendezvous_roundtrip_and_garbage_tolerance(tmp_path):
    d = str(tmp_path)
    write_rendezvous(d, 3, ("10.1.2.3", 8765))
    adv = read_rendezvous(rendezvous_path(d))
    assert adv == {"epoch": 3, "addr": ("10.1.2.3", 8765)}
    # garbage / missing files read as None — the reconnect loop just
    # keeps dialing its last-known address
    with open(rendezvous_path(d), "w") as f:
        f.write("{not json")
    assert read_rendezvous(rendezvous_path(d)) is None
    assert read_rendezvous(str(tmp_path / "absent.json")) is None


# ----------------------------------------------------------------------
# satellite: error paths name the endpoint + epoch
# ----------------------------------------------------------------------


def test_give_up_message_names_endpoint_epoch_and_hints():
    msg = _give_up_message(
        "w3", "10.0.0.9:8765", 2, 30.0, rendezvous="/ctrl/rendezvous.json"
    )
    assert "10.0.0.9:8765" in msg
    assert "epoch 2" in msg
    assert "/ctrl/rendezvous.json" in msg
    assert "--reconnect-give-up" in msg
    # without a rendezvous file the hint says live failover isn't armed
    msg2 = _give_up_message("w3", "10.0.0.9:8765", 0, 30.0)
    assert "--rendezvous" in msg2


def test_wait_for_workers_timeout_names_endpoint_and_epoch():
    coord = Coordinator("127.0.0.1", 0)
    try:
        with pytest.raises(TimeoutError) as exc:
            coord.wait_for_workers(1, timeout=0.2)
        host, port = coord.address
        assert f"{host}:{port}" in str(exc.value)
        assert "epoch 0" in str(exc.value)
    finally:
        coord.close()


# ----------------------------------------------------------------------
# raw-socket worker helpers (handshake only: enough to exercise the
# coordinator's frame paths without a task loop)
# ----------------------------------------------------------------------


def _fake_worker(coord, name, token=None):
    """Register a hello-only worker; returns its connected socket."""
    host, port = coord.address
    s = socket.create_connection((host, port), timeout=10)
    hello = {"type": "hello", "name": name, "nthreads": 1, "pid": os.getpid()}
    if token is not None:
        hello["token"] = token
    send_frame(s, hello)
    ack = recv_frame(s)
    assert ack["type"] == "hello_ack", ack
    return s, ack


def test_drain_complete_sealed_when_link_dies():
    """Satellite regression: a worker whose drain already finished every
    task but whose link tears down before the ``drained`` frame lands
    (e.g. a reconnect loop exhausting its retries mid-drain) seals as a
    completed drain — never counted toward ``workers_lost``."""
    coord = Coordinator("127.0.0.1", 0)
    try:
        s, _ = _fake_worker(coord, "w-drain")
        coord.wait_for_workers(1, timeout=10)
        # the coordinator flips `connected` just after its hello_ack —
        # poll past that handshake race before requesting the drain
        deadline = time.time() + 10
        ok = coord.request_drain("w-drain", grace_s=30.0)
        while not ok and time.time() < deadline:
            time.sleep(0.05)
            ok = coord.request_drain("w-drain", grace_s=30.0)
        assert ok
        # nothing in flight: the drain is complete the moment it began.
        # Kill the link abruptly — no drained frame will ever arrive.
        s.close()
        deadline = time.time() + 10
        while time.time() < deadline:
            if coord.stats["drains_completed"] == 1:
                break
            time.sleep(0.05)
        assert coord.stats["drains_completed"] == 1
        assert coord.stats["workers_lost"] == 0
    finally:
        coord.close()


def test_stale_epoch_frames_fenced_and_counted():
    """Frames stamped by another coordinator incarnation are rejected
    (not applied, not acked) and counted — a zombie's traffic cannot
    corrupt the live epoch's state."""
    coord = Coordinator("127.0.0.1", 0)
    try:
        s, ack = _fake_worker(coord, "w-fence")
        assert ack["epoch"] == 0
        coord.wait_for_workers(1, timeout=10)
        # a sequenced frame from a bogus epoch: must be fenced, and the
        # fence must NOT ack it (an ack would clear the sender's outbox)
        s.sendall(frame_bytes({
            "type": "heartbeat", "seq": 1, "epoch": 7,
        }))
        deadline = time.time() + 10
        while time.time() < deadline:
            if coord.stats["stale_epoch_frames"] == 1:
                break
            time.sleep(0.05)
        assert coord.stats["stale_epoch_frames"] == 1
        # the conn's sequencing never saw the fenced frame
        with coord._lock:
            conn = next(w for w in coord._workers if w.name == "w-fence")
        assert conn.last_seq == 0
        s.close()
    finally:
        coord.close()


# ----------------------------------------------------------------------
# successor adoption (unit: pre-recorded control log, no prior process)
# ----------------------------------------------------------------------


def _seed_prior_epoch(d, inflight=((11, ("op-a", "0.0"), "w0"),)):
    log = ControlLog(d)
    log.record_epoch(0, ("127.0.0.1", 1))
    log.record_worker("w0", "tok-w0", 1, address=("127.0.0.1", 55001))
    for tid, tag, worker in inflight:
        log.record_dispatch(tid, tag, worker)
    log.record_chunk_locations("w0", [("s3://b", "arr/0.0", 128)])
    log.record_decision(0, {"kind": "worker_disconnected", "worker": "w0",
                            "reason": "seeded"})
    log.close()


def test_successor_adopts_fleet_and_submit_returns_adopted_future(tmp_path):
    from cubed_tpu.observability.collect import decisions_since

    d = str(tmp_path / "ctrl")
    _seed_prior_epoch(d)
    t0 = time.time() - 1
    coord = Coordinator("127.0.0.1", 0, control_dir=d, takeover_grace_s=60.0)
    try:
        assert coord.epoch == 1
        assert coord.in_takeover()
        assert coord.stats["coordinator_takeovers"] == 1
        # the adopted worker is alive (counts as fleet capacity) but
        # disconnected — waiting for its token'd reconnect
        assert coord.n_workers == 1
        snap = coord.stats_snapshot()
        assert snap["epoch"] == 1
        row = snap["workers"]["w0"]
        assert row["alive"] and not row["connected"]
        assert row["epoch"] == 0  # joined under the prior epoch
        # task ids live in the successor's shifted space: no collision
        # with worker dedup state that survived the resume
        assert coord._next_task_id >= (1 << 40)
        # a re-submit of the same plan-derived tag hands the adopted
        # future back instead of re-dispatching the work
        fut = coord.submit(None, lambda x: x, 0, tag=("op-a", "0.0"))
        assert coord.stats["tasks_readopted"] == 1
        assert not fut.done()  # waiting on the worker's outbox replay
        # the successor advertised its epoch for the fleet to chase
        adv = read_rendezvous(rendezvous_path(d))
        assert adv["epoch"] == 1
        assert adv["addr"] == coord.address
        # stitched timeline: the prior epoch's replayed connectivity
        # decisions and the takeover marker are both in THIS ring
        kinds = [e["kind"] for e in decisions_since(t0)]
        assert "coordinator_takeover" in kinds
        replayed = [
            e for e in decisions_since(t0)
            if e["kind"] == "worker_disconnected" and e.get("epoch") == 0
        ]
        assert replayed and replayed[0]["reason"] == "seeded"
    finally:
        coord.close()


def test_takeover_window_lease_requeues_exactly_once(tmp_path):
    """Satellite: an adopted assignment whose worker never reports back
    requeues exactly once when the takeover window closes — never
    double-requeued across the epoch boundary."""
    d = str(tmp_path / "ctrl")
    _seed_prior_epoch(d)
    coord = Coordinator(
        "127.0.0.1", 0, control_dir=d, takeover_grace_s=1.0, lease_s=1.0,
    )
    try:
        fut = coord.submit(None, lambda x: x, 0, tag=("op-a", "0.0"))
        assert coord.stats["tasks_readopted"] == 1
        with pytest.raises(WorkerLostError):
            fut.result(timeout=30)
        # exactly one requeue: the backstop consumed the adoption records
        deadline = time.time() + 10
        while time.time() < deadline and coord._adopted_pending:
            time.sleep(0.05)
        assert coord._adopted_pending == []
        assert coord._adopted == {}
        assert coord._adopted_issued == []
    finally:
        coord.close()


def test_autoscaler_holds_during_takeover(tmp_path):
    """An adopted fleet is disconnected-but-leased ON PURPOSE: the
    autoscaler must not read it as holes and spawn a duplicate fleet
    while the takeover window is open."""
    from cubed_tpu.runtime.autoscale import (
        Autoscaler,
        AutoscalePolicy,
        WorkerFactory,
    )

    d = str(tmp_path / "ctrl")
    _seed_prior_epoch(d)
    coord = Coordinator("127.0.0.1", 0, control_dir=d, takeover_grace_s=60.0)

    class CountingFactory(WorkerFactory):
        spawned = 0

        def start_worker(self):
            CountingFactory.spawned += 1
            return f"x-{CountingFactory.spawned}"

        def stop_worker(self, name):
            pass

    scaler = Autoscaler(
        coord, factory=CountingFactory(),
        policy=AutoscalePolicy(min_workers=1, max_workers=4, interval_s=0.05),
        initial_workers=1,
    )
    try:
        assert coord.in_takeover()
        for _ in range(5):
            scaler.tick()
        assert CountingFactory.spawned == 0
        assert scaler.stats["autoscaler_ticks"] == 5
    finally:
        scaler.stop()
        coord.close()


# ----------------------------------------------------------------------
# live takeover, in-process: real worker loop chases the rendezvous
# file to the successor and replays its outbox to the new epoch
# ----------------------------------------------------------------------


class _SlowDouble:
    def __init__(self, delay_s):
        self.delay_s = delay_s

    def __call__(self, x):
        time.sleep(self.delay_s)
        return x * 2


def test_reconnect_requeues_assignments_the_dead_link_ate():
    """An assignment sent on a link that dies before delivery must not
    hang under the worker's renewed lease: the resume hello names every
    task the worker actually holds, and outstanding ids missing from it
    are requeued as worker loss at reconnect."""
    coord = Coordinator("127.0.0.1", 0)
    try:
        s, ack = _fake_worker(coord, "w-req")
        coord.wait_for_workers(1, timeout=10)
        fut = coord.submit(None, _SlowDouble(0.0), 1.0)
        # drain the assignment off the wire so the send definitely
        # completed coordinator-side, then kill the link and reconnect
        # claiming an empty hold — as if the frame never arrived
        frame = recv_frame(s)
        assert frame["type"] == "task"
        s.close()
        s2 = socket.create_connection(coord.address, timeout=10)
        send_frame(s2, {
            "type": "hello", "name": "w-req", "nthreads": 1,
            "pid": os.getpid(), "token": ack["token"], "holding": [],
        })
        ack2 = recv_frame(s2)
        assert ack2["type"] == "hello_ack" and ack2.get("resume") is True
        with pytest.raises(WorkerLostError):
            fut.result(timeout=10)
        assert coord.stats["assignments_requeued"] == 1
        assert coord.stats["workers_lost"] == 0
        s2.close()
    finally:
        coord.close()


def test_live_takeover_worker_rejoins_and_replays(tmp_path):
    """The tentpole end to end, in-process: coordinator A dies abruptly
    with a task in flight; successor B (same control_dir) adopts the
    fleet; the worker — still running the task — chases the rendezvous
    advertisement to B, resumes its session with its token, and replays
    the finished result to the NEW epoch. The adopted future resolves
    without the task ever re-running, and nothing counts as lost."""
    d = str(tmp_path / "ctrl")
    a = Coordinator("127.0.0.1", 0, control_dir=d, lease_s=10.0)
    host, port = a.address
    wt = threading.Thread(
        target=run_worker, args=(f"{host}:{port}",),
        kwargs=dict(
            nthreads=1, name="w-live", rendezvous=rendezvous_path(d),
            reconnect_give_up_s=60.0,
        ),
        daemon=True,
    )
    wt.start()
    b = None
    try:
        a.wait_for_workers(1, timeout=30)
        fut_a = a.submit(
            None, _SlowDouble(2.0), 21.0, tag=("op-live", "0"),
        )
        time.sleep(0.4)  # the dispatch is on the wire and in the log
        assert not fut_a.done()
        # crash A without any goodbye: server + worker socket just die
        a._closed.set()
        a._server.close()
        with a._lock:
            socks = [w.sock for w in a._workers if w.sock is not None]
        for s in socks:
            s.close()

        b = Coordinator(
            "127.0.0.1", 0, control_dir=d, lease_s=10.0,
            takeover_grace_s=30.0,
        )
        assert b.epoch == 1
        assert b.stats["coordinator_takeovers"] == 1
        fut_b = b.submit(
            None, _SlowDouble(2.0), 21.0, tag=("op-live", "0"),
        )
        assert b.stats["tasks_readopted"] == 1
        # the worker finds B through the rendezvous file, resumes with
        # its session token, and its outbox replay resolves the future
        result, _stats = fut_b.result(timeout=60)
        assert result == 42.0
        assert b.stats["workers_lost"] == 0
        deadline = time.time() + 10
        while time.time() < deadline:
            row = b.stats_snapshot()["workers"].get("w-live") or {}
            if row.get("connected"):
                break
            time.sleep(0.05)
        assert row.get("connected"), row
        assert row.get("epoch") == 1  # rejoined under the successor
        # and the fleet still takes NEW work under the new epoch
        fut_new = b.submit(None, _SlowDouble(0.0), 5.0, tag=("op-live", "1"))
        assert fut_new.result(timeout=30)[0] == 10.0
    finally:
        if b is not None:
            b.close()  # shutdown frame stops the worker thread
        a.close()
        wt.join(timeout=15)


# ----------------------------------------------------------------------
# chaos proofs: SIGKILL the coordinator process mid-compute; the
# orphaned worker fleet is adopted by a successor PROCESS
# ----------------------------------------------------------------------


_FAILOVER_SCRIPT = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
import cubed_tpu as ct
from cubed_tpu.observability import get_registry
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

mode = sys.argv[1]
work_dir = {work_dir!r}
journal = {journal!r}
control_dir = {control_dir!r}

def slow_add(x):
    import time
    time.sleep(0.15)
    return x + 1.0

spec = ct.Spec(work_dir=work_dir, allowed_mem="500MB", journal=journal)
an = np.arange(144, dtype=np.float64).reshape(12, 12)
a = ct.from_array(an, chunks=(2, 2), spec=spec)   # 36 map tasks
# a REDUCTION on top of the slow map: its combine rounds are
# dependency-gated, so when the coordinator dies mid-map the successor
# must both re-adopt the running map tasks AND dispatch the combine
# tasks fresh mid-takeover (a pure elementwise chain would fuse into
# one op whose tasks are all already in flight)
import cubed_tpu.array_api as xp
r = xp.sum(ct.map_blocks(slow_add, a, dtype=np.float64))
expected = (an + 1.0).sum()  # integer-valued float64: the sum is exact
total = r.plan.num_tasks()

if mode == "run":
    ex = DistributedDagExecutor(
        n_local_workers=2, worker_threads=1, control_dir=control_dir,
    )
    print(json.dumps({{"phase": "run", "total": total}}), flush=True)
    t0 = time.monotonic()
    r.compute(executor=ex)
    print(json.dumps(
        {{"phase": "run", "done": True,
          "wall_s": time.monotonic() - t0}}), flush=True)
    ex.close()
else:
    # successor: NO local workers of its own — it must adopt the
    # orphaned fleet the killed coordinator left running
    ex = DistributedDagExecutor(
        n_local_workers=0, worker_threads=1, control_dir=control_dir,
        worker_start_timeout=60.0,
    )
    reg = get_registry()
    before = reg.snapshot()
    t0 = time.monotonic()
    result = ex.resume_compute(r, journal)
    wall = time.monotonic() - t0
    delta = reg.snapshot_delta(before)
    stats = ex.stats
    print(json.dumps({{
        "phase": "adopt",
        "correct": bool(np.array_equal(result, expected)),
        "total": total,
        "wall_s": wall,
        "epoch": stats.get("epoch"),
        "takeovers": stats.get("coordinator_takeovers"),
        "readopted": stats.get("tasks_readopted"),
        "workers_lost": stats.get("workers_lost"),
        "resumed_tasks": delta.get("tasks_completed", 0),
        "skipped": delta.get("tasks_skipped_resume", 0),
        "deduped": delta.get("fleet_assignments_deduped", 0),
    }}), flush=True)
    ex.close()
"""


def _reap_control_log_workers(control_dir):
    """Kill any orphaned worker processes recorded in the control log
    (test cleanup: a failed takeover must not leak fleet processes)."""
    prior = load_control(control_log_path(control_dir))
    for rec in prior["workers"].values():
        pid = rec.get("pid")
        if isinstance(pid, int) and pid > 1:
            try:
                os.kill(pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                pass


def _run_failover_phases(tmp_path, adopt_env_extra=None, kills=1):
    """Shared chaos driver: run phase, SIGKILL the coordinator process
    (ONLY the coordinator — its worker subprocesses survive as orphans),
    then run the successor phase in a fresh process. Returns the
    successor's JSON report (plus kill bookkeeping)."""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    journal = str(tmp_path / "failover.journal.jsonl")
    control_dir = str(tmp_path / "ctrl")
    script = _FAILOVER_SCRIPT.format(
        repo=repo, work_dir=str(tmp_path), journal=journal,
        control_dir=control_dir,
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               # cross-process adoption needs stable intermediate paths
               CUBED_TPU_CONTEXT_ID="cubed-failover")
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    proc = subprocess.Popen(
        [sys.executable, "-c", script, "run"], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    report = None
    try:
        # kill the coordinator at ~50% task completions, straggler-held:
        # every 0.15s task keeps the in-flight window real
        deadline = time.time() + 120
        killed_at = None
        while time.time() < deadline and proc.poll() is None:
            if os.path.exists(journal):
                done = len(load_journal(journal)["completed"])
                if done >= 19:  # creates + ~half the 36 slow map tasks
                    os.kill(proc.pid, signal.SIGKILL)  # NOT the group:
                    killed_at = done                   # workers survive
                    break
            time.sleep(0.05)
        proc.wait(timeout=30)
        assert killed_at is not None, (
            "compute finished before the kill landed (rc="
            f"{proc.returncode})"
        )

        adopt_env = dict(env)
        if adopt_env_extra:
            adopt_env.update(adopt_env_extra)
        for attempt in range(kills):
            out = subprocess.run(
                [sys.executable, "-c", script, "adopt"], env=adopt_env,
                capture_output=True, text=True, timeout=240,
            )
            if out.returncode == 137 and attempt < kills - 1:
                # the injected crash-during-takeover landed: the NEXT
                # successor must finish the job un-injected
                adopt_env.pop("CUBED_TPU_FAULTS", None)
                continue
            assert out.returncode == 0, out.stderr[-4000:]
            report = json.loads(out.stdout.strip().splitlines()[-1])
            report["successors"] = attempt + 1
            break
        assert report is not None, "every successor attempt was killed"
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
        _reap_control_log_workers(control_dir)
    return report


@pytest.mark.chaos
def test_chaos_coordinator_sigkill_live_failover(tmp_path, invariant_audit):
    """Acceptance proof: SIGKILL the coordinator process at ~50% task
    completion mid-dataflow-compute; a successor process pointed at the
    same control_dir adopts the orphaned worker fleet (epoch 1), the
    result is bitwise-correct, no worker counts as lost, and strictly
    fewer tasks re-executed than the full plan."""
    # uninterrupted baseline first (same plan, same machine) for the
    # wall-clock ratio; reuse of the work dir is fine — fresh context ids
    report = _run_failover_phases(tmp_path)
    assert report["correct"] is True
    assert report["epoch"] == 1
    assert report["takeovers"] == 1
    assert report["workers_lost"] == 0
    # the adopted fleet's in-flight/finished work was NOT re-run
    assert report["skipped"] > 0
    assert report["resumed_tasks"] < report["total"], report
    # takeover wall clock stays under 2x a generous uninterrupted
    # estimate (~46 tasks x 0.15s across 2 workers, plus fixed overhead)
    assert report["wall_s"] < 2 * (46 * 0.15 / 2 + 3.0), report
    # the two-epoch control log must show the takeover as a LEGAL
    # ownership transfer and strictly increasing epochs; the journal's
    # kill/resume segments must each stay exactly-once
    invariant_audit(
        journal=str(tmp_path / "failover.journal.jsonl"),
        control_dir=str(tmp_path / "ctrl"), work_dir=str(tmp_path),
    )


@pytest.mark.chaos
def test_chaos_coordinator_killed_again_during_takeover(
    tmp_path, invariant_audit
):
    """Second variant: the FIRST successor is itself killed mid-takeover
    (seeded fault: hard-exit after 3 dispatches in an epoch > 0); the
    second successor (epoch 2) adopts whatever both prior epochs left
    and still completes bitwise-correct."""
    faults = json.dumps({
        "seed": 7, "coordinator_takeover_crash_after_dispatches": 3,
    })
    report = _run_failover_phases(
        tmp_path, adopt_env_extra={"CUBED_TPU_FAULTS": faults}, kills=2,
    )
    assert report["successors"] == 2  # the first successor really died
    assert report["correct"] is True
    assert report["epoch"] == 2
    assert report["workers_lost"] == 0
    assert report["resumed_tasks"] < report["total"], report
    # three epochs (0 killed, 1 crashed mid-takeover, 2 finished): the
    # control log must still audit as monotone with legal hand-offs
    invariant_audit(
        journal=str(tmp_path / "failover.journal.jsonl"),
        control_dir=str(tmp_path / "ctrl"), work_dir=str(tmp_path),
    )
