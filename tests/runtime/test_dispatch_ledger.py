"""Dispatch-ledger tests: per-task control-plane stamps on the local and
distributed executors (monotonic, no double-count across retries/backup
twins), the ledger-informed ``ready_wait`` vs ``dispatch_overhead`` split
in ``analyze()``, and the chaos proof that ``dispatch_saturation`` fires
onto every operator surface (decision ring, ``/snapshot.json``, ``top``).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu import top
from cubed_tpu.observability import TraceCollector, analyze
from cubed_tpu.observability.alerts import (
    AlertEngine,
    DispatchSaturationRule,
    default_rules,
)
from cubed_tpu.observability.collect import decisions_since
from cubed_tpu.observability.timeseries import TimeSeriesStore
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor


@pytest.fixture()
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


def _ledgers(col: TraceCollector) -> list:
    return [r["dispatch"] for r in col._records if r.get("dispatch")]


# ---------------------------------------------------------------------------
# stamps on the wire: local loop, distributed coordinator
# ---------------------------------------------------------------------------


def test_local_ledger_stamps_every_task_monotonically(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    r = ct.map_blocks(lambda x: x + 1.0, a, dtype=np.float64)
    col = TraceCollector(trace_dir=None)
    val = np.asarray(
        r.compute(executor=AsyncPythonDagExecutor(), callbacks=[col])
    )
    np.testing.assert_array_equal(val, an + 1.0)
    ledgers = _ledgers(col)
    assert len(ledgers) == len(col._records), "task completed ledger-less"
    for d in ledgers:
        assert d["ready_tstamp"] <= d["submitted_tstamp"]
        assert d["submit_cost_s"] >= 0.0


def test_distributed_ledger_carries_coordinator_costs(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(3, 3), spec=spec)
    r = ct.map_blocks(lambda x: x * 2.0, a, dtype=np.float64)
    col = TraceCollector(trace_dir=None)
    with DistributedDagExecutor(n_local_workers=2, worker_threads=2) as ex:
        val = np.asarray(r.compute(executor=ex, callbacks=[col]))
    np.testing.assert_array_equal(val, an * 2.0)
    ledgers = [
        d for d in _ledgers(col) if d.get("sent_tstamp") is not None
    ]
    assert ledgers, "no task shipped a coordinator-side ledger"
    for d in ledgers:
        # the full lifecycle, in clock order: deps-ready -> dequeued ->
        # on the wire -> result back
        assert d["ready_tstamp"] <= d["submitted_tstamp"]
        assert d["submitted_tstamp"] <= d["sent_tstamp"] + 1e-6
        assert d["sent_tstamp"] <= d["result_recv_tstamp"]
        for k in ("serialize_s", "send_s", "lock_wait_s", "unpickle_s"):
            assert d[k] >= 0.0
        # the coordinator-side parts happened INSIDE the wrapping submit
        # call, so they can never exceed it (the no-double-count invariant
        # analyze() relies on when it prefers submit_cost_s)
        assert (
            d["serialize_s"] + d["send_s"]
            <= d["submit_cost_s"] + 5e-3
        )


def test_retried_tasks_carry_the_winning_attempts_ledger(tmp_path):
    """The ledger on a retried task's end event is the WINNING attempt's
    own dispatch cost, not an accumulation across attempts: its submit
    stamp sits just before the winning execution (after the failed
    attempt and its backoff), while ready_tstamp keeps the task's first
    deps-ready time — so the pre-start gap is never counted twice."""
    # fault decisions hash the op/array names, which embed process-global
    # counters — whether a fixed seed exhausts some task's retry budget
    # depends on suite order. Accept the first seed whose compute both
    # survives and retried at least one task.
    an = np.arange(64.0).reshape(8, 8)
    retried = None
    for i, seed in enumerate((11, 23, 47, 91, 137)):
        spec = ct.Spec(
            work_dir=str(tmp_path / f"w{i}"), allowed_mem="500MB",
            fault_injection={"task_failure_rate": 0.25, "seed": seed},
        )
        a = ct.from_array(an, chunks=(2, 2), spec=spec)
        r = ct.map_blocks(lambda x: x + 3.0, a, dtype=np.float64)
        col = TraceCollector(trace_dir=None)
        try:
            val = np.asarray(
                r.compute(executor=AsyncPythonDagExecutor(), callbacks=[col])
            )
        except Exception:
            continue  # this seed burned through a task's retry budget
        np.testing.assert_array_equal(val, an + 3.0)
        recs = [rec for rec in col._records if rec["attempt"] > 0]
        if recs:
            retried = recs
            break
    assert retried, "no seed produced a survivable retried compute"
    for rec in retried:
        d = rec.get("dispatch")
        assert d is not None
        # the winning attempt's submit immediately precedes its start
        assert d["submitted_tstamp"] <= rec["start"] + 1e-6
        assert rec["start"] - d["submitted_tstamp"] < 2.0
        # ready_tstamp is the FIRST deps-ready time: the failed attempt
        # plus its backoff live between the two stamps exactly once
        assert d["ready_tstamp"] <= d["submitted_tstamp"]
        # per-attempt cost, not a lifetime accumulation
        assert d["submit_cost_s"] < 1.0


# ---------------------------------------------------------------------------
# analyze(): the ready_wait vs dispatch_overhead split
# ---------------------------------------------------------------------------

_US = 1e6


def _task(op, chunk, t0, t1, dispatch=None, tid=1):
    args = {"chunk": chunk, "attempt": 0}
    if dispatch is not None:
        args["dispatch"] = dispatch
    return {
        "name": op, "cat": "task", "ph": "X", "ts": t0 * _US,
        "dur": (t1 - t0) * _US, "tid": tid, "args": args,
    }


def _bundle(events, edges):
    return {
        "manifest": {"compute_id": "c-ledger", "status": "succeeded",
                     "chunk_graph": edges},
        "trace": {"traceEvents": events},
    }


def test_analyze_splits_queue_wait_with_ledger():
    """A 3-task chain with known gaps: ledgered gaps split into
    dispatch_overhead (the coordinator's measured cost, clamped to the
    gap) + ready_wait; the ledger-less task keeps legacy queue_wait. The
    buckets still tile the wall clock exactly."""
    events = [
        {"name": "compute", "cat": "compute", "ph": "X", "ts": 0.0,
         "dur": 10.0 * _US, "tid": 1, "args": {}},
        _task("op-a", "('a', 0)", 1.0, 2.0),  # no ledger: legacy bucket
        # 3s gap, coordinator says 1.2s of it was submit cost
        _task("op-b", "('b', 0)", 5.0, 6.0,
              dispatch={"submit_cost_s": 1.2}),
        # 1s gap, parts-only ledger (serialize+send+lock = 0.4s) and a
        # cost larger than... no: 0.4 < 1.0 -> 0.4 overhead, 0.6 ready
        _task("op-c", "('c', 0)", 7.0, 9.0,
              dispatch={"serialize_s": 0.25, "send_s": 0.1,
                        "lock_wait_s": 0.05}),
    ]
    edges = {
        "op-a\t('a', 0)": [],
        "op-b\t('b', 0)": ["op-a\t('a', 0)"],
        "op-c\t('c', 0)": ["op-b\t('b', 0)"],
    }
    d = analyze(_bundle(events, edges)).to_dict()
    attr = d["attribution"]
    assert attr["queue_wait"] == pytest.approx(1.0, abs=1e-6)
    assert attr["dispatch_overhead"] == pytest.approx(1.6, abs=1e-6)
    assert attr["ready_wait"] == pytest.approx(1.8 + 0.6, abs=1e-6)
    assert sum(attr.values()) == pytest.approx(10.0, rel=1e-6)
    rows = {r["op"]: r for r in d["critical_path"]}
    # rows keep the FULL gap in queue_wait_s (ranking stability) and
    # expose the split beside it only when a ledger informed it
    assert rows["op-b"]["queue_wait_s"] == pytest.approx(3.0, abs=1e-6)
    assert rows["op-b"]["dispatch_overhead_s"] == pytest.approx(1.2)
    assert rows["op-b"]["ready_wait_s"] == pytest.approx(1.8)
    assert "dispatch_overhead_s" not in rows["op-a"]


def test_analyze_clamps_dispatch_cost_to_the_gap():
    """A ledger claiming more submit cost than the observed gap cannot
    mint time: overhead clamps to the gap, ready_wait floors at zero, and
    the total still tiles the wall clock (the no-double-count proof)."""
    events = [
        {"name": "compute", "cat": "compute", "ph": "X", "ts": 0.0,
         "dur": 4.0 * _US, "tid": 1, "args": {}},
        _task("op-a", "('a', 0)", 0.5, 1.0),
        _task("op-b", "('b', 0)", 1.5, 3.0,
              dispatch={"submit_cost_s": 99.0}),
    ]
    edges = {"op-a\t('a', 0)": [], "op-b\t('b', 0)": ["op-a\t('a', 0)"]}
    d = analyze(_bundle(events, edges)).to_dict()
    attr = d["attribution"]
    assert attr["dispatch_overhead"] == pytest.approx(0.5, abs=1e-6)
    assert attr["ready_wait"] == 0.0
    assert sum(attr.values()) == pytest.approx(4.0, rel=1e-6)


def test_live_compute_attribution_includes_dispatch_and_tiles(spec):
    """End to end on the real executor: every task ships a ledger, so the
    legacy queue_wait bucket is empty, dispatch_overhead is nonzero, and
    the buckets sum to the measured wall clock within the 10% bar."""
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    r = a
    for _ in range(3):
        r = ct.map_blocks(lambda x: x + 1.0, a, dtype=np.float64)
    col = TraceCollector(trace_dir=None)
    np.asarray(
        r.compute(executor=AsyncPythonDagExecutor(), callbacks=[col],
                  optimize_graph=False)
    )
    d = analyze(col).to_dict()
    attr = d["attribution"]
    assert attr["queue_wait"] == 0.0, (
        "a ledgered compute left time in the legacy bucket"
    )
    assert attr["dispatch_overhead"] >= 0.0
    wall = d["wall_clock_s"]
    assert abs(sum(attr.values()) - wall) <= 0.10 * wall


# ---------------------------------------------------------------------------
# the dispatch_saturation alert: rule semantics + chaos proof
# ---------------------------------------------------------------------------


def _saturated_store(now: float, draining: bool = False) -> TimeSeriesStore:
    store = TimeSeriesStore()
    for i in range(25):
        ts = now - 25 + i
        store.record("dispatch_utilization", 0.97, ts=ts)
        depth = (30 - i) if draining else (5 + i)
        store.record("queue_depth", depth, ts=ts)
    return store


def test_dispatch_saturation_rule_semantics():
    now = 1000.0
    rule = DispatchSaturationRule(window_s=20.0)
    firing = rule.evaluate(_saturated_store(now), now)
    assert firing is not None
    assert firing["metric"] == "dispatch_utilization"
    assert firing["value"] >= 0.9 and firing["queue_depth"] > 0
    # a draining backlog is saturated-but-coping: no page
    assert rule.evaluate(_saturated_store(now, draining=True), now) is None
    # a dip below the threshold anywhere in the window is not saturation
    dipped = _saturated_store(now)
    dipped.record("dispatch_utilization", 0.5, ts=now - 10)
    assert rule.evaluate(dipped, now) is None
    # partial window coverage (the loop just got busy) is not saturation
    fresh = TimeSeriesStore()
    for i in range(3):
        fresh.record("dispatch_utilization", 0.99, ts=now - 3 + i)
        fresh.record("queue_depth", 9, ts=now - 3 + i)
    assert rule.evaluate(fresh, now) is None
    assert rule.evaluate(TimeSeriesStore(), now) is None


def test_default_rules_include_dispatch_saturation():
    rules = {r.name: r for r in default_rules()}
    assert "dispatch_saturation" in rules
    assert rules["dispatch_saturation"].severity == "critical"


@pytest.mark.chaos
def test_chaos_dispatch_saturation_reaches_every_surface(
    tmp_path, monkeypatch,
):
    """A saturated-coordinator window (pegged utilization, growing queue)
    fires dispatch_saturation through the REAL engine, and the firing is
    visible everywhere an operator looks: the decision ring, the
    ``/snapshot.json`` payload, and a ``top --once``-equivalent render
    (including the DISPATCH panel itself)."""
    from cubed_tpu.observability import export

    export.shutdown()
    monkeypatch.delenv(export.TELEMETRY_PORT_ENV_VAR, raising=False)
    rt = export.ensure_started(0)
    try:
        now = time.time()
        for i in range(25):
            ts = now - 25 + i
            rt.store.record("dispatch_utilization", 0.97, ts=ts)
            rt.store.record("queue_depth", 5 + i, ts=ts)
        rule = DispatchSaturationRule(
            description="coordinator saturated (chaos test)",
        )
        rt.alert_engine.rules = [rule]
        rt.alert_engine._state = {
            rule.name: {"active": False, "last_fired": 0.0}
        }
        fired = rt.alert_engine.tick(now=now)
        assert [f["rule"] for f in fired] == ["dispatch_saturation"]
        assert rt.alert_engine.active() == ["dispatch_saturation"]
        # 1) the decision ring
        ring = [
            d for d in decisions_since(0)
            if d["kind"] == "alert_fired"
            and d["rule"] == "dispatch_saturation"
        ]
        assert ring, "firing missing from the decision ring"
        # 2) /snapshot.json (the same payload the HTTP endpoint serves)
        snap = rt.snapshot()
        assert any(
            a.get("rule") == "dispatch_saturation" for a in snap["alerts"]
        )
        assert "dispatch_saturation" in snap["alerts_active"]
        # the live gauge would populate snapshot["dispatch"] mid-compute;
        # make the panel render deterministically here (the dispatch view
        # wins over metrics when both are present, so inject into both —
        # earlier tests in this process may have left a stale 0.0 gauge)
        snap["metrics"]["dispatch_utilization"] = 0.97
        snap["metrics"]["dispatch_capacity_estimate"] = 120.0
        snap["dispatch"] = dict(
            snap.get("dispatch") or {},
            dispatch_utilization=0.97,
            dispatch_capacity_estimate=120.0,
        )
        # 3) the dashboard frame (what --once prints)
        frame = top.render(snap)
        assert "DISPATCH" in frame
        assert "utilization 97%" in frame
        assert "dispatch_saturation" in frame
    finally:
        export.shutdown()


def test_saturation_engine_edge_and_cooldown():
    now = 1000.0
    store = _saturated_store(now)
    engine = AlertEngine(
        store, rules=[DispatchSaturationRule()], cooldown_s=60.0,
    )
    assert len(engine.tick(now=now)) == 1
    for i in range(5):
        ts = now + 1 + i
        store.record("dispatch_utilization", 0.97, ts=ts)
        store.record("queue_depth", 40 + i, ts=ts)
    assert engine.tick(now=now + 5) == []  # sustained: inside cooldown
