"""Executor registry: create executors by name (Spec(executor_name=...))."""

from __future__ import annotations

from typing import Optional


def create_executor(name: str, executor_options: Optional[dict] = None):
    executor_options = executor_options or {}
    if name in ("single-threaded", "python"):
        from .executors.python import PythonDagExecutor

        return PythonDagExecutor(**executor_options)
    if name in ("threads", "async-python"):
        from .executors.python_async import AsyncPythonDagExecutor

        return AsyncPythonDagExecutor(**executor_options)
    if name == "processes":
        from .executors.multiprocess import MultiprocessDagExecutor

        return MultiprocessDagExecutor(**executor_options)
    if name == "distributed":
        from .executors.distributed import DistributedDagExecutor

        return DistributedDagExecutor(**executor_options)
    if name in ("jax", "tpu", "jax-tpu"):
        from .executors.jax import JaxExecutor

        return JaxExecutor(**executor_options)
    raise ValueError(f"Unrecognized executor name: {name!r}")
