"""Ring-collective (Cannon / ring-reduce) tests on the 8-device CPU mesh."""

import numpy as np
import pytest


def _cpu_devices():
    import jax

    try:
        return jax.devices("cpu")
    except RuntimeError:
        return []


needs_8 = pytest.mark.skipif(
    len(_cpu_devices()) < 8, reason="needs 8 virtual CPU devices"
)


@pytest.fixture
def mesh():
    from cubed_tpu.parallel.mesh import make_mesh

    return make_mesh(shape=(8,), axis_names=("data",), devices=_cpu_devices()[:8])


@needs_8
def test_ring_matmul(mesh):
    import jax.numpy as jnp

    from cubed_tpu.parallel.ring import ring_matmul

    rng = np.random.default_rng(0)
    an = rng.random((16, 24), dtype=np.float32)
    bn = rng.random((24, 8), dtype=np.float32)
    out = ring_matmul(jnp.asarray(an), jnp.asarray(bn), mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), an @ bn, rtol=1e-4)


@needs_8
def test_ring_matmul_shape_check(mesh):
    import jax.numpy as jnp

    from cubed_tpu.parallel.ring import ring_matmul

    with pytest.raises(ValueError, match="divisible"):
        ring_matmul(jnp.zeros((15, 24)), jnp.zeros((24, 8)), mesh=mesh)


@needs_8
def test_ring_reduction(mesh):
    import jax.numpy as jnp

    from cubed_tpu.parallel.ring import ring_reduction

    rng = np.random.default_rng(0)
    xn = rng.random((32, 4), dtype=np.float32)

    out = ring_reduction(jnp.asarray(xn), lambda s: jnp.sum(s), mesh=mesh)
    # every ring position holds the global sum
    np.testing.assert_allclose(np.asarray(out), np.full(8, xn.sum()), rtol=1e-4)
