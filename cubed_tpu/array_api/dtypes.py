"""Array-API dtype objects, categories, and the type-promotion lattice.

Reference parity: cubed/array_api/dtypes.py (173 LoC).
"""

from __future__ import annotations

import numpy as np

int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
uint8 = np.dtype("uint8")
uint16 = np.dtype("uint16")
uint32 = np.dtype("uint32")
uint64 = np.dtype("uint64")
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")
bool = np.dtype("bool")  # noqa: A001

#: TPU-native extension dtype (not in the 2022.12 standard)
bfloat16 = np.dtype("float32")  # alias for promotion purposes on the API surface
try:
    import ml_dtypes

    bfloat16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:
    pass

_all_dtypes = (
    int8, int16, int32, int64,
    uint8, uint16, uint32, uint64,
    float32, float64, complex64, complex128, bool,
)
_boolean_dtypes = (bool,)
_real_floating_dtypes = (float32, float64)
_floating_dtypes = (float32, float64, complex64, complex128)
_complex_floating_dtypes = (complex64, complex128)
_integer_dtypes = (int8, int16, int32, int64, uint8, uint16, uint32, uint64)
_signed_integer_dtypes = (int8, int16, int32, int64)
_unsigned_integer_dtypes = (uint8, uint16, uint32, uint64)
_integer_or_boolean_dtypes = _boolean_dtypes + _integer_dtypes
_real_numeric_dtypes = _real_floating_dtypes + _integer_dtypes
_numeric_dtypes = _floating_dtypes + _integer_dtypes

_dtype_categories = {
    "all": _all_dtypes,
    "real numeric": _real_numeric_dtypes,
    "numeric": _numeric_dtypes,
    "integer": _integer_dtypes,
    "integer or boolean": _integer_or_boolean_dtypes,
    "boolean": _boolean_dtypes,
    "real floating-point": _real_floating_dtypes,
    "floating-point": _floating_dtypes,
    "complex floating-point": _complex_floating_dtypes,
}

# promotion table (Array API spec); keys are (dtype, dtype) pairs
_signed = [int8, int16, int32, int64]
_unsigned = [uint8, uint16, uint32, uint64]
_floats = [float32, float64]
_complexes = [complex64, complex128]

_promotion_table: dict = {}


def _fill_table():
    # same-kind: larger wins
    for fam in (_signed, _unsigned, _floats, _complexes):
        for i, a in enumerate(fam):
            for j, b in enumerate(fam):
                _promotion_table[(a, b)] = fam[max(i, j)]
    # signed x unsigned
    for i, u in enumerate(_unsigned):
        if u is uint64:
            continue  # uint64 x signed is undefined in the spec
        for j, s in enumerate(_signed):
            if u.itemsize < s.itemsize:
                r = s
            else:
                r = _signed[[d.itemsize for d in _signed].index(u.itemsize * 2)]
            _promotion_table[(u, s)] = r
            _promotion_table[(s, u)] = r
    # float x complex
    _promotion_table[(float32, complex64)] = complex64
    _promotion_table[(complex64, float32)] = complex64
    _promotion_table[(float32, complex128)] = complex128
    _promotion_table[(complex128, float32)] = complex128
    _promotion_table[(float64, complex64)] = complex128
    _promotion_table[(complex64, float64)] = complex128
    _promotion_table[(float64, complex128)] = complex128
    _promotion_table[(complex128, float64)] = complex128
    # bool
    _promotion_table[(bool, bool)] = bool


_fill_table()


def promote_types(t1, t2):
    t1, t2 = np.dtype(t1), np.dtype(t2)
    key = (t1, t2)
    if key in _promotion_table:
        return _promotion_table[key]
    raise TypeError(f"{t1} and {t2} cannot be type promoted together")
