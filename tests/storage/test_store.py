"""Zarr v2 store tests: layout conformance, indexing, atomicity, resume
counters. Reference parity: cubed/tests/storage/test_zarr.py."""

import json
import os

import numpy as np
import pytest

from cubed_tpu.storage.store import open_zarr_array
from cubed_tpu.storage.zarr import LazyZarrArray, lazy_empty, open_if_lazy_zarr_array


def test_create_and_roundtrip(tmp_path):
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(5, 7), dtype=np.float64, chunks=(2, 3))
    an = np.arange(35.0).reshape(5, 7)
    z[...] = an
    np.testing.assert_array_equal(z[...], an)
    # reopen
    z2 = open_zarr_array(store, "r")
    np.testing.assert_array_equal(z2[...], an)
    assert z2.chunks == (2, 3)
    assert z2.dtype == np.float64


def test_zarr_v2_layout(tmp_path):
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(4, 4), dtype=np.int32, chunks=(2, 2))
    z[...] = np.arange(16, dtype=np.int32).reshape(4, 4)
    meta = json.loads(open(os.path.join(store, ".zarray")).read())
    assert meta["zarr_format"] == 2
    assert meta["shape"] == [4, 4]
    assert meta["chunks"] == [2, 2]
    assert meta["compressor"] is None
    assert meta["dimension_separator"] == "."
    # chunk 1.1 holds the bottom-right block, raw C-order
    raw = np.frombuffer(open(os.path.join(store, "1.1"), "rb").read(), dtype="<i4")
    np.testing.assert_array_equal(raw.reshape(2, 2), [[10, 11], [14, 15]])


def test_partial_reads_writes(tmp_path):
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(6, 6), dtype=np.float64, chunks=(4, 4))
    an = np.zeros((6, 6))
    z[...] = an
    z[1:3, 2:5] = 7.0
    an[1:3, 2:5] = 7.0
    np.testing.assert_array_equal(z[...], an)
    np.testing.assert_array_equal(z[0:4, 3:6], an[0:4, 3:6])
    np.testing.assert_array_equal(z[5], an[5])
    np.testing.assert_array_equal(z[::2, 1::2], an[::2, 1::2])


def test_edge_chunks_padded(tmp_path):
    # 5x5 with 2x2 chunks: edge chunks stored padded, reads clip to shape
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(5, 5), dtype=np.float64, chunks=(2, 2))
    an = np.arange(25.0).reshape(5, 5)
    z[...] = an
    np.testing.assert_array_equal(z[...], an)
    np.testing.assert_array_equal(z[4:5, 3:5], an[4:5, 3:5])


def test_oindex(tmp_path):
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(6, 8), dtype=np.float64, chunks=(2, 3))
    an = np.arange(48.0).reshape(6, 8)
    z[...] = an
    np.testing.assert_array_equal(z.oindex[[0, 3, 5], :], an[[0, 3, 5], :])
    np.testing.assert_array_equal(
        z.oindex[[1, 4], [0, 2, 7]], an[np.ix_([1, 4], [0, 2, 7])]
    )
    np.testing.assert_array_equal(z.oindex[slice(1, 5), [2, 2, 3]],
                                  an[1:5][:, [2, 2, 3]])


def test_nchunks_initialized(tmp_path):
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(4, 4), dtype=np.float64, chunks=(2, 2))
    assert z.nchunks == 4
    assert z.nchunks_initialized == 0
    z[0:2, 0:2] = 1.0
    assert z.nchunks_initialized == 1
    z[...] = 1.0
    assert z.nchunks_initialized == 4


def test_structured_dtype(tmp_path):
    dtype = np.dtype([("n", np.int64), ("total", np.float64)])
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(2, 2), dtype=dtype, chunks=(1, 2))
    rec = np.zeros((2, 2), dtype=dtype)
    rec["n"] = [[1, 2], [3, 4]]
    rec["total"] = [[0.5, 1.5], [2.5, 3.5]]
    z[...] = rec
    out = z[...]
    np.testing.assert_array_equal(out["n"], rec["n"])
    np.testing.assert_array_equal(out["total"], rec["total"])


def test_0d_array(tmp_path):
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(), dtype=np.float64)
    z[()] = 42.0
    assert float(z[()]) == 42.0


def test_lazy_zarr_array(tmp_path):
    store = str(tmp_path / "a.zarr")
    lazy = lazy_empty((4, 4), dtype=np.float64, chunks=(2, 2), store=store)
    # no metadata until create()
    with pytest.raises(FileNotFoundError):
        lazy.open()
    lazy.create()
    z = open_if_lazy_zarr_array(lazy)
    assert z.shape == (4, 4)


def test_mode_a_preserves_chunks(tmp_path):
    # reopening with mode=a must not clobber existing chunk data (resume)
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(4, 4), dtype=np.float64, chunks=(2, 2))
    z[0:2, 0:2] = 5.0
    z2 = open_zarr_array(store, "a", shape=(4, 4), dtype=np.float64, chunks=(2, 2))
    np.testing.assert_array_equal(z2[0:2, 0:2], np.full((2, 2), 5.0))
    assert z2.nchunks_initialized == 1


def test_fill_value(tmp_path):
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(
        store, "w", shape=(4,), dtype=np.float64, chunks=(2,), fill_value=np.nan
    )
    out = z[...]
    assert np.isnan(out).all()


# ---------------------------------------------------------------------------
# Zarr v2 spec golden files: the on-disk format is the interchange contract
# (other implementations must be able to read our stores); these pin the
# exact metadata JSON so any drift fails loudly. Spec reference:
# https://zarr-specs.readthedocs.io/en/latest/v2/v2.0.html
# ---------------------------------------------------------------------------


def test_zarray_metadata_golden(tmp_path):
    import json
    import os

    a = open_zarr_array(
        str(tmp_path / "g.zarr"), mode="w",
        shape=(10, 7), dtype=np.dtype("float64"), chunks=(4, 3),
    )
    a[...] = np.arange(70.0).reshape(10, 7)
    meta = json.loads((tmp_path / "g.zarr" / ".zarray").read_text())
    assert meta == {
        "zarr_format": 2,
        "shape": [10, 7],
        "chunks": [4, 3],
        "dtype": "<f8",
        "compressor": None,
        "fill_value": 0.0,
        "order": "C",
        "filters": None,
        "dimension_separator": ".",
    }
    # v2 mandatory keys, exactly (no extras that could confuse readers)
    assert set(meta) == {
        "zarr_format", "shape", "chunks", "dtype", "compressor",
        "fill_value", "order", "filters", "dimension_separator",
    }


@pytest.mark.parametrize(
    "np_dtype,v2_dtype",
    [("float32", "<f4"), ("int64", "<i8"), ("uint8", "|u1"), ("bool", "|b1"),
     ("int16", "<i2"), ("complex128", "<c16")],
)
def test_zarray_dtype_encoding(tmp_path, np_dtype, v2_dtype):
    import json

    a = open_zarr_array(
        str(tmp_path / f"d-{np_dtype}.zarr"), mode="w",
        shape=(4,), dtype=np.dtype(np_dtype), chunks=(2,),
    )
    meta = json.loads((tmp_path / f"d-{np_dtype}.zarr" / ".zarray").read_text())
    assert meta["dtype"] == v2_dtype


def test_zarray_structured_dtype_encoding(tmp_path):
    import json

    dt = np.dtype([("n", np.int64), ("total", np.float64)])
    a = open_zarr_array(
        str(tmp_path / "s.zarr"), mode="w", shape=(4,), dtype=dt, chunks=(2,),
    )
    meta = json.loads((tmp_path / "s.zarr" / ".zarray").read_text())
    # v2 structured dtypes are lists of [name, dtype] pairs
    assert meta["dtype"] == [["n", "<i8"], ["total", "<f8"]]


def test_raw_chunk_layout_c_order_readback(tmp_path):
    """Chunk files are raw C-order buffers a third-party v2 reader decodes
    with nothing but the .zarray JSON."""
    import json
    import os

    an = np.arange(70.0).reshape(10, 7)
    a = open_zarr_array(
        str(tmp_path / "r.zarr"), mode="w",
        shape=(10, 7), dtype=np.dtype("float64"), chunks=(4, 3),
    )
    a[...] = an
    meta = json.loads((tmp_path / "r.zarr" / ".zarray").read_text())
    chunks = meta["chunks"]
    sep = meta["dimension_separator"]
    # reconstruct the full array exactly the way an independent reader would
    out = np.empty(meta["shape"], dtype=meta["dtype"])
    for ci in range((meta["shape"][0] + chunks[0] - 1) // chunks[0]):
        for cj in range((meta["shape"][1] + chunks[1] - 1) // chunks[1]):
            raw = (tmp_path / "r.zarr" / f"{ci}{sep}{cj}").read_bytes()
            block = np.frombuffer(raw, dtype=meta["dtype"]).reshape(chunks)
            i0, j0 = ci * chunks[0], cj * chunks[1]
            i1 = min(i0 + chunks[0], meta["shape"][0])
            j1 = min(j0 + chunks[1], meta["shape"][1])
            out[i0:i1, j0:j1] = block[: i1 - i0, : j1 - j0]
    np.testing.assert_array_equal(out, an)


@pytest.mark.parametrize(
    "compressor",
    [
        {"id": "zlib", "level": 5},
        {"id": "gzip", "level": 1},
        {"id": "bz2", "level": 1},
        {"id": "lzma", "preset": 0},
    ],
)
def test_compressed_roundtrip(tmp_path, compressor):
    store = str(tmp_path / "c.zarr")
    z = open_zarr_array(
        store, "w", shape=(5, 7), dtype=np.float64, chunks=(2, 3),
        compressor=compressor,
    )
    an = np.arange(35.0).reshape(5, 7)
    z[...] = an
    np.testing.assert_array_equal(z[...], an)
    # reopened array picks the codec up from the on-disk metadata
    z2 = open_zarr_array(store, "r")
    assert z2.compressor["id"] == compressor["id"]
    np.testing.assert_array_equal(z2[...], an)
    # chunk objects on disk really are compressed (not raw C-order bytes)
    meta = json.loads(open(os.path.join(store, ".zarray")).read())
    assert meta["compressor"]["id"] == compressor["id"]
    raw = open(os.path.join(store, "0.0"), "rb").read()
    assert raw != an[:2, :3].tobytes()


def test_compressed_interop_zlib(tmp_path):
    """Read a zlib-compressed chunk written byte-for-byte the way any other
    Zarr v2 implementation would write it (spec fixture, no zarr-python)."""
    import zlib

    store = tmp_path / "other.zarr"
    store.mkdir()
    an = np.arange(6.0).reshape(2, 3)
    meta = {
        "zarr_format": 2,
        "shape": [2, 3],
        "chunks": [2, 3],
        "dtype": "<f8",
        "compressor": {"id": "zlib", "level": 1},
        "fill_value": 0.0,
        "order": "C",
        "filters": None,
    }
    (store / ".zarray").write_text(json.dumps(meta))
    (store / "0.0").write_bytes(zlib.compress(an.tobytes(), 1))
    z = open_zarr_array(str(store), "r")
    np.testing.assert_array_equal(z[...], an)


def test_unsupported_compressor_raises(tmp_path):
    with pytest.raises(ValueError, match="blosc"):
        open_zarr_array(
            str(tmp_path / "b.zarr"), "w", shape=(2,), dtype=np.float64,
            chunks=(2,), compressor={"id": "blosc", "cname": "lz4"},
        )


def test_to_zarr_compressed_end_to_end(tmp_path):
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp

    spec_ = ct.Spec(work_dir=str(tmp_path / "work"), allowed_mem="500MB")
    an = np.arange(100.0).reshape(10, 10)
    a = ct.from_array(an, chunks=(4, 4), spec=spec_)
    target = str(tmp_path / "out.zarr")
    ct.to_zarr(xp.add(a, 1.0), target, compressor={"id": "zlib", "level": 1})
    z = open_zarr_array(target, "r")
    assert z.compressor == {"id": "zlib", "level": 1}
    np.testing.assert_array_equal(z[...], an + 1.0)
    # and from_zarr reads it back through the framework
    b = ct.from_zarr(target)
    np.testing.assert_array_equal(b.compute(), an + 1.0)


def test_lzma_raw_format_roundtrip(tmp_path):
    """FORMAT_RAW lzma requires the filter chain on decompression too."""
    import lzma

    comp = {
        "id": "lzma",
        "format": lzma.FORMAT_RAW,
        "filters": [{"id": lzma.FILTER_LZMA2, "preset": 1}],
    }
    store = str(tmp_path / "raw.zarr")
    z = open_zarr_array(
        store, "w", shape=(4, 4), dtype=np.float64, chunks=(2, 2),
        compressor=comp,
    )
    an = np.arange(16.0).reshape(4, 4)
    z[...] = an
    np.testing.assert_array_equal(z[...], an)
    np.testing.assert_array_equal(open_zarr_array(store, "r")[...], an)


def test_lzma_xz_with_filters_roundtrip(tmp_path):
    """Container formats embed the filter chain; decompress must NOT be
    handed filters (CPython rejects them except with FORMAT_RAW)."""
    import lzma

    comp = {
        "id": "lzma",
        "format": lzma.FORMAT_XZ,
        "filters": [{"id": lzma.FILTER_LZMA2, "preset": 1}],
    }
    store = str(tmp_path / "xzf.zarr")
    z = open_zarr_array(
        store, "w", shape=(4, 4), dtype=np.float64, chunks=(2, 2),
        compressor=comp,
    )
    an = np.arange(16.0).reshape(4, 4)
    z[...] = an
    np.testing.assert_array_equal(open_zarr_array(store, "r")[...], an)


def test_fsspec_memory_store_roundtrip():
    """The _FsspecIO path (s3://, gs://, ... in production) via memory://."""
    import uuid

    pytest.importorskip("fsspec")

    store = f"memory://zarr-{uuid.uuid4().hex}"
    z = open_zarr_array(
        store, "w", shape=(5, 6), dtype=np.float64, chunks=(2, 3),
        compressor={"id": "zlib", "level": 1},
    )
    an = np.arange(30.0).reshape(5, 6)
    z[...] = an
    np.testing.assert_array_equal(z[...], an)
    z2 = open_zarr_array(store, "r")
    np.testing.assert_array_equal(z2[...], an)
    assert z2.nchunks_initialized == z2.nchunks


def test_fsspec_memory_workdir_end_to_end():
    """A whole plan with its work_dir on an fsspec store (single-process
    executors only: memory:// is per-process)."""
    import uuid

    pytest.importorskip("fsspec")
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp

    spec_ = ct.Spec(
        work_dir=f"memory://work-{uuid.uuid4().hex}", allowed_mem="500MB"
    )
    an = np.arange(64.0).reshape(8, 8)
    a = ct.from_array(an, chunks=(3, 3), spec=spec_)
    got = float(xp.sum(xp.multiply(a, 3.0)).compute())
    assert got == 3 * an.sum()


# -- orphaned .tmp hygiene (crashed mid-write writers) --------------------


def _litter_tmp(store: str, name: str, age_s: float = 120.0) -> str:
    """Plant a stale partial temp file as a crashed writer would leave it."""
    import time

    path = os.path.join(store, name)
    with open(path, "wb") as f:
        f.write(b"\x00" * 7)  # partial payload: not a valid chunk
    old = time.time() - age_s
    os.utime(path, (old, old))
    return path


def test_orphaned_tmp_ignored_by_resume_counters(tmp_path):
    """Regression: a crashed write's leftover .tmp next to chunks must not
    count as an initialized chunk (it would fool resume into skipping an
    op whose output is incomplete)."""
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(4, 4), dtype=np.float64, chunks=(2, 2))
    z[:2, :2] = np.ones((2, 2))  # 1 real chunk of 4
    _litter_tmp(store, "1.1.deadbeef.tmp")
    z2 = open_zarr_array(store, "r")
    assert z2.nchunks_initialized == 1
    # and reading the chunk the orphan shadows returns fill, not garbage
    np.testing.assert_array_equal(z2[2:, 2:], np.zeros((2, 2)))


def test_orphaned_tmp_swept_on_writer_open(tmp_path):
    """Opening in a writer mode (what the create-arrays op and resume do)
    sweeps stale orphans; fresh temp files — possibly a live writer mid
    os.replace — are left alone."""
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(4, 4), dtype=np.float64, chunks=(2, 2))
    z[...] = np.arange(16.0).reshape(4, 4)
    stale = _litter_tmp(store, "0.0.cafe0000.tmp", age_s=120.0)
    fresh = _litter_tmp(store, "0.1.cafe0001.tmp", age_s=0.0)
    os.utime(fresh)  # make it genuinely fresh
    z2 = open_zarr_array(store, "a")  # resume-style reopen
    assert not os.path.exists(stale), "stale orphan should be swept"
    assert os.path.exists(fresh), "a live writer's temp must survive"
    np.testing.assert_array_equal(z2[...], np.arange(16.0).reshape(4, 4))


def test_orphaned_tmp_not_swept_on_read_open(tmp_path):
    """Read opens (every task opening an input) skip the sweep — hygiene
    belongs to the op-start writer open, not the hot read path."""
    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(2,), dtype=np.float64, chunks=(2,))
    z[...] = np.arange(2.0)
    stale = _litter_tmp(store, "0.feed0000.tmp", age_s=120.0)
    open_zarr_array(store, "r")
    assert os.path.exists(stale)


def test_sweep_counts_metric(tmp_path):
    from cubed_tpu.observability.metrics import get_registry
    from cubed_tpu.storage.store import _LocalIO

    store = str(tmp_path / "a.zarr")
    os.makedirs(store)
    _litter_tmp(store, "0.0.aa.tmp")
    _litter_tmp(store, "0.1.bb.tmp")
    before = get_registry().snapshot()
    removed = _LocalIO(store).sweep_tmp()
    assert removed == 2
    delta = get_registry().snapshot_delta(before)
    assert delta.get("orphan_tmps_swept", 0) == 2


def test_vanished_chunk_read_fails_loudly_not_fill(tmp_path, monkeypatch):
    """A FileNotFoundError AFTER a successful exists() is an anomaly
    (chunks are write-once); it must raise — not silently read as an
    absent chunk and substitute fill values for real data."""
    from cubed_tpu.storage.store import _LocalIO

    store = str(tmp_path / "a.zarr")
    z = open_zarr_array(store, "w", shape=(2,), dtype=np.float64, chunks=(2,))
    z[...] = np.arange(2.0)

    monkeypatch.setenv("CUBED_TPU_STORAGE_READ_RETRIES", "1")

    def gone(self, name):
        raise FileNotFoundError(name)

    monkeypatch.setattr(_LocalIO, "read_bytes", gone)
    with pytest.raises(FileNotFoundError):
        z[...]
