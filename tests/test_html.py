"""HTML repr tests (reference parity: cubed/tests/test_html.py)."""

import numpy as np

import cubed_tpu as ct
import cubed_tpu.array_api as xp


def test_repr_html_contains_metadata(spec):
    a = ct.from_array(np.arange(48.0).reshape(6, 8), chunks=(2, 4), spec=spec)
    html = a._repr_html_()
    assert "<svg" in html  # chunk-grid picture
    assert "float64" in html
    assert "(6, 8)" in html or "6" in html and "8" in html
    assert "Chunk" in html or "chunk" in html


def test_repr_html_1d_and_scalar(spec):
    v = xp.ones((12,), chunks=(5,), spec=spec)
    html = v._repr_html_()
    assert "<svg" in html
    s = xp.sum(v)  # 0-d
    assert s._repr_html_()  # must not raise on 0-d


def test_repr_html_ragged_grid(spec):
    a = ct.from_array(np.zeros((19, 13)), chunks=(5, 4), spec=spec)
    html = a._repr_html_()
    assert "<svg" in html


def test_plain_repr(spec):
    a = ct.from_array(np.zeros((4, 4)), chunks=(2, 2), spec=spec)
    r = repr(a)
    assert "Array" in r or "array" in r
    assert "(4, 4)" in r or "4, 4" in r
