"""The user-facing attention bridge (cubed_tpu.parallel.attention):
cubed arrays in, cubed array out, ring-parallel under a mesh."""

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.parallel import attention, make_mesh
from cubed_tpu.parallel.ring_attention import dense_attention


def _cpu_devices():
    import jax

    try:
        return jax.devices("cpu")
    except RuntimeError:
        return []


needs_8 = pytest.mark.skipif(
    len(_cpu_devices()) < 8, reason="needs 8 virtual CPU devices"
)


def _qkv(spec, B=2, S=16, H=2, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.standard_normal((B, S, H, D)).astype(np.float32)
    qn, kn, vn = mk(), mk(), mk()
    wrap = lambda an: ct.from_array(an, chunks=(B, S // 2, H, D), spec=spec)
    return (qn, kn, vn), (wrap(qn), wrap(kn), wrap(vn))


def test_attention_dense_single_device(spec):
    (qn, kn, vn), (q, k, v) = _qkv(spec)
    out = attention(q, k, v)
    expect = np.asarray(dense_attention(qn, kn, vn))
    got = np.asarray(out.compute())
    assert out.chunksize == q.chunksize
    np.testing.assert_allclose(got, expect, atol=2e-5)


@needs_8
@pytest.mark.parametrize("causal", [False, True])
def test_attention_ring_over_mesh(spec, causal):
    mesh = make_mesh(shape=(8,), axis_names=("seq",), devices=_cpu_devices()[:8])
    (qn, kn, vn), (q, k, v) = _qkv(spec)
    out = attention(q, k, v, causal=causal, mesh=mesh)
    expect = np.asarray(dense_attention(qn, kn, vn, causal=causal))
    np.testing.assert_allclose(np.asarray(out.compute()), expect, atol=2e-5)


def test_attention_rejects_bad_rank(spec):
    a = ct.from_array(np.zeros((4, 4)), chunks=(2, 2), spec=spec)
    with pytest.raises(ValueError):
        attention(a, a, a)


@needs_8
def test_attention_rejects_axis_name_miss(spec):
    # a mesh without the requested axis must raise, not silently run dense
    mesh = make_mesh(shape=(8,), axis_names=("data",), devices=_cpu_devices()[:8])
    (_, _, _), (q, k, v) = _qkv(spec)
    with pytest.raises(ValueError, match="not a mesh axis"):
        attention(q, k, v, mesh=mesh)  # default axis_name='seq'
