"""Scale-out (multi-chunk bitonic) sort — beyond the reference, which skips
sort entirely (.github/workflows/array-api-tests.yml skip list).

The headline property: an axis LARGER than ``allowed_mem`` sorts, because
every network task touches exactly two chunks (VERDICT r3 #8 closed the
single-chunk-axis wall). The conformance suite additionally fuzzes the
multi-chunk path against numpy across dtypes/shapes (chunks_for always
splits axes, so sorting there goes through the network).
"""

from __future__ import annotations

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.runtime.executors.jax import JaxExecutor


@pytest.fixture
def spec(tmp_path, monkeypatch):
    # small arrays would pass the memory heuristic and take the one-kernel
    # path; force the network so these tests actually cover it
    monkeypatch.setenv("CUBED_TPU_SORT_NETWORK", "force")
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="100MB", reserved_mem=0)


@pytest.mark.parametrize("executor", [None, "jax"])
def test_sort_axis_larger_than_allowed_mem(tmp_path, executor):
    """The scale criterion: 4MB axis slab, 2MB allowed_mem, 0.125MB chunks.
    The old single-chunk path raised at plan time here; the network sorts."""
    small = ct.Spec(work_dir=str(tmp_path), allowed_mem="2MB", reserved_mem=0)
    n = 500_000  # 4MB f64
    an = np.random.default_rng(0).permutation(n).astype(np.float64)
    a = ct.from_array(an, chunks=(15_625,), spec=small)  # 32 chunks
    kw = {"executor": JaxExecutor()} if executor == "jax" else {}
    got = np.asarray(xp.sort(a).compute(**kw))
    np.testing.assert_array_equal(got, np.arange(n, dtype=np.float64))


def test_argsort_axis_larger_than_allowed_mem(tmp_path):
    # 3MB axis slab, 2MB allowed_mem. Chunks sized for the pair round's
    # projection (7 value + 9 index blocks, both int64 here): 100KB blocks
    # -> 1.6MB per op
    small = ct.Spec(work_dir=str(tmp_path), allowed_mem="2MB", reserved_mem=0)
    n = 375_000
    an = np.random.default_rng(1).integers(0, 50, n).astype(np.int64)
    a = ct.from_array(an, chunks=(12_500,), spec=small)  # 30 chunks, heavy ties
    got = np.asarray(xp.argsort(a).compute(executor=JaxExecutor()))
    np.testing.assert_array_equal(got, np.argsort(an, kind="stable"))


def test_argsort_one_op_per_round(spec):
    """Each argsort network round is ONE multi-output op (merge runs once),
    not a values op plus an indices op over the same merge."""
    an = np.random.default_rng(7).random(64)
    a = ct.from_array(an, chunks=(8,), spec=spec)  # 8 chunks -> 1+6 rounds
    arg = xp.argsort(a)
    dag = arg.plan.dag
    pair_ops = [
        n for n, d in dag.nodes(data=True)
        if d.get("type") == "op" and "pair" in d.get("op_name", "")
    ]
    # local pair sort + log2(8)*(log2(8)+1)/2 = 6 merge rounds
    assert len(pair_ops) == 7
    # every pair op feeds exactly two array nodes (values + indices)
    for op_node in pair_ops:
        outs = list(dag.successors(op_node))
        assert len(outs) == 2
        pop = dag.nodes[op_node]["primitive_op"]
        assert pop.target_arrays is not None and len(pop.target_arrays) == 2
    np.testing.assert_array_equal(
        np.asarray(arg.compute()), np.argsort(an, kind="stable")
    )


def test_multioutput_op_on_distributed_executor(spec):
    """Multi-output ops write all targets on the per-task executor fabric."""
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

    an = np.random.default_rng(8).integers(0, 9, 48)
    a = ct.from_array(an, chunks=(6,), spec=spec)
    got = xp.argsort(a).compute(executor=DistributedDagExecutor(n_workers=2))
    np.testing.assert_array_equal(np.asarray(got), np.argsort(an, kind="stable"))


def test_multioutput_resume_checks_all_outputs(spec):
    """Resume skips a multi-output op only when EVERY output is complete."""
    import shutil

    from cubed_tpu.core.ops import general_blockwise
    from cubed_tpu.runtime.executors.python import PythonDagExecutor

    an = np.arange(12, dtype=np.float64)
    a = ct.from_array(an, chunks=(4,), spec=spec)

    def two(chunk):
        return chunk + 1.0, (chunk * 2.0).astype(np.float64)

    def block_function(out_key):
        return ((a.name, *out_key[1:]),)

    p, d = general_blockwise(
        two, block_function, a,
        shape=a.shape, dtype=[a.dtype, np.dtype(np.float64)],
        chunks=a.chunks, op_name="two_out",
    )
    ex = PythonDagExecutor()
    np.testing.assert_array_equal(np.asarray(p.compute(executor=ex)), an + 1.0)
    np.testing.assert_array_equal(np.asarray(d.compute(executor=ex)), an * 2.0)
    # wipe only the SECONDARY output's store: the op must re-run under
    # resume=True (primary alone being complete is not enough)
    shutil.rmtree(str(d.zarray_maybe_lazy.store))
    np.testing.assert_array_equal(
        np.asarray(d.compute(executor=ex, resume=True)), an * 2.0
    )


def test_auto_network_coarsens_large_m(tmp_path):
    """auto routing rechunks the sort axis to the largest fitting merge
    before building the network: rounds scale as log2(m)*(log2(m)+1)/2 and
    every round is a full pass (O(n log^2 m) IO on non-fused executors), so
    64 tiny chunks must NOT produce a 22-round network when allowed_mem
    admits far larger merges."""
    # 512KB axis in 64 x 8KB chunks; 2MB allowed_mem fits a c=~37k merge
    # (7 blocks x 8B), so the axis coarsens to few chunks
    small = ct.Spec(work_dir=str(tmp_path), allowed_mem="2MB", reserved_mem=0)
    n = 65_536
    an = np.random.default_rng(11).random(n)
    a = ct.from_array(an, chunks=(1_024,), spec=small)
    # single-chunk slab (4x 512KB = 2MB + int64 out) exceeds allowed: network
    srt = xp.sort(a)
    rounds = [
        d["op_name"]
        for _, d in srt.plan.dag.nodes(data=True)
        if d.get("type") == "op" and "bitonic" in d.get("op_name", "")
    ]
    # uncoarsened m2=64 would give 1+21 bitonic ops; coarsened m2=2 gives 2
    assert len(rounds) <= 4, rounds
    np.testing.assert_array_equal(np.asarray(srt.compute()), np.sort(an))
    # argsort coarsens too (int64 outputs priced into the merge bound)
    arg = xp.argsort(a)
    arounds = [
        d["op_name"]
        for _, d in arg.plan.dag.nodes(data=True)
        if d.get("type") == "op" and "bitonic" in d.get("op_name", "")
    ]
    assert len(arounds) <= 7, arounds
    np.testing.assert_array_equal(
        np.asarray(arg.compute()), np.argsort(an, kind="stable")
    )


def test_auto_network_shrinks_oversized_chunks(tmp_path):
    """Chunks larger than the feasible pair-merge rechunk DOWN to it —
    auto routing must not build a network the planner then rejects."""
    small = ct.Spec(work_dir=str(tmp_path), allowed_mem="2MB", reserved_mem=0)
    n = 200_000
    an = np.random.default_rng(13).random(n)
    a = ct.from_array(an, chunks=(50_000,), spec=small)  # merge 2x50k f64 > 2MB
    np.testing.assert_array_equal(np.asarray(xp.sort(a).compute()), np.sort(an))
    np.testing.assert_array_equal(
        np.asarray(xp.argsort(a).compute()), np.argsort(an, kind="stable")
    )


def test_multioutput_plan_hits_struct_cache(spec):
    """Repeat computes of a structurally identical multi-output plan skip
    tracing entirely — the fingerprint covers ALL writes, so a key bug
    would show up here as a recompile instead of a struct hit."""
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()  # a struct hit would skip tracing legitimately
    an = np.random.default_rng(21).random(4096)

    def build():
        a = ct.from_array(an, chunks=(512,), spec=spec)
        return xp.argsort(a)

    ex1 = JaxExecutor()
    r1 = np.asarray(build().compute(executor=ex1))
    assert ex1.stats["segments_traced"] == 1
    ex2 = JaxExecutor()
    r2 = np.asarray(build().compute(executor=ex2))
    assert ex2.stats.get("segment_struct_hits", 0) == 1
    assert ex2.stats.get("segments_compiled", 0) == 0
    np.testing.assert_array_equal(r1, np.argsort(an, kind="stable"))
    np.testing.assert_array_equal(r2, r1)


def test_predecessor_fuses_into_multioutput_consumer(spec):
    """A single-output elemwise producer fuses INTO a multi-output
    consumer (writes_rest carried through fuse_multiple); the multi-output
    op itself never fuses away as a predecessor."""
    from cubed_tpu.core.ops import elemwise, general_blockwise
    from cubed_tpu.core.optimization import multiple_inputs_optimize_dag

    an = np.arange(12, dtype=np.float64)
    a = ct.from_array(an, chunks=(4,), spec=spec)
    doubled = elemwise(
        lambda x: x * 2.0, a, dtype=np.dtype(np.float64)
    )

    def two(chunk):
        return chunk + 1.0, chunk - 1.0

    def block_function(out_key):
        return ((doubled.name, *out_key[1:]),)

    p, q = general_blockwise(
        two, block_function, doubled,
        shape=a.shape, dtype=[a.dtype, a.dtype], chunks=a.chunks,
        op_name="two_out",
    )
    dag = multiple_inputs_optimize_dag(p.plan.dag.copy())
    multi_ops = [
        d["primitive_op"]
        for _, d in dag.nodes(data=True)
        if d.get("type") == "op"
        and d.get("primitive_op") is not None
        and d["primitive_op"].target_arrays is not None
    ]
    assert len(multi_ops) == 1
    # the elemwise producer fused in: the multi-output op reads `a` directly
    reads = {
        proxy.array for proxy in multi_ops[0].pipeline.config.reads_map.values()
    }
    assert a.zarray_maybe_lazy in reads
    np.testing.assert_array_equal(np.asarray(p.compute()), an * 2.0 + 1.0)
    np.testing.assert_array_equal(np.asarray(q.compute()), an * 2.0 - 1.0)


def test_multichunk_sort_matches_numpy(spec):
    rng = np.random.default_rng(2)
    an = rng.random((13, 17))
    a = ct.from_array(an, chunks=(3, 4), spec=spec)
    np.testing.assert_array_equal(
        np.asarray(xp.sort(a, axis=0).compute()), np.sort(an, axis=0)
    )
    np.testing.assert_array_equal(
        np.asarray(xp.sort(a, axis=1, descending=True).compute()),
        np.sort(an, axis=1)[:, ::-1],
    )


def test_multichunk_argsort_stable_with_ties(spec):
    an = np.random.default_rng(3).integers(0, 5, 37)
    a = ct.from_array(an, chunks=(5,), spec=spec)
    np.testing.assert_array_equal(
        np.asarray(xp.argsort(a).compute()), np.argsort(an, kind="stable")
    )
    got = np.asarray(xp.argsort(a, descending=True).compute())
    m = len(an)
    expect = (m - 1 - np.argsort(an[::-1], kind="stable"))[::-1]
    np.testing.assert_array_equal(got, expect)


def test_multichunk_sort_nan_last(spec):
    an = np.random.default_rng(4).random(19)
    an[[2, 7, 11]] = np.nan
    a = ct.from_array(an, chunks=(4,), spec=spec)
    np.testing.assert_array_equal(np.asarray(xp.sort(a).compute()), np.sort(an))
    np.testing.assert_array_equal(
        np.asarray(xp.argsort(a).compute()), np.argsort(an, kind="stable")
    )


def test_multichunk_sort_sentinel_collision(spec):
    """Real int64 max values must survive padding-sentinel dedup."""
    imax = np.iinfo(np.int64).max
    an = np.array([3, imax, 1, imax, 2] * 3, dtype=np.int64)
    a = ct.from_array(an, chunks=(4,), spec=spec)
    np.testing.assert_array_equal(np.asarray(xp.sort(a).compute()), np.sort(an))
    np.testing.assert_array_equal(
        np.asarray(xp.argsort(a).compute()), np.argsort(an, kind="stable")
    )


def test_multichunk_sort_traces_on_jax_executor(spec):
    """The network must stay on the traced/batched path (uniform kernels,
    offsets as data) — no eager fallbacks."""
    an = np.random.default_rng(5).random(100)
    a = ct.from_array(an, chunks=(16,), spec=spec)
    ex = JaxExecutor()
    got = np.asarray(xp.sort(a).compute(executor=ex))
    np.testing.assert_array_equal(got, np.sort(an))
    assert ex.stats["trace_failures"] == 0
    assert ex.stats["eager_fallbacks"] == 0


# -- 'auto' routing heuristic (no force; the default production path) -------


def test_auto_prefers_single_chunk_when_slab_fits(tmp_path, monkeypatch):
    """Plenty of memory: a multi-chunk axis must take the one-kernel path,
    not the network (network entry would hit the raising sentinel)."""
    import cubed_tpu.array_api._block_sort as bs

    def boom(*a, **k):
        raise AssertionError("network used despite fitting slab")

    monkeypatch.setattr(bs, "block_sort", boom)
    monkeypatch.setattr(bs, "block_argsort", boom)
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="100MB", reserved_mem=0)
    an = np.random.default_rng(6).random(1000)
    a = ct.from_array(an, chunks=(100,), spec=spec)
    np.testing.assert_array_equal(np.asarray(xp.sort(a).compute()), np.sort(an))
    a = ct.from_array(an, chunks=(100,), spec=spec)
    np.testing.assert_array_equal(
        np.asarray(xp.argsort(a).compute()), np.argsort(an, kind="stable")
    )


def test_auto_network_when_reserved_mem_eats_budget(tmp_path):
    """reserved_mem counts against the slab fit (review regression): a slab
    whose 4x estimate fits allowed_mem alone must still go to the network
    when reserved_mem leaves no room — and the plan must succeed."""
    # slab 0.8MB f64: 4x = 3.2MB fits 8MB, but reserved 6MB leaves 2MB
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="8MB", reserved_mem="6MB")
    n = 100_000
    an = np.random.default_rng(7).permutation(n).astype(np.float64)
    a = ct.from_array(an, chunks=(12_500,), spec=spec)
    got = np.asarray(xp.sort(a).compute())
    np.testing.assert_array_equal(got, np.arange(n, dtype=np.float64))


def test_auto_argsort_accounts_int64_output(tmp_path):
    """f32 argsort: the int64 output doubles the kernel's output bytes; the
    heuristic must charge it (review regression) so the chosen path plans."""
    # slab 0.4MB f32 -> a naive 4x-input estimate (1.6MB) fits 2.3MB and
    # would pick the single-chunk path, whose kernel the planner prices at
    # 2*0.4 + 2*0.8 = 2.4MB > 2.3MB (ValueError); charging the int64
    # output routes to the network, which plans and sorts
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="2300KB", reserved_mem=0)
    n = 100_000
    an = np.random.default_rng(8).permutation(n).astype(np.float32)
    a = ct.from_array(an, chunks=(12_500,), spec=spec)
    got = np.asarray(xp.argsort(a).compute())
    np.testing.assert_array_equal(got, np.argsort(an, kind="stable"))


# -- searchsorted partial-counts (memory-bounded x1) ------------------------


def test_searchsorted_partial_counts_matches_numpy(spec):
    """Forced network: per-chunk counts summed over the tree must equal the
    single-chunk binary search for both sides, with duplicates straddling
    chunk boundaries."""
    rng = np.random.default_rng(9)
    x1n = np.sort(rng.integers(0, 8, 29)).astype(np.float64)
    x2n = np.array([[0.0, 3.0, 7.0], [8.0, -1.0, 3.5]])
    x1 = ct.from_array(x1n, chunks=(4,), spec=spec)
    x2 = ct.from_array(x2n, chunks=(1, 2), spec=spec)
    for side in ("left", "right"):
        got = np.asarray(xp.searchsorted(x1, x2, side=side).compute())
        np.testing.assert_array_equal(got, np.searchsorted(x1n, x2n, side=side))
        got = np.asarray(
            xp.searchsorted(x1, x2, side=side).compute(executor=JaxExecutor())
        )
        np.testing.assert_array_equal(got, np.searchsorted(x1n, x2n, side=side))


def test_searchsorted_x1_larger_than_allowed_mem(tmp_path):
    """The scale criterion for searchsorted: a sorted x1 bigger than
    allowed_mem searches via partial counts (the old path rechunked x1 to
    one chunk and raised at plan time)."""
    small = ct.Spec(work_dir=str(tmp_path), allowed_mem="2MB", reserved_mem=0)
    n = 500_000  # 4MB f64 > 2MB allowed
    x1n = np.arange(n, dtype=np.float64)
    x2n = np.random.default_rng(10).random(500) * n
    x1 = ct.from_array(x1n, chunks=(31_250,), spec=small)
    x2 = ct.from_array(x2n, chunks=(125,), spec=small)
    got = np.asarray(xp.searchsorted(x1, x2).compute(executor=JaxExecutor()))
    np.testing.assert_array_equal(got, np.searchsorted(x1n, x2n))
