"""Benchmark: BASELINE.json config 1 shape — ``(a + b).sum()`` on 5000x5000
float64 with (1000,1000) chunks, arrays produced by the distributed RNG (the
reference's canonical lithops-add-random workload: data is generated inside
tasks, not transferred from the client).

Compares the JaxExecutor on the real TPU chip against the single-process
numpy-backend PythonDagExecutor (the reference's baseline executor semantics)
running the identical plan in a subprocess.

Prints ONE JSON line: {"metric", "value" (GB/s/chip of array data processed on
the TPU path), "unit", "vs_baseline" (speedup over the numpy executor)}.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

N = 5000
CHUNK = 1000
#: array bytes flowing through the fused kernel: generate a + generate b +
#: add (2 reads + 1 materialized sum input)
WORK_BYTES = 3 * N * N * 8

WORKLOAD = r"""
import json, sys, tempfile, time
import numpy as np
sys.path.insert(0, {repo!r})
import cubed_tpu as ct
import cubed_tpu.array_api as xp
import cubed_tpu.random

spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="4GB")

def build():
    a = cubed_tpu.random.random(({n}, {n}), chunks=({c}, {c}), spec=spec)
    b = cubed_tpu.random.random(({n}, {n}), chunks=({c}, {c}), spec=spec)
    return xp.sum(xp.add(a, b))

# warmup (plan construction + any compilation)
build().compute()
s = build()
t0 = time.perf_counter()
val = s.compute()
t1 = time.perf_counter()
print(json.dumps({{"elapsed": t1 - t0, "value": float(val)}}))
"""


def run_baseline() -> dict:
    env = dict(os.environ, CUBED_TPU_BACKEND="numpy")
    script = WORKLOAD.format(
        repo=os.path.dirname(os.path.abspath(__file__)), n=N, c=CHUNK
    )
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True, text=True,
        timeout=1800,
    )
    if out.returncode != 0:
        raise RuntimeError(f"baseline failed: {out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_tpu() -> dict:
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    import cubed_tpu.random
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="4GB")
    executor = JaxExecutor()

    def build():
        a = cubed_tpu.random.random((N, N), chunks=(CHUNK, CHUNK), spec=spec)
        b = cubed_tpu.random.random((N, N), chunks=(CHUNK, CHUNK), spec=spec)
        return xp.sum(xp.add(a, b))

    # warmup: same structure, compiles the kernels
    build().compute(executor=executor)

    s = build()
    t0 = time.perf_counter()
    val = s.compute(executor=executor)
    t1 = time.perf_counter()
    # sanity: mean of uniform+uniform is ~1.0
    mean = float(val) / (N * N)
    assert 0.95 < mean < 1.05, mean
    return {"elapsed": t1 - t0, "value": float(val)}


def main() -> None:
    tpu = run_tpu()
    try:
        baseline = run_baseline()
        vs_baseline = baseline["elapsed"] / tpu["elapsed"]
    except Exception as e:
        print(f"baseline run failed: {e}", file=sys.stderr)
        vs_baseline = None

    gbps = WORK_BYTES / tpu["elapsed"] / 1e9
    print(
        json.dumps(
            {
                "metric": "add_random_sum_5000x5000_f64_throughput",
                "value": round(gbps, 3),
                "unit": "GB/s/chip",
                "vs_baseline": round(vs_baseline, 3) if vs_baseline is not None else None,
            }
        )
    )


if __name__ == "__main__":
    main()
