"""Satellite regression: concurrent ``Plan.execute`` from multiple threads
in one process is safe — the gensym counter can't mint duplicate plan
identifiers, intermediate array paths never collide, the compute-id env
export can't clobber a live sibling's value, and two concurrent computes
produce bitwise-correct results (the ``CUBED_TPU_CONTEXT_ID`` collision
hazard from PR 8)."""

from __future__ import annotations

import os
import threading

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.observability import logs
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.storage.zarr import LazyZarrArray
from cubed_tpu.utils import gensym


@pytest.fixture()
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


def test_gensym_unique_under_thread_contention():
    names: list = []
    lock = threading.Lock()

    def mint(n=300):
        mine = [gensym("op-race") for _ in range(n)]
        with lock:
            names.extend(mine)

    threads = [threading.Thread(target=mint) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(names) == len(set(names)) == 8 * 300


def test_compute_scope_env_export_is_concurrency_safe():
    """A finishing scope must not clobber a live sibling's env export."""
    var = logs.COMPUTE_ID_ENV_VAR
    os.environ.pop(var, None)
    release_a = threading.Event()
    a_exported = threading.Event()
    b_done = threading.Event()
    observed = {}

    def compute_a():
        with logs.compute_scope("c-AAA", export_env=True):
            a_exported.set()
            release_a.wait(timeout=10)
        observed["after_a_exit"] = os.environ.get(var)

    def compute_b():
        a_exported.wait(timeout=10)
        with logs.compute_scope("c-BBB", export_env=True):
            pass  # B enters and exits while A is still live
        b_done.set()

    ta = threading.Thread(target=compute_a)
    tb = threading.Thread(target=compute_b)
    ta.start()
    tb.start()
    assert b_done.wait(timeout=10)
    # B exited while A's scope is live: B saw A's id as "previous" and
    # restored it — A's export must still stand
    assert os.environ.get(var) == "c-AAA"
    release_a.set()
    ta.join(timeout=10)
    tb.join(timeout=10)
    # both scopes exited: the export is fully cleaned up
    assert os.environ.get(var) is None
    assert observed["after_a_exit"] is None


def test_compute_scope_env_export_drops_dead_previous():
    """Out-of-order exits: when B exits after A already finished, B must
    DROP A's id (a dead compute), not resurrect it into the env. Each
    scope runs on its own thread, like concurrent service computes."""
    var = logs.COMPUTE_ID_ENV_VAR
    os.environ.pop(var, None)
    a_in, a_exit, a_done = (threading.Event() for _ in range(3))
    b_in, b_exit, b_done = (threading.Event() for _ in range(3))

    def compute_a():
        with logs.compute_scope("c-dead", export_env=True):
            a_in.set()
            a_exit.wait(timeout=10)
        a_done.set()

    def compute_b():
        a_in.wait(timeout=10)
        with logs.compute_scope("c-later", export_env=True):
            b_in.set()
            b_exit.wait(timeout=10)
        b_done.set()

    ta = threading.Thread(target=compute_a)
    tb = threading.Thread(target=compute_b)
    ta.start()
    tb.start()
    assert b_in.wait(timeout=10)
    a_exit.set()                     # A dies first, while B is live
    assert a_done.wait(timeout=10)
    assert os.environ.get(var) == "c-later"
    b_exit.set()
    assert b_done.wait(timeout=10)
    # B must not restore the finished A's id
    assert os.environ.get(var) is None
    ta.join(timeout=10)
    tb.join(timeout=10)

    # ...but an EXTERNAL pin (operator-set, never scope-exported) is
    # always restored
    os.environ[var] = "operator-pin"
    with logs.compute_scope("c-x", export_env=True):
        assert os.environ.get(var) == "c-x"
    assert os.environ.get(var) == "operator-pin"
    os.environ.pop(var, None)


def _intermediate_stores(finalized) -> set:
    return {
        str(d["target"].store)
        for _, d in finalized.dag.nodes(data=True)
        if d.get("type") == "array" and isinstance(d.get("target"), LazyZarrArray)
    }


def test_two_concurrent_computes_bitwise_correct_disjoint_paths(spec):
    """The acceptance regression: two computes built and executed
    concurrently in one process produce bitwise-correct results and write
    their intermediates to non-colliding store paths."""
    an = np.arange(144, dtype=np.float64).reshape(12, 12)

    def build(k):
        a = ct.from_array(an, chunks=(3, 3), spec=spec)
        b = ct.map_blocks(lambda x, _k=k: x * _k, a, dtype=np.float64)
        return ct.map_blocks(lambda x, _k=k: x + _k, b, dtype=np.float64)

    r1, r2 = build(2.0), build(5.0)
    # the plans' materialized targets never collide, even within one
    # shared CUBED_TPU_CONTEXT_ID (names come from the locked counter)
    f1 = r1.plan._finalize(array_names=(r1.name,))
    f2 = r2.plan._finalize(array_names=(r2.name,))
    assert _intermediate_stores(f1).isdisjoint(_intermediate_stores(f2))

    results: dict = {}
    errors: list = []

    def run(key, arr, finalized):
        try:
            arr.plan.execute(
                executor=AsyncPythonDagExecutor(),
                array_names=(arr.name,),
                spec=spec,
                finalized=finalized,
            )
            results[key] = arr._read_stored()
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append((key, e))

    t1 = threading.Thread(target=run, args=("a", r1, f1))
    t2 = threading.Thread(target=run, args=("b", r2, f2))
    t1.start()
    t2.start()
    t1.join(timeout=120)
    t2.join(timeout=120)
    assert not errors, errors
    np.testing.assert_array_equal(results["a"], an * 2.0 + 2.0)
    np.testing.assert_array_equal(results["b"], an * 5.0 + 5.0)


def test_concurrent_computes_through_top_level_compute(spec):
    """Same regression through the public ``.compute()`` path (each
    thread owns its finalize + execute end-to-end)."""
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    results: dict = {}
    errors: list = []

    def run(k):
        try:
            a = ct.from_array(an, chunks=(4, 4), spec=spec)
            r = ct.map_blocks(lambda x, _k=k: x - _k, a, dtype=np.float64)
            results[k] = r.compute(executor=AsyncPythonDagExecutor())
        except BaseException as e:  # noqa: BLE001
            errors.append((k, e))

    threads = [
        threading.Thread(target=run, args=(float(k),)) for k in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    for k in range(4):
        np.testing.assert_array_equal(results[float(k)], an - float(k))
