"""Array-API data type functions. Reference parity:
cubed/array_api/data_type_functions.py (147 LoC)."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.ops import map_blocks
from .dtypes import (
    _all_dtypes,
    _boolean_dtypes,
    _complex_floating_dtypes,
    _integer_dtypes,
    _numeric_dtypes,
    _real_floating_dtypes,
    _signed_integer_dtypes,
    _unsigned_integer_dtypes,
    promote_types,
)


def astype(x, dtype, /, *, copy: bool = True):
    dtype = np.dtype(dtype)
    if not copy and dtype == x.dtype:
        return x

    def _astype(a, astype_dtype=None):
        return a.astype(astype_dtype)

    return map_blocks(_astype, x, dtype=dtype, astype_dtype=dtype)


def can_cast(from_, to, /) -> bool:
    if hasattr(from_, "dtype"):
        from_ = from_.dtype
    from_ = np.dtype(from_)
    to = np.dtype(to)
    try:
        return promote_types(from_, to) == to
    except TypeError:
        return False


@dataclass
class finfo_object:
    bits: int
    eps: float
    max: float
    min: float
    smallest_normal: float
    dtype: np.dtype


@dataclass
class iinfo_object:
    bits: int
    max: int
    min: int
    dtype: np.dtype


def finfo(type, /) -> finfo_object:
    fi = np.finfo(np.dtype(type))
    return finfo_object(
        fi.bits, float(fi.eps), float(fi.max), float(fi.min),
        float(fi.smallest_normal), fi.dtype,
    )


def iinfo(type, /) -> iinfo_object:
    ii = np.iinfo(np.dtype(type))
    return iinfo_object(ii.bits, int(ii.max), int(ii.min), np.dtype(type))


def isdtype(dtype, kind) -> bool:
    if isinstance(kind, tuple):
        return any(isdtype(dtype, k) for k in kind)
    dtype = np.dtype(dtype)
    if isinstance(kind, str):
        if kind == "bool":
            return dtype in _boolean_dtypes
        if kind == "signed integer":
            return dtype in _signed_integer_dtypes
        if kind == "unsigned integer":
            return dtype in _unsigned_integer_dtypes
        if kind == "integral":
            return dtype in _integer_dtypes
        if kind == "real floating":
            return dtype in _real_floating_dtypes
        if kind == "complex floating":
            return dtype in _complex_floating_dtypes
        if kind == "numeric":
            return dtype in _numeric_dtypes
        raise ValueError(f"Unrecognized data type kind: {kind!r}")
    return dtype == np.dtype(kind)


def result_type(*arrays_and_dtypes):
    """Array-API type promotion (no value-based promotion)."""
    dtypes = []
    scalars = []
    for a in arrays_and_dtypes:
        if isinstance(a, (int, float, complex)) and not hasattr(a, "dtype"):
            scalars.append(a)
        elif hasattr(a, "dtype"):
            dtypes.append(np.dtype(a.dtype))
        else:
            dtypes.append(np.dtype(a))
    if not dtypes:
        raise ValueError("at least one array or dtype is required")
    t = dtypes[0]
    for other in dtypes[1:]:
        t = promote_types(t, other)
    return t
