"""Runtime memory guard: RESOURCE classification, per-task guard modes,
admission step-down/restore, and chaos proofs that memory pressure degrades
concurrency gracefully (docs/reliability.md "Memory safety").

Tests that need the guard to actually *measure* (a readable
``/proc/self/status``) carry the ``mem`` marker and auto-skip elsewhere
(tests/conftest.py); classification/controller logic is platform-free.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import itertools
import pickle
import threading
import time

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime import faults, memory
from cubed_tpu.runtime.distributed import RemoteTaskError
from cubed_tpu.runtime.executors.python import PythonDagExecutor
from cubed_tpu.runtime.executors.python_async import (
    AsyncPythonDagExecutor,
    map_unordered,
)
from cubed_tpu.runtime.memory import (
    AdmissionController,
    MemoryGuardConfig,
    MemoryGuardExceededError,
    task_guard,
)
from cubed_tpu.runtime.resilience import Classification, RetryPolicy


# -- classification ------------------------------------------------------


@pytest.mark.parametrize(
    "exc",
    [
        MemoryError(),
        MemoryError("out of memory"),
        MemoryGuardExceededError(
            "over budget", chunk_key="k", measured=100, allowed=50
        ),
        RemoteTaskError("worker OOM", remote_type="MemoryError"),
        RemoteTaskError(
            "worker guard trip", remote_type="MemoryGuardExceededError"
        ),
    ],
)
def test_memory_failures_classify_resource(exc):
    cls = RetryPolicy().classify(exc)
    assert cls is Classification.RESOURCE
    assert cls is not Classification.FAIL_FAST
    assert cls is not Classification.RETRY


def test_guard_error_survives_pickling():
    e = MemoryGuardExceededError(
        "task k measured 100 > 50",
        chunk_key="k",
        measured=100,
        allowed=50,
        op_name="op-x",
    )
    e2 = pickle.loads(pickle.dumps(e))
    assert isinstance(e2, MemoryGuardExceededError)
    assert (e2.chunk_key, e2.measured, e2.allowed, e2.op_name) == (
        "k", 100, 50, "op-x"
    )
    assert e2.wire_payload["kind"] == "memory_guard"
    assert RetryPolicy().classify(e2) is Classification.RESOURCE


# -- config / activation -------------------------------------------------


def test_guard_config_roundtrip_and_validation():
    cfg = MemoryGuardConfig(mode="enforce", allowed_mem=123)
    raw = cfg.to_env_json()
    assert MemoryGuardConfig.from_dict(__import__("json").loads(raw)) == cfg
    with pytest.raises(ValueError, match="invalid memory_guard mode"):
        MemoryGuardConfig(mode="nope")
    with pytest.raises(ValueError, match="unknown MemoryGuardConfig fields"):
        MemoryGuardConfig.from_dict({"mode": "off", "bogus": 1})
    assert not MemoryGuardConfig(mode="off", allowed_mem=100).enabled
    assert not MemoryGuardConfig(mode="enforce", allowed_mem=0).enabled
    assert MemoryGuardConfig(mode="enforce", allowed_mem=1).enabled


def test_scoped_arming_and_env_operator_override(monkeypatch):
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    assert memory.get_guard_config() is None
    with memory.scoped("enforce", allowed_mem=100, export_env=True):
        cfg = memory.get_guard_config()
        assert cfg is not None and cfg.mode == "enforce"
        assert cfg.allowed_mem == 100
        import os

        assert memory.MEMORY_GUARD_ENV_VAR in os.environ
    assert memory.get_guard_config() is None
    # the env var is the operator's override: Spec-level arming must not
    # clobber it, and resolution prefers it
    monkeypatch.setenv(
        memory.MEMORY_GUARD_ENV_VAR,
        MemoryGuardConfig(mode="off", allowed_mem=5).to_env_json(),
    )
    with memory.scoped("enforce", allowed_mem=100, export_env=True):
        assert memory.get_guard_config().mode == "off"
    assert memory.get_guard_config().mode == "off"
    # a bare mode string is also accepted from the env
    monkeypatch.setenv(memory.MEMORY_GUARD_ENV_VAR, "off")
    assert memory.get_guard_config().mode == "off"


def test_bare_mode_env_inherits_armed_allowed_mem(monkeypatch):
    """CUBED_TPU_MEMORY_GUARD=enforce overrides the MODE only: the budget
    comes from the Spec arming — an operator asking for enforcement must
    not silently zero allowed_mem and disable the guard."""
    monkeypatch.setenv(memory.MEMORY_GUARD_ENV_VAR, "enforce")
    with memory.scoped("observe", allowed_mem=777):
        cfg = memory.get_guard_config()
        assert cfg.mode == "enforce"
        assert cfg.allowed_mem == 777
        assert cfg.enabled
    # no Spec armed: the bare mode alone has no budget -> guard inactive
    cfg = memory.get_guard_config()
    assert cfg.mode == "enforce" and not cfg.enabled
    # invalid bare mode raises loudly rather than silently downgrading
    monkeypatch.setenv(memory.MEMORY_GUARD_ENV_VAR, "strict")
    with pytest.raises(ValueError, match="invalid memory_guard mode"):
        memory.get_guard_config()


def test_spec_memory_guard_validation(tmp_path):
    spec = ct.Spec(work_dir=str(tmp_path), memory_guard="enforce")
    assert spec.memory_guard == "enforce"
    assert ct.Spec(work_dir=str(tmp_path)).memory_guard is None
    with pytest.raises(ValueError, match="invalid memory_guard"):
        ct.Spec(work_dir=str(tmp_path), memory_guard="strict")


def test_guard_off_is_noop(monkeypatch):
    """mode=off: no guarded task registered, no sampler woken, empty stats
    contribution — the documented true no-op."""
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    with memory.scoped("off", allowed_mem=1):
        with task_guard("k", injected_bytes=10**12) as g:
            pass
        assert g.measured is None
        assert g.stats() == {}
        assert not memory._tasks
    # unarmed entirely: same
    with task_guard("k", injected_bytes=10**12) as g:
        pass
    assert g.stats() == {}


# -- the per-task guard (needs /proc) ------------------------------------


@pytest.mark.mem
def test_observe_mode_counts_and_warns(monkeypatch, caplog):
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    before = get_registry().snapshot()
    with memory.scoped("observe", allowed_mem=1024):
        with caplog.at_level("WARNING", logger="cubed_tpu.runtime.memory"):
            with task_guard("chunk-0", injected_bytes=10 * 1024 * 1024) as g:
                pass
    assert g.measured is not None and g.measured >= 10 * 1024 * 1024
    assert g.stats()["guard_mem_peak"] == g.measured
    delta = get_registry().snapshot_delta(before)
    assert delta.get("mem_guard_soft_exceeded", 0) == 1, delta
    assert any("memory guard (observe)" in r.message for r in caplog.records)


@pytest.mark.mem
def test_enforce_mode_raises_with_measured_and_allowed(monkeypatch):
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    with memory.scoped("enforce", allowed_mem=1024):
        with pytest.raises(MemoryGuardExceededError) as ei:
            with task_guard("chunk-1", injected_bytes=10 * 1024 * 1024):
                pass
    e = ei.value
    assert e.chunk_key == "chunk-1"
    assert e.measured >= 10 * 1024 * 1024
    assert e.allowed == 1024
    assert "allowed_mem" in str(e)


@pytest.mark.mem
def test_enforce_never_masks_the_body_error(monkeypatch):
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    with memory.scoped("enforce", allowed_mem=1):
        with pytest.raises(ValueError, match="body failed"):
            with task_guard("chunk-2", injected_bytes=10**9):
                raise ValueError("body failed")


@pytest.mark.mem
def test_guard_measures_real_allocation(monkeypatch):
    """No injection: a task that genuinely allocates well past allowed_mem
    is caught by RSS-growth sampling."""
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    from cubed_tpu.runtime.utils import execute_with_stats

    def hog(_m, config=None):
        import time

        big = np.ones(60 * 1024 * 1024 // 8, dtype=np.float64)  # ~60 MB
        time.sleep(0.08)  # give the sampler a few periods
        return big

    with memory.scoped("enforce", allowed_mem=16 * 1024 * 1024):
        with pytest.raises(MemoryGuardExceededError):
            execute_with_stats(hog, 0)


@pytest.mark.mem
def test_guard_stats_ride_task_end_event(monkeypatch):
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    from cubed_tpu.runtime.utils import execute_with_stats

    with memory.scoped("observe", allowed_mem=10**12):
        _, stats = execute_with_stats(lambda m, config=None: m, 7)
    assert "guard_mem_peak" in stats
    assert stats["guard_mem_peak"] >= 0


# -- admission controller ------------------------------------------------


def test_admission_stepdown_then_multiplicative_restore():
    before = get_registry().snapshot()
    c = AdmissionController()
    # unbounded until pressure: everything admits
    assert c.limit is None
    assert c.has_slot(64)
    c.step_down(8)
    assert c.limit == 4
    c.step_down(4)
    assert c.limit == 2
    # a full pressure-free window of successes doubles back
    c.on_success(True)
    c.on_success(True)
    assert c.limit == 4
    for _ in range(4):
        c.on_success(True)
    assert c.limit == 8
    # once the limit covers the highest concurrency seen (64), unbounded
    for _ in range(8):
        c.on_success(True)
    for _ in range(16):
        c.on_success(True)
    for _ in range(32):
        c.on_success(True)
    assert c.limit is None
    delta = get_registry().snapshot_delta(before)
    assert delta.get("mem_pressure_stepdowns", 0) == 2
    assert delta.get("mem_pressure_restores", 0) >= 2


def test_admission_pressure_does_not_restore():
    c = AdmissionController()
    c.step_down(8)
    assert c.limit == 4
    for _ in range(16):
        c.on_success(False)  # still pressured: hold, never restore
    assert c.limit == 4


def test_admission_floor_is_one():
    c = AdmissionController()
    c.step_down(1)
    assert c.limit == 1
    c.step_down(1)
    assert c.limit == 1
    assert c.has_slot(0) and not c.has_slot(1)


# -- map_unordered integration -------------------------------------------


def test_map_resource_failure_steps_down_then_completes():
    """A transient MemoryError wave halves concurrency, retries succeed,
    and a pressure-free success window restores the limit."""
    failed: set = set()
    lock = threading.Lock()

    def flaky_mem(i, config=None):
        with lock:
            first = i not in failed
            failed.add(i)
        if first and i < 4:
            raise MemoryError(f"transient pressure on {i}")
        return i

    before = get_registry().snapshot()
    admission = AdmissionController()
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        map_unordered(
            pool, flaky_mem, list(range(16)),
            retry_policy=RetryPolicy(retries=4, backoff_base=0.005),
            admission=admission,
        )
    delta = get_registry().snapshot_delta(before)
    assert delta.get("task_resource_failures", 0) == 4, delta
    assert delta.get("mem_pressure_stepdowns", 0) >= 1, delta
    assert delta.get("task_retries", 0) >= 4, delta


def test_map_resource_aborts_actionably_at_concurrency_one():
    """A task that fails RESOURCE even when admitted alone aborts with the
    actionable error — in far fewer attempts than blind retries would
    burn."""
    calls = {"n": 0}
    lock = threading.Lock()

    def always_oom(i, config=None):
        with lock:
            calls["n"] += 1
        # hold the slot briefly so sibling submissions are provably in
        # flight when the first RESOURCE failure halves the admission
        # window — without it, whether any submission ever has to WAIT
        # (tasks_throttled) is a thread-timing race that loses under a
        # loaded container
        time.sleep(0.05)
        raise MemoryGuardExceededError(
            f"task {i} measured 999 > 10", chunk_key=str(i),
            measured=999, allowed=10,
        )

    n_tasks, retries = 8, 6
    before = get_registry().snapshot()
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        with pytest.raises(MemoryGuardExceededError) as ei:
            map_unordered(
                pool, always_oom, list(range(n_tasks)),
                retry_policy=RetryPolicy(retries=retries, backoff_base=0.005),
                array_name="op-hog",
            )
    msg = str(ei.value)
    assert "op-hog" in msg and "allowed_mem" in msg and "rechunk" in msg
    assert "999" in msg and "10" in msg  # measured/allowed bytes named
    # degradation reached concurrency 1 and aborted: attempts are far
    # below the blind path's n_tasks * (retries + 1)
    assert calls["n"] < n_tasks * (retries + 1)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("mem_guard_hard_exceeded", 0) >= 1, delta
    assert delta.get("mem_guard_aborts", 0) == 1, delta
    assert delta.get("tasks_throttled", 0) > 0, delta


def test_map_resource_retries_draw_shared_budget():
    failed: set = set()
    lock = threading.Lock()

    def flaky_mem(i, config=None):
        with lock:
            first = i not in failed
            failed.add(i)
        if first:
            raise MemoryError("pressure")
        return i

    policy = RetryPolicy(retries=4, backoff_base=0.005)
    budget = policy.new_budget(8)
    spent_before = budget.spent
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        map_unordered(
            pool, flaky_mem, list(range(8)),
            retry_policy=policy, retry_budget=budget,
        )
    assert budget.spent - spent_before == 8  # one RESOURCE retry per input


def test_sequential_resource_exhaustion_is_actionable(tmp_path):
    def always_oom(_m, config=None):
        raise MemoryError("cannot allocate")

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    a = ct.from_array(np.ones((4, 4)), chunks=(2, 2), spec=spec)
    r = ct.map_blocks(always_oom, a, dtype=np.float64)
    with pytest.raises(MemoryGuardExceededError, match="allowed_mem"):
        r.compute(executor=PythonDagExecutor(retries=1))


# -- chaos: seeded memory spikes end-to-end ------------------------------

#: enforce-mode spike profile: ~1 in 4 task attempts "allocates" 600 MB
#: against a 500 MB budget; retries re-roll, so pressure recedes once
#: concurrency steps down
SPIKE = dict(
    seed=11, task_mem_spike_rate=0.25, task_mem_spike_bytes=600_000_000
)


@contextlib.contextmanager
def _pinned_plan_names(base: int):
    """Make a seeded spike test independent of suite ordering.

    Injector decisions hash ``(seed, site, chunk key, occurrence)``, and
    chunk keys embed gensym'd array names drawn from a PROCESS-GLOBAL
    counter — so which tasks spike depends on how many arrays every
    earlier test in the session happened to create. The
    degrade-and-complete tests' determinism argument (seeded pressure
    recedes on re-roll) only holds for a fixed key set: pin the counter
    for this plan's construction, then resume it exactly where the
    natural flow would have landed so no downstream test's names move."""
    from cubed_tpu import utils as ct_utils

    resume_at = next(ct_utils.sym_counter)  # the id natural flow would use
    ct_utils.sym_counter = itertools.count(base)
    try:
        yield
    finally:
        used = next(ct_utils.sym_counter) - base
        ct_utils.sym_counter = itertools.count(resume_at + used)


class _StatsCapture:
    stats: dict = {}

    def on_compute_end(self, event):
        self.stats = event.executor_stats or {}


def _assert_degraded_and_correct(cap, result, expected, local_inject=True):
    np.testing.assert_array_equal(result, expected)  # bitwise-correct
    if local_inject:
        # injection rolls happen in the client process only for in-process
        # executors; pool/fleet workers roll (and count) in their own
        # registries — there the guard trips reaching the client are the
        # cross-boundary proof
        assert cap.stats.get("faults_injected_task_mem_spike", 0) > 0, (
            cap.stats
        )
    assert cap.stats.get("mem_guard_hard_exceeded", 0) > 0, cap.stats
    assert cap.stats.get("mem_pressure_stepdowns", 0) > 0, cap.stats
    assert cap.stats.get("tasks_throttled", 0) > 0, cap.stats


@pytest.mark.chaos
@pytest.mark.mem
def test_chaos_threaded_mem_spikes_degrade_and_complete(tmp_path):
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        fault_injection=SPIKE, memory_guard="enforce",
    )
    an = np.arange(400, dtype=np.float64).reshape(20, 20)
    cap = _StatsCapture()
    with _pinned_plan_names(900_000_000):
        a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 100 tasks
        result = xp.add(a, 1.0).compute(
            executor=AsyncPythonDagExecutor(
                retry_policy=RetryPolicy(retries=6, backoff_base=0.005, seed=0)
            ),
            callbacks=[cap],
        )
    _assert_degraded_and_correct(cap, result, an + 1.0)


@pytest.mark.chaos
@pytest.mark.mem
def test_chaos_multiprocess_mem_spikes_degrade_and_complete(tmp_path):
    """Spikes fire in spawned pool workers (guard + injector both inherited
    via env); the guard error pickles back and the client steps down.

    One worker process, deliberately: injector decisions are per-process
    occurrences, so with several workers a spiked task whose retry lands
    on a *fresh* process repeats the original decision (documented
    faults.py caveat) — pressure then never recedes for that task, which
    is the unfixable-abort scenario, not this recede-and-complete one.
    A single worker's occurrence counters advance across every attempt, so
    retries re-roll and the seeded pressure deterministically recedes;
    step-down/throttling still engage (25 tasks >> 1 slot)."""
    from cubed_tpu.runtime.executors.multiprocess import MultiprocessDagExecutor

    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        fault_injection=dict(SPIKE, task_mem_spike_rate=0.2),
        memory_guard="enforce",
    )
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    cap = _StatsCapture()
    with _pinned_plan_names(910_000_000):
        a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 25 tasks
        result = xp.add(a, 3.0).compute(
            executor=MultiprocessDagExecutor(
                max_workers=1,
                retry_policy=RetryPolicy(
                    retries=6, backoff_base=0.005, seed=0
                ),
            ),
            callbacks=[cap],
        )
    _assert_degraded_and_correct(cap, result, an + 3.0, local_inject=False)


@pytest.mark.chaos
@pytest.mark.mem
def test_chaos_distributed_mem_spikes_degrade_and_complete(tmp_path):
    """Spikes fire on fleet workers (guard config mirrored via task
    messages); RemoteTaskError carries the guard type across the wire and
    the coordinator-side map steps down.

    One worker process (two task threads) for the same reason as the
    multiprocess test: per-process injector occurrences mean a retry
    routed to a different worker would repeat the original spike decision,
    turning recede-able pressure into the unfixable-abort scenario."""
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        fault_injection=dict(SPIKE, task_mem_spike_rate=0.2),
        memory_guard="enforce",
    )
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    cap = _StatsCapture()
    with DistributedDagExecutor(
        n_local_workers=1,
        worker_threads=2,
        retry_policy=RetryPolicy(retries=6, backoff_base=0.005, seed=0),
    ) as ex:
        with _pinned_plan_names(920_000_000):
            a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 64 tasks
            result = xp.add(a, 1.0).compute(executor=ex, callbacks=[cap])
    _assert_degraded_and_correct(cap, result, an + 1.0, local_inject=False)


@pytest.mark.chaos
@pytest.mark.mem
def test_chaos_unfixable_over_memory_op_aborts_promptly(tmp_path):
    """rate=1.0: every attempt spikes — degradation reaches concurrency 1,
    then the compute aborts with the actionable error instead of burning
    the whole budget at full concurrency."""
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        fault_injection=dict(
            seed=5, task_mem_spike_rate=1.0, task_mem_spike_bytes=600_000_000
        ),
        memory_guard="enforce",
    )
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 16 tasks per op
    n_tasks, retries = 16, 6
    cap = _StatsCapture()
    with pytest.raises(MemoryGuardExceededError, match="allowed_mem"):
        xp.add(a, 1.0).compute(
            executor=AsyncPythonDagExecutor(
                retry_policy=RetryPolicy(
                    retries=retries, backoff_base=0.005, seed=0
                )
            ),
            callbacks=[cap],
        )
    # fewer attempts than the plain RETRY path would consume (metrics)
    assert cap.stats.get("tasks_started", 0) < n_tasks * (retries + 1), (
        cap.stats
    )
    assert cap.stats.get("mem_guard_aborts", 0) >= 1, cap.stats


@pytest.mark.chaos
def test_chaos_guard_off_ignores_spikes(tmp_path, monkeypatch):
    """memory_guard='off' with spike injection armed: spikes are rolled
    but nothing measures, so the compute runs exactly as before."""
    monkeypatch.delenv(memory.MEMORY_GUARD_ENV_VAR, raising=False)
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        fault_injection=dict(
            seed=5, task_mem_spike_rate=1.0, task_mem_spike_bytes=10**12
        ),
        memory_guard="off",
    )
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    cap = _StatsCapture()
    result = xp.add(a, 1.0).compute(
        executor=AsyncPythonDagExecutor(), callbacks=[cap]
    )
    np.testing.assert_array_equal(result, an + 1.0)
    assert cap.stats.get("mem_guard_hard_exceeded", 0) == 0
    assert cap.stats.get("mem_guard_soft_exceeded", 0) == 0
    assert cap.stats.get("mem_pressure_stepdowns", 0) == 0


# -- multiprocess pool-death diagnostics (satellite) ---------------------


def test_pool_death_exitcode_hint():
    from cubed_tpu.runtime.executors.multiprocess import exitcode_hint

    assert "likely OOM-killed (SIGKILL)" in exitcode_hint([-9])
    assert "likely OOM-killed (SIGKILL)" in exitcode_hint([137])
    assert exitcode_hint([1]) == "exitcode 1"
    assert exitcode_hint([]) == "unknown exit code"


class _DieSigkill:
    """First invocation SIGKILLs its own worker process (a real OOM-kill
    shape); later invocations, in the rebuilt pool, succeed."""

    def __init__(self, marker):
        self.marker = marker

    def __call__(self, i):
        import os

        if i == 0 and not os.path.exists(self.marker):
            open(self.marker, "w").close()
            os.kill(os.getpid(), 9)
        return i


def test_multiprocess_oom_kill_detected_and_pool_halved(tmp_path, caplog):
    import concurrent.futures as cf
    import multiprocessing
    import os

    from cubed_tpu.runtime.executors.multiprocess import MultiprocessDagExecutor

    ex = MultiprocessDagExecutor(max_workers=2, retries=2)
    marker = str(tmp_path / "oomed")
    ctx = multiprocessing.get_context("spawn")
    before = get_registry().snapshot()
    admission = AdmissionController()
    pool = cf.ProcessPoolExecutor(max_workers=2, mp_context=ctx)
    try:
        with caplog.at_level("WARNING"):
            pool = ex._map_surviving_pool_crash(
                pool, ctx, _DieSigkill(marker), [0, 1], retries=2,
                admission=admission,
            )
        # the rebuilt pool runs at half size after an OOM-kill
        assert getattr(pool, "_max_workers") == 1
    finally:
        pool.shutdown(wait=False, cancel_futures=True)
    assert os.path.exists(marker)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("worker_oom_kills", 0) >= 1, delta
    # the controller stepped down with the pool (it may have restored by
    # completion — restore-on-success is the design — so assert the step)
    assert delta.get("mem_pressure_stepdowns", 0) >= 1, delta
    assert any(
        "likely OOM-killed (SIGKILL)" in r.getMessage()
        for r in caplog.records
    ), [r.getMessage() for r in caplog.records]


# -- per-op over-projection flag (satellite) -----------------------------


def test_per_op_summary_flags_mem_over_projected():
    from cubed_tpu.observability.callback import _ComputeAggregator
    from cubed_tpu.observability.events import PlanRow
    from cubed_tpu.runtime.types import (
        OperationEndEvent,
        OperationStartEvent,
        TaskEndEvent,
    )

    agg = _ComputeAggregator()
    agg.plan = [
        PlanRow(
            array_name="op-big", op_name="add", projected_mem=1_000_000,
            reserved_mem=0, num_tasks=1,
        )
    ]
    agg.on_operation_start(OperationStartEvent("op-big", 1))
    agg.on_task_end(
        TaskEndEvent(array_name="op-big", guard_mem_peak=500_000_000)
    )
    agg.on_operation_end(OperationEndEvent("op-big", 1))
    row = agg.summary()["per_op"]["op-big"]
    assert row["guard_peak_mem"] == 500_000_000
    assert row["mem_over_projected"] is True


# -- spike injector determinism ------------------------------------------


def test_task_mem_spike_rolls_are_seeded_and_per_occurrence():
    inj = faults.FaultInjector(
        faults.FaultConfig(
            seed=3, task_mem_spike_rate=0.5, task_mem_spike_bytes=123
        )
    )
    rolls = [inj.task_mem_spike("k") for _ in range(32)]
    inj2 = faults.FaultInjector(
        faults.FaultConfig(
            seed=3, task_mem_spike_rate=0.5, task_mem_spike_bytes=123
        )
    )
    assert rolls == [inj2.task_mem_spike("k") for _ in range(32)]  # replay
    assert 0 in rolls and 123 in rolls  # both outcomes occur at 50%
    # rate 0 or no bytes: never fires, no counter work
    inj3 = faults.FaultInjector(faults.FaultConfig(seed=3))
    assert inj3.task_mem_spike("k") == 0
