"""TSQR on device: out-of-core QR throughput on one chip.

Framework leg: ``xp.linalg.qr`` over a tall-skinny f32 array
(4M x 64 = 1 GB; 16 row panels of 64 MB) on the JaxExecutor — the panels
batch into one jit(vmap) dispatch, the stacked-R QR is a single small
task, and Q re-forms blockwise. Raw leg: one ``jnp.linalg.qr`` of the
same array in a single jit for the lower bound.

The reference has no QR at all, so there is no baseline to beat — the
numbers position the framework against raw JAX on identical math.
Output: one JSON line per leg + a summary. Run with the device env.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

M, N = 4_000_000, 64
CHUNK_ROWS = 250_000  # 16 panels x 64 MB
BYTES = M * N * 4
FLOPS = 2 * M * N * N  # tall-skinny QR ~ 2mn^2
REPS = 3


def framework_leg() -> dict:
    import numpy as np

    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    import cubed_tpu.random
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="6GB")
    executor = JaxExecutor(compute_dtype="float32")

    def build():
        a = cubed_tpu.random.random((M, N), chunks=(CHUNK_ROWS, N), spec=spec)
        q, r = xp.linalg.qr(a)
        # consume both factors on device: orthonormality residual is a
        # scalar fetch and verifies correctness in the same pass
        qtq = xp.matmul(xp.matrix_transpose(q), q)
        eye = xp.asarray(np.eye(N), spec=spec)
        return xp.max(xp.abs(xp.subtract(qtq, eye)))

    resid = float(build().compute(executor=executor))  # compile + caches
    assert resid < 1e-3, resid
    best = float("inf")
    for _ in range(REPS):
        s = build()
        t0 = time.perf_counter()
        float(s.compute(executor=executor))
        best = min(best, time.perf_counter() - t0)
    return {"leg": "framework_tsqr", "elapsed_s": round(best, 4),
            "gb_per_s": round(BYTES / best / 1e9, 2),
            "gflops": round(FLOPS / best / 1e9, 1),
            "orthonormality_residual": float(resid)}


def raw_leg() -> dict:
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_threefry_partitionable", True)

    @jax.jit
    def step(seed):
        key = jax.random.fold_in(jax.random.key(0), seed * 7919)
        a = jax.random.uniform(key, (M, N), dtype=jnp.float32)
        q, r = jnp.linalg.qr(a)
        return jnp.max(jnp.abs(q.T @ q - jnp.eye(N, dtype=jnp.float32)))

    float(step(0))  # compile
    best = float("inf")
    for i in range(REPS):
        t0 = time.perf_counter()
        float(step(100 + i))  # distinct seed defeats the tunnel result cache
        best = min(best, time.perf_counter() - t0)
    return {"leg": "raw_jax_qr", "elapsed_s": round(best, 4),
            "gb_per_s": round(BYTES / best / 1e9, 2),
            "gflops": round(FLOPS / best / 1e9, 1)}


def main() -> int:
    fw = framework_leg()
    print(json.dumps(fw), flush=True)
    raw = raw_leg()
    print(json.dumps(raw), flush=True)
    print(json.dumps({
        "leg": "summary",
        "framework_gb_per_s": fw["gb_per_s"],
        "raw_jax_gb_per_s": raw["gb_per_s"],
        "fw_over_raw": round(raw["elapsed_s"] / fw["elapsed_s"], 3),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
