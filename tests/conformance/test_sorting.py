"""Sorting conformance (extension beyond the reference, which skips these).

Parity role: array-api-tests test_sorting_functions.py.
"""

from __future__ import annotations

import os

import numpy as np
import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

import cubed_tpu.array_api as xp

from .harness import REAL_FLOAT_DTYPES, arrays, assert_matches, run, wrap


@pytest.fixture(autouse=True)
def _force_network(monkeypatch):
    # conformance shapes are small enough that the memory heuristic would
    # route every multi-chunk sort to the one-kernel path; force the
    # bitonic network so the fuzz covers it (numblocks==1 axes still take
    # the plain path, keeping both in play)
    monkeypatch.setenv("CUBED_TPU_SORT_NETWORK", "force")


@given(data=st.data())
def test_sort(data, spec):
    an = data.draw(arrays(dtypes=REAL_FLOAT_DTYPES))
    axis = data.draw(st.integers(-an.ndim, an.ndim - 1))
    descending = data.draw(st.booleans())
    got = run(xp.sort(wrap(an, spec), axis=axis, descending=descending))
    expect = np.sort(an, axis=axis)
    if descending:
        expect = np.flip(expect, axis=axis)
    assert_matches(got, expect)


#: the two argsort fuzzers cost ~1.5 s/example through the full network;
#: default lower than the profile's, but deep runs still scale them
_ARGSORT_EXAMPLES = int(os.environ.get("CONFORMANCE_EXAMPLES", "8"))


@settings(max_examples=_ARGSORT_EXAMPLES)
@given(data=st.data())
def test_argsort_values(data, spec):
    # indices themselves may differ on ties across implementations when
    # stable=False; validate by GATHERING — the reordered values must match
    an = data.draw(arrays(dtypes=(np.float64,)))
    axis = data.draw(st.integers(0, an.ndim - 1))
    descending = data.draw(st.booleans())
    idx = run(xp.argsort(wrap(an, spec), axis=axis, descending=descending))
    assert idx.dtype == np.int64
    gathered = np.take_along_axis(an, idx, axis=axis)
    expect = np.sort(an, axis=axis)
    if descending:
        expect = np.flip(expect, axis=axis)
    np.testing.assert_allclose(gathered, expect)


def test_argsort_stable_ties(spec):
    # stable: equal elements keep their original relative order
    an = np.asarray([3.0, 1.0, 3.0, 1.0, 2.0, 1.0])
    import cubed_tpu as ct

    a = ct.from_array(an, chunks=(2,), spec=spec)
    idx = run(xp.argsort(a, stable=True))
    np.testing.assert_array_equal(idx, np.argsort(an, stable=True))
    idx_desc = run(xp.argsort(a, descending=True, stable=True))
    # descending stable: among equal values, earlier positions first
    np.testing.assert_array_equal(idx_desc, np.asarray([0, 2, 4, 1, 3, 5]))


@settings(max_examples=_ARGSORT_EXAMPLES)
@given(data=st.data())
def test_argsort_integer_dtypes(data, spec):
    # uints and INT_MIN broke a negation-based descending implementation
    from .harness import INT_DTYPES, UINT_DTYPES

    dt = data.draw(st.sampled_from(INT_DTYPES + UINT_DTYPES))
    an = data.draw(arrays(dtypes=(dt,), min_dims=1))
    lo = np.iinfo(dt).min
    if data.draw(st.booleans()) and an.size:
        an = an.copy()
        an.flat[0] = lo  # plant the dtype minimum
    axis = data.draw(st.integers(0, an.ndim - 1))
    descending = data.draw(st.booleans())
    idx = run(xp.argsort(wrap(an, spec), axis=axis, descending=descending))
    gathered = np.take_along_axis(an, idx, axis=axis)
    expect = np.sort(an, axis=axis)
    if descending:
        expect = np.flip(expect, axis=axis)
    np.testing.assert_array_equal(gathered, expect)


def test_argsort_descending_numpy_backend(tmp_path):
    """The numpy-backend branch (flip/remap, no negation) on uint + INT_MIN."""
    import os
    import subprocess
    import sys

    script = r"""
import sys
sys.path.insert(0, {repo!r})
import numpy as np
import cubed_tpu as ct
import cubed_tpu.array_api as xp
spec = ct.Spec(work_dir={wd!r}, allowed_mem="100MB")
for an in [
    np.asarray([0, 5, 3], dtype=np.uint8),
    np.asarray([np.iinfo(np.int8).min, 4, -2, 4], dtype=np.int8),
    np.asarray([2.0, 1.0, 2.0, 0.0]),
]:
    a = ct.from_array(an, chunks=(2,), spec=spec)
    idx = np.asarray(xp.argsort(a, descending=True).compute())
    got = np.take_along_axis(an, idx, axis=0)
    expect = np.flip(np.sort(an))
    assert np.array_equal(got, expect), (an.dtype, idx, got, expect)
    # stability: ties keep first-appearance order
    order = np.lexsort((np.arange(len(an)), -an.astype(np.float64)))
    assert np.array_equal(idx, order), (an.dtype, idx, order)
print("numpy-backend descending argsort OK")
"""
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = {k: v for k, v in os.environ.items()}
    env["CUBED_TPU_BACKEND"] = "numpy"
    out = subprocess.run(
        [sys.executable, "-c", script.format(repo=repo, wd=str(tmp_path))],
        env=env, capture_output=True, text=True, timeout=240,
    )
    assert out.returncode == 0, out.stderr[-1500:]
    assert "OK" in out.stdout


def test_sort_rechunks_multi_chunk_axis(spec):
    import cubed_tpu as ct

    an = np.random.default_rng(0).random((9, 12))
    a = ct.from_array(an, chunks=(3, 4), spec=spec)  # 3 chunks along axis 1
    got = run(xp.sort(a, axis=1))
    np.testing.assert_allclose(got, np.sort(an, axis=1))


def test_sort_rejects_bool(spec):
    import cubed_tpu as ct

    a = ct.from_array(np.zeros(4, dtype=bool), chunks=(2,), spec=spec)
    with pytest.raises(TypeError):
        xp.sort(a)


def test_sort_axis_validation(spec):
    import cubed_tpu as ct

    a = ct.from_array(np.zeros((3, 4)), chunks=(2, 2), spec=spec)
    with pytest.raises(IndexError):
        xp.sort(a, axis=5)
    with pytest.raises(IndexError):
        xp.argsort(a, axis=-3)
    s0 = ct.from_array(np.float64(3.0).reshape(()), chunks=(), spec=spec)
    with pytest.raises(ValueError):
        xp.sort(s0)


@settings(deadline=None)
@given(data=st.data())
def test_searchsorted_property(data, spec):
    import cubed_tpu as ct

    n1 = data.draw(st.integers(1, 30))
    x1n = np.sort(data.draw(arrays(dtypes=(np.float64,), shape=(n1,))))
    shape2 = data.draw(st.sampled_from([(7,), (3, 5), (2, 2, 3)]))
    x2n = data.draw(arrays(dtypes=(np.float64,), shape=shape2))
    side = data.draw(st.sampled_from(["left", "right"]))
    c1 = data.draw(st.integers(1, n1))
    x1 = ct.from_array(x1n, chunks=(c1,), spec=spec)
    x2 = ct.from_array(x2n, chunks=tuple(max(1, s // 2) for s in shape2), spec=spec)
    got = np.asarray(xp.searchsorted(x1, x2, side=side).compute())
    np.testing.assert_array_equal(got, np.searchsorted(x1n, x2n, side=side))
