"""Plan-fusion (traced segment) behavior of the JAX executor.

The fused path must be an invisible optimization: results identical to
``fuse_plan=False`` (per-op eager execution) across representative plan
shapes, including the ones that exercise segment boundaries (storage-reading
map_direct bodies, large host sources) and in-segment fast paths (rechunk
alias, whole-array elementwise, bucketed ragged grids, RNG seed hoisting).
"""

from __future__ import annotations

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
import cubed_tpu.random
from cubed_tpu.runtime.executors.jax import JaxExecutor


@pytest.fixture
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB", reserved_mem=0)


def _both(arr):
    fused = arr.compute(executor=JaxExecutor(fuse_plan=True))
    eager = arr.compute(executor=JaxExecutor(fuse_plan=False))
    return np.asarray(fused), np.asarray(eager)


def test_fused_elementwise_chain(spec):
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    b = ct.from_array(an, chunks=(4, 4), spec=spec)
    fused, eager = _both(xp.add(xp.multiply(a, 2.0), b))
    np.testing.assert_allclose(fused, an * 2 + an)
    np.testing.assert_allclose(eager, an * 2 + an)


def test_fused_reduction_tree(spec):
    an = np.arange(400, dtype=np.float64).reshape(20, 20)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    fused, eager = _both(xp.mean(a, axis=0))
    np.testing.assert_allclose(fused, an.mean(axis=0))
    np.testing.assert_allclose(eager, an.mean(axis=0))


def test_fused_ragged_grid_and_index(spec):
    an = np.arange(19 * 13, dtype=np.float64).reshape(19, 13)
    a = ct.from_array(an, chunks=(5, 4), spec=spec)  # ragged both dims
    fused, eager = _both(xp.sum(a[1:, ::2]))
    np.testing.assert_allclose(fused, an[1:, ::2].sum())
    np.testing.assert_allclose(eager, an[1:, ::2].sum())


def test_fused_rechunk_alias(spec):
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 8), spec=spec)
    fused, eager = _both(xp.sum(a.rechunk((8, 2))))
    np.testing.assert_allclose(fused, an.sum())
    np.testing.assert_allclose(eager, an.sum())


def test_fused_random_seed_hoisting(spec):
    # two plans with different seeds must produce different data through the
    # SAME traced program structure (the seed is an input, not a constant)
    r1 = float(
        xp.mean(cubed_tpu.random.random((32, 32), chunks=8, spec=spec)).compute(
            executor=JaxExecutor()
        )
    )
    r2 = float(
        xp.mean(cubed_tpu.random.random((32, 32), chunks=8, spec=spec)).compute(
            executor=JaxExecutor()
        )
    )
    assert 0.3 < r1 < 0.7 and 0.3 < r2 < 0.7
    assert r1 != r2  # different seeds -> different arrays


def test_fused_segment_boundary_concat(spec):
    # concat declares whole_concat: with resident sources it becomes one
    # device concatenate INSIDE the traced segment (no eager boundary)
    an = np.arange(24, dtype=np.float64).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    b = ct.from_array(an + 1, chunks=(2, 3), spec=spec)
    fused, eager = _both(xp.sum(xp.concat([xp.multiply(a, 2.0), b], axis=0)))
    expect = np.concatenate([an * 2, an + 1], axis=0).sum()
    np.testing.assert_allclose(fused, expect)
    np.testing.assert_allclose(eager, expect)


def test_var_multiaxis_region_combine(spec):
    """var/std with axis=None over a multi-chunk 2-d grid: the executor's
    region combine hands _var_combine a MULTI-AXIS block region in one call
    (regression: it reduced only axis 0, silently corrupting the result —
    found by the differential fuzzer)."""
    an = np.asarray([[0.0, 1.0], [1.0, 1.0]])
    a = ct.from_array(an, chunks=(1, 1), spec=spec)  # 4 single-element blocks
    got = float(xp.var(a).compute(executor=JaxExecutor()))
    np.testing.assert_allclose(got, an.var())
    an2 = np.random.default_rng(0).random((6, 9))
    b = ct.from_array(an2, chunks=(2, 3), spec=spec)
    np.testing.assert_allclose(
        float(xp.std(b).compute(executor=JaxExecutor())), an2.std(), rtol=1e-12
    )


def test_segment_task_events_partition_wall_time(spec):
    """Per-op TaskEndEvents of a fused segment must PARTITION the segment's
    wall time (contiguous, non-overlapping, summing to the total) — not each
    span the whole segment (which over-reports history totals len(ops)x)."""
    from cubed_tpu.runtime.types import Callback

    events = []

    class Capture(Callback):
        def on_task_end(self, event):
            events.append(event)

    an = np.arange(400, dtype=np.float64).reshape(20, 20)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    xp.mean(xp.multiply(a, 2.0)).compute(
        executor=JaxExecutor(), callbacks=[Capture()]
    )
    assert len(events) >= 2
    spans = sorted(
        (e.function_start_tstamp, e.function_end_tstamp) for e in events
    )
    for (s1, e1), (s2, e2) in zip(spans, spans[1:]):
        assert e1 <= s2 + 1e-9  # non-overlapping
    total = sum(e - s for s, e in spans)
    wall = max(e for _, e in spans) - min(s for s, _ in spans)
    assert total <= wall + 1e-6  # durations sum to (at most) the wall time


@pytest.mark.parametrize(
    "name",
    ["stack", "reshape", "broadcast_to", "eye", "flip", "repeat", "concat"],
)
def test_op_families_trace_without_fallback(name, spec):
    """These plan shapes must all run as traced segments — a regression here
    silently costs the eager path's per-op overhead."""
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()  # a struct hit would skip tracing legitimately
    an = np.arange(24, dtype=np.float64).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    b = ct.from_array(an + 1, chunks=(2, 3), spec=spec)
    exprs = {
        "stack": (xp.sum(xp.stack([a, b], axis=0)), an.sum() + (an + 1).sum()),
        "reshape": (xp.sum(xp.reshape(a, (24,))), an.sum()),
        "broadcast_to": (xp.sum(xp.broadcast_to(a, (3, 4, 6))), 3 * an.sum()),
        "eye": (xp.sum(xp.eye(7, chunks=3, spec=spec)), 7.0),
        "flip": (xp.sum(xp.flip(a, axis=0)), an.sum()),
        "repeat": (xp.sum(xp.repeat(a, 2, axis=1)), 2 * an.sum()),
        "concat": (xp.sum(xp.concat([a, b], axis=0)), an.sum() + (an + 1).sum()),
    }
    expr, expect = exprs[name]
    ex = JaxExecutor()
    val = float(expr.compute(executor=ex))
    np.testing.assert_allclose(val, expect)
    assert ex.stats["segments_traced"] >= 1
    assert ex.stats["trace_failures"] == 0
    assert ex.stats["eager_fallbacks"] == 0


def test_concat_traces_into_one_segment(spec):
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()
    an = np.arange(24, dtype=np.float64).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    b = ct.from_array(an + 1, chunks=(2, 3), spec=spec)
    s = xp.sum(xp.concat([xp.multiply(a, 2.0), b], axis=1))
    ex = JaxExecutor()
    val = float(s.compute(executor=ex))
    np.testing.assert_allclose(val, np.concatenate([an * 2, an + 1], axis=1).sum())
    assert ex.stats["segments_traced"] == 1  # one fused program, no break
    assert ex.stats["whole_concat_hits"] >= 1
    assert ex.stats["eager_fallbacks"] == 0
    assert ex.stats["trace_failures"] == 0


def test_fused_structured_mean_intermediates(spec):
    # mean uses dict-of-arrays ({n, total}) intermediates through the tree
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(3, 3), spec=spec)
    fused, eager = _both(xp.mean(a))
    np.testing.assert_allclose(fused, an.mean())
    np.testing.assert_allclose(eager, an.mean())


# ---------------------------------------------------------------------------
# executor stats: the fast paths must be *observably* taken. A silently broken
# fast path costs 10x quietly; these pins make it fail a test instead.
# ---------------------------------------------------------------------------


def test_stats_fused_elementwise_counts(spec):
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()  # force a real trace so path counters fire
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    b = ct.from_array(an, chunks=(4, 4), spec=spec)
    ex = JaxExecutor()
    result = xp.add(xp.multiply(a, 2.0), b).compute(executor=ex)
    np.testing.assert_allclose(np.asarray(result), an * 2 + an)
    assert ex.stats["segments_traced"] == 1
    assert ex.stats["trace_failures"] == 0
    assert ex.stats["eager_fallbacks"] == 0
    # the fused op must take a vectorized path, never per-chunk dispatch
    assert ex.stats["batched_ops"] + ex.stats["whole_array_hits"] >= 1
    assert ex.stats["chunked_ops"] == 0


def test_stats_vorticity_plan_fully_fused(spec):
    # the benchmark plan shape (bench.py WORKLOAD) at test size: the whole
    # pipeline must run as ONE traced segment with zero eager fallbacks
    def rnd():
        return cubed_tpu.random.random((12, 10, 8), chunks=4, spec=spec)

    a, b, x, y = rnd(), rnd(), rnd(), rnd()
    s = xp.mean(xp.add(xp.multiply(a[1:], x[1:]), xp.multiply(b[1:], y[1:])))
    ex = JaxExecutor()
    val = float(s.compute(executor=ex))
    assert 0.0 < val < 1.0
    assert ex.stats["segments_traced"] == 1
    assert ex.stats["trace_failures"] == 0
    assert ex.stats["eager_fallbacks"] == 0
    assert ex.stats["whole_select_errors"] == 0


def test_stats_segment_cache_hit_on_recompute(spec):
    # same plan structure twice: the second compute reuses the compiled
    # executable — via the structural fingerprint (no re-trace) or, with the
    # structural layer disabled, via the HLO hash (re-trace, no re-compile)
    an = np.arange(36, dtype=np.float64).reshape(6, 6)

    def build():
        a = ct.from_array(an, chunks=(3, 3), spec=spec)
        return xp.sum(xp.multiply(a, 3.7193))

    ex1 = JaxExecutor()
    ex2 = JaxExecutor()
    v1 = float(build().compute(executor=ex1))
    v2 = float(build().compute(executor=ex2))
    assert v1 == v2
    assert ex1.stats["segments_traced"] == 1
    assert ex2.stats["segments_traced"] == 1
    assert (
        ex2.stats["segment_cache_hits"] + ex2.stats["segment_struct_hits"] == 1
    )
    assert ex2.stats["segments_compiled"] == 0


def test_stats_eager_mode_traces_nothing(spec):
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    ex = JaxExecutor(fuse_plan=False)
    xp.add(a, 1.0).compute(executor=ex)
    assert ex.stats["segments_traced"] == 0
    assert ex.stats["eager_ops"] >= 1


def test_stats_reported_via_compute_end_event(spec):
    from cubed_tpu.runtime.types import Callback

    seen = {}

    class Capture(Callback):
        def on_compute_end(self, event):
            seen["stats"] = event.executor_stats

    an = np.arange(16, dtype=np.float64).reshape(4, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    ex = JaxExecutor()
    xp.sum(a).compute(executor=ex, callbacks=[Capture()])
    # executor_stats carries the executor's own counters merged with the
    # per-compute observability metrics (task counters, per_op summary)
    assert seen["stats"]["segments_traced"] == 1
    for key, val in ex.stats.items():
        assert seen["stats"][key] == val
    assert seen["stats"]["tasks_completed"] > 0
    assert "per_op" in seen["stats"]


# ---------------------------------------------------------------------------
# structural segment cache: repeat computes of identical plan shapes must
# skip tracing, rebind seeds, and never alias across different programs
# ---------------------------------------------------------------------------


def test_struct_cache_hit_skips_trace_and_rebinds_seed(spec):
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()

    def build():
        r = cubed_tpu.random.random((24, 24), chunks=6, spec=spec)
        return xp.mean(xp.multiply(r, 1.618))

    ex1, ex2 = JaxExecutor(), JaxExecutor()
    v1 = float(build().compute(executor=ex1))
    v2 = float(build().compute(executor=ex2))
    assert ex1.stats["segment_struct_hits"] == 0
    assert ex1.stats["segments_traced"] == 1
    assert ex2.stats["segment_struct_hits"] == 1  # tracing skipped entirely
    assert ex2.stats["segments_compiled"] == 0
    # both runs valid, and the DIFFERENT per-plan seed was rebound (the
    # cached program did not bake the first plan's randomness)
    assert 0.4 < v1 / 1.618 < 0.6 and 0.4 < v2 / 1.618 < 0.6


def test_struct_cache_stable_across_gensym_counter_positions(spec):
    """Identical plans built at arbitrary points of the process-global
    gensym counter must produce the SAME structural key. Regression: with
    variable-width gensym names (%03d), crossing a digit boundary (999 →
    1000) changed pickle string length-prefix bytes that the post-pickle
    name canonicalization cannot rewrite, silently missing the cache."""
    import itertools

    import cubed_tpu.utils as utilsmod
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()

    def build():
        r = cubed_tpu.random.random((12, 12), chunks=6, spec=spec)
        return xp.mean(xp.multiply(r, 1.618))

    # jump the shared gensym counter forward across what used to be the
    # %03d boundary between the two builds (monotonically — never
    # backwards, so node names stay unique within the process)
    utilsmod.sym_counter = itertools.count(
        max(995, next(utilsmod.sym_counter))
    )
    ex1, ex2 = JaxExecutor(), JaxExecutor()
    v1 = float(build().compute(executor=ex1))
    v2 = float(build().compute(executor=ex2))
    assert ex1.stats["segments_traced"] == 1
    assert ex2.stats["segment_struct_hits"] == 1, (
        "structurally identical plan missed the struct cache across a "
        "gensym counter digit boundary"
    )
    assert 0.4 < v1 / 1.618 < 0.6 and 0.4 < v2 / 1.618 < 0.6
    assert v1 != v2


def test_struct_cache_distinguishes_kernel_constants(spec):
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()
    an = np.arange(16.0).reshape(4, 4)

    def build(c):
        a = ct.from_array(an, chunks=(2, 2), spec=spec)
        return xp.sum(xp.multiply(a, c))

    ex1, ex2 = JaxExecutor(), JaxExecutor()
    v1 = float(build(2.0).compute(executor=ex1))
    v2 = float(build(3.0).compute(executor=ex2))
    assert ex2.stats["segment_struct_hits"] == 0  # different program
    assert v1 == an.sum() * 2 and v2 == an.sum() * 3


def test_struct_cache_distinguishes_chunking(spec):
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()
    an = np.arange(64.0).reshape(8, 8)

    def build(chunks):
        a = ct.from_array(an, chunks=chunks, spec=spec)
        return xp.sum(xp.negative(a))

    v1 = float(build((2, 2)).compute(executor=JaxExecutor()))
    ex2 = JaxExecutor()
    v2 = float(build((4, 4)).compute(executor=ex2))
    assert ex2.stats["segment_struct_hits"] == 0
    assert v1 == v2 == -an.sum()


def test_struct_cache_distinguishes_executor_config(spec):
    # matmul_precision changes the MXU pass count inside the same HLO
    # shape: a program cached for one precision must not be reused by an
    # executor configured for another
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()
    an = np.arange(16 * 16, dtype=np.float32).reshape(16, 16) / 256.0

    def build():
        a = ct.from_array(an, chunks=(8, 8), spec=spec)
        b = ct.from_array(an, chunks=(8, 8), spec=spec)
        return xp.sum(xp.matmul(a, b))

    ex1 = JaxExecutor()
    ex2 = JaxExecutor(matmul_precision="bfloat16")
    v1 = float(build().compute(executor=ex1))
    v2 = float(build().compute(executor=ex2))
    assert ex2.stats["segment_struct_hits"] == 0  # different config, no reuse
    expect = float(np.sum(an @ an))
    np.testing.assert_allclose(v1, expect, rtol=1e-5)
    np.testing.assert_allclose(v2, expect, rtol=2e-2)  # bf16 passes


def test_struct_cache_no_collision_on_gensym_like_user_strings(spec):
    # user closure strings that merely LOOK like gensym identifiers must not
    # normalize away: only this plan's own names are canonicalized
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()
    an = np.full((4, 4), 2.0)

    def build(tag):
        def kernel(block):
            return block * len(tag.split("-")[1])

        a = ct.from_array(an, chunks=(2, 2), spec=spec)
        return xp.sum(ct.map_blocks(kernel, a, dtype=a.dtype))

    ex1, ex2 = JaxExecutor(), JaxExecutor()
    v1 = float(build("exp-0010").compute(executor=ex1))
    v2 = float(build("exp-009876").compute(executor=ex2))
    assert v1 == an.sum() * 4
    assert v2 == an.sum() * 6  # a struct-cache collision would return *4


def test_struct_cache_hit_matches_fresh_result(spec):
    from cubed_tpu.runtime.executors import jax as jxm

    jxm._STRUCT_CACHE.clear()
    an = np.arange(36.0).reshape(6, 6)

    def build():
        a = ct.from_array(an, chunks=(2, 3), spec=spec)
        b = ct.from_array(an + 1, chunks=(2, 3), spec=spec)
        return xp.mean(xp.add(xp.multiply(a, 0.5), b))

    v1 = np.asarray(build().compute(executor=JaxExecutor()))
    ex2 = JaxExecutor()
    v2 = np.asarray(build().compute(executor=ex2))
    assert ex2.stats["segment_struct_hits"] == 1
    np.testing.assert_allclose(v1, (an * 0.5 + an + 1).mean())
    np.testing.assert_allclose(v2, v1)


def test_fused_output_also_persisted(spec, tmp_path):
    # a kept store must flush correctly after a traced segment
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    out = str(tmp_path / "out.zarr")
    ct.to_zarr(xp.add(a, 1.0), out, executor=JaxExecutor())
    readback = ct.from_zarr(out, spec=spec).compute()
    np.testing.assert_allclose(np.asarray(readback), an + 1.0)


def test_compute_dtype_f32_ingestion(spec):
    """f32 ingestion (VERDICT r4 #4): an f64 plan executed with
    ``compute_dtype="float32"`` computes on-device in single precision —
    including random generation — and casts back to the declared f64 at
    the store boundary, within f32 error bounds of the f64 result."""
    import cubed_tpu.random

    def build():
        a = cubed_tpu.random.random((40, 40), chunks=(13, 13), spec=spec)
        b = cubed_tpu.random.random((40, 40), chunks=(13, 13), spec=spec)
        return xp.mean(xp.add(xp.multiply(a, b), xp.sin(a)))

    f64 = np.asarray(build().compute(executor=JaxExecutor()))
    f32 = np.asarray(build().compute(executor=JaxExecutor(compute_dtype="float32")))
    assert f64.dtype == np.float64
    assert f32.dtype == np.float64  # declared dtype preserved at the boundary
    # different seeds each build, so compare statistically: both are means of
    # ~0.25+sin-ish uniform products over 1600 elements
    assert abs(float(f64) - float(f32)) < 0.1
    # a seed-held comparison: same plan, both precisions, one from_array source
    an = np.linspace(0.0, 1.0, 64, dtype=np.float64).reshape(8, 8)
    src = ct.from_array(an, chunks=(3, 3), spec=spec)
    expr = xp.sum(xp.sqrt(xp.abs(xp.sin(src) * 2.0 + 1.0)))
    r64 = float(expr.compute(executor=JaxExecutor()))
    an2 = np.linspace(0.0, 1.0, 64, dtype=np.float64).reshape(8, 8)
    src2 = ct.from_array(an2, chunks=(3, 3), spec=spec)
    expr2 = xp.sum(xp.sqrt(xp.abs(xp.sin(src2) * 2.0 + 1.0)))
    r32 = float(expr2.compute(executor=JaxExecutor(compute_dtype="float32")))
    np.testing.assert_allclose(r32, r64, rtol=1e-5)  # f32 eps * tree depth


def test_compute_dtype_restores_x64(spec):
    """The x64 flag is restored even when the plan fails mid-execution."""
    import jax

    assert jax.config.jax_enable_x64
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    xp.add(a, 1).compute(executor=JaxExecutor(compute_dtype="float32"))
    assert jax.config.jax_enable_x64

    def boom(x):
        raise ValueError("kernel boom")

    b = ct.map_blocks(boom, xp.ones((6, 6), chunks=(2, 2), spec=spec),
                      dtype=np.float64)
    with pytest.raises(Exception, match="kernel boom"):
        b.compute(executor=JaxExecutor(compute_dtype="float32"))
    assert jax.config.jax_enable_x64  # restored on the failure path too


def test_compute_dtype_invalid():
    with pytest.raises(ValueError, match="compute_dtype"):
        JaxExecutor(compute_dtype="bfloat16")


def test_matmul_precision_bf16(spec):
    """The MXU contraction opt-in: matmul under
    ``matmul_precision='bfloat16'`` runs the same plan with one-pass MXU
    contractions — f32-accumulated, inputs rounded to bf16 (~3 decimal
    digits), so the result tracks full precision to ~1e-2 relative."""
    an = np.linspace(0.0, 1.0, 64 * 48, dtype=np.float64).reshape(64, 48)
    bn = np.linspace(1.0, 2.0, 48 * 32, dtype=np.float64).reshape(48, 32)

    def build():
        a = ct.from_array(an, chunks=(16, 16), spec=spec)
        b = ct.from_array(bn, chunks=(16, 16), spec=spec)
        return xp.sum(xp.matmul(a, b))

    exact = float(build().compute(executor=JaxExecutor()))
    fast = float(build().compute(executor=JaxExecutor(
        compute_dtype="float32", matmul_precision="bfloat16")))
    np.testing.assert_allclose(fast, exact, rtol=2e-2)
    np.testing.assert_allclose(exact, float((an @ bn).sum()), rtol=1e-12)


def test_matmul_precision_invalid():
    with pytest.raises(ValueError, match="matmul_precision"):
        JaxExecutor(matmul_precision="int8")


def test_host_sliced_from_array_splits_cleanly(spec):
    """A >256KB from_array source runs as an EAGER op (its host data must
    not bake into a traced program as constants — XLA constant-folds op
    chains over baked data at compile time, measured at minutes for a
    sort network over a 4MB source) while downstream ops still trace.
    Regression x2: previously (a) 1-8MB sources were classified traceable
    and then trace-FAILED the whole segment to eager (their offsets block
    was backend-converted into a tracer the host block-id kernel can't
    consume), (b) the classifier threshold allowed the constant-bake."""
    n = 262_144  # 2MB f64: above the in-memory-virtual cap
    an = np.arange(n, dtype=np.float64)
    a = ct.from_array(an, chunks=(n // 8,), spec=spec)
    ex = JaxExecutor()
    v = float(xp.sum(xp.multiply(a, 2.0)).compute(executor=ex))
    assert v == 2.0 * an.sum()
    assert ex.stats["segments_traced"] == 1  # downstream traced
    assert ex.stats["trace_failures"] == 0   # no failed trace attempt
    assert ex.stats["eager_fallbacks"] == 0
    assert ex.stats["eager_ops"] >= 2        # create-arrays + the source op


def test_small_host_from_array_traces(spec):
    """A small in-memory source (VirtualInMemoryArray, <=1MB cap) is cheap
    to bake: the whole plan stays one traced segment."""
    n = 32_768  # 256KB f64
    an = np.arange(n, dtype=np.float64)
    a = ct.from_array(an, chunks=(n // 4,), spec=spec)
    ex = JaxExecutor()
    v = float(xp.sum(a).compute(executor=ex))
    assert v == an.sum()
    assert ex.stats["segments_traced"] == 1
    assert ex.stats["trace_failures"] == 0
    assert ex.stats["eager_fallbacks"] == 0
