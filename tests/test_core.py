"""Core integration tests, executor-parametrized.

Reference parity: cubed/tests/test_core.py (behavioral).
"""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.core.optimization import fuse_all_optimize_dag, simple_optimize_dag

from .utils import TaskCounter, all_executors


@pytest.fixture(params=all_executors(), ids=lambda e: e.name)
def executor(request):
    return request.param


def test_regular_chunks(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    assert a.chunks == ((2, 2, 2), (2, 2, 2))
    assert a.numblocks == (3, 3)
    assert a.npartitions == 9


def test_ragged_chunks(spec):
    a = xp.ones((7, 5), chunks=(3, 2), spec=spec)
    assert a.chunks == ((3, 3, 1), (2, 2, 1))


def test_add(spec, executor):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    c = xp.add(a, b)
    assert np.array_equal(c.compute(executor=executor), np.full((6, 6), 2.0))


def test_add_ragged(spec, executor):
    an = np.arange(35.0).reshape(7, 5)
    a = ct.from_array(an, chunks=(3, 2), spec=spec)
    b = ct.from_array(an, chunks=(3, 2), spec=spec)
    c = xp.add(a, b)
    assert np.allclose(c.compute(executor=executor), an + an)


def test_add_different_chunks(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(an, chunks=(3, 3), spec=spec)
    c = xp.add(a, b)
    assert np.allclose(c.compute(executor=executor), an + an)


def test_add_scalar(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = a + 5.0
    assert np.allclose(c.compute(executor=executor), an + 5.0)


def test_broadcast(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    bn = np.arange(6.0)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(bn, chunks=(2,), spec=spec)
    c = xp.add(a, b)
    assert np.allclose(c.compute(executor=executor), an + bn)


def test_sum(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    assert np.allclose(xp.sum(a).compute(executor=executor), an.sum())


def test_sum_axis(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    assert np.allclose(xp.sum(a, axis=0).compute(executor=executor), an.sum(axis=0))
    assert np.allclose(xp.sum(a, axis=1).compute(executor=executor), an.sum(axis=1))


def test_mean_keepdims(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    assert np.allclose(
        xp.mean(a, axis=1, keepdims=True).compute(executor=executor),
        an.mean(axis=1, keepdims=True),
    )


def test_fused_add_sum(spec, executor):
    a = xp.ones((10, 10), chunks=(3, 3), spec=spec)
    b = xp.ones((10, 10), chunks=(3, 3), spec=spec)
    s = xp.sum(xp.add(a, b))
    assert float(s.compute(executor=executor)) == 200.0


def test_multiple_outputs(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, a)
    c = xp.multiply(a, a)
    rb, rc = ct.compute(b, c, executor=executor)
    assert np.allclose(rb, an + an)
    assert np.allclose(rc, an * an)


def test_from_zarr_to_zarr(spec, executor, tmp_path):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    store = str(tmp_path / "out.zarr")
    ct.to_zarr(xp.add(a, 1.0), store, executor=executor)
    b = ct.from_zarr(store, spec=spec)
    assert np.allclose(b.compute(executor=executor), an + 1.0)


def test_rechunk(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = a.rechunk((3, 3))
    assert b.chunksize == (3, 3)
    assert np.allclose(b.compute(executor=executor), an)


def test_rechunk_staged(executor, tmp_path):
    # tight memory budget forces the two-pass (intermediate) rechunk
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem=20000, reserved_mem=0)
    an = np.arange(900.0).reshape(30, 30)
    a = ct.from_array(an, chunks=(30, 2), spec=spec)
    b = a.rechunk((2, 30))
    assert np.allclose(b.compute(executor=executor), an)


def test_compute_is_idempotent(spec, executor):
    a = xp.ones((4, 4), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    assert np.array_equal(b.compute(executor=executor), np.full((4, 4), 2.0))
    assert np.array_equal(b.compute(executor=executor), np.full((4, 4), 2.0))


def test_plan_scaling(spec):
    # plan size is O(ops); a long chain must build fast and count tasks
    a = xp.ones((4, 4), chunks=(2, 2), spec=spec)
    for _ in range(50):
        a = xp.add(a, 1)
    assert a.plan.num_tasks(optimize_graph=False) > 0


def test_callbacks(spec, executor):
    counter = TaskCounter()
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    b.compute(executor=executor, callbacks=[counter], optimize_graph=False)
    assert counter.value > 0


def test_resume(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    counter = TaskCounter()
    c.compute(callbacks=[counter], optimize_graph=False)
    n_first = counter.value
    counter2 = TaskCounter()
    c.compute(callbacks=[counter2], optimize_graph=False, resume=True)
    # everything already computed -> no (or far fewer) tasks
    assert counter2.value < n_first


def test_visualize(spec, tmp_path):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    out = b.visualize(filename=str(tmp_path / "plan"))
    import os

    assert os.path.exists(out)


def test_projected_mem_exceeded(tmp_path):
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem=1000, reserved_mem=0)
    a = xp.ones((100, 100), chunks=(100, 100), spec=spec)
    with pytest.raises(ValueError, match="exceeds allowed_mem"):
        xp.add(a, a)


def test_spec_mismatch(tmp_path):
    s1 = ct.Spec(work_dir=str(tmp_path), allowed_mem=100_000_000)
    s2 = ct.Spec(work_dir=str(tmp_path), allowed_mem=200_000_000)
    a = xp.ones((4, 4), chunks=(2, 2), spec=s1)
    b = xp.ones((4, 4), chunks=(2, 2), spec=s2)
    with pytest.raises(ValueError, match="same spec"):
        xp.add(a, b)


def test_optimization_fuses_map_chain(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    unopt = c.plan.num_tasks(optimize_graph=False)
    opt = c.plan.num_tasks(optimize_graph=True)
    assert opt < unopt
    assert np.array_equal(c.compute(), np.full((6, 6), 3.0))


def test_reduction_multiple_rounds(spec, executor):
    an = np.ones((64, 4))
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    s = xp.sum(a, axis=0, split_every=2)
    assert np.allclose(s.compute(executor=executor), an.sum(axis=0))


def test_merge_chunks(spec, executor):
    from cubed_tpu.core.ops import merge_chunks

    an = np.arange(100.0).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = merge_chunks(a, (4, 4))
    assert b.chunksize == (4, 4)
    assert np.allclose(b.compute(executor=executor), an)


def test_unify_chunks_applies(spec, executor):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    b = ct.from_array(an, chunks=(3, 2), spec=spec)
    c = xp.add(a, b)
    assert np.allclose(c.compute(executor=executor), an + an)


@pytest.mark.parametrize("n", [5, 6, 7])
def test_unify_chunks_misaligned_1d(spec, executor, n):
    # reference semantics: add of (3,)-chunked and (2,)-chunked computes
    # (cubed/core/ops.py:1172-1219); here via smallest-chunksize rechunk
    an = np.arange(float(n))
    a = ct.from_array(an, chunks=(3,), spec=spec)
    b = ct.from_array(an, chunks=(2,), spec=spec)
    c = xp.add(a, b)
    assert np.allclose(c.compute(executor=executor), an + an)


def test_unify_chunks_misaligned_2d_with_broadcast(spec, executor):
    an = np.arange(30.0).reshape(6, 5)
    bn = np.arange(5.0)
    a = ct.from_array(an, chunks=(4, 3), spec=spec)
    b = ct.from_array(bn, chunks=(2,), spec=spec)
    c = xp.multiply(a, b)
    assert np.allclose(c.compute(executor=executor), an * bn)


def test_unify_chunks_extent_mismatch_raises(spec):
    a = ct.from_array(np.arange(6.0), chunks=(3,), spec=spec)
    b = ct.from_array(np.arange(7.0), chunks=(2,), spec=spec)
    with pytest.raises(ValueError):
        xp.add(a, b)
