"""``python -m cubed_tpu.chaos`` — composed-failure campaign CLI.

Thin entry point over :mod:`cubed_tpu.runtime.campaign`; see that module
for the schedule format and docs/reliability.md for the repro/shrink
workflow.
"""

from .runtime.campaign import main

if __name__ == "__main__":
    raise SystemExit(main())
