"""Service-level deadlines & cancellation: submit(deadline_s=) enforces
an end-to-end SLO, RequestHandle.cancel() reaches RUNNING computes, and
close() stays bounded against wedged requests."""

from __future__ import annotations

import time

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.runtime.cancellation import (
    ComputeCancelledError,
    ComputeDeadlineExceededError,
)
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.service import ComputeService
from cubed_tpu.service.service import CANCELLED, FAILED, RequestCancelledError

pytestmark = pytest.mark.chaos


def _slow_array(tmp_path, delay_s=0.3, seed=5, shape=(16, 16), chunks=(4, 4)):
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        fault_injection=dict(
            seed=seed, straggler_rate=1.0, straggler_delay_s=delay_s
        ),
    )
    return xp.ones(shape, chunks=chunks, spec=spec) + 1


def _service(**kwargs):
    return ComputeService(
        executor=AsyncPythonDagExecutor(max_workers=2), **kwargs
    ).start()


def test_submit_deadline_fails_running_request_with_typed_error(tmp_path):
    svc = _service()
    try:
        h = svc.submit(_slow_array(tmp_path), tenant="slo", deadline_s=0.6)
        with pytest.raises(ComputeDeadlineExceededError):
            h.result(timeout=30)
        assert h.status() == FAILED
    finally:
        svc.close(timeout=10)


def test_submit_deadline_expired_while_queued_fails_at_admission(tmp_path):
    # a deadline that passes before the request ever runs: the request
    # fails with the typed error without consuming fleet time. One slot,
    # so the blocker pins admission while the deadline expires; distinct
    # shapes so the two queries can never coalesce
    svc = _service(max_concurrent=1)
    try:
        blocker = svc.submit(_slow_array(tmp_path), tenant="a")
        h = svc.submit(
            _slow_array(tmp_path / "b", seed=6, shape=(8, 8)), tenant="a",
            deadline_s=0.05,
        )
        with pytest.raises(ComputeDeadlineExceededError):
            h.result(timeout=60)
        assert h.status() == FAILED
        blocker.result(timeout=60)
    finally:
        svc.close(timeout=10)


def test_cancel_running_request_completes_cancelled(tmp_path):
    svc = _service()
    try:
        h = svc.submit(_slow_array(tmp_path), tenant="gold")
        # wait until it is genuinely RUNNING
        deadline = time.monotonic() + 10
        while h.status() != "running" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert h.status() == "running"
        t0 = time.monotonic()
        assert h.cancel()
        with pytest.raises(RequestCancelledError):
            h.result(timeout=15)
        assert h.status() == CANCELLED
        assert time.monotonic() - t0 < 5.0
        snap = svc.stats_snapshot()
        assert snap["tenants"]["gold"]["cancelled"] == 1
    finally:
        svc.close(timeout=10)


def test_cancel_running_durable_request_is_sealed(tmp_path):
    from cubed_tpu.service.durability import load_requests

    sdir = str(tmp_path / "svc")
    svc = _service(service_dir=sdir)
    try:
        h = svc.submit(_slow_array(tmp_path / "w"), tenant="t")
        deadline = time.monotonic() + 10
        while h.status() != "running" and time.monotonic() < deadline:
            time.sleep(0.02)
        assert h.cancel()
        with pytest.raises(RequestCancelledError):
            h.result(timeout=15)
    finally:
        svc.close(timeout=10)
    # the cancel was sealed durably: a restarted service on the same dir
    # has nothing to recover for this tenant
    pending = load_requests(sdir)
    assert not any(pending.values()), pending


def test_close_is_bounded_by_cancellation(tmp_path):
    # a compute that would run ~13s on 2 threads: close(timeout=1) must
    # not wait it out — the token cancels it and close returns promptly
    svc = _service()
    h = svc.submit(
        _slow_array(tmp_path, delay_s=0.8, shape=(16, 16), chunks=(2, 2)),
        tenant="wedge",
    )
    deadline = time.monotonic() + 10
    while h.status() != "running" and time.monotonic() < deadline:
        time.sleep(0.02)
    t0 = time.monotonic()
    svc.close(timeout=1.0)
    elapsed = time.monotonic() - t0
    assert elapsed < 10.0, f"close took {elapsed:.1f}s"
    assert h.done()
    assert h.status() in (CANCELLED, FAILED)


def test_deadline_survives_service_recovery(tmp_path):
    # the SLO is part of the durable contract: a request recovered after
    # an outage keeps its ABSOLUTE deadline, and one whose deadline
    # passed during the outage fails typed at admission
    sdir = str(tmp_path / "svc")
    svc1 = _service(service_dir=sdir, max_concurrent=1)
    blocker = svc1.submit(_slow_array(tmp_path / "w1"), tenant="t")
    h = svc1.submit(
        _slow_array(tmp_path / "w2", seed=7, shape=(8, 8)), tenant="t",
        deadline_s=0.5,
    )
    rid = h.request_id
    # close while h is still queued: its accepted record stays unsealed
    svc1.close(timeout=0.2)
    time.sleep(0.6)  # the deadline passes "during the outage"
    svc2 = _service(service_dir=sdir)
    try:
        h2 = svc2.handle(rid)
        assert h2 is not None, "recovery did not re-enqueue the request"
        with pytest.raises(ComputeDeadlineExceededError):
            h2.result(timeout=30)
        assert h2.status() == FAILED
    finally:
        svc2.close(timeout=10)


def test_coalesced_follower_cancel_leaves_leader_running(tmp_path):
    # follower cancel must not tear down the leader's execution
    svc = _service(max_concurrent=2)
    try:
        arr = _slow_array(tmp_path, delay_s=0.25)
        leader = svc.submit(arr, tenant="a")
        # leadership is first-to-execute: wait until the leader runs
        # before submitting the coalescing follower
        deadline = time.monotonic() + 10
        while leader.status() != "running" and time.monotonic() < deadline:
            time.sleep(0.02)
        time.sleep(0.3)
        follower = svc.submit(arr, tenant="b")
        deadline = time.monotonic() + 10
        while follower.status() != "running" and (
            time.monotonic() < deadline
        ):
            time.sleep(0.02)
        follower.cancel()
        with pytest.raises(
            (RequestCancelledError, ComputeCancelledError)
        ):
            follower.result(timeout=15)
        value = leader.result(timeout=60)
        np.testing.assert_array_equal(value, np.full((16, 16), 2.0))
    finally:
        svc.close(timeout=10)
