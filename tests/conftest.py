"""Test configuration: run JAX on a virtual 8-device CPU mesh so sharding
paths are exercised without TPU hardware; real-TPU benchmarks live in
bench.py, not the test suite."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import tempfile

import pytest

# the axon TPU plugin ignores JAX_PLATFORMS; pin the default device to the
# (virtual, 8-way) CPU backend so tests never touch the real chip
try:
    import jax

    _cpu = jax.devices("cpu")
    jax.config.update("jax_default_device", _cpu[0])
except Exception:
    pass


@pytest.fixture
def spec(tmp_path):
    import cubed_tpu as ct

    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB", reserved_mem=0)


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False, help="run slow tests"
    )


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: mark test as slow to run")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip_slow = pytest.mark.skip(reason="need --runslow option to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)
