"""Overload robustness for the compute service: the degradation ladder,
deadline-feasibility admission, and per-tenant circuit breakers.

Every infrastructure failure domain below this layer already degrades
gracefully (retries, integrity, memory, partitions, failover — PRs 2–18);
this module handles the day the *workload itself* is the fault: sustained
2x overload, or one tenant whose requests cannot succeed. The design is
the standard production answer (Google SRE "Handling Overload"): degrade
in stages, shed the cheapest work first, and fail requests *fast* when
executing them can only produce a guaranteed SLO miss.

**The ladder.** :class:`OverloadController` ticks inside the service
dispatch loop (~4/s) reading live signals the stack already emits — the
service queue depth, ``dispatch_utilization`` (PR 16),
``fleet_pressured_fraction`` (PR 10, via the telemetry store when armed),
and the trailing deadline-miss rate (PR 15 deadlines) — and walks:

- **L0 normal** — admit everything.
- **L1 shed optional work** — speculative backups off (the executors
  consult :func:`sheds_optional_work`), the telemetry sampler throttled,
  the peer cache shrunk through its existing pressure hook.
- **L2 shed load** — deadline-infeasible requests are failed at admission
  with :class:`DeadlineInfeasibleError` (estimated cost from the plan
  cache's task count x the observed per-tenant seconds-per-task rate),
  and new *batch*-class submits are rejected with
  :class:`ServiceOverloadedError` carrying a retry-after hint.
  Interactive-class submits still land.
- **L3 emergency** — every new submit is rejected; already-accepted and
  running requests are protected and drain the backlog.

Transitions are hysteresis-guarded — stepping up is immediate, stepping
down requires the exit condition to hold for a dwell window, and happens
one level at a time — so a sawtoothing queue cannot flap the ladder.
Every transition is a decision-ring record (``overload_level``) and the
``overload_level`` gauge, which the telemetry sampler auto-records into
the time-series store, where the ``overload_shedding`` alert rule reads
it.

**Circuit breakers.** :class:`TenantBreaker` is the classic
consecutive-failure breaker with a half-open probe, one per tenant, so a
tenant whose every request fails (the poison tenant, a broken pipeline)
stops consuming admission slots and retry budget after ``threshold``
consecutive failures. Breaker state is durable (one small JSON per tenant
beside its request journal) and reloads on service restart — a SIGKILL
does not reset a tripped breaker (the PR 11 recovery contract extends to
shed state).

``CUBED_TPU_OVERLOAD=off`` (or ``0``/``false``) disables the whole
ladder — the escape hatch, and the control arm of
``bench.py measure_overload_shedding()``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, Optional

from ..observability.collect import record_decision
from ..observability.metrics import get_registry

logger = logging.getLogger(__name__)

#: env escape hatch: "off" / "0" / "false" disables the ladder entirely
OVERLOAD_ENV_VAR = "CUBED_TPU_OVERLOAD"

#: ladder levels (the gauge value IS the level)
L0_NORMAL = 0
L1_SHED_OPTIONAL = 1
L2_SHED_LOAD = 2
L3_EMERGENCY = 3

LEVEL_NAMES = ("normal", "shed_optional", "shed_load", "emergency")


def overload_env_disabled() -> bool:
    return os.environ.get(OVERLOAD_ENV_VAR, "").strip().lower() in (
        "off", "0", "false", "no",
    )


class ServiceOverloadedError(RuntimeError):
    """The service is shedding load: the request was rejected, not run.

    ``retry_after_s`` is the hint a well-behaved client should wait
    before resubmitting (estimated backlog drain time, or the breaker's
    remaining cooldown). Pickles faithfully (``__reduce__``) so the typed
    rejection survives the durable-journal round trip and pool result
    queues."""

    def __init__(self, message: str, retry_after_s: Optional[float] = None):
        self.retry_after_s = (
            None if retry_after_s is None else float(retry_after_s)
        )
        super().__init__(message)

    def __reduce__(self):
        return (type(self), (self.args[0], self.retry_after_s))


class DeadlineInfeasibleError(ServiceOverloadedError):
    """The request's estimated cost cannot meet its deadline: executing
    it would only produce a guaranteed SLO miss while displacing feasible
    work — failed fast at admission instead (L2+)."""


# -- module-level ladder state (what the executors consult) --------------

_live_lock = threading.Lock()
#: id(controller) -> current level, for every live controller in-process
_live_levels: Dict[int, int] = {}


def _publish_level(controller_id: int, level: Optional[int]) -> None:
    with _live_lock:
        if level is None:
            _live_levels.pop(controller_id, None)
        else:
            _live_levels[controller_id] = level


def current_overload_level() -> int:
    """The worst (highest) level across live controllers in this process."""
    with _live_lock:
        return max(_live_levels.values(), default=L0_NORMAL)


def sheds_optional_work() -> bool:
    """True at L1+: speculative backups and other optional work are shed
    (consulted by ``map_unordered`` on every backup-launch scan)."""
    return current_overload_level() >= L1_SHED_OPTIONAL


@dataclass
class OverloadPolicy:
    """Ladder thresholds. Defaults are sized for the reference service
    (a handful of admission slots); tests and small fixtures pass their
    own. Enter thresholds step UP; the exit condition is the enter
    threshold scaled by ``exit_fraction``, held for ``down_dwell_s``."""

    #: queued (accepted, not yet running) requests
    queue_l1: int = 8
    queue_l2: int = 16
    queue_l3: int = 32
    #: fraction of live fleet workers reporting memory pressure (PR 10)
    pressured_l1: float = 0.5
    #: dispatch-loop busy fraction (PR 16)
    util_l1: float = 0.95
    #: trailing deadline-miss fraction that proves the backlog is already
    #: blowing SLOs (needs >= miss_min_samples completions in the window)
    miss_rate_l2: float = 0.5
    miss_window_s: float = 30.0
    miss_min_samples: int = 4
    #: hysteresis: exit thresholds = enter * exit_fraction, and the exit
    #: condition must hold this long before stepping DOWN one level
    exit_fraction: float = 0.5
    down_dwell_s: float = 2.0
    #: controller tick spacing (the dispatch loop calls more often)
    tick_interval_s: float = 0.25
    #: L1 brownout: the telemetry sampler's interval is stretched by this
    #: factor while shedding optional work
    sampler_throttle_factor: float = 5.0
    #: retry-after hint bounds
    retry_after_min_s: float = 1.0
    retry_after_max_s: float = 60.0


class CostEstimator:
    """Observed seconds-per-task, per tenant (EWMA) with a global
    fallback: the feasibility model is ``estimate = plan task count x
    observed rate``. No observations yet -> no estimate -> admission
    fails OPEN (a cold service must not reject its first requests)."""

    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        #: tenant (or None = global) -> EWMA seconds per task
        self._rates: Dict[Optional[str], float] = {}

    def observe(self, tenant: Optional[str], num_tasks: int,
                wall_s: float) -> None:
        if not num_tasks or num_tasks <= 0 or wall_s <= 0:
            return
        per_task = float(wall_s) / float(num_tasks)
        with self._lock:
            for key in (tenant, None):
                prev = self._rates.get(key)
                self._rates[key] = (
                    per_task if prev is None
                    else prev + self.alpha * (per_task - prev)
                )

    def seconds_per_task(self, tenant: Optional[str]) -> Optional[float]:
        with self._lock:
            return self._rates.get(tenant, self._rates.get(None))

    def estimate_s(self, tenant: Optional[str],
                   num_tasks: Optional[int]) -> Optional[float]:
        """Estimated wall seconds for a request of ``num_tasks`` tasks,
        or None when either side of the model is unknown."""
        if not num_tasks:
            return None
        rate = self.seconds_per_task(tenant)
        if rate is None:
            return None
        return rate * int(num_tasks)


class TenantBreaker:
    """One tenant's circuit breaker: consecutive-failure trip, timed
    cooldown, half-open single probe — with the strike record durable
    beside the tenant's request journal so a tripped breaker survives a
    service SIGKILL.

    States: ``closed`` (admitting; ``strikes`` consecutive failures so
    far), ``open`` (rejecting until ``cooldown_s`` elapses), ``half_open``
    (exactly one probe request admitted; its success closes the breaker,
    its failure re-opens a fresh cooldown)."""

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    def __init__(
        self, tenant: str, threshold: int = 3, cooldown_s: float = 10.0,
        state_path: Optional[str] = None, clock=time.time,
    ):
        self.tenant = str(tenant)
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.state_path = state_path
        self._clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.strikes = 0
        self.opened_at = 0.0
        self._probing = False
        self._load()

    # -- durability ----------------------------------------------------

    def _load(self) -> None:
        if not self.state_path or not os.path.isfile(self.state_path):
            return
        try:
            with open(self.state_path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            self.state = str(doc.get("state", self.CLOSED))
            self.strikes = int(doc.get("strikes", 0))
            self.opened_at = float(doc.get("opened_at", 0.0))
            if self.state not in (self.CLOSED, self.OPEN, self.HALF_OPEN):
                self.state = self.CLOSED
            if self.state == self.HALF_OPEN:
                # a probe in flight when the process died resolved nothing:
                # come back OPEN with the cooldown it re-entered from
                self.state = self.OPEN
        except (OSError, ValueError):
            logger.warning(
                "tenant %s: unreadable breaker state %s — starting closed",
                self.tenant, self.state_path,
            )

    def _persist_locked(self) -> None:
        if not self.state_path:
            return
        try:
            tmp = self.state_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({
                    "tenant": self.tenant,
                    "state": self.state,
                    "strikes": self.strikes,
                    "opened_at": self.opened_at,
                }, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.state_path)
        except OSError:
            logger.warning(
                "tenant %s: breaker state not durable (%s unwritable)",
                self.tenant, self.state_path,
            )

    # -- the breaker ---------------------------------------------------

    def check(self) -> Optional[float]:
        """None -> admit. A float -> reject, retry after that many
        seconds. An elapsed cooldown flips OPEN -> HALF_OPEN and admits
        exactly one probe."""
        now = self._clock()
        with self._lock:
            if self.state == self.CLOSED:
                return None
            if self.state == self.OPEN:
                remaining = self.opened_at + self.cooldown_s - now
                if remaining > 0:
                    return max(0.1, remaining)
                self.state = self.HALF_OPEN
                self._probing = False
                self._persist_locked()
                record_decision(
                    "tenant_breaker", tenant=self.tenant,
                    state=self.HALF_OPEN, strikes=self.strikes,
                )
            # HALF_OPEN: one probe at a time
            if self._probing:
                return max(0.1, self.cooldown_s / 2.0)
            self._probing = True
            return None

    def on_failure(self) -> bool:
        """Count one request failure; True when this strike TRIPPED the
        breaker (closed/half-open -> open)."""
        now = self._clock()
        with self._lock:
            self.strikes += 1
            tripped = (
                self.state == self.HALF_OPEN
                or (self.state == self.CLOSED
                    and self.strikes >= self.threshold)
            )
            if tripped:
                self.state = self.OPEN
                self.opened_at = now
                self._probing = False
            self._persist_locked()
        if tripped:
            get_registry().counter("tenant_breaker_trips").inc()
            record_decision(
                "tenant_breaker", tenant=self.tenant, state=self.OPEN,
                strikes=self.strikes, cooldown_s=self.cooldown_s,
            )
            logger.warning(
                "tenant %s: circuit breaker OPEN after %d consecutive "
                "failures (cooldown %.1fs)", self.tenant, self.strikes,
                self.cooldown_s,
            )
        return tripped

    def abort_probe(self) -> None:
        """Release the half-open probe slot without resolving it: the
        admitted probe request died of something that was NOT the
        tenant's workload (throttle bound, journal error) before it could
        run, so the next submit may probe instead."""
        with self._lock:
            if self.state == self.HALF_OPEN:
                self._probing = False

    def on_success(self) -> None:
        with self._lock:
            was_open = self.state != self.CLOSED
            self.state = self.CLOSED
            self.strikes = 0
            self._probing = False
            self._persist_locked()
        if was_open:
            record_decision(
                "tenant_breaker", tenant=self.tenant, state=self.CLOSED,
            )
            logger.info(
                "tenant %s: circuit breaker closed (probe succeeded)",
                self.tenant,
            )

    @property
    def is_open(self) -> bool:
        with self._lock:
            if self.state == self.OPEN:
                return self.opened_at + self.cooldown_s > self._clock()
            return self.state == self.HALF_OPEN and self._probing

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "tenant": self.tenant,
                "state": self.state,
                "strikes": self.strikes,
                "opened_at": self.opened_at,
            }


class OverloadController:
    """The hysteresis-guarded degradation ladder (module docstring).

    The owning service calls :meth:`tick` from its dispatch loop with the
    live queue depth, :meth:`note_completion` as requests finish (feeding
    the deadline-miss window), and :meth:`close` on shutdown. Everything
    else — the other signals, the L1 side effects, the gauge and the
    decision records — the controller handles itself, and everything
    degrades to a no-op when telemetry is not armed."""

    def __init__(self, policy: Optional[OverloadPolicy] = None,
                 clock=time.time):
        self.policy = policy or OverloadPolicy()
        self._clock = clock
        self._lock = threading.Lock()
        self.level = L0_NORMAL
        self.transitions = 0
        self._last_tick = 0.0
        #: when the exit (step-down) condition was first continuously true
        self._exit_since: Optional[float] = None
        #: trailing (ts, missed) completions for the miss-rate signal
        self._completions: deque = deque(maxlen=1024)
        #: telemetry sampler interval saved across the L1 brownout
        self._saved_sampler_interval: Optional[float] = None
        self._closed = False
        _publish_level(id(self), L0_NORMAL)
        get_registry().gauge("overload_level").set(L0_NORMAL)

    # -- signal feeds ---------------------------------------------------

    def note_completion(self, deadline_missed: bool) -> None:
        self._completions.append((self._clock(), bool(deadline_missed)))

    def miss_rate(self, now: Optional[float] = None) -> float:
        """Deadline-miss fraction over the trailing window (0.0 until
        ``miss_min_samples`` completions have landed in it)."""
        now = self._clock() if now is None else now
        cutoff = now - self.policy.miss_window_s
        total = missed = 0
        for ts, m in self._completions:
            if ts >= cutoff:
                total += 1
                missed += bool(m)
        if total < self.policy.miss_min_samples:
            return 0.0
        return missed / total

    @staticmethod
    def _dispatch_utilization() -> float:
        try:
            return float(
                get_registry().gauge("dispatch_utilization").value or 0.0
            )
        except Exception:
            return 0.0

    @staticmethod
    def _fleet_pressured_fraction() -> float:
        try:
            from ..observability.export import get_runtime

            rt = get_runtime()
            if rt is not None:
                v = rt.store.latest("fleet_pressured_fraction")
                if v is not None:
                    return float(v)
        except Exception:
            pass
        return 0.0

    # -- the ladder -----------------------------------------------------

    def _propose(self, queue_depth: int, util: float, pressured: float,
                 miss: float, scale: float = 1.0) -> int:
        """The level the signals justify; ``scale`` < 1 evaluates the
        (lower) exit thresholds for the step-down condition."""
        p = self.policy
        if queue_depth >= p.queue_l3 * scale:
            return L3_EMERGENCY
        if queue_depth >= p.queue_l2 * scale or miss >= p.miss_rate_l2 * scale:
            return L2_SHED_LOAD
        if (
            queue_depth >= p.queue_l1 * scale
            or pressured >= p.pressured_l1 * scale
            or util >= p.util_l1 * scale
        ):
            return L1_SHED_OPTIONAL
        return L0_NORMAL

    def tick(self, queue_depth: int, now: Optional[float] = None) -> int:
        """One policy-loop step; returns the (possibly new) level."""
        now = self._clock() if now is None else now
        with self._lock:
            if self._closed:
                return self.level
            if now - self._last_tick < self.policy.tick_interval_s:
                return self.level
            self._last_tick = now
            util = self._dispatch_utilization()
            pressured = self._fleet_pressured_fraction()
            miss = self.miss_rate(now)
            up = self._propose(queue_depth, util, pressured, miss)
            if up > self.level:
                # overload response must be immediate: jump straight to
                # the level the signals justify
                self._transition_locked(
                    up, now, queue_depth, util, pressured, miss,
                )
                return self.level
            down = self._propose(
                queue_depth, util, pressured, miss,
                scale=self.policy.exit_fraction,
            )
            if down < self.level:
                if self._exit_since is None:
                    self._exit_since = now
                elif now - self._exit_since >= self.policy.down_dwell_s:
                    # recovery is deliberate: one level per dwell window,
                    # so a queue oscillating around a threshold cannot
                    # flap the ladder
                    self._transition_locked(
                        self.level - 1, now, queue_depth, util, pressured,
                        miss,
                    )
            else:
                self._exit_since = None
            return self.level

    def _transition_locked(self, new: int, now: float, queue_depth: int,
                           util: float, pressured: float,
                           miss: float) -> None:
        old, self.level = self.level, new
        self.transitions += 1
        self._exit_since = None
        _publish_level(id(self), new)
        reg = get_registry()
        reg.gauge("overload_level").set(new)
        reg.counter("overload_transitions").inc()
        record_decision(
            "overload_level",
            from_level=old, to_level=new, name=LEVEL_NAMES[new],
            queue_depth=int(queue_depth), utilization=round(util, 4),
            pressured_fraction=round(pressured, 4),
            miss_rate=round(miss, 4),
        )
        logger.warning(
            "overload ladder: L%d (%s) -> L%d (%s) [queue=%d util=%.2f "
            "pressured=%.2f miss=%.2f]", old, LEVEL_NAMES[old], new,
            LEVEL_NAMES[new], queue_depth, util, pressured, miss,
        )
        if old < L1_SHED_OPTIONAL <= new:
            self._enter_brownout_locked()
        elif new < L1_SHED_OPTIONAL <= old:
            self._exit_brownout_locked()

    # -- L1 side effects (shed optional work) ---------------------------

    def _enter_brownout_locked(self) -> None:
        # telemetry sampler throttled: observation is optional work too
        try:
            from ..observability.export import get_runtime

            rt = get_runtime()
            if rt is not None and self._saved_sampler_interval is None:
                self._saved_sampler_interval = rt.sampler.interval_s
                rt.sampler.interval_s = (
                    self._saved_sampler_interval
                    * self.policy.sampler_throttle_factor
                )
        except Exception:
            pass
        # the peer cache sheds half its footprint through the existing
        # memory-pressure hook (workers do the same via their own guard
        # heartbeats when the pressure is fleet-wide)
        try:
            from ..runtime import transfer

            rt_peer = transfer.get_worker_runtime()
            if rt_peer is not None:
                rt_peer.pressure_tick("soft")
        except Exception:
            pass

    def _exit_brownout_locked(self) -> None:
        if self._saved_sampler_interval is not None:
            try:
                from ..observability.export import get_runtime

                rt = get_runtime()
                if rt is not None:
                    rt.sampler.interval_s = self._saved_sampler_interval
            except Exception:
                pass
            self._saved_sampler_interval = None

    # -- admission helpers ----------------------------------------------

    def retry_after_s(self, queue_depth: int,
                      drain_rate_s: Optional[float] = None) -> float:
        """The hint attached to a shed: roughly when the backlog should
        have drained (``queue_depth x seconds-per-request`` when a drain
        rate is known, else half a second per queued request), clamped
        to the policy bounds."""
        per = drain_rate_s if drain_rate_s and drain_rate_s > 0 else 0.5
        est = max(1, int(queue_depth)) * per
        return min(
            self.policy.retry_after_max_s,
            max(self.policy.retry_after_min_s, est),
        )

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "level": self.level,
                "name": LEVEL_NAMES[self.level],
                "transitions": self.transitions,
                "miss_rate": round(self.miss_rate(), 4),
            }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            if self.level >= L1_SHED_OPTIONAL:
                self._exit_brownout_locked()
        _publish_level(id(self), None)
