"""Durable compute journal: coordinator state that survives a client crash.

The coordinator (the client process driving ``Plan.execute``) was the last
stateful, non-durable, single point of failure in the system: workers are
stateless, every task is an idempotent whole-chunk write, and chunk-granular
resume (PR 3) can rebuild progress from the store — but which *compute* was
running, how far it had gotten, and why the scheduler did what it did all
died with the client process. This module journals exactly that:

- an **append-only JSONL file beside the Zarr store** (``Spec(journal=
  "/path/to/file.jsonl")``), one record per line, written by a
  :class:`JournalCallback` riding the ordinary compute-lifecycle events so
  every executor journals identically;
- **fsync'd completion records** — a ``complete`` line is durable before
  anything depends on it (dispatch/decision lines are forensic and flushed
  but not individually fsynced);
- the **same torn-line-tolerant loader discipline as the integrity
  manifests** (``storage/integrity.py``): a crash mid-append tears at most
  the final line, which :func:`load_journal` skips without poisoning
  earlier records — corrupt journal data can cost recomputation, never
  correctness;
- the **decision ring**: every ``record_decision`` entry made while the
  journal is open (retries, requeues, disconnects, lease expiries, scale
  events) is mirrored into the file, so a post-crash journal doubles as a
  flight-recorder timeline for a compute whose ``on_compute_end`` never
  fired.

**Crash recovery.** After the client process is killed mid-compute, rebuild
the same plan (same code ⇒ same deterministic op names) and resume it:

.. code-block:: python

    spec = cubed_tpu.Spec(work_dir=..., journal="/data/c.journal.jsonl")
    ...build the identical arrays...
    executor.resume_compute(result_array, "/data/c.journal.jsonl")
    # equivalently: result_array.compute(executor=..., resume_from_journal=...)

Resume runs from the intersection of two frontiers: a task is skipped only
when **the chunk-integrity resume scan verifies every output chunk** AND
**the journal recorded the task complete** — the journal narrows the skip
set (e.g. a multi-output task that wrote one side before dying re-runs),
it never widens it, so the result is bitwise-identical to an uninterrupted
run. Both re-executions and repeated crashes append to the same file; the
loader folds every run's completions.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Optional

from ..observability.metrics import get_registry
from .types import Callback

logger = logging.getLogger(__name__)

JOURNAL_VERSION = 1


class ComputeJournal:
    """Append-only JSONL writer with fsync'd load-bearing records.

    Thread-safe (task-end events arrive from the completion loop while
    decision-ring mirrors arrive from arbitrary threads). ``append`` after
    ``close`` is a silent no-op — a late decision must not resurrect the
    file handle."""

    def __init__(self, path: str):
        self.path = str(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._f = open(self.path, "ab")
        self._lock = threading.Lock()

    def append(self, kind: str, fsync: bool = True, **fields) -> bool:
        """Append one record; returns True once it is durably written.

        Failures never raise (journaling is additive: a full disk
        degrades resume granularity, it must not fail the compute) — but
        the return value lets a caller whose record is LOAD-BEARING (the
        service's ``accepted`` records promise recoverability) refuse to
        make promises the file doesn't back."""
        record = {"kind": kind, "t": time.time()}
        record.update(fields)
        try:
            line = (json.dumps(record, default=str) + "\n").encode()
        except (TypeError, ValueError):
            logger.warning("unserializable journal record dropped: %r", kind)
            return False
        with self._lock:
            if self._f is None:
                return False
            try:
                self._f.write(line)
                self._f.flush()
                if fsync:
                    os.fsync(self._f.fileno())
            except OSError as e:
                logger.warning("journal append failed (%s): %s", kind, e)
                return False
        get_registry().counter("journal_appends").inc()
        return True

    def close(self) -> None:
        with self._lock:
            f, self._f = self._f, None
        if f is not None:
            try:
                f.flush()
                os.fsync(f.fileno())
            except OSError:
                pass
            try:
                f.close()
            except OSError:
                pass


class JournalCallback(Callback):
    """Journals a compute's lifecycle through the ordinary callback events.

    ``compute_start`` records the plan shape (per-op task counts — what
    resume validates against), ``dispatch``/``complete`` record per-task
    progress keyed by ``(op, chunk_key)``, ``decision`` mirrors the
    decision ring, and ``compute_end`` seals the run. Attached by
    ``Plan.execute`` when ``Spec(journal=...)`` names a path."""

    def __init__(self, path: str):
        self.path = str(path)
        self._journal: Optional[ComputeJournal] = None
        self._sink_registered = False

    def on_compute_start(self, event) -> None:
        from ..observability.collect import add_decision_sink
        from .pipeline import iter_op_nodes

        self._journal = ComputeJournal(self.path)
        ops = {
            name: d["primitive_op"].num_tasks
            for name, d in iter_op_nodes(event.dag)
        }
        self._journal.append(
            "compute_start",
            version=JOURNAL_VERSION,
            compute_id=getattr(event, "compute_id", None),
            resume=bool(getattr(event, "resume", None)),
            tasks_total=sum(ops.values()),
            ops=ops,
        )
        add_decision_sink(self._on_decision)
        self._sink_registered = True
        logger.info("journaling compute to %s", self.path)

    def _on_decision(self, entry: dict) -> None:
        j = self._journal
        if j is not None:
            fields = dict(entry)
            # the ring's "kind" (retry/requeue/lease_expired/...) moves to
            # "decision" — "kind" is the journal's own record discriminator
            fields["decision"] = fields.pop("kind", None)
            j.append("decision", fsync=False, **fields)

    def on_task_start(self, event) -> None:
        j = self._journal
        if j is not None:
            j.append(
                "dispatch", fsync=False, op=event.array_name,
                key=event.chunk_key, attempt=event.attempt,
            )

    def on_task_end(self, event) -> None:
        j = self._journal
        if j is not None:
            # the load-bearing record: fsync'd, so a completion the resume
            # frontier will skip is durable before the client can crash
            j.append("complete", op=event.array_name, key=event.chunk_key)

    def on_compute_end(self, event) -> None:
        from ..observability.collect import remove_decision_sink

        if self._sink_registered:
            remove_decision_sink(self._on_decision)
            self._sink_registered = False
        j = self._journal
        if j is not None:
            err = getattr(event, "error", None)
            j.append(
                "compute_end",
                status="failed" if err is not None else "completed",
                error=(f"{type(err).__name__}: {err}" if err is not None
                       else None),
            )
            j.close()
            self._journal = None


# ----------------------------------------------------------------------
# control-plane snapshot log (live coordinator failover)
# ----------------------------------------------------------------------

CONTROL_VERSION = 1
CONTROL_FILE = "control.jsonl"
RENDEZVOUS_FILE = "rendezvous.json"

#: bound on decision records retained by ``load_control`` — the successor
#: replays these into its decision ring for the stitched two-epoch
#: timeline; an unbounded replay would let a long-lived prior epoch flood
#: the successor's bounded ring
CONTROL_DECISIONS_KEEP = 100


def control_log_path(control_dir: str) -> str:
    return os.path.join(str(control_dir), CONTROL_FILE)


def rendezvous_path(control_dir: str) -> str:
    return os.path.join(str(control_dir), RENDEZVOUS_FILE)


class ControlLog:
    """The coordinator's epoch-stamped control-plane snapshot.

    A minimal, bounded record of the fleet's control state — registered
    workers + their session tokens, the per-task dispatch frontier, chunk
    locations, and the connectivity decision mirror — appended under the
    same journal discipline as :class:`ComputeJournal` (append-only JSONL,
    load-bearing records fsync'd, torn-line-tolerant fold). A successor
    coordinator pointed at the same ``control_dir`` folds this file with
    :func:`load_control` and re-adopts the running fleet instead of
    cold-starting one; the sibling ``rendezvous.json`` (atomic whole-file
    replace) advertises the live epoch + address so workers that lost
    their socket can find the successor.
    """

    def __init__(self, control_dir: str):
        self.dir = str(control_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.path = control_log_path(self.dir)
        self._journal = ComputeJournal(self.path)

    # -- load-bearing (fsync'd) records --------------------------------

    def record_epoch(self, epoch: int, addr) -> bool:
        """One fsync'd line per coordinator incarnation: the epoch fence
        everything else hangs off. Durable before the rendezvous file
        advertises it."""
        return self._journal.append(
            "epoch", version=CONTROL_VERSION, epoch=int(epoch),
            addr=list(addr),
        )

    def record_worker(self, name: str, token: str, nthreads: int,
                      peer_addr=None, address=None, pid=None) -> bool:
        """A registered worker + its session token — what a successor
        needs to recognize the reconnect handshake as a resume, not an
        impostor."""
        return self._journal.append(
            "worker", name=name, token=token, nthreads=int(nthreads or 1),
            peer_addr=list(peer_addr) if peer_addr else None,
            address=list(address) if address else None,
            pid=pid,
        )

    def record_worker_gone(self, name: str) -> bool:
        return self._journal.append("worker_gone", name=name)

    # -- frontier records (flushed, not individually fsync'd: losing one
    # costs at most one idempotent re-run, never correctness) -----------

    def record_dispatch(self, task_id: int, tag, worker: str) -> None:
        self._journal.append(
            "dispatch", fsync=False, task_id=int(task_id),
            tag=list(tag) if tag else None, worker=worker,
        )

    def record_done(self, task_id: int) -> None:
        self._journal.append("done", fsync=False, task_id=int(task_id))

    def record_chunk_locations(self, worker: str, produced) -> None:
        for item in produced or ():
            try:
                store, key, nbytes = item[0], item[1], int(item[2])
            except (TypeError, IndexError, ValueError):
                continue
            self._journal.append(
                "chunk_loc", fsync=False, worker=worker,
                store=str(store), key=str(key), nbytes=nbytes,
            )

    def record_decision(self, epoch: int, entry: dict) -> None:
        fields = dict(entry)
        fields["decision"] = fields.pop("kind", None)
        self._journal.append(
            "decision", fsync=False, epoch=int(epoch), **fields
        )

    # -- the successor advertisement -----------------------------------

    def advertise(self, epoch: int, addr) -> None:
        write_rendezvous(self.dir, epoch, addr)

    def close(self) -> None:
        self._journal.close()


def write_rendezvous(control_dir: str, epoch: int, addr) -> None:
    """Atomically (re)write the rendezvous advertisement: the live
    coordinator's epoch + dial address. Workers re-read this file inside
    their reconnect loop; a torn write must never be observable, hence
    write-tmp + rename."""
    path = rendezvous_path(control_dir)
    doc = {"epoch": int(epoch), "addr": list(addr), "t": time.time()}
    try:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError as e:
        logger.warning("could not write rendezvous file %s: %s", path, e)


def read_rendezvous(path: str) -> Optional[dict]:
    """The current advertisement, or None (missing/garbage file — the
    reconnect loop just keeps dialing its last-known address)."""
    try:
        with open(path, "r") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    epoch = doc.get("epoch")
    addr = doc.get("addr")
    if not isinstance(epoch, int) or not (
        isinstance(addr, (list, tuple)) and len(addr) == 2
    ):
        return None
    return {"epoch": epoch, "addr": (str(addr[0]), int(addr[1]))}


def load_control(path: str) -> dict:
    """Fold a control log into the successor's adoption state.

    Returns ``{"epoch" (latest recorded, -1 when none — a fresh dir),
    "addr", "workers" ({name: record}), "inflight" ({task_id: {"tag",
    "worker"}}), "chunk_locations" ([{worker, store, key, nbytes}]),
    "decisions" (bounded, newest last), "bad_lines"}``. Same torn-line
    tolerance as every journal: a lost ``done`` line means one idempotent
    task re-runs; a lost ``worker`` line means one worker re-registers
    fresh instead of resuming."""
    epoch = -1
    addr = None
    workers: dict = {}
    inflight: dict = {}
    chunk_locations: list = []
    decisions: list = []
    bad_lines = 0
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        raw = b""
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
            if not isinstance(doc, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError):
            bad_lines += 1
            continue
        kind = doc.get("kind")
        if kind == "epoch":
            e = doc.get("epoch")
            if isinstance(e, int):
                epoch = max(epoch, e)
                addr = doc.get("addr")
        elif kind == "worker":
            name = doc.get("name")
            if isinstance(name, str) and isinstance(doc.get("token"), str):
                workers[name] = doc
        elif kind == "worker_gone":
            name = doc.get("name")
            workers.pop(name, None)
            inflight = {
                tid: rec for tid, rec in inflight.items()
                if rec.get("worker") != name
            }
        elif kind == "dispatch":
            tid = doc.get("task_id")
            if isinstance(tid, int):
                inflight[tid] = {
                    "tag": doc.get("tag"), "worker": doc.get("worker"),
                }
        elif kind == "done":
            inflight.pop(doc.get("task_id"), None)
        elif kind == "chunk_loc":
            chunk_locations.append(doc)
        elif kind == "decision":
            decisions.append(doc)
    if bad_lines:
        logger.warning(
            "control log %s: skipped %d undecodable line(s)", path, bad_lines,
        )
    return {
        "path": str(path),
        "epoch": epoch,
        "addr": addr,
        "workers": workers,
        "inflight": inflight,
        "chunk_locations": chunk_locations,
        "decisions": decisions[-CONTROL_DECISIONS_KEEP:],
        "bad_lines": bad_lines,
    }


def load_journal(path: str) -> dict:
    """Fold a journal file into a resume frontier.

    Returns ``{"path", "meta" (the latest compute_start record),
    "completed" (set of (op, chunk_key)), "decisions" (list), "complete"
    (True when the latest run sealed with status=completed), "dispatches",
    "bad_lines"}``. Same tolerance discipline as the manifest loader: any
    torn/garbage line is skipped and only costs its own record — a lost
    ``complete`` line means one task re-runs, never a wrong result.
    """
    with open(path, "rb") as f:
        raw = f.read()
    meta: dict = {}
    completed: set = set()
    decisions: list = []
    complete = False
    dispatches = 0
    bad_lines = 0
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
            if not isinstance(doc, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError):
            bad_lines += 1
            continue
        kind = doc.get("kind")
        if kind == "compute_start":
            meta = doc
            complete = False  # a new run opened; the previous seal is moot
        elif kind == "complete":
            op, key = doc.get("op"), doc.get("key")
            if isinstance(op, str) and isinstance(key, str):
                completed.add((op, key))
        elif kind == "dispatch":
            dispatches += 1
        elif kind == "decision":
            decisions.append(doc)
        elif kind == "compute_end":
            complete = doc.get("status") == "completed"
    if bad_lines:
        logger.warning(
            "journal %s: skipped %d undecodable line(s) (their tasks will "
            "re-run)", path, bad_lines,
        )
    return {
        "path": str(path),
        "meta": meta,
        "completed": completed,
        "decisions": decisions,
        "complete": complete,
        "dispatches": dispatches,
        "bad_lines": bad_lines,
    }
