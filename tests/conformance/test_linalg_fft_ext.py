"""linalg + fft extension namespaces against the numpy oracle.

Parity role: array-api-tests extension suites (test_linalg.py /
test_fft.py) — the reference has neither namespace, so this is
beyond-reference conformance. Decomposition factors are compared via
backend-invariant properties (reconstruction, orthonormality,
triangularity, uniqueness of singular/eigen values), not raw factor
equality, because LAPACK sign conventions are not part of the spec.
Tolerances scale with the input dtype's eps (the generators draw float32
as well as float64).
"""

from __future__ import annotations

import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

import cubed_tpu.array_api as xp

from .harness import arrays, run, wrap


def _tol(an, k=100, extra=None):
    """eps-scaled absolute tolerance for a result derived from ``an``."""
    scale = max(1.0, float(np.max(np.abs(an))) if an.size else 1.0)
    if extra is not None:
        scale = max(scale, float(np.max(np.abs(extra))) if np.size(extra) else 1.0)
    return float(np.finfo(an.dtype).eps) * k * scale


@settings(max_examples=20, deadline=None)
@given(data=st.data())
def test_qr_properties(data):
    m = data.draw(st.integers(2, 12))
    n = data.draw(st.integers(1, min(m, 6)))
    an = data.draw(arrays(shape=(m, n)))
    a = wrap(an, None)
    a = a.rechunk((data.draw(st.integers(1, m)), n))
    q, r = xp.linalg.qr(a)
    qn, rn = run(q), run(r)
    assert qn.shape == (m, n) and rn.shape == (n, n)
    tol = _tol(an, k=200)
    np.testing.assert_allclose(qn @ rn, an, atol=tol)
    np.testing.assert_allclose(
        qn.T @ qn, np.eye(n), atol=float(np.finfo(an.dtype).eps) * 200
    )
    np.testing.assert_allclose(np.triu(rn), rn, atol=tol)


@settings(max_examples=15, deadline=None)
@given(data=st.data())
def test_svd_and_svdvals_match_numpy(data):
    m = data.draw(st.integers(1, 10))
    n = data.draw(st.integers(1, 10))
    an = data.draw(arrays(shape=(m, n)))
    a = wrap(an, None)
    if m > 1:
        a = a.rechunk((data.draw(st.integers(1, m)), n))
    s_expect = np.linalg.svd(an, compute_uv=False)
    tol = _tol(an, k=200, extra=s_expect)
    np.testing.assert_allclose(run(xp.linalg.svdvals(a)), s_expect, atol=tol)
    u, s, vh = xp.linalg.svd(a, full_matrices=False)
    un, sn, vhn = run(u), run(s), run(vh)
    np.testing.assert_allclose(sn, s_expect, atol=tol)
    np.testing.assert_allclose((un * sn) @ vhn, an, atol=tol * 5)


@settings(max_examples=15, deadline=None)
@given(data=st.data())
def test_solve_inv_det_roundtrip(data):
    n = data.draw(st.integers(1, 6))
    base = data.draw(arrays(shape=(n, n)))
    # normalize before forming the SPD matrix: a huge draw makes
    # base@base.T rank-1-dominant and the ridge negligible, i.e. an
    # ill-conditioned system where f32 legitimately loses ~cond*eps
    denom = max(1.0, float(np.max(np.abs(base))) if base.size else 1.0)
    base = (base / denom).astype(base.dtype)
    an = (base @ base.T + n * np.eye(n)).astype(base.dtype)  # SPD, cond O(1)
    a = wrap(an, None)
    bn = data.draw(
        arrays(shape=(n, data.draw(st.integers(1, 3))))
    ).astype(an.dtype)
    b = wrap(bn, None)
    xn = run(xp.linalg.solve(a, b))
    tol = _tol(an, k=500 * n, extra=bn)
    np.testing.assert_allclose(an @ xn, bn, atol=tol)
    np.testing.assert_allclose(
        run(xp.linalg.inv(a)) @ an, np.eye(n),
        atol=float(np.finfo(an.dtype).eps) * 500 * n,
    )
    det_expect = np.linalg.det(an)
    np.testing.assert_allclose(
        np.asarray(run(xp.linalg.det(a))), det_expect,
        atol=float(np.finfo(an.dtype).eps) * 500 * max(1.0, abs(float(det_expect))),
    )


@settings(max_examples=20, deadline=None)
@given(data=st.data())
def test_fft_matches_numpy(data):
    an = data.draw(arrays(min_dims=1))
    ndim = an.ndim
    axis = data.draw(st.integers(-ndim, ndim - 1))
    norm = data.draw(st.sampled_from(["backward", "ortho", "forward"]))
    a = wrap(an, None)
    expect = np.fft.fft(an, axis=axis, norm=norm)
    np.testing.assert_allclose(
        run(xp.fft.fft(a, axis=axis, norm=norm)), expect,
        atol=_tol(an, k=100, extra=np.abs(expect)),
    )


@settings(max_examples=15, deadline=None)
@given(data=st.data())
def test_rfft_irfft_roundtrip_property(data):
    an = data.draw(arrays(min_dims=1))
    ndim = an.ndim
    axis = data.draw(st.integers(-ndim, ndim - 1))
    if an.shape[axis] < 2:
        return
    a = wrap(an, None)
    out = run(xp.fft.irfft(xp.fft.rfft(a, axis=axis), n=an.shape[axis],
                           axis=axis))
    np.testing.assert_allclose(out, an, atol=_tol(an, k=100))


@settings(max_examples=15, deadline=None)
@given(data=st.data())
def test_norms_match_numpy(data):
    an = data.draw(arrays(shape=(
        data.draw(st.integers(1, 7)), data.draw(st.integers(1, 7))
    )))
    a = wrap(an, None)
    ordv = data.draw(st.sampled_from(["fro", 1, -1, np.inf, -np.inf]))
    expect = np.linalg.norm(an, ord=ordv)
    np.testing.assert_allclose(
        float(run(xp.linalg.matrix_norm(a, ord=ordv))), expect,
        atol=_tol(an, k=100, extra=expect),
    )
    vord = data.draw(st.sampled_from([2, 1, 3, np.inf]))
    expect_v = np.linalg.norm(an.ravel(), ord=vord)
    np.testing.assert_allclose(
        float(run(xp.linalg.vector_norm(a, ord=vord))), expect_v,
        atol=_tol(an, k=100, extra=expect_v),
    )


@settings(max_examples=15, deadline=None)
@given(data=st.data())
def test_quantile_matches_numpy_property(data):
    # NaN poisoning is pinned by tests/test_quantile.py (the harness
    # generators draw finite values only)
    an = data.draw(arrays(min_dims=1))
    axis = data.draw(st.integers(0, an.ndim - 1))
    if an.shape[axis] == 0:
        return
    q = data.draw(st.sampled_from([0.0, 0.1, 0.5, 0.9, 1.0]))
    a = wrap(an.astype(np.float64), None)
    got = run(xp.quantile(a, q, axis=axis))
    expect = np.quantile(an.astype(np.float64), q, axis=axis)
    np.testing.assert_allclose(got, expect, atol=1e-12)


@settings(max_examples=15, deadline=None)
@given(data=st.data())
def test_histogram_matches_numpy_property(data):
    an = data.draw(arrays(min_dims=1))
    if an.size == 0:
        return
    an = an.astype(np.float64)
    nbins = data.draw(st.integers(1, 8))
    a = wrap(an, None)
    h, e = xp.histogram(a, bins=nbins)
    en = run(e)
    # edges match numpy's linspace to a few ulps of the extent (the
    # convex-combination formula differs in the last bits; a sample
    # within an ulp of an interior edge may legitimately bin differently)
    _, ex = np.histogram(an, bins=nbins)
    scale = max(1.0, float(np.max(np.abs(ex))))
    np.testing.assert_allclose(en, ex, atol=16 * np.finfo(np.float64).eps * scale)
    # counts validate against numpy binning with OUR edges: exact
    np.testing.assert_array_equal(run(h), np.histogram(an, bins=en)[0])
