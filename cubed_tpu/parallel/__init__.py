from .mesh import make_mesh, sharding_for_chunks  # noqa: F401
