"""Post-hoc invariant auditing over the artifacts the system already writes.

Every recovery path in the stack (retries, requeues, takeover, resume,
quarantine) ultimately rests on a handful of *global* invariants that no
single test assertion states: a task's result is applied exactly once per
run, a task has one owner at a time unless a recorded failure event moved
it, every consumed retry was accounted, the store's bytes match the
integrity manifest modulo quarantine, the bookkeeping counters conserve,
and coordinator epochs only move forward. The chaos suites prove *bitwise
output*; this module upgrades those proofs to *bitwise + invariant-clean*
by re-deriving the invariants from durable artifacts after the fact — the
Jepsen discipline: compose failures first, then let a checker (not a
reviewer) decide whether the history was legal.

Inputs (all optional — each invariant runs only when its artifact is
present):

- the **compute journal** (``Spec(journal=...)``, runtime/journal.py):
  per-attempt ``dispatch`` and once-per-task ``complete`` records, split
  into run segments at each ``compute_start``;
- the **control log** (``DistributedDagExecutor(control_dir=...)``):
  epoch fences, worker registrations, the per-task dispatch frontier, and
  the mirrored connectivity decisions;
- the **work dir**: every array store carrying integrity-manifest shards
  is re-read and re-checksummed;
- a **metrics snapshot delta** (``get_registry().snapshot_delta(before)``)
  for the conservation laws counters must obey.

The invariant catalogue (names are stable API — tests and docs key on
them):

``exactly_once_application``
    Within one run segment a ``complete`` record appears at most once per
    ``(op, chunk_key)``, and never without a prior ``dispatch`` of that
    task in the same segment. Re-runs across segments (resume re-running
    an unverifiable task) are legal; double-application within a run —
    e.g. a speculative twin or a replayed fleet result leaking past dedup
    — is not.

``single_ownership``
    In the control log, a task_id re-dispatched to a *different* worker
    requires an intervening ownership-release event: a ``worker_gone``
    record for the previous owner, or a requeue-class decision
    (disconnect, lease expiry, drain, preemption, timeout, takeover).
    Silent re-dispatch means two workers could hold the same assignment.

``retry_budget_conservation``
    Every consumed retry was backoff-spaced exactly once:
    ``retry_backoff_s.count == task_retries`` in the metrics delta. A
    compute that claims success must not have tripped the circuit breaker
    (``retry_budget_exhausted`` = 0 when ``expect_success=True``).

``manifest_store_crc``
    Every manifested chunk is either present with matching CRC-32 and
    byte length, or has been quarantined (``<key>.quarantine.<ts>`` —
    quarantine keeps the manifest entry on purpose). A present chunk
    whose bytes disagree with its manifest is corruption the runtime
    failed to catch; a manifested chunk that vanished without a
    quarantine marker is a silent hole resume would mis-trust.

``counter_conservation``
    Per journal segment, ``complete`` records never exceed ``dispatch``
    records (results cannot outnumber attempts). In the metrics delta,
    ``tasks_completed <= tasks_started`` and ``faults_injected`` equals
    the sum of its per-site counters (each injection increments both).

``epoch_monotonicity``
    Epoch records in the control log are strictly increasing in file
    order, and the rendezvous advertisement never names an epoch newer
    than the last durably recorded one (``record_epoch`` is fsync'd
    *before* ``advertise`` — an advertisement from the future means the
    fence is not durable).

Use ``InvariantAuditor(...).audit()`` programmatically (the chaos suites'
shared fixture does), or ``python -m cubed_tpu.audit --journal J
--control-dir D --work-dir W`` against a production run's artifacts
(exit code 1 names every violated invariant).
"""

from __future__ import annotations

import argparse
import json
import os
import zlib
from dataclasses import dataclass, field
from typing import Optional

#: decision kinds that legitimately release task ownership between two
#: dispatches of the same task_id (the requeue-class events the control
#: plane records when a worker stops being trustworthy or departs)
OWNERSHIP_RELEASE_DECISIONS = frozenset({
    "worker_disconnected",
    "worker_reconnected",
    "lease_expired",
    "requeue",
    "worker_preempted",
    "worker_draining",
    "worker_drained",
    "worker_drain_requested",
    "task_timeout",
    "coordinator_takeover",
    "worker_rejected",
    "spawn_died",
})

#: per-site fault counters are dynamic (``faults_injected_<site>``); the
#: conservation law is total == sum(sites)
FAULTS_TOTAL = "faults_injected"
FAULTS_SITE_PREFIX = "faults_injected_"


@dataclass
class Violation:
    """One invariant breach, with enough context to reproduce the claim."""

    invariant: str
    message: str
    context: dict = field(default_factory=dict)

    def render(self) -> str:
        ctx = " ".join(f"{k}={v}" for k, v in self.context.items())
        return f"[{self.invariant}] {self.message}" + (f"  ({ctx})" if ctx else "")


@dataclass
class AuditReport:
    """The auditor's verdict: which invariants ran, what they found."""

    violations: list = field(default_factory=list)
    checked: list = field(default_factory=list)
    #: artifact stats for the human reading the report (segments folded,
    #: chunks re-checksummed, ...) — diagnostic, not load-bearing
    stats: dict = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.violations

    def by_invariant(self, name: str) -> list:
        return [v for v in self.violations if v.invariant == name]

    def render(self) -> str:
        lines = [
            f"invariant audit: {'CLEAN' if self.ok else 'VIOLATED'} "
            f"({len(self.checked)} invariant(s) checked: "
            f"{', '.join(self.checked) or 'none'})"
        ]
        for k, v in sorted(self.stats.items()):
            lines.append(f"  {k}: {v}")
        for v in self.violations:
            lines.append("  " + v.render())
        return "\n".join(lines)


def _hashable(v):
    """JSON round-trips chunk keys as lists; fold to tuples so they can
    key the per-segment dispatch/complete maps."""
    if isinstance(v, list):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


class _PlainIO:
    """Injection-free local store IO for the auditor: the auditor reads
    ground truth, so it must bypass the fault injector that
    ``storage.store._LocalIO`` consults (an armed injector would make the
    audit roll chaos decisions of its own)."""

    def __init__(self, root: str):
        self.root = root

    def list_names(self) -> list:
        try:
            return os.listdir(self.root)
        except FileNotFoundError:
            return []

    def read_bytes(self, name: str) -> bytes:
        with open(os.path.join(self.root, name), "rb") as f:
            return f.read()

    def exists(self, name: str) -> bool:
        return os.path.exists(os.path.join(self.root, name))


def _read_jsonl(path: str) -> tuple:
    """All decodable records of a JSONL file, in order, plus the count of
    torn/garbage lines skipped — the same tolerance discipline every
    journal loader in the codebase uses (a torn tail costs its own line,
    never the audit)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return [], 0
    records, bad = [], 0
    for line in raw.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
            if not isinstance(doc, dict):
                raise ValueError("not an object")
        except (ValueError, UnicodeDecodeError):
            bad += 1
            continue
        records.append(doc)
    return records, bad


def journal_segments(path: str) -> list:
    """Split a compute journal into run segments at each ``compute_start``.

    Returns ``[{"meta": compute_start record (or {}), "records": [...]}]``
    — resume and crash-rerun append to one file, so per-run invariants
    must fold per segment, not per file."""
    records, _bad = _read_jsonl(path)
    segments: list = []
    current = {"meta": {}, "records": []}
    for rec in records:
        if rec.get("kind") == "compute_start":
            if current["records"] or current["meta"]:
                segments.append(current)
            current = {"meta": rec, "records": []}
        else:
            current["records"].append(rec)
    if current["records"] or current["meta"]:
        segments.append(current)
    return segments


class InvariantAuditor:
    """Verify global invariants post-hoc from a compute's durable artifacts.

    Every input is optional; each invariant is checked exactly when its
    artifact was provided, and ``AuditReport.checked`` names what actually
    ran — an audit that silently checked nothing must be visible as such.

    Parameters
    ----------
    journal:
        Path to a compute journal (``Spec(journal=...)``).
    control_dir:
        The distributed coordinator's ``control_dir`` (``control.jsonl``
        + ``rendezvous.json``).
    work_dir:
        Root directory scanned for array stores with integrity-manifest
        shards; every manifested chunk is re-read and re-checksummed.
    metrics:
        A metrics snapshot delta covering the compute
        (``get_registry().snapshot_delta(before)``).
    expect_success:
        When True, artifacts of a compute that *claims* it succeeded are
        held to the stricter laws (no budget exhaustion).
    """

    def __init__(
        self,
        journal: Optional[str] = None,
        control_dir: Optional[str] = None,
        work_dir: Optional[str] = None,
        metrics: Optional[dict] = None,
        expect_success: Optional[bool] = None,
    ):
        self.journal = str(journal) if journal else None
        self.control_dir = str(control_dir) if control_dir else None
        self.work_dir = str(work_dir) if work_dir else None
        self.metrics = metrics
        self.expect_success = expect_success

    # -- entry point ----------------------------------------------------

    def audit(self) -> AuditReport:
        report = AuditReport()
        if self.journal and os.path.exists(self.journal):
            self._audit_journal(report)
        if self.control_dir:
            from .journal import control_log_path

            if os.path.exists(control_log_path(self.control_dir)):
                self._audit_control(report)
        if self.work_dir and os.path.isdir(self.work_dir):
            self._audit_manifests(report)
        if self.metrics is not None:
            self._audit_metrics(report)
        return report

    # -- journal: exactly-once + dispatch/complete conservation ---------

    def _audit_journal(self, report: AuditReport) -> None:
        report.checked.append("exactly_once_application")
        if "counter_conservation" not in report.checked:
            report.checked.append("counter_conservation")
        segments = journal_segments(self.journal)
        report.stats["journal_segments"] = len(segments)
        for si, seg in enumerate(segments):
            dispatched: dict = {}
            completed: dict = {}
            n_dispatch = n_complete = 0
            for rec in seg["records"]:
                kind = rec.get("kind")
                op, key = rec.get("op"), _hashable(rec.get("key"))
                if kind == "dispatch" and isinstance(op, str):
                    n_dispatch += 1
                    dispatched[(op, key)] = dispatched.get((op, key), 0) + 1
                elif kind == "complete" and isinstance(op, str):
                    n_complete += 1
                    completed[(op, key)] = completed.get((op, key), 0) + 1
                    if (op, key) not in dispatched:
                        report.violations.append(Violation(
                            "exactly_once_application",
                            "result applied for a task this run never "
                            "dispatched",
                            {"segment": si, "op": op, "key": key},
                        ))
            for (op, key), n in completed.items():
                if n > 1:
                    report.violations.append(Violation(
                        "exactly_once_application",
                        f"result applied {n} times in one run",
                        {"segment": si, "op": op, "key": key},
                    ))
            if n_complete > n_dispatch:
                report.violations.append(Violation(
                    "counter_conservation",
                    f"{n_complete} completions exceed {n_dispatch} "
                    "dispatches in one run segment",
                    {"segment": si},
                ))
            report.stats[f"segment_{si}_dispatches"] = n_dispatch
            report.stats[f"segment_{si}_completes"] = n_complete

    # -- control log: single ownership + epoch monotonicity -------------

    def _audit_control(self, report: AuditReport) -> None:
        from .journal import control_log_path, read_rendezvous, rendezvous_path

        report.checked.append("single_ownership")
        report.checked.append("epoch_monotonicity")
        records, _bad = _read_jsonl(control_log_path(self.control_dir))

        # single ownership: fold the dispatch frontier in file order; a
        # re-dispatch to a new worker needs a release event in between
        owner: dict = {}
        releases_since: dict = {}  # task_id -> release seen since dispatch
        released_workers: set = set()
        redispatches = 0
        for rec in records:
            kind = rec.get("kind")
            if kind == "worker_gone":
                name = rec.get("name")
                released_workers.add(name)
                for tid, w in list(owner.items()):
                    if w == name:
                        releases_since[tid] = True
            elif kind == "decision":
                if rec.get("decision") in OWNERSHIP_RELEASE_DECISIONS:
                    w = rec.get("worker")
                    tid = rec.get("task_id")
                    if tid is not None and tid in owner:
                        releases_since[tid] = True
                    elif w is not None:
                        released_workers.add(w)
                        for t, ow in list(owner.items()):
                            if ow == w:
                                releases_since[t] = True
                    else:
                        # a release event naming neither (e.g. a takeover
                        # marker) releases everything in flight: the new
                        # epoch re-issues under its own fence
                        for t in list(owner):
                            releases_since[t] = True
            elif kind == "worker":
                # a worker (re)registration ends any prior release state
                released_workers.discard(rec.get("name"))
            elif kind == "dispatch":
                tid = rec.get("task_id")
                worker = rec.get("worker")
                if tid is None:
                    continue
                prev = owner.get(tid)
                if (
                    prev is not None
                    and worker != prev
                    and not releases_since.get(tid)
                    and prev not in released_workers
                ):
                    redispatches += 1
                    report.violations.append(Violation(
                        "single_ownership",
                        "task re-dispatched to a second worker with no "
                        "recorded ownership release",
                        {"task_id": tid, "from": prev, "to": worker,
                         "tag": rec.get("tag")},
                    ))
                owner[tid] = worker
                releases_since[tid] = False
            elif kind == "done":
                owner.pop(rec.get("task_id"), None)
                releases_since.pop(rec.get("task_id"), None)

        # epoch monotonicity: strictly increasing fences, and the
        # advertisement never runs ahead of the durable record
        last_epoch = None
        for rec in records:
            if rec.get("kind") != "epoch":
                continue
            e = rec.get("epoch")
            if not isinstance(e, int):
                continue
            if last_epoch is not None and e <= last_epoch:
                report.violations.append(Violation(
                    "epoch_monotonicity",
                    f"epoch fence went from {last_epoch} to {e}",
                    {"control_log": control_log_path(self.control_dir)},
                ))
            last_epoch = e
        adv = read_rendezvous(rendezvous_path(self.control_dir))
        if adv is not None and last_epoch is not None:
            if adv["epoch"] > last_epoch:
                report.violations.append(Violation(
                    "epoch_monotonicity",
                    f"rendezvous advertises epoch {adv['epoch']} but the "
                    f"last durably recorded fence is {last_epoch}",
                    {"control_dir": self.control_dir},
                ))
        report.stats["control_records"] = len(records)
        if last_epoch is not None:
            report.stats["last_epoch"] = last_epoch

    # -- store vs manifest: CRC consistency modulo quarantine ------------

    def _iter_manifest_dirs(self):
        from ..storage.integrity import MANIFEST_PREFIX

        for root, _dirs, names in os.walk(self.work_dir):
            if any(n.startswith(MANIFEST_PREFIX) for n in names):
                yield root

    def _audit_manifests(self, report: AuditReport) -> None:
        from ..storage.integrity import load_manifest

        report.checked.append("manifest_store_crc")
        verified = 0
        stores = 0
        for store_root in self._iter_manifest_dirs():
            stores += 1
            io = _PlainIO(store_root)
            entries, _had = load_manifest(io)
            names = set(io.list_names())
            for key, ent in entries.items():
                quarantined = any(
                    n.startswith(f"{key}.quarantine.") for n in names
                )
                if key not in names:
                    if not quarantined:
                        report.violations.append(Violation(
                            "manifest_store_crc",
                            "manifested chunk is missing with no "
                            "quarantine marker",
                            {"store": store_root, "key": key},
                        ))
                    continue
                try:
                    data = io.read_bytes(key)
                except OSError as e:
                    report.violations.append(Violation(
                        "manifest_store_crc",
                        f"manifested chunk unreadable: {e}",
                        {"store": store_root, "key": key},
                    ))
                    continue
                verified += 1
                if len(data) != ent.get("n") or (
                    zlib.crc32(data) & 0xFFFFFFFF
                ) != ent.get("c"):
                    report.violations.append(Violation(
                        "manifest_store_crc",
                        "chunk bytes disagree with the integrity manifest "
                        "(undetected corruption)",
                        {"store": store_root, "key": key,
                         "manifest_crc": ent.get("c"),
                         "actual_crc": zlib.crc32(data) & 0xFFFFFFFF,
                         "manifest_n": ent.get("n"), "actual_n": len(data)},
                    ))
        report.stats["manifest_stores"] = stores
        report.stats["chunks_reverified"] = verified

    # -- metrics: conservation laws --------------------------------------

    @staticmethod
    def _hist_count(val) -> Optional[int]:
        if isinstance(val, dict):
            c = val.get("count")
            return int(c) if isinstance(c, (int, float)) else None
        return None

    def _audit_metrics(self, report: AuditReport) -> None:
        m = self.metrics or {}
        report.checked.append("retry_budget_conservation")
        if "counter_conservation" not in report.checked:
            report.checked.append("counter_conservation")

        retries = int(m.get("task_retries", 0) or 0)
        backoffs = self._hist_count(m.get("retry_backoff_s"))
        if backoffs is not None and backoffs != retries:
            report.violations.append(Violation(
                "retry_budget_conservation",
                f"{retries} retries drawn from the budget but "
                f"{backoffs} backoff delays scheduled — a retry ran "
                "unaccounted (or was double-counted)",
                {"task_retries": retries, "retry_backoff_count": backoffs},
            ))
        if self.expect_success and int(m.get("retry_budget_exhausted", 0) or 0):
            report.violations.append(Violation(
                "retry_budget_conservation",
                "compute claims success but the retry circuit breaker "
                "tripped",
                {"retry_budget_exhausted": m.get("retry_budget_exhausted")},
            ))

        started = int(m.get("tasks_started", 0) or 0)
        completed = int(m.get("tasks_completed", 0) or 0)
        if completed > started:
            report.violations.append(Violation(
                "counter_conservation",
                f"{completed} tasks completed but only {started} started",
                {"tasks_started": started, "tasks_completed": completed},
            ))
        site_total = sum(
            int(v or 0) for k, v in m.items()
            if k.startswith(FAULTS_SITE_PREFIX) and isinstance(v, (int, float))
        )
        total = int(m.get(FAULTS_TOTAL, 0) or 0)
        if total != site_total:
            report.violations.append(Violation(
                "counter_conservation",
                f"faults_injected={total} but per-site counters sum to "
                f"{site_total}",
                {"faults_injected": total, "site_sum": site_total},
            ))


def audit_artifacts(**kwargs) -> AuditReport:
    """One-call convenience: ``audit_artifacts(journal=..., ...)``."""
    return InvariantAuditor(**kwargs).audit()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cubed_tpu.audit",
        description="Verify global invariants post-hoc from a compute's "
        "durable artifacts (journal, control log, integrity manifests).",
    )
    parser.add_argument("--journal", help="compute journal JSONL path")
    parser.add_argument(
        "--control-dir", help="coordinator control_dir (control.jsonl)"
    )
    parser.add_argument(
        "--work-dir",
        help="work dir scanned for stores with integrity manifests",
    )
    parser.add_argument(
        "--expect-success", action="store_true",
        help="hold the artifacts to the stricter success-claim laws",
    )
    args = parser.parse_args(argv)
    if not (args.journal or args.control_dir or args.work_dir):
        parser.error(
            "nothing to audit: pass --journal, --control-dir and/or "
            "--work-dir"
        )
    report = InvariantAuditor(
        journal=args.journal,
        control_dir=args.control_dir,
        work_dir=args.work_dir,
        expect_success=args.expect_success or None,
    ).audit()
    print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
