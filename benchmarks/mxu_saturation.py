"""MXU saturation probe: how fast can the framework drive the systolic array?

The canonical matmul config (4000x4000, (1000,1000) chunks) exists to price
orchestration and is dispatch-latency-bound on device (~70 ms floor for
~0.087 s total — BENCH_PROFILE.md §round 5). This script measures the
framework at a size where the MXU, not the tunnel, is the bottleneck:

    sum(a @ b), n=16384, chunks (8192, 8192), f32 storage,
    bf16 matmul precision (the ``matmul_precision="bfloat16"`` opt-in)

= 8.8 TFLOP across a 2x2x2 blockwise contraction of 8192^3 tile matmuls —
large enough that even at full v5e bf16 peak (~197 TFLOP/s) device compute
exceeds the dispatch floor. A raw-JAX jit of the same math (same RNG, same
precision) runs second for the framework/raw ratio.

Output: one JSON line per leg (framework, raw) + a summary line with
fraction-of-peak. Run with the inherited device env.
"""

from __future__ import annotations

import json
import sys
import tempfile
import time

import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from device_session import V5E_BF16_PEAK_GFLOPS  # noqa: E402  (shared constant)

N = 16384
CHUNK = 8192
FLOPS = 2 * N * N * N  # 8.796 TFLOP
REPS = 3


def framework_leg() -> dict:
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    import cubed_tpu.random
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="8GB")
    executor = JaxExecutor(compute_dtype="float32",
                           matmul_precision="bfloat16")

    def build():
        a = cubed_tpu.random.random((N, N), chunks=CHUNK, spec=spec)
        b = cubed_tpu.random.random((N, N), chunks=CHUNK, spec=spec)
        return xp.sum(xp.matmul(a, b))

    build().compute(executor=executor)  # compile + caches
    best = float("inf")
    for _ in range(REPS):
        s = build()
        t0 = time.perf_counter()
        v = float(s.compute(executor=executor))
        best = min(best, time.perf_counter() - t0)
    assert 0.85 < v / (0.25 * N**3) < 1.15, v  # E[sum(A@B)] = n^3/4
    return {"leg": "framework", "elapsed_s": round(best, 4),
            "gflops": round(FLOPS / best / 1e9, 1)}


def raw_leg() -> dict:
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_threefry_partitionable", True)

    @jax.jit
    def step(seed):
        ka = jax.random.fold_in(jax.random.key(0), seed * 7919 + 1)
        kb = jax.random.fold_in(jax.random.key(0), seed * 7919 + 2)
        a = jax.random.uniform(ka, (N, N), dtype=jnp.float32)
        b = jax.random.uniform(kb, (N, N), dtype=jnp.float32)
        with jax.default_matmul_precision("bfloat16"):
            return jnp.sum(a @ b)

    float(step(0))  # compile
    best = float("inf")
    for i in range(REPS):
        t0 = time.perf_counter()
        float(step(100 + i))  # distinct seed defeats the tunnel result cache
        best = min(best, time.perf_counter() - t0)
    return {"leg": "raw_jax", "elapsed_s": round(best, 4),
            "gflops": round(FLOPS / best / 1e9, 1)}


def main() -> int:
    fw = framework_leg()
    print(json.dumps(fw), flush=True)
    raw = raw_leg()
    print(json.dumps(raw), flush=True)
    print(json.dumps({
        "leg": "summary",
        "framework_gflops": fw["gflops"],
        "raw_jax_gflops": raw["gflops"],
        "fw_over_raw": round(fw["gflops"] / raw["gflops"], 3),
        "framework_fraction_of_bf16_peak": round(
            fw["gflops"] / V5E_BF16_PEAK_GFLOPS, 4),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
