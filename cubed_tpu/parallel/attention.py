"""User-facing attention over cubed arrays: the bridge that makes the
sequence-parallel ring kernels reachable from the array layer.

Global attention needs cross-chunk communication along the sequence axis —
exactly what the array layer's embarrassingly-parallel task model cannot
express (the reference has no attention at all; SURVEY.md §5.7 maps the
long-context obligation to sequence sharding over the mesh). So this API
sits beside the plan machinery, not inside it: inputs are computed (storage
-> HBM), attention runs as ONE jitted sequence-parallel program
(parallel/ring_attention.py — ring over the mesh's axis, dense on a single
device), and the result re-enters the plan world as a cubed array.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    mesh=None,
    axis_name: str = "seq",
    chunks=None,
    spec=None,
):
    """Multi-head attention over cubed arrays of shape (B, S, H, D).

    With ``mesh``, the sequence axis is sharded over ``axis_name`` and the
    kernel is blockwise ring attention (KV blocks rotate via collective
    permute over ICI; numerically-stable streaming softmax). Without it, a
    single-device dense kernel. Returns a cubed array chunked like ``q``
    (override with ``chunks``).
    """
    from ..core.array import CoreArray
    from ..core.ops import from_array
    from .ring_attention import dense_attention, ring_attention, sequence_sharded

    import jax

    # evaluate all cubed inputs in ONE plan so a shared upstream subgraph
    # (the usual one-source-three-projections pattern) computes once
    from ..core.array import compute as compute_multi

    core = [x for x in (q, k, v) if isinstance(x, CoreArray)]
    computed = iter(compute_multi(*core)) if core else iter(())

    def materialize(x):
        if isinstance(x, CoreArray):
            return np.asarray(next(computed)), x
        return np.asarray(x), None

    qn, q_arr = materialize(q)
    kn, _ = materialize(k)
    vn, _ = materialize(v)
    if qn.ndim != 4:
        raise ValueError(f"attention expects (B, S, H, D) arrays, got {qn.shape}")

    if mesh is not None and axis_name not in mesh.axis_names:
        raise ValueError(
            f"axis_name {axis_name!r} is not a mesh axis {mesh.axis_names}; "
            "pass axis_name= matching your mesh (a silent dense fallback "
            "would run the whole sequence on one device)"
        )
    if mesh is not None:
        qd = sequence_sharded(qn, mesh, axis_name=axis_name)
        kd = sequence_sharded(kn, mesh, axis_name=axis_name)
        vd = sequence_sharded(vn, mesh, axis_name=axis_name)
        out = ring_attention(
            qd, kd, vd, mesh=mesh, axis_name=axis_name, causal=causal, scale=scale
        )
    else:
        out = jax.jit(
            lambda a, b, c: dense_attention(a, b, c, causal=causal, scale=scale)
        )(qn, kn, vn)

    out_np = np.asarray(out).astype(qn.dtype)
    if chunks is None:
        chunks = q_arr.chunksize if q_arr is not None else out_np.shape
    if spec is None and q_arr is not None:
        spec = q_arr.spec
    return from_array(out_np, chunks=chunks, spec=spec)
