"""Process-local metrics: counters, gauges, and histograms.

One registry serves the whole process (``get_registry()``); executors, the
distributed coordinator and the storage layer all report through it, so a
compute's ``ComputeEndEvent.executor_stats`` can carry a single coherent
snapshot. ``snapshot()`` is a plain flat dict (JSON-serializable), so it can
ride inside bench records, cross process boundaries, and be merged with
``merge_snapshots`` (worker-side snapshots folding into a coordinator's).

The canonical metric names used across the codebase:

- ``tasks_completed`` / ``tasks_started`` — task lifecycle counts
- ``task_retries`` / ``task_timeouts`` / ``speculative_backups`` /
  ``workers_lost`` — the reliability machinery's counters
- ``task_failfast`` / ``worker_loss_requeues`` / ``retry_budget_exhausted``
  / ``pool_rebuilds`` / ``storage_read_retries`` — the resilience layer's
  classified-failure counters (``runtime/resilience.py``)
- ``retry_backoff_s`` — histogram of backoff delays scheduled before retries
- ``faults_injected`` (+ ``faults_injected_<site>``) /
  ``orphan_tmps_swept`` — chaos-testing fault injection
  (``runtime/faults.py``) and crash-litter hygiene
- ``chunks_verified`` / ``chunks_corrupt_detected`` /
  ``chunks_quarantined`` / ``chunks_recomputed`` /
  ``tasks_skipped_resume`` / ``zarray_meta_recreated`` — the chunk
  integrity layer (``storage/integrity.py``): checksum verifications,
  detected corruption, quarantined files, upstream-task recomputes, and
  the tasks a chunk-granular resume proved already done
- ``mem_guard_soft_exceeded`` / ``mem_guard_hard_exceeded`` /
  ``mem_guard_aborts`` / ``task_resource_failures`` — the runtime memory
  guard (``runtime/memory.py``): observe-mode exceedances, enforce-mode
  guard trips, actionable concurrency-1 aborts, and all
  RESOURCE-classified task failures
- ``tasks_throttled`` / ``mem_pressure_stepdowns`` /
  ``mem_pressure_restores`` / ``admission_limit`` (gauge) — the admission
  controller's adaptive concurrency degradation under memory pressure
- ``worker_rss_bytes`` / ``fleet_worker_rss_bytes`` /
  ``mem_host_available_bytes`` / ``mem_pressure`` (gauges) — sampler- and
  heartbeat-reported memory telemetry (host watermarks)
- ``worker_oom_kills`` / ``dispatch_skipped_pressured`` — OOM-killed pool
  workers detected by exit code, and fleet dispatches rerouted away from
  memory-pressured workers
- ``bytes_read`` / ``bytes_written`` / ``chunks_read`` / ``chunks_written``
  — Zarr store IO (see ``accounting.py``)
- ``virtual_bytes_read`` — reads served by virtual (never-materialized) arrays
- ``queue_depth`` — gauge of in-flight tasks in the completion-ordered map
- ``op_wall_clock_s`` — histogram of per-operation wall clock
"""

from __future__ import annotations

import logging
import random
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)


class Counter:
    """A monotonically increasing integer."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """A point-in-time value; tracks the maximum it has ever been set to."""

    __slots__ = ("name", "_value", "_max", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._max = 0
        self._lock = threading.Lock()

    def set(self, v) -> None:
        with self._lock:
            self._value = v
            if v > self._max:
                self._max = v

    @property
    def value(self):
        return self._value

    @property
    def max(self):
        return self._max


#: bounded reservoir size for histogram quantile estimation (per
#: histogram): sized so the p99 estimate of a 512-sample reservoir stays
#: within a few observations of the true p99 for any stream length, at a
#: fixed ~4KB-per-histogram memory cost
RESERVOIR_SIZE = 512

#: the quantiles every histogram estimates, exported through ``summary()``
#: (and from there ``snapshot()`` / the Prometheus ``/metrics`` endpoint)
#: — latency SLO rules need percentiles, not just count/sum/min/max
QUANTILES = ((0.5, "p50"), (0.95, "p95"), (0.99, "p99"))


class Histogram:
    """Streaming summary (count/sum/min/max + estimated p50/p95/p99) of an
    observed quantity.

    Quantiles come from a bounded reservoir (Vitter's algorithm R,
    ``RESERVOIR_SIZE`` samples, seeded per histogram name so replacement is
    deterministic for a given observation order): every observation has an
    equal chance of being retained, so the sorted reservoir's order
    statistics estimate the stream's quantiles at fixed memory."""

    __slots__ = ("name", "count", "sum", "min", "max", "_lock",
                 "_reservoir", "_rng")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._lock = threading.Lock()
        self._reservoir: list = []
        self._rng = random.Random(name)

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._reservoir) < RESERVOIR_SIZE:
                self._reservoir.append(v)
            else:
                slot = self._rng.randrange(self.count)
                if slot < RESERVOIR_SIZE:
                    self._reservoir[slot] = v

    def quantiles(self) -> dict:
        """Estimated quantiles from the reservoir (empty dict when nothing
        was observed). Keys are the ``QUANTILES`` labels (p50/p95/p99)."""
        with self._lock:
            sample = sorted(self._reservoir)
        if not sample:
            return {}
        n = len(sample)
        out = {}
        for q, label in QUANTILES:
            # nearest-rank on the retained sample
            idx = min(n - 1, max(0, int(round(q * (n - 1)))))
            out[label] = sample[idx]
        return out

    def summary(self) -> dict:
        q = self.quantiles()
        with self._lock:
            out = {
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "mean": (self.sum / self.count) if self.count else None,
            }
        out.update(q)
        return out


class MetricsRegistry:
    """Named counters/gauges/histograms with a flat dict snapshot.

    Snapshot keys: a counter appears under its name; a gauge under its name
    plus ``<name>_max``; a histogram under ``<name>`` as a nested summary
    dict. ``snapshot_delta(before)`` subtracts counter/histogram
    accumulations so a long-lived process (a persistent fleet, a REPL) can
    report per-compute numbers.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        #: gauge keys already log-noted as dropped from snapshot_delta
        self._delta_gauges_logged: set = set()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def snapshot(self) -> dict:
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            histograms = list(self._histograms.values())
        out: dict = {}
        for c in counters:
            out[c.name] = c.value
        for g in gauges:
            out[g.name] = g.value
            out[f"{g.name}_max"] = g.max
        for h in histograms:
            out[h.name] = h.summary()
        return out

    def snapshot_delta(self, before: dict, now: Optional[dict] = None) -> dict:
        """Current snapshot minus a previous one.

        ``now`` lets a caller that already took the current snapshot reuse
        it — the heartbeat path needs the delta and the new baseline to be
        the SAME observation, or increments landing between two internal
        snapshots would ship twice (once in this delta, again in the
        next).

        Counters and histogram count/sum/mean subtract, so the result is a
        true per-window reading. Quantities that CANNOT be windowed from two
        snapshots are dropped rather than reported stale: a gauge's
        ``_max`` key appears only if the window set a new high, a gauge's
        instantaneous value is omitted entirely (the end-of-window reading —
        e.g. ``queue_depth`` after the queue drained — measures nothing),
        and histogram summaries omit lifetime min/max and quantiles (a
        long-lived process — persistent fleet, bench loop — must not
        attribute an old compute's extremes to a later one).

        Dropped gauges are NOT silent: each unwindowable gauge reading is
        counted in the ``gauges_dropped_in_delta`` counter (and logged once
        per key per registry), so a consumer shipping deltas — the fleet
        heartbeat path — can see that a gauge existed and was windowed
        away rather than never reported at all."""
        if now is None:
            now = self.snapshot()
        with self._lock:
            gauge_names = set(self._gauges)
        out: dict = {}
        dropped_gauges = []
        for k, v in now.items():
            prev = before.get(k)
            if isinstance(v, dict):  # histogram summary
                pc = (prev or {}).get("count", 0) if isinstance(prev, dict) else 0
                ps = (prev or {}).get("sum", 0.0) if isinstance(prev, dict) else 0.0
                count = v["count"] - pc
                out[k] = {
                    "count": count,
                    "sum": v["sum"] - ps,
                    "mean": ((v["sum"] - ps) / count) if count else None,
                }
            elif k.endswith("_max") and k[: -len("_max")] in gauge_names:
                # lifetime high-water mark: only meaningful for this window
                # if the window raised it
                if not isinstance(prev, (int, float)) or v > prev:
                    out[k] = v
            elif k in gauge_names:
                dropped_gauges.append(k)
                continue  # instantaneous reading: not a per-window quantity
            elif isinstance(prev, (int, float)):
                out[k] = v - prev
            else:
                out[k] = v
        if dropped_gauges:
            # count AFTER the snapshot above, so this window's delta is not
            # perturbed by its own bookkeeping (the next window sees it)
            self.counter("gauges_dropped_in_delta").inc(len(dropped_gauges))
            for k in dropped_gauges:
                if k not in self._delta_gauges_logged:
                    self._delta_gauges_logged.add(k)
                    logger.info(
                        "snapshot_delta: gauge %r has no per-window value "
                        "and is dropped from deltas (its _max rides when "
                        "the window raises it; counted in "
                        "gauges_dropped_in_delta)", k,
                    )
        return out

    def report(self) -> str:
        """Human-readable table of the current snapshot."""
        snap = self.snapshot()
        if not snap:
            return "(no metrics recorded)"
        rows = []
        for k in sorted(snap):
            v = snap[k]
            if isinstance(v, dict):
                mean = v.get("mean")
                row = (
                    f"count={v['count']} sum={_fmt(v['sum'])} "
                    f"mean={_fmt(mean)} min={_fmt(v['min'])} "
                    f"max={_fmt(v['max'])}"
                )
                if v.get("p50") is not None:
                    row += (
                        f" p50={_fmt(v['p50'])} p95={_fmt(v.get('p95'))} "
                        f"p99={_fmt(v.get('p99'))}"
                    )
                rows.append((k, row))
            else:
                rows.append((k, _fmt(v)))
        width = max(len(k) for k, _ in rows)
        lines = [f"{k.ljust(width)}  {v}" for k, v in rows]
        return "\n".join(lines)

    def kinds(self) -> Dict[str, str]:
        """Metric name -> ``"counter"`` / ``"gauge"`` / ``"histogram"`` for
        every registered metric — what the Prometheus exposition needs to
        emit correct ``# TYPE`` lines (``observability/export.py``)."""
        with self._lock:
            out: Dict[str, str] = {n: "counter" for n in self._counters}
            out.update({n: "gauge" for n in self._gauges})
            out.update({n: "histogram" for n in self._histograms})
            return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._delta_gauges_logged.clear()


def _fmt(v) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def merge_snapshots(a: dict, b: dict) -> dict:
    """Combine two snapshots: counters add, histogram summaries fold, and
    gauge readings take the max. A gauge is recognized structurally — a key
    whose ``<key>_max`` sibling exists in either snapshot (``snapshot()``
    always emits both) — because summing point-in-time readings (e.g. two
    workers each reporting queue_depth=3) would claim load that never
    existed at any instant. Used to merge worker-side metrics into a
    coordinator-side view."""
    out = dict(a)
    for k, v in b.items():
        if k not in out:
            out[k] = v
        elif (
            isinstance(v, (int, float))
            and isinstance(out[k], (int, float))
            and (f"{k}_max" in a or f"{k}_max" in b)
        ):
            out[k] = max(out[k], v)  # gauge reading: point-in-time, not additive
        elif isinstance(v, dict) and isinstance(out[k], dict):
            ac, bc = out[k], v
            count = (ac.get("count") or 0) + (bc.get("count") or 0)
            total = (ac.get("sum") or 0.0) + (bc.get("sum") or 0.0)
            mins = [x for x in (ac.get("min"), bc.get("min")) if x is not None]
            maxs = [x for x in (ac.get("max"), bc.get("max")) if x is not None]
            out[k] = {
                "count": count,
                "sum": total,
                "mean": (total / count) if count else None,
                "min": min(mins) if mins else None,
                "max": max(maxs) if maxs else None,
            }
        elif isinstance(v, (int, float)) and isinstance(out[k], (int, float)):
            out[k] = max(out[k], v) if k.endswith("_max") else out[k] + v
        else:
            out[k] = v
    return out


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _default_registry
