"""Span-based tracing with Chrome-trace/Perfetto export.

``Tracer`` records three kinds of events, all thread-safe:

- ``span(name, **attrs)`` — a context manager timing a block of code;
  nesting is tracked per-thread (each span knows its parent and depth).
- ``add_complete(name, start, end, **attrs)`` — an externally-timed span
  (e.g. a task whose timestamps were measured on a remote worker).
- ``instant(name, **attrs)`` — a zero-duration marker.

Events are kept in memory (bounded by ``max_events``) and optionally
streamed to a JSONL sink as they finish — one JSON object per line, raw
epoch-seconds timestamps, so external tools can tail a live compute.

``export_chrome(path)`` writes the standard Chrome trace-event JSON
(``{"traceEvents": [...]}``, phase ``X`` complete events with microsecond
timestamps) which loads directly in Perfetto (https://ui.perfetto.dev) or
chrome://tracing. Lane assignment: every distinct ``lane`` label (defaults
to the recording thread) becomes a ``tid`` with a ``thread_name`` metadata
record, so ops/workers get their own rows in the UI.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional


class Tracer:
    def __init__(
        self,
        jsonl_path: Optional[str] = None,
        max_events: int = 1_000_000,
        clock=time.time,
    ):
        self.events: list[dict] = []
        self.max_events = max_events
        self.dropped = 0
        self._clock = clock
        self._lock = threading.Lock()
        #: spans entered but not yet exited, across all threads — so an
        #: export can close them (error=True) instead of dropping them
        self._open: dict[int, "_Span"] = {}
        self._tls = threading.local()
        self._jsonl_path = jsonl_path
        self._jsonl_file = None
        #: separate lock so slow sink IO never serializes event recording
        self._jsonl_lock = threading.Lock()

    # -- recording -----------------------------------------------------

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _record(self, event: dict) -> None:
        with self._lock:
            if len(self.events) >= self.max_events:
                self.dropped += 1
                return
            self.events.append(event)
        if self._jsonl_path is not None:
            # serialize + write under the sink's own lock, NOT the recording
            # lock: a slow filesystem must not throttle other threads' spans
            line = json.dumps(event, default=str) + "\n"
            with self._jsonl_lock:
                try:
                    if self._jsonl_file is None:
                        d = os.path.dirname(self._jsonl_path)
                        if d:
                            os.makedirs(d, exist_ok=True)
                        self._jsonl_file = open(self._jsonl_path, "a")
                    self._jsonl_file.write(line)
                    self._jsonl_file.flush()
                except (OSError, ValueError):
                    pass  # a broken sink must never fail the compute

    def span(self, name: str, lane: Optional[str] = None, **attrs):
        """Context manager recording a complete span around a block."""
        return _Span(self, name, lane, attrs)

    def add_complete(
        self,
        name: str,
        start: float,
        end: float,
        lane: Optional[str] = None,
        cat: str = "span",
        **attrs,
    ) -> None:
        """Record an externally-timed span (epoch-second timestamps)."""
        self._record(
            {
                "name": name,
                "cat": cat,
                "ph": "X",
                "ts": start,
                "dur": max(0.0, end - start),
                "lane": lane or f"thread-{threading.get_ident()}",
                "args": attrs,
            }
        )

    def instant(self, name: str, lane: Optional[str] = None, ts: Optional[float] = None, **attrs) -> None:
        self._record(
            {
                "name": name,
                "cat": "instant",
                "ph": "i",
                "ts": ts if ts is not None else self._clock(),
                "dur": 0.0,
                "lane": lane or f"thread-{threading.get_ident()}",
                "args": attrs,
            }
        )

    def add_counter(
        self, name: str, ts: float, value, lane: Optional[str] = None
    ) -> None:
        """A counter sample (Perfetto renders these as a value track)."""
        self._record(
            {
                "name": name,
                "cat": "counter",
                "ph": "C",
                "ts": ts,
                "dur": 0.0,
                "lane": lane or name,
                "args": {"value": value},
            }
        )

    # -- export --------------------------------------------------------

    def chrome_events(self) -> list[dict]:
        """Chrome trace-event list: lanes mapped to tids + name metadata.

        Spans still open at export time (entered but never exited — a task
        that raised through a frame holding one, or an export taken
        mid-compute) are closed AT the export instant and emitted with
        ``error=True`` + ``unterminated=True`` instead of being silently
        dropped: a crash is exactly when the trace matters most.
        """
        with self._lock:
            events = list(self.events)
            open_spans = list(self._open.values())
        end = self._clock()
        for s in open_spans:
            attrs = dict(s.attrs)
            attrs["error"] = True
            attrs["unterminated"] = True
            events.append(
                {
                    "name": s.name,
                    "cat": "span",
                    "ph": "X",
                    "ts": s.start,
                    "dur": max(0.0, end - s.start),
                    # the OWNING thread's lane, not the exporting thread's:
                    # a crashed task's span must land on its own lane
                    "lane": s.lane or f"thread-{s.owner}",
                    "args": attrs,
                }
            )
        if not events:
            return []
        t0 = min(e["ts"] for e in events)
        lanes: dict[str, int] = {}
        out: list[dict] = []
        pid = os.getpid()
        for e in events:
            lane = e.get("lane") or "main"
            tid = lanes.get(lane)
            if tid is None:
                tid = lanes[lane] = len(lanes) + 1
                out.append(
                    {
                        "name": "thread_name",
                        "ph": "M",
                        "pid": pid,
                        "tid": tid,
                        "args": {"name": lane},
                    }
                )
            rec = {
                "name": e["name"],
                "cat": e.get("cat", "span"),
                "ph": e.get("ph", "X"),
                "ts": (e["ts"] - t0) * 1e6,  # microseconds
                "pid": pid,
                "tid": tid,
                "args": e.get("args", {}),
            }
            if rec["ph"] == "X":
                rec["dur"] = e.get("dur", 0.0) * 1e6
            elif rec["ph"] == "i":
                rec["s"] = "t"  # instant scope: thread
            out.append(rec)
        return out

    def export_chrome(self, path: str) -> str:
        """Write a Perfetto/chrome://tracing-loadable trace JSON file."""
        doc = {
            "traceEvents": self.chrome_events(),
            "displayTimeUnit": "ms",
        }
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        return path

    def clear(self) -> None:
        with self._lock:
            self.events = []
            self.dropped = 0
            # also drop spans left open before the clear: a reused tracer
            # (TracingCallback clears per compute) must not re-emit a prior
            # compute's abandoned span into every later export — its stale
            # ts would anchor t0 and shift the whole new timeline (and the
            # strong ref would pin the span forever). A span live across
            # the clear still records on exit; it just can't be synthesized
            # if abandoned.
            self._open.clear()

    def close(self) -> None:
        with self._jsonl_lock:
            if self._jsonl_file is not None:
                try:
                    self._jsonl_file.close()
                except OSError:
                    pass
                self._jsonl_file = None


class _Span:
    """The context manager returned by ``Tracer.span``."""

    __slots__ = (
        "tracer", "name", "lane", "attrs", "start", "parent", "depth",
        "owner",
    )

    def __init__(self, tracer: Tracer, name: str, lane, attrs: dict):
        self.tracer = tracer
        self.name = name
        self.lane = lane
        self.attrs = attrs

    def __enter__(self) -> "_Span":
        stack = self.tracer._stack()
        self.parent = stack[-1].name if stack else None
        self.depth = len(stack)
        stack.append(self)
        self.start = self.tracer._clock()
        self.owner = threading.get_ident()
        with self.tracer._lock:
            self.tracer._open[id(self)] = self
        return self

    def __exit__(self, exc_type, *exc) -> None:
        end = self.tracer._clock()
        self.tracer._stack().pop()
        with self.tracer._lock:
            self.tracer._open.pop(id(self), None)
        attrs = dict(self.attrs)
        if self.parent is not None:
            attrs["parent"] = self.parent
        attrs["depth"] = self.depth
        if exc_type is not None:
            # error=True is the machine-checkable flag; error_type names it
            attrs["error"] = True
            attrs["error_type"] = exc_type.__name__
        self.tracer.add_complete(
            self.name, self.start, end, lane=self.lane, **attrs
        )
