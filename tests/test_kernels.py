"""Pallas kernel tests (interpret mode on the CPU mesh; the same kernels
compile and run on real TPU — exercised by bench/manual runs)."""

import numpy as np
import pytest

from cubed_tpu.kernels import block_sum, fused_fma_mean
from cubed_tpu.kernels.reductions import region_sum


@pytest.fixture
def jnp():
    import jax.numpy as jnp

    return jnp


def test_block_sum(jnp):
    rng = np.random.default_rng(0)
    an = rng.random((300, 260), dtype=np.float32)
    s = block_sum(jnp.asarray(an), interpret=True)
    np.testing.assert_allclose(float(s), an.sum(), rtol=1e-4)


def test_block_sum_aligned(jnp):
    an = np.ones((512, 512), dtype=np.float32)
    s = block_sum(jnp.asarray(an), interpret=True)
    assert float(s) == 512 * 512


def test_fused_fma_mean(jnp):
    rng = np.random.default_rng(1)
    arrs = [rng.random((130, 70), dtype=np.float32) for _ in range(4)]
    a, x, b, y = arrs
    m = fused_fma_mean(*[jnp.asarray(v) for v in arrs], interpret=True)
    np.testing.assert_allclose(float(m), (a * x + b * y).mean(), rtol=1e-4)


def test_fused_fma_mean_3d(jnp):
    rng = np.random.default_rng(2)
    arrs = [rng.random((9, 10, 20), dtype=np.float32) for _ in range(4)]
    a, x, b, y = arrs
    m = fused_fma_mean(*[jnp.asarray(v) for v in arrs], interpret=True)
    np.testing.assert_allclose(float(m), (a * x + b * y).mean(), rtol=1e-4)


@pytest.mark.parametrize(
    "shape,axis",
    [
        ((40, 30), (0,)),
        ((40, 30), (1,)),
        ((40, 30), (0, 1)),
        ((6, 20, 15), (1,)),
        ((6, 20, 15), (0, 2)),
        ((7,), (0,)),
    ],
)
def test_region_sum(jnp, shape, axis):
    rng = np.random.default_rng(3)
    an = rng.random(shape, dtype=np.float32)
    out = region_sum(jnp.asarray(an), axis=axis, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out), an.sum(axis=axis, keepdims=True), rtol=1e-4
    )


def test_region_sum_no_keepdims(jnp):
    rng = np.random.default_rng(4)
    an = rng.random((12, 9), dtype=np.float32)
    out = region_sum(jnp.asarray(an), axis=(0,), keepdims=False, interpret=True)
    np.testing.assert_allclose(np.asarray(out), an.sum(axis=0), rtol=1e-4)


# ---------------------------------------------------------------------------
# executor wiring: the Pallas region combine must actually run in a plan
# ---------------------------------------------------------------------------


def test_executor_routes_sum_combine_through_pallas(tmp_path):
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB", reserved_mem=0)
    rng = np.random.default_rng(5)
    an = rng.random((64, 8), dtype=np.float32)
    a = ct.from_array(an, chunks=(4, 8), spec=spec)  # 16 blocks -> combine rounds
    ex = JaxExecutor(use_pallas=True)
    out = xp.sum(a, axis=0).compute(executor=ex)
    np.testing.assert_allclose(np.asarray(out), an.sum(axis=0), rtol=1e-4)
    assert ex.stats["pallas_region_hits"] >= 1
    assert ex.stats["pallas_errors"] == 0
    assert ex.stats["eager_fallbacks"] == 0


def test_executor_pallas_disabled_keeps_xla_combine(tmp_path):
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB", reserved_mem=0)
    an = np.arange(64 * 8, dtype=np.float32).reshape(64, 8)
    a = ct.from_array(an, chunks=(4, 8), spec=spec)
    ex = JaxExecutor(use_pallas=False)
    out = xp.sum(a, axis=0).compute(executor=ex)
    np.testing.assert_allclose(np.asarray(out), an.sum(axis=0), rtol=1e-4)
    assert ex.stats["pallas_region_hits"] == 0


def test_executor_pallas_skips_f64(tmp_path):
    # f64 must keep the exact XLA combine (the kernels accumulate in f32)
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB", reserved_mem=0)
    an = np.arange(64 * 8, dtype=np.float64).reshape(64, 8)
    a = ct.from_array(an, chunks=(4, 8), spec=spec)
    ex = JaxExecutor(use_pallas=True)
    out = xp.sum(a, axis=0).compute(executor=ex)
    np.testing.assert_allclose(np.asarray(out), an.sum(axis=0))
    assert ex.stats["pallas_region_hits"] == 0
