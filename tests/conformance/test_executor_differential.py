"""Differential executor fuzzing: hypothesis-generated random plans computed
on the fused JaxExecutor must match the PythonDagExecutor oracle exactly.

This is the conformance suite's executor analogue: instead of checking each
function against numpy, it checks that the TPU execution machinery (segment
tracing, batched vmap dispatch, whole-array/whole-select/whole-concat fast
paths, rechunk aliasing, struct-cache reuse) is an invisible optimization
across arbitrarily composed plans.
"""

from __future__ import annotations

import numpy as np
import pytest
from hypothesis import given, settings
from hypothesis import strategies as st
from hypothesis.extra import numpy as hnp

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.runtime.executors.jax import JaxExecutor
from cubed_tpu.runtime.executors.python import PythonDagExecutor

from .harness import arrays


@pytest.fixture(autouse=True)
def _force_sort_network(monkeypatch):
    # keep the bitonic network in the differential fuzz (small shapes would
    # otherwise take the single-kernel path under the memory heuristic)
    monkeypatch.setenv("CUBED_TPU_SORT_NETWORK", "force")


def _unary_step(draw, a):
    op = draw(st.sampled_from(["negative", "abs", "multiply2", "add1", "transpose",
                               "flip", "slice", "rechunk", "reshape_flat",
                               "cumsum", "diff", "tile"]))
    if op == "cumsum":
        return xp.cumulative_sum(a, axis=draw(st.integers(0, a.ndim - 1)))
    if op == "diff":
        ax = draw(st.integers(0, a.ndim - 1))
        if a.shape[ax] < 2:
            return a
        return xp.diff(a, axis=ax)
    if op == "tile":
        reps = tuple(draw(st.integers(1, 2)) for _ in range(a.ndim))
        return xp.tile(a, reps)
    if op == "negative":
        return xp.negative(a)
    if op == "abs":
        return xp.abs(a)
    if op == "multiply2":
        return xp.multiply(a, draw(st.sampled_from([2.0, -0.5, 3.0])))
    if op == "add1":
        return xp.add(a, draw(st.sampled_from([1.0, -2.0])))
    if op == "transpose":
        return xp.permute_dims(a, tuple(reversed(range(a.ndim)))) if a.ndim >= 2 else a
    if op == "flip":
        return xp.flip(a, axis=draw(st.integers(0, a.ndim - 1)))
    if op == "slice":
        if a.shape[0] < 2:
            return a
        start = draw(st.integers(0, a.shape[0] - 2))
        return a[start:]
    if op == "rechunk":
        new = tuple(max(1, s // draw(st.sampled_from([1, 2, 3]))) for s in a.shape)
        return a.rechunk(new)
    if op == "reshape_flat":
        n = 1
        for s in a.shape:
            n *= s
        return xp.reshape(a, (n,))
    return a


def _binary_step(draw, a, b):
    op = draw(st.sampled_from(["add", "multiply", "subtract", "concat", "stack"]))
    if a.shape != b.shape:
        return xp.add(a, xp.zeros(a.shape, chunks=a.chunksize, spec=a.spec))
    if op == "concat":
        return xp.concat([a, b], axis=draw(st.integers(0, a.ndim - 1)))
    if op == "stack":
        return xp.stack([a, b], axis=0)
    return getattr(xp, op)(a, b)


def _reduce_step(draw, a):
    op = draw(st.sampled_from(["sum", "mean", "max", "none"]))
    if op == "none":
        return a
    axis = draw(st.one_of(st.none(), st.integers(0, a.ndim - 1)))
    return getattr(xp, op)(a, axis=axis)


@given(data=st.data())
def test_random_plans_match_oracle(data, spec):
    an = data.draw(
        arrays(dtypes=(np.float64,), shape=data.draw(
            st.sampled_from([(6, 8), (9, 4), (5, 5, 4), (12,)])
        ))
    )
    bn = data.draw(arrays(dtypes=(np.float64,), shape=an.shape))
    chunks = tuple(max(1, (s + 1) // 2) for s in an.shape)

    def build():
        a = ct.from_array(an, chunks=chunks, spec=spec)
        b = ct.from_array(bn, chunks=chunks, spec=spec)
        x = _unary_step(data.draw, a)
        x = _binary_step(data.draw, x, _unary_step(data.draw, b)) if x.shape == b.shape else x
        x = _unary_step(data.draw, x)
        return _reduce_step(data.draw, x)

    expr = build()  # ONE plan; draws must not repeat across executors
    oracle = np.asarray(expr.compute(executor=PythonDagExecutor()))
    fused = np.asarray(expr.compute(executor=JaxExecutor()))
    np.testing.assert_allclose(fused, oracle, rtol=1e-12, atol=1e-12)


@settings(max_examples=12, deadline=None)
@given(data=st.data())
def test_random_linalg_and_stats_match_oracle(data, spec):
    """matmul/tensordot contractions, var/std, nan functions, int-array
    indexing, and sort — the op families the main fuzzer doesn't reach."""
    m, k, n = (data.draw(st.integers(2, 6)) for _ in range(3))
    an = data.draw(arrays(dtypes=(np.float64,), shape=(m, k)))
    bn = data.draw(arrays(dtypes=(np.float64,), shape=(k, n)))
    a = ct.from_array(an, chunks=(max(1, m // 2), max(1, k // 2)), spec=spec)
    b = ct.from_array(bn, chunks=(max(1, k // 2), max(1, n // 2)), spec=spec)

    kind = data.draw(st.sampled_from(
        ["matmul", "tensordot", "var", "std", "nanmean", "index", "sort",
         "argsort", "take_along_axis", "count_nonzero", "gufunc_multi",
         "qr_recon", "svdvals", "fft", "ifft_roundtrip", "einsum"]
    ))
    if kind == "matmul":
        expr = xp.matmul(a, b)
    elif kind == "tensordot":
        expr = xp.tensordot(a, b, axes=1)
    elif kind == "var":
        expr = xp.var(a, axis=data.draw(st.one_of(st.none(), st.integers(0, 1))))
    elif kind == "std":
        expr = xp.std(a, axis=data.draw(st.one_of(st.none(), st.integers(0, 1))))
    elif kind == "nanmean":
        expr = ct.nanmean(a, axis=data.draw(st.one_of(st.none(), st.integers(0, 1))))
    elif kind == "index":
        rows = data.draw(
            st.lists(st.integers(0, m - 1), min_size=1, max_size=m, unique=True)
        )
        expr = a[sorted(rows), :]
    elif kind == "argsort":
        expr = xp.argsort(
            a, axis=data.draw(st.integers(0, 1)),
            descending=data.draw(st.booleans()),
        )
    elif kind == "take_along_axis":
        ax = data.draw(st.integers(0, 1))
        nax = a.shape[ax]
        idx_np = data.draw(
            hnp.arrays(
                np.int64,
                tuple(nax if d == ax else a.shape[d] for d in range(2)),
                elements=st.integers(-nax, nax - 1),
            )
        )
        idx = ct.from_array(
            idx_np, chunks=(max(1, m // 2), max(1, k // 2)), spec=spec
        )
        expr = xp.take_along_axis(a, idx, axis=ax)
    elif kind == "count_nonzero":
        expr = xp.count_nonzero(
            xp.greater(a, 0.5),
            axis=data.draw(st.one_of(st.none(), st.integers(0, 1))),
        )
    elif kind == "gufunc_multi":
        ac = a.rechunk((max(1, m // 2), k))  # core dim single-chunk
        mo = ct.apply_gufunc(
            lambda v: (v.mean(axis=-1), v.max(axis=-1)),
            "(i)->(),()", ac, output_dtypes=[np.float64, np.float64],
        )
        expr = mo[data.draw(st.integers(0, 1))]
    elif kind == "qr_recon":
        # decomposition factors are sign-ambiguous across backends; the
        # reconstruction Q @ R is the invariant both executors must agree on
        q, r = xp.linalg.qr(a)
        expr = xp.matmul(q, r)
    elif kind == "svdvals":
        expr = xp.linalg.svdvals(a)  # singular values are unique
    elif kind == "fft":
        expr = xp.abs(xp.fft.fft(a, axis=data.draw(st.integers(0, 1))))
    elif kind == "ifft_roundtrip":
        ax = data.draw(st.integers(0, 1))
        expr = xp.real(xp.fft.ifft(xp.fft.fft(a, axis=ax), axis=ax))
    elif kind == "einsum":
        spec_s = data.draw(st.sampled_from(
            ["ij,jk->ik", "ij,jk->", "ij,ij->i", "ij,ij->j"]
        ))
        second = b if "jk" in spec_s else a  # shapes must align per labels
        expr = xp.einsum(spec_s, a, second)
    else:
        expr = xp.sort(a, axis=data.draw(st.integers(0, 1)))

    oracle = np.asarray(expr.compute(executor=PythonDagExecutor()))
    fused = np.asarray(expr.compute(executor=JaxExecutor()))
    if kind in ("qr_recon", "svdvals", "fft", "ifft_roundtrip"):
        # numpy (LAPACK/pocketfft) vs XLA kernels agree to roundoff, not ULP
        scale = max(1.0, float(np.max(np.abs(oracle))) if oracle.size else 1.0)
        np.testing.assert_allclose(fused, oracle, atol=1e-8 * scale)
    else:
        np.testing.assert_allclose(fused, oracle, rtol=1e-10, atol=1e-12)


def _mesh_or_none():
    import jax

    try:
        devs = jax.devices("cpu")
    except RuntimeError:
        return None
    if len(devs) < 8:
        return None
    from cubed_tpu.parallel.mesh import make_mesh

    return make_mesh(shape=(8,), axis_names=("data",), devices=devs[:8])


@settings(max_examples=8, deadline=None)
@given(data=st.data())
def test_random_plans_match_oracle_sharded(data, spec):
    """Same fuzz, mesh-sharded executor: sharding must also be invisible."""
    import pytest

    mesh = _mesh_or_none()
    if mesh is None:
        pytest.skip("needs 8 virtual CPU devices")
    an = data.draw(arrays(dtypes=(np.float64,), shape=(8, 12)))
    bn = data.draw(arrays(dtypes=(np.float64,), shape=(8, 12)))
    chunks = (2, 6)

    a = ct.from_array(an, chunks=chunks, spec=spec)
    b = ct.from_array(bn, chunks=chunks, spec=spec)
    x = _binary_step(data.draw, _unary_step(data.draw, a), b)
    expr = _reduce_step(data.draw, _unary_step(data.draw, x))

    oracle = np.asarray(expr.compute(executor=PythonDagExecutor()))
    sharded = np.asarray(expr.compute(executor=JaxExecutor(mesh=mesh)))
    np.testing.assert_allclose(sharded, oracle, rtol=1e-12, atol=1e-12)


# -- distributed executor: the fabric must also be invisible ---------------


@pytest.fixture(scope="module")
def fleet():
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

    ex = DistributedDagExecutor(n_local_workers=2, worker_threads=2)
    try:
        ex._ensure_fleet()
        yield ex
    finally:
        ex.close()


@settings(max_examples=6, deadline=None)
@given(data=st.data())
def test_random_plans_match_oracle_distributed(data, spec, fleet):
    """Same fuzz over the TCP coordinator/worker fabric: serialization,
    blob caching, and completion-ordered remote execution must not change a
    single bit of any plan's result."""
    an = data.draw(arrays(dtypes=(np.float64,), shape=(6, 8)))
    bn = data.draw(arrays(dtypes=(np.float64,), shape=(6, 8)))
    chunks = (3, 4)

    a = ct.from_array(an, chunks=chunks, spec=spec)
    b = ct.from_array(bn, chunks=chunks, spec=spec)
    x = _binary_step(data.draw, _unary_step(data.draw, a), b)
    expr = _reduce_step(data.draw, _unary_step(data.draw, x))

    oracle = np.asarray(expr.compute(executor=PythonDagExecutor()))
    remote = np.asarray(expr.compute(executor=fleet))
    np.testing.assert_allclose(remote, oracle, rtol=1e-12, atol=1e-12)


# -- f32 ingestion: the documented error bound, fuzz-validated --------------


@settings(max_examples=15, deadline=None)
@given(data=st.data())
def test_f32_ingestion_within_documented_bounds(data, spec):
    """``compute_dtype="float32"`` promises f32-eps-scale divergence from
    the f64 result (executor docstring): fuzz random plans and hold every
    one to a tolerance derived from the f32 bound — declared dtype must
    stay f64 throughout."""
    # inputs bounded to 1e3 so a drawn multiply yields terms <= ~1e6; the
    # atol anchor below still uses scale^2 because the plan may multiply
    # before a cancelling sum (rounding error scales with the TERMS, not
    # the result)
    bounded = st.floats(
        min_value=-1e3, max_value=1e3, allow_nan=False,
        allow_infinity=False, allow_subnormal=False, width=64,
    )
    an = data.draw(arrays(dtypes=(np.float64,), elements=bounded, shape=data.draw(
        st.sampled_from([(6, 8), (5, 5, 4), (12,)])
    )))
    bn = data.draw(arrays(dtypes=(np.float64,), elements=bounded, shape=an.shape))
    chunks = tuple(max(1, (s + 1) // 2) for s in an.shape)

    a = ct.from_array(an, chunks=chunks, spec=spec)
    b = ct.from_array(bn, chunks=chunks, spec=spec)
    x = _binary_step(data.draw, _unary_step(data.draw, a), b)
    expr = _reduce_step(data.draw, _unary_step(data.draw, x))

    f64 = np.asarray(expr.compute(executor=JaxExecutor()))
    f32 = np.asarray(expr.compute(executor=JaxExecutor(compute_dtype="float32")))
    assert f32.dtype == f64.dtype == np.float64
    # f32-eps bound anchored to the largest possible intermediate term
    # (scale^2 from a multiply), not the result, which cancellation can
    # shrink arbitrarily
    scale = max(
        float(np.max(np.abs(an), initial=0.0)),
        float(np.max(np.abs(bn), initial=0.0)),
        1.0,
    )
    k = max(an.size, 1)
    atol = 16.0 * k * scale * scale * float(np.finfo(np.float32).eps)
    np.testing.assert_allclose(f32, f64, rtol=1e-4, atol=atol, equal_nan=True)
