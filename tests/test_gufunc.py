"""apply_gufunc tests. Reference parity: cubed/tests/test_gufunc.py."""

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.backend_array_api import nxp


def test_elementwise_gufunc(spec):
    an = np.arange(12.0).reshape(3, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    r = ct.apply_gufunc(nxp.negative, "()->()", a, output_dtypes=a.dtype)
    np.testing.assert_allclose(r.compute(), -an)


def test_core_dim_reduction(spec):
    an = np.arange(24.0).reshape(4, 6)
    # core dim must be single-chunk
    a = ct.from_array(an, chunks=(2, 6), spec=spec)

    def last_mean(x):
        return nxp.mean(x, axis=-1)

    r = ct.apply_gufunc(last_mean, "(i)->()", a, output_dtypes=a.dtype)
    np.testing.assert_allclose(r.compute(), an.mean(axis=-1))


def test_matvec_gufunc(spec):
    rng = np.random.default_rng(0)
    mats = rng.random((3, 4, 5))
    vecs = rng.random((3, 5))
    a = ct.from_array(mats, chunks=(1, 4, 5), spec=spec)
    b = ct.from_array(vecs, chunks=(1, 5), spec=spec)

    def matvec(m, v):
        return nxp.einsum("...ij,...j->...i", m, v)

    r = ct.apply_gufunc(matvec, "(i,j),(j)->(i)", a, b, output_dtypes=mats.dtype)
    np.testing.assert_allclose(r.compute(), np.einsum("bij,bj->bi", mats, vecs),
                               rtol=1e-12)


def test_chunked_core_dim_raises(spec):
    an = np.arange(24.0).reshape(4, 6)
    a = ct.from_array(an, chunks=(2, 3), spec=spec)  # core dim chunked
    with pytest.raises(ValueError, match="core dimension"):
        ct.apply_gufunc(lambda x: nxp.sum(x, axis=-1), "(i)->()", a,
                        output_dtypes=a.dtype)


def test_vectorize(spec):
    an = np.arange(6.0)
    a = ct.from_array(an, chunks=3, spec=spec)

    def add_one_scalar(x):
        return x + 1

    r = ct.apply_gufunc(
        add_one_scalar, "()->()", a, output_dtypes=a.dtype, vectorize=True
    )
    np.testing.assert_allclose(r.compute(), an + 1)


def test_bad_signature(spec):
    a = ct.from_array(np.zeros(3), chunks=3, spec=spec)
    with pytest.raises(ValueError, match="valid gufunc signature"):
        ct.apply_gufunc(lambda x: x, "bad sig", a, output_dtypes=np.float64)
