"""Chunk-integrity unit tests: checksum manifests, quarantine, read-time
verification, corrupt-metadata tolerance, and the corruption fault sites.

The end-to-end story (RECOMPUTE classification, chunk-granular resume,
corruption chaos across executors) lives in tests/runtime/test_integrity.py.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from cubed_tpu.observability.accounting import task_scope
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.faults import FaultConfig, FaultInjector
from cubed_tpu.storage import integrity
from cubed_tpu.storage.integrity import ChunkIntegrityError
from cubed_tpu.storage.store import open_zarr_array


def _make_array(path, shape=(4, 4), chunks=(2, 2)):
    arr = open_zarr_array(
        str(path), mode="a", shape=shape, dtype=np.float64, chunks=chunks
    )
    arr[:] = np.arange(np.prod(shape), dtype=np.float64).reshape(shape)
    return arr


def _flip_byte(path, offset=0):
    with open(path, "r+b") as f:
        data = bytearray(f.read())
        data[offset] ^= 0xFF
        f.seek(0)
        f.write(data)


# -- manifest recording ---------------------------------------------------


def test_chunk_writes_record_manifest(tmp_path):
    store = tmp_path / "a"
    arr = _make_array(store)
    shards = [n for n in os.listdir(store) if n.startswith(".manifest-")]
    assert len(shards) == 1
    # local shards are append-only JSONL: one line per chunk write
    lines = [
        json.loads(line)
        for line in (store / shards[0]).read_text().splitlines()
        if line.strip()
    ]
    assert {line["k"] for line in lines} == {"0.0", "0.1", "1.0", "1.1"}
    entries, had = integrity.load_manifest(arr._io)
    assert had and set(entries) == {"0.0", "0.1", "1.0", "1.1"}
    for key, ent in entries.items():
        data = (store / key).read_bytes()
        assert ent["c"] == integrity.checksum(data)
        assert ent["n"] == len(data)
    # the sidecar preserves the Zarr v2 layout: chunk accounting unchanged
    assert arr.nchunks_initialized == 4


def test_integrity_off_records_nothing(tmp_path):
    with integrity.scoped("off"):
        _make_array(tmp_path / "a")
    assert not [
        n for n in os.listdir(tmp_path / "a") if n.startswith(".manifest-")
    ]


def test_manifest_merges_shards_last_write_wins(tmp_path):
    store = tmp_path / "a"
    _make_array(store)
    # a second writer's shard (e.g. a backup task in another process):
    # fresher timestamp wins for the shared key, unique keys merge
    io = open_zarr_array(str(store), mode="r")._io
    entries, _ = integrity.load_manifest(io)
    newer = dict(entries["0.0"], c=12345, t=entries["0.0"]["t"] + 100)
    (store / ".manifest-99999-abc.json").write_text(
        json.dumps({"writer": "99999-abc", "entries": {"0.0": newer}})
    )
    merged, had = integrity.load_manifest(io)
    assert had
    assert merged["0.0"]["c"] == 12345
    assert merged["0.1"] == entries["0.1"]


def test_torn_trailing_manifest_line_tolerated(tmp_path):
    """A crash mid-append can tear the last JSONL line; earlier lines stay
    usable — only the torn line's chunk loses its entry."""
    store = tmp_path / "a"
    arr = _make_array(store)
    shard = next(n for n in os.listdir(store) if n.startswith(".manifest-"))
    raw = (store / shard).read_bytes()
    (store / shard).write_bytes(raw[: len(raw) - 9])  # tear the final line
    entries, had = integrity.load_manifest(arr._io)
    assert had and len(entries) == 3
    valid, corrupt, verified = arr.verify_chunks(quarantine=False)
    assert verified and len(valid) == 3 and len(corrupt) == 1


def test_corrupt_manifest_shard_tolerated(tmp_path):
    """An undecodable shard is skipped: its chunks lose their entries and
    verify as untrustworthy — never as valid, and never a crash."""
    store = tmp_path / "a"
    arr = _make_array(store)
    shard = next(n for n in os.listdir(store) if n.startswith(".manifest-"))
    (store / shard).write_bytes(b"{not json!!")
    entries, had = integrity.load_manifest(arr._io)
    assert had and entries == {}
    valid, corrupt, verified = open_zarr_array(str(store), mode="r").verify_chunks(
        quarantine=False
    )
    assert verified and not valid
    assert sorted(corrupt) == ["0.0", "0.1", "1.0", "1.1"]
    # present-but-unmanifested chunks are NOT quarantined (they may simply
    # predate the manifest); re-running their producer overwrites in place
    assert not [n for n in os.listdir(store) if "quarantine" in n]


# -- verify_chunks --------------------------------------------------------


def test_verify_chunks_detects_bitflip_and_quarantines(
    tmp_path, invariant_audit
):
    from cubed_tpu.runtime.audit import InvariantAuditor

    store = tmp_path / "a"
    _make_array(store)
    _flip_byte(store / "1.0", offset=5)
    # pre-quarantine, the manifest/store CRC invariant is genuinely broken
    # — the post-hoc auditor sees the same corruption the verify scan will
    dirty = InvariantAuditor(work_dir=str(tmp_path)).audit()
    assert [v.invariant for v in dirty.violations] == ["manifest_store_crc"]
    before = get_registry().snapshot()
    arr = open_zarr_array(str(store), mode="r")
    valid, corrupt, verified = arr.verify_chunks()
    assert verified
    assert corrupt == ["1.0"]
    assert valid == {"0.0", "0.1", "1.1"}
    quarantined = [n for n in os.listdir(store) if n.startswith("1.0.quarantine.")]
    assert len(quarantined) == 1
    assert arr.nchunks_initialized == 3  # quarantine left the chunk namespace
    delta = get_registry().snapshot_delta(before)
    assert delta.get("chunks_corrupt_detected") == 1
    assert delta.get("chunks_quarantined") == 1
    assert delta.get("chunks_verified", 0) >= 4
    # quarantine restores the invariant: the marker legalises the absence
    invariant_audit(work_dir=str(tmp_path), metrics=delta)


def test_verify_chunks_detects_truncation(tmp_path):
    store = tmp_path / "a"
    _make_array(store)
    data = (store / "0.1").read_bytes()
    (store / "0.1").write_bytes(data[: len(data) // 2])
    _, corrupt, _ = open_zarr_array(str(store), mode="r").verify_chunks()
    assert corrupt == ["0.1"]


def test_verify_chunks_without_manifest_falls_back_to_existence(tmp_path):
    with integrity.scoped("off"):
        _make_array(tmp_path / "a")
    arr = open_zarr_array(str(tmp_path / "a"), mode="r")
    valid, corrupt, verified = arr.verify_chunks()
    assert not verified  # legacy store: existence-only accounting
    assert valid == {"0.0", "0.1", "1.0", "1.1"} and not corrupt


# -- read-time verification ----------------------------------------------


def test_task_scope_read_verifies_and_quarantines(tmp_path):
    store = tmp_path / "a"
    expected = np.arange(16.0).reshape(4, 4)
    _make_array(store)
    _flip_byte(store / "0.0")
    arr = open_zarr_array(str(store), mode="r")
    with integrity.scoped("verify"):
        with task_scope():
            with pytest.raises(ChunkIntegrityError) as ei:
                arr[0:2, 0:2]
    assert ei.value.kind == "checksum"
    assert ei.value.chunk_key == "0.0"
    assert ei.value.store == str(store)
    assert [n for n in os.listdir(store) if n.startswith("0.0.quarantine.")]
    # clean chunks still read fine under verification
    with integrity.scoped("verify"):
        with task_scope():
            np.testing.assert_array_equal(arr[2:4, 2:4], expected[2:4, 2:4])


def test_quarantined_chunk_reads_as_missing_not_fill_values(tmp_path):
    """After quarantine the manifest entry survives, so a blind re-read
    raises (kind="missing") instead of silently serving fill values."""
    store = tmp_path / "a"
    _make_array(store)
    _flip_byte(store / "0.0")
    with integrity.scoped("verify"):
        with task_scope():
            arr = open_zarr_array(str(store), mode="r")
            with pytest.raises(ChunkIntegrityError):
                arr[0:2, 0:2]
            arr2 = open_zarr_array(str(store), mode="r")
            with pytest.raises(ChunkIntegrityError) as ei:
                arr2[0:2, 0:2]
    assert ei.value.kind == "missing"


def test_write_mode_does_not_verify_reads(tmp_path):
    """The default ``write`` mode records checksums but never verifies
    reads — corruption is caught by resume scans, not the hot path."""
    store = tmp_path / "a"
    _make_array(store)
    _flip_byte(store / "0.0")
    arr = open_zarr_array(str(store), mode="r")
    with task_scope():
        arr[0:2, 0:2]  # no error: mode is "write"


def test_client_side_reads_never_verify(tmp_path):
    """Outside a task scope even ``verify`` mode reads unchecked (the same
    boundary fault injection uses)."""
    store = tmp_path / "a"
    _make_array(store)
    _flip_byte(store / "0.0")
    with integrity.scoped("verify"):
        open_zarr_array(str(store), mode="r")[0:2, 0:2]


def test_chunk_integrity_error_pickles():
    import pickle

    err = ChunkIntegrityError(
        "boom", store="/s", chunk_key="1.2", kind="checksum",
        expected=(1, 2), actual=(3, 4),
    )
    back = pickle.loads(pickle.dumps(err))
    assert back.store == "/s" and back.chunk_key == "1.2"
    assert back.kind == "checksum" and back.wire_payload == err.wire_payload


# -- integrity mode knob --------------------------------------------------


def test_env_var_overrides_mode(monkeypatch):
    monkeypatch.setenv(integrity.INTEGRITY_ENV_VAR, "verify")
    assert integrity.current_mode() == "verify"
    monkeypatch.setenv(integrity.INTEGRITY_ENV_VAR, "bogus")
    with pytest.raises(ValueError, match="integrity mode"):
        integrity.current_mode()
    monkeypatch.delenv(integrity.INTEGRITY_ENV_VAR)
    assert integrity.current_mode() == "write"
    with integrity.scoped("off"):
        assert integrity.current_mode() == "off"
    assert integrity.current_mode() == "write"


def test_env_override_wins_over_scoped_spec_mode(monkeypatch):
    """The env var is the operator's override: a Spec-level mode armed via
    scoped(export_env=True) must neither shadow nor clobber it."""
    monkeypatch.setenv(integrity.INTEGRITY_ENV_VAR, "verify")
    with integrity.scoped("off", export_env=True):
        assert integrity.current_mode() == "verify"  # env wins
        assert os.environ[integrity.INTEGRITY_ENV_VAR] == "verify"  # unclobbered
    assert integrity.current_mode() == "verify"


def test_spec_rejects_invalid_mode(tmp_path):
    import cubed_tpu as ct

    with pytest.raises(ValueError, match="integrity mode"):
        ct.Spec(work_dir=str(tmp_path), integrity="sometimes")
    assert ct.Spec(work_dir=str(tmp_path), integrity="verify").integrity == "verify"


# -- corrupt .zarray hardening -------------------------------------------


def test_corrupt_zarray_read_raises_clear_error(tmp_path):
    store = tmp_path / "a"
    _make_array(store)
    (store / ".zarray").write_bytes(b'{"zarr_format": 2, "shape')
    with pytest.raises(ValueError, match="corrupt .zarray"):
        open_zarr_array(str(store), mode="r")


def test_corrupt_zarray_writer_mode_recreates(tmp_path):
    """A writer-mode open with full creation parameters (the create-arrays
    op) quarantines a corrupt .zarray and recreates it; chunk data and
    manifests survive."""
    store = tmp_path / "a"
    _make_array(store)
    (store / ".zarray").write_bytes(b"\x00garbage")
    arr = open_zarr_array(
        str(store), mode="a", shape=(4, 4), dtype=np.float64, chunks=(2, 2)
    )
    assert arr.shape == (4, 4)
    assert [n for n in os.listdir(store) if n.startswith(".zarray.quarantine.")]
    np.testing.assert_array_equal(arr[:], np.arange(16.0).reshape(4, 4))
    valid, corrupt, verified = arr.verify_chunks()
    assert verified and len(valid) == 4 and not corrupt


# -- fsync durability (behavioral smoke) ---------------------------------


def test_atomic_write_fsyncs_before_rename(tmp_path, monkeypatch):
    """The temp file must be fsynced before the rename makes it visible —
    asserted by interposition, since a real crash can't run under pytest."""
    from cubed_tpu.storage import store as store_mod

    events = []
    real_fsync, real_replace = os.fsync, os.replace
    monkeypatch.setattr(os, "fsync", lambda fd: (events.append("fsync"), real_fsync(fd))[1])
    monkeypatch.setattr(
        os, "replace", lambda a, b: (events.append("replace"), real_replace(a, b))[1]
    )
    io = store_mod._LocalIO(str(tmp_path))
    io.write_bytes_atomic("0", b"hello")
    assert "fsync" in events and "replace" in events
    assert events.index("fsync") < events.index("replace")
    assert (tmp_path / "0").read_bytes() == b"hello"


# -- corruption fault injection ------------------------------------------


def test_fault_injector_corruption_deterministic_bitflip_or_truncation(tmp_path):
    inj = FaultInjector(FaultConfig(seed=5, storage_corrupt_rate=1.0))
    data = bytes(range(256))
    with task_scope():
        out1 = inj.storage_corrupt_fault("arr/0.0", data)
        assert out1 is not None and out1 != data
        assert len(out1) in (len(data), len(data) // 2)  # bit-flip or truncation
        # the corruption itself is a pure function of (seed, key)
        out2 = FaultInjector(
            FaultConfig(seed=5, storage_corrupt_rate=1.0)
        ).storage_corrupt_fault("arr/0.0", data)
        assert out1 == out2
    # outside a task scope corruption never fires
    assert inj.storage_corrupt_fault("arr/0.0", data) is None


def test_injected_corruption_caught_by_verification(tmp_path):
    from cubed_tpu.runtime import faults

    store = tmp_path / "a"
    with faults.scoped({"seed": 1, "storage_corrupt_rate": 1.0}):
        with task_scope():
            arr = open_zarr_array(
                str(store), mode="a", shape=(2,), dtype=np.float64, chunks=(2,)
            )
            arr[:] = np.arange(2.0)
    valid, corrupt, verified = open_zarr_array(str(store), mode="r").verify_chunks()
    assert verified and corrupt == ["0"] and not valid
