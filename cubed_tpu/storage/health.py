"""Store-brownout tolerance: throttle classification + a per-store
health breaker that paces storage concurrency instead of burning retries.

Object stores don't fail cleanly under load — they *brown out*: requests
start answering HTTP 429/503/"SlowDown", and the correct response is to
SLOW DOWN, not to retry harder. Before this module a browned-out store
classified as generic transient RETRY: the whole fleet kept hammering it
at full concurrency, each throttle burning task retries and draining the
shared retry budget until the circuit breaker aborted a compute that
would have finished fine at half the request rate.

Two pieces:

- :func:`is_throttle_error` recognizes throttle-shaped failures (HTTP
  429/503/SlowDown/rate-exceeded text on an OSError-family exception,
  plus the seeded ``storage_throttle_rate`` chaos fault) so the
  resilience layer can classify them ``THROTTLE`` instead of ``RETRY``.

- :class:`StoreHealthBreaker` (one per store root, process-local) is the
  AIMD pacer — the same multiplicative-decrease shape the PR 4
  ``AdmissionController`` uses for memory, applied to storage
  concurrency: every throttle halves the store's in-flight IO limit
  (``open``), chunk reads/writes then queue for a slot (the wait is a
  ``throttle_wait`` span, so ``analyze()`` attributes brownout time
  honestly) and throttled ops retry IN PLACE with paced backoff —
  drawing nothing from the task-retry budget. After a throttle-free
  probe window the breaker turns ``half_open`` and successes restore the
  limit multiplicatively back to unbounded (``closed``). The peer data
  plane is unaffected: cache and peer fetches bypass the store entirely,
  so while the store is degraded the p2p path (tried first on every
  read) carries what it can.

``CUBED_TPU_STORE_BREAKER=off`` disables the breaker everywhere —
throttles then surface to the task level immediately (classified
THROTTLE, retried with backoff, drawing budget), which is exactly the
baseline the ``store_brownout`` bench and chaos tests compare against.
"""

from __future__ import annotations

import logging
import os
import re
import threading
import time
from typing import Optional

from ..observability.metrics import get_registry

logger = logging.getLogger(__name__)

#: operator kill switch for the breaker (pacing + in-place paced retries);
#: throttle CLASSIFICATION is unaffected — it is just a fact about errors
BREAKER_ENV_VAR = "CUBED_TPU_STORE_BREAKER"
_OFF_VALUES = ("0", "off", "false", "no")

#: message fragments that identify a throttle-shaped storage error (the
#: shapes real object stores emit: S3 "SlowDown"/503, GCS 429 "rateLimit",
#: Azure 503 "ServerBusy"); the bare status codes are matched
#: word-bounded via _STATUS_RE, not as substrings
THROTTLE_MARKERS = (
    "slowdown", "slow down", "too many requests",
    "throttl", "rate limit", "ratelimit", "rate exceeded", "server busy",
    "serverbusy",
)

#: 429/503 only WITH HTTP-ish context: preceded by http/status/code/error
#: or followed by throttle words — a chunk file named '503.12', a path
#: segment '/run-429/', or a 503-element shape in an IO error message
#: must never read as a throttle
_STATUS_RE = re.compile(
    r"(?:http|status|code|error)[\s:=_-]{0,3}(?:429|503)(?![0-9])"
    r"|(?<![0-9a-z])(?:429|503)[\s:,-]{1,3}"
    r"(?:slow ?down|too many|service unavailable|server (?:is )?busy)"
)

#: exception type names that are throttles by construction (local or via
#: RemoteTaskError.remote_type off the fleet wire)
THROTTLE_TYPE_NAMES = frozenset({"FaultInjectedThrottleError"})

#: remote exception families whose MESSAGE is worth sniffing for throttle
#: shapes: IO-flavored errors only — a remote ValueError mentioning
#: "(503,)" in a broadcast-shape complaint is not a brownout
_REMOTE_IO_TYPE_NAMES = frozenset({
    "OSError", "IOError", "ConnectionError", "ConnectionResetError",
    "TimeoutError", "FaultInjectedIOError", "FaultInjectedThrottleError",
    "ClientError", "HTTPError", "HttpError", "StorageError",
})

#: in-place paced retries per logical chunk IO while the breaker is on —
#: past this the throttle surfaces to the task level (classified THROTTLE)
THROTTLE_IO_RETRIES = 8

#: numeric breaker states for the ``store_breaker_state`` gauge
STATE_CLOSED, STATE_HALF_OPEN, STATE_OPEN = 0, 1, 2


def breaker_enabled() -> bool:
    return os.environ.get(
        BREAKER_ENV_VAR, ""
    ).strip().lower() not in _OFF_VALUES


def is_throttle_error(exc: BaseException) -> bool:
    """True for throttle-shaped storage failures (see module docstring).

    Checked by name as well as locally so a worker-side throttle crossing
    the fleet wire as ``RemoteTaskError`` still classifies THROTTLE.
    Message sniffing only applies to IO-flavored exceptions (locally by
    isinstance, remotely by ``remote_type``): a ValueError whose text
    happens to contain "503" must never read as a brownout."""
    rtype = getattr(exc, "remote_type", None)
    if type(exc).__name__ in THROTTLE_TYPE_NAMES or (
        rtype in THROTTLE_TYPE_NAMES
    ):
        return True
    if isinstance(
        exc,
        (FileNotFoundError, IsADirectoryError, NotADirectoryError,
         PermissionError),
    ):
        # definitely-local filesystem failures: their messages embed
        # PATHS, which is exactly where digit false-positives live
        return False
    if isinstance(exc, (OSError, ConnectionError)):
        pass  # local IO error: sniff the message
    elif rtype is not None:
        if rtype not in _REMOTE_IO_TYPE_NAMES:
            return False  # remote non-IO error: never a throttle
    else:
        return False
    text = str(exc).lower()
    if any(marker in text for marker in THROTTLE_MARKERS):
        return True
    return _STATUS_RE.search(text) is not None


class StoreHealthBreaker:
    """AIMD pacer for one store's chunk IO (see module docstring).

    ``closed`` (healthy): no limit, :meth:`acquire` is a counter bump.
    ``open``: a throttle was seen recently; the in-flight limit is active
    and halves again on further throttles (cooldown-spaced, like the
    admission controller). ``half_open``: no throttle for
    ``probe_idle_s`` — the limit still applies, but a success streak now
    doubles it back toward unbounded.
    """

    #: minimum spacing between throttle-triggered halvings, so one salvo
    #: of concurrent 429s costs one step, not a collapse to 1
    STEP_COOLDOWN_S = 0.25
    #: throttle-free seconds before recovery probing starts
    PROBE_IDLE_S = 1.0
    #: a blocked acquire waits at most this long for a slot before
    #: proceeding anyway — the breaker degrades throughput, it must never
    #: deadlock a compute against a limit nothing will ever release
    MAX_SLOT_WAIT_S = 30.0

    def __init__(self, store: str):
        self.store = str(store)
        self._cond = threading.Condition()
        self._limit: Optional[int] = None
        #: IOs currently HOLDING a slot (waiters are deliberately not
        #: counted: a waiter inflating the count would keep the
        #: wait-condition true forever once there are more waiters than
        #: slots — the halving base and the gate both want holders only)
        self._active = 0
        self._max_seen = 1
        self._streak = 0
        self._last_throttle = 0.0
        self._last_step = 0.0
        #: consecutive in-place throttle retries observed (pacing input)
        self._consecutive = 0

    # -- state ---------------------------------------------------------

    @property
    def state(self) -> str:
        with self._cond:
            if self._limit is None:
                return "closed"
            if time.monotonic() - self._last_throttle >= self.PROBE_IDLE_S:
                return "half_open"
            return "open"

    def _state_int(self) -> int:
        return {
            "closed": STATE_CLOSED,
            "half_open": STATE_HALF_OPEN,
            "open": STATE_OPEN,
        }[self.state]

    def _publish_state(self) -> None:
        # snapshot under the registry lock: store_breaker() inserts while
        # other stores' IO threads publish, and iterating the live dict
        # would raise mid-exception-handler
        with _breakers_lock:
            breakers = list(_breakers.values())
        get_registry().gauge("store_breaker_state").set(
            max(
                (b._state_int() for b in breakers),
                default=STATE_CLOSED,
            )
        )

    # -- slots ---------------------------------------------------------

    def acquire(self, poll=None) -> float:
        """Take an IO slot; returns the seconds spent waiting for one
        (0.0 on the healthy fast path). Callers record the wait as a
        ``throttle_wait`` span so brownout time is attributed. ``poll``
        (if given) runs between wait quanta and may raise — how a
        cancelled/deadlined compute escapes a long slot wait instead of
        sitting out the full ``MAX_SLOT_WAIT_S``; a poll-raise leaves the
        slot untaken, so the caller's release never runs for it."""
        deadline = None
        waited = 0.0
        with self._cond:
            while self._limit is not None and self._active >= self._limit:
                if deadline is None:
                    deadline = time.monotonic() + self.MAX_SLOT_WAIT_S
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break  # degrade, never deadlock
                t0 = time.monotonic()
                self._cond.wait(timeout=min(remaining, 0.1))
                waited += time.monotonic() - t0
                if poll is not None:
                    poll()
            self._active += 1
            if self._active > self._max_seen:
                self._max_seen = self._active
        if waited:
            get_registry().counter("store_throttle_waits").inc()
        return waited

    def release(self) -> None:
        with self._cond:
            self._active = max(0, self._active - 1)
            self._cond.notify_all()

    # -- AIMD ----------------------------------------------------------

    def on_throttle(self) -> float:
        """A throttle was observed against this store: step the limit
        down (cooldown-spaced) and return the paced delay the caller
        should wait before its in-place retry."""
        now = time.monotonic()
        opened = False
        with self._cond:
            self._last_throttle = now
            self._streak = 0
            self._consecutive += 1
            consecutive = self._consecutive
            if now - self._last_step >= self.STEP_COOLDOWN_S:
                base = (
                    self._limit if self._limit is not None
                    else max(1, self._active)
                )
                new = max(1, base // 2)
                if self._limit is None or new < self._limit:
                    opened = self._limit is None
                    self._limit = new
                    self._last_step = now
                    get_registry().counter("store_breaker_trips").inc()
        if opened:
            from ..observability.collect import record_decision

            record_decision(
                "store_breaker_open", store=self.store, limit=self._limit,
            )
            logger.warning(
                "store %s is throttling (429/503/SlowDown-shaped errors): "
                "breaker open, storage concurrency paced to %d in-flight",
                self.store, self._limit,
            )
        self._publish_state()
        # exponential pacing for the in-place retry, deterministic (chaos
        # tests assert timing bounds): 50ms, 100ms, ... capped at 1s
        return min(1.0, 0.05 * (2 ** min(consecutive - 1, 6)))

    def on_success(self) -> None:
        """A storage op completed cleanly: while half-open, a full
        window of successes doubles the limit back toward unbounded."""
        closed = False
        with self._cond:
            self._consecutive = 0
            if self._limit is None:
                return
            if (
                time.monotonic() - self._last_throttle < self.PROBE_IDLE_S
            ):
                return  # still open: recovery probing hasn't started
            self._streak += 1
            if self._streak < max(2, self._limit):
                return
            self._streak = 0
            new = self._limit * 2
            if new >= self._max_seen:
                self._limit = None
                closed = True
            else:
                self._limit = new
            get_registry().counter("store_breaker_restores").inc()
            limit = self._limit
            self._cond.notify_all()
        from ..observability.collect import record_decision

        record_decision(
            "store_breaker_close" if closed else "store_breaker_restore",
            store=self.store, limit=limit,
        )
        if closed:
            logger.info(
                "store %s recovered: breaker closed, storage concurrency "
                "unbounded", self.store,
            )
        self._publish_state()


_breakers_lock = threading.Lock()
_breakers: dict = {}


def store_breaker(store: str) -> StoreHealthBreaker:
    """The process-local breaker for a store root (created on demand)."""
    key = str(store)
    breaker = _breakers.get(key)
    if breaker is None:
        with _breakers_lock:
            breaker = _breakers.get(key)
            if breaker is None:
                breaker = StoreHealthBreaker(key)
                _breakers[key] = breaker
    return breaker


def reset_breakers() -> None:
    """Drop every breaker (tests; a fresh compute against a recovered
    store should not inherit a previous test's open breaker)."""
    with _breakers_lock:
        _breakers.clear()
    get_registry().gauge("store_breaker_state").set(STATE_CLOSED)
