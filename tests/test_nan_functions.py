"""NaN-aware reduction tests. Reference parity: cubed/tests/test_nan_functions.py."""

import numpy as np
import pytest

import cubed_tpu as ct


def test_nansum(spec):
    an = np.array([[1.0, np.nan, 3.0], [np.nan, 5.0, 6.0]])
    a = ct.from_array(an, chunks=(1, 2), spec=spec)
    np.testing.assert_allclose(ct.nansum(a).compute(), np.nansum(an))
    np.testing.assert_allclose(
        ct.nansum(a, axis=0).compute(), np.nansum(an, axis=0)
    )


def test_nanmean(spec):
    an = np.array([[1.0, np.nan, 3.0], [np.nan, 5.0, 6.0]])
    a = ct.from_array(an, chunks=(1, 2), spec=spec)
    np.testing.assert_allclose(ct.nanmean(a).compute(), np.nanmean(an))
    np.testing.assert_allclose(
        ct.nanmean(a, axis=1).compute(), np.nanmean(an, axis=1)
    )


def test_nanmean_all_nan_block(spec):
    an = np.array([[np.nan, np.nan], [1.0, 2.0]])
    a = ct.from_array(an, chunks=(1, 2), spec=spec)
    np.testing.assert_allclose(
        ct.nanmean(a, axis=1).compute(), np.nanmean(an, axis=1)
    )


def test_nansum_int_passthrough(spec):
    an = np.arange(6)
    a = ct.from_array(an, chunks=3, spec=spec)
    assert int(ct.nansum(a).compute()) == an.sum()


def test_nanmax_nanmin(spec):
    an = np.array([[1.0, np.nan, 3.0], [np.nan, np.nan, np.nan], [-2.0, 5.0, np.nan]])
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    import warnings

    # the cubed side is advertised warning-free: compute OUTSIDE suppression
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        got_max1 = ct.nanmax(a, axis=1).compute()
        got_min0 = ct.nanmin(a, axis=0).compute()
        got_max = float(ct.nanmax(a).compute())
        got_min = float(ct.nanmin(a).compute())
    # only numpy's reference needs the all-NaN-slice warning suppressed
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        np.testing.assert_allclose(got_max1, np.nanmax(an, axis=1))
        np.testing.assert_allclose(got_min0, np.nanmin(an, axis=0))
        np.testing.assert_allclose(got_max, np.nanmax(an))
        np.testing.assert_allclose(got_min, np.nanmin(an))


def test_nanmax_all_nan_region_is_nan(spec):
    an = np.full((4, 4), np.nan)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    out = ct.nanmax(a, axis=0).compute()
    assert np.isnan(out).all()


def test_nanmax_int_dtype(spec):
    an = np.arange(12, dtype=np.int32).reshape(3, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    got = ct.nanmax(a, axis=0).compute()
    assert got.dtype == np.int32
    np.testing.assert_array_equal(got, an.max(axis=0))


def test_nanmax_rejects_complex(spec):
    an = np.ones((2, 2), dtype=np.complex64)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    with pytest.raises(TypeError):
        ct.nanmax(a)


def test_nanmin_multichunk_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    rng = np.random.default_rng(0)
    an = rng.uniform(-10, 10, (9, 8))
    an[rng.uniform(size=an.shape) < 0.3] = np.nan
    a = ct.from_array(an, chunks=(3, 3), spec=spec)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        got = ct.nanmin(a, axis=1).compute(executor=JaxExecutor())
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        np.testing.assert_allclose(got, np.nanmin(an, axis=1))


def test_nanmax_int64_exact_above_2_53(spec):
    an = np.array([2**53 + 1, 5], dtype=np.int64)
    a = ct.from_array(an, chunks=(2,), spec=spec)
    assert int(ct.nanmax(a).compute()) == 2**53 + 1


def test_nanmax_empty_raises(spec):
    a = ct.from_array(np.empty((0,), dtype=np.float64), chunks=(1,), spec=spec)
    with pytest.raises(ValueError, match="zero-size"):
        ct.nanmax(a)
