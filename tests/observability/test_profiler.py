"""The folded device profiler: the legacy import path keeps working, both
callbacks feed the span pipeline's decision ring, and neither can fail a
compute when jax's profiler/device stats are unavailable."""

from __future__ import annotations

import time
import types

from cubed_tpu.observability.collect import decisions_since
from cubed_tpu.observability.profiler import (
    DeviceMemoryCallback,
    JaxProfilerCallback,
)


def test_legacy_extensions_import_path_is_a_shim():
    from cubed_tpu.extensions import profiler as legacy

    assert legacy.JaxProfilerCallback is JaxProfilerCallback
    assert legacy.DeviceMemoryCallback is DeviceMemoryCallback


def test_jax_profiler_callback_brackets_the_compute(monkeypatch):
    import jax

    calls = []
    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop", None))
    )
    t0 = time.time()
    cb = JaxProfilerCallback(log_dir="prof-dir")
    cb.on_compute_start(types.SimpleNamespace(dag=None))
    assert cb._active
    cb.on_compute_end(types.SimpleNamespace(dag=None))
    assert not cb._active
    assert [c[0] for c in calls] == ["start", "stop"]
    kinds = [d["kind"] for d in decisions_since(t0)]
    assert "jax_profiler_start" in kinds and "jax_profiler_stop" in kinds


def test_jax_profiler_start_failure_is_swallowed(monkeypatch):
    import jax

    def boom(_):
        raise RuntimeError("no profiler here")

    monkeypatch.setattr(jax.profiler, "start_trace", boom)
    cb = JaxProfilerCallback()
    cb.on_compute_start(types.SimpleNamespace(dag=None))
    assert not cb._active
    cb.on_compute_end(types.SimpleNamespace(dag=None))  # no stop, no raise


def test_device_memory_callback_samples_per_op(monkeypatch):
    import jax

    fake = types.SimpleNamespace(
        memory_stats=lambda: {"bytes_in_use": 123, "peak_bytes_in_use": 456}
    )
    monkeypatch.setattr(jax, "devices", lambda: [fake])
    t0 = time.time()
    cb = DeviceMemoryCallback()
    cb.on_operation_start(types.SimpleNamespace(name="op-a", num_tasks=4))
    assert cb.samples == [
        {"op": "op-a", "bytes_in_use": 123, "peak_bytes_in_use": 456}
    ]
    assert any(
        d["kind"] == "device_memory" and d.get("op") == "op-a"
        for d in decisions_since(t0)
    )


def test_device_memory_callback_tolerates_missing_stats(monkeypatch):
    import jax

    def broken():
        raise RuntimeError("no device")

    monkeypatch.setattr(jax, "devices", broken)
    cb = DeviceMemoryCallback()
    cb.on_operation_start(types.SimpleNamespace(name="op-b", num_tasks=1))
    assert cb.samples[0]["bytes_in_use"] is None
