"""Compatibility shim: the device profiler callbacks moved into the span
pipeline at ``cubed_tpu.observability.profiler`` (their start/stop and
per-op device-memory snapshots now land on the merged trace's scheduler
lane and in flight-recorder bundles). This module keeps the historical
import path working.
"""

from __future__ import annotations

from ..observability.profiler import (  # noqa: F401
    DeviceMemoryCallback,
    JaxProfilerCallback,
)

__all__ = ["JaxProfilerCallback", "DeviceMemoryCallback"]
