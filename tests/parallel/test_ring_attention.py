"""Ring attention vs dense oracle on a virtual 8-device CPU mesh."""

from __future__ import annotations

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from cubed_tpu.parallel.mesh import make_mesh  # noqa: E402
from cubed_tpu.parallel.ring_attention import (  # noqa: E402
    dense_attention,
    ring_attention,
    sequence_sharded,
)


def _mesh(n):
    devices = jax.devices()
    if len(devices) < n:
        try:
            devices = jax.devices("cpu")
        except RuntimeError:
            pass
    if len(devices) < n:
        pytest.skip(f"need {n} devices")
    return make_mesh(shape=(n,), axis_names=("seq",), devices=devices[:n])


def _qkv(B=2, S=64, H=2, D=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.standard_normal((B, S, H, D)), dtype=jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    mesh = _mesh(8)
    q, k, v = _qkv()
    expect = dense_attention(q, k, v, causal=causal)
    qs = sequence_sharded(q, mesh)
    ks = sequence_sharded(k, mesh)
    vs = sequence_sharded(v, mesh)
    out = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, mesh=mesh, causal=causal)
    )(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect), atol=2e-5)


def test_ring_no_mesh_is_dense():
    q, k, v = _qkv(S=16)
    np.testing.assert_allclose(
        np.asarray(ring_attention(q, k, v, causal=True)),
        np.asarray(dense_attention(q, k, v, causal=True)),
        atol=1e-6,
    )


@pytest.mark.slow
def test_ring_gradients_flow():
    """Slow-marked: grad-of-shard_map compiles ~10-30 s on one CPU core
    regardless of mesh/shape size; forward ring-vs-dense equivalence (both
    causal modes) stays in the default suite."""
    mesh = _mesh(4)
    q, k, v = _qkv(B=1, S=32, H=1, D=4)
    expect = dense_attention(q, k, v, causal=True)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.grad(ring_loss)(q, k, v)
    g_dense = jax.grad(dense_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), atol=2e-4)


def test_ring_output_stays_sharded():
    mesh = _mesh(8)
    q, k, v = _qkv()
    qs = sequence_sharded(q, mesh)
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh=mesh))(
        qs, sequence_sharded(k, mesh), sequence_sharded(v, mesh)
    )
    # seq dim sharded over the ring: each shard holds S/8 of dim 1
    shard_shapes = {s.data.shape for s in out.addressable_shards}
    assert shard_shapes == {(2, 8, 2, 8)}
