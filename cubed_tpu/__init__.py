"""cubed_tpu: a TPU-native, bounded-memory, distributed N-dimensional array
framework implementing the Python Array API standard on a lazy whole-operation
DAG with exactly two primitives (blockwise, rechunk), plan-time per-task memory
guarantees, Zarr persistent storage at plan boundaries, and pluggable
executors — including a JAX executor that keeps intermediates resident in HBM,
shards chunk grids over a ``jax.sharding.Mesh``, lowers rechunk to in-HBM
resharding (XLA all-to-all) and reductions to collective trees.

Capability parity target: rsignell/cubed (see SURVEY.md).
"""

__version__ = "0.1.0"

from .spec import Spec  # noqa: F401
from .runtime.types import Callback, TaskEndEvent  # noqa: F401
from .core.array import (  # noqa: F401
    compute,
    measure_reserved_mem,
    visualize,
)
from .core.ops import (  # noqa: F401
    from_array,
    from_zarr,
    map_blocks,
    map_direct,
    map_overlap,
    merge_chunks,
    rechunk,
    store,
    to_zarr,
)
from .core.gufunc import apply_gufunc  # noqa: F401
from .nan_functions import nanmax, nanmean, nanmin, nansum  # noqa: F401

from . import array_api  # noqa: F401
from .array_api import Array  # noqa: F401  (reference: cubed/__init__.py)
from . import observability  # noqa: F401
from . import random  # noqa: F401
from . import service  # noqa: F401
from .service import ComputeService, ServiceConfig  # noqa: F401

__all__ = [
    "__version__",
    "Array",
    "Spec",
    "Callback",
    "TaskEndEvent",
    "compute",
    "measure_reserved_mem",
    "visualize",
    "from_array",
    "from_zarr",
    "map_blocks",
    "rechunk",
    "store",
    "to_zarr",
    "apply_gufunc",
    "map_direct",
    "map_overlap",
    "merge_chunks",
    "nanmax",
    "nanmean",
    "nanmin",
    "nansum",
    "array_api",
    "observability",
    "random",
    "service",
    "ComputeService",
    "ServiceConfig",
]
