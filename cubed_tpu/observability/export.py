"""Live telemetry endpoints: ``/metrics``, ``/healthz``, ``/snapshot.json``.

A stdlib-HTTP daemon thread over the telemetry pipeline
(``observability/timeseries.py``), armed the same way integrity and the
memory guard are: the ``CUBED_TPU_TELEMETRY_PORT`` env var (operator
override, wins) > ``Spec(telemetry_port=...)`` > off. Port ``0`` binds an
ephemeral port (tests, multiple fleets per host); the env value ``off``
disables telemetry even when a Spec asks for it.

- ``GET /metrics`` — Prometheus text exposition (format 0.0.4) of the
  process metrics registry plus the fleet/compute series the sampler
  maintains: counters, gauges (+ ``_max`` high-water marks), histogram
  ``_count``/``_sum`` with p50/p95/p99 quantile samples, and per-worker /
  per-compute labelled series. Metric names are sanitized
  (``[^a-zA-Z0-9_:]`` -> ``_``) and prefixed ``cubed_tpu_``; label values
  are escaped per the exposition spec.
- ``GET /healthz`` — JSON fleet liveness: sampler freshness, live /
  pressured / disconnected worker counts, running computes, active
  alerts. 200 while the sampler is fresh, 503 once it goes stale (the
  probe a front-door load balancer points at).
- ``GET /snapshot.json`` — the dashboard feed: metrics snapshot, fleet
  table, compute progress, recent alert firings, and a bounded dump of
  every time series (what ``python -m cubed_tpu.top`` renders).

``Plan.execute`` calls :func:`maybe_start` per compute; the runtime is a
process-global singleton that persists once armed (a service endpoint
outlives any one compute — exactly the lifecycle a scrape target needs).
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .alerts import AlertEngine
from .metrics import get_registry
from .timeseries import (
    TelemetrySampler,
    TimeSeriesStore,
    compute_progress,
    dispatch_view,
    fleet_view,
    service_view,
)

logger = logging.getLogger(__name__)

#: env var naming the telemetry port (operator override: wins over
#: ``Spec(telemetry_port=...)``; ``off``/empty disables; ``0`` = ephemeral)
TELEMETRY_PORT_ENV_VAR = "CUBED_TPU_TELEMETRY_PORT"

#: env var naming the bind host. Default ``0.0.0.0`` — a scrape target is
#: remote by nature (the runbook points Prometheus at it) and the fabric
#: already trusts its network (runtime/distributed.py's trust model);
#: set ``127.0.0.1`` to keep the endpoint loopback-only
TELEMETRY_HOST_ENV_VAR = "CUBED_TPU_TELEMETRY_HOST"
DEFAULT_BIND_HOST = "0.0.0.0"

#: every exported metric name carries the namespace prefix
METRIC_PREFIX = "cubed_tpu_"

#: /healthz reports degraded once the sampler is this stale (seconds)
HEALTH_STALE_S = 10.0

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")
_LEADING_DIGIT = re.compile(r"^[0-9]")


def sanitize_metric_name(name: str) -> str:
    """A Prometheus-legal metric name: dots/dashes/anything illegal become
    underscores, and a leading digit gains an underscore prefix."""
    name = _NAME_SANITIZE.sub("_", str(name))
    if _LEADING_DIGIT.match(name):
        name = "_" + name
    return name


def escape_label_value(value) -> str:
    """Escape a label value per the text exposition format: backslash,
    double-quote, and newline."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{sanitize_metric_name(k)}="{escape_label_value(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def prometheus_text(
    registry=None, store: Optional[TimeSeriesStore] = None,
) -> str:
    """Render the registry (and the store's labelled fleet/compute series)
    as Prometheus text exposition format 0.0.4.

    Counters keep their registered names (sanitized + prefixed) so the
    docs inventory, ``snapshot()`` keys and scrape labels all agree;
    histograms export as summaries (``_count``/``_sum`` + quantile
    samples)."""
    if registry is None:
        registry = get_registry()
    snap = registry.snapshot()
    kinds = registry.kinds()
    lines: list = []

    # store series, split: labelled samples merge into their registry
    # family (one TYPE line per family — duplicating metadata is a
    # conformance violation), unlabelled store-only series (the fleet
    # aggregates the sampler derives: fleet_pressured_fraction, ...) get
    # their own gauge families below
    labelled_by_name: dict = {}
    store_only: dict = {}
    if store is not None:
        hist_suffixes = ("_count", "_sum", "_p50", "_p95", "_p99")
        for name, labels, value in store.latest_series():
            if labels:
                labelled_by_name.setdefault(name, []).append((labels, value))
            elif name not in kinds and not any(
                name.endswith(sfx)
                and kinds.get(name[: -len(sfx)]) == "histogram"
                for sfx in hist_suffixes
            ):
                # registry names and histogram-derived mirrors already
                # export through their own families; only genuinely
                # store-only series add a family here
                store_only[name] = value

    def emit(name, kind, help_text, samples):
        """One metric family: HELP + TYPE + its samples."""
        full = METRIC_PREFIX + sanitize_metric_name(name)
        lines.append(f"# HELP {full} {help_text}")
        lines.append(f"# TYPE {full} {kind}")
        for suffix, labels, value in samples:
            if value is None:
                continue
            lines.append(
                f"{full}{suffix}{_fmt_labels(labels)} {_fmt_value(value)}"
            )

    for name in sorted(kinds):
        kind = kinds[name]
        extra = [
            ("", labels, v)
            for labels, v in labelled_by_name.pop(name, [])
        ]
        if kind == "counter":
            emit(
                name, "counter",
                f"cubed_tpu counter {name}",
                [("", None, snap.get(name))] + extra,
            )
        elif kind == "gauge":
            emit(
                name, "gauge",
                f"cubed_tpu gauge {name} (current value)",
                [("", None, snap.get(name))] + extra,
            )
            emit(
                f"{name}_max", "gauge",
                f"cubed_tpu gauge {name} (lifetime high-water mark)",
                [("", None, snap.get(f"{name}_max"))],
            )
        elif kind == "histogram":
            summary = snap.get(name)
            if not isinstance(summary, dict):
                continue
            full = METRIC_PREFIX + sanitize_metric_name(name)
            lines.append(
                f"# HELP {full} cubed_tpu histogram {name} "
                "(summary: count/sum + estimated quantiles)"
            )
            lines.append(f"# TYPE {full} summary")
            for q, label in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                v = summary.get(label)
                if v is not None:
                    lines.append(
                        f'{full}{{quantile="{q}"}} {_fmt_value(v)}'
                    )
            lines.append(f"{full}_count {_fmt_value(summary.get('count', 0))}")
            lines.append(f"{full}_sum {_fmt_value(summary.get('sum', 0.0))}")

    # labelled quantile mirrors (the sampler's <base>_p50/_p95/_p99
    # series, e.g. the per-tenant slo_request_latency family): regroup
    # into ONE summary-convention family per base name with
    # {quantile="..."} labels — the shape Prometheus tooling expects for
    # estimated quantiles — instead of three disjoint gauge families.
    # Only when the base name has no registry family of its own (a
    # registry histogram already exports its summary above).
    quantile_suffixes = (("_p50", "0.5"), ("_p95", "0.95"), ("_p99", "0.99"))
    summary_groups: dict = {}
    plain_labelled: dict = {}
    for name, samples in labelled_by_name.items():
        base = None
        q = None
        for sfx, qv in quantile_suffixes:
            if name.endswith(sfx) and name[: -len(sfx)]:
                base, q = name[: -len(sfx)], qv
                break
        if base is None or base in kinds or base in labelled_by_name:
            plain_labelled[name] = samples
            continue
        group = summary_groups.setdefault(base, [])
        for labels, v in samples:
            group.append((dict(labels or {}, quantile=q), v))
    for base in sorted(summary_groups):
        emit(
            base, "summary",
            f"cubed_tpu telemetry series {base} "
            "(estimated quantiles, latest samples)",
            [("", labels, v) for labels, v in summary_groups[base]],
        )
    # remaining labelled fleet/compute series whose name has no registry
    # family: one gauge family each, latest sample per label set
    for name in sorted(plain_labelled):
        emit(
            name, "gauge",
            f"cubed_tpu telemetry series {name} (latest sample)",
            [("", labels, v) for labels, v in plain_labelled[name]],
        )
    # unlabelled store-only series: the sampler-derived fleet aggregates
    for name in sorted(store_only):
        emit(
            name, "gauge",
            f"cubed_tpu telemetry series {name} (latest sample)",
            [("", None, store_only[name])],
        )
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# the HTTP endpoint
# ----------------------------------------------------------------------


class TelemetryRuntime:
    """The process-global telemetry singleton: store + sampler + alert
    engine + HTTP server. Built by :func:`ensure_started`."""

    def __init__(self, port: int, interval_s: float = 1.0,
                 rules: Optional[list] = None):
        self.store = TimeSeriesStore()
        self.alert_engine = AlertEngine(self.store, rules=rules)
        self.sampler = TelemetrySampler(
            self.store, interval_s=interval_s, alert_engine=self.alert_engine
        )
        self.requested_port = port
        self.server: Optional[ThreadingHTTPServer] = None
        self.port: Optional[int] = None
        self._server_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        runtime = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # no stderr chatter
                pass

            def do_GET(self) -> None:
                get_registry().counter("telemetry_http_requests").inc()
                try:
                    if self.path.startswith("/metrics"):
                        body = prometheus_text(store=runtime.store).encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                        code = 200
                    elif self.path.startswith("/healthz"):
                        payload, code = runtime.health()
                        body = json.dumps(payload).encode()
                        ctype = "application/json"
                    elif self.path.startswith("/snapshot.json"):
                        body = json.dumps(
                            runtime.snapshot(), default=str
                        ).encode()
                        ctype = "application/json"
                        code = 200
                    else:
                        body = b"not found (try /metrics, /healthz, /snapshot.json)\n"
                        ctype = "text/plain"
                        code = 404
                except Exception:
                    logger.exception("telemetry endpoint %s failed", self.path)
                    body = b"internal error\n"
                    ctype = "text/plain"
                    code = 500
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (ConnectionError, OSError):
                    pass  # scraper went away mid-reply

        bind_host = (
            os.environ.get(TELEMETRY_HOST_ENV_VAR, "").strip()
            or DEFAULT_BIND_HOST
        )
        self.server = ThreadingHTTPServer(
            (bind_host, self.requested_port), Handler
        )
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self._server_thread = threading.Thread(
            target=self.server.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="telemetry-http",
            daemon=True,
        )
        self._server_thread.start()
        self.sampler.start()
        logger.info(
            "telemetry endpoint live on port %d (/metrics /healthz "
            "/snapshot.json)", self.port,
        )

    def stop(self) -> None:
        self.sampler.stop()
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
            self.server = None
        t = self._server_thread
        if t is not None:
            t.join(timeout=2.0)
        self._server_thread = None

    # -- endpoint payloads ---------------------------------------------

    def health(self) -> tuple:
        """(payload, status_code) for /healthz."""
        now = time.time()
        last = self.sampler.last_sample_ts
        stale = last is None or (now - last) > HEALTH_STALE_S
        fleet = fleet_view()
        computes = compute_progress()
        running = [c for c in computes if c.get("status") == "running"]
        status = "ok"
        if stale:
            status = "stale"
        elif fleet["workers_live"] and (
            fleet["workers_pressured"] * 2 >= fleet["workers_live"]
        ):
            status = "degraded"
        payload = {
            "status": status,
            "sampler_alive": self.sampler.alive,
            "last_sample_age_s": (
                round(now - last, 3) if last is not None else None
            ),
            "workers_live": fleet["workers_live"],
            "workers_pressured": fleet["workers_pressured"],
            "workers_disconnected": fleet["workers_disconnected"],
            "fleets": fleet["fleets"],
            "computes_running": len(running),
            "alerts_active": self.alert_engine.active(),
        }
        return payload, (503 if stale else 200)

    def snapshot(self) -> dict:
        """The /snapshot.json payload (also what the dashboard renders)."""
        return {
            "ts": time.time(),
            "port": self.port,
            "metrics": get_registry().snapshot(),
            "fleet": fleet_view(),
            "service": service_view(),
            "dispatch": dispatch_view(),
            "computes": compute_progress(),
            "alerts": self.alert_engine.recent(),
            "alerts_active": self.alert_engine.active(),
            "series": self.store.to_dict(window_s=300.0),
        }


# ----------------------------------------------------------------------
# arming (env > Spec > off), process-global singleton
# ----------------------------------------------------------------------

_runtime_lock = threading.Lock()
_runtime: Optional[TelemetryRuntime] = None


def resolve_port(spec=None) -> Optional[int]:
    """The effective telemetry port: ``CUBED_TPU_TELEMETRY_PORT`` env var
    (operator override — ``off``/empty disables even a Spec-armed
    endpoint) > ``Spec(telemetry_port=...)`` > None (off). ``0`` means an
    ephemeral port. Malformed env values raise loudly — a typo silently
    disabling the operator's telemetry would be worse than an error."""
    raw = os.environ.get(TELEMETRY_PORT_ENV_VAR)
    if raw is not None:
        raw = raw.strip()
        if raw == "" or raw.lower() == "off":
            return None
        try:
            port = int(raw)
        except ValueError:
            raise ValueError(
                f"invalid {TELEMETRY_PORT_ENV_VAR}={raw!r}: expected an "
                "integer port (0 = ephemeral) or 'off'"
            )
        if port < 0 or port > 65535:
            raise ValueError(
                f"invalid {TELEMETRY_PORT_ENV_VAR}={raw!r}: port out of range"
            )
        return port
    port = getattr(spec, "telemetry_port", None)
    return None if port is None else int(port)


def get_runtime() -> Optional[TelemetryRuntime]:
    """The live runtime, or None while telemetry is unarmed."""
    return _runtime


def ensure_started(port: int) -> TelemetryRuntime:
    """Start (or return) the process-global telemetry runtime.

    Idempotent: the first call binds the endpoint and starts the sampler;
    later calls return the same runtime even if they ask for a different
    port (the endpoint is a process-level resource — one scrape target per
    process, logged when a conflicting port is requested)."""
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            if port not in (0, _runtime.requested_port, _runtime.port):
                logger.warning(
                    "telemetry already serving on port %s; ignoring "
                    "request for port %s (one endpoint per process)",
                    _runtime.port, port,
                )
            return _runtime
        rt = TelemetryRuntime(port)
        rt.start()
        _runtime = rt
        return rt


def maybe_start(spec=None) -> Optional[TelemetryRuntime]:
    """Arm telemetry for a compute when the resolved config asks for it.

    Called by ``Plan.execute``; returns the runtime (started now or
    earlier) or None when telemetry is off. Never raises for server
    trouble — a busy port must not fail a compute (it logs and runs
    unobserved instead). A malformed env config DOES raise
    (``resolve_port``): a typo silently disabling the operator's
    telemetry would be worse than an error."""
    port = resolve_port(spec)
    if port is None:
        return None
    try:
        return ensure_started(port)
    except OSError as e:
        logger.error(
            "telemetry endpoint failed to bind port %s (%s); compute "
            "proceeds without live telemetry", port, e,
        )
        return None


def shutdown() -> None:
    """Stop and discard the runtime (tests; normal processes let the
    daemon threads die with the interpreter)."""
    global _runtime
    with _runtime_lock:
        rt = _runtime
        _runtime = None
    if rt is not None:
        rt.stop()
