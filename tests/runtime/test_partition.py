"""Partition tolerance: reconnect handshake, lease-based ownership, seq
dedup, outbox replay, frame robustness, and the chaos proof that a one-way
partition plus seeded message faults cannot corrupt a compute.

Pure protocol units drive a raw socket speaking the worker wire protocol
against a real ``Coordinator`` (no subprocess boots, no wall-clock chaos);
the chaos proof at the end runs the full fleet path. Wall-clock chaos for
other failure classes lives in ``test_chaos.py``.
"""

from __future__ import annotations

import socket
import threading
import time

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.observability import get_registry
from cubed_tpu.runtime import faults
from cubed_tpu.runtime.distributed import (
    Coordinator,
    WorkerLostError,
    _WorkerLink,
    recv_frame,
    run_worker,
    send_frame,
)
from cubed_tpu.runtime.resilience import Classification, RetryPolicy

from ..utils import SlowAdd, TaskCounter


# ----------------------------------------------------------------------
# pure units: the worker link state machine
# ----------------------------------------------------------------------


def test_worker_link_outbox_is_bounded():
    before = get_registry().counter("outbox_dropped").value
    link = _WorkerLink("w-unit", sock=None, outbox_cap=4)
    for i in range(6):
        # sock=None: the link is down — sends fail but important frames
        # must queue for replay
        assert link.send({"type": "result", "task_id": i}, important=True) \
            is False
    assert len(link.outbox) == 4  # bounded: the two OLDEST were dropped
    assert [seq for seq, _t, _d in link.outbox] == [3, 4, 5, 6]
    assert get_registry().counter("outbox_dropped").value - before == 2


def test_worker_link_seq_monotonic_and_ack_prunes():
    link = _WorkerLink("w-unit", sock=None)
    for i in range(5):
        link.send({"type": "result", "task_id": i}, important=True)
    assert [seq for seq, _t, _d in link.outbox] == [1, 2, 3, 4, 5]
    assert link.unacked_age() >= 0.0
    link.on_ack(3)
    assert [seq for seq, _t, _d in link.outbox] == [4, 5]
    link.on_ack(None)  # malformed ack: no-op, never a crash
    link.on_ack(99)
    assert not link.outbox
    assert link.unacked_age() == 0.0


def test_worker_link_unimportant_frames_not_retained():
    link = _WorkerLink("w-unit", sock=None)
    link.send({"type": "heartbeat"})
    link.send({"type": "started", "task_id": 1})
    assert not link.outbox  # nothing to replay: stale acks are useless


def test_worker_link_adopt_fresh_session_clears_outbox():
    link = _WorkerLink("w-unit", sock=None)
    link.send({"type": "result", "task_id": 0}, important=True)
    a, b = socket.socketpair()
    try:
        # resumed=False: the coordinator registered us as a NEW session —
        # our old lease is gone, replaying its results would only be noise
        link.adopt(a, "tok-1", resumed=False)
        assert link.token == "tok-1"
        assert not link.outbox
    finally:
        a.close()
        b.close()


def test_worker_link_adopt_resumed_replays_in_order():
    link = _WorkerLink("w-unit", sock=None)
    for i in range(3):
        link.send({"type": "result", "task_id": i}, important=True)
    a, b = socket.socketpair()
    try:
        link.adopt(a, "tok-2", resumed=True)
        got = [recv_frame(b) for _ in range(3)]
        assert [m["task_id"] for m in got] == [0, 1, 2]
        assert [m["seq"] for m in got] == [1, 2, 3]
        # replayed frames stay queued until the coordinator acks them
        assert len(link.outbox) == 3
    finally:
        a.close()
        b.close()


# ----------------------------------------------------------------------
# protocol units: a raw socket speaking the worker protocol
# ----------------------------------------------------------------------


def _fake_worker_connect(coord, name, token=None, nthreads=1):
    """Raw-socket registration; returns (sock, hello_ack)."""
    s = socket.create_connection(coord.address, timeout=10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    hello = {"type": "hello", "name": name, "nthreads": nthreads, "pid": 0}
    if token is not None:
        hello["token"] = token
    send_frame(s, hello)
    ack = recv_frame(s)
    return s, ack


def test_reconnect_within_lease_keeps_task_ownership():
    """The core lease guarantee: disconnect + reconnect inside the lease
    window keeps in-flight tasks owned by the worker — no WorkerLostError,
    no requeue, no retry-budget draw — and the replayed result resolves
    the original future."""
    coord = Coordinator("127.0.0.1", 0, lease_s=8.0)
    reg = get_registry()
    before = reg.snapshot()
    try:
        s, ack = _fake_worker_connect(coord, "w-p0")
        assert ack["type"] == "hello_ack" and ack["resume"] is False
        assert ack["lease_s"] == 8.0
        token = ack["token"]

        fut = coord.submit(None, SlowAdd(0.0), 1.0)
        task = recv_frame(s)
        assert task["type"] == "task"

        # abrupt disconnect: socket EOF must NOT be worker death
        s.close()
        deadline = time.time() + 5
        while coord.stats["workers_disconnected"] == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert coord.stats["workers_disconnected"] == 1
        time.sleep(0.3)
        assert not fut.done(), "socket EOF must not fail a leased task"
        assert coord.stats["workers_lost"] == 0
        snap = coord.stats_snapshot()
        assert snap["workers"]["w-p0"]["alive"] is True
        assert snap["workers"]["w-p0"]["connected"] is False

        # reconnect with the session token: the lease is re-adopted
        s2, ack2 = _fake_worker_connect(coord, "w-p0", token=token)
        assert ack2["type"] == "hello_ack" and ack2["resume"] is True
        assert ack2["token"] == token
        send_frame(s2, {
            "type": "result", "task_id": task["task_id"],
            "result": 42.0, "stats": {}, "seq": 1,
        })
        assert recv_frame(s2) == {"type": "ack", "seq": 1, "epoch": 0}
        result, _stats = fut.result(timeout=5)
        assert result == 42.0

        assert coord.stats["workers_reconnected"] == 1
        assert coord.stats["workers_lost"] == 0
        assert coord.stats["leases_expired"] == 0
        delta = reg.snapshot_delta(before)
        assert delta.get("worker_loss_requeues", 0) == 0
        assert delta.get("task_retries", 0) == 0
        s2.close()
    finally:
        coord.close()


def test_lease_expiry_requeues_exactly_once_as_worker_loss():
    """A worker that stays dark past its lease is declared lost exactly
    once: its in-flight task fails with WorkerLostError — which the retry
    policy classifies REQUEUE (a free reroute, not a budget-drawing
    retry)."""
    coord = Coordinator("127.0.0.1", 0, lease_s=0.4)
    try:
        s, ack = _fake_worker_connect(coord, "w-dark")
        fut = coord.submit(None, SlowAdd(0.0), 1.0)
        recv_frame(s)  # the task reaches the worker, then: darkness
        s.close()
        with pytest.raises(WorkerLostError, match="lease expired"):
            fut.result(timeout=8)
        # the counter lands just after the futures fail: allow it a moment
        deadline = time.time() + 2
        while coord.stats["leases_expired"] == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert coord.stats["leases_expired"] == 1
        assert coord.stats["workers_lost"] == 1
        assert coord.stats["workers_disconnected"] == 1
        # "requeued as worker loss": the classification every executor's
        # map routes through — free reroute, capped by max_requeues
        exc = fut.exception()
        assert RetryPolicy().classify(exc) is Classification.REQUEUE
    finally:
        coord.close()


def test_impostor_name_rejected_while_live():
    """A hello claiming a live connected worker's name without its session
    token must be rejected — and must not perturb the real worker."""
    coord = Coordinator("127.0.0.1", 0)
    try:
        s, ack = _fake_worker_connect(coord, "w-real")
        imp, reply = _fake_worker_connect(coord, "w-real")  # no token
        assert reply["type"] == "hello_reject"
        assert "token" in reply["reason"]
        imp.close()
        assert coord.stats["workers_rejected"] == 1
        assert coord.n_workers == 1
        # the real worker still serves tasks on its original connection
        fut = coord.submit(None, SlowAdd(0.0), 1.0)
        task = recv_frame(s)
        send_frame(s, {
            "type": "result", "task_id": task["task_id"], "result": 7.0,
            "stats": {}, "seq": 1,
        })
        assert fut.result(timeout=5)[0] == 7.0
        s.close()
    finally:
        coord.close()


def test_duplicate_sequenced_result_applied_once():
    """A replayed/duplicated result frame is acked (the original ack may be
    the lost frame) but never applied twice."""
    coord = Coordinator("127.0.0.1", 0)
    before = get_registry().counter("fleet_messages_deduped").value
    try:
        s, _ack = _fake_worker_connect(coord, "w-dup")
        fut = coord.submit(None, SlowAdd(0.0), 1.0)
        task = recv_frame(s)
        msg = {
            "type": "result", "task_id": task["task_id"], "result": 5.0,
            "stats": {}, "seq": 1,
        }
        send_frame(s, msg)
        send_frame(s, msg)  # the duplicate
        assert recv_frame(s) == {"type": "ack", "seq": 1, "epoch": 0}
        assert recv_frame(s) == {"type": "ack", "seq": 1, "epoch": 0}
        assert fut.result(timeout=5)[0] == 5.0
        assert (
            get_registry().counter("fleet_messages_deduped").value - before
            == 1
        )
        s.close()
    finally:
        coord.close()


def test_corrupt_frames_counted_and_peer_dropped_cleanly():
    """Fuzz the coordinator with malformed frames: a garbage payload and a
    hostile length prefix must each be a connection-level error on that
    peer — counted, logged, connection dropped — never an uncaught
    exception killing the recv thread (the coordinator keeps serving)."""
    import struct

    coord = Coordinator("127.0.0.1", 0, lease_s=0.3)
    try:
        # garbage payload under a sane length prefix
        s1, _ = _fake_worker_connect(coord, "w-fuzz1")
        s1.sendall(struct.pack(">Q", 16) + b"\xde\xad\xbe\xef" * 4)
        # hostile length prefix (64 EiB)
        s2, _ = _fake_worker_connect(coord, "w-fuzz2")
        s2.sendall(struct.pack(">Q", 1 << 63))
        deadline = time.time() + 5
        while coord.stats["frames_corrupt"] < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert coord.stats["frames_corrupt"] == 2
        # the fuzzed peers were disconnected (then lease-dropped), and the
        # coordinator still accepts registrations and serves tasks
        s3, ack3 = _fake_worker_connect(coord, "w-clean")
        assert ack3["type"] == "hello_ack"
        fut = coord.submit(None, SlowAdd(0.0), 1.0)
        task = recv_frame(s3)
        send_frame(s3, {
            "type": "result", "task_id": task["task_id"], "result": 2.0,
            "stats": {}, "seq": 1,
        })
        assert fut.result(timeout=5)[0] == 2.0
        for s in (s1, s2, s3):
            s.close()
    finally:
        coord.close()


def test_worker_recv_survives_corrupt_frame_and_reconnects():
    """Worker-side frame robustness: a garbage frame from the coordinator
    makes the worker drop the connection and reconnect with its session
    token — the recv thread survives. A hello_reject on reconnect is
    fatal (the worker gives up instead of hammering)."""
    import struct

    server = socket.create_server(("127.0.0.1", 0))
    host, port = server.getsockname()[:2]
    before = get_registry().counter("frames_corrupt").value
    box: dict = {}
    done = threading.Event()

    def fake_coordinator():
        # first registration
        c1, _ = server.accept()
        hello1 = recv_frame(c1)
        box["hello1"] = hello1
        send_frame(c1, {"type": "hello_ack", "token": "tok-X",
                        "resume": False, "lease_s": 5.0})
        # feed a garbage frame: the worker must reconnect, not die
        c1.sendall(struct.pack(">Q", 8) + b"notapkl!")
        c2, _ = server.accept()
        hello2 = recv_frame(c2)
        box["hello2"] = hello2
        # reject the reconnect: the worker should exit, not retry forever
        send_frame(c2, {"type": "hello_reject", "reason": "test says no"})
        c1.close()
        c2.close()
        done.set()

    t = threading.Thread(target=fake_coordinator, daemon=True)
    t.start()
    w = threading.Thread(
        target=run_worker, args=(f"{host}:{port}",),
        kwargs=dict(nthreads=1, name="w-fuzzed", reconnect_give_up_s=10.0),
        daemon=True,
    )
    w.start()
    assert done.wait(timeout=15)
    w.join(timeout=15)
    assert not w.is_alive(), "worker must exit after a fatal rejection"
    assert box["hello1"].get("token") is None
    assert box["hello2"].get("token") == "tok-X"  # session token presented
    assert get_registry().counter("frames_corrupt").value - before >= 1
    server.close()


def test_new_session_clears_assignment_dedup():
    """Regression: a persistent worker re-registered as a NEW session (a
    fresh coordinator after a client crash — its task-id counter restarts
    at 0) must not swallow the new session's assignments as duplicates of
    the dead session's task ids."""
    import hashlib

    import cloudpickle

    server = socket.create_server(("127.0.0.1", 0))
    host, port = server.getsockname()[:2]
    results: list = []
    done = threading.Event()

    def blob_task(task_id):
        blob = cloudpickle.dumps((SlowAdd(0.0), None))
        return {
            "type": "task", "task_id": task_id,
            "blob_id": hashlib.sha1(blob).hexdigest(), "blob": blob,
            "input": 1.0, "ack": False,
        }

    def await_result(c):
        while True:
            m = recv_frame(c)
            if m.get("type") == "result":
                results.append(m)
                send_frame(c, {"type": "ack", "seq": m["seq"]})
                return

    def fake_coordinators():
        # coordinator A: session 1, assigns task id 0
        c1, _ = server.accept()
        recv_frame(c1)
        send_frame(c1, {"type": "hello_ack", "token": "t1",
                        "resume": False, "lease_s": 5.0})
        send_frame(c1, blob_task(0))
        await_result(c1)
        c1.close()  # the client process "crashes"
        # coordinator B: a fresh process — new session, ids restart at 0
        c2, _ = server.accept()
        recv_frame(c2)
        send_frame(c2, {"type": "hello_ack", "token": "t2",
                        "resume": False, "lease_s": 5.0})
        send_frame(c2, blob_task(0))
        await_result(c2)
        send_frame(c2, {"type": "shutdown"})
        c2.close()
        done.set()

    threading.Thread(target=fake_coordinators, daemon=True).start()
    w = threading.Thread(
        target=run_worker, args=(f"{host}:{port}",),
        kwargs=dict(nthreads=1, name="w-sessions"), daemon=True,
    )
    w.start()
    assert done.wait(timeout=30), (
        "the new session's task id 0 was swallowed by stale dedup state"
    )
    w.join(timeout=15)
    assert [m["result"] for m in results] == [2.0, 2.0]
    server.close()


def test_injected_duplication_deduped_on_both_sides():
    """With every frame duplicated in both directions (rate 1.0), task
    assignments execute once (worker-side task-id dedup) and sequenced
    results apply once (coordinator-side seq dedup) — the compute's
    arithmetic is untouched."""
    coord = Coordinator("127.0.0.1", 0)
    host, port = coord.address
    reg = get_registry()
    before = reg.snapshot()
    faults.activate({"seed": 7, "net_msg_dup_rate": 1.0})
    try:
        threading.Thread(
            target=run_worker, args=(f"{host}:{port}",),
            kwargs=dict(nthreads=1, name="w-dupes"), daemon=True,
        ).start()
        coord.wait_for_workers(1, timeout=30)
        futs = [coord.submit(None, SlowAdd(0.0), float(i)) for i in range(4)]
        assert [f.result(timeout=15)[0] for f in futs] == [1.0, 2.0, 3.0, 4.0]
        delta = reg.snapshot_delta(before)
        assert delta.get("fleet_assignments_deduped", 0) >= 1, delta
        assert delta.get("fleet_messages_deduped", 0) >= 1, delta
        assert coord.stats["workers_lost"] == 0
    finally:
        faults.deactivate()
        coord.close()


def test_autoscaler_does_not_backfill_leased_worker():
    """A disconnected-but-leased worker is not a hole: the policy loop
    must neither spawn a replacement for it nor pick it as a drain
    victim."""
    from cubed_tpu.runtime.autoscale import (
        Autoscaler,
        AutoscalePolicy,
        WorkerFactory,
    )

    class Factory(WorkerFactory):
        def __init__(self):
            self.started = []

        def start_worker(self):
            name = f"x-{len(self.started)}"
            self.started.append(name)
            return name

        def stop_worker(self, name):
            pass

    class View:
        """Coordinator stub: two workers, one disconnected-but-leased."""

        backfill_grace_s = 0.0

        def __init__(self):
            self.drained = []

        def load_view(self):
            return [
                {"name": "a", "draining": False, "pressured": False,
                 "connected": True, "outstanding": 0, "nthreads": 1},
                {"name": "b", "draining": False, "pressured": False,
                 "connected": False, "outstanding": 2, "nthreads": 1},
            ]

        def known_worker_names(self):
            return {"a", "b"}

        def request_drain(self, name, grace_s=30.0, reason="scale_down"):
            self.drained.append(name)
            return True

    coord = View()
    factory = Factory()
    scaler = Autoscaler(
        coord, factory=factory,
        policy=AutoscalePolicy(
            min_workers=1, max_workers=4, idle_rounds_before_down=1,
            cooldown_down_s=0.0,
        ),
        initial_workers=2,
    )
    scaler.tick()
    assert factory.started == []  # the leased worker still counts as capacity
    # idle long enough to scale down: the victim must be the CONNECTED one
    # (b has more outstanding anyway, but only a is reachable)
    scaler.tick()
    assert coord.drained in ([], ["a"]) and "b" not in coord.drained


# ----------------------------------------------------------------------
# chaos proof A: partition + message faults, end to end
# ----------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_partition_and_message_faults_bitwise_correct(
    tmp_path, invariant_audit
):
    """Acceptance proof: seeded message drop/delay/duplication plus a
    ≥2s one-way partition of one worker mid-compute (dataflow scheduler
    on) completes bitwise-correct with ZERO workers_lost, at least one
    reconnect, and every task's result applied exactly once."""
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

    journal = str(tmp_path / "partition.journal.jsonl")
    control_dir = str(tmp_path / "ctrl")
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB",
        scheduler="dataflow", journal=journal,
        fault_injection=dict(
            seed=1234,
            net_msg_drop_rate=0.04,
            net_msg_dup_rate=0.05,
            net_msg_delay_rate=0.10,
            net_msg_delay_s=0.02,
            partition_worker_names=["local-0"],
            partition_after_tasks=3,
            partition_duration_s=2.5,
            partition_direction="tx",
        ),
    )
    an = np.arange(144, dtype=np.float64).reshape(12, 12)
    ex = DistributedDagExecutor(
        n_local_workers=2, worker_threads=1, control_dir=control_dir,
        task_timeout=6.0, retries=6, use_backups=False, lease_s=12.0,
    )
    try:
        a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 36 tasks
        r = ct.map_blocks(SlowAdd(0.05), a, dtype=np.float64)
        expected_tasks = r.plan.num_tasks()
        counter = TaskCounter()
        result = r.compute(executor=ex, callbacks=[counter])
        np.testing.assert_array_equal(result, an + 1.0)  # bitwise-correct
        stats = ex._coordinator.stats
        assert stats["workers_lost"] == 0, stats
        assert stats["leases_expired"] == 0, stats
        assert stats["workers_reconnected"] >= 1, stats
        # "no task result applied twice": each task completes exactly once
        # at the map layer, however many times its frames were delivered
        assert counter.value == expected_tasks, (
            counter.value, expected_tasks,
        )
    finally:
        ex.close()
    # exactly-once is also provable post-hoc: duplicate frame deliveries
    # must never reach the journal as duplicate applications, and every
    # re-dispatch across the partition must show an ownership release
    invariant_audit(
        journal=journal, control_dir=control_dir, work_dir=str(tmp_path)
    )
