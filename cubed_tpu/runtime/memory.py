"""Runtime memory guard: per-task enforcement of the bounded-memory promise.

The paper's headline guarantee — a bounded maximum memory per task — was
until now a *plan-time projection* only (``projected_mem`` checked against
``Spec.allowed_mem`` before execution). Nothing watched what a task
actually allocated: a mis-modelled ``extra_projected_mem``, a kernel with a
hidden copy, or plain memory pressure from too many concurrent tasks all
surfaced as an opaque ``MemoryError`` (blind-retried at full concurrency)
or an OOM-killed worker (indistinguishable from any other worker loss).
This module closes the loop at runtime, the way production schedulers do
(Ray's memory-monitor OOM prevention, Dask distributed's worker memory
watermarks):

- **Task-scope guard.** ``task_guard`` (entered by
  ``runtime/utils.execute_with_stats`` around every task body) attributes
  process RSS *growth* to the running task: a shared low-overhead sampler
  thread reads ``/proc/self/status`` every ``sample_interval_s`` and keeps,
  per active task, the peak of ``rss_now - rss_at_task_start``. When that
  peak (plus any chaos-injected synthetic spike) exceeds ``allowed_mem``:
  mode ``observe`` (the default) records ``mem_guard_soft_exceeded`` and
  logs a structured warning naming the task and the measured-vs-allowed
  bytes; mode ``enforce`` fails the task with a picklable
  :class:`MemoryGuardExceededError`, which the resilience layer classifies
  ``RESOURCE``. Mode ``off`` is a true no-op: no sampler thread, no
  per-task work beyond one env lookup. Attribution under concurrency is
  deliberately conservative-approximate — RSS is process-wide, so a
  spike lands on every task in flight; that is the right bias for a
  *guard* (pressure is real whether or not attribution is exact), and at
  concurrency 1 the measurement is exact, which is when enforcement uses
  it to produce an actionable abort.

- **Host-pressure watermarks.** While tasks are active the sampler also
  compares process RSS growth to ``allowed_mem x tasks-in-flight``: above
  ``soft_fraction`` of it is *soft* pressure (stop growing concurrency),
  above it is *hard* pressure (step down). ``/proc/meminfo``'s
  ``MemAvailable`` under ``host_floor_bytes`` is hard pressure regardless
  — when the machine is nearly out, per-process accounting is moot.
  Exported as gauges (``worker_rss_bytes``, ``mem_host_available_bytes``,
  ``mem_pressure``); the distributed worker heartbeats its RSS + pressure
  flag so the coordinator stops dispatching to a pressured host.

- **Admission control.** :class:`AdmissionController` (one per compute,
  consulted by ``map_unordered``) bounds tasks in flight. On a
  RESOURCE-classified failure or hard host pressure it *halves* the limit
  (AIMD's multiplicative decrease — the same shape Ray/Dask use to shed
  memory pressure); after a full window of pressure-free successes it
  restores multiplicatively (doubling) until back to unbounded. A task
  that fails RESOURCE even at concurrency 1 cannot be helped by
  degradation: the compute aborts promptly with an actionable error
  ("op X measured N bytes > allowed_mem M — raise allowed_mem or
  rechunk") instead of burning the whole retry budget.

Activation mirrors the integrity layer: ``Spec(memory_guard=...)`` (armed
by ``Plan.execute`` for the compute, exported to the env so spawned pool
workers inherit it), the ``CUBED_TPU_MEMORY_GUARD`` env var (operator
override — wins everywhere), and distributed task messages mirror the
client's config to pre-started fleets. The guard needs ``allowed_mem`` to
judge anything, so with no Spec in play it stays inactive.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass, fields
from typing import Optional

from ..observability.accounting import record_scoped_counter
from ..observability.metrics import get_registry
from ..utils import current_measured_mem, host_available_mem, memory_repr

logger = logging.getLogger(__name__)

#: env var carrying a JSON MemoryGuardConfig into child processes (and the
#: operator's override: when set it wins over Spec-level arming)
MEMORY_GUARD_ENV_VAR = "CUBED_TPU_MEMORY_GUARD"

MODES = ("off", "observe", "enforce")
DEFAULT_MODE = "observe"


class MemoryGuardExceededError(RuntimeError):
    """A task's measured memory exceeded ``allowed_mem`` under
    ``memory_guard="enforce"``.

    Picklable (it crosses pool and fleet boundaries like any task failure)
    and structured: ``chunk_key``/``op_name`` locate the task,
    ``measured``/``allowed`` are bytes. Classified ``RESOURCE`` by the
    resilience layer — retried only after a concurrency step-down, and
    fatal (with an actionable message) when it recurs at concurrency 1.
    """

    def __init__(
        self,
        message: str,
        chunk_key: Optional[str] = None,
        measured: Optional[int] = None,
        allowed: Optional[int] = None,
        op_name: Optional[str] = None,
    ):
        super().__init__(message)
        self.chunk_key = chunk_key
        self.measured = measured
        self.allowed = allowed
        self.op_name = op_name

    def __reduce__(self):
        return (
            MemoryGuardExceededError,
            (
                self.args[0] if self.args else "",
                self.chunk_key,
                self.measured,
                self.allowed,
                self.op_name,
            ),
        )

    @property
    def wire_payload(self) -> dict:
        """Plain-dict form riding distributed error frames (the same
        channel ``ChunkIntegrityError`` uses), so the coordinator-side
        abort message can name real byte counts measured on the worker."""
        return {
            "chunk_key": self.chunk_key,
            "measured": self.measured,
            "allowed": self.allowed,
            "op_name": self.op_name,
            "kind": "memory_guard",
        }


#: remote exception class names that classify RESOURCE (resilience.py reads
#: this so the wire table and the local isinstance checks can't drift)
RESOURCE_TYPE_NAMES = frozenset({"MemoryError", "MemoryGuardExceededError"})


@dataclass(frozen=True)
class MemoryGuardConfig:
    """What to enforce, and how aggressively to watch."""

    #: "off" (true no-op) | "observe" (count + warn) | "enforce" (fail task)
    mode: str = DEFAULT_MODE
    #: the per-task budget (bytes) — ``Spec.allowed_mem``; 0 disables the
    #: guard entirely (nothing to judge against)
    allowed_mem: int = 0
    #: sampler period; 20 ms keeps worst-case overhead well under the <2 %
    #: wall-clock bench budget (one /proc read + a few dict walks per tick)
    sample_interval_s: float = 0.02
    #: host-pressure soft watermark as a fraction of allowed_mem x in-flight
    soft_fraction: float = 0.85
    #: MemAvailable floor below which the host is hard-pressured regardless
    host_floor_bytes: int = 128 * 1024 * 1024

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(
                f"invalid memory_guard mode {self.mode!r}; expected one of "
                f"{MODES}"
            )

    @classmethod
    def from_dict(cls, d: dict) -> "MemoryGuardConfig":
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown MemoryGuardConfig fields {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        return cls(**d)

    def to_env_json(self) -> str:
        return json.dumps({f.name: getattr(self, f.name) for f in fields(self)})

    @property
    def enabled(self) -> bool:
        return self.mode != "off" and self.allowed_mem > 0


# ----------------------------------------------------------------------
# process-level activation (env > activated > None; mirrors integrity.py:
# the env var is the operator's override and how children inherit arming)
# ----------------------------------------------------------------------

_lock = threading.Lock()
_active: Optional[MemoryGuardConfig] = None
#: (raw env string, parsed config) — parse once per distinct value
_env_cache: tuple = (None, None)


def _validate_mode(mode: str) -> str:
    if mode not in MODES:
        raise ValueError(
            f"invalid memory_guard mode {mode!r}; expected one of {MODES}"
        )
    return mode


def _coerce(config) -> MemoryGuardConfig:
    if isinstance(config, MemoryGuardConfig):
        return config
    if isinstance(config, dict):
        return MemoryGuardConfig.from_dict(config)
    if isinstance(config, str):
        return MemoryGuardConfig(mode=config)
    raise TypeError(
        f"expected MemoryGuardConfig, dict or mode string, got "
        f"{type(config).__name__}"
    )


def activate(config, export_env: bool = False) -> MemoryGuardConfig:
    """Arm the guard in this process (and, with ``export_env``, in every
    child process spawned afterwards)."""
    global _active, _baseline_rss
    cfg = _coerce(config)
    with _lock:
        _active = cfg
        # RSS growth is measured against the footprint at THIS arming, not
        # absolute RSS: a fat parent (jax imported, a long test session's
        # caches) must not read as standing pressure — and re-baselining
        # per arming keeps a long-lived process's slow cache growth from
        # accruing into phantom pressure across computes
        _baseline_rss = current_measured_mem()
    if export_env:
        os.environ[MEMORY_GUARD_ENV_VAR] = cfg.to_env_json()
    return cfg


def deactivate() -> None:
    global _active, _env_cache
    with _lock:
        _active = None
        _env_cache = (None, None)
    os.environ.pop(MEMORY_GUARD_ENV_VAR, None)


def get_guard_config() -> Optional[MemoryGuardConfig]:
    """The effective config, or None (unarmed — the common fast path).

    The env var wins (operator override; also how spawned workers
    self-arm); a malformed value raises loudly — a typo silently disabling
    the memory guard would be worse than an error. Accepts either a JSON
    config or a bare mode string (``CUBED_TPU_MEMORY_GUARD=enforce``) —
    the bare form overrides the MODE only, inheriting ``allowed_mem`` and
    the sampler knobs from whatever the Spec armed (an operator asking for
    enforcement must not silently zero the budget and disable the guard)."""
    global _env_cache, _baseline_rss
    raw = os.environ.get(MEMORY_GUARD_ENV_VAR)
    if raw:
        # cache key includes the armed base config: a bare-mode override
        # merges over it, so a new compute's arming must rebuild
        base = _active
        cached_key, cached_cfg = _env_cache
        if (raw, base) == cached_key:
            return cached_cfg
        if raw.strip().startswith("{"):
            cfg = MemoryGuardConfig.from_dict(json.loads(raw))
        else:
            mode = _validate_mode(raw.strip())
            if base is not None:
                cfg = MemoryGuardConfig(
                    mode=mode,
                    allowed_mem=base.allowed_mem,
                    sample_interval_s=base.sample_interval_s,
                    soft_fraction=base.soft_fraction,
                    host_floor_bytes=base.host_floor_bytes,
                )
            else:
                cfg = MemoryGuardConfig(mode=mode)
        with _lock:
            _env_cache = ((raw, base), cfg)
            # a NEW env config = a new compute arming: re-baseline so
            # growth accrued before it doesn't read as pressure
            if cfg.enabled:
                _baseline_rss = current_measured_mem()
        return cfg
    return _active


def wire_config() -> Optional[str]:
    """The client's current arming state, serialized for distributed task
    messages (None = unarmed) — pre-started fleets mirror the client."""
    cfg = get_guard_config()
    return cfg.to_env_json() if cfg is not None else None


_wire_cache: tuple = (None, None)


def arm_from_wire(raw: Optional[str]) -> Optional[MemoryGuardConfig]:
    """Fleet-worker side: adopt the guard config a task message carried
    (None disarms, overriding any stale spawn-time env)."""
    global _active, _wire_cache, _baseline_rss
    if raw is None:
        with _lock:
            _active = None
        return None
    cached_raw, cached_cfg = _wire_cache
    fresh = raw != cached_raw
    if fresh:
        try:
            cached_cfg = MemoryGuardConfig.from_dict(json.loads(raw))
        except (ValueError, TypeError):
            logger.warning("ignoring invalid memory-guard config from wire")
            return _active
    with _lock:
        _wire_cache = (raw, cached_cfg)
        _active = cached_cfg
        # re-baseline on a new wire config OR whenever this worker is idle
        # (no guarded task in flight): a persistent fleet worker's slow
        # cache growth across many computes must not accrue into phantom
        # pressure — and back-to-back computes with an IDENTICAL Spec send
        # identical wire strings, so "new config" alone is not enough.
        # Idle arming ≈ a task starting with nothing else running, which
        # is exactly when growth-so-far is nobody's working set.
        if cached_cfg is not None and cached_cfg.enabled and (
            fresh or not _tasks
        ):
            _baseline_rss = _read_rss(
                max_age_s=cached_cfg.sample_interval_s
            )
    return cached_cfg


class scoped:
    """Arm the guard for a ``with`` block (``Plan.execute`` uses this for
    ``Spec(memory_guard=...)``). ``mode=None`` with a known ``allowed_mem``
    arms the default ``observe`` mode; with neither it is a no-op. Like the
    integrity layer, a pre-existing env var is the OPERATOR's override: the
    process-global config is still recorded (env shadows it via
    ``get_guard_config``) but the env passes through untouched to this
    process and every spawned worker."""

    def __init__(self, mode=None, allowed_mem=None, export_env: bool = False):
        if mode is None and allowed_mem:
            mode = DEFAULT_MODE
        self._config = (
            None
            if mode is None
            else MemoryGuardConfig(mode=mode, allowed_mem=int(allowed_mem or 0))
        )
        self._export_env = export_env

    def __enter__(self):
        if self._config is None:
            return None
        self._prev = _active
        self._prev_env = os.environ.get(MEMORY_GUARD_ENV_VAR)
        return activate(
            self._config,
            export_env=self._export_env and self._prev_env is None,
        )

    def __exit__(self, *exc) -> None:
        if self._config is None:
            return
        global _active
        with _lock:
            _active = self._prev
        if self._export_env:
            if self._prev_env is None:
                os.environ.pop(MEMORY_GUARD_ENV_VAR, None)
            else:
                os.environ[MEMORY_GUARD_ENV_VAR] = self._prev_env


# ----------------------------------------------------------------------
# the sampler and per-task guard
# ----------------------------------------------------------------------

#: RSS at first arming — growth (not absolute RSS) is what watermarks see
_baseline_rss: Optional[int] = None

#: (monotonic ts, rss) — /proc/self/status costs ~200 us in containerized
#: kernels, so per-task guard enter/exit must not each pay a fresh read;
#: the sampler refreshes this every tick and tasks accept a reading up to
#: ~1.5 ticks stale (a memory *guard* doesn't need microsecond freshness)
_rss_cache: tuple = (0.0, None)


def _read_rss(max_age_s: float = 0.0) -> Optional[int]:
    global _rss_cache
    if max_age_s > 0.0:
        ts, val = _rss_cache
        if val is not None and time.monotonic() - ts <= max_age_s:
            return val
    val = current_measured_mem()
    if val is not None:
        _rss_cache = (time.monotonic(), val)
    return val

#: active guarded tasks: id(guard) -> _GuardedTask
_tasks: dict = {}
_tasks_lock = threading.Lock()
_tasks_present = threading.Event()

_sampler_thread: Optional[threading.Thread] = None

#: "ok" | "soft" | "hard" — written by the sampler, read by admission
_pressure_level = "ok"

#: trace-sample throttle (see _sample_once): the guard samples every ~20ms
#: but the collect ring is bounded, so the memory lane records at most one
#: sample per period (plus every pressure-level change)
_TRACE_SAMPLE_PERIOD_S = 0.25
_last_trace_sample = 0.0


class _GuardedTask:
    __slots__ = ("key", "start_rss", "injected", "peak_delta")

    def __init__(self, key: str, start_rss: int, injected: int):
        self.key = key
        self.start_rss = start_rss
        self.injected = injected
        self.peak_delta = 0


def _ensure_sampler() -> None:
    global _sampler_thread
    if _sampler_thread is not None and _sampler_thread.is_alive():
        return
    with _lock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return
        _sampler_thread = threading.Thread(
            target=_sampler_loop, name="mem-guard-sampler", daemon=True
        )
        _sampler_thread.start()


def _sample_once(cfg: MemoryGuardConfig, tasks: list) -> None:
    global _pressure_level
    rss = _read_rss()  # fresh read; keeps the shared cache warm for tasks
    if rss is None:
        return
    for t in tasks:
        delta = rss - t.start_rss
        if delta > t.peak_delta:
            t.peak_delta = delta
    reg = get_registry()
    reg.gauge("worker_rss_bytes").set(rss)
    # host watermarks: growth over the arming-time baseline vs what the
    # bounded-memory model says this many concurrent tasks may use
    base = _baseline_rss if _baseline_rss is not None else rss
    growth = max(0, rss - base)
    watermark = cfg.allowed_mem * max(1, len(tasks))
    level = "ok"
    if growth > watermark:
        level = "hard"
    elif growth > cfg.soft_fraction * watermark:
        level = "soft"
    avail = host_available_mem()
    if avail is not None:
        reg.gauge("mem_host_available_bytes").set(avail)
        if avail < cfg.host_floor_bytes:
            level = "hard"
    level_changed = level != _pressure_level
    if level_changed:
        logger.debug("memory pressure level: %s -> %s", _pressure_level, level)
    _pressure_level = level
    level_int = {"ok": 0, "soft": 1, "hard": 2}[level]
    reg.gauge("mem_pressure").set(level_int)
    # feed the trace merger's memory lane, throttled to one sample per
    # _TRACE_SAMPLE_PERIOD_S (plus every pressure-level change): at the
    # guard's 20ms cadence the bounded ring would only hold the last ~80s
    # of a long compute, silently hiding the pressure ramp that triggered
    # early step-downs; at 250ms it covers ~17 minutes — longer than the
    # bench budget
    global _last_trace_sample
    now_s = time.monotonic()
    if now_s - _last_trace_sample >= _TRACE_SAMPLE_PERIOD_S or level_changed:
        _last_trace_sample = now_s
        from ..observability.collect import record_sample

        record_sample(rss=rss, pressure=level_int, available=avail)


def _sampler_loop() -> None:
    global _pressure_level
    while True:
        if not _tasks_present.wait(timeout=5.0):
            continue
        cfg = get_guard_config()
        with _tasks_lock:
            tasks = list(_tasks.values())
        if not tasks or cfg is None or not cfg.enabled:
            # the last guard exited between the wait and here — or the
            # compute disarmed (abort path) while already-running task
            # threads are still inside their guards, which keeps
            # _tasks_present set: sleep, or this branch busy-spins a core
            # until the last straggler task finishes
            _pressure_level = "ok"
            time.sleep(0.05)
            continue
        _sample_once(cfg, tasks)
        time.sleep(cfg.sample_interval_s)


def pressure_level() -> str:
    """The sampler's latest host-pressure reading ("ok" when the guard is
    inactive). Cheap — a module attribute read — so admission paths can
    consult it per loop iteration."""
    if get_guard_config() is None:
        return "ok"
    return _pressure_level


class task_guard:
    """Context manager guarding one task body (see module docstring).

    ``injected_bytes`` is the chaos injector's synthetic memory spike: it
    adds to the measured peak so seeded chaos tests can deterministically
    exercise observe/enforce behavior without actually allocating (and
    risking a real OOM of the test host).

    ``observe_only=True`` coerces ``enforce`` down to ``observe`` — used by
    the JAX executor, where the guarded unit is a whole fused segment, not
    a retryable task, so failing it would abort the compute rather than
    trigger degradation.
    """

    _INACTIVE = object()

    def __init__(
        self, key: str = "", injected_bytes: int = 0, observe_only: bool = False
    ):
        self._key = key
        self._injected = int(injected_bytes or 0)
        self._observe_only = observe_only
        self._task: Optional[_GuardedTask] = None
        self._cfg: Optional[MemoryGuardConfig] = None
        #: peak RSS growth attributed to this task (+ injected spike);
        #: None while inactive
        self.measured: Optional[int] = None

    def __enter__(self) -> "task_guard":
        cfg = get_guard_config()
        if cfg is None or not cfg.enabled:
            return self
        start = _read_rss(max_age_s=cfg.sample_interval_s * 1.5)
        if start is None:
            return self  # no /proc: the guard cannot measure here
        self._cfg = cfg
        self._task = _GuardedTask(self._key, start, self._injected)
        with _tasks_lock:
            _tasks[id(self)] = self._task
            _tasks_present.set()
        _ensure_sampler()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        global _pressure_level
        task = self._task
        if task is None:
            return
        with _tasks_lock:
            _tasks.pop(id(self), None)
            if not _tasks:
                _tasks_present.clear()
                # no tasks in flight = no watermark to exceed: a stale
                # "hard" reading must not step down some later compute
                _pressure_level = "ok"
        # one final sample so short tasks (shorter than a sampler period)
        # still measure their live allocations at completion; cached up to
        # ~1.5 ticks — the sampler keeps it warm, so steady-state guarded
        # tasks pay dict lookups here, not ~200 us /proc reads
        rss = _read_rss(max_age_s=self._cfg.sample_interval_s * 1.5)
        if rss is not None:
            delta = rss - task.start_rss
            if delta > task.peak_delta:
                task.peak_delta = delta
        self.measured = max(0, task.peak_delta) + task.injected
        if exc_type is not None:
            return  # the body already failed; never mask its error
        cfg = self._cfg
        if self.measured <= cfg.allowed_mem:
            return
        if self._observe_only:
            # the guarded unit is NOT a single task (a fused JAX segment,
            # a whole eager op): comparing its aggregate growth against the
            # PER-TASK budget would pollute mem_guard_soft_exceeded and
            # spam warnings for correctly-modelled work — measure only
            return
        if cfg.mode == "enforce":
            raise MemoryGuardExceededError(
                f"task {self._key or '<unnamed>'} measured "
                f"{memory_repr(self.measured)} ({self.measured} bytes) > "
                f"allowed_mem {memory_repr(cfg.allowed_mem)} "
                f"({cfg.allowed_mem} bytes)",
                chunk_key=self._key,
                measured=self.measured,
                allowed=cfg.allowed_mem,
            )
        # observe: per-task attribution rides the task's scope counters
        # back to the client registry (surviving process boundaries); the
        # decision entry feeds the trace/bundle guard timeline (in-process
        # executors only — a pool/fleet worker's ring stays local)
        record_scoped_counter("mem_guard_soft_exceeded")
        from ..observability.collect import record_decision

        record_decision(
            "guard_soft_exceeded", chunk=self._key,
            measured=self.measured, allowed=cfg.allowed_mem,
        )
        logger.warning(
            "memory guard (observe): task %s measured %s (%d bytes) > "
            "allowed_mem %s (%d bytes) — enforcement is off; set "
            "memory_guard='enforce' to fail such tasks, or raise "
            "allowed_mem / rechunk",
            self._key or "<unnamed>",
            memory_repr(self.measured),
            self.measured,
            memory_repr(cfg.allowed_mem),
            cfg.allowed_mem,
        )

    def stats(self) -> dict:
        """The guard's contribution to the task stats dict ({} while
        inactive, so ``memory_guard="off"`` stays byte-identical)."""
        if self.measured is None:
            return {}
        return {"guard_mem_peak": self.measured}


# ----------------------------------------------------------------------
# admission control
# ----------------------------------------------------------------------


class AdmissionController:
    """AIMD-style concurrency limiter shared by one compute's maps.

    Unbounded (``limit is None``) until the first step-down, so computes
    that never hit memory pressure pay nothing and behave exactly as
    before. ``step_down`` halves (multiplicative decrease), ``on_success``
    doubles back after a full pressure-free window of successes
    (multiplicative restore), returning to unbounded once the limit covers
    the highest concurrency ever seen.
    """

    #: minimum seconds between pressure-triggered step-downs, so one
    #: sustained pressure episode doesn't collapse the limit to 1 instantly
    PRESSURE_COOLDOWN_S = 1.0

    def __init__(self):
        self.limit: Optional[int] = None
        self._max_seen = 1
        self._streak = 0
        self._last_stepdown = 0.0
        self._lock = threading.Lock()

    def has_slot(self, in_flight: int) -> bool:
        with self._lock:
            if in_flight > self._max_seen:
                self._max_seen = in_flight
            return self.limit is None or in_flight < self.limit

    @property
    def throttling(self) -> bool:
        return self.limit is not None

    def step_down(self, in_flight: int) -> int:
        """Halve the in-flight limit (RESOURCE failure observed)."""
        reg = get_registry()
        with self._lock:
            base = self.limit if self.limit is not None else max(1, in_flight)
            new = max(1, base // 2)
            if self.limit is None or new < self.limit:
                # WARN once on entering degraded mode; further halvings
                # (and AIMD flapping around the sustainable level) are
                # normal operation under pressure — INFO, not 30 warnings
                log = logger.warning if self.limit is None else logger.info
                self.limit = new
                self._streak = 0
                self._last_stepdown = time.monotonic()
                reg.counter("mem_pressure_stepdowns").inc()
                reg.gauge("admission_limit").set(new)
                from ..observability.collect import record_decision

                record_decision("admission_step_down", limit=new)
                log(
                    "memory pressure: concurrency stepped down to %d "
                    "in-flight task(s)", new,
                )
            return self.limit

    def on_pressure(self, in_flight: int) -> None:
        """Hard host pressure observed (sampler watermark): step down at
        most once per cooldown window."""
        with self._lock:
            if time.monotonic() - self._last_stepdown < self.PRESSURE_COOLDOWN_S:
                return
            if self.limit is not None and in_flight < self.limit:
                return  # already below the limit; let it drain
        self.step_down(in_flight)

    def on_success(self, pressure_ok: bool = True) -> None:
        """A task completed; restore multiplicatively after a full window
        of successes with no pressure."""
        with self._lock:
            if self.limit is None:
                return
            if not pressure_ok:
                self._streak = 0
                return
            self._streak += 1
            if self._streak < self.limit:
                return
            self._streak = 0
            new = self.limit * 2
            reg = get_registry()
            reg.counter("mem_pressure_restores").inc()
            from ..observability.collect import record_decision

            if new >= self._max_seen:
                self.limit = None
                reg.gauge("admission_limit").set(self._max_seen)
                record_decision("admission_restore", limit=None)
                logger.info("memory pressure receded: concurrency unbounded")
            else:
                self.limit = new
                reg.gauge("admission_limit").set(new)
                record_decision("admission_restore", limit=new)
                logger.info(
                    "memory pressure receding: concurrency restored to %d", new
                )


# ----------------------------------------------------------------------
# client-side failure accounting + the actionable abort
# ----------------------------------------------------------------------


def count_resource_failure(metrics, exc: BaseException) -> None:
    """Count a RESOURCE-classified failure client-side.

    Like integrity detection, the failing task's scope (where the guard
    would have counted) is discarded on failure, so the completion loop
    counts — once per failure it actually observes, for every executor
    (local raise, pickled from a pool worker, or off the fleet wire)."""
    metrics.counter("task_resource_failures").inc()
    if isinstance(exc, MemoryGuardExceededError) or (
        getattr(exc, "remote_type", None) == "MemoryGuardExceededError"
    ):
        metrics.counter("mem_guard_hard_exceeded").inc()


def _guard_details(exc: BaseException) -> tuple:
    """(measured, allowed, chunk_key) from a guard error, whether local,
    unpickled, or a RemoteTaskError carrying the wire payload."""
    measured = getattr(exc, "measured", None)
    allowed = getattr(exc, "allowed", None)
    key = getattr(exc, "chunk_key", None)
    payload = getattr(exc, "remote_payload", None)
    if measured is None and isinstance(payload, dict):
        if payload.get("kind") == "memory_guard":
            measured = payload.get("measured")
            allowed = payload.get("allowed")
            key = key or payload.get("chunk_key")
    return measured, allowed, key


def resource_abort_error(
    op_name: Optional[str], exc: BaseException, at_floor: bool = True
) -> MemoryGuardExceededError:
    """The actionable fail-fast for a task that exceeds memory even at
    concurrency 1 (``at_floor``) or after exhausting its retries under
    memory pressure: degradation cannot help, only a bigger budget or
    smaller chunks can."""
    get_registry().counter("mem_guard_aborts").inc()
    measured, allowed, key = _guard_details(exc)
    if measured is not None and allowed is not None:
        detail = (
            f"measured {memory_repr(measured)} ({measured} bytes) > "
            f"allowed_mem {memory_repr(allowed)} ({allowed} bytes)"
        )
    else:
        detail = f"failed with {type(exc).__name__} ({exc})"
    context = (
        "even at concurrency 1"
        if at_floor
        else "after exhausting its retries under memory pressure"
    )
    return MemoryGuardExceededError(
        f"op {op_name or '<unknown>'} {detail} {context} — reduced "
        "concurrency cannot help: raise allowed_mem, or rechunk to "
        "smaller chunks (adjust extra_projected_mem if the projection "
        "was trusted)",
        chunk_key=key,
        measured=measured,
        allowed=allowed,
        op_name=op_name,
    )
