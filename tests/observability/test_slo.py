"""Per-tenant SLO tests: spec/board units, burn-rate math, the live
service integration (SLI recording, sampler series, burn-rate alerts,
top panel, summary-convention ``/metrics`` export), and the chaos proof
that a SIGKILLed service folds its error budget back from the durable
run archive — no reset, no double-count."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.observability.alerts import (
    AlertEngine,
    SloBurnRateRule,
    default_rules,
)
from cubed_tpu.observability.export import prometheus_text
from cubed_tpu.observability.runhistory import load_runs
from cubed_tpu.observability.slo import (
    BURN_WINDOWS,
    FAST_BURN_THRESHOLD,
    SloBoard,
    SloSpec,
    parse_slos_env,
)
from cubed_tpu.observability.timeseries import (
    TelemetrySampler,
    TimeSeriesStore,
    service_view,
)
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.service import ComputeService

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


# ---------------------------------------------------------------------------
# spec + env parsing units
# ---------------------------------------------------------------------------


def test_spec_requires_at_least_one_objective():
    with pytest.raises(ValueError, match="latency_s and/or"):
        SloSpec("a")
    SloSpec("a", latency_s=2.0)
    SloSpec("a", availability_objective=0.999)


def test_spec_validates_objective_bounds():
    with pytest.raises(ValueError, match="must be in"):
        SloSpec("a", latency_s=2.0, latency_objective=1.0)
    with pytest.raises(ValueError, match="must be in"):
        SloSpec("a", availability_objective=0.0)


def test_spec_from_value_rejects_unknown_fields():
    with pytest.raises(ValueError, match="unknown SLO field"):
        SloSpec.from_value("a", {"latency_s": 2.0, "typo_field": 1})
    spec = SloSpec.from_value("a", {"latency_s": 2.0})
    assert spec.latency_s == 2.0
    assert SloSpec.from_value("a", spec) is spec


def test_parse_slos_env(monkeypatch):
    from cubed_tpu.observability.slo import SLOS_ENV_VAR

    monkeypatch.delenv(SLOS_ENV_VAR, raising=False)
    assert parse_slos_env() is None
    monkeypatch.setenv(SLOS_ENV_VAR, '{"t": {"latency_s": 2.0}}')
    assert parse_slos_env()["t"]["latency_s"] == 2.0
    # malformed values are logged and ignored, never fatal
    monkeypatch.setenv(SLOS_ENV_VAR, "{not json")
    assert parse_slos_env() is None
    monkeypatch.setenv(SLOS_ENV_VAR, '{"t": {"bogus": 1}}')
    assert parse_slos_env() is None


def test_board_resolve_env_wins_per_tenant(monkeypatch):
    from cubed_tpu.observability.slo import SLOS_ENV_VAR

    monkeypatch.setenv(SLOS_ENV_VAR, '{"a": {"latency_s": 9.0}}')
    board = SloBoard.resolve({
        "a": {"latency_s": 1.0}, "b": {"latency_s": 2.0},
    })
    assert board.spec_for("a").latency_s == 9.0  # env override
    assert board.spec_for("b").latency_s == 2.0
    monkeypatch.delenv(SLOS_ENV_VAR)
    assert SloBoard.resolve(None) is None


# ---------------------------------------------------------------------------
# SLI / burn-rate math
# ---------------------------------------------------------------------------


def _board(**fields):
    fields = fields or {"latency_s": 1.0, "availability_objective": 0.99}
    return SloBoard({"t": SloSpec("t", **fields)})


def test_empty_window_is_healthy_not_paging():
    board = _board()
    row = board.status(now=1000.0)["t"]
    assert row["events"] == 0
    assert row["budget_remaining"] == 1.0
    assert all(v == 0.0 for v in row["burn"].values())
    assert not row["fast_burn"] and not row["slow_burn"]


def test_all_good_traffic_burns_nothing():
    board = _board()
    for i in range(50):
        board.record("t", ok=True, latency_s=0.1, ts=1000.0 + i)
    row = board.status(now=1100.0)["t"]
    assert row["burn"]["5m"] == 0.0
    assert row["budget_remaining"] == 1.0
    assert row["good_fraction"] == 1.0


def test_latency_misses_and_failures_both_burn_latency_budget():
    board = _board()
    board.record("t", ok=True, latency_s=5.0, ts=1000.0)   # too slow
    board.record("t", ok=False, latency_s=0.1, ts=1001.0)  # failed
    board.record("t", ok=True, latency_s=0.1, ts=1002.0)   # good
    row = board.status(now=1003.0)["t"]
    assert row["events"] == 3
    assert row["latency_bad"] == 2
    assert row["availability_bad"] == 1
    # bad_frac 2/3 over a 1% latency budget: burn ~66x on every window
    assert row["burn"]["5m"] == pytest.approx((2 / 3) / 0.01, rel=1e-3)
    assert row["budget_remaining"] == 0.0


def test_burn_1x_means_spending_exactly_the_budget():
    # availability objective 0.99: 1 bad in 100 is burn exactly 1.0
    board = _board(availability_objective=0.99)
    for i in range(99):
        board.record("t", ok=True, ts=1000.0 + i)
    board.record("t", ok=False, ts=1099.0)
    row = board.status(now=1100.0)["t"]
    assert row["burn"]["5m"] == pytest.approx(1.0, rel=1e-6)
    assert row["budget_remaining"] == pytest.approx(0.0, abs=1e-6)


def test_windows_forget_old_badness():
    board = _board(availability_objective=0.99)
    board.record("t", ok=False, ts=1000.0)  # ancient failure
    for i in range(10):
        board.record("t", ok=True, ts=5000.0 + i)
    now = 5000.0 + BURN_WINDOWS["5m"]
    row = board.status(now=now)["t"]
    assert row["burn"]["5m"] == 0.0  # the 5m window no longer sees it
    assert row["burn"]["3d"] > 0.0   # the compliance window still does


def test_record_for_unconfigured_tenant_is_ignored():
    board = _board()
    board.record("stranger", ok=False, latency_s=9.0, ts=1000.0)
    assert "stranger" not in board.status(now=1001.0)
    assert board.status(now=1001.0)["t"]["events"] == 0


def test_fold_skips_ineligible_and_malformed_records():
    board = _board()
    folded = board.fold([
        {"kind": "request", "tenant": "t", "status": "completed",
         "ok": True, "latency_s": 0.1, "ts": 1000.0},
        {"kind": "request", "tenant": "t", "status": "failed",
         "ok": False, "ts": 1001.0},
        {"kind": "request", "tenant": "t", "status": "shed", "ts": 1002.0},
        {"kind": "request", "tenant": "t", "status": "cancelled",
         "ts": 1003.0},
        {"kind": "request", "tenant": "other", "status": "completed",
         "ok": True, "ts": 1004.0},
        {"kind": "compute", "tenant": "t", "ts": 1005.0},
        {"kind": "request", "tenant": "t", "status": "completed",
         "ok": True},  # no ts: unplaceable in any window
    ])
    assert folded == 2  # the completed + the failed only
    row = board.status(now=1010.0)["t"]
    assert row["events"] == 2 and row["availability_bad"] == 1


# ---------------------------------------------------------------------------
# the burn-rate alert rule
# ---------------------------------------------------------------------------


def _store_with_burns(now, fast=20.0, slow=20.0):
    store = TimeSeriesStore()
    labels = {"tenant": "t"}
    store.record("slo_burn_5m", fast, ts=now, labels=labels)
    store.record("slo_burn_1h", fast, ts=now, labels=labels)
    store.record("slo_burn_6h", slow, ts=now, labels=labels)
    store.record("slo_burn_3d", slow, ts=now, labels=labels)
    return store


def test_slo_burn_rule_requires_both_windows():
    now = 1000.0
    rule = SloBurnRateRule("fast", "1h", "5m", FAST_BURN_THRESHOLD)
    details = rule.evaluate(_store_with_burns(now), now)
    assert details is not None
    assert details["tenants"] == ["t"]
    # long window hot but short window recovered: no page (quick reset)
    store = TimeSeriesStore()
    store.record("slo_burn_1h", 20.0, ts=now, labels={"tenant": "t"})
    store.record("slo_burn_5m", 0.0, ts=now, labels={"tenant": "t"})
    assert rule.evaluate(store, now) is None


def test_slo_burn_rule_ignores_stale_series():
    now = 1000.0
    rule = SloBurnRateRule("fast", "1h", "5m", FAST_BURN_THRESHOLD)
    store = _store_with_burns(now - 60.0)  # a closed service's last word
    assert rule.evaluate(store, now) is None


def test_default_rules_ship_both_slo_burn_rules():
    rules = {r.name: r for r in default_rules()}
    assert rules["slo_fast_burn"].severity == "critical"
    assert rules["slo_slow_burn"].severity == "warning"
    assert rules["slo_fast_burn"].threshold == FAST_BURN_THRESHOLD


# ---------------------------------------------------------------------------
# live service integration
# ---------------------------------------------------------------------------


def _service_with_bad_slo(tmp_path, n_requests=6):
    """A service whose tenant can never meet its (microsecond) latency
    objective: every completed request burns budget."""
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    svc = ComputeService(
        executor=AsyncPythonDagExecutor(), spec=spec,
        service_dir=str(tmp_path / "svc"), result_cache=False,
        slos={"alpha": {"latency_s": 1e-6,
                        "availability_objective": 0.999}},
    ).start()
    for i in range(n_requests):
        a = ct.from_array(an, chunks=(4, 4), spec=spec)
        r = ct.map_blocks(lambda x, _k=float(i): x + _k, a, dtype=np.float64)
        svc.submit(r, tenant="alpha").result(timeout=600)
    return svc


def test_service_snapshot_and_archive_carry_slo_state(tmp_path):
    svc = _service_with_bad_slo(tmp_path)
    try:
        row = svc.stats_snapshot()["slo"]["alpha"]
        assert row["events"] == 6
        assert row["latency_bad"] == 6
        assert row["budget_remaining"] == 0.0
        assert row["fast_burn"] and row["slow_burn"]
        assert row["latency"]["p99_s"] > 0
        records, bad = load_runs(str(tmp_path / "svc"))
        reqs = [r for r in records if r["kind"] == "request"]
        assert bad == 0 and len(reqs) == 6
        assert all(r["status"] == "completed" for r in reqs)
        assert all(r["tenant"] == "alpha" for r in reqs)
    finally:
        svc.close()


def test_sampler_series_fire_both_burn_alerts(tmp_path):
    """The wiring proof: board -> sampler slo_* series -> default rules
    -> firings, on the first engine tick."""
    svc = _service_with_bad_slo(tmp_path)
    try:
        store = TimeSeriesStore()
        TelemetrySampler(store).sample_once()
        names = {name for name, labels, _v in store.latest_series()
                 if labels.get("tenant") == "alpha"}
        for expected in (
            "slo_burn_5m", "slo_burn_1h", "slo_burn_6h", "slo_burn_3d",
            "slo_budget_remaining", "slo_events_total", "slo_bad_total",
            "slo_request_latency_p50", "slo_request_latency_p99",
        ):
            assert expected in names, expected
        engine = AlertEngine(store)
        fired = {f["rule"] for f in engine.tick()}
        assert {"slo_fast_burn", "slo_slow_burn"} <= fired
    finally:
        svc.close()


def test_metrics_export_regroups_latency_quantiles_as_summary(tmp_path):
    svc = _service_with_bad_slo(tmp_path, n_requests=2)
    try:
        store = TimeSeriesStore()
        TelemetrySampler(store).sample_once()
        text = prometheus_text(store=store)
        assert "# TYPE cubed_tpu_slo_request_latency summary" in text
        assert 'quantile="0.99"' in text and 'tenant="alpha"' in text
        # the regrouped family must not also appear as per-suffix gauges
        assert "slo_request_latency_p99{" not in text
    finally:
        svc.close()


def test_top_panel_renders_slo_rows(tmp_path):
    from cubed_tpu import top as top_mod

    svc = _service_with_bad_slo(tmp_path, n_requests=2)
    try:
        rendered = top_mod.render(
            {"ts": time.time(), "metrics": {}, "service": service_view()}
        )
        assert "SLO" in rendered
        assert "alpha" in rendered
        assert "FAST BURN" in rendered
    finally:
        svc.close()


# ---------------------------------------------------------------------------
# SIGKILL-restart: the budget folds back from the archive
# ---------------------------------------------------------------------------


_KILL_SCRIPT = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
import cubed_tpu as ct
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor
from cubed_tpu.service import ComputeService

mode = sys.argv[1]
work_dir = {work_dir!r}
sdir = {sdir!r}
N = {n_requests!r}

AN = np.arange(64, dtype=np.float64).reshape(8, 8)
spec = ct.Spec(work_dir=work_dir, allowed_mem="500MB")
SLOS = {{"alpha": {{"latency_s": 1e-6,
                    "availability_objective": 0.999}}}}


def build(k, delay=0.05):
    def kernel(x, _k=float(k), _d=delay):
        time.sleep(_d)
        return x + _k

    a = ct.from_array(AN, chunks=(4, 4), spec=spec)  # 4 tasks
    return ct.map_blocks(kernel, a, dtype=np.float64)


if mode == "run":
    svc = ComputeService(
        executor=AsyncPythonDagExecutor(), max_concurrent=1,
        service_dir=sdir, recover=False, spec=spec,
        plan_cache=False, result_cache=False, slos=SLOS,
    ).start()
    for i in range(N):
        svc.submit(build(i), tenant="alpha")
    svc.wait_idle(timeout=600)  # parent SIGKILLs us mid-flood
else:
    svc = ComputeService(
        executor=AsyncPythonDagExecutor(), max_concurrent=1,
        service_dir=sdir, spec=spec,
        plan_cache=False, result_cache=False, slos=SLOS,
    ).start()
    try:
        folded_at_start = svc.stats_snapshot()["slo"]["alpha"]["events"]
        svc.wait_idle(timeout=300)  # recovery re-runs interrupted work
        row = svc.stats_snapshot()["slo"]["alpha"]
        print(json.dumps({{
            "folded_at_start": folded_at_start,
            "events": row["events"],
            "latency_bad": row["latency_bad"],
            "budget_remaining": row["budget_remaining"],
        }}), flush=True)
    finally:
        svc.close()
"""


@pytest.mark.chaos
def test_chaos_sigkill_budget_folds_durably_from_archive(tmp_path):
    """SIGKILL the service mid-flood with a tenant that burns budget on
    every request: the restarted service seeds its board from
    ``runs.jsonl`` (no reset), recovery re-runs only the interrupted
    requests (no double-count), and the final event count equals the
    archive's — one completion record per request, exactly."""
    n_requests = 6
    sdir = str(tmp_path / "svc")
    runs_path = os.path.join(sdir, "runs.jsonl")
    script = _KILL_SCRIPT.format(
        repo=REPO, work_dir=str(tmp_path), sdir=sdir, n_requests=n_requests,
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def completed_records():
        try:
            with open(runs_path, "rb") as f:
                lines = f.read().splitlines()
        except OSError:
            return []
        out = []
        for raw in lines:
            try:
                rec = json.loads(raw)
            except ValueError:
                continue
            if rec.get("kind") == "request" and rec.get("status") in (
                "completed", "failed",
            ):
                out.append(rec)
        return out

    proc = subprocess.Popen(
        [sys.executable, "-c", script, "run"], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
    )
    killed = False
    try:
        deadline = time.time() + 120
        while time.time() < deadline and proc.poll() is None:
            done = len(completed_records())
            if 1 <= done < n_requests:
                os.killpg(proc.pid, signal.SIGKILL)
                killed = True
                break
            time.sleep(0.02)
        proc.wait(timeout=30)
        assert killed, (
            f"flood drained before the kill landed (rc={proc.returncode}): "
            f"{proc.stderr.read()[-2000:]}"
        )
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait(timeout=30)

    pre_kill = len(completed_records())
    assert 1 <= pre_kill < n_requests

    out = subprocess.run(
        [sys.executable, "-c", script, "recover"], env=env,
        capture_output=True, text=True, timeout=400,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    report = json.loads(out.stdout.strip().splitlines()[-1])

    # the budget survived the SIGKILL: the board was seeded from the
    # archive BEFORE any recovered request re-ran
    assert report["folded_at_start"] == pre_kill
    # no reset: with a microsecond objective every folded event is bad
    assert report["budget_remaining"] == 0.0
    assert report["latency_bad"] == report["events"]
    # no double-count: every request contributed exactly one completion
    # record — the interrupted one wrote nothing pre-kill and exactly one
    # on its recovery re-run
    final_records = completed_records()
    assert report["events"] == len(final_records)
    assert len(final_records) == n_requests
    ids = [r["request_id"] for r in final_records]
    assert len(ids) == len(set(ids)), f"duplicate completion records: {ids}"
