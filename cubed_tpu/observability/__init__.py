"""Unified observability: span tracing, metrics, and byte accounting.

Every executor reports through one event stream (the ``Callback`` lifecycle
in ``runtime/types.py``); this package turns that stream into

- **traces**: :class:`TracingCallback` writes a Perfetto/chrome://tracing
  loadable ``trace.json`` with one span per task (op, chunk key, attempt,
  executor, peak memory) — see ``docs/observability.md``;
- **metrics**: a process-local :class:`MetricsRegistry`
  (:func:`get_registry`) of counters/gauges/histograms, snapshotted into
  ``ComputeEndEvent.executor_stats`` for every compute;
- **byte accounting**: the Zarr storage layer records per-store
  ``bytes_read`` / ``bytes_written``, attributed to the task that did the
  IO even across process boundaries (``accounting.task_scope``);
- **distributed traces**: :class:`TraceCollector` merges client spans,
  worker-shipped task sub-spans (storage IO, kernel time, verification),
  scheduler decisions and memory-guard samples into one clock-aligned
  Perfetto trace per compute, with a live straggler watch (``collect``);
- **correlated structured logs**: compute/op/chunk contextvars make every
  client, pool and fleet-worker log line attributable to its task
  (``logs``);
- **flight recorder**: :class:`FlightRecorder` bundles the merged trace,
  metrics, plan projections, decision timelines, alert timeline +
  time-series dump and last-N logs into a post-mortem directory readable
  by ``python -m cubed_tpu.diagnose`` (``flightrecorder``);
- **live telemetry**: a bounded :class:`TimeSeriesStore` sampled ~1s from
  the merged fleet view (``timeseries``), served as Prometheus
  ``/metrics`` + ``/healthz`` + ``/snapshot.json`` by a stdlib-HTTP
  thread armed via ``Spec(telemetry_port=...)`` /
  ``CUBED_TPU_TELEMETRY_PORT`` (``export``), watched by an
  :class:`AlertEngine` (``alerts``) and rendered live by
  ``python -m cubed_tpu.top``;
- **control-plane observability**: a per-task dispatch ledger (stamps +
  coordinator-side costs riding the task-stats channel, split into
  ``ready_wait`` vs ``dispatch_overhead`` by :func:`analyze`), the
  :class:`DispatchProfiler` — a bounded ``sys._current_frames()``
  sampling profiler over the coordinator threads armed via
  ``Spec(dispatch_profile=True)`` / ``CUBED_TPU_DISPATCH_PROFILE`` —
  and the dispatch-saturation flight deck (``dispatch_utilization`` /
  ``dispatch_capacity_estimate`` gauges, the ``dispatch_saturation``
  alert, the ``top`` DISPATCH panel) (``dispatchprofile``);
- **SLOs & run history**: a durable, bounded, torn-line-tolerant run
  archive (``runs.jsonl`` via ``Spec(run_history=...)`` / the service's
  ``service_dir``) records every compute/request outcome with its
  ``analyze()`` bucket decomposition (``runhistory``); per-tenant
  :class:`SloSpec` objectives are evaluated into error budgets that
  survive restarts and multi-window burn rates (``slo``), alerted by
  ``slo_fast_burn`` / ``slo_slow_burn``, and cross-run regressions are
  attributed bucket-by-bucket by ``python -m cubed_tpu.regress`` /
  ``analyze(baseline=...)``;
- **compute analytics**: :func:`explain` / ``plan.explain()`` renders the
  finalized plan's predictions pre-execution (task counts, projected vs
  allowed memory, predicted IO, fusion + scheduler/barrier decisions;
  ``python -m cubed_tpu.explain``), and :func:`analyze` extracts the
  dependency-weighted **critical path** and a wall-clock attribution
  breakdown (kernel / storage / peer / queue wait / retry / straggler
  excess) from a flight-recorder bundle (``analytics``;
  ``python -m cubed_tpu.diagnose <bundle> --analyze``).
"""

from .accounting import (  # noqa: F401
    record_bytes_read,
    record_bytes_written,
    record_virtual_read,
    reset_store_totals,
    scope_span,
    store_totals,
    task_scope,
)
from .analytics import (  # noqa: F401
    AnalysisReport,
    ExplainReport,
    analyze,
    explain,
    regression_diff,
    render_regression,
)
from .callback import TracingCallback  # noqa: F401
from .collect import (  # noqa: F401
    TraceCollector,
    record_decision,
    record_sample,
)
from .alerts import (  # noqa: F401
    AlertEngine,
    AlertRule,
    BurnRateRule,
    DispatchSaturationRule,
    SloBurnRateRule,
    StallRule,
    ThresholdRule,
    default_rules,
)
from .runhistory import (  # noqa: F401
    RunHistory,
    find_baseline,
    load_runs,
)
from .slo import (  # noqa: F401
    SloBoard,
    SloSpec,
)
from .dispatchprofile import (  # noqa: F401
    DispatchProfiler,
    profile_enabled,
    profile_for,
)
from .events import EventLogCallback, PlanRow  # noqa: F401
from .export import (  # noqa: F401
    TelemetryRuntime,
    prometheus_text,
    resolve_port,
)
from .flightrecorder import FlightRecorder, load_bundle  # noqa: F401
from .metrics import (  # noqa: F401
    MetricsRegistry,
    get_registry,
    merge_snapshots,
)
from .timeseries import (  # noqa: F401
    TelemetrySampler,
    TimeSeriesStore,
)
from .tracer import Tracer  # noqa: F401
