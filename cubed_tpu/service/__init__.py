"""Multi-tenant compute service: the persistent front door over one fleet.

See ``docs/service.md`` for the API, tenancy/quota model, caching and
invalidation rules, and the durability contract.
"""

from .admission import FairShareArbiter, ServiceAdmission  # noqa: F401
from .cache import (  # noqa: F401
    PlanCache,
    ResultCache,
    input_state_digest,
    structural_fingerprint,
)
from .overload import (  # noqa: F401
    CostEstimator,
    DeadlineInfeasibleError,
    OverloadController,
    OverloadPolicy,
    ServiceOverloadedError,
    TenantBreaker,
)
from .service import (  # noqa: F401
    ComputeService,
    RequestCancelledError,
    RequestHandle,
    ServiceConfig,
    TenantThrottledError,
)

__all__ = [
    "ComputeService",
    "ServiceConfig",
    "RequestHandle",
    "RequestCancelledError",
    "TenantThrottledError",
    "ServiceOverloadedError",
    "DeadlineInfeasibleError",
    "OverloadController",
    "OverloadPolicy",
    "TenantBreaker",
    "CostEstimator",
    "FairShareArbiter",
    "ServiceAdmission",
    "PlanCache",
    "ResultCache",
    "structural_fingerprint",
    "input_state_digest",
]
