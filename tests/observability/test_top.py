"""``python -m cubed_tpu.top`` dashboard tests: frame rendering from a
canned snapshot, and one full refresh against a live endpoint."""

from __future__ import annotations

import time

from cubed_tpu import top
from cubed_tpu.observability.export import TelemetryRuntime


def _snapshot(ts=None):
    ts = ts or time.time()
    return {
        "ts": ts,
        "metrics": {"tasks_completed": 42, "alerts_fired": 2},
        "fleet": {
            "workers_live": 2,
            "workers_pressured": 1,
            "workers_disconnected": 0,
            "workers": {
                "local-0": {
                    "alive": True, "connected": True, "pressured": False,
                    "nthreads": 2, "outstanding": 1, "tasks_sent": 20,
                    "rss": 150 * 2**20,
                    "peer_cache": {"bytes": 32 * 2**20},
                    "clock_offset": 0.002,
                    "metrics": {"peer_hits": 9, "peer_misses": 1},
                },
                "local-1": {
                    "alive": True, "connected": False, "pressured": True,
                    "nthreads": 2, "outstanding": 0, "tasks_sent": 22,
                    "rss": None, "peer_cache": None, "clock_offset": None,
                    "metrics": None,
                },
            },
        },
        "computes": [
            {"compute_id": "c-done", "tasks_done": 8, "tasks_total": 8,
             "status": "succeeded", "started_at": ts - 60,
             "ended_at": ts - 30},
            {"compute_id": "c-live", "tasks_done": 30, "tasks_total": 100,
             "status": "running", "started_at": ts - 10, "ended_at": None},
        ],
        "alerts": [
            {"ts": ts - 5, "rule": "fleet_memory_pressure",
             "severity": "critical", "metric": "fleet_pressured_fraction",
             "value": 0.5, "threshold": 0.5},
        ],
        "alerts_active": ["fleet_memory_pressure"],
        "series": [
            {"name": "compute_tasks_done", "labels": {"compute": "c-live"},
             "points": [[ts - 10, 0], [ts - 5, 15], [ts, 30]]},
        ],
    }


def test_render_fleet_table_progress_and_alerts():
    frame = top.render(_snapshot())
    # fleet table: both workers, state flags, RSS, load, hit rate
    assert "local-0" in frame and "local-1" in frame
    assert "disconnected" in frame  # local-1's state (pressured is masked)
    assert "157.3 MB" in frame  # 150 MiB rendered decimal by memory_repr
    assert "1/2" in frame  # outstanding/threads
    assert "90%" in frame  # peer cache hit rate 9/(9+1)
    # compute progress: fraction, bar, rate + ETA from the series
    assert "c-live" in frame and "30/100" in frame and "30%" in frame
    assert "tasks/s" in frame and "ETA" in frame
    assert "succeeded" in frame  # the finished compute stays listed
    # alerts: the firing with its active flag
    assert "fleet_memory_pressure" in frame and "critical" in frame
    assert "ALERTS (1 active)" in frame


def test_render_tenant_panel():
    """The multi-tenant service section: one row per tenant with weight,
    queue/run/done counts, cache hit rate and throttle state."""
    snap = _snapshot()
    snap["service"] = {
        "tenants": {
            "gold": {
                "weight": 2.0, "queued": 3, "running": 1, "completed": 10,
                "failed": 0, "throttled": 0, "plan_cache_hits": 2,
                "result_cache_hits": 3,
            },
            "free": {
                "weight": 1.0, "queued": 7, "running": 0, "completed": 4,
                "failed": 1, "throttled": 5, "plan_cache_hits": 0,
                "result_cache_hits": 0,
            },
        },
        "queue_depth": 10, "running": 1, "slots": 2, "throttling": True,
    }
    frame = top.render(snap)
    assert "TENANTS" in frame and "THROTTLING" in frame
    assert "gold" in frame and "free" in frame
    assert "50%" in frame  # gold's cache hit rate: (2+3)/10
    # a service-less snapshot renders no tenant panel at all
    assert "TENANTS" not in top.render(_snapshot())


def test_render_cost_panel():
    """Tenant rows carrying cost sub-dicts render the COST panel."""
    snap = _snapshot()
    snap["service"] = {
        "tenants": {
            "gold": {
                "weight": 2.0, "queued": 0, "running": 0, "completed": 5,
                "failed": 0, "throttled": 0, "plan_cache_hits": 0,
                "result_cache_hits": 0,
                "cost": {
                    "task_seconds": 12.5, "bytes_read": 1_000_000,
                    "bytes_written": 2_000_000, "peer_bytes": 0,
                    "retries": 1,
                },
            },
        },
        "queue_depth": 0, "running": 0, "slots": 2, "throttling": False,
    }
    frame = top.render(snap)
    assert "COST" in frame and "TASK-SEC" in frame
    assert "12.50" in frame  # gold's task-seconds
    assert "2.0 MB" in frame  # bytes written
    # tenant rows WITHOUT cost dicts render no COST panel (old snapshots)
    del snap["service"]["tenants"]["gold"]["cost"]
    assert "COST" not in top.render(snap)


def test_main_snapshot_offline_mode(capsys):
    """--snapshot renders a saved /snapshot.json with no live endpoint —
    the checked-in fixture covers fleet, tenants, cost, compute progress
    and alerts in one frame."""
    import os

    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "snapshot.json"
    )
    rc = top.main(["--snapshot", fixture])
    assert rc == 0
    out = capsys.readouterr().out
    assert "cubed_tpu.top" in out
    assert "local-0" in out and "local-1" in out
    assert "TENANTS" in out and "gold" in out and "free" in out
    assert "COST" in out and "184.25" in out
    assert "c-8e3fcfe019" in out and "1620/3240" in out
    assert "fleet_memory_pressure" in out


def test_main_snapshot_missing_file(capsys):
    assert top.main(["--snapshot", "/nonexistent/snap.json"]) == 2
    assert "cannot read snapshot" in capsys.readouterr().err


def test_render_empty_snapshot_is_graceful():
    frame = top.render({"ts": time.time(), "metrics": {}, "fleet": {},
                        "computes": [], "alerts": [], "series": []})
    assert "no live workers" in frame
    assert "(none tracked)" in frame
    assert "(none fired)" in frame


def test_render_eta_formats():
    assert top._fmt_eta(None) == "-"
    assert top._fmt_eta(30) == "30s"
    assert top._fmt_eta(90) == "1m30s"
    assert top._fmt_eta(4000) == "1h06m"


def test_series_rate_uses_trailing_window():
    snap = _snapshot(ts=1000.0)
    rate = top._series_rate(
        snap, "compute_tasks_done", {"compute": "c-live"}, window_s=30.0
    )
    assert rate == 3.0  # 30 tasks over 10s
    assert top._series_rate(snap, "missing", {}) is None


def test_main_once_renders_from_live_endpoint(capsys):
    rt = TelemetryRuntime(port=0)
    rt.start()
    try:
        rt.sampler.sample_once()
        rc = top.main([f"127.0.0.1:{rt.port}", "--once"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "cubed_tpu.top" in out
        assert "WORKER" in out and "COMPUTES" in out and "ALERTS" in out
    finally:
        rt.stop()


def test_main_unreachable_endpoint_fails_with_hint(capsys):
    rc = top.main(["127.0.0.1:9", "--once"])  # port 9: discard, nothing there
    assert rc == 2
    err = capsys.readouterr().err
    assert "cannot reach telemetry endpoint" in err
    assert "CUBED_TPU_TELEMETRY_PORT" in err
