"""Peer-to-peer chunk transfer (runtime/transfer.py).

Unit coverage for the worker chunk cache (byte budget, LRU, pressure
eviction), the coordinator's chunk-location registry, and the
locality-placement scoring — plus real-fleet proofs: the peer data plane
produces bitwise-identical results with substantial store-read savings,
and every chaos shape (seeded drop/corrupt/delay, a serving peer resetting
mid-fetch, a producer hard-killed mid-compute) resolves to a transparent
store fallback that draws zero retry budget.
"""

from __future__ import annotations

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime import faults, transfer
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor


# ----------------------------------------------------------------------
# unit: the chunk cache
# ----------------------------------------------------------------------


def test_chunk_cache_respects_byte_budget_lru():
    cache = transfer.ChunkCache(max_bytes=100)
    assert cache.put("s", "0.0", b"x" * 40)
    assert cache.put("s", "0.1", b"x" * 40)
    assert cache.get("s", "0.0") is not None
    # 0.1 is now LRU; inserting 40 more evicts it, not the just-touched 0.0
    assert cache.put("s", "0.2", b"x" * 40)
    assert cache.get("s", "0.1") is None
    assert cache.get("s", "0.0") is not None
    assert cache.get("s", "0.2") is not None
    assert cache.bytes <= 100
    assert cache.evictions == 1
    # an entry bigger than the whole budget is rejected outright
    assert not cache.put("s", "big", b"x" * 101)
    assert cache.get("s", "big") is None
    # re-putting an existing key replaces, never double-counts
    assert cache.put("s", "0.0", b"y" * 40)
    assert cache.bytes <= 100


def test_chunk_cache_pressure_eviction():
    cache = transfer.ChunkCache(max_bytes=100)
    for i in range(5):
        cache.put("s", f"0.{i}", b"x" * 20)
    assert cache.bytes == 100
    # ok pressure: nothing happens
    assert cache.evict_for_pressure("ok") == 0
    # soft pressure: down to half the budget
    assert cache.evict_for_pressure("soft") > 0
    assert cache.bytes <= 50
    # hard pressure: the cache empties entirely
    cache.put("s", "9.9", b"x" * 20)
    assert cache.evict_for_pressure("hard") > 0
    assert cache.bytes == 0 and cache.stats()["entries"] == 0
    assert cache.pressure_evictions > 0


def test_chunk_cache_eviction_notify_drain():
    """Evicted keys accumulate for the heartbeat piggyback; a hard flush
    (or overflow) collapses into one forget-everything marker."""
    cache = transfer.ChunkCache(max_bytes=40)
    cache.put("s", "0.0", b"x" * 20)
    cache.put("s", "0.1", b"x" * 20)
    cache.put("s", "0.2", b"x" * 20)  # evicts 0.0
    evicted, flush = cache.drain_evictions()
    assert evicted == [("s", "0.0")] and not flush
    # drained: a second call returns nothing
    assert cache.drain_evictions() == ([], False)
    # hard pressure = full flush marker, no per-key list
    cache.evict_for_pressure("hard")
    evicted, flush = cache.drain_evictions()
    assert flush and evicted == []


def test_location_registry_remove_respects_ownership():
    """An eviction notice removes only entries still owned by that worker:
    a newer producer's entry survives a stale notice."""
    reg = transfer.ChunkLocationRegistry()
    reg.record("w1", [("s", "0.0", 10), ("s", "0.1", 10)])
    reg.record("w2", [("s", "0.0", 10)])  # w2 re-produced 0.0
    assert reg.remove("w1", [("s", "0.0"), ("s", "0.1"), ("s", "bad")]) == 1
    assert reg.locate("s", "0.0") == "w2"  # w1's stale notice didn't win
    assert reg.locate("s", "0.1") is None


# ----------------------------------------------------------------------
# unit: the location registry + placement scoring
# ----------------------------------------------------------------------


def test_location_registry_record_locate_drop():
    reg = transfer.ChunkLocationRegistry(max_entries=8)
    reg.record("w1", [("s", "0.0", 100), ("s", "0.1", 100)])
    reg.record("w2", [("s", "1.0", 200)])
    assert reg.locate("s", "0.0") == "w1"
    assert reg.locate("s", "1.0") == "w2"
    assert reg.locate("s", "9.9") is None
    # a retry/backup re-produced a chunk elsewhere: newest producer wins
    reg.record("w2", [("s", "0.0", 100)])
    assert reg.locate("s", "0.0") == "w2"
    resident = reg.resident_bytes([("s", "0.0"), ("s", "0.1"), ("s", "1.0")])
    assert resident == {"w2": 300, "w1": 100}
    # a departed worker's entries drop eagerly
    reg.drop_worker("w2")
    assert reg.locate("s", "0.0") is None
    assert reg.locate("s", "1.0") is None
    assert reg.locate("s", "0.1") == "w1"
    # malformed advertisements are ignored, never raise
    reg.record("w1", [("s",), None, ("s", "2.0", "nan")])
    assert reg.locate("s", "2.0") is None


def test_location_registry_bounded():
    reg = transfer.ChunkLocationRegistry(max_entries=4)
    reg.record("w1", [("s", f"0.{i}", 10) for i in range(10)])
    assert reg.stats()["entries"] == 4
    assert reg.locate("s", "0.9") == "w1"  # newest kept
    assert reg.locate("s", "0.0") is None  # oldest evicted


class _FakeWorker:
    def __init__(self, name, load):
        self.name = name
        self._load = load


def test_pick_worker_by_locality():
    load_of = lambda w: w._load  # noqa: E731
    a, b, c = _FakeWorker("a", 0.0), _FakeWorker("b", 1.0), _FakeWorker("c", 9.0)
    # most resident bytes wins while inside the load slack
    got = transfer.pick_worker_by_locality(
        [a, b, c], {"a": 100, "b": 500}, load_of
    )
    assert got is b
    # a best-scoring worker too far above the least-loaded is passed over
    got = transfer.pick_worker_by_locality([a, b, c], {"c": 500}, load_of)
    assert got is None
    # no resident bytes anywhere: locality has no opinion
    assert transfer.pick_worker_by_locality([a, b], {}, load_of) is None


def test_peer_config_wire_roundtrip():
    cfg = transfer.PeerConfig(enabled=True, fetch_timeout_s=0.5)
    armed = transfer.arm_from_wire(cfg.to_wire())
    assert armed is not None and armed.enabled
    assert armed.fetch_timeout_s == 0.5
    assert transfer.arm_from_wire(None) is None
    assert transfer.armed_config() is None
    # client side: wire_config is None unless a compute armed it
    assert transfer.wire_config() is None
    with transfer.client_scoped(True):
        raw = transfer.wire_config()
        assert raw is not None
        assert transfer.PeerConfig.from_dict(__import__("json").loads(raw)).enabled
    assert transfer.wire_config() is None


# ----------------------------------------------------------------------
# fleet integration
# ----------------------------------------------------------------------


def _deep_chain(spec, depth=3, n=16, chunk=4):
    an = np.arange(n * n, dtype=np.float64).reshape(n, n)
    a = ct.from_array(an, chunks=(chunk, chunk), spec=spec)
    r = a
    for _ in range(depth):
        r = ct.map_blocks(_bump, r, dtype=np.float64)
    return an, r


def _bump(x):
    return x + 1.0


def test_peer_transfer_end_to_end_bitwise_and_saves_store_reads(tmp_path):
    """The tentpole proof: a deep chain under dataflow + peer transfer is
    bitwise-identical to numpy, serves inter-op reads from worker caches
    (locality placement makes the local hit the common case), and records
    the saved store bytes."""
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", scheduler="dataflow",
        peer_transfer=True,
    )
    an, r = _deep_chain(spec, depth=3)
    ex = DistributedDagExecutor(n_local_workers=2)
    reg = get_registry()
    before = reg.snapshot()
    try:
        result = r.compute(executor=ex, optimize_graph=False)
        np.testing.assert_array_equal(result, an + 3.0)
        coord_stats = ex._coordinator.stats_snapshot()
    finally:
        ex.close()
    delta = reg.snapshot_delta(before)
    assert delta.get("peer_hits", 0) > 0, delta
    assert delta.get("store_read_bytes_saved", 0) > 0, delta
    assert delta.get("placement_locality_hits", 0) > 0, delta
    # fallbacks require injected faults; a healthy fleet has none
    assert delta.get("peer_fetch_fallbacks", 0) == 0, delta
    # producers advertised locations over the sequenced result frames
    assert coord_stats["chunk_locations"]["recorded"] > 0, coord_stats


def test_peer_transfer_remote_fetch_and_store_only_parity(tmp_path):
    """A reduction forces cross-worker reads: some bytes move over the
    direct worker→worker connection (locate RPC + framed fetch), and the
    result matches the store-only data plane bitwise."""
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    vals = {}
    reg = get_registry()
    deltas = {}
    for mode in (False, True):
        spec = ct.Spec(
            work_dir=str(tmp_path / f"peer-{mode}"), allowed_mem="500MB",
            scheduler="dataflow",
        )
        a = ct.from_array(an, chunks=(4, 4), spec=spec)
        r = xp.sum(ct.map_blocks(_bump, a, dtype=np.float64))
        ex = DistributedDagExecutor(n_local_workers=2, peer_transfer=mode)
        before = reg.snapshot()
        try:
            vals[mode] = float(r.compute(executor=ex, optimize_graph=False))
        finally:
            ex.close()
        deltas[mode] = reg.snapshot_delta(before)
    assert vals[True] == vals[False] == float((an + 1.0).sum())
    assert deltas[True].get("peer_hits", 0) > 0, deltas[True]
    # the reduce tree reads chunks produced on the OTHER worker too
    assert deltas[True].get("peer_locate_requests", 0) > 0, deltas[True]
    # store-only keeps the historical data plane: no peer counters at all
    assert deltas[False].get("peer_hits", 0) == 0, deltas[False]
    assert deltas[False].get("store_read_bytes_saved", 0) == 0


def test_peer_cache_eviction_transparently_falls_back_to_store(
    tmp_path, monkeypatch
):
    """Satellite: with a cache budget smaller than one chunk nothing is
    ever peer-servable — every read falls back to the store read path and
    the result is still bitwise-correct (the fallback contract, eviction
    edition)."""
    monkeypatch.setenv(transfer.CACHE_BYTES_ENV_VAR, "64")  # < one chunk
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", scheduler="dataflow",
        peer_transfer=True,
    )
    an, r = _deep_chain(spec, depth=2)
    ex = DistributedDagExecutor(n_local_workers=2)
    reg = get_registry()
    before = reg.snapshot()
    try:
        result = r.compute(executor=ex, optimize_graph=False)
        np.testing.assert_array_equal(result, an + 2.0)
    finally:
        ex.close()
    delta = reg.snapshot_delta(before)
    # nothing fit the budget: no advertisements, so reads miss and go to
    # the store — zero peer hits, zero failures, correct bytes
    assert delta.get("peer_hits", 0) == 0, delta
    assert delta.get("peer_misses", 0) > 0, delta
    assert delta.get("task_retries", 0) == 0, delta


# ----------------------------------------------------------------------
# chaos: every peer-path failure resolves to a store fallback
# ----------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_peer_fetch_drop_corrupt_delay_reset_bitwise(
    tmp_path, monkeypatch
):
    """Seeded drop (vanished reply), corrupt (CRC must catch), delay, and
    serve-side reset (peer dies mid-fetch, as the reader sees it): the
    compute stays bitwise-correct, every injected failure lands as a
    transparent store fallback, and NO retry budget is drawn."""
    monkeypatch.setenv(
        faults.FAULTS_ENV_VAR,
        faults.FaultConfig(
            seed=7,
            peer_drop_rate=0.3,
            peer_corrupt_rate=0.3,
            peer_delay_rate=0.2,
            peer_delay_s=0.01,
            peer_reset_rate=0.2,
        ).to_env_json(),
    )
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", scheduler="dataflow",
        peer_transfer=True,
    )
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    r = ct.map_blocks(_bump, a, dtype=np.float64)
    r = xp.sum(ct.map_blocks(_bump, r, dtype=np.float64))
    ex = DistributedDagExecutor(n_local_workers=2)
    reg = get_registry()
    before = reg.snapshot()
    try:
        val = float(r.compute(executor=ex, optimize_graph=False))
    finally:
        ex.close()
    assert val == float((an + 2.0).sum())
    delta = reg.snapshot_delta(before)
    assert delta.get("peer_fetch_fallbacks", 0) > 0, delta
    # the contract the whole design hangs on: fallbacks are invisible to
    # the retry machinery — zero user-visible retry-budget draw
    assert delta.get("task_retries", 0) == 0, delta
    assert delta.get("worker_loss_requeues", 0) == 0, delta


@pytest.mark.chaos
def test_chaos_peer_death_mid_fetch_falls_back(tmp_path, monkeypatch):
    """A producing worker hard-killed mid-compute: its advertised chunks
    become unreachable (dead peer server, registry entries dropped with
    the worker) and consumers transparently read the store instead — the
    result is bitwise-correct, with worker loss costing only the usual
    free requeues."""
    monkeypatch.setenv(
        faults.FAULTS_ENV_VAR,
        faults.FaultConfig(
            seed=11,
            worker_crash_names=("local-0",),
            worker_crash_after_tasks=3,
        ).to_env_json(),
    )
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", scheduler="dataflow",
        peer_transfer=True,
    )
    an, r = _deep_chain(spec, depth=3)
    ex = DistributedDagExecutor(n_local_workers=2)
    reg = get_registry()
    before = reg.snapshot()
    try:
        result = r.compute(executor=ex, optimize_graph=False)
        np.testing.assert_array_equal(result, an + 3.0)
        assert ex._coordinator.stats["workers_lost"] >= 1
    finally:
        ex.close()
    delta = reg.snapshot_delta(before)
    # the peer path was exercised AND the compute survived the producer's
    # death; any reads pointed at the corpse resolved via the store
    assert delta.get("peer_hits", 0) + delta.get("peer_misses", 0) > 0, delta
    assert delta.get("task_retries", 0) == 0, delta
