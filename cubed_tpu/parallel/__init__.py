from .attention import attention  # noqa: F401
from .mesh import factorized_mesh, make_mesh, reshard, sharding_for_chunks  # noqa: F401
from .multihost import dcn_mesh, host_chunk_assignment, local_chunks  # noqa: F401
