"""All ~60 Array-API elementwise functions: dtype-category check, then
``elemwise(nxp.<f>)``. Reference parity:
cubed/array_api/elementwise_functions.py (393 LoC)."""

from __future__ import annotations

import math

import numpy as np

from ..backend_array_api import nxp
from ..core.ops import elemwise
from .dtypes import (
    _boolean_dtypes,
    _complex_floating_dtypes,
    _floating_dtypes,
    _integer_dtypes,
    _integer_or_boolean_dtypes,
    _numeric_dtypes,
    _real_floating_dtypes,
    _real_numeric_dtypes,
    complex64,
    complex128,
    float32,
    float64,
    promote_types,
)


def _check(x, dtypes, fname):
    if x.dtype not in dtypes:
        raise TypeError(f"Unsupported dtype {x.dtype} in {fname}")


def _unary(nxp_func, x, dtypes, fname, result_dtype=None):
    _check(x, dtypes, fname)
    return elemwise(nxp_func, x, dtype=result_dtype or x.dtype)


def _promote_pair(x1, x2):
    """Promote a Python scalar operand to a 0-d array of the other's kind."""
    from ..core.array import CoreArray

    if isinstance(x1, CoreArray) and not isinstance(x2, CoreArray):
        x2 = x1._promote_scalar(x2)
        if x2 is None:
            raise TypeError("unsupported operand type")
    elif isinstance(x2, CoreArray) and not isinstance(x1, CoreArray):
        x1 = x2._promote_scalar(x1)
        if x1 is None:
            raise TypeError("unsupported operand type")
    return x1, x2


def _binary(nxp_func, x1, x2, dtypes, fname, result_dtype=None):
    x1, x2 = _promote_pair(x1, x2)
    _check(x1, dtypes, fname)
    _check(x2, dtypes, fname)
    dtype = result_dtype or promote_types(x1.dtype, x2.dtype)
    return elemwise(nxp_func, x1, x2, dtype=dtype)


def _float_of(dtype):
    if dtype == complex64:
        return float32
    if dtype == complex128:
        return float64
    return dtype


def abs(x, /):  # noqa: A001
    _check(x, _numeric_dtypes, "abs")
    return elemwise(nxp.abs, x, dtype=_float_of(x.dtype))


def acos(x, /):
    return _unary(nxp.acos, x, _floating_dtypes, "acos")


def acosh(x, /):
    return _unary(nxp.acosh, x, _floating_dtypes, "acosh")


def add(x1, x2, /):
    return _binary(nxp.add, x1, x2, _numeric_dtypes, "add")


def asin(x, /):
    return _unary(nxp.asin, x, _floating_dtypes, "asin")


def asinh(x, /):
    return _unary(nxp.asinh, x, _floating_dtypes, "asinh")


def atan(x, /):
    return _unary(nxp.atan, x, _floating_dtypes, "atan")


def atan2(x1, x2, /):
    return _binary(nxp.atan2, x1, x2, _real_floating_dtypes, "atan2")


def atanh(x, /):
    return _unary(nxp.atanh, x, _floating_dtypes, "atanh")


def bitwise_and(x1, x2, /):
    return _binary(nxp.bitwise_and, x1, x2, _integer_or_boolean_dtypes, "bitwise_and")


def bitwise_invert(x, /):
    return _unary(nxp.bitwise_invert, x, _integer_or_boolean_dtypes, "bitwise_invert")


def bitwise_left_shift(x1, x2, /):
    return _binary(nxp.bitwise_left_shift, x1, x2, _integer_dtypes, "bitwise_left_shift")


def bitwise_or(x1, x2, /):
    return _binary(nxp.bitwise_or, x1, x2, _integer_or_boolean_dtypes, "bitwise_or")


def bitwise_right_shift(x1, x2, /):
    return _binary(nxp.bitwise_right_shift, x1, x2, _integer_dtypes, "bitwise_right_shift")


def bitwise_xor(x1, x2, /):
    return _binary(nxp.bitwise_xor, x1, x2, _integer_or_boolean_dtypes, "bitwise_xor")


def ceil(x, /):
    _check(x, _real_numeric_dtypes, "ceil")
    if x.dtype in _integer_dtypes:
        return x
    return elemwise(nxp.ceil, x, dtype=x.dtype)


def conj(x, /):
    return _unary(nxp.conj, x, _numeric_dtypes, "conj")


def cos(x, /):
    return _unary(nxp.cos, x, _floating_dtypes, "cos")


def cosh(x, /):
    return _unary(nxp.cosh, x, _floating_dtypes, "cosh")


def divide(x1, x2, /):
    return _binary(nxp.divide, x1, x2, _floating_dtypes, "divide")


def equal(x1, x2, /):
    x1, x2 = _promote_pair(x1, x2)
    return elemwise(nxp.equal, x1, x2, dtype=np.dtype(np.bool_))


def exp(x, /):
    return _unary(nxp.exp, x, _floating_dtypes, "exp")


def expm1(x, /):
    return _unary(nxp.expm1, x, _floating_dtypes, "expm1")


def floor(x, /):
    _check(x, _real_numeric_dtypes, "floor")
    if x.dtype in _integer_dtypes:
        return x
    return elemwise(nxp.floor, x, dtype=x.dtype)


def floor_divide(x1, x2, /):
    return _binary(nxp.floor_divide, x1, x2, _real_numeric_dtypes, "floor_divide")


def greater(x1, x2, /):
    return _binary(
        nxp.greater, x1, x2, _real_numeric_dtypes, "greater", result_dtype=np.dtype(np.bool_)
    )


def greater_equal(x1, x2, /):
    return _binary(
        nxp.greater_equal, x1, x2, _real_numeric_dtypes, "greater_equal",
        result_dtype=np.dtype(np.bool_),
    )


def imag(x, /):
    _check(x, _complex_floating_dtypes, "imag")
    return elemwise(nxp.imag, x, dtype=_float_of(x.dtype))


def isfinite(x, /):
    _check(x, _numeric_dtypes, "isfinite")
    return elemwise(nxp.isfinite, x, dtype=np.dtype(np.bool_))


def isinf(x, /):
    _check(x, _numeric_dtypes, "isinf")
    return elemwise(nxp.isinf, x, dtype=np.dtype(np.bool_))


def isnan(x, /):
    _check(x, _numeric_dtypes, "isnan")
    return elemwise(nxp.isnan, x, dtype=np.dtype(np.bool_))


def less(x1, x2, /):
    return _binary(
        nxp.less, x1, x2, _real_numeric_dtypes, "less", result_dtype=np.dtype(np.bool_)
    )


def less_equal(x1, x2, /):
    return _binary(
        nxp.less_equal, x1, x2, _real_numeric_dtypes, "less_equal",
        result_dtype=np.dtype(np.bool_),
    )


def log(x, /):
    return _unary(nxp.log, x, _floating_dtypes, "log")


def log1p(x, /):
    return _unary(nxp.log1p, x, _floating_dtypes, "log1p")


def log2(x, /):
    return _unary(nxp.log2, x, _floating_dtypes, "log2")


def log10(x, /):
    return _unary(nxp.log10, x, _floating_dtypes, "log10")


def logaddexp(x1, x2, /):
    return _binary(nxp.logaddexp, x1, x2, _real_floating_dtypes, "logaddexp")


def logical_and(x1, x2, /):
    return _binary(nxp.logical_and, x1, x2, _boolean_dtypes, "logical_and")


def logical_not(x, /):
    return _unary(nxp.logical_not, x, _boolean_dtypes, "logical_not")


def logical_or(x1, x2, /):
    return _binary(nxp.logical_or, x1, x2, _boolean_dtypes, "logical_or")


def logical_xor(x1, x2, /):
    return _binary(nxp.logical_xor, x1, x2, _boolean_dtypes, "logical_xor")


def multiply(x1, x2, /):
    return _binary(nxp.multiply, x1, x2, _numeric_dtypes, "multiply")


def negative(x, /):
    return _unary(nxp.negative, x, _numeric_dtypes, "negative")


def not_equal(x1, x2, /):
    x1, x2 = _promote_pair(x1, x2)
    return elemwise(nxp.not_equal, x1, x2, dtype=np.dtype(np.bool_))


def positive(x, /):
    return _unary(nxp.positive, x, _numeric_dtypes, "positive")


def pow(x1, x2, /):  # noqa: A001
    return _binary(nxp.pow, x1, x2, _numeric_dtypes, "pow")


def real(x, /):
    _check(x, _complex_floating_dtypes, "real")
    return elemwise(nxp.real, x, dtype=_float_of(x.dtype))


def remainder(x1, x2, /):
    return _binary(nxp.remainder, x1, x2, _real_numeric_dtypes, "remainder")


def round(x, /):  # noqa: A001
    _check(x, _numeric_dtypes, "round")
    if x.dtype in _integer_dtypes:
        return x
    return elemwise(nxp.round, x, dtype=x.dtype)


def sign(x, /):
    return _unary(nxp.sign, x, _numeric_dtypes, "sign")


def sin(x, /):
    return _unary(nxp.sin, x, _floating_dtypes, "sin")


def sinh(x, /):
    return _unary(nxp.sinh, x, _floating_dtypes, "sinh")


def sqrt(x, /):
    return _unary(nxp.sqrt, x, _floating_dtypes, "sqrt")


def square(x, /):
    return _unary(nxp.square, x, _numeric_dtypes, "square")


def subtract(x1, x2, /):
    return _binary(nxp.subtract, x1, x2, _numeric_dtypes, "subtract")


def tan(x, /):
    return _unary(nxp.tan, x, _floating_dtypes, "tan")


def tanh(x, /):
    return _unary(nxp.tanh, x, _floating_dtypes, "tanh")


def trunc(x, /):
    _check(x, _real_numeric_dtypes, "trunc")
    if x.dtype in _integer_dtypes:
        return x
    return elemwise(nxp.trunc, x, dtype=x.dtype)


# -- 2023.12 additions (beyond the reference's 2022.12 surface) ------------


def maximum(x1, x2, /):
    return _binary(nxp.maximum, x1, x2, _real_numeric_dtypes, "maximum")


def minimum(x1, x2, /):
    return _binary(nxp.minimum, x1, x2, _real_numeric_dtypes, "minimum")


def hypot(x1, x2, /):
    return _binary(nxp.hypot, x1, x2, _real_floating_dtypes, "hypot")


def copysign(x1, x2, /):
    return _binary(nxp.copysign, x1, x2, _real_floating_dtypes, "copysign")


def signbit(x, /):
    from .dtypes import bool as _bool

    return _unary(nxp.signbit, x, _real_floating_dtypes, "signbit",
                  result_dtype=_bool)


def nextafter(x1, x2, /):
    """2024.12 ``nextafter`` (the reference stops at 2022.12)."""
    return _binary(nxp.nextafter, x1, x2, _real_floating_dtypes, "nextafter")


def reciprocal(x, /):
    """2024.12 ``reciprocal`` (the reference stops at 2022.12)."""
    return _unary(nxp.reciprocal, x, _floating_dtypes, "reciprocal")


def clip(x, /, min=None, max=None):
    """2023.12 ``clip``: bounds are scalars or arrays, None = unbounded.

    Per spec, the result dtype is x's; bounds participate only by value."""
    _check(x, _real_numeric_dtypes, "clip")
    if min is None and max is None:
        return x  # spec: elements returned unchanged; no kernel needed
    from ..core.array import CoreArray

    args = [x]
    spec_parts = []
    for bound in (min, max):
        if bound is None:
            spec_parts.append(None)
        elif isinstance(bound, CoreArray):
            if bound.dtype not in _real_numeric_dtypes:
                raise TypeError("clip bounds must be real numeric")
            args.append(bound)
            spec_parts.append("array")
        elif isinstance(bound, (int, float, np.integer, np.floating)):
            # a float bound on an integer array would be cast to x.dtype in
            # the kernel (min=2.5 silently behaving as min=2; inf/nan have
            # no integer value at all); the raw-ndarray path already raises
            # for mixed kinds, so mirror it
            if x.dtype.kind in "iu":
                if isinstance(bound, (float, np.floating)) and not (
                    math.isfinite(bound) and float(bound) == int(bound)
                ):
                    raise TypeError(
                        "clip: float bound without an exact integer value "
                        f"on an integer array would truncate (got {bound!r} "
                        f"for {x.dtype})"
                    )
                info = np.iinfo(x.dtype)
                if not (info.min <= int(bound) <= info.max):
                    raise TypeError(
                        "clip: bound not representable in the array's "
                        f"dtype would wrap (got {bound!r} for {x.dtype}, "
                        f"valid range [{info.min}, {info.max}])"
                    )
            spec_parts.append(bound)
        else:
            # raw ndarrays/lists would bake into the kernel as per-BLOCK
            # constants — silently wrong on multi-chunk grids
            raise TypeError(
                "clip bounds must be None, real scalars, or cubed arrays; "
                f"got {type(bound).__name__} (wrap with from_array/asarray)"
            )

    lo_spec, hi_spec = spec_parts

    def _clip(a, *bounds):
        bounds = list(bounds)
        lo = bounds.pop(0) if lo_spec == "array" else lo_spec
        hi = bounds.pop(0) if hi_spec == "array" else hi_spec
        out = a
        if lo is not None:
            out = nxp.maximum(out, nxp.asarray(lo, dtype=a.dtype))
        if hi is not None:
            out = nxp.minimum(out, nxp.asarray(hi, dtype=a.dtype))
        return out

    return elemwise(_clip, *args, dtype=x.dtype)
