"""Graph-optimization (fusion) tests: fusion shapes, task/array count deltas,
result correctness, fan-in limits and overrides.

Reference parity: cubed/tests/test_optimization.py (708 LoC, behavioral).
"""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.core.optimization import (
    fuse_all_optimize_dag,
    fuse_only_optimize_dag,
    multiple_inputs_optimize_dag,
    simple_optimize_dag,
)


def num_ops(plan, optimize_function=None, optimize_graph=True):
    finalized = plan._finalize(
        optimize_graph=optimize_graph, optimize_function=optimize_function
    )
    return finalized.num_ops()


def test_unary_chain_fuses(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.negative(b)
    d = xp.negative(c)
    unopt = num_ops(d.plan, optimize_graph=False)
    opt = num_ops(d.plan, optimize_function=simple_optimize_dag)
    assert opt < unopt
    np.testing.assert_allclose(
        d.compute(optimize_function=simple_optimize_dag), -an * 1.0 * -1 * -1
    )


def test_scalar_chain_fuses_with_multiple_inputs(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    d = xp.add(c, 1)
    unopt = num_ops(d.plan, optimize_graph=False)
    opt = num_ops(d.plan, optimize_function=multiple_inputs_optimize_dag)
    assert opt < unopt
    np.testing.assert_array_equal(d.compute(), np.full((6, 6), 4.0))


def test_binary_fuses_with_multiple_inputs(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = xp.add(xp.negative(a), xp.negative(b))
    unopt = num_ops(c.plan, optimize_graph=False)
    opt = num_ops(c.plan, optimize_function=multiple_inputs_optimize_dag)
    assert opt < unopt
    np.testing.assert_allclose(c.compute(), -an + -an)


def test_diamond(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.add(b, b)  # diamond: b consumed twice by the same op
    np.testing.assert_allclose(c.compute(), -an + -an)


def test_other_dependents_blocks_fusion(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.add(b, 1)
    # b is also a requested output: it must not be fused away
    rb, rc = ct.compute(b, c)
    np.testing.assert_allclose(rb, -an)
    np.testing.assert_allclose(rc, -an + 1)


def test_fuse_all(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    opt = num_ops(c.plan, optimize_function=fuse_all_optimize_dag)
    # create-arrays + single fused op
    assert opt <= 2
    np.testing.assert_array_equal(
        c.compute(optimize_function=fuse_all_optimize_dag), np.full((6, 6), 3.0)
    )


def test_fuse_only(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    # find the op node producing c
    dag = c.plan.dag
    target_op = [n for n in dag.predecessors(c.name)][0]
    opt_dag = fuse_only_optimize_dag(dag.copy(), only_fuse={target_op})
    assert target_op in opt_dag
    np.testing.assert_array_equal(
        c.compute(optimize_function=lambda d, array_names=None: fuse_only_optimize_dag(
            d, array_names=array_names, only_fuse={target_op})),
        np.full((6, 6), 3.0),
    )


def test_max_total_source_arrays_gate(spec):
    arrays = [xp.ones((4, 4), chunks=(2, 2), spec=spec) for _ in range(6)]
    s = arrays[0]
    for a in arrays[1:]:
        s = xp.add(s, a)
    # default gate (4) still yields a correct result
    np.testing.assert_array_equal(s.compute(), np.full((4, 4), 6.0))


def test_fusion_preserves_num_tasks(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    ntasks_unopt = b.plan.num_tasks(optimize_graph=False)
    ntasks_opt = b.plan.num_tasks(optimize_graph=True)
    assert ntasks_opt <= ntasks_unopt


def test_rechunk_not_fused(spec):
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = b.rechunk((3, 3))
    d = xp.add(c, 1)
    np.testing.assert_allclose(d.compute(), an + 2)


def test_fused_different_chunk_elementwise(spec):
    # inputs with different chunking unify (rechunk) then fuse downstream
    an = np.arange(36.0).reshape(6, 6)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.from_array(an, chunks=(6, 6), spec=spec)
    c = xp.add(a, b)
    np.testing.assert_allclose(c.compute(), an * 2)


# ---------------------------------------------------------------------------
# exact num_ops / num_tasks / num_arrays deltas per fusion shape (reference:
# cubed/tests/test_optimization.py:492-684 asserts the same count matrix)
# ---------------------------------------------------------------------------


def counts(arr, optimize_function=None, optimize_graph=True):
    plan = arr.plan
    return (
        num_ops(plan, optimize_function=optimize_function, optimize_graph=optimize_graph),
        plan.num_tasks(optimize_graph=optimize_graph, optimize_function=optimize_function),
        plan.num_arrays(optimize_graph=optimize_graph, optimize_function=optimize_function),
    )


def test_unary_chain_exact_counts(spec):
    # ones(virtual) -> neg -> neg -> neg: 3 blockwise ops collapse to 1
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    d = xp.negative(xp.negative(xp.negative(a)))
    ops_un, tasks_un, arrays_un = counts(d, optimize_graph=False)
    ops_opt, tasks_opt, arrays_opt = counts(d, optimize_function=multiple_inputs_optimize_dag)
    assert ops_un - ops_opt == 2       # two producer ops fused away
    assert arrays_un - arrays_opt == 2  # their intermediate arrays vanish
    assert tasks_opt == 10              # 3x3 block grid + the create-arrays task
    assert tasks_un == 30               # 9 per op + 3 create-arrays tasks
    np.testing.assert_array_equal(d.compute(), np.full((6, 6), -1.0))


def test_fan_in_exact_counts(spec):
    # 4 independent sources -> binary tree of adds: all fuse into one op
    arrs = [xp.ones((4, 4), chunks=(2, 2), spec=spec) for _ in range(4)]
    s = xp.add(xp.add(arrs[0], arrs[1]), xp.add(arrs[2], arrs[3]))
    ops_un, tasks_un, _ = counts(s, optimize_graph=False)
    ops_opt, tasks_opt, _ = counts(s, optimize_function=multiple_inputs_optimize_dag)
    assert tasks_un == 3 * 4 + 3  # 3 add ops x 4 blocks + 3 create-arrays tasks
    assert tasks_opt == 5     # one fused op over the 2x2 grid + create-arrays
    assert ops_un - ops_opt == 2
    np.testing.assert_array_equal(s.compute(), np.full((4, 4), 4.0))


def test_fan_in_gate_blocks_wide_fusion(spec):
    # 5 sources exceeds max_total_source_arrays=4: top add keeps distinct
    # predecessors under the default gate, fuses under an explicit override
    arrs = [xp.ones((4, 4), chunks=(2, 2), spec=spec) for _ in range(5)]
    s = xp.add(
        xp.add(xp.add(arrs[0], arrs[1]), xp.add(arrs[2], arrs[3])), arrs[4]
    )
    import functools

    gated = functools.partial(multiple_inputs_optimize_dag, max_total_source_arrays=4)
    wide = functools.partial(multiple_inputs_optimize_dag, max_total_source_arrays=5)
    ops_gated, tasks_gated, _ = counts(s, optimize_function=gated)
    ops_wide, tasks_wide, _ = counts(s, optimize_function=wide)
    assert tasks_wide == 5           # fully fused: one op, 4 blocks + create-arrays
    assert ops_wide < ops_gated      # the gate left at least one op unfused
    assert tasks_gated > tasks_wide
    np.testing.assert_array_equal(
        s.compute(optimize_function=wide), np.full((4, 4), 5.0)
    )


def test_never_fuse_override_pins_op(spec):
    a = xp.ones((4, 4), chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.negative(b)
    import functools

    # find c's producing op name: the last op node in the unoptimized dag
    dag = c.plan._finalize(optimize_graph=False).dag
    op_of_c = [
        n for n, d in dag.nodes(data=True)
        if d.get("type") == "op" and any(s == c.name for s in dag.successors(n))
    ]
    assert len(op_of_c) == 1
    never = functools.partial(
        multiple_inputs_optimize_dag, never_fuse={op_of_c[0]}
    )
    ops_plain, tasks_plain, _ = counts(c, optimize_function=multiple_inputs_optimize_dag)
    ops_never, tasks_never, _ = counts(c, optimize_function=never)
    assert tasks_plain == 5          # neg-neg fused over 2x2 blocks + create-arrays
    assert tasks_never == 10         # pinned op stays separate (+2 creates)
    assert ops_never == ops_plain + 1
    np.testing.assert_array_equal(
        c.compute(optimize_function=never), np.full((4, 4), 1.0)
    )


def test_repeated_argument_fuses_once(spec):
    # the same predecessor array consumed twice by one op (multigraph edge)
    a = xp.ones((4, 4), chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.add(b, b)
    ops_un, tasks_un, _ = counts(c, optimize_graph=False)
    ops_opt, tasks_opt, _ = counts(c, optimize_function=multiple_inputs_optimize_dag)
    assert tasks_opt == 5  # fused op's 4 blocks + create-arrays
    assert ops_un - ops_opt == 1
    np.testing.assert_array_equal(c.compute(), np.full((4, 4), -2.0))


def test_other_dependent_keeps_producer_alive(spec):
    # b is consumed by c AND persisted separately: the producer can't vanish
    an = np.arange(16.0).reshape(4, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.negative(a)
    c = xp.negative(b)
    # computing BOTH b and c: b's op must survive optimization
    from cubed_tpu.core.array import compute as compute_multi

    res_b, res_c = compute_multi(b, c, optimize_function=multiple_inputs_optimize_dag)
    np.testing.assert_allclose(np.asarray(res_b), -an)
    np.testing.assert_allclose(np.asarray(res_c), an)


def test_mixed_levels_partial_fusion_counts(spec):
    # reduction output feeding elementwise: the reduce op can't fuse into its
    # consumer (different task grids) but the elementwise tail fuses
    a = xp.ones((8, 8), chunks=(2, 2), spec=spec)
    s = xp.sum(a, axis=0)           # tree-reduce: multiple ops
    t = xp.negative(xp.negative(s))  # fusable tail
    ops_un, _, _ = counts(t, optimize_graph=False)
    ops_opt, _, _ = counts(t, optimize_function=multiple_inputs_optimize_dag)
    assert ops_un - ops_opt >= 1     # at least the tail pair fused
    np.testing.assert_array_equal(t.compute(), np.full((8,), 8.0))
