"""fft extension namespace (beyond the reference): chunked transforms with
the dask semantics — the transform axis gathers to one chunk, other axes
stay chunked; N-d transforms are separable (one gathered axis per op)."""

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.array_api import fft


def asnp(x):
    return np.asarray(x.compute())


def test_fft_ifft_roundtrip(spec):
    an = np.random.default_rng(0).standard_normal((6, 32))
    a = ct.from_array(an, chunks=(2, 8), spec=spec)  # chunked transform axis
    f = fft.fft(a)
    np.testing.assert_allclose(asnp(f), np.fft.fft(an), atol=1e-10)
    np.testing.assert_allclose(asnp(fft.ifft(f)), an, atol=1e-10)


def test_fft_other_axes_stay_chunked(spec):
    an = np.random.default_rng(1).standard_normal((8, 16))
    a = ct.from_array(an, chunks=(2, 4), spec=spec)
    f = fft.fft(a, axis=1)
    assert f.numblocks[0] == 4  # rows still chunked
    np.testing.assert_allclose(asnp(f), np.fft.fft(an, axis=1), atol=1e-10)


def test_fft_n_pad_truncate(spec):
    an = np.random.default_rng(2).standard_normal((4, 10))
    a = ct.from_array(an, chunks=(2, 5), spec=spec)
    for n in (6, 16):
        np.testing.assert_allclose(
            asnp(fft.fft(a, n=n)), np.fft.fft(an, n=n), atol=1e-10
        )


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_norms(spec, norm):
    an = np.random.default_rng(3).standard_normal(24)
    a = ct.from_array(an, chunks=(8,), spec=spec)
    np.testing.assert_allclose(
        asnp(fft.fft(a, norm=norm)), np.fft.fft(an, norm=norm), atol=1e-10
    )


def test_rfft_irfft(spec):
    an = np.random.default_rng(4).standard_normal((3, 20))
    a = ct.from_array(an, chunks=(1, 5), spec=spec)
    r = fft.rfft(a)
    assert r.shape == (3, 11)
    np.testing.assert_allclose(asnp(r), np.fft.rfft(an), atol=1e-10)
    np.testing.assert_allclose(asnp(fft.irfft(r)), an, atol=1e-10)
    np.testing.assert_allclose(
        asnp(fft.irfft(r, n=20)), np.fft.irfft(np.fft.rfft(an), n=20),
        atol=1e-10,
    )


def test_hfft_ihfft(spec):
    an = np.random.default_rng(5).standard_normal(9)
    a = ct.from_array(an, chunks=(3,), spec=spec)
    h = fft.ihfft(a)
    np.testing.assert_allclose(asnp(h), np.fft.ihfft(an), atol=1e-12)
    np.testing.assert_allclose(
        asnp(fft.hfft(h, n=9)), np.fft.hfft(np.fft.ihfft(an), n=9),
        atol=1e-10,
    )


def test_fftn_separable(spec):
    an = np.random.default_rng(6).standard_normal((8, 12, 6))
    a = ct.from_array(an, chunks=(2, 3, 2), spec=spec)
    np.testing.assert_allclose(asnp(fft.fftn(a)), np.fft.fftn(an), atol=1e-9)
    np.testing.assert_allclose(
        asnp(fft.ifftn(fft.fftn(a))), an, atol=1e-9
    )
    np.testing.assert_allclose(
        asnp(fft.fftn(a, axes=(0, 2))), np.fft.fftn(an, axes=(0, 2)),
        atol=1e-9,
    )
    np.testing.assert_allclose(
        asnp(fft.fftn(a, s=(4, 8), axes=(1, 2))),
        np.fft.fftn(an, s=(4, 8), axes=(1, 2)), atol=1e-9,
    )


def test_rfftn_irfftn(spec):
    an = np.random.default_rng(7).standard_normal((6, 10))
    a = ct.from_array(an, chunks=(2, 5), spec=spec)
    np.testing.assert_allclose(asnp(fft.rfftn(a)), np.fft.rfftn(an),
                               atol=1e-10)
    np.testing.assert_allclose(
        asnp(fft.irfftn(fft.rfftn(a))), an, atol=1e-10
    )


def test_fftfreq_rfftfreq(spec):
    for n in (8, 9):
        np.testing.assert_allclose(
            asnp(fft.fftfreq(n, spec=spec)), np.fft.fftfreq(n), atol=1e-15
        )
        np.testing.assert_allclose(
            asnp(fft.fftfreq(n, d=0.25, spec=spec)),
            np.fft.fftfreq(n, d=0.25), atol=1e-15,
        )
        np.testing.assert_allclose(
            asnp(fft.rfftfreq(n, spec=spec)), np.fft.rfftfreq(n), atol=1e-15
        )


def test_fftshift_roundtrip(spec):
    an = np.random.default_rng(8).standard_normal((5, 8))
    a = ct.from_array(an, chunks=(2, 3), spec=spec)
    np.testing.assert_allclose(asnp(fft.fftshift(a)), np.fft.fftshift(an))
    np.testing.assert_allclose(
        asnp(fft.ifftshift(fft.fftshift(a))), an
    )
    np.testing.assert_allclose(
        asnp(fft.fftshift(a, axes=1)), np.fft.fftshift(an, axes=1)
    )


def test_fft_dtype_rules(spec):
    a32 = ct.from_array(np.ones((4,), np.float32), chunks=(4,), spec=spec)
    assert fft.fft(a32).dtype == np.complex64
    assert fft.rfft(a32).dtype == np.complex64
    a64 = ct.from_array(np.ones((4,), np.float64), chunks=(4,), spec=spec)
    assert fft.fft(a64).dtype == np.complex128
    c = fft.fft(a64)
    assert fft.irfft(c).dtype == np.float64
    ai = ct.from_array(np.ones((4,), np.int32), chunks=(4,), spec=spec)
    with pytest.raises(TypeError):
        fft.fft(ai)
    with pytest.raises(ValueError):
        fft.fft(a64, norm="bogus")


def test_fft_on_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.random.default_rng(9).standard_normal((4, 16))
    a = ct.from_array(an, chunks=(2, 4), spec=spec)
    out = fft.ifft(fft.fft(a)).compute(executor=JaxExecutor())
    np.testing.assert_allclose(np.asarray(out), an, atol=1e-8)


def test_axis_and_s_validation(spec):
    a = ct.from_array(np.ones(8), chunks=(4,), spec=spec)
    with pytest.raises(IndexError):
        fft.fft(a, axis=3)
    with pytest.raises(IndexError):
        fft.fftn(a, s=(4, 4))  # more transform axes than dimensions
    with pytest.raises(IndexError):
        fft.fftshift(a, axes=2)


def test_fftshift_repeated_axes(spec):
    an = np.arange(5.0)
    a = ct.from_array(an, chunks=(5,), spec=spec)
    np.testing.assert_allclose(
        asnp(fft.fftshift(a, axes=(0, 0))), np.fft.fftshift(an, axes=(0, 0))
    )


def test_roll_repeated_axes_accumulate(spec):
    import cubed_tpu.array_api as xp

    an = np.arange(5.0)
    a = ct.from_array(an, chunks=(5,), spec=spec)
    np.testing.assert_allclose(
        asnp(xp.roll(a, (1, 1), axis=(0, 0))),
        np.roll(an, (1, 1), axis=(0, 0)),
    )
