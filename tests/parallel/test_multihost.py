"""Multi-host IO-sharding seams on the virtual CPU mesh with simulated
hosts (docs/multihost.md; real DCN needs >1 process — the partitioning
logic is host-count agnostic and fully testable here)."""

import itertools
import math

import numpy as np
import pytest

from cubed_tpu.parallel.mesh import make_mesh, sharding_for_chunks
from cubed_tpu.parallel.multihost import (
    dcn_mesh,
    host_chunk_assignment,
)


def _cpu_devices():
    import jax

    try:
        return jax.devices("cpu")
    except RuntimeError:
        return []


needs_8 = pytest.mark.skipif(
    len(_cpu_devices()) < 8, reason="needs 8 virtual CPU devices"
)


def virtual_host(device):
    """Simulate 2 hosts of 4 devices on the virtual CPU mesh."""
    return device.id // 4


@needs_8
def test_host_assignment_partitions_chunk_grid():
    devs = _cpu_devices()[:8]
    mesh = make_mesh(shape=(8,), axis_names=("data",), devices=devs)
    shape, chunks = (16, 24), (2, 6)
    chunkset = ((2,) * 8, (6,) * 4)
    sharding = sharding_for_chunks(mesh, chunkset, shape)
    assignment = host_chunk_assignment(
        sharding, shape, chunks, host_of_device=virtual_host
    )
    # exactly two hosts, all 32 chunks covered exactly once
    all_chunks = sorted(itertools.chain.from_iterable(assignment.values()))
    assert all_chunks == sorted(
        itertools.product(range(8), range(4))
    )
    assert set(assignment) == {0, 1}
    # the sharded dim is dim 0 (8 blocks over 8 devices): host 0 gets the
    # first half of the grid rows, host 1 the second
    assert all(c[0] < 4 for c in assignment[0])
    assert all(c[0] >= 4 for c in assignment[1])


@needs_8
def test_host_assignment_balanced_on_2d_mesh():
    devs = _cpu_devices()[:8]
    mesh = make_mesh(shape=(2, 4), axis_names=("dcn", "ici"), devices=devs)
    shape, chunks = (8, 16), (2, 2)
    chunkset = ((2,) * 4, (2,) * 8)
    sharding = sharding_for_chunks(mesh, chunkset, shape)
    assignment = host_chunk_assignment(
        sharding, shape, chunks, host_of_device=virtual_host
    )
    total = sum(len(v) for v in assignment.values())
    assert total == 4 * 8
    # both virtual hosts own work
    assert len(assignment) == 2
    sizes = sorted(len(v) for v in assignment.values())
    assert sizes == [16, 16]


@needs_8
def test_chunk_within_owner_shard():
    from cubed_tpu.parallel.multihost import chunk_within_owner_shard

    devs = _cpu_devices()[:8]
    mesh = make_mesh(shape=(8,), axis_names=("data",), devices=devs)
    # aligned: 16 rows / 8 shards of 2 rows; chunks of 2 rows sit in shards
    shape = (16, 4)
    aligned = sharding_for_chunks(mesh, ((2,) * 8, (4,)), shape)
    chunkset = ((2,) * 8, (4,))
    assert all(
        chunk_within_owner_shard(aligned, shape, chunkset, (i, 0))
        for i in range(8)
    )
    # misaligned: chunks of 4 rows straddle 2-row shards? no — larger chunks
    # over smaller shards DO straddle: chunk rows [0:4) spans shards 0 and 1
    big_chunkset = ((4,) * 4, (4,))
    assert not chunk_within_owner_shard(aligned, shape, big_chunkset, (0, 0))


@needs_8
def test_host_assignment_replicated_goes_to_one_host():
    devs = _cpu_devices()[:8]
    mesh = make_mesh(shape=(8,), axis_names=("data",), devices=devs)
    # prime dims: nothing shards -> fully replicated -> host of first device
    shape, chunks = (7, 11), (7, 11)
    sharding = sharding_for_chunks(mesh, ((7,), (11,)), shape)
    assignment = host_chunk_assignment(
        sharding, shape, chunks, host_of_device=virtual_host
    )
    assert sum(len(v) for v in assignment.values()) == 1


@needs_8
def test_dcn_mesh_shape_and_order():
    devs = _cpu_devices()[:8]
    # single real process: all devices report process_index 0 -> 1 host
    mesh = dcn_mesh(ici_shape=(8,), devices=devs)
    assert mesh.devices.shape == (1, 8)
    assert mesh.axis_names == ("dcn", "ici0")
    with pytest.raises(ValueError):
        dcn_mesh(ici_shape=(3,), devices=devs)


@needs_8
def test_dcn_mesh_simulated_two_hosts():
    devs = _cpu_devices()[:8]
    mesh = dcn_mesh(ici_shape=(2, 2), devices=devs, host_of_device=virtual_host)
    assert mesh.devices.shape == (2, 2, 2)
    assert mesh.axis_names == ("dcn", "ici0", "ici1")
    # leading axis is exactly the (virtual) host axis, host-major order
    for h in range(2):
        assert all(virtual_host(d) == h for d in mesh.devices[h].flat)


@needs_8
def test_sharded_zarr_roundtrip_uses_per_host_io_seams(tmp_path_factory):
    """End-to-end through the REAL seams: zarr source ingested via
    make_array_from_callback (per-shard reads), computed under the mesh,
    flushed via the per-host chunk assignment, read back exactly."""
    import tempfile

    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    devs = _cpu_devices()[:8]
    mesh = make_mesh(shape=(8,), axis_names=("data",), devices=devs)
    tmp = tempfile.mkdtemp()
    spec = ct.Spec(work_dir=tmp, allowed_mem="1GB")

    an = np.arange(16.0 * 24).reshape(16, 24)
    src = f"{tmp}/src.zarr"
    a0 = ct.from_array(an, chunks=(2, 6), spec=spec)
    ct.to_zarr(a0, src)  # default executor writes the source

    a = ct.from_zarr(src, spec=spec)  # concrete zarr input -> preload path
    out = f"{tmp}/out.zarr"
    ex = JaxExecutor(mesh=mesh)
    ct.to_zarr(xp.add(xp.multiply(a, 2.0), 1.0), out, executor=ex)

    back = np.asarray(ct.from_zarr(out, spec=spec).compute())
    np.testing.assert_allclose(back, an * 2.0 + 1.0)


@needs_8
def test_sharded_compute_matches_io_assignment():
    """End-to-end: a sharded compute's result is correct AND the assignment
    the flush seam would use covers the output grid exactly once."""
    import cubed_tpu as ct
    import cubed_tpu.array_api as xp
    import tempfile
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    devs = _cpu_devices()[:8]
    mesh = make_mesh(shape=(8,), axis_names=("data",), devices=devs)
    spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="1GB")
    an = np.arange(16.0 * 24).reshape(16, 24)
    a = ct.from_array(an, chunks=(2, 6), spec=spec)
    ex = JaxExecutor(mesh=mesh)
    out = xp.add(a, 1.0).compute(executor=ex)
    np.testing.assert_allclose(np.asarray(out), an + 1.0)

    sharding = ex._sharding_for((16, 24), ((2,) * 8, (6,) * 4))
    assignment = host_chunk_assignment(
        sharding, (16, 24), (2, 6), host_of_device=virtual_host
    )
    covered = sorted(itertools.chain.from_iterable(assignment.values()))
    assert covered == sorted(itertools.product(range(8), range(4)))


def test_two_process_jax_distributed_smoke(tmp_path):
    """REAL multi-controller SPMD over a process boundary: 2 processes x 4
    virtual CPU devices call jax.distributed.initialize on localhost, run
    the SAME framework plan under the mesh-sharded executor, and the
    instrumented Zarr store proves the per-host IO seams: each element of
    the source read exactly once and each element of the output written
    exactly once, split across the two processes (docs/multihost.md)."""
    import os
    import socket
    import subprocess
    import sys

    import cubed_tpu as ct

    work = str(tmp_path)
    shape = (16, 24)
    an = np.arange(float(np.prod(shape))).reshape(shape)
    spec = ct.Spec(work_dir=work, allowed_mem="1GB")
    a0 = ct.from_array(an, chunks=(2, 6), spec=spec)
    ct.to_zarr(a0, f"{work}/src.zarr")

    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_", "XLA_FLAGS"))
    }
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")

    def spawn_and_wait():
        # ephemeral-port pick races the coordinator's rebind; retry with a
        # fresh port if a worker loses the race
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, worker, str(pid), f"localhost:{port}", work],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True,
            )
            for pid in range(2)
        ]
        outs = []
        for p in procs:
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                raise
            outs.append(out)
        return [(p.returncode, out) for p, out in zip(procs, outs)]

    for attempt in range(3):
        results = spawn_and_wait()
        if all(rc == 0 for rc, _ in results):
            break
        if not any("bind" in out.lower() for _, out in results):
            break
    for rc, out in results:
        assert rc == 0, out[-4000:]

    # exactly-once IO, partitioned across the two processes
    reads = [np.load(f"{work}/read_mask_{pid}.npy") for pid in range(2)]
    writes = [np.load(f"{work}/write_mask_{pid}.npy") for pid in range(2)]
    np.testing.assert_array_equal(reads[0] + reads[1], np.ones(shape, np.int32))
    np.testing.assert_array_equal(writes[0] + writes[1], np.ones(shape, np.int32))
    # both processes did a real share of the IO (no one-host degeneracy)
    for m in (*reads, *writes):
        assert 0 < m.sum() < np.prod(shape), m.sum()

    # and the output is the correct computation
    back = np.asarray(ct.from_zarr(f"{work}/out.zarr", spec=spec).compute())
    np.testing.assert_allclose(back, an * 2.0 + 1.0)
