"""Fault-injection harness for executor tests.

Reference parity: cubed/tests/runtime/utils.py:20-103 — a task that, per
input, consults a timing map of signed sleep codes (positive = slow success,
negative = sleep then raise), persisting invocation counters in files so it
works across threads/processes; then assert exact retry counts.
"""

from __future__ import annotations

import os
import time
import uuid


def read_int_from_file(path: str) -> int:
    try:
        with open(path) as f:
            return int(f.read())
    except FileNotFoundError:
        return 0


def write_int_to_file(path: str, value: int) -> None:
    tmp = f"{path}.{uuid.uuid4().hex[:8]}.tmp"
    with open(tmp, "w") as f:
        f.write(str(value))
    os.replace(tmp, path)


def deterministic_failure(path: str, timing_map: dict, i, *, config=None) -> int:
    """Task that fails/succeeds deterministically per invocation count.

    ``timing_map[i]`` is a list of signed sleep durations (ms): one entry per
    invocation; positive sleeps then succeeds, negative sleeps then raises.
    Invocations beyond the list succeed immediately.
    """
    # unpack task keys of the form (name, i)
    if isinstance(i, tuple):
        i = i[-1]
    invocation_count_file = os.path.join(path, str(i))
    invocation_count = read_int_from_file(invocation_count_file)
    write_int_to_file(invocation_count_file, invocation_count + 1)
    timing_codes = timing_map.get(i, [])
    if invocation_count >= len(timing_codes):
        return i
    timing_code = timing_codes[invocation_count]
    if timing_code >= 0:
        time.sleep(timing_code / 1000.0)
        return i
    time.sleep(-timing_code / 1000.0)
    raise RuntimeError(
        f"Deliberately fail on invocation number {invocation_count + 1} for input {i}"
    )


def check_invocation_counts(
    path: str,
    timing_map: dict,
    n_tasks: int,
    retries: int | None = None,
    expected_invocation_counts_overrides: dict | None = None,
) -> None:
    expected = {}
    for i in range(n_tasks):
        timing_codes = timing_map.get(i, [])
        expected_count = 1
        for timing_code in timing_codes:
            if timing_code < 0:
                expected_count += 1
            else:
                break
        if retries is not None:
            expected_count = min(expected_count, retries + 1)
        expected[i] = expected_count
    if expected_invocation_counts_overrides:
        expected.update(expected_invocation_counts_overrides)
    actual = {i: read_int_from_file(os.path.join(path, str(i))) for i in range(n_tasks)}
    assert actual == expected, f"expected {expected}, got {actual}"
