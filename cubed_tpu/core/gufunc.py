"""apply_gufunc: apply a generalized ufunc ("(i,j),(j)->(i)" signatures) over
loop dimensions by lowering to blockwise. Core dimensions must be single-chunk
(no allow_rechunk). Multiple outputs are supported when every output shares
the same core dimensions ("(i)->(),()" etc.) — ONE multi-output op evaluates
the gufunc once per task and writes every output (the reference rejects all
multi-output signatures, cubed/core/gufunc.py:7-148; differing per-output
core dims would need per-output block-coordinate maps and stay rejected)."""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from .ops import blockwise

_DIMENSION_NAME = r"\w+"
_CORE_DIMENSION_LIST = "(?:{0:}(?:,{0:})*,?)?".format(_DIMENSION_NAME)
_ARGUMENT = rf"\({_CORE_DIMENSION_LIST}\)"
_INPUT_ARGUMENTS = "(?:{0:}(?:,{0:})*,?)?".format(_ARGUMENT)
_OUTPUT_ARGUMENTS = "{0:}(?:,{0:})*".format(_ARGUMENT)
_SIGNATURE = f"^{_INPUT_ARGUMENTS}->{_OUTPUT_ARGUMENTS}$"


def _parse_gufunc_signature(signature: str):
    """Parse a NumPy gufunc signature into (input dims, output dims)."""
    signature = signature.replace(" ", "")
    if not re.match(_SIGNATURE, signature):
        raise ValueError(f"not a valid gufunc signature: {signature}")
    ins, outs = signature.split("->")
    input_dims = [
        tuple(re.findall(_DIMENSION_NAME, arg))
        for arg in re.findall(_ARGUMENT, ins)
    ]
    output_dims = [
        tuple(re.findall(_DIMENSION_NAME, arg))
        for arg in re.findall(_ARGUMENT, outs)
    ]
    return input_dims, output_dims


def apply_gufunc(
    func,
    signature: str,
    *args,
    axes=None,
    axis=None,
    output_dtypes=None,
    vectorize: Optional[bool] = None,
    **kwargs,
):
    """Apply a generalized ufunc over the loop dimensions of chunked arrays."""
    input_dims, output_dims = _parse_gufunc_signature(signature)
    n_out = len(output_dims)
    if n_out > 1 and len(set(output_dims)) != 1:
        raise NotImplementedError(
            "apply_gufunc supports multiple outputs only when they share "
            f"the same core dimensions; got {output_dims}"
        )
    output_dim = output_dims[0]

    if axes is not None or axis is not None:
        raise NotImplementedError("axes/axis are not supported")

    if len(input_dims) != len(args):
        raise ValueError(
            f"signature {signature} expects {len(input_dims)} arrays, got {len(args)}"
        )

    if output_dtypes is None:
        raise ValueError("output_dtypes must be specified")
    if n_out > 1:
        if not isinstance(output_dtypes, (list, tuple)) or len(
            output_dtypes
        ) != n_out:
            raise ValueError(
                f"output_dtypes must list {n_out} dtypes for {n_out} outputs"
            )
        otype = list(output_dtypes)
    else:
        otype = (
            output_dtypes[0]
            if isinstance(output_dtypes, (list, tuple))
            else output_dtypes
        )

    if vectorize:
        func = np.vectorize(func, signature=signature)

    # dimension sizes from args
    dim_sizes: dict = {}
    loop_ndims = []
    for a, dims in zip(args, input_dims):
        if len(dims) > a.ndim:
            raise ValueError(
                f"array with {a.ndim} dims cannot supply core dims {dims}"
            )
        loop_ndims.append(a.ndim - len(dims))
        for d, size in zip(dims, a.shape[a.ndim - len(dims):]):
            if d in dim_sizes and dim_sizes[d] != size:
                raise ValueError(f"inconsistent size for core dimension {d!r}")
            dim_sizes[d] = size

    max_loop = max(loop_ndims) if loop_ndims else 0

    # core dims must be single-chunk
    for a, dims in zip(args, input_dims):
        nc = len(dims)
        if nc:
            for ax, d in enumerate(dims):
                chunks_ax = a.chunks[a.ndim - nc + ax]
                if len(chunks_ax) > 1:
                    raise ValueError(
                        f"core dimension {d!r} of array is chunked "
                        f"({chunks_ax}); rechunk so core dimensions have a "
                        "single chunk"
                    )

    # index symbols: loop dims (broadcast-aligned, negative positions) then
    # core; output-only core dims (e.g. the "k" in "(i,j)->(i,k)") get
    # symbols too — their sizes come from output_sizes via new_axes below
    core_syms = {
        d: f"c_{d}"
        for d in {*dim_sizes, *(d for dims in output_dims for d in dims)}
    }

    blockwise_args = []
    for a, dims, lnd in zip(args, input_dims, loop_ndims):
        loop_syms = tuple(range(max_loop - lnd, max_loop))
        ind = loop_syms + tuple(core_syms[d] for d in dims)
        blockwise_args.extend([a, ind])

    out_ind = tuple(range(max_loop)) + tuple(core_syms[d] for d in output_dim)

    # output core dims may be new symbols (not in any input)
    new_axes = {}
    for d in output_dim:
        if not any(d in dims for dims in input_dims):
            new_axes[core_syms[d]] = dim_sizes.get(d, kwargs.get("output_sizes", {}).get(d))
            if new_axes[core_syms[d]] is None:
                raise ValueError(f"size of output core dimension {d!r} unknown")

    kwargs.pop("output_sizes", None)

    return blockwise(
        _UnwrapCoreDims(func),
        out_ind,
        *blockwise_args,
        dtype=otype,
        new_axes=new_axes or None,
        **kwargs,
    )


class _UnwrapCoreDims:
    """Contracted (core) dims arrive as single-element nested lists, since core
    dims are single-chunk by contract; unwrap them to plain chunks."""

    def __init__(self, func):
        self.func = func
        self.__name__ = getattr(func, "__name__", "apply_gufunc")

    def __call__(self, *args, **kwargs):
        return self.func(*[_unwrap_single(a) for a in args], **kwargs)


def _unwrap_single(x):
    while isinstance(x, list) and len(x) == 1:
        x = x[0]
    return x
