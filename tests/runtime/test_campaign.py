"""Composed-failure campaign proofs.

The tier-1 headline: a fixed-seed schedule composing >= 3 failure
domains simultaneously (storage brownout + spot preemption + network
partition, during a rechunk) completes bitwise-correct AND
invariant-auditor-clean. Plus: schedule generation is deterministic per
seed, failing schedules shrink to a minimal reproducing subset, and the
repro file replays the identical failure.
"""

from __future__ import annotations

import json

import pytest

from cubed_tpu.runtime.campaign import (
    KNOB_ATOMS,
    KNOB_DOMAINS,
    CampaignRunner,
    FaultSchedule,
    WORKLOADS,
    main as chaos_main,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _restore_gensym_names():
    """CampaignRunner pins plan names per run and advances the global
    gensym continuation by design; later suites' seeded chaos decisions
    key on array NAMES (store._fault_key), so leave the counter exactly
    where it started."""
    import itertools

    from cubed_tpu import utils as ct_utils

    n0 = next(ct_utils.sym_counter)
    ct_utils.sym_counter = itertools.count(n0)
    yield
    ct_utils.sym_counter = itertools.count(n0)


# -- schedule model -------------------------------------------------------


def test_schedule_roundtrip(tmp_path):
    sched = FaultSchedule(
        seed=7, workload="rechunk",
        faults={"seed": 7, "storage_read_failure_rate": 0.1,
                "partition_worker_names": ["local-1"]},
        events=[{"kind": "cancel", "after_completes": 3}],
    )
    path = str(tmp_path / "repro-7.json")
    sched.save(path)
    back = FaultSchedule.load(path)
    assert back.to_dict() == sched.to_dict()
    assert back.domains == {"storage", "partition", "cancellation"}


def test_schedule_mode_properties():
    threaded = FaultSchedule(
        seed=1, workload="blockwise_chain",
        faults={"seed": 1, "task_failure_rate": 0.1},
    )
    assert not threaded.needs_fleet and not threaded.needs_subprocess
    fleet = FaultSchedule(
        seed=1, workload="rechunk",
        faults={"seed": 1, "worker_preempt_rate": 0.3},
    )
    assert fleet.needs_fleet and not fleet.needs_subprocess
    proc = FaultSchedule(
        seed=1, workload="rechunk",
        faults={"seed": 1, "coordinator_crash_after_dispatches": 5},
    )
    assert proc.needs_subprocess and proc.needs_fleet
    killer = FaultSchedule(
        seed=1, workload="rechunk", faults={"seed": 1},
        events=[{"kind": "client_kill", "after_s": 1.0}],
    )
    assert killer.needs_subprocess


def test_every_fault_knob_has_a_domain_and_an_atom():
    # the shrink atoms and the domain map must cover the full knob set
    from dataclasses import fields

    from cubed_tpu.runtime.faults import FaultConfig

    knobs = {f.name for f in fields(FaultConfig)} - {"seed"}
    atom_knobs = {k for group in KNOB_ATOMS for k in group}
    assert knobs == atom_knobs, knobs ^ atom_knobs
    assert knobs == set(KNOB_DOMAINS), knobs ^ set(KNOB_DOMAINS)


def test_generate_deterministic_per_seed_and_composes_domains(tmp_path):
    runner = CampaignRunner(str(tmp_path))
    a = runner.generate(123)
    b = runner.generate(123)
    assert a.to_dict() == b.to_dict()
    assert len(a.domains) >= 3
    assert a.workload in WORKLOADS
    assert not a.needs_subprocess  # process faults are opt-in
    # different seeds explore different schedules
    assert any(
        runner.generate(s).to_dict() != a.to_dict() for s in range(5)
    )


def test_generate_process_faults_only_when_allowed(tmp_path):
    runner = CampaignRunner(str(tmp_path))
    assert not any(
        runner.generate(s).needs_subprocess for s in range(30)
    )
    armed = [
        runner.generate(s, n_domains=6, allow_process_faults=True)
        for s in range(30)
    ]
    assert any(s.needs_subprocess for s in armed)


def test_unknown_knob_fails_loudly(tmp_path):
    runner = CampaignRunner(str(tmp_path))
    res = runner.run(FaultSchedule(
        seed=1, workload="blockwise_chain",
        faults={"seed": 1, "no_such_knob": 0.5},
    ))
    assert not res.ok and res.stage == "compute"
    assert "no_such_knob" in res.error


# -- the tier-1 composed-failure proof ------------------------------------

#: storage brownout + spot preemption + network partition, composed on
#: one seed during a rechunk: >= 3 domains firing simultaneously
COMPOSED_3DOMAIN = FaultSchedule(
    seed=1800,
    workload="rechunk",
    faults={
        "seed": 1800,
        # storage: brownout-grade flakiness + throttling
        "storage_read_failure_rate": 0.08,
        "storage_write_failure_rate": 0.08,
        "storage_throttle_rate": 0.1,
        # elasticity: a spot preemption wave mid-compute
        "worker_preempt_rate": 0.3,
        "worker_preempt_after_tasks": 2,
        "preempt_notice_s": 0.3,
        # partition: control-plane message delay/duplication
        "net_msg_delay_rate": 0.15,
        "net_msg_delay_s": 0.05,
        "net_msg_dup_rate": 0.1,
    },
)


def test_composed_three_domain_campaign_bitwise_and_auditor_clean(tmp_path):
    assert len(COMPOSED_3DOMAIN.domains) >= 3, COMPOSED_3DOMAIN.domains
    runner = CampaignRunner(str(tmp_path))
    res = runner.run(COMPOSED_3DOMAIN)
    assert res.ok, res.render()
    assert res.report is not None and res.report.ok, res.report.render()
    # the audit actually covered the journal, control log, and store
    for inv in ("exactly_once_application", "single_ownership",
                "epoch_monotonicity", "manifest_store_crc",
                "retry_budget_conservation", "counter_conservation"):
        assert inv in res.report.checked, res.report.checked
    # and faults genuinely fired: fleet-side injections count in the
    # worker processes' registries, but the retries they force (and any
    # client-side injections) are visible here
    assert (
        res.stats.get("task_retries", 0) > 0
        or res.stats.get("faults_injected", 0) > 0
    ), res.stats


def test_threaded_schedule_deterministic_per_seed(tmp_path):
    """The same seeded schedule rolls identical injector decisions run
    over run (plan names pinned), so the injected-fault count is exactly
    reproducible — what makes repro files trustworthy."""
    sched = FaultSchedule(
        seed=77, workload="blockwise_chain",
        faults={"seed": 77, "storage_read_failure_rate": 0.1,
                "storage_write_failure_rate": 0.1,
                "task_failure_rate": 0.05},
    )
    runner = CampaignRunner(str(tmp_path))
    r1 = runner.run(sched)
    r2 = runner.run(sched)
    assert r1.ok and r2.ok, (r1.render(), r2.render())
    assert r1.stats.get("faults_injected") == r2.stats.get(
        "faults_injected"
    ), (r1.stats, r2.stats)


def test_cancel_event_composes_with_faults_and_resumes_bitwise(tmp_path):
    """A mid-compute cancel composed with storage flakiness: the run is
    cancelled, resumed from its journal, and must still land bitwise and
    auditor-clean (two journal segments, no duplicate application)."""
    sched = FaultSchedule(
        seed=31, workload="blockwise_chain",
        faults={"seed": 31, "storage_read_failure_rate": 0.08,
                "straggler_rate": 0.5, "straggler_delay_s": 0.1},
        events=[{"kind": "cancel", "after_completes": 2}],
    )
    runner = CampaignRunner(str(tmp_path))
    res = runner.run(sched)
    assert res.ok, res.render()
    assert "cancellation" in sched.domains


# -- shrink + repro -------------------------------------------------------


def _failing_schedule():
    # task_failure_rate=1.0 deterministically exhausts the retry budget;
    # the straggler and storage-throttle atoms are irrelevant passengers
    # shrink must strip
    return FaultSchedule(
        seed=55, workload="blockwise_chain",
        faults={
            "seed": 55,
            "task_failure_rate": 1.0,
            "straggler_rate": 0.2, "straggler_delay_s": 0.05,
            "storage_throttle_rate": 0.05,
        },
    )


def test_failing_schedule_shrinks_to_minimal_and_replays(tmp_path):
    runner = CampaignRunner(str(tmp_path))
    sched = _failing_schedule()
    res = runner.run(sched)
    assert not res.ok and res.stage == "compute", res.render()
    assert res.signature[1] == "FaultInjectedTaskError", res.error

    minimal = runner.shrink(sched, signature=res.signature)
    # only the culprit atom (plus the seed) survives
    assert set(minimal.faults) == {"seed", "task_failure_rate"}, (
        minimal.faults
    )
    assert minimal.seed == sched.seed

    # the repro file replays the identical failure
    final = runner.run(minimal)
    repro = runner.write_repro(minimal, final, str(tmp_path / "repro.json"))
    doc = json.loads(open(repro).read())
    assert doc["failure"]["stage"] == "compute"
    replayed = runner.replay(repro)
    assert replayed.signature == res.signature, replayed.render()


def test_shrink_refuses_passing_schedule(tmp_path):
    runner = CampaignRunner(str(tmp_path))
    sched = FaultSchedule(
        seed=2, workload="blockwise_chain", faults={"seed": 2},
    )
    with pytest.raises(ValueError, match="passing schedule"):
        runner.shrink(sched)


def test_shrink_drops_irrelevant_event(tmp_path):
    # shrink removes events too, not just knobs (no run needed: custom check)
    runner = CampaignRunner(str(tmp_path))
    sched = FaultSchedule(
        seed=9, workload="blockwise_chain",
        faults={"seed": 9, "task_failure_rate": 1.0},
        events=[{"kind": "cancel", "after_completes": 2}],
    )

    def only_needs_task_faults(s):
        return "task_failure_rate" in s.faults

    minimal = runner.shrink(sched, check=only_needs_task_faults)
    assert minimal.events == []
    assert set(minimal.faults) == {"seed", "task_failure_rate"}


# -- CLI ------------------------------------------------------------------


def test_cli_repro_replay_exit_codes(tmp_path, capsys):
    runner = CampaignRunner(str(tmp_path / "scratch"))
    passing = FaultSchedule(
        seed=3, workload="blockwise_chain",
        faults={"seed": 3, "storage_read_failure_rate": 0.05},
    )
    p = str(tmp_path / "repro-pass.json")
    passing.save(p)
    assert chaos_main(["--repro", p, "--base-dir",
                       str(tmp_path / "scratch")]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out

    failing = FaultSchedule(
        seed=4, workload="blockwise_chain",
        faults={"seed": 4, "task_failure_rate": 1.0},
    )
    f = str(tmp_path / "repro-fail.json")
    failing.save(f)
    assert chaos_main(["--repro", f, "--base-dir",
                       str(tmp_path / "scratch")]) == 1


def test_cli_requires_exactly_one_mode():
    with pytest.raises(SystemExit):
        chaos_main([])
    with pytest.raises(SystemExit):
        chaos_main(["--seed", "1", "--repro", "x.json"])


# -- soak (slow) ----------------------------------------------------------


@pytest.mark.slow
def test_campaign_soak_generated_seeds_all_clean(tmp_path):
    """The --campaign soak shape: generated schedules over a seed range
    must all land bitwise + auditor-clean (failures would shrink and
    write repros, failing this test with the repro path in the log)."""
    runner = CampaignRunner(str(tmp_path))
    summary = runner.run_campaign(range(4), log=print)
    assert summary["failures"] == [], summary


@pytest.mark.slow
def test_subprocess_mode_coordinator_kill_recovers(tmp_path):
    """A schedule carrying a coordinator-crash knob runs in a child
    interpreter; the child dies by injection and the clean replay from
    the same seed must succeed."""
    runner = CampaignRunner(str(tmp_path))
    sched = FaultSchedule(
        seed=88, workload="blockwise_chain",
        faults={"seed": 88, "storage_read_failure_rate": 0.05,
                "coordinator_crash_after_dispatches": 3},
    )
    assert sched.needs_subprocess
    res = runner.run(sched)
    assert res.ok, res.render()
    assert res.stats.get("child_rc") != 0 or res.stats.get("child_killed")
