"""Indexing edge cases: slices with steps, negative steps, integer and
integer-array (orthogonal) indexing, newaxis/ellipsis, and compositions.

Reference scope: cubed/tests/test_indexing.py (int-array indexing) plus the
slice/step matrix the reference covers in test_array_object.py; the
negative-step cases are regressions for the resolved-stop wraparound bug
(stop=-1 reinterpreted as "end of array").
"""

from __future__ import annotations

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from tests.utils import all_executors


@pytest.fixture(params=all_executors(), ids=lambda e: e.name)
def executor(request):
    return request.param


DN = np.arange(37.0)
EN = np.arange(60.0).reshape(6, 10)


@pytest.mark.parametrize(
    "key",
    [
        slice(None, None, -1),
        slice(None, None, -2),
        slice(30, 2, -3),
        slice(5, 25, 4),
        slice(36, None, -1),
        slice(None, 0, -1),
        slice(3, None),
        slice(None, -4),
        slice(-10, -2),
        slice(-2, -10, -1),
    ],
)
def test_slice_steps_1d(spec, executor, key):
    a = ct.from_array(DN, chunks=(10,), spec=spec)
    expected = DN[key]
    got = np.asarray(a[key].compute(executor=executor))
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected)


@pytest.mark.parametrize(
    "key",
    [
        (slice(None, None, -1), slice(None, None, -2)),
        (slice(None, None, -1), slice(2, None)),
        (slice(4, 0, -2), slice(None, None, 3)),
        (slice(None, None, -1), 3),
        (2, slice(None, None, -1)),
    ],
)
def test_slice_steps_2d(spec, executor, key):
    a = ct.from_array(EN, chunks=(2, 4), spec=spec)
    expected = EN[key]
    got = np.asarray(a[key].compute(executor=executor))
    assert got.shape == expected.shape
    np.testing.assert_allclose(got, expected)


def test_composed_negative_then_slice(spec, executor):
    a = ct.from_array(DN, chunks=(10,), spec=spec)
    expected = DN[::-1][3:]
    got = np.asarray(a[::-1][3:].compute(executor=executor))
    np.testing.assert_allclose(got, expected)


@pytest.mark.parametrize(
    "ind",
    [[1, 5, 10], [10, 5, 1], [1, 1, 5], [-1, -5], np.array([1, 5, 10])],
)
def test_int_array_index_1d(spec, executor, ind):
    a = ct.from_array(DN, chunks=(10,), spec=spec)
    expected = DN[ind]
    got = np.asarray(a[ind].compute(executor=executor))
    np.testing.assert_allclose(got, expected)


@pytest.mark.parametrize(
    "ind", [[0, 3, 5], [5, 3, 0], [-1, 2]]
)
def test_int_array_index_2d(spec, executor, ind):
    a = ct.from_array(EN, chunks=(2, 4), spec=spec)
    np.testing.assert_allclose(
        np.asarray(a[ind, :].compute(executor=executor)), EN[ind, :]
    )
    np.testing.assert_allclose(
        np.asarray(a[:, ind].compute(executor=executor)), EN[:, ind]
    )


def test_multiple_int_array_indexes_rejected(spec):
    a = ct.from_array(EN, chunks=(2, 4), spec=spec)
    with pytest.raises((NotImplementedError, IndexError)):
        a[[0, 1], [1, 2]]


def test_int_index_drops_axis(spec, executor):
    a = ct.from_array(EN, chunks=(2, 4), spec=spec)
    got = a[3]
    assert got.shape == (10,)
    np.testing.assert_allclose(np.asarray(got.compute(executor=executor)), EN[3])
    got2 = a[-1, -1]
    assert got2.shape == ()
    assert float(got2.compute(executor=executor)) == EN[-1, -1]


@pytest.mark.parametrize(
    "key",
    [
        (None, Ellipsis, 2),
        (Ellipsis, None),
        (3, None),
        (None,),
        (slice(1, 4), None, 2),
        (2, Ellipsis, None, 3),
    ],
)
def test_newaxis_and_ellipsis(spec, executor, key):
    a = ct.from_array(EN, chunks=(2, 4), spec=spec)
    expected = EN[key]
    got = a[key]
    assert got.shape == expected.shape
    np.testing.assert_allclose(
        np.asarray(got.compute(executor=executor)), expected
    )


def test_double_ellipsis_rejected(spec):
    a = ct.from_array(EN, chunks=(2, 4), spec=spec)
    with pytest.raises(IndexError):
        a[..., ...]


def test_out_of_bounds_raises(spec):
    a = ct.from_array(DN, chunks=(10,), spec=spec)
    with pytest.raises(IndexError):
        a[37]
    with pytest.raises(IndexError):
        a[-38]
    with pytest.raises(IndexError):
        a[0, 0]


def test_empty_selection(spec, executor):
    a = ct.from_array(DN, chunks=(10,), spec=spec)
    got = a[5:5]
    assert got.shape == (0,)
    assert np.asarray(got.compute(executor=executor)).shape == (0,)


def test_lazy_array_as_index(spec, executor):
    a = ct.from_array(DN, chunks=(10,), spec=spec)
    idx = ct.from_array(np.array([2, 4, 8]), chunks=(3,), spec=spec)
    np.testing.assert_allclose(
        np.asarray(a[idx].compute(executor=executor)), DN[[2, 4, 8]]
    )


def test_index_then_reduce(spec, executor):
    # indexing composed with downstream ops (the vorticity pattern a[1:])
    a = ct.from_array(EN, chunks=(2, 4), spec=spec)
    got = float(xp.sum(a[1:]).compute(executor=executor))
    assert np.isclose(got, EN[1:].sum())


# -- take_along_axis (2024.12 extension; pairs with argsort) -----------------


def test_take_along_axis_matches_numpy(spec):
    an = np.random.default_rng(0).random((12, 16))
    a = ct.from_array(an, chunks=(4, 5), spec=spec)
    for axis in (0, 1, -1):
        order = np.argsort(an, axis=axis)
        idx = ct.from_array(order, chunks=(4, 5), spec=spec)
        got = np.asarray(xp.take_along_axis(a, idx, axis=axis).compute())
        np.testing.assert_array_equal(
            got, np.take_along_axis(an, order, axis=axis)
        )


def test_take_along_axis_argsort_roundtrip(spec):
    # the headline consumer: gathering by argsort yields the sorted array
    an = np.random.default_rng(1).integers(0, 50, 60).astype(np.int64)
    a = ct.from_array(an, chunks=(8,), spec=spec)
    srt = xp.take_along_axis(a, xp.argsort(a))
    np.testing.assert_array_equal(np.asarray(srt.compute()), np.sort(an))


def test_take_along_axis_negative_and_short_indices(spec):
    an = np.random.default_rng(2).random((6, 9))
    a = ct.from_array(an, chunks=(3, 4), spec=spec)
    # k != n along axis, negative indices, int32 dtype
    order = np.asarray([[-1, 0, 3], [2, -9, 1], [0, 1, 2],
                        [5, 4, 3], [1, 1, 1], [-2, -3, -4]], dtype=np.int32)
    idx = ct.from_array(order, chunks=(3, 2), spec=spec)
    got = np.asarray(xp.take_along_axis(a, idx, axis=1).compute())
    np.testing.assert_array_equal(
        got, np.take_along_axis(an, order.astype(np.int64), axis=1)
    )


def test_take_along_axis_axis_larger_than_allowed_mem(tmp_path):
    # the axis streams one x chunk at a time: 3 MB axis, 1 MB allowed
    small = ct.Spec(work_dir=str(tmp_path), allowed_mem="1MB", reserved_mem=0)
    n = 375_000
    an = np.random.default_rng(3).random(n)
    a = ct.from_array(an, chunks=(12_500,), spec=small)
    order = np.argsort(an)
    idx = ct.from_array(order, chunks=(12_500,), spec=small)
    got = np.asarray(xp.take_along_axis(a, idx).compute())
    np.testing.assert_array_equal(got, np.sort(an))


def test_take_along_axis_broadcasts_and_small_dtypes(spec):
    # size-1 non-axis dims broadcast per spec (both directions), and
    # uint8 indices must not overflow the in-kernel arithmetic
    an = np.random.default_rng(4).random((6, 9))
    a = ct.from_array(an, chunks=(3, 4), spec=spec)
    order = np.asarray([[0, 8, 3, 5, 1]], dtype=np.int64)  # (1, 5)
    idx = ct.from_array(order, chunks=(1, 3), spec=spec)
    got = np.asarray(xp.take_along_axis(a, idx, axis=1).compute())
    np.testing.assert_array_equal(
        got, np.take_along_axis(an, np.broadcast_to(order, (6, 5)), axis=1)
    )
    # x-side broadcast: size-1 non-axis dim in x stretches to indices'
    xn = np.random.default_rng(6).random((1, 9))
    x1 = ct.from_array(xn, chunks=(1, 4), spec=spec)
    order2 = np.argsort(np.broadcast_to(xn, (6, 9)), axis=1)
    idx2 = ct.from_array(order2, chunks=(3, 4), spec=spec)
    got2 = np.asarray(xp.take_along_axis(x1, idx2, axis=1).compute())
    np.testing.assert_array_equal(
        got2,
        np.take_along_axis(np.broadcast_to(xn, (6, 9)), order2, axis=1),
    )
    bn = np.random.default_rng(5).random(300)
    b = ct.from_array(bn, chunks=(100,), spec=spec)
    small = np.arange(0, 200, dtype=np.uint8)
    sidx = ct.from_array(small, chunks=(64,), spec=spec)
    got2 = np.asarray(xp.take_along_axis(b, sidx).compute())
    np.testing.assert_array_equal(got2, bn[small.astype(np.int64)])


def test_take_along_axis_rejections(spec):
    a = ct.from_array(np.arange(8.0), chunks=(4,), spec=spec)
    f = ct.from_array(np.zeros(8), chunks=(4,), spec=spec)
    with pytest.raises(TypeError, match="integer dtype"):
        xp.take_along_axis(a, f)
    i2 = ct.from_array(np.zeros((2, 2), dtype=np.int64), chunks=(2, 2), spec=spec)
    with pytest.raises(ValueError, match="same rank"):
        xp.take_along_axis(a, i2)
    b = ct.from_array(np.zeros((3, 4)), chunks=(2, 2), spec=spec)
    i3 = ct.from_array(np.zeros((2, 4), dtype=np.int64), chunks=(2, 2), spec=spec)
    with pytest.raises(ValueError, match="broadcast-compatible"):
        xp.take_along_axis(b, i3, axis=1)
