"""Array-API utility functions. Reference parity:
cubed/array_api/utility_functions.py (15 LoC)."""

from __future__ import annotations

import numpy as np

from ..backend_array_api import nxp
from ..core.ops import reduction


def all(x, /, *, axis=None, keepdims=False, split_every=None):  # noqa: A001
    if x.size == 0:
        from .creation_functions import asarray

        return asarray(True, dtype=np.bool_, spec=x.spec)
    return reduction(
        x, _all_fn, axis=axis, dtype=np.dtype(np.bool_), keepdims=keepdims,
        split_every=split_every,
    )


def any(x, /, *, axis=None, keepdims=False, split_every=None):  # noqa: A001
    if x.size == 0:
        from .creation_functions import asarray

        return asarray(False, dtype=np.bool_, spec=x.spec)
    return reduction(
        x, _any_fn, axis=axis, dtype=np.dtype(np.bool_), keepdims=keepdims,
        split_every=split_every,
    )


def _all_fn(a, axis=None, keepdims=True, **kw):
    return nxp.all(a, axis=axis, keepdims=keepdims)


def _any_fn(a, axis=None, keepdims=True, **kw):
    return nxp.any(a, axis=axis, keepdims=keepdims)
