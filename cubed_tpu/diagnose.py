"""Read a flight-recorder bundle: ``python -m cubed_tpu.diagnose <bundle>``.

Prints the post-mortem a human wants first: what failed (op + chunk +
error), the slowest ops, the top stragglers, the retry/quarantine/guard
decision timeline, and per-worker clock skew. The bundle is the directory
``FlightRecorder`` wrote (``bundle-<compute_id>/``) — see
``docs/observability.md``.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from .observability.flightrecorder import load_bundle


def _fmt_s(v) -> str:
    return f"{v:.3f}s" if isinstance(v, (int, float)) else "-"


def _section(title: str) -> str:
    return f"\n== {title} " + "=" * max(1, 60 - len(title))


#: decision kinds grouped into the timelines the report prints (every kind
#: here has a record_decision call site; fail-fasts are task_failed rows
#: with classification=fail_fast)
_TIMELINE_GROUPS = {
    "retries": ("retry", "requeue", "backup", "task_failed", "pool_rebuild"),
    "integrity": ("recompute", "quarantine"),
    "memory guard": ("admission_step_down", "admission_restore",
                     "guard_soft_exceeded", "device_memory"),
    "stragglers": ("straggler",),
    "scheduling": ("scheduler_mode", "dataflow_graph", "dispatch_early"),
    # the control plane's connection lifecycle: partitions, reconnects,
    # lease expiries, impostor rejections, and the drain/scale events that
    # change fleet membership (PR 8)
    "connectivity": ("worker_disconnected", "worker_reconnected",
                     "lease_expired", "worker_rejected",
                     "worker_drain_requested", "worker_draining",
                     "worker_drained", "scale_up", "scale_down",
                     "spawn_died", "coordinator_takeover"),
    # the p2p data plane: per-compute arming, locality-preferred
    # dispatches, and peer-fetch store fallbacks (runtime/transfer.py)
    "data movement": ("peer_transfer", "placement_locality",
                      "peer_fallback"),
    # seeded chaos: every fault the injector fired (runtime/faults.py) —
    # a repro bundle names what was injected, where, and when
    "injected faults": ("fault_injected",),
    # the live-telemetry alert engine's firings (observability/alerts.py);
    # the dedicated "alerts" section above prints the same rows with their
    # severities — this keeps them in timeline context with everything else
    "alerts": ("alert_fired",),
    # the overload ladder's transitions, what it shed at admission, the
    # per-tenant circuit breakers, and poison-request quarantines
    # (service/overload.py + the executors' quarantine path)
    "overload": ("overload_level", "request_shed", "tenant_breaker",
                 "poison_quarantine"),
}

#: the data-movement section's metric rows (manifest metrics snapshot);
#: printed only when the compute actually moved bytes peer-to-peer
_DATA_MOVEMENT_METRICS = (
    ("peer_hits", "reads served from a worker chunk cache (local or peer)"),
    ("peer_misses", "peer-path reads that went to the store"),
    ("peer_bytes_fetched", "bytes fetched worker-to-worker"),
    ("store_read_bytes_saved", "store read bytes the caches saved"),
    ("peer_fetch_fallbacks", "located fetches that fell back to the store"),
    ("peer_locate_requests", "chunk_locate RPCs answered"),
    ("placement_locality_hits", "dispatches placed for input locality"),
    ("cache_evictions", "worker cache evictions (LRU + pressure)"),
)


def _merge_intervals(intervals: list) -> list:
    """Coalesce [start, end) intervals into a sorted disjoint union."""
    out: list = []
    for s, e in sorted(i for i in intervals if i[1] > i[0]):
        if out and s <= out[-1][1]:
            out[-1][1] = max(out[-1][1], e)
        else:
            out.append([s, e])
    return out


def _intersection_s(a: list, b: list) -> float:
    """Total length of the intersection of two disjoint interval unions."""
    total, i, j = 0.0, 0, 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def op_overlap_rows(trace: Optional[dict]) -> list:
    """Per-op overlap with its predecessors, from the bundle's task spans.

    For each op (in first-task-start order): how long its tasks ran
    CONCURRENTLY with tasks of any earlier-starting op. Under the
    op-level scheduler this is ~0 by construction; under
    ``scheduler="dataflow"`` it is the barrier time the scheduler won
    back — the post-mortem proof the overlap actually happened."""
    events = [
        e for e in ((trace or {}).get("traceEvents") or [])
        if e.get("ph") == "X" and e.get("cat") == "task"
        and e.get("dur") is not None
    ]
    by_op: dict = {}
    for e in events:
        s = e["ts"] / 1e6
        by_op.setdefault(e.get("name"), []).append((s, s + e["dur"] / 1e6))
    order = sorted(by_op, key=lambda op: min(s for s, _ in by_op[op]))
    rows = []
    earlier: list = []
    for op in order:
        iv = _merge_intervals(by_op[op])
        busy = sum(e - s for s, e in iv)
        rows.append({
            "op": op,
            "tasks": len(by_op[op]),
            "busy_s": busy,
            "overlap_s": _intersection_s(iv, earlier),
        })
        earlier = _merge_intervals(earlier + iv)
    return rows


def render_report(bundle: dict, timeline_limit: int = 20) -> str:
    m = bundle["manifest"]
    out = []
    out.append(f"compute {m.get('compute_id')}  [{m.get('status')}]  "
               f"wall clock {_fmt_s(m.get('wall_clock_s'))}  "
               f"({m.get('created_at')})")

    err = m.get("error")
    if err:
        out.append(_section("failure"))
        if not isinstance(err, dict):
            # tolerate degenerate/older manifests that stored a bare string
            err = {"type": "error", "message": str(err)}
        where = ""
        if err.get("op") or err.get("chunk"):
            where = f" in op {err.get('op')} chunk {err.get('chunk')}"
        out.append(f"{err.get('type')}: {err.get('message')}{where}")
        failures = m.get("failing_tasks") or []
        for f in failures[-5:]:
            out.append(
                f"  task_failed op={f.get('op')} chunk={f.get('chunk')} "
                f"attempt={f.get('attempt')} error={f.get('error_type')}: "
                f"{str(f.get('error'))[:120]}"
            )

    ops = sorted(
        (m.get("op_wall_clock") or {}).items(),
        key=lambda kv: -(kv[1] or 0),
    )
    if ops:
        out.append(_section("slowest ops"))
        plan = {r.get("array_name"): r for r in (m.get("plan") or [])}
        for name, wall in ops[:10]:
            row = plan.get(name, {})
            util = row.get("projected_mem_utilization")
            out.append(
                f"  {name:<28} {_fmt_s(wall):>10}  tasks={row.get('num_tasks', '-'):<6} "
                f"projected_mem={row.get('projected_mem', '-')} "
                f"peak={row.get('peak_measured_mem', '-')}"
                + (f" ({util:.0%} of projection)" if util else "")
            )

    # bundles written before the live-telemetry layer existed carry no
    # "alerts"/"timeseries" keys at all — every section here treats a
    # missing artifact as empty, never as an error (regression-tested in
    # tests/observability/test_analytics.py)
    alerts = m.get("alerts") or []
    if alerts:
        from .observability.alerts import format_alert_row

        out.append(_section(f"alerts ({len(alerts)} fired)"))
        t0 = alerts[0].get("ts", 0)
        for a in alerts[-timeline_limit:]:
            out.append(
                f"  +{(a.get('ts', 0) - t0):8.3f}s {format_alert_row(a)}"
            )

    # per-tenant SLO posture at bundle time, from the bundled time-series
    # dump (the sampler publishes slo_* series whenever a service with SLO
    # specs is live) — last point per series, grouped by tenant
    slo_rows: dict = {}
    for s in m.get("timeseries") or []:
        name = s.get("name") or ""
        tenant = (s.get("labels") or {}).get("tenant")
        points = s.get("points") or []
        if name.startswith("slo_") and tenant and points:
            slo_rows.setdefault(tenant, {})[name] = points[-1][1]
    if slo_rows:
        out.append(_section("SLOs (at bundle time)"))
        for tenant, row in sorted(slo_rows.items()):
            budget = row.get("slo_budget_remaining")
            out.append(
                f"  {tenant:<20} budget "
                + (f"{budget:>6.0%}" if isinstance(budget, (int, float))
                   else "     -")
                + "  burn "
                + " ".join(
                    f"{w}={row[f'slo_burn_{w}']:.1f}"
                    for w in ("5m", "1h", "6h", "3d")
                    if isinstance(row.get(f"slo_burn_{w}"), (int, float))
                )
                + (
                    f"  p99 {_fmt_s(row.get('slo_request_latency_p99'))}"
                    if row.get("slo_request_latency_p99") is not None
                    else ""
                )
            )

    stragglers = m.get("stragglers") or []
    if stragglers:
        out.append(_section("top stragglers"))
        for s in stragglers:
            out.append(
                f"  {s.get('op')} chunk={s.get('chunk')} "
                f"{_fmt_s(s.get('duration_s'))} "
                f"({(s.get('factor') or 0):.1f}x op median "
                f"{_fmt_s(s.get('op_median_s'))}) on {s.get('worker')}"
            )

    overlap = op_overlap_rows(bundle.get("trace"))
    if len(overlap) >= 2:
        mode_rows = [
            d for d in (m.get("decisions") or [])
            if d.get("kind") == "scheduler_mode"
        ]
        mode = mode_rows[-1].get("mode") if mode_rows else None
        out.append(_section(
            "per-op overlap" + (f" (scheduler={mode})" if mode else "")
        ))
        total = 0.0
        for r in overlap:
            pct = r["overlap_s"] / r["busy_s"] if r["busy_s"] else 0.0
            total += r["overlap_s"]
            out.append(
                f"  {r['op']:<28} tasks={r['tasks']:<6} "
                f"busy {_fmt_s(r['busy_s']):>10}  "
                f"ran concurrently with predecessors "
                f"{_fmt_s(r['overlap_s'])} ({pct:.0%})"
            )
        out.append(
            f"  total cross-op overlap: {_fmt_s(total)}"
            + ("  (op barrier held: no overlap)" if total < 1e-6 else "")
        )

    metrics = m.get("metrics") or {}
    if any(metrics.get(name) for name, _ in _DATA_MOVEMENT_METRICS):
        out.append(_section("data movement (peer-to-peer)"))
        hits = metrics.get("peer_hits") or 0
        misses = metrics.get("peer_misses") or 0
        if hits or misses:
            out.append(
                f"  peer hit rate {hits / max(hits + misses, 1):.0%} "
                f"({hits} hits / {misses} store reads on the peer path)"
            )
        for name, caption in _DATA_MOVEMENT_METRICS:
            v = metrics.get(name)
            if v:
                out.append(f"  {name:<26} {v:>12}  {caption}")

    # chaos runs: the per-site injection counters, so the bundle states
    # up front how much seeded failure the compute absorbed (the per-event
    # detail follows in the "injected faults" timeline)
    if metrics.get("faults_injected"):
        out.append(_section(
            f"injected faults ({metrics['faults_injected']} total)"
        ))
        for name in sorted(metrics):
            if name.startswith("faults_injected_") and metrics[name]:
                out.append(
                    f"  {name[len('faults_injected_'):]:<26} "
                    f"{metrics[name]:>8}"
                )

    decisions = m.get("decisions") or []
    for title, kinds in _TIMELINE_GROUPS.items():
        rows = [d for d in decisions if d.get("kind") in kinds]
        if not rows:
            continue
        out.append(_section(f"{title} timeline ({len(rows)} events)"))
        t0 = rows[0].get("ts", 0)
        for d in rows[-timeline_limit:]:
            extra = " ".join(
                f"{k}={v}" for k, v in d.items()
                if k not in ("ts", "kind", "compute_id")
            )
            out.append(f"  +{(d.get('ts', 0) - t0):8.3f}s {d.get('kind'):<20} {extra}")

    prof = m.get("dispatch_profile")
    if prof:
        out.append(_section(
            f"dispatch (coordinator self-profile, {prof.get('samples', 0)} "
            f"samples @ {prof.get('hz', '?')}Hz)"
        ))
        for s in (prof.get("top_stacks") or [])[:8]:
            frac = s.get("fraction")
            frac_s = f"{frac:.0%}" if isinstance(frac, (int, float)) else "-"
            out.append(
                f"  {frac_s:>5} {s.get('thread')}: {s.get('leaf')}"
            )
        if prof.get("overflow"):
            out.append(
                f"  NOTE: {prof['overflow']} sample(s) beyond the "
                "folded-stack cap were counted but not retained"
            )
        out.append(
            f"  full collapsed stacks: profile-{m.get('compute_id')}.folded "
            "(feed to flamegraph.pl / speedscope)"
        )

    offsets = m.get("clock_offsets") or {}
    skewed = {k: v for k, v in offsets.items() if k != "client"}
    if skewed:
        out.append(_section("per-worker clock skew"))
        for name, row in sorted(skewed.items()):
            rtt = row.get("rtt")
            out.append(
                f"  {name:<20} offset {row.get('offset', 0):+0.6f}s "
                f"({row.get('source')})"
                + (f" rtt {rtt * 1e3:.1f}ms" if rtt else "")
            )

    trace = bundle.get("trace")
    if trace:
        n = len(trace.get("traceEvents") or [])
        out.append(_section("artifacts"))
        out.append(f"  trace.json: {n} events — open at https://ui.perfetto.dev")
        out.append(f"  logs.jsonl: {len(bundle.get('logs') or [])} structured records")
        series = m.get("timeseries")
        if series:
            npts = sum(len(s.get("points") or []) for s in series)
            out.append(
                f"  timeseries: {len(series)} series / {npts} points "
                "sampled over the compute window (manifest.json)"
            )
    dropped = m.get("task_records_dropped")
    if dropped:
        out.append(f"  NOTE: {dropped} task record(s) beyond the retention "
                   "bound were dropped; the trace is truncated")
    return "\n".join(out) + "\n"


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cubed_tpu.diagnose", description=__doc__
    )
    parser.add_argument(
        "bundle", help="flight-recorder bundle directory (or its manifest.json)"
    )
    parser.add_argument(
        "--timeline-limit", type=int, default=20,
        help="max events shown per decision timeline (default 20)",
    )
    parser.add_argument(
        "--analyze", action="store_true",
        help="append the ANALYZE report: dependency-weighted critical "
        "path + wall-clock attribution (kernel/storage/peer/queue/retry/"
        "straggler buckets) from the bundle's trace",
    )
    parser.add_argument(
        "--history", default=None,
        help="run-history directory (runs.jsonl): append the REGRESSION "
        "section diffing this bundle's compute against its archived "
        "baseline (same plan fingerprint)",
    )
    args = parser.parse_args(argv)
    try:
        bundle = load_bundle(args.bundle)
    except (OSError, ValueError) as e:
        print(f"cannot read bundle {args.bundle!r}: {e}", file=sys.stderr)
        return 2
    sys.stdout.write(render_report(bundle, timeline_limit=args.timeline_limit))
    if args.analyze:
        from .observability.analytics import analyze

        sys.stdout.write(_section("analysis") + "\n")
        try:
            sys.stdout.write(analyze(bundle).render())
        except (ValueError, KeyError) as e:
            # an old/partial bundle (no trace.json, no task spans) still
            # renders the base report — analysis degrades with a note
            sys.stdout.write(f"analysis unavailable: {e}\n")
    if args.history:
        from .observability.analytics import regression_diff, render_regression
        from .observability.runhistory import find_baseline, load_runs

        sys.stdout.write(_section("regression") + "\n")
        records, _bad = load_runs(args.history)
        compute_id = (bundle.get("manifest") or {}).get("compute_id")
        current = next(
            (
                r for r in reversed(records)
                if r.get("kind") == "compute"
                and r.get("compute_id") == compute_id
            ),
            None,
        )
        baseline = find_baseline(
            records,
            current.get("fingerprint") if current else None,
            before_ts=current.get("ts") if current else None,
            exclude_compute_id=compute_id,
        ) if current else None
        if current is None or not current.get("buckets"):
            sys.stdout.write(
                f"no diffable archive record for {compute_id!r} under "
                f"{args.history!r}\n"
            )
        elif baseline is None:
            sys.stdout.write(
                "no comparable baseline in the archive (same fingerprint, "
                "earlier, OK, with a decomposition)\n"
            )
        else:
            sys.stdout.write(render_regression(
                regression_diff(baseline, current)
            ))
    return 0


if __name__ == "__main__":
    sys.exit(main())
