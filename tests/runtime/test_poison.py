"""Poison-request quarantine, unit level: the ``task_fatal`` injector
knobs (a chunk whose task hard-kills its worker on EVERY attempt), the
worker-fatal strike counting in ``map_unordered``, and the
``PoisonTaskError`` verdict's pickling + fail-fast classification.

The live-fleet proof (seeded poison chunk on a real 2-worker fleet under
a 2x flood) lives in ``tests/service/test_overload.py``.
"""

from __future__ import annotations

import concurrent.futures
import pickle
import time

import pytest

from cubed_tpu.observability.collect import decisions_since
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime import faults
from cubed_tpu.runtime.executors.python_async import map_unordered
from cubed_tpu.runtime.resilience import (
    Classification,
    PoisonTaskError,
    RetryPolicy,
)


# -- the task_fatal injector knobs ---------------------------------------


def test_task_fatal_is_deterministic_and_pinned_to_occurrence_zero():
    """The fatal verdict is a pure function of (seed, chunk_key) — the
    SAME chunk re-kills on every attempt (no occurrence advance), which
    is exactly the poison shape the quarantine must end."""
    inj = faults.FaultInjector(
        faults.FaultConfig(seed=7, task_fatal_rate=0.3)
    )
    verdicts = {k: inj.task_fatal(k) for k in (f"('a', {i})" for i in range(40))}
    assert any(verdicts.values()) and not all(verdicts.values())
    # re-asking never changes the answer: retries of a poison chunk
    # re-kill, retries of a clean chunk stay clean
    for _ in range(3):
        for k, v in verdicts.items():
            assert inj.task_fatal(k) is v
    # a fresh injector with the same seed replays identically...
    inj2 = faults.FaultInjector(
        faults.FaultConfig(seed=7, task_fatal_rate=0.3)
    )
    assert {k: inj2.task_fatal(k) for k in verdicts} == verdicts
    # ...and a different seed picks different victims
    inj3 = faults.FaultInjector(
        faults.FaultConfig(seed=8, task_fatal_rate=0.3)
    )
    assert {k: inj3.task_fatal(k) for k in verdicts} != verdicts


def test_task_fatal_explicit_chunk_keys_and_counting():
    """An explicitly named chunk key is fatal regardless of rate, every
    hit is counted (faults_injected + faults_injected_task_fatal), and
    an unarmed injector never fires."""
    before = get_registry().snapshot()
    inj = faults.FaultInjector(
        faults.FaultConfig(seed=1, task_fatal_chunk_keys=("('x', 0, 0)",))
    )
    assert inj.task_fatal("('x', 0, 0)") is True
    assert inj.task_fatal("('x', 0, 1)") is False
    delta = get_registry().snapshot_delta(before)
    assert delta.get("faults_injected", 0) == 1
    assert delta.get("faults_injected_task_fatal", 0) == 1
    # both knobs at zero: no rolls, no counting
    off = faults.FaultInjector(faults.FaultConfig(seed=1))
    assert off.task_fatal("('x', 0, 0)") is False


# -- the PoisonTaskError verdict -----------------------------------------


def test_poison_task_error_pickles_and_classifies_fail_fast():
    err = PoisonTaskError("op-add-000000003", "('array-x', 1, 2)", 4)
    assert "op-add-000000003" in str(err) and "('array-x', 1, 2)" in str(err)
    rt = pickle.loads(pickle.dumps(err))
    assert (rt.op, rt.chunk, rt.attempts) == (err.op, err.chunk, err.attempts)
    policy = RetryPolicy()
    assert policy.classify(err) is Classification.FAIL_FAST
    # the verdict crossing the fleet wire by type NAME classifies the same
    remote = RuntimeError("remote poison")
    remote.remote_type = "PoisonTaskError"
    assert policy.classify(remote) is Classification.FAIL_FAST


# -- quarantine in map_unordered -----------------------------------------


def _worker_lost(kind="abrupt"):
    from cubed_tpu.runtime.distributed import (
        WorkerDrainedError,
        WorkerLostError,
    )

    if kind == "drained":
        return WorkerDrainedError("worker w0 drained (preemption notice)")
    return WorkerLostError("worker w0 died abruptly (exitcode 137)")


def test_map_unordered_quarantines_abrupt_worker_fatal_strikes():
    """One input whose task takes out its worker on every attempt: after
    max_requeues + 1 abrupt losses the quarantine convicts THAT input
    with a PoisonTaskError naming it, instead of requeueing forever."""
    calls = {"poison": 0}

    def work(i, config=None):
        if i == 3:
            calls["poison"] += 1
            raise _worker_lost("abrupt")
        return i

    before = get_registry().snapshot()
    t0 = time.time()
    policy = RetryPolicy(retries=2, backoff_base=0.01, max_requeues=2)
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        with pytest.raises(PoisonTaskError) as exc_info:
            map_unordered(
                pool, work, list(range(8)), retry_policy=policy
            )
    err = exc_info.value
    # K = max_requeues + 1 consecutive worker-fatal attempts convicts
    assert err.attempts == policy.max_requeues + 1 == calls["poison"]
    assert err.chunk == "3"
    delta = get_registry().snapshot_delta(before)
    assert delta.get("poison_quarantined", 0) == 1
    quarantines = [
        d for d in decisions_since(t0) if d["kind"] == "poison_quarantine"
    ]
    assert quarantines and quarantines[0]["chunk"] == "3"
    assert quarantines[0]["attempts"] == err.attempts


def test_clean_worker_drains_never_count_as_poison_strikes():
    """A drain/preemption is the INFRASTRUCTURE's announced exit, not
    evidence about the task: the same number of consecutive losses that
    would convict a poison task requeues for free and completes."""
    failures = {"n": 0}

    def work(i, config=None):
        if i == 3 and failures["n"] < 3:  # 3 would convict if abrupt
            failures["n"] += 1
            raise _worker_lost("drained")
        return i

    before = get_registry().snapshot()
    policy = RetryPolicy(retries=2, backoff_base=0.01, max_requeues=3)
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        map_unordered(pool, work, list(range(8)), retry_policy=policy)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("poison_quarantined", 0) == 0
    assert delta.get("worker_loss_requeues", 0) >= 3


def test_quarantine_cancels_pending_work_for_the_request():
    """The conviction ends the WHOLE request promptly: siblings that
    never ran are cancelled rather than executed after the verdict."""
    started = set()

    def work(i, config=None):
        started.add(i)
        if i == 0:
            raise _worker_lost("abrupt")
        time.sleep(0.3)  # siblings outlive the ~2 instant poison strikes
        return i

    policy = RetryPolicy(retries=1, backoff_base=0.01, max_requeues=1)
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        with pytest.raises(PoisonTaskError):
            map_unordered(
                pool, work, list(range(16)), retry_policy=policy,
                batch_size=4,
            )
    # the verdict lands inside the first batch: later batches are never
    # pulled, so the tail of the input list never starts
    assert started <= set(range(8)) and len(started) < 16
