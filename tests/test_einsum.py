"""Chunked einsum (beyond-standard extension; no reference counterpart).

One n-ary blockwise contraction + tree-sum; shared labels unify chunks."""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp


def asnp(x):
    return np.asarray(x.compute())


CASES = [
    ("ij,jk->ik", [(20, 12), (12, 8)], [(5, 4), (4, 4)]),
    ("ij,jk", [(6, 5), (5, 7)], [(3, 5), (5, 7)]),
    ("bij,bjk->bik", [(3, 6, 5), (3, 5, 4)], [(1, 3, 5), (1, 5, 2)]),
    ("i,i->", [(24,), (24,)], [(6,), (8,)]),
    ("ij,ij->ij", [(6, 4), (6, 4)], [(3, 2), (2, 4)]),
    ("i,j->ij", [(5,), (7,)], [(2,), (3,)]),
    ("abc,cd,be->ade", [(3, 4, 5), (5, 6), (4, 2)],
     [(1, 2, 5), (5, 3), (2, 2)]),
    ("ijk->ki", [(3, 4, 5)], [(1, 2, 5)]),
    ("ij->", [(5, 6)], [(2, 3)]),
    ("ij,kj->ik", [(4, 6), (5, 6)], [(2, 3), (5, 2)]),
]


@pytest.mark.parametrize("subscripts,shapes,chunksets", CASES)
def test_einsum_matches_numpy(spec, subscripts, shapes, chunksets):
    rng = np.random.default_rng(0)
    arrs_np = [rng.standard_normal(s) for s in shapes]
    arrs = [
        ct.from_array(a, chunks=c, spec=spec)
        for a, c in zip(arrs_np, chunksets)
    ]
    np.testing.assert_allclose(
        asnp(xp.einsum(subscripts, *arrs)),
        np.einsum(subscripts, *arrs_np),
        atol=1e-10,
    )


def test_einsum_on_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    rng = np.random.default_rng(1)
    an, bn = rng.standard_normal((16, 12)), rng.standard_normal((12, 10))
    a = ct.from_array(an, chunks=(4, 4), spec=spec)
    b = ct.from_array(bn, chunks=(4, 5), spec=spec)
    got = np.asarray(
        xp.einsum("ij,jk->ik", a, b).compute(executor=JaxExecutor())
    )
    np.testing.assert_allclose(got, an @ bn, atol=1e-8)


def test_einsum_contraction_larger_than_memory(tmp_path):
    # contracted axis spans many chunks; every task touches only blocks
    rng = np.random.default_rng(2)
    an = rng.standard_normal((8, 4000))
    bn = rng.standard_normal((4000, 8))
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem=300_000)
    a = ct.from_array(an, chunks=(8, 250), spec=spec)
    b = ct.from_array(bn, chunks=(250, 8), spec=spec)
    np.testing.assert_allclose(
        asnp(xp.einsum("ij,jk->ik", a, b)), an @ bn, atol=1e-8
    )


def test_einsum_validation(spec):
    a = ct.from_array(np.ones((3, 3)), chunks=(3, 3), spec=spec)
    with pytest.raises(NotImplementedError, match="ellipsis"):
        xp.einsum("...i,i->...", a, a)
    with pytest.raises(NotImplementedError, match="repeated"):
        xp.einsum("ii->i", a)
    with pytest.raises(ValueError, match="operand"):
        xp.einsum("ij,jk->ik", a)
    with pytest.raises(ValueError, match="dimensions"):
        xp.einsum("ijk->k", a)
    bi = ct.from_array(np.ones((3, 3), dtype=bool), chunks=(3, 3), spec=spec)
    with pytest.raises(TypeError):
        xp.einsum("ij,jk->ik", bi, bi)


def test_einsum_dtype_applies_to_block_contraction(spec):
    # int32 products would overflow per block without the dtype cast
    an = np.full((4, 64), 100_000_000, dtype=np.int32)
    bn = np.full((64, 4), 1, dtype=np.int32)
    a = ct.from_array(an, chunks=(4, 16), spec=spec)
    b = ct.from_array(bn, chunks=(16, 4), spec=spec)
    got = asnp(xp.einsum("ij,jk->ik", a, b, dtype=np.float64))
    np.testing.assert_allclose(
        got, np.einsum("ij,jk->ik", an, bn, dtype=np.float64)
    )


def test_einsum_label_size_mismatch_names_label(spec):
    a = ct.from_array(np.ones((2, 3)), chunks=(2, 3), spec=spec)
    b = ct.from_array(np.ones((4, 2)), chunks=(4, 2), spec=spec)
    with pytest.raises(ValueError, match="label 'j'"):
        xp.einsum("ij,jk->ik", a, b)
