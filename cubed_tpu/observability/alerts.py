"""Alert rules over the live time series: threshold + burn-rate + stall.

The rules run inside the telemetry sampler's ~1s tick
(``observability/timeseries.py``), so alert latency is one sampling
interval. A firing is never just a log line — it lands everywhere an
operator might be looking:

- the ``alerts_fired`` counter (per-rule visibility via the firing ring),
- a ``scheduler``-lane decision (``record_decision("alert_fired", ...)``)
  — which means the flight-recorder bundle and ``python -m
  cubed_tpu.diagnose`` both show the alert timeline for free,
- a structured warning on the ``cubed_tpu`` logger (compute-correlated
  when one is running),
- the engine's bounded firing ring, served by ``/snapshot.json`` and the
  ``cubed_tpu.top`` dashboard.

Rules fire on the rising edge (condition flips false->true) and re-fire
while still active only after ``cooldown_s`` — a sustained condition
reads as one alert per cooldown window, not one per second.

The default rule set (:func:`default_rules`) covers the failure shapes
the PRs so far taught the runtime to survive — so an operator sees them
*while* the machinery absorbs them, not in the post-mortem: retry-budget
burn, a half-pressured fleet, a straggler burst, a stalled queue, and a
peer-fetch fallback spike.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Optional

from .metrics import get_registry

logger = logging.getLogger(__name__)

#: firings retained for /snapshot.json, the dashboard and the bundle
MAX_FIRINGS = 256


class AlertRule:
    """One named condition over the telemetry store.

    Subclasses implement ``evaluate(store, now) -> Optional[dict]``: None
    while healthy, else a dict of firing details (at least ``value`` and
    ``threshold``). ``severity`` is ``"warning"`` or ``"critical"``
    (display only — every firing takes the same paths)."""

    def __init__(self, name: str, description: str = "",
                 severity: str = "warning"):
        self.name = name
        self.description = description
        self.severity = severity

    def evaluate(self, store, now: float) -> Optional[dict]:
        raise NotImplementedError


class ThresholdRule(AlertRule):
    """Fire when a series' latest value (or its rate over ``window_s``
    when ``rate=True``) crosses ``threshold``.

    ``comparison`` is ``">="`` or ``"<="``. A missing series is healthy —
    absence of data must not page anyone — and so is a FROZEN one: a
    latest-value reading older than ``stale_after_s`` means its writer is
    gone (a closed fleet, a finished compute), and a long-lived telemetry
    endpoint must not re-fire on that fossil every cooldown forever."""

    #: latest-value samples older than this are treated as no-data (the
    #: sampler ticks at ~1s, so 10 missed writes means the writer is gone)
    STALE_AFTER_S = 10.0

    def __init__(
        self, name: str, metric: str, threshold: float,
        comparison: str = ">=", rate: bool = False, window_s: float = 30.0,
        labels: Optional[dict] = None, description: str = "",
        severity: str = "warning", stale_after_s: Optional[float] = None,
    ):
        super().__init__(name, description, severity)
        if comparison not in (">=", "<="):
            raise ValueError(
                f"comparison must be '>=' or '<=', got {comparison!r}"
            )
        self.metric = metric
        self.threshold = float(threshold)
        self.comparison = comparison
        self.rate = rate
        self.window_s = float(window_s)
        self.labels = labels
        self.stale_after_s = (
            self.STALE_AFTER_S if stale_after_s is None
            else float(stale_after_s)
        )

    def evaluate(self, store, now: float) -> Optional[dict]:
        if self.rate:
            value = store.rate(
                self.metric, self.window_s, labels=self.labels, now=now
            )
        else:
            pt = store.latest_point(self.metric, labels=self.labels)
            value = None
            if pt is not None and now - pt[0] <= self.stale_after_s:
                value = pt[1]
        if value is None:
            return None
        crossed = (
            value >= self.threshold if self.comparison == ">="
            else value <= self.threshold
        )
        if not crossed:
            return None
        return {
            "metric": self.metric,
            "value": round(float(value), 6),
            "threshold": self.threshold,
            "comparison": self.comparison,
            "window_s": self.window_s if self.rate else None,
        }


class BurnRateRule(AlertRule):
    """Fire when a cumulative counter consumes more than ``burn_frac`` of
    ``budget`` within ``window_s`` — the classic error-budget burn alert,
    here sized for bounded allowances like the per-compute retry budget:
    spending 10% of the whole allowance inside one window means the
    failures are systemic, and the circuit breaker is where this ends."""

    def __init__(
        self, name: str, counter: str, budget: float,
        burn_frac: float = 0.1, window_s: float = 60.0,
        description: str = "", severity: str = "critical",
    ):
        super().__init__(name, description, severity)
        self.counter = counter
        self.budget = float(budget)
        self.burn_frac = float(burn_frac)
        self.window_s = float(window_s)

    def evaluate(self, store, now: float) -> Optional[dict]:
        pts = store.window(self.counter, self.window_s, now=now)
        if len(pts) < 2:
            return None
        burned = max(0.0, pts[-1][1] - pts[0][1])
        allowance = self.budget * self.burn_frac
        if burned < max(allowance, 1.0):
            return None
        return {
            "metric": self.counter,
            "value": burned,
            "threshold": allowance,
            "budget": self.budget,
            "window_s": self.window_s,
        }


class StallRule(AlertRule):
    """Fire when work is queued but nothing completes: ``gauge_metric``
    (latest) is positive while ``progress_counter`` shows zero increase
    over ``window_s`` — the queue-depth stall shape (a wedged fleet, a
    dead dispatch loop, an all-pressured admission floor)."""

    def __init__(
        self, name: str, gauge_metric: str = "queue_depth",
        progress_counter: str = "tasks_completed", window_s: float = 30.0,
        description: str = "", severity: str = "critical",
    ):
        super().__init__(name, description, severity)
        self.gauge_metric = gauge_metric
        self.progress_counter = progress_counter
        self.window_s = float(window_s)

    def evaluate(self, store, now: float) -> Optional[dict]:
        depth = store.latest(self.gauge_metric)
        if not depth:
            return None
        # the queue must have been non-empty for the WHOLE window — a
        # queue that just filled is starting, not stalled
        depth_pts = store.window(self.gauge_metric, self.window_s, now=now)
        if len(depth_pts) < 2 or depth_pts[0][0] > now - self.window_s * 0.8:
            return None
        if any(v <= 0 for _, v in depth_pts):
            return None
        rate = store.rate(self.progress_counter, self.window_s, now=now)
        # a MISSING progress series is zero progress, not health: a fleet
        # wedged before the first task ever completes never creates the
        # tasks_completed counter at all — and the full-window depth
        # series above already proves the sampler covered the window
        if rate is not None and rate > 0:
            return None
        return {
            "metric": self.gauge_metric,
            "value": depth,
            "threshold": 0,
            "progress_counter": self.progress_counter,
            "window_s": self.window_s,
        }


class TenantStarvationRule(AlertRule):
    """Fire when any tenant has queued work for a whole window with zero
    completions — the multi-tenant stall shape (a wedged service
    dispatcher, a fair-share weight misconfigured to ~0, or every slot
    pinned by another tenant's long computes).

    Evaluates every ``tenant_queued{tenant=...}`` series the telemetry
    sampler maintains (one per tenant the service has seen), so new
    tenants are covered the tick they first queue work. A starving tenant
    must show a positive queue across the ENTIRE window while its
    ``tenant_completed`` counter shows no increase."""

    def __init__(
        self, name: str = "tenant_starvation", window_s: float = 30.0,
        description: str = "", severity: str = "critical",
    ):
        super().__init__(name, description, severity)
        self.window_s = float(window_s)

    def evaluate(self, store, now: float) -> Optional[dict]:
        starving = []
        worst = 0.0
        for sname, labels, _latest in store.latest_series():
            if sname != "tenant_queued" or "tenant" not in labels:
                continue
            pts = store.window(sname, self.window_s, labels=labels, now=now)
            # queued for the WHOLE window (same discipline as StallRule:
            # a queue that just filled is starting, not starved)
            if len(pts) < 2 or pts[0][0] > now - self.window_s * 0.8:
                continue
            if any(v <= 0 for _, v in pts):
                continue
            rate = store.rate(
                "tenant_completed", self.window_s, labels=labels, now=now,
            )
            if rate is not None and rate > 0:
                continue
            starving.append(labels["tenant"])
            worst = max(worst, pts[-1][1])
        if not starving:
            return None
        return {
            "metric": "tenant_queued",
            "value": worst,
            "threshold": 0,
            "tenants": sorted(starving),
            "window_s": self.window_s,
        }


class DispatchSaturationRule(AlertRule):
    """Fire when the dispatch loop is pegged while the queue deepens:
    ``dispatch_utilization`` at or above ``threshold`` across the ENTIRE
    window while ``queue_depth`` stays positive and does not shrink — the
    coordinator-saturation shape the fleet-scaling curve collapses on
    (efficiency 0.21/0.05 at 16/32 workers, ROADMAP item 1). Distinct
    from :class:`StallRule`: tasks ARE completing, the host just can't
    dispatch them any faster — adding workers past this point buys
    nothing (see docs/operations.md for the first moves)."""

    def __init__(
        self, name: str = "dispatch_saturation", threshold: float = 0.9,
        window_s: float = 20.0, description: str = "",
        severity: str = "critical",
    ):
        super().__init__(name, description, severity)
        self.threshold = float(threshold)
        self.window_s = float(window_s)

    def evaluate(self, store, now: float) -> Optional[dict]:
        util_pts = store.window(
            "dispatch_utilization", self.window_s, now=now
        )
        # utilization pegged for the WHOLE window (same full-coverage
        # discipline as StallRule: a briefly-busy loop is working, not
        # saturated)
        if len(util_pts) < 2 or util_pts[0][0] > now - self.window_s * 0.8:
            return None
        if any(v < self.threshold for _, v in util_pts):
            return None
        depth_pts = store.window("queue_depth", self.window_s, now=now)
        if len(depth_pts) < 2 or any(v <= 0 for _, v in depth_pts):
            return None
        if depth_pts[-1][1] < depth_pts[0][1]:
            return None  # the backlog is draining: saturated but coping
        return {
            "metric": "dispatch_utilization",
            "value": round(float(util_pts[-1][1]), 6),
            "threshold": self.threshold,
            "queue_depth": depth_pts[-1][1],
            "window_s": self.window_s,
        }


class SloBurnRateRule(AlertRule):
    """Multi-window multi-burn-rate SLO alert (the SRE-workbook shape).

    The service's :class:`~cubed_tpu.observability.slo.SloBoard`
    publishes each tenant's burn rate over four windows as
    ``slo_burn_{5m,1h,6h,3d}{tenant=...}`` series (burn 1.0 = spending
    the error budget exactly as fast as the objective tolerates). A
    rule pairs a LONG window (the page signal: enough evidence that the
    budget is truly bleeding) with a SHORT window (the reset signal:
    the alert clears quickly once the bleeding stops) and fires for any
    tenant whose burn exceeds ``threshold`` on BOTH.

    Two instances ship in :func:`default_rules`: ``slo_fast_burn``
    (5m + 1h at 14.4x — page-grade, that pace empties a 3-day budget in
    ~5 hours) and ``slo_slow_burn`` (6h + 3d at 1x — warn-grade, a
    sustained slow leak). Stale series (a closed service) are no-data,
    not a firing."""

    STALE_AFTER_S = 10.0

    def __init__(
        self, name: str, long_window: str, short_window: str,
        threshold: float, description: str = "",
        severity: str = "warning",
    ):
        super().__init__(name, description, severity)
        self.long_series = f"slo_burn_{long_window}"
        self.short_series = f"slo_burn_{short_window}"
        self.threshold = float(threshold)

    def evaluate(self, store, now: float) -> Optional[dict]:
        burning = []
        worst = 0.0
        for sname, labels, _latest in store.latest_series():
            if sname != self.long_series or "tenant" not in labels:
                continue
            long_pt = store.latest_point(self.long_series, labels=labels)
            short_pt = store.latest_point(self.short_series, labels=labels)
            ok = True
            for pt in (long_pt, short_pt):
                if pt is None or now - pt[0] > self.STALE_AFTER_S:
                    ok = False  # a frozen board must not page forever
                    break
            if not ok:
                continue
            if long_pt[1] >= self.threshold and short_pt[1] >= self.threshold:
                burning.append(labels["tenant"])
                worst = max(worst, float(long_pt[1]), float(short_pt[1]))
        if not burning:
            return None
        return {
            "metric": self.long_series,
            "value": round(worst, 4),
            "threshold": self.threshold,
            "tenants": sorted(burning),
            "short_window": self.short_series,
        }


def default_rules(retry_budget_hint: float = 50.0) -> list:
    """The standing rule set, covering the runtime's known failure shapes.

    ``retry_budget_hint`` sizes the burn-rate rule when no compute-specific
    budget is known (the resilience layer sizes real budgets off the task
    count; 50 matches a mid-sized compute's allowance)."""
    return [
        BurnRateRule(
            "retry_budget_burn", counter="task_retries",
            budget=retry_budget_hint, burn_frac=0.2, window_s=60.0,
            description="task retries consumed >=20% of the retry budget "
            "within a minute: failures are systemic, the circuit breaker "
            "is next",
        ),
        ThresholdRule(
            "fleet_memory_pressure", metric="fleet_pressured_fraction",
            threshold=0.5, severity="critical",
            description=">=50% of live fleet workers report memory "
            "pressure: admission control is degrading throughput; raise "
            "allowed_mem, shrink chunks, or add workers",
        ),
        ThresholdRule(
            "straggler_rate", metric="stragglers_detected", rate=True,
            threshold=0.2, window_s=30.0,
            description="stragglers detected faster than 1 per 5s over "
            "30s: a slow worker or skewed chunking is serializing the "
            "compute",
        ),
        StallRule(
            "queue_depth_stall",
            description="tasks are queued but none completed for a whole "
            "window: a wedged fleet or a dead dispatch loop",
        ),
        ThresholdRule(
            "peer_fetch_fallback_spike", metric="peer_fetch_fallbacks",
            rate=True, threshold=1.0, window_s=30.0,
            description="peer fetches falling back to the store >1/s: "
            "the p2p data plane is degraded (cache pressure, peer churn, "
            "or network faults) — correctness is unaffected, the "
            "store-read savings are gone",
        ),
        TenantStarvationRule(
            description="a tenant has had queued requests for a whole "
            "window with zero completions: check the service dispatcher, "
            "the tenant's quota weight, and whether another tenant's "
            "long computes hold every admission slot",
        ),
        DispatchSaturationRule(
            description="the dispatch loop ran >=90% busy for a whole "
            "window while the ready queue kept growing: the coordinator "
            "is the bottleneck, not the fleet — check the top DISPATCH "
            "panel, pull the folded dispatch profile, reduce fleet size "
            "or batch dispatch",
        ),
        ThresholdRule(
            "store_brownout", metric="store_throttled", rate=True,
            threshold=0.5, window_s=30.0, severity="critical",
            description="the store is answering 429/503/SlowDown faster "
            "than 1 per 2s over 30s: a brownout — the per-store health "
            "breaker is pacing storage concurrency (check "
            "store_breaker_state); expect degraded throughput, raise "
            "provisioned store throughput or lean on the peer data plane",
        ),
        ThresholdRule(
            "overload_shedding", metric="overload_level",
            threshold=2, severity="critical",
            description="the service's degradation ladder reached L2+: "
            "deadline-infeasible requests are failed at admission and "
            "new batch submits are rejected with retry-after hints "
            "(L3 rejects everything) — check the top OVERLOAD row, the "
            "overload_level decisions for the signals that drove it, "
            "and drain or widen the fleet",
        ),
        ThresholdRule(
            "tenant_breaker_open", metric="tenant_breakers_open",
            threshold=1,
            description="at least one tenant's circuit breaker is open "
            "(consecutive request failures hit the trip threshold): "
            "that tenant's submits are rejected until a half-open probe "
            "succeeds — check its tenant_breaker decisions and whether "
            "a poison request (poison_quarantine) is the root cause",
        ),
        SloBurnRateRule(
            "slo_fast_burn", long_window="1h", short_window="5m",
            threshold=14.4, severity="critical",
            description="a tenant's SLO error budget is burning >=14.4x "
            "faster than its objective tolerates on BOTH the 1h and 5m "
            "windows — at this pace a 3-day budget empties in ~5 hours; "
            "page-grade: check the top SLO panel, the tenant's "
            "slo_request_latency quantiles, and run "
            "python -m cubed_tpu.regress to name the regressed bucket",
        ),
        SloBurnRateRule(
            "slo_slow_burn", long_window="3d", short_window="6h",
            threshold=1.0, severity="warning",
            description="a tenant's SLO error budget is being spent "
            "faster than it accrues on BOTH the 3d and 6h windows — a "
            "sustained slow leak that will exhaust the budget before "
            "the compliance window rolls; warn-grade: schedule the "
            "regression hunt before it becomes a page",
        ),
    ]


class AlertEngine:
    """Evaluates rules against a :class:`TimeSeriesStore` each tick."""

    def __init__(
        self, store, rules: Optional[list] = None, cooldown_s: float = 60.0,
    ):
        self.store = store
        self.rules = list(rules) if rules is not None else default_rules()
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        #: rule name -> {"active": bool, "last_fired": ts}
        self._state = {
            r.name: {"active": False, "last_fired": 0.0} for r in self.rules
        }
        self.firings: deque = deque(maxlen=MAX_FIRINGS)

    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            self.rules.append(rule)
            self._state[rule.name] = {"active": False, "last_fired": 0.0}

    def tick(self, now: Optional[float] = None) -> list:
        """Evaluate every rule; returns the firings this tick produced."""
        if now is None:
            now = time.time()
        fired = []
        with self._lock:
            rules = list(self.rules)
        for rule in rules:
            try:
                details = rule.evaluate(self.store, now)
            except Exception:
                logger.exception("alert rule %s failed to evaluate", rule.name)
                continue
            state = self._state.setdefault(
                rule.name, {"active": False, "last_fired": 0.0}
            )
            if details is None:
                state["active"] = False
                continue
            rising = not state["active"]
            state["active"] = True
            if not rising and now - state["last_fired"] < self.cooldown_s:
                continue  # sustained condition inside its cooldown window
            state["last_fired"] = now
            firing = self._fire(rule, details, now)
            fired.append(firing)
        return fired

    def _fire(self, rule: AlertRule, details: dict, now: float) -> dict:
        from .collect import record_decision

        firing = {
            "ts": now,
            "rule": rule.name,
            "severity": rule.severity,
            "description": rule.description,
        }
        firing.update(details)
        with self._lock:
            self.firings.append(firing)
        get_registry().counter("alerts_fired").inc()
        record_decision(
            "alert_fired", rule=rule.name, severity=rule.severity,
            metric=details.get("metric"), value=details.get("value"),
            threshold=details.get("threshold"),
        )
        logger.warning(
            "ALERT %s [%s]: %s=%s crossed %s — %s",
            rule.name, rule.severity, details.get("metric"),
            details.get("value"), details.get("threshold"),
            rule.description or "(no description)",
        )
        return firing

    def recent(self, n: int = 50) -> list:
        """The last ``n`` firings, oldest first."""
        with self._lock:
            return list(self.firings)[-n:]

    def active(self) -> list:
        """Names of rules currently in the active (condition-true) state."""
        with self._lock:
            return [name for name, s in self._state.items() if s["active"]]


def format_alert_row(firing: dict) -> str:
    """One firing as a fixed-width row — the shared format both
    ``python -m cubed_tpu.top`` and ``python -m cubed_tpu.diagnose``
    render (callers prepend their own timestamp/flag column)."""
    return (
        f"{firing.get('severity', '?'):<9}"
        f"{firing.get('rule', '?'):<28}"
        f"{firing.get('metric', '')}={firing.get('value', '')} "
        f"(threshold {firing.get('threshold', '')})"
    )
