"""Straggler-backup policy unit tests. Reference parity:
cubed/tests/runtime/test_backup.py."""

from cubed_tpu.runtime.backup import should_launch_backup


def test_not_enough_started():
    start = {i: 0.0 for i in range(5)}
    end = {i: 1.0 for i in range(4)}
    assert not should_launch_backup(4, 100.0, start, end)


def test_not_enough_completed():
    start = {i: 0.0 for i in range(20)}
    end = {i: 1.0 for i in range(5)}  # <50%
    assert not should_launch_backup(19, 100.0, start, end)


def test_not_slow_enough():
    start = {i: 0.0 for i in range(20)}
    end = {i: 1.0 for i in range(15)}
    # median duration 1.0; task at 2.5x is under the 3x threshold
    assert not should_launch_backup(19, 2.5, start, end)


def test_backup_launched_for_straggler():
    start = {i: 0.0 for i in range(20)}
    end = {i: 1.0 for i in range(15)}
    assert should_launch_backup(19, 3.5, start, end)
