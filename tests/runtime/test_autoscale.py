"""Autoscaler policy-loop unit tests.

These drive :meth:`Autoscaler.tick` synchronously against a fake
coordinator/factory, so every decision — backfill, hysteresis, cooldowns,
straggler pressure, the memory-pressure veto, graceful scale-down — is
asserted without subprocesses or timing races. The end-to-end elastic
behavior (real fleet, real preemption) lives in
``test_chaos.py::test_chaos_spot_preemption_autoscaler_backfills_sublinear``
and ``test_distributed.py``.
"""

from __future__ import annotations

import pytest

from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.autoscale import (
    Autoscaler,
    AutoscalePolicy,
    WorkerFactory,
)


class FakeCoordinator:
    def __init__(self):
        self.workers: dict = {}  # name -> row
        self.drained: list = []

    def add(self, name, outstanding=0, nthreads=1, pressured=False,
            draining=False):
        self.workers[name] = dict(
            name=name, outstanding=outstanding, nthreads=nthreads,
            pressured=pressured, draining=draining,
        )

    def load_view(self):
        return [dict(row) for row in self.workers.values()]

    def request_drain(self, name, grace_s=30.0, reason="scale_down"):
        if name not in self.workers:
            return False
        self.workers[name]["draining"] = True
        self.drained.append((name, reason))
        return True


class FakeFactory(WorkerFactory):
    def __init__(self, coordinator):
        self.coordinator = coordinator
        self.started: list = []
        self.stopped: list = []
        self._next = 0

    def start_worker(self):
        name = f"w-{self._next}"
        self._next += 1
        self.started.append(name)
        return name

    def stop_worker(self, name):
        self.stopped.append(name)
        self.coordinator.workers.pop(name, None)


def mk(policy=None, initial=2, pending=None, coordinator=None):
    coord = coordinator or FakeCoordinator()
    factory = FakeFactory(coord)
    scaler = Autoscaler(
        coord, factory=factory,
        policy=policy or AutoscalePolicy(min_workers=1, max_workers=4),
        initial_workers=initial, pending_workers=pending,
    )
    return coord, factory, scaler


def test_backfill_replaces_lost_workers_immediately():
    coord, factory, scaler = mk(initial=3)
    coord.add("a"), coord.add("b"), coord.add("c")
    scaler.tick()
    assert factory.started == []  # fleet healthy: nothing to do
    del coord.workers["b"]  # preempted/crashed
    scaler.tick()
    assert len(factory.started) == 1  # replaced without any cooldown
    assert scaler.stats["workers_scaled_up"] == 1
    # the spawn is pending: no double-backfill while it boots
    scaler.tick()
    assert len(factory.started) == 1


def test_pending_spawn_that_registers_then_dies_is_backfilled():
    """A replacement that joins and is immediately preempted must read as
    a hole again, not as still-pending capacity (the bug class: pending
    entries only cleared against *currently*-live names)."""
    coord, factory, scaler = mk(initial=2)
    coord.add("a")
    scaler.tick()  # backfills one
    name = factory.started[0]
    coord.add(name)
    scaler.tick()  # registered: pending settled
    del coord.workers[name]  # ...and instantly preempted
    scaler.tick()
    assert len(factory.started) == 2


def test_pending_spawn_that_dies_before_registering_is_backfilled():
    """A spawn preempted mid-boot never registers, so the ever-joined set
    can't settle it; the factory's spawn_failed probe must reopen the slot
    immediately instead of stalling for spawn_pending_timeout_s."""
    coord, factory, scaler = mk(initial=2, pending=["a", "b"])
    dead = set()
    factory.spawn_failed = lambda name: name in dead
    coord.add("a")
    scaler.tick()
    assert factory.started == []  # "b" still booting: not damage yet
    dead.add("b")  # SIGTERMed before it ever joined
    scaler.tick()
    assert len(factory.started) == 1  # slot reopened and backfilled now
    assert scaler.stats["workers_scaled_up"] == 1


def test_initial_pending_workers_suppress_startup_backfill():
    coord, factory, scaler = mk(initial=2, pending=["a", "b"])
    scaler.tick()  # nothing registered yet: still booting, not damage
    assert factory.started == []
    coord.add("a"), coord.add("b")
    scaler.tick()
    assert factory.started == []


def test_scale_up_on_queue_depth_with_cooldown():
    policy = AutoscalePolicy(
        min_workers=1, max_workers=4, scale_up_queue_per_thread=4.0,
        cooldown_up_s=3600.0,
    )
    coord, factory, scaler = mk(policy=policy, initial=2)
    coord.add("a", outstanding=10), coord.add("b", outstanding=10)
    scaler.tick()
    assert len(factory.started) == 1 and scaler.desired == 3
    coord.add(factory.started[0], outstanding=0)
    scaler.tick()  # still loaded, but inside the up-cooldown
    assert len(factory.started) == 1
    scaler._last_up = -1e9  # cooldown elapsed
    scaler.tick()
    assert len(factory.started) == 2 and scaler.desired == 4
    # max_workers is a hard ceiling
    coord.add(factory.started[1], outstanding=0)
    scaler._last_up = -1e9
    scaler.tick()
    assert scaler.desired == 4 and len(factory.started) == 2


def test_scale_up_vetoed_under_memory_pressure():
    policy = AutoscalePolicy(min_workers=1, max_workers=4)
    coord, factory, scaler = mk(policy=policy, initial=2)
    coord.add("a", outstanding=20, pressured=True)
    coord.add("b", outstanding=20, pressured=True)
    scaler.tick()
    assert factory.started == []  # more workers would deepen the pressure
    assert scaler.desired == 2


def test_straggler_pressure_triggers_scale_up():
    policy = AutoscalePolicy(
        min_workers=1, max_workers=4, straggler_pressure=2
    )
    coord, factory, scaler = mk(policy=policy, initial=2)
    coord.add("a", outstanding=1), coord.add("b", outstanding=1)
    scaler.tick()
    assert factory.started == []  # shallow queue, no stragglers
    get_registry().counter("stragglers_detected").inc(2)
    scaler.tick()
    assert len(factory.started) == 1  # backups need somewhere to run


def test_idle_fleet_ignores_foreign_straggler_detections():
    """stragglers_detected is process-global: detections from some OTHER
    compute running in the same client process must not scale an idle
    fleet (a straggler on this fleet implies in-flight work here)."""
    policy = AutoscalePolicy(
        min_workers=1, max_workers=4, straggler_pressure=2
    )
    coord, factory, scaler = mk(policy=policy, initial=2)
    coord.add("a", outstanding=0), coord.add("b", outstanding=0)
    scaler.tick()
    get_registry().counter("stragglers_detected").inc(5)  # someone else's
    scaler.tick()
    assert factory.started == []  # no work here: not our stragglers


def test_scale_down_needs_sustained_idleness_then_drains_gracefully():
    policy = AutoscalePolicy(
        min_workers=1, max_workers=4, idle_rounds_before_down=3,
        cooldown_down_s=0.0, drain_grace_s=7.5,
    )
    coord, factory, scaler = mk(policy=policy, initial=3)
    coord.add("a", outstanding=0)
    coord.add("b", outstanding=1)
    coord.add("c", outstanding=0)
    scaler.tick(), scaler.tick()
    assert coord.drained == []  # hysteresis: 2 idle rounds are not enough
    scaler.tick()
    assert len(coord.drained) == 1
    name, reason = coord.drained[0]
    assert name in ("a", "c") and reason == "scale_down"  # least-loaded
    assert scaler.desired == 2
    assert factory.stopped == [name]  # reap follows the drain request
    assert scaler.stats["workers_scaled_down"] == 1


def test_overcapacity_above_desired_is_reconciled_down():
    """A fleet whose LIVE count exceeds the steering target (out-of-band
    joiners, or workers started above the ceiling) must be drained toward
    ``desired`` once idle — previously scale-down was gated purely on
    ``desired > min_workers``, so desired at min left overcapacity
    running forever."""
    policy = AutoscalePolicy(
        min_workers=1, max_workers=4, idle_rounds_before_down=1,
        cooldown_down_s=0.0,
    )
    coord = FakeCoordinator()
    scaler = Autoscaler(coord, factory=None, policy=policy, initial_workers=1)
    assert scaler.desired == 1
    for n in ("a", "b", "c"):  # three out-of-band workers join
        coord.add(n)
    scaler.tick()  # idle round
    scaler.tick()
    assert len(coord.drained) >= 1  # overcapacity shrinks toward desired
    assert scaler.desired == 1  # ...without pushing desired below target


def test_policy_rejects_min_above_max():
    with pytest.raises(ValueError, match="min_workers=5 exceeds"):
        AutoscalePolicy(min_workers=5, max_workers=2)


def test_executor_rejects_unsatisfiable_max_workers():
    from cubed_tpu.runtime.executors.distributed import (
        DistributedDagExecutor,
    )

    with pytest.raises(ValueError, match="max_workers=2 is below"):
        DistributedDagExecutor(n_local_workers=4, max_workers=2)


def test_scale_down_never_goes_below_min_workers():
    policy = AutoscalePolicy(
        min_workers=2, max_workers=4, idle_rounds_before_down=1,
        cooldown_down_s=0.0,
    )
    coord, factory, scaler = mk(policy=policy, initial=2)
    coord.add("a"), coord.add("b")
    for _ in range(5):
        scaler.tick()
    assert coord.drained == [] and scaler.desired == 2


def test_factory_none_skips_spawns_but_still_drains():
    """Listen-mode fleets (out-of-band workers) have no factory: the
    autoscaler cannot spawn, but graceful scale-down still works."""
    policy = AutoscalePolicy(
        min_workers=1, max_workers=4, idle_rounds_before_down=1,
        cooldown_down_s=0.0,
    )
    coord = FakeCoordinator()
    scaler = Autoscaler(coord, factory=None, policy=policy, initial_workers=3)
    coord.add("a"), coord.add("b"), coord.add("c")
    del coord.workers["b"]
    scaler.tick()  # a hole, but nothing to spawn with: no crash
    assert scaler.stats["workers_scaled_up"] == 0
    scaler.tick()
    assert len(coord.drained) == 1  # idle fleet still shrinks


def test_start_stop_runs_policy_loop():
    import time

    policy = AutoscalePolicy(min_workers=1, max_workers=2, interval_s=0.02)
    coord, factory, scaler = mk(policy=policy, initial=1)
    coord.add("a")
    scaler.start()
    try:
        deadline = time.monotonic() + 5
        while (
            scaler.stats["autoscaler_ticks"] < 3
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        assert scaler.stats["autoscaler_ticks"] >= 3
    finally:
        scaler.stop()
    ticks = scaler.stats["autoscaler_ticks"]
    time.sleep(0.1)
    assert scaler.stats["autoscaler_ticks"] == ticks  # loop actually stopped


def test_scale_up_with_live_surplus_spawns_only_the_shortfall():
    """Out-of-band joiners above the old desired already serve the new
    target: a scale-up step must spawn ``desired - n_active``, not the
    full step (previously a 5th worker was spawned when 4 live workers
    already covered desired=4, only for the overcapacity reconciler to
    drain it again)."""
    policy = AutoscalePolicy(
        min_workers=1, max_workers=8, scale_up_queue_per_thread=4.0,
    )
    coord, factory, scaler = mk(policy=policy, initial=3)
    for n in ("a", "b", "c", "d"):  # one more live than desired=3
        coord.add(n, outstanding=10)
    scaler.tick()
    assert scaler.desired == 4  # demand raised the steering target...
    assert factory.started == []  # ...but live surplus already covers it


def test_start_arms_backfill_grace_only_with_a_factory():
    """Without a factory (listen-mode, out-of-band workers) nothing can be
    backfilled: arming the coordinator's backfill grace would only convert
    a fast, actionable NoWorkersError into a pointless multi-second stall
    per submit attempt."""
    policy = AutoscalePolicy(min_workers=1, max_workers=4, interval_s=60.0)

    coord = FakeCoordinator()
    coord.backfill_grace_s = 0.0
    scaler = Autoscaler(coord, factory=None, policy=policy)
    scaler.start()
    try:
        assert coord.backfill_grace_s == 0.0  # no factory: left unarmed
    finally:
        scaler.stop()

    coord2 = FakeCoordinator()
    coord2.backfill_grace_s = 0.0
    coord2.add("a")
    _, factory, scaler2 = mk(policy=policy, initial=1, coordinator=coord2)
    scaler2.start()
    try:
        assert coord2.backfill_grace_s == policy.spawn_pending_timeout_s
    finally:
        scaler2.stop()
    assert coord2.backfill_grace_s == 0.0  # stop() disarms


def test_malformed_drain_grace_env_falls_back(monkeypatch):
    """A malformed CUBED_TPU_DRAIN_GRACE_S must not crash every worker at
    argparse construction (the fleet would fail to boot with only a
    wait_for_workers timeout as the diagnostic)."""
    from cubed_tpu.runtime.worker import _default_drain_grace

    monkeypatch.setenv("CUBED_TPU_DRAIN_GRACE_S", "30s")
    assert _default_drain_grace() == 10.0
    monkeypatch.setenv("CUBED_TPU_DRAIN_GRACE_S", "2.5")
    assert _default_drain_grace() == 2.5
    monkeypatch.delenv("CUBED_TPU_DRAIN_GRACE_S")
    assert _default_drain_grace() == 10.0


def test_worker_factory_abstract_contract():
    f = WorkerFactory()
    with pytest.raises(NotImplementedError):
        f.start_worker()
    with pytest.raises(NotImplementedError):
        f.stop_worker("x")
