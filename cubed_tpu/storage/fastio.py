"""ctypes bindings for the native parallel chunk-file reader (_fastio.c).

Compiled on first use with the system C compiler into a per-user cache dir;
every failure (no compiler, exotic platform) degrades to ``available() ==
False`` and callers keep the pure-Python read path. The binding layer stays
in Python; the GIL-free IO loop is native (see _fastio.c for why).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

#: reading fewer files than this isn't worth the call overhead
MIN_FILES = 4

_DEFAULT_THREADS = min(16, (os.cpu_count() or 1) * 4)  # IO-bound: oversubscribe


def _build() -> Optional[ctypes.CDLL]:
    src = os.path.join(os.path.dirname(__file__), "_fastio.c")
    if not os.path.exists(src):
        return None
    cache = os.environ.get(
        "CUBED_TPU_FASTIO_DIR",
        os.path.join(
            os.path.expanduser("~"), ".cache", "cubed_tpu_native"
        ),
    )
    os.makedirs(cache, exist_ok=True)
    so = os.path.join(cache, "_fastio.so")
    if not os.path.exists(so) or os.path.getmtime(so) < os.path.getmtime(src):
        cc = os.environ.get("CC", "cc")
        tmp = so + f".tmp{os.getpid()}"
        cmd = [cc, "-O2", "-shared", "-fPIC", "-pthread", src, "-o", tmp]
        try:
            subprocess.run(
                cmd, check=True, capture_output=True, timeout=120
            )
            os.replace(tmp, so)
        except Exception as e:  # no compiler / unsupported platform
            logger.debug("fastio build failed (%s); using Python IO", e)
            return None
    try:
        lib = ctypes.CDLL(so)
        lib.fastio_read_files.restype = ctypes.c_int
        lib.fastio_read_files.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_void_p),  # char** in C; ABI-compatible
            ctypes.POINTER(ctypes.c_long),
            ctypes.POINTER(ctypes.c_int),
            ctypes.c_int,
            ctypes.c_int,
        ]
        return lib
    except OSError as e:
        logger.debug("fastio load failed (%s); using Python IO", e)
        return None


def _get() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if not _tried:
            _lib = _build()
            _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


def read_files(
    paths: Sequence[str],
    buffers: Sequence[np.ndarray],
    nthreads: Optional[int] = None,
) -> list[int]:
    """Read each file fully into the matching contiguous uint8/byte buffer.

    Returns per-file status: 0 = ok, 1 = missing, 2 = error. Raises OSError
    if any file hit a hard IO error (status 2), after all reads finish.
    """
    lib = _get()
    assert lib is not None, "call available() first"
    n = len(paths)
    assert len(buffers) == n
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    c_dsts = (ctypes.c_void_p * n)()
    c_sizes = (ctypes.c_long * n)()
    for i, buf in enumerate(buffers):
        assert buf.flags["C_CONTIGUOUS"] and buf.flags["WRITEABLE"]
        c_dsts[i] = buf.ctypes.data
        c_sizes[i] = buf.nbytes
    c_status = (ctypes.c_int * n)()
    errs = lib.fastio_read_files(
        ctypes.cast(c_paths, ctypes.POINTER(ctypes.c_char_p)),
        ctypes.cast(c_dsts, ctypes.POINTER(ctypes.c_void_p)),
        ctypes.cast(c_sizes, ctypes.POINTER(ctypes.c_long)),
        ctypes.cast(c_status, ctypes.POINTER(ctypes.c_int)),
        n,
        nthreads or _DEFAULT_THREADS,
    )
    status = list(c_status)
    if errs:
        bad = [paths[i] for i, s in enumerate(status) if s == 2]
        raise OSError(f"fastio: {errs} files failed to read: {bad[:3]}...")
    return status
