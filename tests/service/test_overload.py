"""Overload robustness: the staged degradation ladder, deadline-aware
shedding, per-tenant circuit breakers, and the poison-request quarantine
working end to end.

Layers covered here:

- ladder units (immediate step-up, dwell-gated one-level step-down,
  hysteresis against flapping, the miss-rate signal);
- ``TenantBreaker`` units (trip / half-open probe / abort_probe /
  durable state surviving a process death);
- ``CostEstimator`` + the deadline-feasibility admission gate (fails
  OPEN cold, sheds with the typed error warm);
- the service submit gates (L2 sheds batch, L3 sheds all, retry-after
  attached, ``CUBED_TPU_OVERLOAD=off`` kill switch);
- the typed-rejection journal round trip (live + recovered) — the
  regression for ``RequestHandle.result()`` raising the SAME typed
  error with its retry-after hint after a service restart;
- SIGKILL mid-flood with a tripped breaker and L2 active (subprocess):
  restart recovers every accepted request, the poison tenant stays
  rejected by the durable breaker record;
- the live-fleet acceptance proof: 2x flood plus a seeded poison tenant
  on a real 2-worker fleet — the poison request fails with a
  ``PoisonTaskError`` naming op+chunk within its strike budget, zero
  workers are permanently lost, the innocent tenant keeps its
  deadlines, and the invariant audit is clean.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.observability.collect import decisions_since
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.service import (
    ComputeService,
    CostEstimator,
    DeadlineInfeasibleError,
    OverloadPolicy,
    ServiceOverloadedError,
    TenantBreaker,
)
from cubed_tpu.service.overload import (
    L0_NORMAL,
    L1_SHED_OPTIONAL,
    L2_SHED_LOAD,
    L3_EMERGENCY,
    OverloadController,
    current_overload_level,
    sheds_optional_work,
)


@pytest.fixture()
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


AN = np.arange(16, dtype=np.float64).reshape(4, 4)


def _build(spec, k=1.0, delay=0.0):
    def kernel(x, _k=k, _d=delay):
        if _d:
            time.sleep(_d)
        return x + _k

    a = ct.from_array(AN, chunks=(2, 2), spec=spec)
    return ct.map_blocks(kernel, a, dtype=np.float64)


def _build_bad(spec):
    def boom(x):
        raise ValueError("kernel exploded")

    a = ct.from_array(AN, chunks=(2, 2), spec=spec)
    return ct.map_blocks(boom, a, dtype=np.float64)


# ----------------------------------------------------------------------
# ladder units
# ----------------------------------------------------------------------


def _controller(**policy_kwargs):
    t = [1000.0]
    pol = OverloadPolicy(
        tick_interval_s=0.0, down_dwell_s=1.0, **policy_kwargs
    )
    ctl = OverloadController(pol, clock=lambda: t[0])
    return ctl, t


def test_ladder_steps_up_immediately_and_down_one_level_per_dwell():
    ctl, t = _controller(queue_l1=2, queue_l2=4, queue_l3=8)
    try:
        t0 = time.time()
        assert ctl.tick(0) == L0_NORMAL
        # overload response is immediate: straight to the justified level
        assert ctl.tick(9) == L3_EMERGENCY
        assert ctl.transitions == 1
        # recovery is deliberate: nothing before the dwell...
        assert ctl.tick(0) == L3_EMERGENCY  # arms the exit clock
        t[0] += 0.5
        assert ctl.tick(0) == L3_EMERGENCY
        # ...then exactly one level per dwell window
        t[0] += 0.6
        assert ctl.tick(0) == L2_SHED_LOAD
        assert ctl.tick(0) == L2_SHED_LOAD  # fresh dwell after each step
        t[0] += 1.1
        assert ctl.tick(0) == L1_SHED_OPTIONAL
        ctl.tick(0)
        t[0] += 1.1
        assert ctl.tick(0) == L0_NORMAL
        assert ctl.transitions == 4
        # every transition is a decision-ring record
        levels = [
            d for d in decisions_since(t0) if d["kind"] == "overload_level"
        ]
        assert len(levels) == 4
        assert levels[0]["to_level"] == L3_EMERGENCY
        assert levels[0]["queue_depth"] == 9
    finally:
        ctl.close()


def test_ladder_hysteresis_does_not_flap_around_a_threshold():
    """A queue sawtoothing between the exit and enter thresholds holds
    the level it reached: entering needs >= enter, leaving needs the
    queue below enter * exit_fraction for a whole dwell."""
    ctl, t = _controller(queue_l1=10, queue_l2=100, queue_l3=1000)
    try:
        assert ctl.tick(10) == L1_SHED_OPTIONAL
        for i in range(20):  # oscillate 6..9 — above exit (5), below enter
            t[0] += 0.3
            assert ctl.tick(6 + (i % 4)) == L1_SHED_OPTIONAL
        assert ctl.transitions == 1
    finally:
        ctl.close()


def test_deadline_miss_rate_drives_l2():
    ctl, t = _controller(queue_l2=1000, miss_min_samples=4)
    try:
        # below the sample floor the signal stays silent (cold start)
        for _ in range(3):
            ctl.note_completion(True)
        assert ctl.miss_rate() == 0.0
        assert ctl.tick(0) == L0_NORMAL
        ctl.note_completion(True)
        assert ctl.miss_rate() == 1.0
        t[0] += 0.1
        assert ctl.tick(0) == L2_SHED_LOAD
        # completions age out of the window
        t[0] += ctl.policy.miss_window_s + 1
        assert ctl.miss_rate() == 0.0
    finally:
        ctl.close()


def test_sheds_optional_work_reflects_live_controllers():
    base = current_overload_level()
    ctl, _ = _controller(queue_l1=1)
    try:
        assert ctl.tick(5) >= L1_SHED_OPTIONAL
        assert sheds_optional_work()
        assert current_overload_level() >= L1_SHED_OPTIONAL
    finally:
        ctl.close()
    # closing unpublishes: the module-level view falls back to the rest
    assert current_overload_level() == base


def test_retry_after_hint_is_bounded():
    ctl, _ = _controller()
    try:
        assert ctl.retry_after_s(0) >= ctl.policy.retry_after_min_s
        assert ctl.retry_after_s(10**6) == ctl.policy.retry_after_max_s
        # a known drain rate scales the estimate
        assert ctl.retry_after_s(10, drain_rate_s=2.0) == 20.0
    finally:
        ctl.close()


# ----------------------------------------------------------------------
# breaker + estimator units
# ----------------------------------------------------------------------


def test_breaker_trips_on_consecutive_failures_and_probes_half_open():
    t = [0.0]
    b = TenantBreaker("t", threshold=2, cooldown_s=10.0, clock=lambda: t[0])
    assert b.check() is None
    assert b.on_failure() is False  # 1 strike: below threshold
    b.on_success()  # success resets the streak
    assert b.strikes == 0
    assert b.on_failure() is False
    assert b.on_failure() is True  # tripped
    assert b.state == TenantBreaker.OPEN and b.is_open
    retry = b.check()
    assert retry is not None and 9.0 <= retry <= 10.0
    t[0] = 5.0
    assert 4.0 <= b.check() <= 5.0  # counts down the cooldown
    # cooldown elapsed: half-open admits exactly ONE probe
    t[0] = 10.5
    assert b.check() is None
    assert b.state == TenantBreaker.HALF_OPEN
    assert b.check() is not None  # second caller: probe slot taken
    # a probe that died of something else hands the slot back
    b.abort_probe()
    assert b.check() is None
    # a failed probe re-opens a fresh cooldown
    assert b.on_failure() is True
    assert b.state == TenantBreaker.OPEN
    t[0] = 21.0
    assert b.check() is None  # half-open again
    b.on_success()
    assert b.state == TenantBreaker.CLOSED and b.strikes == 0
    assert not b.is_open


def test_breaker_state_is_durable_and_half_open_reloads_open(tmp_path):
    path = str(tmp_path / "breaker.json")
    t = [0.0]
    b = TenantBreaker("t", threshold=1, cooldown_s=50.0, state_path=path,
                      clock=lambda: t[0])
    assert b.on_failure() is True
    # a fresh process (same path) comes back OPEN with the strike record
    t2 = [10.0]
    b2 = TenantBreaker("t", threshold=1, cooldown_s=50.0, state_path=path,
                       clock=lambda: t2[0])
    assert b2.state == TenantBreaker.OPEN and b2.strikes == 1
    assert b2.check() is not None
    # die while HALF_OPEN: the in-flight probe resolved nothing, so the
    # reload is conservative — OPEN, not half-open
    t2[0] = 60.1
    assert b2.check() is None and b2.state == TenantBreaker.HALF_OPEN
    b3 = TenantBreaker("t", threshold=1, cooldown_s=50.0, state_path=path,
                       clock=lambda: 60.2)
    assert b3.state == TenantBreaker.OPEN


def test_cost_estimator_fails_open_cold_and_tracks_per_tenant():
    est = CostEstimator()
    assert est.estimate_s("a", 100) is None  # cold: no estimate at all
    assert est.estimate_s("a", None) is None
    est.observe("a", 10, 5.0)  # 0.5 s/task
    assert est.seconds_per_task("a") == pytest.approx(0.5)
    assert est.estimate_s("a", 100) == pytest.approx(50.0)
    # an unseen tenant falls back to the global rate
    assert est.estimate_s("never-seen", 100) == pytest.approx(50.0)
    # zero/empty observations are ignored
    est.observe("a", 0, 5.0)
    est.observe("a", 10, 0.0)
    assert est.seconds_per_task("a") == pytest.approx(0.5)


# ----------------------------------------------------------------------
# service submit gates
# ----------------------------------------------------------------------

#: forces the named level regardless of load (and never steps down)
def _forced_level_policy(level):
    kw = dict(tick_interval_s=0.0, down_dwell_s=3600.0, queue_l1=10**6,
              queue_l2=10**6, queue_l3=10**6)
    if level >= L1_SHED_OPTIONAL:
        kw["queue_l1"] = 0
    if level >= L2_SHED_LOAD:
        kw["queue_l2"] = 0
    if level >= L3_EMERGENCY:
        kw["queue_l3"] = 0
    return OverloadPolicy(**kw)


def test_l3_sheds_every_submit_with_retry_after(spec):
    t0 = time.time()
    with ComputeService(
        max_concurrent=1, plan_cache=False, result_cache=False,
        overload_policy=_forced_level_policy(L3_EMERGENCY),
    ) as svc:
        for req_class in ("batch", "interactive"):
            with pytest.raises(ServiceOverloadedError) as ei:
                svc.submit(_build(spec), tenant="t", request_class=req_class)
            assert ei.value.retry_after_s >= 1.0
        snap = svc.stats_snapshot()
        assert snap["overload"]["level"] == L3_EMERGENCY
        assert snap["overload"]["requests_shed"] >= 2
        assert snap["tenants"]["t"]["shed"] == 2
        assert snap["tenants"]["t"]["accepted"] == 0
    sheds = [d for d in decisions_since(t0) if d["kind"] == "request_shed"]
    assert len(sheds) >= 2
    assert all(s["reason"] == "overload_level" for s in sheds[:2])


def test_l2_sheds_batch_but_admits_interactive(spec):
    with ComputeService(
        max_concurrent=1, plan_cache=False, result_cache=False,
        overload_policy=_forced_level_policy(L2_SHED_LOAD),
    ) as svc:
        with pytest.raises(ServiceOverloadedError):
            svc.submit(_build(spec), tenant="t")  # batch is the default
        h = svc.submit(
            _build(spec, k=3.0), tenant="t", request_class="interactive"
        )
        np.testing.assert_array_equal(h.result(120), AN + 3.0)


def test_overload_env_kill_switch(spec, monkeypatch):
    monkeypatch.setenv("CUBED_TPU_OVERLOAD", "off")
    with ComputeService(
        max_concurrent=1, plan_cache=False, result_cache=False,
        overload_policy=_forced_level_policy(L3_EMERGENCY),
    ) as svc:
        assert svc.overload is None
        h = svc.submit(_build(spec, k=2.0), tenant="t")  # nothing sheds
        np.testing.assert_array_equal(h.result(120), AN + 2.0)
        assert svc.stats_snapshot()["overload"]["enabled"] is False


def test_invalid_request_class_rejected(spec):
    with ComputeService(max_concurrent=1) as svc:
        with pytest.raises(ValueError, match="request_class"):
            svc.submit(_build(spec), request_class="best-effort")


# ----------------------------------------------------------------------
# breakers through the service
# ----------------------------------------------------------------------


def test_tenant_breaker_trips_sheds_and_probe_recloses(spec):
    t0 = time.time()
    before = get_registry().snapshot()
    with ComputeService(
        max_concurrent=1, plan_cache=False, result_cache=False,
        breaker_threshold=2, breaker_cooldown_s=0.4,
    ) as svc:
        for _ in range(2):
            h = svc.submit(_build_bad(spec), tenant="bad")
            with pytest.raises(ValueError):
                h.result(120)
        # tripped: the tenant's submits shed with a retry-after, and the
        # shed itself is NOT a strike (no self-amplification)
        with pytest.raises(ServiceOverloadedError) as ei:
            svc.submit(_build(spec), tenant="bad")
        assert ei.value.retry_after_s is not None
        snap = svc.stats_snapshot()
        assert snap["tenants"]["bad"]["breaker"]["state"] == "open"
        assert snap["tenants"]["bad"]["breaker"]["strikes"] == 2
        assert snap["tenants"]["bad"]["shed"] == 1
        assert "bad" in snap["overload"]["breakers_open"]
        # an innocent tenant is untouched by its neighbor's breaker
        h = svc.submit(_build(spec, k=5.0), tenant="good")
        np.testing.assert_array_equal(h.result(120), AN + 5.0)
        # cooldown over: the half-open probe admits ONE request, and its
        # success re-closes the breaker
        time.sleep(0.5)
        h = svc.submit(_build(spec, k=6.0), tenant="bad")
        np.testing.assert_array_equal(h.result(120), AN + 6.0)
        snap = svc.stats_snapshot()
        assert snap["tenants"]["bad"]["breaker"]["state"] == "closed"
        assert snap["overload"]["breakers_open"] == []
    delta = get_registry().snapshot_delta(before)
    assert delta.get("tenant_breaker_trips", 0) >= 1
    trips = [
        d for d in decisions_since(t0)
        if d["kind"] == "tenant_breaker" and d.get("state") == "open"
    ]
    assert trips and trips[0]["tenant"] == "bad"


# ----------------------------------------------------------------------
# deadline feasibility + the typed-rejection journal round trip
# ----------------------------------------------------------------------


def _warm_and_poison_estimator(svc, spec, tenant="t"):
    """Warm the plan cache with one real run, then teach the estimator a
    ruinous seconds-per-task rate so any deadline is infeasible."""
    h = svc.submit(
        _build(spec, k=1.0), tenant=tenant, request_class="interactive"
    )
    np.testing.assert_array_equal(h.result(120), AN + 1.0)
    for _ in range(16):  # EWMA converges near 100 s/task
        svc.estimator.observe(tenant, 1, 100.0)
    return h


def test_deadline_infeasible_requests_shed_at_admission(spec, tmp_path):
    t0 = time.time()
    sdir = str(tmp_path / "svc")
    with ComputeService(
        max_concurrent=1, result_cache=False, service_dir=sdir,
        recover=False,
        overload_policy=_forced_level_policy(L2_SHED_LOAD),
    ) as svc:
        _warm_and_poison_estimator(svc, spec)
        # cold-tenant fail-open proof rode the warm call: it had no
        # estimate yet and ran to completion at L2

        # live leg: an infeasible deadline sheds with the typed error
        h = svc.submit(
            _build(spec, k=1.0), tenant="t", request_class="interactive",
            deadline_s=5.0,
        )
        with pytest.raises(DeadlineInfeasibleError) as ei:
            h.result(120)
        live_err = ei.value
        assert live_err.retry_after_s is not None
        assert h.status() == "failed"
        sheds = [
            d for d in decisions_since(t0)
            if d["kind"] == "request_shed"
            and d.get("reason") == "deadline_infeasible"
        ]
        assert sheds and sheds[0]["estimated_s"] > sheds[0]["remaining_s"]

        # the typed rejection is sealed STRUCTURED in the durable journal
        from cubed_tpu.service.durability import REQUESTS_FILE, _raw_records

        recs = _raw_records(os.path.join(sdir, "t", REQUESTS_FILE))
        done = [
            r for r in recs
            if r.get("kind") == "done" and r["request_id"] == h.request_id
        ]
        assert done and done[0]["error_type"] == "DeadlineInfeasibleError"
        assert done[0]["retry_after_s"] == pytest.approx(
            live_err.retry_after_s
        )


def test_recovered_request_sheds_with_the_same_typed_rejection(
    spec, tmp_path
):
    """The satellite-6 regression, recovered leg: a request accepted (and
    journalled) before a crash carries its deadline AND fingerprint
    through the journal round trip, so the restarted service sheds it
    with the same typed error — which ``result()`` raises, retry-after
    intact."""
    from cubed_tpu.service.durability import TenantRequestJournal

    sdir = str(tmp_path / "svc")
    with ComputeService(
        max_concurrent=1, result_cache=False, service_dir=sdir,
        recover=False,
        overload_policy=_forced_level_policy(L2_SHED_LOAD),
    ) as svc:
        warm = _warm_and_poison_estimator(svc, spec)
        fp = svc._requests[warm.request_id].fingerprint
        assert fp is not None
        # fake the crashed predecessor's journal: an accepted, unsealed
        # request with a deadline it can no longer meet (the exact records
        # submit() writes)
        j = TenantRequestJournal(sdir, "t2")
        j.record_accepted(
            "req-recovered-1", _build(spec, k=1.0), fingerprint=fp,
            deadline_epoch=time.time() + 5.0,
        )
        j.close()
        assert svc.recover() == 1
        h = svc.handle("req-recovered-1")
        assert h is not None
        with pytest.raises(DeadlineInfeasibleError) as ei:
            h.result(120)
        assert ei.value.retry_after_s is not None


# ----------------------------------------------------------------------
# SIGKILL mid-flood: recovery without re-admitting poison
# ----------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))

_KILL_SCRIPT = r"""
import json, sys, time
import numpy as np
sys.path.insert(0, {repo!r})
import cubed_tpu as ct
from cubed_tpu.service import ComputeService, ServiceOverloadedError
from cubed_tpu.service.overload import OverloadPolicy

mode = sys.argv[1]
work_dir = {work_dir!r}
sdir = {sdir!r}
state_path = {state!r}
N = {n_requests!r}

AN = np.arange(64, dtype=np.float64).reshape(8, 8)
spec = ct.Spec(work_dir=work_dir, allowed_mem="500MB")


def build(k, delay=0.06):
    def kernel(x, _k=float(k), _d=delay):
        time.sleep(_d)
        return x + _k

    a = ct.from_array(AN, chunks=(2, 2), spec=spec)  # 16 tasks
    return ct.map_blocks(kernel, a, dtype=np.float64)


def build_bad():
    def boom(x):
        raise ValueError("poison tenant kernel")

    a = ct.from_array(AN, chunks=(4, 4), spec=spec)
    return ct.map_blocks(boom, a, dtype=np.float64)


if mode == "run":
    svc = ComputeService(
        max_concurrent=1, service_dir=sdir, recover=False,
        plan_cache=False, result_cache=False,
        breaker_threshold=2, breaker_cooldown_s=600.0,
        overload_policy=OverloadPolicy(
            queue_l1=1, queue_l2=2, queue_l3=1000,
            tick_interval_s=0.0, down_dwell_s=600.0,
        ),
    ).start()
    # trip the poison tenant's breaker (2 consecutive failures)
    for _ in range(2):
        h = svc.submit(build_bad(), tenant="poison")
        try:
            h.result(120)
        except ValueError:
            pass
    # flood alpha (interactive rides through L2) until the ladder is up
    idmap = {{}}
    for i in range(N):
        idmap[str(i)] = svc.submit(
            build(i), tenant="alpha", request_class="interactive"
        ).request_id
    snap = svc.stats_snapshot()
    with open(state_path + ".tmp", "w") as f:
        json.dump({{
            "idmap": idmap,
            "level": snap["overload"]["level"],
            "breaker": snap["tenants"]["poison"]["breaker"],
        }}, f)
    import os as _os
    _os.replace(state_path + ".tmp", state_path)
    svc.wait_idle(timeout=600)  # parent SIGKILLs us mid-flood
else:
    with open(state_path) as f:
        state = json.load(f)
    svc = ComputeService(
        max_concurrent=2, service_dir=sdir,
        breaker_threshold=2, breaker_cooldown_s=600.0,
    ).start()
    try:
        ok = svc.wait_idle(timeout=300)
        report = {{"idle": bool(ok), "results": {{}}}}
        for k, rid in state["idmap"].items():
            h = svc.handle(rid)
            if h is None:
                report["results"][k] = "missing"
            elif h.status() != "done":
                report["results"][k] = h.status()
            else:
                report["results"][k] = (
                    "correct"
                    if np.array_equal(h.result(10), AN + float(k))
                    else "WRONG"
                )
        snap = svc.stats_snapshot()["tenants"]
        report["recovered"] = (snap.get("alpha") or {{}}).get("recovered", 0)
        # the poison tenant must STAY rejected: its breaker record is
        # durable, and a SIGKILL must not hand it a fresh admission streak
        try:
            svc.submit(build(0.0), tenant="poison")
            report["poison_submit"] = "ADMITTED"
        except ServiceOverloadedError as e:
            report["poison_submit"] = "shed"
            report["poison_retry_after"] = e.retry_after_s
        print(json.dumps(report), flush=True)
    finally:
        svc.close()
"""


@pytest.mark.chaos
def test_chaos_sigkill_mid_flood_recovers_without_readmitting_poison(
    tmp_path,
):
    """SIGKILL the service while L2 is active with a tripped tenant
    breaker: the restart recovers every accepted request bitwise-correct,
    and the poison tenant's next submit is rejected straight from the
    durable breaker record."""
    from cubed_tpu.service.durability import REQUESTS_FILE, _raw_records

    n_requests = 6
    sdir = str(tmp_path / "svc")
    state = str(tmp_path / "state.json")
    script = _KILL_SCRIPT.format(
        repo=REPO, work_dir=str(tmp_path), sdir=sdir, state=state,
        n_requests=n_requests,
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    requests_jsonl = os.path.join(sdir, "alpha", REQUESTS_FILE)

    proc = subprocess.Popen(
        [sys.executable, "-c", script, "run"], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
    )
    killed = False
    try:
        deadline = time.time() + 120
        while time.time() < deadline and proc.poll() is None:
            if os.path.isfile(state) and os.path.isfile(requests_jsonl):
                done = sum(
                    1 for r in _raw_records(requests_jsonl)
                    if r.get("kind") == "done"
                )
                if 1 <= done < n_requests:
                    os.killpg(proc.pid, signal.SIGKILL)
                    killed = True
                    break
            time.sleep(0.05)
        proc.wait(timeout=30)
        assert killed, (
            f"flood drained before the kill landed (rc={proc.returncode}): "
            f"{proc.stderr.read()[-2000:]}"
        )
    finally:
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            proc.wait(timeout=30)

    with open(state) as f:
        st = json.load(f)
    # the kill landed with the ladder genuinely up and the breaker open
    assert st["level"] >= L2_SHED_LOAD, st
    assert st["breaker"]["state"] == "open", st
    assert os.path.isfile(os.path.join(sdir, "poison", "breaker.json"))

    records = _raw_records(requests_jsonl)
    accepted = {
        r["request_id"] for r in records if r.get("kind") == "accepted"
    }
    done = {r["request_id"] for r in records if r.get("kind") == "done"}
    assert len(accepted) == n_requests and 0 < len(done) < n_requests

    out = subprocess.run(
        [sys.executable, "-c", script, "recover"], env=env,
        capture_output=True, text=True, timeout=400,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["idle"] is True
    pending = accepted - done
    assert report["recovered"] == len(pending)
    for k, rid in st["idmap"].items():
        if rid in pending:
            assert report["results"][k] == "correct", (k, report)
    # the durable breaker record survived the SIGKILL: poison stays out
    assert report["poison_submit"] == "shed", report
    assert report["poison_retry_after"] and report["poison_retry_after"] > 0


# ----------------------------------------------------------------------
# the live-fleet acceptance proof
# ----------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_overload_flood_with_poison_tenant_on_live_fleet(
    tmp_path, monkeypatch, invariant_audit,
):
    """2x flood plus a poison tenant on a real 2-worker fleet: the poison
    request fails with a PoisonTaskError naming its op+chunk within the
    strike budget, zero workers are permanently lost (the autoscaler
    backfills every kill), the innocent tenant keeps >= 0.8 of its
    deadlines, the ladder's transitions land in the decision ring, and
    the post-hoc invariant audit is clean."""
    from cubed_tpu.runtime import faults
    from cubed_tpu.runtime.executors.distributed import (
        DistributedDagExecutor,
    )
    from cubed_tpu.runtime.resilience import PoisonTaskError, RetryPolicy

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    an = np.arange(144, dtype=np.float64).reshape(12, 12)

    def build(k, delay=0.05):
        def kernel(x, _k=float(k), _d=delay):
            time.sleep(_d)
            return x + _k

        a = ct.from_array(an, chunks=(3, 3), spec=spec)  # 16 tasks
        return ct.map_blocks(kernel, a, dtype=np.float64)

    # the poison request: a SINGLE-chunk array whose one blockwise task
    # is named in task_fatal_chunk_keys — with worker_threads=1 the kill
    # can never take an innocent in-flight task down with it
    pn = np.arange(16, dtype=np.float64).reshape(4, 4)
    psrc = ct.from_array(pn, chunks=(4, 4), spec=spec)
    poison_arr = ct.map_blocks(
        lambda x: x + 1.0, psrc, dtype=np.float64
    )
    poison_key = str((poison_arr.name, 0, 0))
    monkeypatch.setenv(
        faults.FAULTS_ENV_VAR,
        faults.FaultConfig(
            seed=11, task_fatal_chunk_keys=(poison_key,)
        ).to_env_json(),
    )

    t0 = time.time()
    control_dir = str(tmp_path / "ctrl")
    ex = DistributedDagExecutor(
        n_local_workers=2, min_workers=2, max_workers=3, autoscale=True,
        control_dir=control_dir,
        retry_policy=RetryPolicy(
            retries=2, backoff_base=0.05, seed=0, max_requeues=2
        ),
    )
    try:
        ex._ensure_fleet()
        with ComputeService(
            executor=ex, max_concurrent=2, plan_cache=True,
            result_cache=False, breaker_threshold=3,
            breaker_cooldown_s=5.0,
            overload_policy=OverloadPolicy(
                queue_l1=2, queue_l2=4, queue_l3=1000,
                tick_interval_s=0.02, down_dwell_s=30.0,
            ),
        ) as svc:
            h_poison = svc.submit(poison_arr, tenant="poison")
            flood_handles, flood_shed = [], 0
            for i in range(10):
                try:
                    flood_handles.append(svc.submit(build(i), tenant="flood"))
                except ServiceOverloadedError as e:
                    assert e.retry_after_s is not None
                    flood_shed += 1
                time.sleep(0.03)  # let the ladder tick between submits
            slo_handles = []
            for i in range(5):
                slo_handles.append(svc.submit(
                    build(100 + i), tenant="slo", deadline_s=90.0,
                    request_class="interactive",
                ))
                time.sleep(0.03)

            # the poison request is convicted within its strike budget,
            # naming the culprit op and chunk
            with pytest.raises(PoisonTaskError) as ei:
                h_poison.result(240)
            assert ei.value.chunk == poison_key
            assert ei.value.attempts <= 3  # K = max_requeues + 1

            # innocent tenants ride through: every accepted flood request
            # completes, and the deadline tenant meets >= 0.8 of its SLOs
            for i, h in enumerate(flood_handles):
                np.testing.assert_array_equal(h.result(240), an + float(i))
            met = 0
            for i, h in enumerate(slo_handles):
                try:
                    np.testing.assert_array_equal(
                        h.result(240), an + float(100 + i)
                    )
                    met += 1
                except Exception:
                    pass
            assert met / len(slo_handles) >= 0.8

            # the ladder genuinely engaged under the flood
            snap = svc.stats_snapshot()
            assert snap["overload"]["transitions"] >= 1, snap["overload"]
            level_records = [
                d for d in decisions_since(t0)
                if d["kind"] == "overload_level"
            ]
            assert level_records, "no ladder transitions in the ring"
            quarantines = [
                d for d in decisions_since(t0)
                if d["kind"] == "poison_quarantine"
            ]
            assert quarantines and quarantines[0]["chunk"] == poison_key

        # zero workers PERMANENTLY lost: kills happened, and the
        # autoscaler backfilled the fleet to its floor
        assert ex._coordinator.stats["workers_lost"] >= 1
        deadline = time.time() + 60
        while time.time() < deadline and ex._coordinator.n_workers < 2:
            time.sleep(0.25)
        assert ex._coordinator.n_workers >= 2, (
            f"fleet not backfilled: {ex._coordinator.n_workers} worker(s)"
        )
    finally:
        ex.close()
    # survived the flood AND never did anything illegal along the way
    invariant_audit(
        control_dir=control_dir, work_dir=str(tmp_path),
        expect_success=False,
    )
