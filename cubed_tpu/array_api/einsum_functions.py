"""Chunked ``einsum`` — beyond both the standard and the reference.

Generalizes the matmul/tensordot contraction pattern
(linear_algebra_functions.py; reference analogue
cubed/array_api/linear_algebra_functions.py:13-149) to arbitrary
subscripts: one n-ary blockwise op contracts block-locally with every
contracted label kept as a size-1 axis (``adjust_chunks``), then a tree
reduction sums over the contracted axes. Shared labels align their chunk
grids via the blockwise core's ``unify_chunks``; on the TPU executor each
per-block kernel is a single ``nxp.einsum`` (an MXU contraction for the
matmul-shaped cases) and the sum lowers to the collective tree.

Not supported (raise ``NotImplementedError``): ellipsis and repeated
labels within one operand (block-local traces/diagonals don't compose
across a chunk grid without a gather).
"""

from __future__ import annotations

import numpy as np

from ..backend_array_api import nxp
from ..core.ops import blockwise
from .data_type_functions import result_type
from .dtypes import _numeric_dtypes

__all__ = ["einsum"]


def _parse(subscripts: str, n_operands: int):
    subscripts = subscripts.replace(" ", "")
    if "..." in subscripts:
        raise NotImplementedError("einsum: ellipsis is not supported")
    if "->" in subscripts:
        lhs, out_labels = subscripts.split("->")
        explicit = True
    else:
        lhs, out_labels, explicit = subscripts, "", False
    in_labels = lhs.split(",")
    if len(in_labels) != n_operands:
        raise ValueError(
            f"einsum: {len(in_labels)} operand subscripts for "
            f"{n_operands} operands"
        )
    for labels in in_labels:
        if not labels.isalpha() and labels != "":
            raise ValueError(f"einsum: invalid subscript {labels!r}")
        if len(set(labels)) != len(labels):
            raise NotImplementedError(
                "einsum: repeated labels within one operand (diagonal/"
                "trace) are not supported"
            )
    counts: dict = {}
    for labels in in_labels:
        for ch in labels:
            counts[ch] = counts.get(ch, 0) + 1
    if not explicit:
        out_labels = "".join(sorted(ch for ch, c in counts.items() if c == 1))
    if len(set(out_labels)) != len(out_labels):
        raise ValueError("einsum: repeated output labels")
    for ch in out_labels:
        if ch not in counts:
            raise ValueError(f"einsum: output label {ch!r} not in inputs")
    contracted = sorted(ch for ch in counts if ch not in out_labels)
    return in_labels, out_labels, contracted


def einsum(subscripts, /, *operands, dtype=None):
    """Evaluate the Einstein summation over chunked arrays.

    ``einsum("ij,jk->ik", a, b)`` and friends; any number of operands,
    batch labels, multiple contractions (``"abc,cd,be->ae"``), implicit
    output. Memory-bounded like every other op: the contraction runs
    per block and sums through the reduction tree.
    """
    if not operands:
        raise ValueError("einsum requires at least one operand")
    for op in operands:
        if op.dtype not in _numeric_dtypes:
            raise TypeError("Only numeric dtypes are allowed in einsum")
    in_labels, out_labels, contracted = _parse(subscripts, len(operands))
    extents: dict = {}
    for labels, op in zip(in_labels, operands):
        if len(labels) != op.ndim:
            raise ValueError(
                f"einsum: subscript {labels!r} does not match operand "
                f"with {op.ndim} dimensions"
            )
        for ch, size in zip(labels, op.shape):
            if extents.setdefault(ch, size) != size:
                raise ValueError(
                    f"einsum: label {ch!r} has inconsistent sizes "
                    f"{extents[ch]} and {size}"
                )

    if dtype is None:
        dtype = result_type(*operands)
    dtype = np.dtype(dtype)

    sym = {ch: i for i, ch in enumerate(out_labels + "".join(contracted))}
    out_ind = tuple(sym[ch] for ch in out_labels) + tuple(
        sym[ch] for ch in contracted
    )

    # block kernel: contract locally to the OUTPUT labels, then append a
    # size-1 axis per contracted label (out_ind keeps them for the tree)
    kernel_spec = ",".join(in_labels) + "->" + out_labels
    n_contracted = len(contracted)

    def _einsum_block(*blocks):
        # contract IN the requested dtype (np.einsum dtype semantics):
        # an int32 product must not overflow before a float64 cast; cast
        # only blocks whose dtype differs (astype always copies)
        res = nxp.einsum(
            kernel_spec,
            *[b if b.dtype == dtype else b.astype(dtype) for b in blocks],
        )
        for _ in range(n_contracted):
            res = nxp.expand_dims(res, axis=res.ndim)
        return res

    _einsum_block.__name__ = f"einsum[{subscripts}]"

    blockwise_args = []
    for labels, op in zip(in_labels, operands):
        blockwise_args.extend([op, tuple(sym[ch] for ch in labels)])

    # contraction temporaries: same 3-output-block pricing as matmul
    # (linear_algebra_functions.py) — the block result materializes before
    # the fusable sum consumes it, plus the write-path copy
    label_chunk = {}
    for labels, op in zip(in_labels, operands):
        for ch, c in zip(labels, op.chunksize):
            label_chunk[ch] = max(label_chunk.get(ch, 1), c)
    out_block_elems = 1
    for ch in out_labels:
        out_block_elems *= label_chunk[ch]
    contraction_extra = 3 * out_block_elems * dtype.itemsize
    # widened input-block copies (the kernel casts mismatched dtypes and
    # briefly holds original + widened block together)
    for labels, op in zip(in_labels, operands):
        if np.dtype(op.dtype) != dtype:
            in_elems = 1
            for ch in labels:
                in_elems *= label_chunk[ch]
            contraction_extra += in_elems * dtype.itemsize

    out = blockwise(
        _einsum_block,
        out_ind,
        *blockwise_args,
        dtype=dtype,
        adjust_chunks={sym[ch]: 1 for ch in contracted},
        extra_projected_mem=contraction_extra,
    )

    if contracted:
        from .statistical_functions import sum as xp_sum

        axes = tuple(range(len(out_labels), len(out_labels) + n_contracted))
        out = xp_sum(out, axis=axes, dtype=dtype)
    return out
