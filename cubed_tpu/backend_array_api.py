"""The single seam selecting the per-chunk compute namespace.

TPU-first: the default backend namespace is ``jax.numpy``, so every per-chunk
kernel in the framework is a pure jittable function and fused op chains compile
to one XLA program. A numpy backend is selectable (``CUBED_TPU_BACKEND=numpy``)
as the float64-exact CPU oracle for differential testing.

Reference parity: cubed/backend_array_api.py:1-23 (there the namespace is
array_api_compat.numpy; here the seam itself is the TPU design point).
"""

from __future__ import annotations

import os

import numpy as np

BACKEND = os.environ.get("CUBED_TPU_BACKEND", "jax").lower()

if BACKEND == "jax":
    import jax

    # Array-API dtype parity (int64 indices, float64 defaults) requires x64.
    # TPU kernels run in f32/bf16; the TPU executor downcasts f64 tiles on
    # device ingestion when the hardware lacks double support.
    if os.environ.get("CUBED_TPU_ENABLE_X64", "1") == "1":
        jax.config.update("jax_enable_x64", True)

    # Every plan builds fresh kernel closures, which defeats jax's in-process
    # jit cache; the persistent (HLO-keyed) compilation cache makes repeat
    # compiles of structurally identical kernels ~100x cheaper.
    # CPU-only runs (tests) skip it: XLA:CPU AOT entries bake host machine
    # features, so a cache written on one machine can SIGILL on another.
    if (
        os.environ.get("CUBED_TPU_COMPILATION_CACHE", "1") == "1"
        and os.environ.get("JAX_PLATFORMS", "").lower() != "cpu"
    ):
        cache_dir = os.environ.get(
            "CUBED_TPU_COMPILATION_CACHE_DIR",
            os.path.expanduser("~/.cache/cubed_tpu_xla"),
        )
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        except Exception:
            pass

    import jax.numpy as namespace  # noqa: F401

    def backend_array_to_numpy_array(arr) -> np.ndarray:
        """Device array -> host numpy (blocks on transfer)."""
        return np.asarray(arr)

    def numpy_array_to_backend_array(arr, *, dtype=None):
        """Host numpy -> backend array (device placement is executor policy).

        Structured numpy arrays become dict-of-arrays pytrees (jax has no
        structured dtypes); the dict presents the same ``arr["field"]`` access
        the reference's kernels use on zarr structured intermediates.
        """
        if isinstance(arr, dict):  # pytree chunk (e.g. mean's {n, total})
            return {k: numpy_array_to_backend_array(v, dtype=None) for k, v in arr.items()}
        a = np.asarray(arr)
        if a.dtype.fields is not None:
            return {k: namespace.asarray(np.ascontiguousarray(a[k])) for k in a.dtype.names}
        return namespace.asarray(a, dtype=dtype)

else:
    import numpy as namespace  # noqa: F401

    def backend_array_to_numpy_array(arr) -> np.ndarray:
        return np.asarray(arr)

    def numpy_array_to_backend_array(arr, *, dtype=None):
        if isinstance(arr, dict):
            return {k: numpy_array_to_backend_array(v, dtype=None) for k, v in arr.items()}
        return np.asarray(arr, dtype=dtype)


#: alias used throughout the codebase, mirroring the reference's ``nxp``
nxp = namespace


def default_dtypes() -> dict:
    """Array-API default dtypes (float64/int64/complex128, bool)."""
    return {
        "real floating": np.dtype(np.float64),
        "integral": np.dtype(np.int64),
        "complex floating": np.dtype(np.complex128),
        "boolean": np.dtype(np.bool_),
    }
