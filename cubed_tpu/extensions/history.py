"""HistoryCallback: record plan-time projections and per-task measurements,
write CSVs, and compute projected-memory utilization.

Reference parity: cubed/extensions/history.py:11-103.
"""

from __future__ import annotations

import csv
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Optional

from ..runtime.types import Callback, TaskEndEvent


@dataclass
class PlanRow:
    array_name: str
    op_name: str
    projected_mem: int
    reserved_mem: int
    num_tasks: int


class HistoryCallback(Callback):
    def __init__(self, history_dir: str = "history"):
        self.history_dir = history_dir
        self.plan: list[PlanRow] = []
        self.events: list[TaskEndEvent] = []

    def on_compute_start(self, event) -> None:
        self.plan = []
        self.events = []
        for name, d in event.dag.nodes(data=True):
            if d.get("type") == "op" and d.get("primitive_op") is not None:
                op = d["primitive_op"]
                self.plan.append(
                    PlanRow(
                        array_name=name,
                        op_name=d.get("op_name", ""),
                        projected_mem=op.projected_mem,
                        reserved_mem=op.reserved_mem,
                        num_tasks=op.num_tasks,
                    )
                )

    def on_task_end(self, event: TaskEndEvent) -> None:
        self.events.append(event)

    def on_compute_end(self, event) -> None:
        ts = int(time.time())
        os.makedirs(self.history_dir, exist_ok=True)
        self._write_csv(
            os.path.join(self.history_dir, f"plan-{ts}.csv"),
            [asdict(r) for r in self.plan],
        )
        self._write_csv(
            os.path.join(self.history_dir, f"events-{ts}.csv"),
            [asdict(e) for e in self.events],
        )
        stats = self.stats()
        if stats:
            self._write_csv(os.path.join(self.history_dir, f"stats-{ts}.csv"), stats)

    def stats(self) -> list[dict]:
        """Join plan projections against measured peaks per op."""
        peak_by_array: dict[str, int] = {}
        for e in self.events:
            if e.peak_measured_mem_end is not None:
                peak_by_array[e.array_name] = max(
                    peak_by_array.get(e.array_name, 0), e.peak_measured_mem_end
                )
        rows = []
        for r in self.plan:
            peak = peak_by_array.get(r.array_name)
            row = asdict(r)
            row["peak_measured_mem"] = peak
            row["projected_mem_utilization"] = (
                peak / r.projected_mem if peak and r.projected_mem else None
            )
            rows.append(row)
        return rows

    @staticmethod
    def _write_csv(path: str, rows: list[dict]) -> None:
        if not rows:
            return
        with open(path, "w", newline="") as f:
            writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
            writer.writeheader()
            writer.writerows(rows)
