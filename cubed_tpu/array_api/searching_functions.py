"""Array-API searching functions. Reference parity:
cubed/array_api/searching_functions.py (33 LoC)."""

from __future__ import annotations

import numpy as np

from ..backend_array_api import nxp
from ..core.ops import arg_reduction, elemwise
from .data_type_functions import result_type
from .dtypes import _real_numeric_dtypes
from .manipulation_functions import flatten


def argmax(x, /, *, axis=None, keepdims=False, split_every=None):
    if x.dtype not in _real_numeric_dtypes:
        raise TypeError("Only real numeric dtypes are allowed in argmax")
    if axis is None:
        x = flatten(x)
        axis = 0
    return _maybe_keepdims(
        arg_reduction(x, nxp.argmax, nxp.max, axis=axis, dtype=np.dtype(np.int64)),
        keepdims, axis, x.ndim,
    )


def argmin(x, /, *, axis=None, keepdims=False, split_every=None):
    if x.dtype not in _real_numeric_dtypes:
        raise TypeError("Only real numeric dtypes are allowed in argmin")
    if axis is None:
        x = flatten(x)
        axis = 0
    return _maybe_keepdims(
        arg_reduction(x, nxp.argmin, nxp.min, axis=axis, dtype=np.dtype(np.int64)),
        keepdims, axis, x.ndim,
    )


def _maybe_keepdims(out, keepdims, axis, ndim):
    if keepdims:
        from .manipulation_functions import expand_dims

        return expand_dims(out, axis=axis % ndim)
    return out


def where(condition, x1, x2, /):
    dtype = result_type(x1, x2)
    return elemwise(nxp.where, condition, x1, x2, dtype=dtype)
