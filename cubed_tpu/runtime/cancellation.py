"""End-to-end deadlines and cooperative cancellation: bound *time* the way
the rest of the stack bounds memory.

The paper's guarantee is a bounded resource per task — but until this
module nothing bounded how LONG a compute may run: a browned-out store or
a pathological kernel ran forever, and a client's only recourse was
killing its own process (recoverable thanks to the journal, but never
graceful). A :class:`CancellationToken` closes that gap with the same
layered discipline the memory guard uses:

- **One token per compute.** ``Plan.execute(deadline_s=...)`` (or an
  explicit ``cancellation=CancellationToken()``) mints it;
  ``ComputeService.submit(deadline_s=...)`` threads one through every
  request so ``RequestHandle.cancel()`` finally works on RUNNING
  requests, not just queued ones. The deadline is an absolute wall-clock
  epoch so it can cross process boundaries unchanged.

- **The dispatch loop is the first enforcement point.**
  ``map_unordered`` checks the token every iteration: a tripped token
  stops new submissions, cancels pending futures, and raises the typed
  error (:class:`ComputeCancelledError` /
  :class:`ComputeDeadlineExceededError` — picklable, classified
  ``CANCELLED`` by the resilience layer, drawing ZERO retry budget).

- **Workers abort cooperatively.** Every distributed task message
  carries the token's wire form (compute id + deadline + cancelled
  flag); an explicit cancel additionally broadcasts a ``compute_cancel``
  frame so pre-started fleet workers learn within one frame delivery,
  not one task round-trip. Worker-side checks run in
  ``execute_with_stats`` (before the task body) and between chunk
  reads/writes in ``storage/store.py`` — tasks abort at the next safe
  boundary, never mid-write, so the store and journal stay consistent
  and ``resume_compute`` after a deadline abort is bitwise-correct.

Token lookup is keyed by the compute id already riding the
``logs.compute_id_var`` contextvar (set by ``Plan.execute`` client-side
and per task message worker-side), so concurrent computes in one process
— the multi-tenant service's normal state — cancel independently.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from ..observability.metrics import get_registry

#: bounded worker-side token registries (a long-lived fleet worker serves
#: many computes; stale tokens must age out, not accumulate)
MAX_WORKER_TOKENS = 128
#: compute ids cancelled via ``compute_cancel`` frames, retained so a
#: cancel that RACES its compute's first task message still sticks
MAX_CANCELLED_IDS = 512


class ComputeCancelledError(RuntimeError):
    """The compute's cancellation token was tripped (explicit
    ``CancellationToken.cancel()`` — a client cancel, a service shutdown).

    Picklable (it crosses pool and fleet boundaries like any task
    failure) and classified ``CANCELLED`` by the resilience layer: no
    retry, no backoff, zero retry-budget draw — cancellation is an
    *instruction*, not a failure to recover from."""

    def __init__(self, message: str = "compute cancelled",
                 compute_id: Optional[str] = None,
                 reason: Optional[str] = None):
        super().__init__(message)
        self.compute_id = compute_id
        self.reason = reason

    def __reduce__(self):
        return (
            type(self),
            (self.args[0] if self.args else "", self.compute_id, self.reason),
        )


class ComputeDeadlineExceededError(ComputeCancelledError):
    """The compute ran past its deadline (``deadline_s``). A subclass of
    :class:`ComputeCancelledError` so every cooperative-abort check covers
    both; kept distinct so callers (and the service's request states) can
    tell an operator-initiated cancel from an SLO violation."""


class CancellationToken:
    """One compute's deadline + cancel flag, shared by every layer.

    Thread-safe; cheap to poll (``cancelled`` is an event check plus one
    ``time.time()`` comparison). ``on_abort`` callbacks fire exactly once
    — on explicit :meth:`cancel`, or when the first enforcement point
    observes an expired deadline (:meth:`notify_abort`) — which is how
    the distributed executor broadcasts ``compute_cancel`` to the fleet
    the moment the token trips."""

    def __init__(self, deadline_s: Optional[float] = None,
                 deadline_epoch: Optional[float] = None,
                 compute_id: Optional[str] = None):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._callbacks: list[Callable[[], None]] = []
        self._notified = False
        #: True when cancel() tripped the token BEFORE the deadline
        #: passed: error() must then report the explicit cancel even if
        #: the deadline has also expired by observation time
        self._explicit = False
        self.reason: Optional[str] = None
        self.compute_id = compute_id
        self.deadline_epoch: Optional[float] = deadline_epoch
        if deadline_s is not None:
            self.set_deadline(deadline_s)

    # -- arming --------------------------------------------------------

    def set_deadline(self, deadline_s: float) -> None:
        """Arm (or tighten) the deadline to ``deadline_s`` seconds from
        now. A later deadline never loosens an armed earlier one."""
        epoch = time.time() + float(deadline_s)
        with self._lock:
            if self.deadline_epoch is None or epoch < self.deadline_epoch:
                self.deadline_epoch = epoch

    def on_abort(self, fn: Callable[[], None]) -> None:
        """Register a callback fired once when the token trips (already
        tripped -> fired immediately)."""
        fire = False
        with self._lock:
            if self._notified:
                fire = True
            else:
                self._callbacks.append(fn)
        if fire:
            try:
                fn()
            except Exception:
                pass

    # -- state ---------------------------------------------------------

    @property
    def expired(self) -> bool:
        d = self.deadline_epoch
        return d is not None and time.time() >= d

    @property
    def cancelled(self) -> bool:
        """True once the token has tripped (explicit cancel or deadline)."""
        return self._event.is_set() or self.expired

    def remaining(self) -> Optional[float]:
        """Seconds until the deadline (None = no deadline; <= 0 expired)."""
        d = self.deadline_epoch
        return None if d is None else d - time.time()

    # -- tripping ------------------------------------------------------

    def cancel(self, reason: Optional[str] = None) -> None:
        """Trip the token explicitly. Idempotent; fires the abort
        callbacks (fleet broadcast) from the CALLER's thread so a cancel
        reaches workers without waiting for the dispatch loop to wake."""
        with self._lock:
            if self.reason is None:
                self.reason = reason
            if not self.expired:
                # which bound tripped FIRST is decided here, not at the
                # (possibly much later) observation point
                self._explicit = True
        self._event.set()
        self.notify_abort()

    def notify_abort(self) -> None:
        """Fire the abort callbacks exactly once (also called by the
        first enforcement point to observe an expired deadline)."""
        with self._lock:
            if self._notified:
                return
            self._notified = True
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            try:
                fn()
            except Exception:
                pass

    def error(self) -> ComputeCancelledError:
        """The typed error this token aborts with: whichever bound
        tripped FIRST wins — an explicit cancel() issued before the
        deadline passed reports the cancel even when the dispatch loop
        only observes it after expiry."""
        if self._explicit or (self._event.is_set() and not self.expired):
            return ComputeCancelledError(
                f"compute {self.compute_id or '<unnamed>'} cancelled"
                + (f": {self.reason}" if self.reason else ""),
                compute_id=self.compute_id, reason=self.reason,
            )
        if self.expired:
            return ComputeDeadlineExceededError(
                f"compute {self.compute_id or '<unnamed>'} exceeded its "
                f"deadline (epoch {self.deadline_epoch})",
                compute_id=self.compute_id, reason="deadline",
            )
        return ComputeCancelledError(
            f"compute {self.compute_id or '<unnamed>'} cancelled"
            + (f": {self.reason}" if self.reason else ""),
            compute_id=self.compute_id, reason=self.reason,
        )

    def check(self) -> None:
        """Raise the typed error if tripped (cooperative-abort check)."""
        if self.cancelled:
            raise self.error()

    # -- wire ----------------------------------------------------------

    def wire(self) -> Optional[dict]:
        """The plain-dict form riding distributed task messages. ``None``
        when there is nothing to enforce (no deadline, not cancelled) —
        workers then skip registration entirely."""
        cancelled = self._event.is_set()
        if self.deadline_epoch is None and not cancelled:
            return None
        return {
            "compute": self.compute_id,
            "deadline": self.deadline_epoch,
            "cancelled": cancelled,
            "reason": self.reason,
        }

    def __repr__(self) -> str:
        return (
            f"CancellationToken(compute={self.compute_id!r}, "
            f"deadline_epoch={self.deadline_epoch}, "
            f"cancelled={self.cancelled})"
        )


# ----------------------------------------------------------------------
# per-process registries: client side (Plan.execute) and worker side
# (task-message wire arming + compute_cancel frames)
# ----------------------------------------------------------------------

_lock = threading.Lock()
#: client-process: compute id -> the token Plan.execute armed for it
_client_tokens: "OrderedDict[str, CancellationToken]" = OrderedDict()
#: worker-process: compute id -> the token mirrored off task messages
_worker_tokens: "OrderedDict[str, CancellationToken]" = OrderedDict()
#: worker-process: compute ids cancelled via compute_cancel frames (kept
#: so a cancel frame racing the compute's first task message still lands)
_cancelled_ids: "OrderedDict[str, float]" = OrderedDict()
#: fast path: True only while ANY token is registered in this process —
#: the per-chunk-IO check must cost one attribute read when unused
_any_tokens = False


def _refresh_any() -> None:
    global _any_tokens
    _any_tokens = bool(_client_tokens or _worker_tokens)


def register_compute(compute_id: str, token: CancellationToken) -> None:
    """Client side: associate a compute's token with its id for the
    duration of ``Plan.execute`` (the coordinator reads it per task
    message; in-process task threads read it per chunk IO)."""
    token.compute_id = token.compute_id or compute_id
    with _lock:
        _client_tokens[compute_id] = token
        _refresh_any()


def unregister_compute(compute_id: str) -> None:
    with _lock:
        _client_tokens.pop(compute_id, None)
        _refresh_any()


def wire_for_compute(compute_id: Optional[str]) -> Optional[dict]:
    """The wire form of the current compute's token, for task messages
    (None = nothing to enforce). Read per submit, so a cancel that trips
    mid-compute rides every LATER task message too — a worker that missed
    the broadcast still learns."""
    if compute_id is None:
        return None
    with _lock:
        token = _client_tokens.get(compute_id)
    return token.wire() if token is not None else None


def arm_from_wire(raw: Optional[dict]) -> Optional[CancellationToken]:
    """Worker side: adopt the token a task message carried. Registered by
    compute id (bounded LRU), merged with any ``compute_cancel`` frame
    that arrived first."""
    if not isinstance(raw, dict):
        return None
    cid = raw.get("compute")
    if not cid:
        return None
    with _lock:
        token = _worker_tokens.get(cid)
        if token is None:
            token = CancellationToken(compute_id=cid)
            _worker_tokens[cid] = token
            while len(_worker_tokens) > MAX_WORKER_TOKENS:
                _worker_tokens.popitem(last=False)
        else:
            _worker_tokens.move_to_end(cid)
        already_cancelled = cid in _cancelled_ids
        _refresh_any()
    deadline = raw.get("deadline")
    if deadline is not None:
        with token._lock:
            if (
                token.deadline_epoch is None
                or deadline < token.deadline_epoch
            ):
                token.deadline_epoch = float(deadline)
    if raw.get("cancelled") or already_cancelled:
        token.cancel(raw.get("reason"))
    return token


def cancel_compute(compute_id: Optional[str],
                   reason: Optional[str] = None) -> None:
    """Worker side: a ``compute_cancel`` frame arrived. Trips the
    registered token (or records the id so a racing task message's
    arming finds the cancel waiting)."""
    if not compute_id:
        return
    with _lock:
        _cancelled_ids[compute_id] = time.time()
        while len(_cancelled_ids) > MAX_CANCELLED_IDS:
            _cancelled_ids.popitem(last=False)
        token = _worker_tokens.get(compute_id)
    if token is not None:
        token.cancel(reason or "coordinator compute_cancel")


def current_token() -> Optional[CancellationToken]:
    """The token governing the CURRENT compute, resolved through the
    compute-id CONTEXTVAR only (set by ``Plan.execute`` client-side and
    per task message worker-side). Deliberately NOT the env-var fallback
    ``logs.current_compute_id`` uses: the env export is last-writer-wins
    across concurrent computes, so a pool task thread of compute A could
    resolve compute B's id and abort on B's tripped token — the
    dispatch-loop check covers in-process pool threads instead. None
    when no compute is armed — the common fast path, one flag read."""
    if not _any_tokens:
        return None
    from ..observability.logs import compute_id_var

    cid = compute_id_var.get()
    if not cid:
        return None
    with _lock:
        return _client_tokens.get(cid) or _worker_tokens.get(cid)


def check_current() -> None:
    """Cooperative-abort check at a safe boundary (task start, between
    chunk reads/writes): raises the typed error when the governing token
    has tripped. A no-op (one attribute read) with no tokens armed."""
    token = current_token()
    if token is not None and token.cancelled:
        raise token.error()


def abort(token: CancellationToken) -> ComputeCancelledError:
    """The one counted/recorded abort path every dispatch loop shares:
    counts ``deadline_aborts`` or ``cancellations``, records the decision
    (``deadline_exceeded`` / ``compute_cancelled``), fires the token's
    abort callbacks (fleet broadcast), and returns the error to raise."""
    from ..observability.collect import record_decision

    token.notify_abort()
    err = token.error()
    reg = get_registry()
    if isinstance(err, ComputeDeadlineExceededError):
        reg.counter("deadline_aborts").inc()
        record_decision(
            "deadline_exceeded", compute=token.compute_id,
            deadline_epoch=token.deadline_epoch,
        )
    else:
        reg.counter("cancellations").inc()
        record_decision(
            "compute_cancelled", compute=token.compute_id,
            reason=token.reason,
        )
    return err
