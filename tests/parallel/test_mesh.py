"""Mesh-sharded execution tests on the virtual 8-device CPU mesh."""

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp


def _cpu_devices():
    import jax

    try:
        return jax.devices("cpu")
    except RuntimeError:
        return []


needs_8 = pytest.mark.skipif(
    len(_cpu_devices()) < 8, reason="needs 8 virtual CPU devices"
)


@pytest.fixture
def mesh():
    from cubed_tpu.parallel.mesh import make_mesh

    return make_mesh(shape=(8,), axis_names=("data",), devices=_cpu_devices()[:8])


@pytest.fixture
def mesh_executor(mesh):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    return JaxExecutor(mesh=mesh)


@needs_8
def test_sharded_elementwise(spec, mesh_executor):
    an = np.arange(16.0 * 24).reshape(16, 24)
    a = ct.from_array(an, chunks=(2, 6), spec=spec)
    b = ct.from_array(an, chunks=(2, 6), spec=spec)
    c = xp.add(xp.multiply(a, 2.0), b)
    np.testing.assert_allclose(c.compute(executor=mesh_executor), an * 3.0)


@needs_8
def test_sharded_reduction(spec, mesh_executor):
    an = np.arange(16.0 * 24).reshape(16, 24)
    a = ct.from_array(an, chunks=(2, 6), spec=spec)
    s = xp.sum(a, axis=0)
    np.testing.assert_allclose(s.compute(executor=mesh_executor), an.sum(axis=0))
    m = xp.mean(a)
    np.testing.assert_allclose(m.compute(executor=mesh_executor), an.mean())


@needs_8
def test_sharded_rechunk_is_reshard(spec, mesh_executor):
    an = np.arange(16.0 * 24).reshape(16, 24)
    a = ct.from_array(an, chunks=(2, 24), spec=spec)
    b = a.rechunk((16, 3))
    np.testing.assert_allclose(b.compute(executor=mesh_executor), an)


@needs_8
def test_sharded_matmul(spec, mesh_executor):
    rng = np.random.default_rng(0)
    an = rng.random((16, 24))
    bn = rng.random((24, 8))
    a = ct.from_array(an, chunks=(8, 12), spec=spec)
    b = ct.from_array(bn, chunks=(12, 8), spec=spec)
    np.testing.assert_allclose(
        xp.matmul(a, b).compute(executor=mesh_executor), an @ bn, rtol=1e-12
    )


@needs_8
def test_sharded_vorticity_pipeline(spec, mesh_executor):
    import cubed_tpu.random

    shape = (16, 16, 16)
    a = cubed_tpu.random.random(shape, chunks=8, spec=spec)
    b = cubed_tpu.random.random(shape, chunks=8, spec=spec)
    r = xp.mean(xp.add(xp.multiply(a[1:], 2.0), xp.multiply(b[1:], 3.0)))
    val = float(r.compute(executor=mesh_executor))
    assert 2.0 < val < 3.0  # 2*U + 3*U has mean 2.5


def test_spill_to_storage(spec):
    """With a tiny device budget, residents spill to zarr and results stay right."""
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.arange(64.0 * 64).reshape(64, 64)
    a = ct.from_array(an, chunks=(16, 16), spec=spec)
    b = xp.add(a, 1.0)
    c = xp.multiply(b, 2.0)
    d = b.rechunk((32, 32))
    e = xp.add(c, d)
    # budget smaller than one array: everything evicts constantly
    ex = JaxExecutor(device_mem=20_000)
    np.testing.assert_allclose(
        e.compute(executor=ex), (an + 1) * 2 + (an + 1)
    )


def test_sharding_for_chunks():
    from cubed_tpu.parallel.mesh import make_mesh, sharding_for_chunks

    devs = _cpu_devices()
    if len(devs) < 8:
        pytest.skip("needs 8 devices")
    mesh = make_mesh(shape=(8,), devices=devs[:8])
    sharding = sharding_for_chunks(mesh, ((2,) * 8, (6,) * 4), (16, 24))
    spec_dims = sharding.spec
    assert spec_dims[0] == "data"  # most blocks and divisible


def test_prime_factors():
    from cubed_tpu.parallel.mesh import prime_factors

    assert prime_factors(8) == [2, 2, 2]
    assert prime_factors(12) == [2, 2, 3]
    assert prime_factors(7) == [7]
    assert prime_factors(1) == []


@needs_8
def test_factorized_mesh_shards_odd_shapes():
    # the round-2 verdict case: (499, 450, 400) replicated under a 1-d 8-mesh
    # because no dim divides by 8; the factorized (2,2,2) placement shards it
    # 8-way across two dims
    from cubed_tpu.parallel.mesh import (
        factorized_mesh,
        make_mesh,
        sharding_for_chunks,
    )

    mesh = make_mesh(shape=(8,), devices=_cpu_devices()[:8])
    fmesh = factorized_mesh(mesh)
    assert fmesh.devices.shape == (2, 2, 2)

    shape = (499, 450, 400)
    chunkset = tuple(
        tuple(min(100, s - i) for i in range(0, s, 100)) for s in shape
    )
    sharding = sharding_for_chunks(fmesh, chunkset, shape)
    shard_shape = sharding.shard_shape(shape)
    # fully 8-way sharded: each shard holds 1/8 of the elements
    import math

    assert math.prod(shard_shape) * 8 == math.prod(shape)


@needs_8
def test_sharding_for_chunks_2d_mesh_uneven_grid():
    from cubed_tpu.parallel.mesh import make_mesh, sharding_for_chunks

    mesh = make_mesh(shape=(4, 2), axis_names=("a", "b"), devices=_cpu_devices()[:8])
    # ragged chunk grid: 19 = 5+5+5+4 blocks of chunk 5; both dims uneven
    sharding = sharding_for_chunks(mesh, ((5, 5, 5, 4), (6, 6, 2)), (19, 14))
    # 19 is prime (no axis divides); 14 % 2 == 0 -> 'b' lands on dim 1
    assert sharding.spec[1] == "b" or sharding.spec[1] == ("b",)
    assert sharding.spec[0] is None


@needs_8
def test_sharded_execution_nondivisible_shape(spec, mesh_executor):
    # shape with no dim divisible by 8: the factorized placement mesh must
    # still shard it AND produce correct results
    an = np.arange(34.0 * 12).reshape(34, 12)
    a = ct.from_array(an, chunks=(8, 6), spec=spec)
    b = ct.from_array(an, chunks=(8, 6), spec=spec)
    out = xp.sum(xp.add(xp.multiply(a, 2.0), b))
    np.testing.assert_allclose(
        float(out.compute(executor=mesh_executor)), (an * 3.0).sum()
    )


@needs_8
def test_executor_uses_mesh_policy(mesh_executor):
    # the executor must delegate to parallel.mesh.sharding_for_chunks (one
    # policy); (34, 12) has no dim divisible by 8 but shards 8-way factorized
    s = mesh_executor._sharding_for((34, 12))
    assert s is not None
    import math

    assert math.prod(s.shard_shape((34, 12))) * 8 == 34 * 12
