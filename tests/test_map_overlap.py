"""map_overlap — the chunked stencil primitive (no reference counterpart;
dask.array.map_overlap semantics)."""

import numpy as np
import pytest

import cubed_tpu as ct


def asnp(x):
    return np.asarray(x.compute())


def smooth(block):
    b = np.asarray(block)
    return sum(
        np.roll(np.roll(b, i, 0), j, 1)
        for i in (-1, 0, 1) for j in (-1, 0, 1)
    ) / 9.0


def expected(an, npmode, **kw):
    pe = np.pad(an, 1, mode=npmode, **kw)
    n, m = an.shape
    return sum(
        pe[1 + i:n + 1 + i, 1 + j:m + 1 + j]
        for i in (-1, 0, 1) for j in (-1, 0, 1)
    ) / 9.0


@pytest.mark.parametrize(
    "boundary,npmode,kw",
    [
        ("reflect", "symmetric", {}),
        ("nearest", "edge", {}),
        ("periodic", "wrap", {}),
        (0.0, "constant", {"constant_values": 0.0}),
        (2.5, "constant", {"constant_values": 2.5}),
    ],
)
def test_map_overlap_boundaries(spec, boundary, npmode, kw):
    an = np.random.default_rng(0).standard_normal((40, 40))
    a = ct.from_array(an, chunks=(10, 10), spec=spec)
    got = asnp(ct.map_overlap(smooth, a, depth=1, boundary=boundary))
    np.testing.assert_allclose(got, expected(an, npmode, **kw), atol=1e-12)


def test_map_overlap_depth_forms(spec):
    an = np.random.default_rng(1).standard_normal((24, 18))
    a = ct.from_array(an, chunks=(8, 6), spec=spec)

    def ident(b):
        return np.asarray(b)

    np.testing.assert_allclose(asnp(ct.map_overlap(ident, a, depth=2)), an)
    np.testing.assert_allclose(
        asnp(ct.map_overlap(ident, a, depth={0: 1})), an
    )
    np.testing.assert_allclose(
        asnp(ct.map_overlap(ident, a, depth=(2, 0))), an
    )
    with pytest.raises(ValueError):
        ct.map_overlap(ident, a, depth=-1)
    with pytest.raises(ValueError):
        ct.map_overlap(ident, a, depth=100)
    with pytest.raises(ValueError):
        ct.map_overlap(ident, a, depth=1, boundary="bogus")
    with pytest.raises(IndexError):
        ct.map_overlap(ident, a, depth={2: 1})
    # negative axis keys normalize
    np.testing.assert_allclose(
        asnp(ct.map_overlap(ident, a, depth={-1: 1})), an
    )


def test_map_overlap_ragged_chunks(spec):
    an = np.random.default_rng(2).standard_normal((23, 17))
    a = ct.from_array(an, chunks=(7, 5), spec=spec)
    got = asnp(ct.map_overlap(smooth, a, depth=1))
    np.testing.assert_allclose(got, expected(an, "symmetric"), atol=1e-12)


def test_map_overlap_on_jax_executor(spec):
    from cubed_tpu.runtime.executors.jax import JaxExecutor

    an = np.random.default_rng(3).standard_normal((20, 20))
    a = ct.from_array(an, chunks=(5, 5), spec=spec)
    got = np.asarray(
        ct.map_overlap(smooth, a, depth=1).compute(executor=JaxExecutor())
    )
    np.testing.assert_allclose(got, expected(an, "symmetric"), atol=1e-10)


def test_map_overlap_trim_false_grows_chunks(spec):
    """Regression: ``trim=False`` used to declare the output with the
    SOURCE chunks while each task produced the extended (halo-kept) block
    — a broadcast failure at write time. Dask semantics: the untrimmed
    output keeps its halo, so chunks grow by ``2*depth`` per axis."""
    an = np.arange(48, dtype=np.float64).reshape(8, 6)
    a = ct.from_array(an, chunks=(4, 3), spec=spec)

    def ident(b):
        return np.asarray(b)

    r = ct.map_overlap(ident, a, depth=1, boundary="nearest", trim=False)
    assert r.chunks == ((6, 6), (5, 5))
    assert r.shape == (12, 10)
    got = asnp(r)
    # every output block is the source block + its 1-deep padded halo
    pe = np.pad(an, 1, mode="edge")
    for bi, r0 in enumerate((0, 4)):
        for bj, c0 in enumerate((0, 3)):
            block = got[bi * 6:(bi + 1) * 6, bj * 5:(bj + 1) * 5]
            np.testing.assert_array_equal(
                block, pe[r0:r0 + 6, c0:c0 + 5]
            )
    # per-axis depth: only the deep axis grows
    r2 = ct.map_overlap(ident, a, depth={0: 2}, trim=False)
    assert r2.chunks == ((8, 8), (3, 3))
    assert r2.shape == (16, 6)


def test_map_overlap_1d_diffusion_step(spec):
    # heat-equation step: the canonical halo-exchange workload
    an = np.random.default_rng(4).standard_normal(1000)
    a = ct.from_array(an, chunks=(100,), spec=spec)

    def step(b):
        b = np.asarray(b)
        return b + 0.1 * (np.roll(b, 1) - 2 * b + np.roll(b, -1))

    got = asnp(ct.map_overlap(step, a, depth=1, boundary="periodic"))
    expect = an + 0.1 * (np.roll(an, 1) - 2 * an + np.roll(an, -1))
    np.testing.assert_allclose(got, expect, atol=1e-12)
