"""Whole-compute trace collection: one clock-aligned Perfetto timeline.

``TraceCollector`` is a callback that merges, for one compute:

- **client-side lifecycle** — the compute span, one span per operation;
- **worker-side task spans** — every task's body plus the sub-spans its
  task scope buffered where it ran (storage reads/writes, kernel apply,
  integrity verification, retry sleeps — ``accounting.TaskScope.add_span``),
  shipped back in the task stats dict over whatever channel the executor
  already had (in-process events, the pool result, the fleet wire); failed
  attempts ship their buffer on the exception itself and client-side
  recompute repairs hand theirs to the out-of-band ring, so both still
  land on the timeline. Span recording is armed only while a collector is
  attached (or ``CUBED_TPU_TASK_SPANS=1``) — unobserved computes record
  and ship nothing;
- **scheduler decisions** — retries, requeues, backups, fail-fasts,
  admission step-downs, recompute repairs (``record_decision``), as
  instants on a ``scheduler`` lane;
- **memory-guard samples** — the sampler's RSS/pressure readings
  (``record_sample``) as Perfetto counter tracks.

Worker timestamps are **clock-aligned** before export: fleet workers carry
an NTP-style offset measured over the heartbeat channel (coordinator echoes
the worker's timestamp; accuracy ~RTT/2 — ``runtime/distributed.py``);
other remote processes get a min-skew estimate from the shipping latency of
their own results; in-process tasks need none. Each worker process gets its
own lane, so overlap, stragglers and skew are visible at a glance.

``export()`` writes ``trace-<compute_id>.json``; the flight recorder
(``observability/flightrecorder.py``) embeds the same merged trace in its
post-mortem bundle.

The decision/sample rings are process-global (bounded deques) with the same
known limitation as the metrics registry: computes running concurrently in
one process see each other's entries inside their windows.
"""

from __future__ import annotations

import logging
import os
import statistics
import threading
import time
from collections import deque
from typing import Optional

from . import clock, logs
from .events import EventLogCallback
from .metrics import get_registry
from .tracer import Tracer

logger = logging.getLogger(__name__)

#: bounded process-global rings (see module docstring)
MAX_DECISIONS = 4096
MAX_SAMPLES = 4096
MAX_OOB_TASKS = 1024
#: chunk graphs retained for post-compute analytics (one per recent
#: compute) and the per-graph task bound — a million-task graph must not
#: pin a million edge lists in the ring; truncation is counted, not silent
MAX_CHUNK_GRAPHS = 4
MAX_GRAPH_TASKS = 50_000

_ring_lock = threading.Lock()
_decisions: deque = deque(maxlen=MAX_DECISIONS)
_samples: deque = deque(maxlen=MAX_SAMPLES)
#: out-of-band task records: failed attempts (salvaged off the exception)
#: and client-side recompute repairs — work with no TaskEndEvent to ride,
#: merged into the trace at export like the decision ring
_oob_tasks: deque = deque(maxlen=MAX_OOB_TASKS)
#: chunk-level dependency edges per recent compute (dataflow scheduler
#: records them while spans are armed); the flight recorder embeds them in
#: its manifest so ``analytics.analyze`` can walk the true critical path
_chunk_graphs: deque = deque(maxlen=MAX_CHUNK_GRAPHS)


#: extra consumers of decision entries beyond the bounded ring — the
#: durable compute journal (runtime/journal.py) registers here so a
#: coordinator crash still leaves the decision timeline on disk
_decision_sinks: list = []


def add_decision_sink(fn) -> None:
    """Register a callable receiving every decision entry (a plain dict)."""
    with _ring_lock:
        if fn not in _decision_sinks:
            _decision_sinks.append(fn)


def remove_decision_sink(fn) -> None:
    with _ring_lock:
        try:
            _decision_sinks.remove(fn)
        except ValueError:
            pass


def record_decision(kind: str, **attrs) -> None:
    """Record one scheduler/controller decision (timestamped, correlated).

    Cheap (a dict append under a lock) and bounded; called from the retry
    machinery, the admission controller, and the executors."""
    entry = {"ts": clock.now(), "kind": kind}
    cid = logs.current_compute_id()
    if cid is not None:
        entry["compute_id"] = cid
    if attrs:
        entry.update(attrs)
    with _ring_lock:
        _decisions.append(entry)
        sinks = list(_decision_sinks)
    for fn in sinks:
        try:
            fn(dict(entry))
        except Exception:  # a broken sink must never fail a decision site
            logger.exception("decision sink failed")


def record_sample(**attrs) -> None:
    """Record one memory-guard sampler reading (rss/pressure/available)."""
    entry = {"ts": clock.now()}
    entry.update(attrs)
    with _ring_lock:
        _samples.append(entry)


def record_failed_task(op, chunk, attempt, exc) -> None:
    """Salvage a failed attempt's span buffer for the merged trace.

    A raising task never produces a ``TaskEndEvent``, but
    ``execute_with_stats`` attaches the task scope's stats (spans, timing,
    pid/worker label) to the exception before it propagates — intact
    in-process, preserved by pickling off a pool worker, copied onto the
    ``RemoteTaskError`` from the fleet error frame. The failure handlers
    (``map_unordered`` and the sequential executor) call this once per
    observed failure, so the failing attempt lands on its worker's lane
    with ``error=True`` — exactly the case the trace exists for. A no-op
    for exceptions carrying no stats (spans disarmed, or a failure outside
    the task body)."""
    stats = getattr(exc, "cubed_tpu_task_stats", None)
    if not isinstance(stats, dict):
        return
    dropped = stats.get("spans_dropped") or 0
    if dropped:
        get_registry().counter("spans_dropped").inc(dropped)
    entry = {
        "ts": clock.now(),
        "op": op,
        "chunk": chunk,
        "attempt": attempt,
        "start": stats.get("function_start_tstamp"),
        "end": stats.get("function_end_tstamp"),
        "pid": stats.get("pid"),
        "worker": stats.get("worker"),
        "spans": stats.get("spans") or [],
        "error_type": stats.get("error_type") or type(exc).__name__,
        #: emit a task-level error span at merge, not just the sub-spans
        "task": True,
    }
    with _ring_lock:
        _oob_tasks.append(entry)


def record_repair_spans(chunk, store, scope_stats: dict) -> None:
    """Ship a client-side recompute repair's span buffer to the trace.

    The repair (``pipeline.RecomputeResolver``) runs in its own task scope
    but has no task event to ride, so its spans — the ``recompute_repair``
    wrapper plus the storage IO inside it — are handed straight to this
    ring. Only the sub-spans are merged (``task=False``): the
    ``recompute_repair`` scope span already brackets the whole repair."""
    spans = scope_stats.get("spans") or []
    if not spans:
        return  # spans disarmed: nothing to place on the trace
    from .accounting import get_process_label

    entry = {
        "ts": clock.now(),
        "op": "recompute_repair",
        "chunk": chunk,
        "store": store,
        "attempt": 0,
        "start": None,
        "end": None,
        "pid": os.getpid(),
        "worker": get_process_label(),
        "spans": spans,
        "task": False,
    }
    with _ring_lock:
        _oob_tasks.append(entry)


def record_chunk_graph(edges: dict, compute_id: Optional[str] = None) -> None:
    """Retain one compute's chunk-level dependency edges for analytics.

    ``edges`` maps ``"<op>\\t<chunk>"`` task keys to lists of the task keys
    they depend on (``ChunkGraph.edges_by_key``). Graphs beyond
    ``MAX_GRAPH_TASKS`` tasks are truncated to the bound (counted in
    ``chunk_graph_tasks_truncated``) — the analytics layer degrades to the
    op-graph approximation for the missing tail, it never silently loses
    the whole graph."""
    if compute_id is None:
        compute_id = logs.current_compute_id()
    truncated = 0
    if len(edges) > MAX_GRAPH_TASKS:
        truncated = len(edges) - MAX_GRAPH_TASKS
        edges = dict(list(edges.items())[:MAX_GRAPH_TASKS])
        get_registry().counter("chunk_graph_tasks_truncated").inc(truncated)
        logger.warning(
            "chunk graph for compute %s exceeds the %d-task analytics "
            "bound; %d task(s) truncated (critical-path extraction falls "
            "back to op-level edges for them)",
            compute_id, MAX_GRAPH_TASKS, truncated,
        )
    entry = {
        "ts": clock.now(),
        "compute_id": compute_id,
        "edges": edges,
        "truncated": truncated,
    }
    with _ring_lock:
        _chunk_graphs.append(entry)


def chunk_graph_for(
    compute_id: Optional[str] = None, since: Optional[float] = None,
) -> Optional[dict]:
    """The most recent recorded chunk graph matching ``compute_id`` (or,
    when None, the newest one recorded at/after ``since``); None when the
    compute ran without the dataflow scheduler or unobserved."""
    with _ring_lock:
        entries = list(_chunk_graphs)
    for entry in reversed(entries):
        if compute_id is not None and entry["compute_id"] == compute_id:
            return entry["edges"]
    if compute_id is not None and since is None:
        return None
    for entry in reversed(entries):
        # id-less fallback (graphs recorded outside a compute scope —
        # direct scheduler use in tests): newest graph in the window
        if entry["compute_id"] is None and (
            since is None or entry["ts"] >= since
        ):
            return entry["edges"]
    return None


def decisions_since(t0: float) -> list:
    with _ring_lock:
        return [d for d in _decisions if d["ts"] >= t0]


def samples_since(t0: float) -> list:
    with _ring_lock:
        return [s for s in _samples if s["ts"] >= t0]


def oob_tasks_since(t0: float) -> list:
    with _ring_lock:
        return [t for t in _oob_tasks if t["ts"] >= t0]


class TraceCollector(EventLogCallback):
    """Merge client spans, worker spans, decisions and memory samples into
    a single clock-aligned Perfetto trace for one compute.

    Parameters
    ----------
    trace_dir : str | None
        Directory to write ``trace-<compute_id>.json`` into at compute end
        (None disables the automatic export; ``export()`` still works).
    straggler_factor / straggler_min_s / straggler_min_tasks
        Live straggler watch: once an op has ``straggler_min_tasks``
        completed tasks, any task slower than ``straggler_factor`` x the
        op's rolling median (and ``straggler_min_s``) is flagged as it
        lands — a structured warning, the ``stragglers_detected`` counter,
        and a ``scheduler`` instant in the trace.
    max_task_records
        Bound on retained per-task records; overflow is counted and
        reported, never silent.
    offset_threshold_s
        Minimum magnitude for a latency-estimated clock offset to be
        applied (same-host processes share a clock; sub-threshold
        estimates are measurement noise, not skew).
    """

    def __init__(
        self,
        trace_dir: Optional[str] = ".",
        straggler_factor: float = 3.0,
        straggler_min_s: float = 0.05,
        straggler_min_tasks: int = 5,
        max_task_records: int = 100_000,
        offset_threshold_s: float = 0.05,
    ):
        super().__init__()
        self.trace_dir = trace_dir
        self.straggler_factor = straggler_factor
        self.straggler_min_s = straggler_min_s
        self.straggler_min_tasks = straggler_min_tasks
        self.max_task_records = max_task_records
        self.offset_threshold_s = offset_threshold_s
        self.compute_id: str = "unknown"
        self.executor_stats: Optional[dict] = None
        self.error = None
        self.trace_path: Optional[str] = None
        self._t0: float = 0.0
        self._records: list[dict] = []
        self.records_dropped = 0
        self._peaks: dict[str, int] = {}
        self._durations: dict[str, deque] = {}
        #: worker/pid key -> smallest observed (result-receipt - worker-end)
        #: delta, the latency-bounded clock-offset estimate
        self._raw_offsets: dict[str, float] = {}
        #: op -> sorted producing-op names, captured from the finalized dag
        #: at compute start — the op-level dependency skeleton analytics
        #: falls back to when no chunk graph was recorded (op-level
        #: scheduler, or a task beyond the chunk-graph bound)
        self._op_graph: dict[str, list] = {}
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------

    def on_compute_start(self, event) -> None:
        super().on_compute_start(event)
        cid = getattr(event, "compute_id", None)
        self.compute_id = cid or f"c-pid{os.getpid()}-{int(time.time())}"
        self.executor_stats = None
        self.error = None
        self.trace_path = None
        self._t0 = time.time()
        self._records = []
        self.records_dropped = 0
        self._peaks = {}
        self._durations = {}
        self._raw_offsets = {}
        self._op_graph = {}
        try:
            dag = event.dag
            nodes = dict(dag.nodes(data=True))
            for name, d in nodes.items():
                if d.get("type") != "op" or d.get("primitive_op") is None:
                    continue
                preds = set()
                for pred in dag.predecessors(name):
                    pd = nodes[pred]
                    if pd.get("type") == "op":
                        if pd.get("primitive_op") is not None:
                            preds.add(pred)
                        continue
                    for producer in dag.predecessors(pred):
                        pr = nodes[producer]
                        if (
                            pr.get("type") == "op"
                            and pr.get("primitive_op") is not None
                        ):
                            preds.add(producer)
                self._op_graph[name] = sorted(preds)
        except Exception:  # introspection must never fail a compute
            logger.exception("op-graph capture failed; analytics degrades")

    def op_graph(self) -> dict:
        """``op -> [producing op, ...]`` for the compute's finalized dag."""
        return {k: list(v) for k, v in self._op_graph.items()}

    def chunk_graph(self) -> Optional[dict]:
        """This compute's recorded chunk-level edges (dataflow scheduler,
        spans armed), or None — see :func:`chunk_graph_for`."""
        return chunk_graph_for(self.compute_id, since=self._t0)

    def on_task_end(self, event) -> None:
        # deliberately NOT super(): fold into bounded records instead of
        # retaining every TaskEndEvent (EventLogCallback keeps them all)
        start = event.function_start_tstamp
        end = event.function_end_tstamp
        if start is None or end is None:
            return
        if event.peak_measured_mem_end is not None:
            peak = self._peaks.get(event.array_name, 0)
            if event.peak_measured_mem_end > peak:
                self._peaks[event.array_name] = event.peak_measured_mem_end
        dropped = getattr(event, "spans_dropped", None)
        if dropped:
            get_registry().counter("spans_dropped").inc(dropped)
        rec = {
            "op": event.array_name,
            "chunk": event.chunk_key,
            "attempt": event.attempt,
            "executor": event.executor,
            "start": start,
            "end": end,
            "pid": getattr(event, "pid", None),
            "worker": getattr(event, "worker", None),
            "spans": getattr(event, "spans", None) or [],
            "spans_dropped": dropped or 0,
            # the task's control-plane dispatch ledger (runtime/types.py):
            # analytics splits queue_wait into ready_wait vs
            # dispatch_overhead from these stamps
            "dispatch": getattr(event, "dispatch", None),
        }
        with self._lock:
            if len(self._records) >= self.max_task_records:
                self.records_dropped += 1
            else:
                self._records.append(rec)
            self._note_offset(rec, event.task_result_tstamp)
        self._straggler_watch(rec)

    def on_compute_end(self, event) -> None:
        super().on_compute_end(event)
        self.executor_stats = getattr(event, "executor_stats", None)
        self.error = getattr(event, "error", None)
        if self.records_dropped:
            logger.warning(
                "trace collector dropped %d task record(s) beyond the "
                "%d-record bound; the exported trace is truncated",
                self.records_dropped, self.max_task_records,
            )
        if self.trace_dir is not None:
            try:
                self.trace_path = self.export()
            except OSError:
                logger.exception(
                    "failed to export merged trace for compute %s",
                    self.compute_id,
                )

    # -- clock alignment -----------------------------------------------

    @staticmethod
    def _offset_key(rec: dict) -> str:
        if rec.get("worker"):
            return str(rec["worker"])
        if rec.get("pid") and rec["pid"] != os.getpid():
            return f"pid-{rec['pid']}"
        return "client"

    def _note_offset(self, rec: dict, result_tstamp) -> None:
        key = self._offset_key(rec)
        if key == "client":
            return
        if result_tstamp is None or rec["end"] is None:
            return
        # result receipt (client clock) minus task end (worker clock) =
        # true offset + shipping latency; the minimum over many tasks
        # approaches the true offset from above
        raw = result_tstamp - rec["end"]
        prev = self._raw_offsets.get(key)
        if prev is None or raw < prev:
            self._raw_offsets[key] = raw

    def clock_offsets(self) -> dict:
        """Per-worker clock corrections applied at export: seconds to ADD
        to that process's timestamps to land on the client timeline, with
        the estimate's source (``handshake``/``latency``/``local``)."""
        out: dict = {"client": {"offset": 0.0, "source": "local"}}
        workers = (self.executor_stats or {}).get("workers") or {}
        keys = set(self._raw_offsets)
        with self._lock:
            for rec in self._records:
                keys.add(self._offset_key(rec))
        for rec in oob_tasks_since(self._t0):
            # failed attempts off a worker that never completed a task still
            # need that worker's correction looked up (handshake offsets
            # exist regardless of completions)
            keys.add(self._offset_key(rec))
        for key in keys:
            if key == "client":
                continue
            row = workers.get(key) if isinstance(workers, dict) else None
            handshake = (row or {}).get("clock_offset")
            if handshake is not None:
                out[key] = {
                    "offset": float(handshake),
                    "rtt": (row or {}).get("clock_rtt"),
                    "source": "handshake",
                }
                continue
            raw = self._raw_offsets.get(key)
            if raw is not None and abs(raw) >= self.offset_threshold_s:
                out[key] = {"offset": float(raw), "source": "latency"}
            else:
                out[key] = {"offset": 0.0, "source": "local"}
        return out

    # -- straggler watch -----------------------------------------------

    def _straggler_watch(self, rec: dict) -> None:
        dur = rec["end"] - rec["start"]
        dq = self._durations.get(rec["op"])
        if dq is None:
            dq = self._durations[rec["op"]] = deque(maxlen=512)
        if len(dq) >= self.straggler_min_tasks:
            median = statistics.median(dq)
            if dur > max(self.straggler_min_s, self.straggler_factor * median):
                get_registry().counter("stragglers_detected").inc()
                record_decision(
                    "straggler",
                    op=rec["op"],
                    chunk=rec["chunk"],
                    duration_s=round(dur, 6),
                    op_median_s=round(median, 6),
                    worker=rec.get("worker") or rec.get("pid"),
                )
                logger.warning(
                    "straggler: task %s of %s took %.3fs (%.1fx the op "
                    "median %.3fs) on %s",
                    rec["chunk"], rec["op"], dur,
                    dur / median if median else float("inf"), median,
                    rec.get("worker") or rec.get("pid") or "client",
                )
        dq.append(dur)

    def stragglers(self, top: int = 10) -> list[dict]:
        """Post-hoc straggler table over ALL retained records: tasks slower
        than ``straggler_factor`` x their op's full-compute median."""
        with self._lock:
            records = list(self._records)
        by_op: dict[str, list] = {}
        for r in records:
            by_op.setdefault(r["op"], []).append(r)
        out = []
        for op, recs in by_op.items():
            durs = [r["end"] - r["start"] for r in recs]
            if len(durs) < 2:
                continue
            median = statistics.median(durs)
            for r, d in zip(recs, durs):
                if d > max(self.straggler_min_s, self.straggler_factor * median):
                    out.append(
                        {
                            "op": op,
                            "chunk": r["chunk"],
                            "duration_s": d,
                            "op_median_s": median,
                            "factor": d / median if median else None,
                            "worker": r.get("worker") or r.get("pid"),
                        }
                    )
        out.sort(key=lambda s: -(s["factor"] or 0))
        return out[:top]

    # -- export ----------------------------------------------------------

    def peak_measured_mem_by_op(self) -> dict[str, int]:
        return dict(self._peaks)

    def merged_tracer(self) -> Tracer:
        """Build the merged, clock-aligned event set as a :class:`Tracer`."""
        tr = Tracer(max_events=2_000_000)
        end_default = self.end_tstamp or time.time()
        if self.start_tstamp is not None:
            attrs = {"compute_id": self.compute_id}
            if self.error is not None:
                attrs["error"] = True
                attrs["error_type"] = type(self.error).__name__
            tr.add_complete(
                "compute", self.start_tstamp, end_default, lane="compute",
                cat="compute", **attrs,
            )
        for name, timing in self.op_timings.items():
            if timing.start_tstamp is None:
                continue
            tr.add_complete(
                name, timing.start_tstamp,
                timing.end_tstamp or end_default,
                lane="operations", cat="operation",
                num_tasks=timing.num_tasks,
            )
        offsets = {k: v["offset"] for k, v in self.clock_offsets().items()}

        def lane_of(rec: dict) -> str:
            if rec.get("worker"):
                return f"worker {rec['worker']}"
            if rec.get("pid") and rec["pid"] != os.getpid():
                return f"worker pid-{rec['pid']}"
            return "client tasks"

        def add_sub_spans(rec: dict, lane: str, off: float) -> None:
            for s in rec["spans"]:
                attrs = dict(s.get("attrs") or {})
                attrs["chunk_of_task"] = rec["chunk"]
                tr.add_complete(
                    s["name"], s["ts"] + off, s["ts"] + s["dur"] + off,
                    lane=lane, cat=s.get("cat", "span"), **attrs,
                )

        with self._lock:
            records = list(self._records)
        for rec in records:
            off = offsets.get(self._offset_key(rec), 0.0)
            lane = lane_of(rec)
            extra = {}
            if rec.get("dispatch"):
                # the ledger rides the task event so analyze() on a LOADED
                # trace can still split ready_wait vs dispatch_overhead
                extra["dispatch"] = rec["dispatch"]
            tr.add_complete(
                rec["op"], rec["start"] + off, rec["end"] + off,
                lane=lane, cat="task", chunk=rec["chunk"],
                attempt=rec["attempt"], executor=rec["executor"],
                **extra,
            )
            add_sub_spans(rec, lane, off)
        for rec in oob_tasks_since(self._t0):
            # failed attempts and client-side repairs: no TaskEndEvent ever
            # fired for these, so they merge straight off the ring —
            # clock-corrected and lane-assigned exactly like completions
            off = offsets.get(self._offset_key(rec), 0.0)
            lane = lane_of(rec)
            if rec.get("task") and rec.get("start") is not None:
                tr.add_complete(
                    rec["op"], rec["start"] + off,
                    (rec.get("end") or rec["start"]) + off,
                    lane=lane, cat="task", chunk=rec["chunk"],
                    attempt=rec["attempt"], error=True,
                    error_type=rec.get("error_type"),
                )
            add_sub_spans(rec, lane, off)
        for d in decisions_since(self._t0):
            attrs = {k: v for k, v in d.items() if k not in ("ts", "kind")}
            tr.instant(d["kind"], lane="scheduler", ts=d["ts"], **attrs)
        prof = None
        try:
            from .dispatchprofile import profile_for

            prof = profile_for(self.compute_id)
        except Exception:
            pass
        if prof is not None:
            # the coordinator self-profiler's leaf reservoir as instants:
            # a "dispatch profile" lane showing where the control plane's
            # threads were, aligned with the task lanes it dispatched
            for ts, leaf in prof.lane_samples():
                tr.instant(leaf, lane="dispatch profile", ts=ts)
        for s in samples_since(self._t0):
            # fleet-worker heartbeat samples carry the worker name and get
            # their own memory lane; sampler readings land on "memory"
            mlane = (
                f"memory {s['worker']}" if s.get("worker") else "memory"
            )
            if s.get("rss") is not None:
                tr.add_counter("rss_bytes", s["ts"], s["rss"], lane=mlane)
            if s.get("pressure") is not None:
                tr.add_counter(
                    "mem_pressure", s["ts"], s["pressure"], lane=mlane
                )
        return tr

    def export(self, path: Optional[str] = None) -> str:
        """Write the merged Perfetto trace; returns the path written."""
        if path is None:
            path = os.path.join(
                self.trace_dir or ".", f"trace-{self.compute_id}.json"
            )
        return self.merged_tracer().export_chrome(path)
