"""Chaos suite: end-to-end computes must survive injected storage
flakiness, task crashes, stragglers, and mid-compute worker loss — with
bitwise-correct results and bounded attempt counts — on every executor.

All tests run a seeded deterministic ``FaultInjector``
(``cubed_tpu/runtime/faults.py``); none touch the network beyond
localhost. Marked ``chaos`` (registered in conftest; tier-1, not slow).
"""

from __future__ import annotations

import concurrent.futures
import os
import threading
import time

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime import faults
from cubed_tpu.runtime.executors.python import PythonDagExecutor
from cubed_tpu.runtime.executors.python_async import (
    AsyncPythonDagExecutor,
    map_unordered,
)
from cubed_tpu.runtime.resilience import RetryBudgetExceededError, RetryPolicy

pytestmark = pytest.mark.chaos

#: the acceptance-criteria storage chaos profile: ~10% write flakiness plus
#: some read flakiness and task crashes; a seeded run replays identically
CHAOS_STORAGE = dict(
    seed=42,
    storage_read_failure_rate=0.1,
    storage_write_failure_rate=0.15,
    task_failure_rate=0.1,
)


class _StatsCapture:
    stats: dict = {}

    def on_compute_end(self, event):
        self.stats = event.executor_stats or {}


def _spec(tmp_path, **fault_kwargs):
    return ct.Spec(
        work_dir=str(tmp_path),
        allowed_mem="500MB",
        fault_injection=fault_kwargs or None,
    )


# -- end-to-end under storage flakiness, per executor --------------------


def test_chaos_threaded_storage_flakiness_bitwise_correct(
    tmp_path, invariant_audit
):
    journal = str(tmp_path / "chaos.journal.jsonl")
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", journal=journal,
        fault_injection=CHAOS_STORAGE,
    )
    an = np.arange(400, dtype=np.float64).reshape(20, 20)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 100 chunks
    b = xp.add(a, 1.0)
    cap = _StatsCapture()
    result = b.compute(
        executor=AsyncPythonDagExecutor(
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0)
        ),
        callbacks=[cap],
    )
    np.testing.assert_array_equal(result, an + 1.0)  # bitwise-correct
    # every injection and every retry shows up in the metrics snapshot
    assert cap.stats.get("faults_injected", 0) > 0, cap.stats
    assert cap.stats.get("task_retries", 0) > 0, cap.stats
    bo = cap.stats.get("retry_backoff_s") or {}
    assert bo.get("count", 0) == cap.stats["task_retries"]
    # and the durable artifacts prove nothing illegal happened on the way
    invariant_audit(
        journal=journal, work_dir=str(tmp_path), metrics=cap.stats
    )


def test_chaos_sequential_storage_flakiness(tmp_path):
    spec = _spec(tmp_path, **CHAOS_STORAGE)
    an = np.arange(144, dtype=np.float64).reshape(12, 12)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 36 chunks
    cap = _StatsCapture()
    result = xp.multiply(a, 2.0).compute(
        executor=PythonDagExecutor(
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0)
        ),
        callbacks=[cap],
    )
    np.testing.assert_array_equal(result, an * 2.0)
    assert cap.stats.get("faults_injected", 0) > 0, cap.stats
    assert cap.stats.get("task_retries", 0) > 0, cap.stats


def test_chaos_multiprocess_storage_flakiness(tmp_path, monkeypatch):
    # env-var activation: spawned pool workers inherit the armed injector
    monkeypatch.setenv(
        faults.FAULTS_ENV_VAR,
        faults.FaultConfig(
            seed=42, storage_write_failure_rate=0.2
        ).to_env_json(),
    )
    from cubed_tpu.runtime.executors.multiprocess import MultiprocessDagExecutor

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 25 chunks
    cap = _StatsCapture()
    result = xp.add(a, 3.0).compute(
        executor=MultiprocessDagExecutor(
            max_workers=2,
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
        ),
        callbacks=[cap],
    )
    np.testing.assert_array_equal(result, an + 3.0)
    # injections happen worker-side; the retries they force are client-side
    assert cap.stats.get("task_retries", 0) > 0, cap.stats


def test_chaos_distributed_worker_crash_mid_compute(
    tmp_path, monkeypatch, invariant_audit
):
    """Storage flakiness plus one injected worker hard-exit: in-flight tasks
    fail with WorkerLostError and requeue onto the survivor for free, task
    faults burn normal retries, and the result is still bitwise-correct."""
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

    monkeypatch.setenv(
        faults.FAULTS_ENV_VAR,
        faults.FaultConfig(
            seed=7,
            storage_write_failure_rate=0.1,
            # locally spawned workers are named local-0/local-1; the
            # injector (armed in each worker via the inherited env) crashes
            # local-0 when it starts its 2nd task
            worker_crash_names=("local-0",),
            worker_crash_after_tasks=2,
        ).to_env_json(),
    )
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    before = get_registry().snapshot()
    control_dir = str(tmp_path / "ctrl")
    ex = DistributedDagExecutor(
        n_local_workers=2, control_dir=control_dir,
        retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
    )
    try:
        ex._ensure_fleet()
        a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 64 tasks per op
        cap = _StatsCapture()
        result = xp.add(a, 1.0).compute(executor=ex, callbacks=[cap])
        np.testing.assert_array_equal(result, an + 1.0)
        assert ex._coordinator.stats["workers_lost"] >= 1
        assert ex._coordinator.n_workers >= 1  # the survivor carried it
        delta = get_registry().snapshot_delta(before)
        assert delta.get("worker_loss_requeues", 0) >= 1, delta
        # pool-death diagnostics: the injected hard-exit (os._exit(137), a
        # SIGKILL shape) is attributed via the local-worker exit probe, so
        # the drop reason — and every WorkerLostError built from it — names
        # the exit code with the OOM hint instead of a bare reset
        departed = ex._coordinator.stats_snapshot()["workers"]
        assert any(
            "exitcode 137" in str(row.get("reason", ""))
            and "likely OOM-killed" in str(row.get("reason", ""))
            for row in departed.values()
        ), departed
    finally:
        ex.close()
    # the control log must show the crash as a LEGAL ownership hand-off
    # (worker_gone release between re-dispatches), and the metrics delta
    # must conserve the retry and injection counters
    invariant_audit(
        control_dir=control_dir, work_dir=str(tmp_path), metrics=delta
    )


from ..utils import SlowAdd as _SlowAdd  # noqa: E402


def test_chaos_spot_preemption_autoscaler_backfills_sublinear(tmp_path):
    """The headline elasticity proof: ~30% of the fleet is spot-preempted
    mid-compute (seeded SIGTERM -> drain notice -> hard kill), the
    autoscaler backfills replacements, and the compute finishes
    bitwise-correct with wall clock degrading SUB-linearly (< 2x the
    no-fault run on the same config) — preemptible capacity degrades
    gracefully instead of stalling.

    Seed 12 at rate 0.34 deterministically preempts local-0 (1 of 3 = 33%)
    after its 2nd task; the replacement names (local-3..) roll safe. The
    fleet is sized to this container (2 cores), not to a pod — the policy
    loop and drain path are identical at any scale."""
    from cubed_tpu.observability import collect
    from cubed_tpu.runtime.autoscale import AutoscalePolicy
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    delay = 0.25  # 64 tasks x 0.25s / 3 workers ~ 5s of real fleet work

    def run(workdir, fault_kwargs):
        spec = ct.Spec(
            work_dir=str(workdir), allowed_mem="500MB",
            fault_injection=fault_kwargs or None,
        )
        a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 64 tasks
        r = ct.map_blocks(_SlowAdd(delay), a, dtype=np.float64)
        ex = DistributedDagExecutor(
            n_local_workers=3,
            autoscale_policy=AutoscalePolicy(
                min_workers=3, max_workers=4, interval_s=0.25,
                # no scale-down mid-test: this test is about backfill
                idle_rounds_before_down=10**6, cooldown_down_s=3600,
            ),
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
        )
        try:
            coord = ex._ensure_fleet()
            t0 = time.monotonic()
            result = r.compute(executor=ex)
            elapsed = time.monotonic() - t0
            np.testing.assert_array_equal(result, an + 1.0)  # bitwise
            snap = coord.stats_snapshot()
            if ex._autoscaler is not None:
                snap["autoscale"] = dict(ex._autoscaler.stats)
            # give a still-booting replacement a moment to register so the
            # snapshot proves the backfill, not just the spawn
            if fault_kwargs:
                deadline = time.monotonic() + 15
                while time.monotonic() < deadline:
                    snap = coord.stats_snapshot()
                    snap["autoscale"] = dict(ex._autoscaler.stats)
                    if any(
                        row.get("alive")
                        for name, row in snap["workers"].items()
                        if name in ("local-3", "local-4")
                    ):
                        break
                    time.sleep(0.1)
            return elapsed, snap
        finally:
            ex.close()

    base_elapsed, _ = run(tmp_path / "base", None)
    t_ring = time.time()
    fault_elapsed, snap = run(
        tmp_path / "fault",
        dict(
            seed=12,
            worker_preempt_rate=0.34,
            worker_preempt_after_tasks=2,
            preempt_notice_s=0.8,
        ),
    )

    # ~30% of the fleet was actually preempted...
    assert snap["workers_preempted"] >= 1, snap
    assert snap["drains_completed"] >= 1, snap
    # ...the autoscaler backfilled, and at least one replacement REGISTERED
    assert snap["autoscale"]["workers_scaled_up"] >= 1, snap
    assert any(
        row.get("alive")
        for name, row in snap["workers"].items()
        if name in ("local-3", "local-4")
    ), snap["workers"]
    # the preempted workers departed cleanly (drained), not as lost crashes
    departed = [
        row for row in snap["workers"].values() if row.get("drained")
    ]
    assert len(departed) >= 1, snap["workers"]
    # sub-linear degradation: losing 33% of capacity for the whole run
    # would cost 1.5x; with backfill the run must stay under 2x the
    # no-fault run
    assert fault_elapsed < 2.0 * base_elapsed, (
        f"preempted run took {fault_elapsed:.2f}s vs {base_elapsed:.2f}s "
        "no-fault — degradation is not sub-linear"
    )
    # scale decisions landed in the decision ring (and with it the trace)
    kinds = {d["kind"] for d in collect.decisions_since(t_ring)}
    assert "worker_draining" in kinds, kinds
    assert "worker_drained" in kinds, kinds
    assert "scale_up" in kinds, kinds


# -- failure classification ----------------------------------------------


def test_chaos_nonretryable_fails_fast_exactly_one_attempt():
    """A deterministic programming error gets exactly 1 attempt: no retry,
    no backoff, even with retries configured."""
    calls = {}
    lock = threading.Lock()

    def boom(i, config=None):
        with lock:
            calls[i] = calls.get(i, 0) + 1
        raise TypeError(f"deterministic bug on {i}")

    before = get_registry().snapshot()
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        with pytest.raises(TypeError, match="deterministic bug"):
            map_unordered(
                pool, boom, [0],
                retry_policy=RetryPolicy(retries=5, backoff_base=0.2),
            )
    assert calls == {0: 1}
    delta = get_registry().snapshot_delta(before)
    assert delta.get("task_failfast", 0) == 1
    assert delta.get("task_retries", 0) == 0
    assert (delta.get("retry_backoff_s") or {}).get("count", 0) == 0


def test_chaos_nonretryable_fails_fast_end_to_end(tmp_path):
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    an = np.ones((4, 4))
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    calls = {}
    lock = threading.Lock()

    def bad(x):
        with lock:
            calls["n"] = calls.get("n", 0) + 1
        raise ValueError("wrong units")

    r = ct.map_blocks(bad, a, dtype=np.float64)
    with pytest.raises(ValueError, match="wrong units"):
        r.compute(executor=AsyncPythonDagExecutor(retries=5))
    # each of the 4 chunk tasks ran at most once; none was ever retried
    assert calls["n"] <= 4


def test_chaos_remote_programming_error_fails_fast(tmp_path):
    """The distributed fleet ships the remote exception's class name, so a
    remote TypeError fails fast instead of burning retries on reruns."""
    from cubed_tpu.runtime.distributed import RemoteTaskError
    from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor

    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    a = ct.from_array(np.ones((4, 4)), chunks=(2, 2), spec=spec)
    path = tmp_path / "counts"
    path.mkdir()

    with DistributedDagExecutor(n_local_workers=1) as ex:
        r = ct.map_blocks(
            _CountingTypeErrorTask(str(path)), a, dtype=np.float64
        )
        with pytest.raises(RemoteTaskError, match="TypeError"):
            r.compute(executor=ex, retries=5)
    from .utils import read_int_from_file

    total = sum(
        read_int_from_file(str(path / str(i))) for i in range(8)
    )
    assert 1 <= total <= 4  # at most once per chunk task, never retried


class _CountingTypeErrorTask:
    """Picklable task recording invocations in files, then raising a
    deterministic programming error."""

    def __init__(self, path):
        self.path = path
        self.n = 0

    def __call__(self, x):
        from .utils import read_int_from_file, write_int_to_file

        f = os.path.join(self.path, str(os.getpid() % 8))
        write_int_to_file(f, read_int_from_file(f) + 1)
        raise TypeError("deterministic remote bug")


# -- backoff spacing ------------------------------------------------------


def test_chaos_retries_spaced_by_exponential_backoff():
    times = []
    lock = threading.Lock()

    def flaky(i, config=None):
        with lock:
            times.append(time.monotonic())
            n = len(times)
        if n <= 2:
            raise OSError(f"transient {n}")
        return i

    before = get_registry().snapshot()
    policy = RetryPolicy(
        retries=3, backoff_base=0.15, backoff_multiplier=2.0, jitter="none"
    )
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        map_unordered(pool, flaky, [0], retry_policy=policy)
    assert len(times) == 3
    # failure 1 -> wait >= 0.15s; failure 2 -> wait >= 0.30s
    assert times[1] - times[0] >= 0.15 - 0.01, times
    assert times[2] - times[1] >= 0.30 - 0.01, times
    delta = get_registry().snapshot_delta(before)
    bo = delta.get("retry_backoff_s") or {}
    assert bo.get("count") == 2
    assert abs(bo.get("sum", 0) - 0.45) < 1e-6


# -- circuit breaker ------------------------------------------------------


def test_chaos_retry_budget_bounds_systemic_outage():
    """Every task failing transiently (a dead store) must abort after the
    compute-wide budget, not after n_tasks * retries attempts."""
    calls = {"n": 0}
    lock = threading.Lock()

    def always_down(i, config=None):
        with lock:
            calls["n"] += 1
        raise OSError("store is down")

    n_tasks, retries = 12, 5
    policy = RetryPolicy(
        retries=retries, backoff_base=0.005, budget_factor=0.1, budget_min=4
    )
    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        with pytest.raises(RetryBudgetExceededError, match="retry budget"):
            map_unordered(
                pool, always_down, list(range(n_tasks)), retry_policy=policy
            )
    budget_limit = policy.new_budget(n_tasks).limit  # max(4, ceil(.1*12*5))=6
    # first attempts + budgeted retries (+ small in-flight slack), far
    # below the un-breakered n_tasks * (retries + 1) = 72
    assert calls["n"] <= n_tasks + budget_limit + 4, calls["n"]


# -- stragglers -----------------------------------------------------------


def test_chaos_injected_stragglers_complete(tmp_path):
    spec = _spec(
        tmp_path, seed=1, straggler_rate=0.3, straggler_delay_s=0.15
    )
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 16 tasks
    cap = _StatsCapture()
    result = xp.add(a, 5.0).compute(
        executor=AsyncPythonDagExecutor(use_backups=True), callbacks=[cap]
    )
    np.testing.assert_array_equal(result, an + 5.0)
    assert cap.stats.get("faults_injected_straggler", 0) >= 1, cap.stats


# -- storage-layer read retries -------------------------------------------


def test_chaos_transient_chunk_read_retried_at_storage_layer(tmp_path):
    """A flaky chunk read is absorbed by the storage layer's own retry
    (cheap, in place) instead of failing the whole task."""
    from cubed_tpu.observability.accounting import task_scope
    from cubed_tpu.storage.store import open_zarr_array

    store = str(tmp_path / "arr")
    arr = open_zarr_array(store, mode="a", shape=(4,), dtype=np.float64, chunks=(4,))
    arr[:] = np.arange(4.0)

    before = get_registry().snapshot()
    # seed 9: the first read of key "arr/0" is injected to fail, its first
    # in-place retry succeeds (verified deterministic — see faults.py)
    with faults.scoped({"seed": 9, "storage_read_failure_rate": 0.9}):
        with task_scope():
            out = arr[:]
    np.testing.assert_array_equal(out, np.arange(4.0))
    delta = get_registry().snapshot_delta(before)
    assert delta.get("storage_read_retries", 0) >= 1, delta
    assert delta.get("faults_injected_storage_read", 0) >= 1, delta


def test_chaos_write_faults_leave_tmp_litter_that_resume_ignores(tmp_path):
    """Injected write failures litter partial .tmp files (a writer killed
    mid-write); resume accounting must not count them as chunks."""
    spec = _spec(
        tmp_path, seed=3, storage_write_failure_rate=0.3,
        storage_write_leaves_tmp=True,
    )
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 25 chunks
    result = xp.add(a, 1.0).compute(
        executor=AsyncPythonDagExecutor(
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0)
        )
    )
    np.testing.assert_array_equal(result, an + 1.0)
    # chaos left litter somewhere under the work dir...
    tmps = [
        f for root, _, names in os.walk(str(tmp_path))
        for f in names if f.endswith(".tmp")
    ]
    assert tmps, "expected injected write failures to leave .tmp litter"
    # ...and every store still reports only clean chunks
    from cubed_tpu.storage.store import open_zarr_array

    for root, _, names in os.walk(str(tmp_path)):
        if ".zarray" in names:
            arr = open_zarr_array(root, mode="r")
            assert arr.nchunks_initialized <= arr.nchunks
