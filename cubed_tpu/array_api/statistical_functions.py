"""Array-API statistical functions (reductions).

``mean``/``var``/``std`` use dict-of-arrays (pytree) intermediates instead of
the reference's Zarr structured dtypes — jax has no structured arrays, and
pytrees jit cleanly. The tree machinery stores each field as a PLAIN array
written by multi-output ops (core/ops.py reduction + partial_reduce_multi),
so intermediates shard under a device mesh like any other array; the
structured np.dtype passed as ``intermediate_dtype`` only declares the field
names/dtypes.
Reference parity: cubed/array_api/statistical_functions.py (156 LoC).
"""

from __future__ import annotations

import numpy as np

from ..backend_array_api import nxp
from ..core.ops import reduction
from .dtypes import (
    _numeric_dtypes,
    _real_floating_dtypes,
    _real_numeric_dtypes,
    _signed_integer_dtypes,
    _unsigned_integer_dtypes,
    complex64,
    complex128,
    float32,
    float64,
    int64,
    uint64,
)


def max(x, /, *, axis=None, keepdims=False, split_every=None):  # noqa: A001
    if x.dtype not in _real_numeric_dtypes:
        raise TypeError("Only real numeric dtypes are allowed in max")
    return reduction(
        x, nxp.max, axis=axis, dtype=x.dtype, keepdims=keepdims, split_every=split_every
    )


def min(x, /, *, axis=None, keepdims=False, split_every=None):  # noqa: A001
    if x.dtype not in _real_numeric_dtypes:
        raise TypeError("Only real numeric dtypes are allowed in min")
    return reduction(
        x, nxp.min, axis=axis, dtype=x.dtype, keepdims=keepdims, split_every=split_every
    )


def sum(x, /, *, axis=None, dtype=None, keepdims=False, split_every=None):  # noqa: A001
    if x.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in sum")
    if dtype is None:
        if x.dtype in _signed_integer_dtypes:
            dtype = int64
        elif x.dtype in _unsigned_integer_dtypes:
            dtype = uint64
        elif x.dtype == float32:
            dtype = float32
        elif x.dtype == complex64:
            dtype = complex64
        else:
            dtype = x.dtype
    dtype = np.dtype(dtype)
    return reduction(
        x,
        _sum_with_dtype,
        combine_func=_sum_with_dtype,
        axis=axis,
        intermediate_dtype=dtype,
        dtype=dtype,
        keepdims=keepdims,
        split_every=split_every,
        extra_func_kwargs=dict(dtype=dtype),
    )


def _sum_with_dtype(a, axis=None, keepdims=False, dtype=None):
    return nxp.sum(a, axis=axis, keepdims=keepdims, dtype=dtype)


# semantic tag on the combine (e.g. "sum"): kept as the seam for kernel
# substitution experiments — the round-3 Pallas streaming-reduction kernels
# consumed it before being retired on measured evidence (see
# benchmarks/BENCH_PROFILE.md "Pallas verdict")
_sum_with_dtype.reduce_kind = "sum"


def prod(x, /, *, axis=None, dtype=None, keepdims=False, split_every=None):
    if x.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in prod")
    if dtype is None:
        if x.dtype in _signed_integer_dtypes:
            dtype = int64
        elif x.dtype in _unsigned_integer_dtypes:
            dtype = uint64
        elif x.dtype == float32:
            dtype = float32
        elif x.dtype == complex64:
            dtype = complex64
        else:
            dtype = x.dtype
    dtype = np.dtype(dtype)
    return reduction(
        x,
        _prod_with_dtype,
        combine_func=_prod_with_dtype,
        axis=axis,
        intermediate_dtype=dtype,
        dtype=dtype,
        keepdims=keepdims,
        split_every=split_every,
        extra_func_kwargs=dict(dtype=dtype),
    )


def _prod_with_dtype(a, axis=None, keepdims=False, dtype=None):
    return nxp.prod(a, axis=axis, keepdims=keepdims, dtype=dtype)


# -- mean / var / std (pytree intermediates) --------------------------------

#: field declaration for the {n, total} intermediate (each field rides as a
#: plain array through the multi-output tree; the reference instead stores a
#: single structured array, cubed/array_api/statistical_functions.py:33-36)
def _mean_intermediate_dtype(x_dtype):
    return np.dtype([("n", np.int64), ("total", np.float64)])


def mean(x, /, *, axis=None, keepdims=False, split_every=None):
    if x.dtype not in _real_floating_dtypes:
        raise TypeError("Only real floating-point dtypes are allowed in mean")
    dtype = x.dtype
    intermediate_dtype = _mean_intermediate_dtype(dtype)
    return reduction(
        x,
        _mean_func,
        combine_func=_mean_combine,
        aggregate_func=_mean_aggregate,
        axis=axis,
        intermediate_dtype=intermediate_dtype,
        dtype=dtype,
        keepdims=keepdims,
        split_every=split_every,
    )


def _numel(x, axis=None, keepdims=False, dtype=np.float64):
    """Number of elements along axis, broadcast to the reduced shape."""
    shape = x.shape
    n = 1
    for ax in axis:
        n *= shape[ax]
    reduced_shape = tuple(
        1 if ax in axis else s for ax, s in enumerate(shape)
    )
    return nxp.broadcast_to(nxp.asarray(n, dtype=dtype), reduced_shape)


def _mean_func(a, axis=None, keepdims=True, **kwargs):
    n = _numel(a, axis=axis, keepdims=keepdims, dtype=np.int64)
    total = nxp.sum(a, axis=axis, keepdims=keepdims, dtype=np.float64)
    return {"n": n, "total": total}


def _mean_combine(a, axis=None, keepdims=True, **kwargs):
    n = nxp.sum(a["n"], axis=axis, keepdims=keepdims)
    total = nxp.sum(a["total"], axis=axis, keepdims=keepdims)
    return {"n": n, "total": total}


def _mean_aggregate(a):
    return nxp.divide(a["total"], a["n"])


def _var_intermediate_dtype(x_dtype):
    return np.dtype([("n", np.int64), ("mu", np.float64), ("M2", np.float64)])


def var(x, /, *, axis=None, correction=0.0, keepdims=False, split_every=None):
    """Variance via parallel Welford (Chan et al.) combination."""
    if x.dtype not in _real_floating_dtypes:
        raise TypeError("Only real floating-point dtypes are allowed in var")
    dtype = x.dtype
    intermediate_dtype = _var_intermediate_dtype(dtype)
    import functools

    return reduction(
        x,
        _var_func,
        combine_func=_var_combine,
        aggregate_func=functools.partial(_var_aggregate, correction=correction),
        axis=axis,
        intermediate_dtype=intermediate_dtype,
        dtype=dtype,
        keepdims=keepdims,
        split_every=split_every,
    )


def _var_func(a, axis=None, keepdims=True, **kwargs):
    n = _numel(a, axis=axis, dtype=np.int64)
    mu = nxp.mean(a, axis=axis, keepdims=keepdims, dtype=np.float64)
    M2 = nxp.sum(
        nxp.square(nxp.subtract(a, mu)), axis=axis, keepdims=keepdims, dtype=np.float64
    )
    return {"n": n, "mu": mu, "M2": M2}


def _var_combine(a, axis=None, keepdims=True, **kwargs):
    # n-ary Chan/Welford merge over ALL reduced axes at once. Reducing only
    # axis[0] broke the executor's region combine, which hands a multi-axis
    # block region in one call (the streaming path masked it by always
    # concatenating along one axis) — caught by the differential fuzzer.
    n = a["n"]
    mu = a["mu"]
    M2 = a["M2"]
    total_n = nxp.sum(n, axis=axis, keepdims=True)
    total = nxp.sum(nxp.multiply(mu, n), axis=axis, keepdims=True)
    new_mu = nxp.divide(total, total_n)
    # M2_total = sum(M2_i) + sum(n_i * (mu_i - new_mu)^2)
    new_M2 = nxp.sum(M2, axis=axis, keepdims=True) + nxp.sum(
        nxp.multiply(n, nxp.square(nxp.subtract(mu, new_mu))), axis=axis, keepdims=True
    )
    return {"n": total_n, "mu": new_mu, "M2": new_M2}


def _var_aggregate(a, correction=0.0):
    d = nxp.subtract(nxp.asarray(a["n"], dtype=np.float64), correction)
    return nxp.divide(a["M2"], d)


def std(x, /, *, axis=None, correction=0.0, keepdims=False, split_every=None):
    from .elementwise_functions import sqrt

    return sqrt(var(x, axis=axis, correction=correction, keepdims=keepdims,
                    split_every=split_every))


# -- cumulative_sum / cumulative_prod (2023.12 standard; beyond-reference) --
#
# The reference has no cumulative scan at all. Chunked prefix scan in two
# passes, both XLA-friendly (cumsum lowers to an associative scan):
#   1. per-block inclusive scan (embarrassingly parallel);
#   2. per-block totals -> one tiny single-chunk exclusive scan along the
#      axis -> per-block offsets, combined into the local scans blockwise.
# All intermediates are bounded: the totals array has one element per block
# along the scanned axis.


def _cumsum_backend(a, axis, dtype):
    return nxp.cumsum(a, axis=axis, dtype=dtype)


def _cumprod_backend(a, axis, dtype):
    return nxp.cumprod(a, axis=axis, dtype=dtype)


def _scan_default_dtype(x_dtype):
    if x_dtype in _signed_integer_dtypes:
        return int64
    if x_dtype in _unsigned_integer_dtypes:
        return uint64
    return x_dtype


def _cumulative(x, axis, dtype, include_initial, *, scan, reduce_fn, identity):
    from ..core.ops import general_blockwise, rechunk

    if x.dtype not in _numeric_dtypes:
        raise TypeError("Only numeric dtypes are allowed in cumulative scans")
    if axis is None:
        if x.ndim > 1:
            raise ValueError(
                "axis must be specified for multi-dimensional cumulative scans"
            )
        axis = 0
    if not -x.ndim <= axis < x.ndim:
        raise IndexError(f"axis {axis} out of bounds for ndim {x.ndim}")
    axis = axis % x.ndim
    if dtype is None:
        dtype = _scan_default_dtype(x.dtype)
    dtype = np.dtype(dtype)

    # CoreArray grids are always the regular blockdims of chunksize, so the
    # offsets pipeline below can rebuild every stage's grid from x.chunksize
    # with block coordinates staying 1:1 with x's
    chunkset = x.chunks
    nb = len(chunkset[axis])

    # 1. per-block inclusive scan
    def _local(a):
        return scan(a, axis, dtype)

    local = general_blockwise(
        _local,
        _same_block(x.name),
        x,
        shape=x.shape,
        dtype=dtype,
        chunks=chunkset,
        op_name="cumulative-local",
    )

    if nb > 1:
        # 2a. per-block totals: grid unchanged except size-1 blocks on axis
        def _totals(a):
            return reduce_fn(a, axis=(axis,), keepdims=True, dtype=dtype)

        totals_chunks = tuple(
            (1,) * nb if d == axis else chunkset[d] for d in range(x.ndim)
        )
        totals_shape = tuple(
            nb if d == axis else s for d, s in enumerate(x.shape)
        )
        totals = general_blockwise(
            _totals,
            _same_block(x.name),
            x,
            shape=totals_shape,
            dtype=dtype,
            chunks=totals_chunks,
            op_name="cumulative-totals",
        )
        # 2b. exclusive scan of the totals along the (now tiny) axis
        one_chunk = tuple(
            nb if d == axis else x.chunksize[d] for d in range(x.ndim)
        )
        gathered = rechunk(totals, one_chunk)

        def _exclusive(t):
            # shift the inclusive scan right by one block-slot, filling with
            # the identity (no subtract/divide: exact for unsigned wrap and
            # for products containing zeros)
            incl = scan(t, axis, dtype)
            head = tuple(
                slice(0, 1) if d == axis else slice(None) for d in range(t.ndim)
            )
            body = tuple(
                slice(0, -1) if d == axis else slice(None) for d in range(t.ndim)
            )
            lead = nxp.full_like(incl[head], identity)
            return nxp.concatenate([lead, incl[body]], axis=axis)

        excl = general_blockwise(
            _exclusive,
            _same_block(gathered.name),
            gathered,
            shape=totals_shape,
            dtype=dtype,
            chunks=gathered.chunks,
            op_name="cumulative-exclusive",
        )
        offsets = rechunk(excl, tuple(
            1 if d == axis else x.chunksize[d] for d in range(x.ndim)
        ))

        # 3. combine: out block i = local block i (+ or *) offsets block i
        l_name, o_name = local.name, offsets.name

        def _block_function(out_key):
            coords = out_key[1:]
            return ((l_name, *coords), (o_name, *coords))

        combine = _combine_add if identity == 0 else _combine_mul
        local = general_blockwise(
            combine,
            _block_function,
            local,
            offsets,
            shape=x.shape,
            dtype=dtype,
            chunks=chunkset,
            op_name="cumulative-combine",
        )

    if include_initial:
        from .creation_functions import full
        from .manipulation_functions import concat

        lead_shape = tuple(
            1 if d == axis else s for d, s in enumerate(x.shape)
        )
        lead = full(lead_shape, identity, dtype=dtype, spec=x.spec)
        return concat([lead, local], axis=axis)
    return local


def _same_block(name):
    def block_function(out_key):
        return ((name, *out_key[1:]),)

    return block_function


def _combine_add(a, o):
    return nxp.add(a, o)


def _combine_mul(a, o):
    return nxp.multiply(a, o)


def cumulative_sum(x, /, *, axis=None, dtype=None, include_initial=False):
    """Cumulative sum along ``axis`` (array-api 2023.12; reference gap)."""
    return _cumulative(
        x, axis, dtype, include_initial,
        scan=_cumsum_backend, reduce_fn=_sum_with_dtype, identity=0,
    )


def cumulative_prod(x, /, *, axis=None, dtype=None, include_initial=False):
    """Cumulative product along ``axis`` (array-api 2023.12; reference gap)."""
    return _cumulative(
        x, axis, dtype, include_initial,
        scan=_cumprod_backend, reduce_fn=_prod_with_dtype, identity=1,
    )


def _check_quantile_args(x, q, fname):
    if not isinstance(q, (int, float)) or isinstance(q, bool):
        raise TypeError(f"{fname}: q must be a python float in [0, 1]")
    q = float(q)
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"{fname}: q must be in [0, 1]")
    if x.dtype not in _real_floating_dtypes:
        raise TypeError(
            f"Only real floating-point dtypes are allowed in {fname}"
        )
    return q


def _check_quantile_axis(x, axis, fname):
    if not -x.ndim <= axis < x.ndim:
        raise IndexError(
            f"{fname}: axis {axis} is out of bounds for array of "
            f"dimension {x.ndim}"
        )
    axis = axis % x.ndim
    if x.shape[axis] == 0:
        raise ValueError(f"{fname} of an empty axis")
    return axis


def quantile(x, q, /, *, axis=None, keepdims=False, method="linear"):
    """EXACT quantile along an axis — beyond both the standard and the
    reference (dask only approximates multi-chunk quantiles): the axis
    runs through the scale-out sort network (so it may exceed
    ``allowed_mem``), and the quantile is two STATIC slices of the sorted
    axis interpolated elementwise — no data-dependent shapes anywhere.

    ``q`` is a python float in [0, 1] (scalar only; map over floats for
    several). ``method``: "linear" (numpy default), "lower", "higher",
    "nearest"."""
    from .elementwise_functions import add, multiply
    from .manipulation_functions import flatten, squeeze
    from .sorting_functions import sort

    q = _check_quantile_args(x, q, "quantile")
    if method not in ("linear", "lower", "higher", "nearest"):
        raise ValueError(f"quantile: unsupported method {method!r}")

    if axis is None:
        flat = flatten(x)
        out = quantile(flat, q, axis=0, method=method)
        if keepdims:
            from .manipulation_functions import expand_dims

            for _ in range(x.ndim):
                out = expand_dims(out, axis=0)
        return out

    axis = _check_quantile_axis(x, axis, "quantile")
    n = x.shape[axis]

    pos = q * (n - 1)
    lo = int(np.floor(pos))
    hi = int(np.ceil(pos))
    frac = pos - lo
    if method == "lower":
        hi, frac = lo, 0.0
    elif method == "higher":
        lo, frac = hi, 0.0
    elif method == "nearest":
        lo = hi = int(round(pos))
        frac = 0.0

    s = sort(x, axis=axis)
    sel_lo = tuple(
        slice(lo, lo + 1) if d == axis else slice(None) for d in range(x.ndim)
    )
    out = s[sel_lo]
    if hi != lo:
        sel_hi = tuple(
            slice(hi, hi + 1) if d == axis else slice(None)
            for d in range(x.ndim)
        )
        from .creation_functions import asarray

        w = asarray(frac, dtype=x.dtype, spec=x.spec)
        one_minus = asarray(1.0 - frac, dtype=x.dtype, spec=x.spec)
        out = add(multiply(out, one_minus), multiply(s[sel_hi], w))

    # numpy semantics: any NaN along the axis poisons the quantile. sort
    # parks NaNs at the END of the axis, so the LAST element alone tells
    # whether any NaN exists — one static slice, not a second full pass
    from .creation_functions import asarray as _asarray
    from .elementwise_functions import isnan
    from .searching_functions import where

    sel_last = tuple(
        slice(n - 1, n) if d == axis else slice(None) for d in range(x.ndim)
    )
    has_nan = isnan(s[sel_last])
    out = where(has_nan, _asarray(float("nan"), dtype=x.dtype, spec=x.spec),
                out)
    return out if keepdims else squeeze(out, axis=axis)


def median(x, /, *, axis=None, keepdims=False):
    """Exact median via :func:`quantile` (q=0.5) — the sorted axis may
    exceed ``allowed_mem`` (sort network)."""
    return quantile(x, 0.5, axis=axis, keepdims=keepdims)


def histogram(x, /, *, bins=10, range=None, weights=None, density=False):
    """Chunked histogram (numpy semantics; no reference counterpart).

    Output shapes are STATIC: ``bins`` is an int (with optional
    ``range``) or an explicit edges sequence; when ``range`` is omitted
    the data min/max are computed lazily IN the plan (data-dependent
    values, never data-dependent shapes). Per-block partial counts sum
    through the reduction tree, so ``x`` may exceed ``allowed_mem``.
    Returns ``(counts, edges)``; ``weights``/``density`` as in numpy.

    Documented deviation: NaN data with an IMPLICIT range yields NaN
    edges (and meaningless counts) instead of numpy's runtime
    ValueError — a lazy plan cannot raise on data-dependent values.
    Pass an explicit ``range``/edges (numpy-identical semantics: NaNs
    fall outside every bin) or filter NaNs first."""
    from ..core.ops import general_blockwise
    from .creation_functions import arange, asarray
    from .data_type_functions import astype
    from .elementwise_functions import add, divide, greater, multiply, subtract
    from .manipulation_functions import flatten
    from .searching_functions import where
    from .utility_functions import diff

    if x.dtype not in _real_floating_dtypes:
        raise TypeError(
            "Only real floating-point dtypes are allowed in histogram"
        )
    flat = flatten(x)
    wflat = None
    if weights is not None:
        if weights.shape != x.shape:
            raise ValueError("histogram: weights must match x's shape")
        wflat = flatten(weights)
        if wflat.chunks != flat.chunks:
            wflat = wflat.rechunk(flat.chunksize)

    spec = x.spec
    if np.ndim(bins) == 0:
        nbins = int(bins)
        if nbins <= 0:
            raise ValueError("histogram: bins must be positive")
        if range is not None:
            lo_v, hi_v = float(range[0]), float(range[1])
            if not lo_v <= hi_v:
                raise ValueError("histogram: range must be increasing")
            if lo_v == hi_v:
                lo_v, hi_v = lo_v - 0.5, hi_v + 0.5
            # exact endpoints (numpy linspace semantics): the max sample
            # must land IN the closed last bin
            edges = asarray(
                np.linspace(lo_v, hi_v, nbins + 1), spec=spec
            )
        else:
            # lazy data extent in ONE pass over the data: a {lo, hi}
            # field tree (the mean/var pytree machinery) instead of two
            # independent min/max reductions
            from ..core.ops import _aggregate_fields, reduction_fields

            parts = reduction_fields(
                flat, _extent_func, _extent_combine, axis=(0,),
                fields={"lo": np.dtype(np.float64),
                        "hi": np.dtype(np.float64)},
            )
            names = ["lo", "hi"]
            f64 = np.dtype(np.float64)
            lo = _aggregate_fields(parts, _take_lo, f64, names)
            hi = _aggregate_fields(parts, _take_hi, f64, names)
            degenerate = greater(hi, lo)
            half = asarray(0.5, dtype=np.dtype(np.float64), spec=spec)
            lo = where(degenerate, lo, subtract(lo, half))
            hi = where(degenerate, hi, add(hi, half))
            # convex combination lo*(1-t) + hi*t with t = i/nbins: the
            # first/last edges equal lo/hi EXACTLY (a lo + i*step form
            # can round the last edge below the data max, dropping the
            # max sample from the closed last bin)
            t = divide(
                arange(nbins + 1, dtype=np.dtype(np.float64), spec=spec),
                asarray(float(nbins), dtype=np.dtype(np.float64), spec=spec),
            )
            one = asarray(1.0, dtype=np.dtype(np.float64), spec=spec)
            edges = add(
                multiply(lo, subtract(one, t)), multiply(hi, t)
            )
    else:
        edges_np = np.asarray(bins, dtype=np.float64)
        if edges_np.ndim != 1 or edges_np.size < 2:
            raise ValueError("histogram: bins edges must be 1-d with >= 2")
        if np.any(np.diff(edges_np) < 0):
            raise ValueError("histogram: bins edges must be monotonic")
        nbins = edges_np.size - 1
        edges = asarray(edges_np, spec=spec)

    if len(edges.chunks[0]) > 1:
        edges = edges.rechunk((nbins + 1,))

    nb = flat.numblocks[0]
    out_dtype = (
        np.dtype(np.float64) if wflat is not None or density
        else np.dtype(np.int64)
    )
    flat_name, edges_name = flat.name, edges.name
    w_name = wflat.name if wflat is not None else None

    def bf(out_key):
        i = out_key[1]
        keys = [(flat_name, i), (edges_name, 0)]
        if w_name is not None:
            keys.append((w_name, i))
        return tuple(keys)

    def _hist_block(xb, eb, *maybe_w):
        wb = maybe_w[0] if maybe_w else None
        counts, _ = nxp.histogram(xb, bins=eb, weights=wb)
        return nxp.reshape(counts.astype(out_dtype), (1, -1))

    args = [flat, edges] + ([wflat] if wflat is not None else [])
    partial = general_blockwise(
        _hist_block, bf, *args,
        shape=(nb, nbins),
        dtype=out_dtype,
        chunks=((1,) * nb, (nbins,)),
        op_name="histogram_partial",
    )
    counts = sum(partial, axis=0, dtype=out_dtype)

    if density:
        widths = diff(edges)
        total = sum(astype(counts, np.dtype(np.float64)))
        counts = divide(
            astype(counts, np.dtype(np.float64)), multiply(total, widths)
        )
    return counts, edges


def _extent_func(a, axis=None, keepdims=True, **kwargs):
    return {
        "lo": nxp.min(a, axis=axis, keepdims=keepdims).astype(np.float64),
        "hi": nxp.max(a, axis=axis, keepdims=keepdims).astype(np.float64),
    }


def _extent_combine(a, axis=None, keepdims=True, **kwargs):
    return {
        "lo": nxp.min(a["lo"], axis=axis, keepdims=keepdims),
        "hi": nxp.max(a["hi"], axis=axis, keepdims=keepdims),
    }


def _take_lo(d):
    return d["lo"]


def _take_hi(d):
    return d["hi"]


def cov(m, /, *, rowvar=True, ddof=1):
    """Covariance matrix of chunked observations (numpy semantics, no
    reference counterpart): centering + one blockwise contraction, so
    the observation axis may exceed ``allowed_mem``."""
    from .linear_algebra_functions import matmul, matrix_transpose

    if m.ndim != 2:
        raise ValueError("cov requires a 2-d array")
    if m.dtype not in _real_floating_dtypes:
        raise TypeError("Only real floating-point dtypes are allowed in cov")
    x = m if rowvar else matrix_transpose(m)
    n_obs = x.shape[1]
    if n_obs - ddof <= 0:
        raise ValueError("cov: not enough observations for ddof")
    centered = _subtract_mean(x, axis=1)
    from .elementwise_functions import divide
    from .creation_functions import asarray

    return divide(
        matmul(centered, matrix_transpose(centered)),
        asarray(float(n_obs - ddof), dtype=x.dtype, spec=x.spec),
    )


def _subtract_mean(x, axis):
    from .elementwise_functions import subtract

    m = mean(x, axis=axis, keepdims=True)
    return subtract(x, m)


def corrcoef(m, /, *, rowvar=True):
    """Correlation matrix from :func:`cov` (numpy semantics)."""
    from .elementwise_functions import clip, divide, sqrt
    from .linalg import diagonal

    c = cov(m, rowvar=rowvar, ddof=1)
    d = sqrt(diagonal(c))
    # rounding can push perfectly-correlated entries past 1; numpy clips
    return clip(divide(c, _outer_like(d)), min=-1.0, max=1.0)


def _outer_like(d):
    from .elementwise_functions import multiply
    from .manipulation_functions import expand_dims

    return multiply(expand_dims(d, axis=1), expand_dims(d, axis=0))


def nanquantile(x, q, /, *, axis=None, keepdims=False):
    """EXACT quantile ignoring NaNs (numpy.nanquantile semantics, linear
    interpolation). The sorted axis parks NaNs at the END, so the number
    of valid elements per lane gives COMPUTED gather indices — resolved
    with ``take_along_axis`` (chunked, memory-bounded) rather than static
    slices; all shapes stay static. All-NaN lanes yield NaN."""
    from .creation_functions import asarray
    from .data_type_functions import astype
    from .elementwise_functions import (
        add, floor, isnan, logical_not, multiply, subtract,
    )
    from .indexing_functions import take_along_axis
    from .manipulation_functions import expand_dims, flatten, squeeze
    from .searching_functions import where
    from .sorting_functions import sort

    q = _check_quantile_args(x, q, "nanquantile")
    if axis is None:
        out = nanquantile(flatten(x), q, axis=0)
        if keepdims:
            for _ in range(x.ndim):
                out = expand_dims(out, axis=0)
        return out

    axis = _check_quantile_axis(x, axis, "nanquantile")

    s = sort(x, axis=axis)
    # valid (non-NaN) count per lane, kept as a size-1 axis
    n_valid = sum(
        astype(logical_not(isnan(x)), np.dtype(np.int64)),
        axis=axis, keepdims=True,
    )
    nf = astype(n_valid, np.dtype(np.float64))
    qk = asarray(q, dtype=np.dtype(np.float64), spec=x.spec)
    one = asarray(1.0, dtype=np.dtype(np.float64), spec=x.spec)
    pos = multiply(qk, subtract(nf, one))          # q * (n_valid - 1)
    zero = asarray(0.0, dtype=np.dtype(np.float64), spec=x.spec)
    # n_valid == 0 gives pos = -q: clamp (the all-NaN overwrite below
    # decides the lane's value either way)
    pos = where(pos < zero, zero, pos)
    lo_f = floor(pos)
    frac = astype(subtract(pos, lo_f), x.dtype)
    lo_i = astype(lo_f, np.dtype(np.int64))
    hi_i = where(
        add(lo_i, asarray(1, dtype=np.dtype(np.int64), spec=x.spec))
        < n_valid,
        add(lo_i, asarray(1, dtype=np.dtype(np.int64), spec=x.spec)),
        lo_i,
    )
    # ONE streamed gather for both bounds (take_along_axis reads every
    # chunk of the sorted axis per output block; two calls would read
    # the whole sorted array twice)
    from .manipulation_functions import concat

    both = take_along_axis(s, concat([lo_i, hi_i], axis=axis), axis=axis)
    sel_lo = tuple(
        slice(0, 1) if d == axis else slice(None) for d in range(x.ndim)
    )
    sel_hi = tuple(
        slice(1, 2) if d == axis else slice(None) for d in range(x.ndim)
    )
    v_lo, v_hi = both[sel_lo], both[sel_hi]
    out = add(
        multiply(v_lo, subtract(asarray(1.0, dtype=x.dtype, spec=x.spec),
                                frac)),
        multiply(v_hi, frac),
    )
    # all-NaN lanes: no valid data -> NaN
    nan_c = asarray(float("nan"), dtype=x.dtype, spec=x.spec)
    out = where(
        n_valid < asarray(1, dtype=np.dtype(np.int64), spec=x.spec),
        nan_c, out,
    )
    return out if keepdims else squeeze(out, axis=axis)


def nanmedian(x, /, *, axis=None, keepdims=False):
    """Exact median ignoring NaNs (see :func:`nanquantile`)."""
    return nanquantile(x, 0.5, axis=axis, keepdims=keepdims)
