"""Durable, bounded run-history archive: the cross-run memory.

``analyze()`` explains one compute and forgets it; the ``TimeSeriesStore``
dies with the process. The run archive is what survives: an append-only
``runs.jsonl`` (under ``Spec(run_history=path)`` for plain computes, or
the service's ``service_dir`` for per-request records) holding one
compact record per finished compute / service request — compute id,
tenant, the plan's structural fingerprint, wall clock, the ``analyze()``
bucket decomposition, metrics-delta highlights, and the
deadline/shed/error outcome.

Three consumers stand on it:

- **SLOs** (``observability/slo.py``): per-tenant error budgets are
  recomputed from the archive fold on service start, so a restart (or a
  SIGKILL) never resets a burned budget;
- **regression attribution** (``python -m cubed_tpu.regress`` /
  ``analyze(baseline=...)``): a baseline record with the same plan
  fingerprint is diffed bucket-by-bucket to name what got slower;
- **operators**: the archive is plain JSONL — ``jq`` away.

Durability discipline mirrors ``runtime/journal.py``: records are
appended whole-line with an fsync, the loader tolerates a torn final
line (a crash mid-append costs exactly that line), and appends never
raise into the compute path. The archive is BOUNDED: when the active
file passes ``max_bytes`` it rotates to ``runs.jsonl.1`` (one previous
generation retained — worst case on disk is ~2x the bound), and the
loader folds the previous generation first so history stays contiguous
across a rotation.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .metrics import get_registry

logger = logging.getLogger(__name__)

#: archive file name under the run-history / service directory
RUNS_FILENAME = "runs.jsonl"

#: default rotation bound for the active file (env override below); one
#: rotated generation is kept, so the archive occupies <= ~2x this
DEFAULT_MAX_ARCHIVE_BYTES = 8 * 1024 * 1024

MAX_BYTES_ENV_VAR = "CUBED_TPU_RUN_HISTORY_MAX_BYTES"

#: digest size caps: a record must stay compact (the archive is read
#: whole on every fold)
MAX_PER_OP = 16
MAX_STRAGGLERS = 5


def archive_path(history_dir: str) -> str:
    return os.path.join(history_dir, RUNS_FILENAME)


def _resolve_max_bytes(max_bytes: Optional[int]) -> int:
    if max_bytes is not None:
        return int(max_bytes)
    raw = os.environ.get(MAX_BYTES_ENV_VAR)
    if raw:
        try:
            return int(raw)
        except ValueError:
            logger.warning(
                "ignoring non-integer %s=%r", MAX_BYTES_ENV_VAR, raw
            )
    return DEFAULT_MAX_ARCHIVE_BYTES


class RunHistory:
    """Append-only, size-rotated ``runs.jsonl`` writer.

    Same contract as :class:`~cubed_tpu.runtime.journal.ComputeJournal`:
    ``append`` never raises (a full disk degrades observability, it must
    not fail the compute), every record is flushed + fsync'd before
    ``append`` returns, and a reader may fold the file at any moment."""

    def __init__(self, history_dir: str, max_bytes: Optional[int] = None):
        self.history_dir = history_dir
        self.path = archive_path(history_dir)
        self.max_bytes = max(4096, _resolve_max_bytes(max_bytes))
        self._lock = threading.Lock()
        self._file = None
        try:
            os.makedirs(history_dir, exist_ok=True)
            self._file = open(self.path, "ab")
        except OSError:
            logger.exception(
                "could not open run archive %s; records will be dropped",
                self.path,
            )

    def append(self, record: Dict[str, Any], fsync: bool = True) -> bool:
        """Write one record (with rotation); True when it landed."""
        if self._file is None:
            return False
        record.setdefault("ts", time.time())
        try:
            line = json.dumps(record, default=str) + "\n"
        except (TypeError, ValueError):
            logger.exception("unserializable run-history record dropped")
            return False
        data = line.encode()
        with self._lock:
            try:
                if self._file.tell() + len(data) > self.max_bytes:
                    self._rotate_locked()
                self._file.write(data)
                self._file.flush()
                if fsync:
                    os.fsync(self._file.fileno())
            except (OSError, ValueError):
                logger.exception(
                    "run-history append failed (%s)", self.path
                )
                return False
        get_registry().counter("run_history_appends").inc()
        return True

    def _rotate_locked(self) -> None:
        """Active file -> ``runs.jsonl.1`` (replacing any previous
        generation), then reopen fresh. Bounds the archive at ~2x
        ``max_bytes`` while keeping at least one full generation of
        history for the SLO fold and baseline search."""
        try:
            self._file.close()
        except OSError:
            pass
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            logger.exception("run-history rotation failed (%s)", self.path)
        self._file = open(self.path, "ab")
        get_registry().counter("run_history_rotations").inc()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


#: open writers, one per directory — the service and Plan.execute share
#: a handle so rotation bookkeeping stays coherent within a process
_histories: Dict[str, RunHistory] = {}
_histories_lock = threading.Lock()


def history_for(history_dir: str, max_bytes: Optional[int] = None) -> RunHistory:
    key = os.path.abspath(history_dir)
    with _histories_lock:
        h = _histories.get(key)
        if h is None or h._file is None:
            h = RunHistory(history_dir, max_bytes=max_bytes)
            _histories[key] = h
        return h


def load_runs(history_dir: str) -> Tuple[List[dict], int]:
    """Fold the archive: ``(records, bad_lines)``, oldest first.

    Reads the rotated generation (``runs.jsonl.1``) before the active
    file so history is contiguous across a rotation. Torn-line tolerant:
    a line that does not parse (the crash-interrupted tail, a truncated
    rotation boundary) is counted and skipped — it costs only itself."""
    records: List[dict] = []
    bad = 0
    path = archive_path(history_dir)
    for p in (path + ".1", path):
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for raw in data.splitlines():
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw)
            except (ValueError, UnicodeDecodeError):
                bad += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                bad += 1
    if bad:
        get_registry().counter("run_history_bad_lines").inc(bad)
    return records, bad


# ----------------------------------------------------------------------
# record assembly
# ----------------------------------------------------------------------


_METRIC_HIGHLIGHTS = (
    "tasks_completed", "task_retries", "task_errors", "bytes_read",
    "bytes_written", "peer_bytes_fetched", "stragglers_detected",
    "store_throttled",
)


def _metrics_digest(stats: Optional[dict]) -> Optional[dict]:
    if not isinstance(stats, dict):
        return None
    out = {}
    for k in _METRIC_HIGHLIGHTS:
        v = stats.get(k)
        if isinstance(v, (int, float)) and v:
            out[k] = v
    return out or None


def _analysis_digest(data: dict) -> dict:
    """The compact slice of an ``analyze()`` report a record carries:
    the bucket attribution, a bounded per-op busy digest, and the top
    stragglers (op + worker + slowdown factor)."""
    per_op = {}
    rows = sorted(
        (data.get("per_op") or {}).items(),
        key=lambda kv: -(kv[1].get("busy_s") or 0.0),
    )[:MAX_PER_OP]
    for name, row in rows:
        per_op[name] = {
            "busy_s": round(row.get("busy_s") or 0.0, 6),
            "tasks": row.get("tasks"),
            "stragglers": row.get("stragglers"),
            "buckets": {
                k: round(v, 6)
                for k, v in (row.get("buckets") or {}).items()
                if v and v > 1e-6
            },
        }
    stragglers = [
        {
            "op": s.get("op"),
            "worker": s.get("worker"),
            "factor": (
                round(s["factor"], 3)
                if isinstance(s.get("factor"), (int, float)) else None
            ),
            "duration_s": (
                round(s["duration_s"], 6)
                if isinstance(s.get("duration_s"), (int, float)) else None
            ),
        }
        for s in (data.get("stragglers") or [])[:MAX_STRAGGLERS]
    ]
    return {
        "buckets": {
            k: round(v, 6)
            for k, v in (data.get("attribution") or {}).items()
            if v and v > 1e-6
        },
        "attribution_coverage": data.get("attribution_coverage"),
        "per_op": per_op,
        "stragglers": stragglers,
    }


def record_compute(
    history_dir: str,
    *,
    compute_id: str,
    dag=None,
    error: Optional[BaseException] = None,
    stats: Optional[dict] = None,
    collector=None,
    wall_clock_s: Optional[float] = None,
    tenant: Optional[str] = None,
) -> Optional[dict]:
    """Assemble + append one compute record; returns the record (or
    None when nothing could be written). Never raises — archive failure
    must not fail the compute that just finished."""
    try:
        rec: Dict[str, Any] = {
            "kind": "compute",
            "ts": time.time(),
            "compute_id": compute_id,
            "ok": error is None,
            "error": type(error).__name__ if error is not None else None,
        }
        if tenant is not None:
            rec["tenant"] = tenant
        if dag is not None:
            try:
                from ..service.cache import structural_fingerprint

                fp, _ = structural_fingerprint(dag)
                rec["fingerprint"] = fp
            except Exception:
                rec["fingerprint"] = None
        if collector is not None:
            try:
                from .analytics import analyze

                data = analyze(collector).to_dict()
                rec.update(_analysis_digest(data))
                if wall_clock_s is None:
                    wall_clock_s = data.get("wall_clock_s")
            except Exception:
                # an empty trace (zero-task compute) or a collector that
                # failed mid-flight: the record still lands, just without
                # the bucket decomposition
                logger.debug(
                    "run-history: no analysis for %s", compute_id,
                    exc_info=True,
                )
        if wall_clock_s is not None:
            rec["wall_clock_s"] = round(float(wall_clock_s), 6)
        digest = _metrics_digest(stats)
        if digest:
            rec["metrics"] = digest
        history_for(history_dir).append(rec)
        return rec
    except Exception:
        logger.exception("run-history record assembly failed")
        return None


def record_request(
    history_dir: str,
    *,
    request_id: str,
    tenant: str,
    status: str,
    latency_s: Optional[float] = None,
    fingerprint: Optional[str] = None,
    compute_id: Optional[str] = None,
    error: Optional[str] = None,
    deadline_missed: bool = False,
    shed: bool = False,
    request_class: Optional[str] = None,
) -> Optional[dict]:
    """One service-request record (the SLO fold's raw material)."""
    try:
        rec: Dict[str, Any] = {
            "kind": "request",
            "ts": time.time(),
            "request_id": request_id,
            "tenant": tenant,
            "status": status,
            "ok": status == "completed",
        }
        if latency_s is not None:
            rec["latency_s"] = round(float(latency_s), 6)
        if fingerprint is not None:
            rec["fingerprint"] = fingerprint
        if compute_id is not None:
            rec["compute_id"] = compute_id
        if error is not None:
            rec["error"] = error
        if deadline_missed:
            rec["deadline_missed"] = True
        if shed:
            rec["shed"] = True
        if request_class is not None:
            rec["request_class"] = request_class
        history_for(history_dir).append(rec)
        return rec
    except Exception:
        logger.exception("run-history request record failed")
        return None


def find_baseline(
    records: List[dict],
    fingerprint: Optional[str],
    before_ts: Optional[float] = None,
    exclude_compute_id: Optional[str] = None,
) -> Optional[dict]:
    """Latest OK compute record matching ``fingerprint`` (strictly
    earlier than ``before_ts`` when given) — the regression baseline."""
    best = None
    for rec in records:
        if rec.get("kind") != "compute" or not rec.get("ok"):
            continue
        if exclude_compute_id and rec.get("compute_id") == exclude_compute_id:
            continue
        if fingerprint is not None and rec.get("fingerprint") != fingerprint:
            continue
        if before_ts is not None and (rec.get("ts") or 0) >= before_ts:
            continue
        if not rec.get("buckets"):
            continue  # a record without a decomposition cannot be diffed
        if best is None or (rec.get("ts") or 0) > (best.get("ts") or 0):
            best = rec
    return best
