"""Worker entry point: ``python -m cubed_tpu.runtime.worker HOST:PORT``.

Start one per host, pointing at the coordinator created by
``DistributedDagExecutor`` (its listen address; DCN-reachable in a TPU pod
deployment). The shared ``Spec.work_dir`` must be reachable from every host
(shared filesystem or object-store mount) — all chunk data moves through it,
the socket carries control messages only.
"""

from __future__ import annotations

import argparse
import logging
import os

from .distributed import run_worker


def _default_drain_grace() -> float:
    """``CUBED_TPU_DRAIN_GRACE_S`` or 10.0; a malformed value must not
    crash every worker at argparse construction (the fleet would fail to
    boot with only a wait_for_workers timeout as the diagnostic)."""
    raw = os.environ.get("CUBED_TPU_DRAIN_GRACE_S", "")
    try:
        return float(raw) if raw else 10.0
    except ValueError:
        logging.getLogger(__name__).warning(
            "ignoring malformed CUBED_TPU_DRAIN_GRACE_S=%r "
            "(want a float of seconds); using default 10.0", raw,
        )
        return 10.0


def _default_reconnect_give_up() -> float:
    """``CUBED_TPU_RECONNECT_GIVE_UP_S`` or 30.0; malformed values warn and
    fall back (same argparse-construction hazard as the drain grace)."""
    raw = os.environ.get("CUBED_TPU_RECONNECT_GIVE_UP_S", "")
    try:
        return float(raw) if raw else 30.0
    except ValueError:
        logging.getLogger(__name__).warning(
            "ignoring malformed CUBED_TPU_RECONNECT_GIVE_UP_S=%r "
            "(want a float of seconds); using default 30.0", raw,
        )
        return 30.0


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("coordinator", help="coordinator address, host:port")
    parser.add_argument(
        "--threads", type=int, default=1,
        help="concurrent task slots in this worker process (default 1)",
    )
    parser.add_argument("--name", default=None, help="worker display name")
    parser.add_argument(
        "--drain-grace", type=float, default=_default_drain_grace(),
        help="seconds allowed to finish in-flight tasks when draining "
        "(scale-down, or the SIGTERM spot-preemption notice window); "
        "in-flight work still running at the end of the window is "
        "abandoned and requeued by the coordinator (default 10, env "
        "CUBED_TPU_DRAIN_GRACE_S)",
    )
    parser.add_argument(
        "--reconnect-give-up", type=float,
        default=_default_reconnect_give_up(),
        help="seconds to keep retrying a lost coordinator connection "
        "before exiting; in-flight tasks keep running across a disconnect "
        "and unacked results replay on reconnect (default 30, env "
        "CUBED_TPU_RECONNECT_GIVE_UP_S)",
    )
    parser.add_argument(
        "--rendezvous", default=None,
        help="path to the coordinator's rendezvous advertisement file "
        "(written when the coordinator runs with a control_dir); the "
        "reconnect loop re-reads it to chase a successor coordinator "
        "after a control-plane crash, and the give-up clock is suspended "
        "while a takeover window is open",
    )
    parser.add_argument(
        "-v", "--verbose", action="store_true", help="log at INFO level"
    )
    parser.add_argument(
        "--log-json", action="store_true",
        help="emit structured JSON log lines carrying compute/op/chunk "
        "correlation ids (observability/logs.py)",
    )
    args = parser.parse_args(argv)
    level = logging.INFO if args.verbose else logging.WARNING
    if args.log_json:
        from ..observability.logs import basic_structured_config

        basic_structured_config(level)
    else:
        logging.basicConfig(
            level=level,
            format="%(asctime)s %(name)s %(levelname)s %(message)s",
        )
    run_worker(
        args.coordinator, nthreads=args.threads, name=args.name,
        drain_grace_s=args.drain_grace,
        reconnect_give_up_s=args.reconnect_give_up,
        rendezvous=args.rendezvous,
    )


if __name__ == "__main__":
    main()
