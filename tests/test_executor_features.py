"""Executor feature tests: retries, callbacks, resume, parallel generations,
history/timeline extensions, measure_reserved_mem.

Reference parity: cubed/tests/test_executor_features.py.
"""

import os

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.extensions.history import HistoryCallback
from cubed_tpu.extensions.timeline import TimelineVisualizationCallback
from cubed_tpu.extensions.tqdm import TqdmProgressBar
from cubed_tpu.runtime.executors.python_async import AsyncPythonDagExecutor

from .utils import TaskCounter


def test_callbacks_count_tasks(spec):
    counter = TaskCounter()
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    b.compute(callbacks=[counter], optimize_graph=False)
    # 9 compute tasks + create-arrays tasks
    assert counter.value >= 9


def test_history_callback(spec, tmp_path):
    history = HistoryCallback(history_dir=str(tmp_path / "history"))
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    b.compute(callbacks=[history])
    assert len(history.plan) > 0
    assert len(history.events) > 0
    stats = history.stats()
    compute_rows = [r for r in stats if r["op_name"] != "create-arrays"]
    assert all(r["projected_mem"] > 0 for r in compute_rows)
    assert os.path.isdir(str(tmp_path / "history"))
    assert any(f.startswith("plan-") for f in os.listdir(str(tmp_path / "history")))


def test_timeline_callback(spec, tmp_path):
    timeline = TimelineVisualizationCallback(plots_dir=str(tmp_path / "plots"))
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    xp.add(a, 1).compute(callbacks=[timeline])
    assert os.path.isdir(str(tmp_path / "plots"))
    assert len(os.listdir(str(tmp_path / "plots"))) == 1


def test_progress_bar(spec, capsys):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    xp.add(a, 1).compute(callbacks=[TqdmProgressBar()])


def test_resume_skips_completed(spec):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.add(b, 1)
    counter1 = TaskCounter()
    c.compute(callbacks=[counter1], optimize_graph=False)
    counter2 = TaskCounter()
    c.compute(callbacks=[counter2], optimize_graph=False, resume=True)
    assert counter2.value < counter1.value


def test_compute_arrays_in_parallel(spec):
    an = np.arange(16.0).reshape(4, 4)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    c = xp.multiply(a, 2)
    ex = AsyncPythonDagExecutor(compute_arrays_in_parallel=True)
    rb, rc = ct.compute(b, c, executor=ex)
    np.testing.assert_allclose(rb, an + 1)
    np.testing.assert_allclose(rc, an * 2)


def test_measure_reserved_mem(tmp_path):
    mem = ct.measure_reserved_mem(work_dir=str(tmp_path))
    assert mem > 1_000_000  # a python process uses more than 1MB


def test_executor_by_name(tmp_path):
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", executor_name="single-threaded"
    )
    a = xp.ones((4, 4), chunks=(2, 2), spec=spec)
    assert spec.executor is not None
    np.testing.assert_allclose(xp.add(a, 1).compute(), np.full((4, 4), 2.0))


def test_unknown_executor_name():
    from cubed_tpu.runtime.create import create_executor

    with pytest.raises(ValueError, match="Unrecognized executor name"):
        create_executor("nonexistent")


def test_visualize_outputs_dot(spec, tmp_path):
    a = xp.ones((6, 6), chunks=(2, 2), spec=spec)
    b = xp.add(a, 1)
    out = ct.visualize(b, filename=str(tmp_path / "plan"))
    assert os.path.exists(out)
    if out.endswith(".dot"):
        content = open(out).read()
        assert "digraph" in content
