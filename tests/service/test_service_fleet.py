"""End-to-end: a live ComputeService over a real 2-worker distributed
fleet serving two tenants, with the telemetry endpoint scraped for the
tenant-labelled series while the service is live (subprocess workers, in
the smoke.yml fast slice)."""

from __future__ import annotations

import json
from urllib.request import urlopen

import numpy as np
import pytest

import cubed_tpu as ct
from cubed_tpu.observability import export
from cubed_tpu.runtime.executors.distributed import DistributedDagExecutor
from cubed_tpu.service import ComputeService


@pytest.fixture()
def spec(tmp_path):
    return ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")


def test_service_over_live_fleet_with_tenant_metrics(spec):
    an = np.arange(144, dtype=np.float64).reshape(12, 12)

    def build(k):
        a = ct.from_array(an, chunks=(3, 3), spec=spec)
        return ct.map_blocks(
            lambda x, _k=k: x + _k, a, dtype=np.float64
        )

    export.shutdown()
    rt = export.ensure_started(0)  # ephemeral port
    ex = DistributedDagExecutor(n_local_workers=2)
    try:
        ex._ensure_fleet()
        with ComputeService(
            executor=ex, tenants={"gold": 2.0, "free": 1.0},
            max_concurrent=2, plan_cache=False, result_cache=False,
        ) as svc:
            handles = []
            for i in range(3):
                handles.append(
                    (svc.submit(build(float(i)), tenant="gold"), float(i))
                )
                handles.append(
                    (
                        svc.submit(build(100.0 + i), tenant="free"),
                        100.0 + i,
                    )
                )
            for h, k in handles:
                np.testing.assert_array_equal(h.result(300), an + k)

            # scrape the live endpoints DURING the service's lifetime
            rt.sampler.sample_once()
            base = f"http://127.0.0.1:{rt.port}"
            with urlopen(f"{base}/metrics", timeout=10) as resp:
                text = resp.read().decode()
            assert 'tenant_queued{tenant="gold"}' in text
            assert 'tenant_completed{tenant="free"}' in text
            # per-tenant COST accounting scraped live: the computes above
            # really consumed fleet task-seconds, attributed per tenant
            cost_lines = [
                line for line in text.splitlines()
                if line.startswith(
                    'cubed_tpu_tenant_cost_task_seconds{tenant="gold"}'
                )
            ]
            assert cost_lines, "tenant_cost_task_seconds{tenant=} missing"
            assert float(cost_lines[0].rsplit(" ", 1)[1]) > 0
            accepted = [
                float(line.rsplit(" ", 1)[1])
                for line in text.splitlines()
                if line.startswith("cubed_tpu_service_requests_accepted ")
            ]
            # the registry is process-global: at least THIS service's 6
            assert accepted and accepted[0] >= 6
            with urlopen(f"{base}/snapshot.json", timeout=10) as resp:
                snap = json.loads(resp.read().decode())
            tenants = (snap.get("service") or {}).get("tenants") or {}
            assert set(tenants) == {"gold", "free"}
            assert tenants["gold"]["completed"] == 3
            assert tenants["free"]["completed"] == 3
            assert tenants["gold"]["weight"] == 2.0
            # cost rows ride /snapshot.json: both tenants consumed real
            # fleet task-seconds and wrote their output arrays
            for tenant in ("gold", "free"):
                cost = tenants[tenant].get("cost") or {}
                assert cost.get("task_seconds", 0) > 0
                assert cost.get("bytes_written", 0) >= an.nbytes
            # ...and the top dashboard renders them as the COST panel
            from cubed_tpu import top

            frame = top.render(snap)
            assert "COST" in frame and "TASK-SEC" in frame
            assert "gold" in frame and "free" in frame
            # the fleet really ran these: live workers visible
            assert (snap.get("fleet") or {}).get("workers_live", 0) >= 1
    finally:
        try:
            ex.close()
        finally:
            export.shutdown()
