"""Append one tunnel-probe attempt to benchmarks/TUNNEL_LOG.jsonl.

Runs the canonical liveness check (a tiny jitted reduction with a scalar
fetch, since block_until_ready does not block through the tunnel — see
benchmarks/BENCH_PROFILE.md) in a subprocess under a hard timeout, and
records timestamp + outcome so "tunnel dead all round" is auditable
evidence rather than assertion (VERDICT r4 item #1).
"""
import json, os, subprocess, sys, time

LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "TUNNEL_LOG.jsonl")
SNIPPET = (
    "import jax, jax.numpy as jnp;"
    " print(float(jax.jit(lambda: jnp.sum(jnp.ones((128,128))))()))"
)

def probe(timeout=90):
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-c", SNIPPET],
            capture_output=True, text=True, timeout=timeout,
        )
        elapsed = round(time.time() - t0, 1)
        ok = r.returncode == 0 and "16384" in r.stdout
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "outcome": "alive" if ok else "error",
            "elapsed_s": elapsed,
            "returncode": r.returncode,
        }
        if not ok:
            entry["stderr_tail"] = r.stderr.strip()[-300:]
    except subprocess.TimeoutExpired:
        entry = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "outcome": "timeout",
            "elapsed_s": round(time.time() - t0, 1),
            "timeout_s": timeout,
        }
    with open(LOG, "a") as f:
        f.write(json.dumps(entry) + "\n")
    print(json.dumps(entry))
    return entry["outcome"] == "alive"

if __name__ == "__main__":
    alive = probe(int(sys.argv[1]) if len(sys.argv) > 1 else 90)
    sys.exit(0 if alive else 1)
