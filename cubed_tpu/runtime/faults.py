"""Deterministic, seedable fault injection — off by default, on everywhere.

Chaos testing the runtime needs failures that are (a) *representative* —
storage read/write errors, task crashes, stragglers, worker loss — and
(b) *reproducible*, so a failing chaos run replays. Decisions here are
pure functions of ``(seed, site, key, nth-occurrence-in-this-process)``
hashed through SHA-256, not draws from a shared RNG stream: the same
chunk's first write attempt fails (or not) identically in every process
that tries it, and a retry in the *same* process rolls a fresh decision —
so an injected fault behaves transiently, which is exactly the class of
failure the retry machinery exists for. The honest caveat: occurrence
counters are per-process, so a retry that lands in a *different* process
re-rolls that process's occurrence 0 and repeats the original decision;
counters still advance wherever attempts land, so retries converge, but
exact bit-for-bit replay holds only within one process — multi-process
chaos runs are deterministic per (process, occurrence), not per global
attempt order. Size retry counts accordingly (the chaos suite uses
``retries=6`` against ~10-20% rates).

Activation (everything defaults to off):

- ``activate(FaultConfig(...))`` / ``deactivate()`` — programmatic,
  process-local.
- ``Spec(fault_injection={...})`` — ``Plan.execute`` activates for the
  duration of that compute (via ``scoped``).
- env ``CUBED_TPU_FAULTS='{"seed": 42, "storage_write_failure_rate": 0.1}'``
  — a JSON ``FaultConfig``; this is how injection crosses process
  boundaries: multiprocess pool workers and distributed fleet workers
  inherit the environment, so one env var arms the whole fleet.

Injection sites (each counted in the metrics registry under
``faults_injected`` plus a per-site counter):

- storage chunk reads/writes (``storage/store.py``) — raises
  ``FaultInjectedIOError`` (an ``OSError``: classified transient). Only
  fires inside a task scope, so plan-construction metadata IO and
  client-side result fetches are never poisoned — the same places real
  task-level retry protection exists. A failed local write can first
  litter a partial ``.tmp`` file (``storage_write_leaves_tmp``), modelling
  a task killed mid-write. With ``storage_corrupt_rate`` a chunk write can
  instead *succeed with wrong bytes* — a seeded bit-flip or truncation —
  which only the checksum layer (``storage/integrity.py``) can catch.
- task bodies (``runtime/utils.execute_with_stats``) — raises
  ``FaultInjectedTaskError`` (transient), sleeps ``straggler_delay_s``
  (what speculative backups exist for), or hands the memory guard a
  synthetic ``task_mem_spike_bytes`` allocation (``task_mem_spike_rate``)
  so chaos tests exercise the RESOURCE/step-down path deterministically.
- the distributed worker loop (``runtime/distributed.run_worker``) — a
  named worker hard-exits (``os._exit``) or hangs after its nth task,
  modelling OOM-kills and wedged hosts.
- the control plane's framing layer (``runtime/distributed._WorkerLink``) —
  seeded per-frame message drop / duplication / delay / connection reset
  on the worker's side of the coordinator socket (worker tx covers
  worker→coordinator traffic, worker rx covers coordinator→worker), plus a
  timed **one-way partition** of a named worker: once its executed-task
  count reaches ``partition_after_tasks``, frames in
  ``partition_direction`` vanish for ``partition_duration_s`` — including
  reconnect attempts, which a real partition also blackholes. This is what
  the reconnect handshake / lease machinery is chaos-tested against.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from dataclasses import dataclass, field, fields
from typing import Optional

from ..observability.accounting import current_scope
from ..observability.metrics import get_registry

#: env var carrying a JSON FaultConfig into every child process
FAULTS_ENV_VAR = "CUBED_TPU_FAULTS"


class FaultInjectedError(Exception):
    """Base for injected faults (never raised itself)."""


class FaultInjectedIOError(FaultInjectedError, OSError):
    """An injected storage failure — an OSError, classified transient."""


class FaultInjectedTaskError(FaultInjectedError, RuntimeError):
    """An injected task-body crash — classified transient."""


class FaultInjectedThrottleError(FaultInjectedIOError):
    """An injected store THROTTLE (the 429/503/"SlowDown" shape):
    classified ``THROTTLE`` by the resilience layer, absorbed by the
    per-store health breaker's paced in-place retries when it is on."""


@dataclass(frozen=True)
class FaultConfig:
    """What to break, how often. All rates are probabilities in [0, 1]."""

    seed: int = 0
    #: chunk read/write failure probability (inside task scopes only)
    storage_read_failure_rate: float = 0.0
    storage_write_failure_rate: float = 0.0
    #: probability a chunk read/write is THROTTLED (429/503/SlowDown
    #: shape) — the seeded store-brownout knob; decided per occurrence, so
    #: a paced retry rolls fresh (modelling a store that answers once the
    #: request rate drops)
    storage_throttle_rate: float = 0.0
    #: a failed local write first leaves a partial .tmp file behind
    storage_write_leaves_tmp: bool = True
    #: probability a chunk write's bytes are silently corrupted in flight
    #: (the write "succeeds"): seeded per-chunk choice between a single
    #: bit-flip and a truncation to half length — the two shapes of real
    #: corruption the checksum layer must catch
    storage_corrupt_rate: float = 0.0
    #: task body raises before running
    task_failure_rate: float = 0.0
    #: task body sleeps straggler_delay_s before running
    straggler_rate: float = 0.0
    straggler_delay_s: float = 0.25
    #: probability a task "allocates" a synthetic memory spike of
    #: task_mem_spike_bytes: the memory guard (runtime/memory.py) adds the
    #: injected bytes to the task's measured peak, so chaos tests prove
    #: observe/enforce behavior deterministically without real allocations
    #: (which could genuinely OOM the test host)
    task_mem_spike_rate: float = 0.0
    task_mem_spike_bytes: int = 0
    #: distributed workers (by --name) that hard-exit / hang when their
    #: per-process executed-task count reaches worker_*_after_tasks (>=1)
    worker_crash_names: tuple = field(default_factory=tuple)
    worker_crash_after_tasks: int = 0
    worker_hang_names: tuple = field(default_factory=tuple)
    worker_hang_after_tasks: int = 0
    worker_hang_s: float = 3600.0
    #: probability a fleet worker is SPOT-PREEMPTED: decided once per
    #: worker name (seeded, so ~rate of the fleet is hit deterministically),
    #: fired when that worker's executed-task count reaches
    #: worker_preempt_after_tasks. The worker SIGTERMs itself — exercising
    #: the real spot path: preemption notice (preempt_notice_s) -> graceful
    #: drain -> hard kill at the end of the notice window
    worker_preempt_rate: float = 0.0
    worker_preempt_after_tasks: int = 2
    preempt_notice_s: float = 1.0
    #: POISON-TASK faults (the overload/quarantine chaos shape): a task
    #: whose chunk key rolls under task_fatal_rate — or is listed in
    #: task_fatal_chunk_keys — hard-kills its WORKER (os._exit 137,
    #: modelling a kernel OOM-kill or segfault pinned to one poison
    #: input). Deterministic PER CHUNK KEY with a fixed occurrence-0 roll:
    #: every retry/requeue of the same chunk kills its next host too, so
    #: only the quarantine path (PoisonTaskError after K worker-fatal
    #: attempts) ever ends it. Fleet-only: fires in run_worker, never in
    #: thread/process executors (it would kill the client process)
    task_fatal_rate: float = 0.0
    task_fatal_chunk_keys: tuple = field(default_factory=tuple)
    #: control-plane message faults, decided per frame at the worker's
    #: framing layer ("tx" = worker→coordinator, "rx" = coordinator→worker):
    #: a dropped frame silently vanishes (the reconnect/outbox/lease
    #: machinery must absorb it), a duplicated one is delivered twice (the
    #: seq/task-id dedup must ignore the copy), a delayed one sleeps
    #: net_msg_delay_s in the framing path, and a reset closes the socket
    #: mid-conversation (the worker must reconnect and replay)
    net_msg_drop_rate: float = 0.0
    net_msg_dup_rate: float = 0.0
    net_msg_delay_rate: float = 0.0
    net_msg_delay_s: float = 0.05
    net_reset_rate: float = 0.0
    #: one-way partition of named fleet workers: once such a worker's
    #: executed-task count reaches partition_after_tasks (>=1), frames in
    #: partition_direction ("tx" | "rx" | "both") stop being delivered for
    #: partition_duration_s — reconnect attempts included, exactly like a
    #: real network partition. In-flight tasks keep running; the protocol
    #: must carry their results across the gap (outbox replay) while the
    #: coordinator's lease keeps ownership from being requeued
    partition_worker_names: tuple = field(default_factory=tuple)
    partition_after_tasks: int = 0
    partition_duration_s: float = 2.0
    partition_direction: str = "tx"
    #: peer-to-peer chunk-fetch faults (runtime/transfer.py), decided per
    #: fetch on the READING worker: "drop" makes the reply vanish (store
    #: fallback, like a timeout), "delay" sleeps peer_delay_s in the fetch
    #: path, "corrupt" flips a bit in the fetched bytes so the CRC verify
    #: against the authoritative manifest must catch it. peer_reset_rate
    #: fires on the SERVING worker: the connection is closed mid-
    #: conversation, modelling a peer dying mid-fetch. Every one of these
    #: must resolve to a transparent store fallback — never a task failure
    peer_drop_rate: float = 0.0
    peer_delay_rate: float = 0.0
    peer_delay_s: float = 0.05
    peer_corrupt_rate: float = 0.0
    peer_reset_rate: float = 0.0
    #: coordinator-side crash knobs (live-failover chaos): the coordinator
    #: PROCESS hard-exits (137) once its per-process count of real task
    #: dispatches reaches the threshold (>=1, one-shot). The takeover
    #: variant fires only in a SUCCESSOR (epoch > 0) — killing the control
    #: plane again mid-takeover, the double-failure a second successor
    #: must absorb
    coordinator_crash_after_dispatches: int = 0
    coordinator_takeover_crash_after_dispatches: int = 0

    @classmethod
    def from_dict(cls, d: dict) -> "FaultConfig":
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown FaultConfig fields {sorted(unknown)}; "
                f"known: {sorted(known)}"
            )
        d = dict(d)
        for k in (
            "worker_crash_names", "worker_hang_names",
            "partition_worker_names", "task_fatal_chunk_keys",
        ):
            if k in d:
                d[k] = tuple(d[k])
        return cls(**d)

    def to_env_json(self) -> str:
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            out[f.name] = list(v) if isinstance(v, tuple) else v
        return json.dumps(out)

    @property
    def any_enabled(self) -> bool:
        return bool(
            self.storage_read_failure_rate
            or self.storage_write_failure_rate
            or self.storage_throttle_rate
            or self.storage_corrupt_rate
            or self.task_failure_rate
            or self.straggler_rate
            or (self.task_mem_spike_rate and self.task_mem_spike_bytes)
            or (self.worker_crash_names and self.worker_crash_after_tasks)
            or (self.worker_hang_names and self.worker_hang_after_tasks)
            or (self.worker_preempt_rate and self.worker_preempt_after_tasks)
            or self.task_fatal_rate
            or self.task_fatal_chunk_keys
            or self.net_msg_drop_rate
            or self.net_msg_dup_rate
            or self.net_msg_delay_rate
            or self.net_reset_rate
            or (self.partition_worker_names and self.partition_after_tasks)
            or self.peer_drop_rate
            or self.peer_delay_rate
            or self.peer_corrupt_rate
            or self.peer_reset_rate
            or self.coordinator_crash_after_dispatches
            or self.coordinator_takeover_crash_after_dispatches
        )


class FaultInjector:
    """Seeded decision engine; one instance per process while active."""

    def __init__(self, config: FaultConfig):
        self.config = config
        self._lock = threading.Lock()
        #: (site, key) -> occurrence count; the count is part of the hash
        #: input, so a retry of the same operation rolls a fresh decision
        self._counts: dict = {}
        #: worker name -> monotonic deadline of its active one-way
        #: partition (armed by worker_task_tick, consulted per frame)
        self._partition_until: dict = {}

    # -- the decision function ------------------------------------------

    def _roll(self, site: str, key: str) -> float:
        with self._lock:
            n = self._counts.get((site, key), 0)
            self._counts[(site, key)] = n + 1
        digest = hashlib.sha256(
            f"{self.config.seed}:{site}:{key}:{n}".encode()
        ).digest()
        return int.from_bytes(digest[:8], "big") / 2**64

    def _hit(self, site: str, key: str, rate: float) -> bool:
        if rate <= 0.0:
            return False
        if self._roll(site, key) >= rate:
            return False
        self._count_injection(site, key=key)
        return True

    @staticmethod
    def _count_injection(site: str, **context) -> None:
        """One injected fault: the conservation-law counters (total +
        per-site, incremented together — the invariant auditor checks
        they stay equal) plus a decision-ring record, so a diagnose
        bundle's timeline names what was injected and when."""
        from ..observability.collect import record_decision

        reg = get_registry()
        reg.counter("faults_injected").inc()
        reg.counter(f"faults_injected_{site}").inc()
        record_decision("fault_injected", site=site, **context)

    # -- storage --------------------------------------------------------

    def storage_read_fault(self, key: str) -> bool:
        """True -> the caller should raise FaultInjectedIOError. Only fires
        inside a task scope (see module docstring)."""
        if current_scope() is None:
            return False
        return self._hit("storage_read", key, self.config.storage_read_failure_rate)

    def storage_write_fault(self, key: str) -> bool:
        if current_scope() is None:
            return False
        return self._hit("storage_write", key, self.config.storage_write_failure_rate)

    def storage_throttle_fault(self, key: str) -> bool:
        """True -> the caller should raise FaultInjectedThrottleError (a
        seeded store brownout). Task-scope-only like the other storage
        sites, and CHUNK files only (digit-dotted names, like the
        corruption knob): the brownout being modelled is chunk-IO
        request pressure, and chunk IO is where the breaker's paced
        in-place retries exist — throttling metadata/manifest IO would
        measure unpaced side doors, not the breaker. Per-occurrence
        rolls mean a paced retry usually succeeds — exactly how a real
        throttling store behaves once the request rate drops."""
        if self.config.storage_throttle_rate <= 0.0:
            return False
        if current_scope() is None:
            return False
        name = key.rsplit("/", 1)[-1]
        if not all(p.lstrip("-").isdigit() for p in name.split(".")):
            return False
        return self._hit(
            "storage_throttle", key, self.config.storage_throttle_rate
        )

    def storage_corrupt_fault(self, key: str, data: bytes) -> Optional[bytes]:
        """Corrupted bytes for this chunk write, or None to write faithfully.

        The corruption itself is a pure function of ``(seed, key)`` — a
        single bit-flip at a seeded position, or truncation to half length —
        so a replayed chaos run corrupts identically; *whether* a given
        write is corrupted rolls per occurrence like every other site."""
        if not data or current_scope() is None:
            return None
        # corruption targets CHUNK files only (digit-dotted names): rotting
        # .zarray/manifest sidecars models a different failure (covered by
        # the metadata-tolerance paths), and would turn every subsequent
        # open into a metadata error instead of exercising checksums
        name = key.rsplit("/", 1)[-1]
        if not all(p.lstrip("-").isdigit() for p in name.split(".")):
            return None
        if not self._hit("storage_corrupt", key, self.config.storage_corrupt_rate):
            return None
        digest = hashlib.sha256(
            f"{self.config.seed}:corrupt:{key}".encode()
        ).digest()
        if digest[0] % 2 == 0:
            pos = int.from_bytes(digest[1:5], "big") % len(data)
            out = bytearray(data)
            out[pos] ^= 1 << (digest[5] % 8)
            return bytes(out)
        return data[: len(data) // 2]

    # -- task bodies ----------------------------------------------------

    def task_fault(self, key: str) -> None:
        """Raise an injected task failure and/or sleep a straggler delay."""
        if self._hit("straggler", key, self.config.straggler_rate):
            import time

            time.sleep(self.config.straggler_delay_s)
        if self._hit("task", key, self.config.task_failure_rate):
            raise FaultInjectedTaskError(
                f"injected task failure (seed={self.config.seed}, key={key!r})"
            )

    def task_fatal(self, chunk_key: str) -> bool:
        """True -> this task's worker must hard-exit (fleet-only call
        site: ``run_worker``, which ``os._exit(137)``s before executing).

        Unlike every other site this decision does NOT advance an
        occurrence counter: the roll is a pure function of
        ``(seed, chunk_key)``, so the same poison chunk kills its host on
        EVERY attempt — requeues reroute it to a fresh worker and kill
        that one too, which is exactly the shape the poison-request
        quarantine must end."""
        cfg = self.config
        if not (cfg.task_fatal_rate or cfg.task_fatal_chunk_keys):
            return False
        hit = str(chunk_key) in cfg.task_fatal_chunk_keys
        if not hit and cfg.task_fatal_rate > 0.0:
            digest = hashlib.sha256(
                f"{cfg.seed}:task_fatal:{chunk_key}:0".encode()
            ).digest()
            hit = (
                int.from_bytes(digest[:8], "big") / 2**64
                < cfg.task_fatal_rate
            )
        if hit:
            self._count_injection("task_fatal", key=str(chunk_key)[:120])
        return hit

    def task_mem_spike(self, key: str) -> int:
        """Synthetic memory-spike bytes for this task attempt (0 = none).

        The guard adds these to the task's measured peak; a retry in the
        same process rolls a fresh decision, so a spiked task usually
        passes on re-run — modelling pressure that recedes once
        concurrency steps down (a rate of 1.0 models a task that is
        genuinely over budget and must abort actionably)."""
        cfg = self.config
        if not (cfg.task_mem_spike_rate and cfg.task_mem_spike_bytes):
            return 0
        if self._hit("task_mem_spike", key, cfg.task_mem_spike_rate):
            return int(cfg.task_mem_spike_bytes)
        return 0

    # -- control plane (coordinator <-> worker framing) -----------------

    def net_fault(self, direction: str, worker_name: str,
                  msg_type: Optional[str]) -> Optional[str]:
        """One seeded decision for a control-plane frame: ``"drop"``,
        ``"reset"``, ``"dup"``, ``"delay"``, or None (deliver faithfully).
        ``direction`` is the worker's view ("tx" = worker→coordinator).
        At most one fault per frame, evaluated in severity order."""
        cfg = self.config
        if not (
            cfg.net_msg_drop_rate
            or cfg.net_msg_dup_rate
            or cfg.net_msg_delay_rate
            or cfg.net_reset_rate
        ):
            return None
        key = f"{worker_name}:{direction}:{msg_type}"
        if self._hit(f"net_{direction}_drop", key, cfg.net_msg_drop_rate):
            return "drop"
        if self._hit(f"net_{direction}_reset", key, cfg.net_reset_rate):
            return "reset"
        if self._hit(f"net_{direction}_dup", key, cfg.net_msg_dup_rate):
            return "dup"
        if self._hit(f"net_{direction}_delay", key, cfg.net_msg_delay_rate):
            return "delay"
        return None

    def peer_fetch_fault(self, key: str) -> Optional[str]:
        """One seeded decision for a peer chunk fetch on the reading side:
        ``"drop"`` (reply vanishes → store fallback), ``"corrupt"`` (a bit
        flips in the fetched bytes — the CRC verify must catch it), or
        ``"delay"`` (sleep ``peer_delay_s`` in the fetch path); None =
        fetch faithfully. At most one fault per fetch, severity order."""
        cfg = self.config
        if not (
            cfg.peer_drop_rate or cfg.peer_corrupt_rate or cfg.peer_delay_rate
        ):
            return None
        if self._hit("peer_drop", key, cfg.peer_drop_rate):
            return "drop"
        if self._hit("peer_corrupt", key, cfg.peer_corrupt_rate):
            return "corrupt"
        if self._hit("peer_delay", key, cfg.peer_delay_rate):
            return "delay"
        return None

    def peer_serve_reset(self, key: str) -> bool:
        """True -> the SERVING worker closes the peer connection instead of
        answering this chunk_get — a peer dying mid-fetch, as seen by the
        reader (who must fall back to the store)."""
        return self._hit("peer_reset", key, self.config.peer_reset_rate)

    def partitioned(self, worker_name: str, direction: str) -> bool:
        """True while ``worker_name`` is inside its injected one-way
        partition window for frames flowing in ``direction``. A reconnect
        attempt must check both directions — a real partition blackholes
        the TCP handshake too."""
        cfg = self.config
        if not (cfg.partition_worker_names and cfg.partition_after_tasks):
            return False
        if worker_name not in cfg.partition_worker_names:
            return False
        with self._lock:
            until = self._partition_until.get(worker_name)
        if until is None:
            return False
        import time

        if time.monotonic() >= until:
            return False
        return cfg.partition_direction in ("both", direction)

    # -- distributed workers --------------------------------------------

    def worker_task_tick(self, worker_name: str) -> Optional[str]:
        """Called once per executed task on a fleet worker; returns
        ``"crash"``/``"hang"``/``"preempt"`` exactly when this worker's
        per-process task count reaches the configured threshold (one-shot
        per process). Preemption is decided by a seeded per-name roll
        rather than an explicit name list: at ``worker_preempt_rate=0.3``
        about 30% of the fleet — the SAME ~30% in every replay — gets a
        SIGTERM-then-hard-kill spot preemption mid-compute."""
        cfg = self.config
        if not (
            (cfg.worker_crash_names and cfg.worker_crash_after_tasks)
            or (cfg.worker_hang_names and cfg.worker_hang_after_tasks)
            or (cfg.worker_preempt_rate and cfg.worker_preempt_after_tasks)
            or (cfg.partition_worker_names and cfg.partition_after_tasks)
        ):
            return None
        with self._lock:
            n = self._counts.get(("worker_tick", worker_name), 0) + 1
            self._counts[("worker_tick", worker_name)] = n
        if (
            cfg.partition_worker_names
            and worker_name in cfg.partition_worker_names
            and n == cfg.partition_after_tasks
        ):
            # arm the one-way partition window; the task itself proceeds —
            # the point is that work completed DURING the partition must
            # reach the coordinator afterwards via the reconnect/replay path
            import time

            with self._lock:
                self._partition_until[worker_name] = (
                    time.monotonic() + cfg.partition_duration_s
                )
            self._count_injection("partition", worker=worker_name)
        if (
            worker_name in cfg.worker_crash_names
            and n == cfg.worker_crash_after_tasks
        ):
            self._count_injection("worker_crash", worker=worker_name)
            return "crash"
        if (
            worker_name in cfg.worker_hang_names
            and n == cfg.worker_hang_after_tasks
        ):
            self._count_injection("worker_hang", worker=worker_name)
            return "hang"
        if (
            cfg.worker_preempt_rate
            and n == cfg.worker_preempt_after_tasks
            # decided per NAME at occurrence 0 (no count consumed by other
            # ticks): deterministic per (seed, worker) — the fleet loses
            # the same ~rate fraction in every replay, and a replacement
            # worker (fresh name) rolls its own fate
            # _hit counts the injection (faults_injected +
            # faults_injected_worker_preempt) — unlike the name-list
            # branches above, nothing to count here
            and self._hit(
                "worker_preempt", worker_name, cfg.worker_preempt_rate
            )
        ):
            return "preempt"
        return None

    # -- coordinator (live-failover chaos) -------------------------------

    def coordinator_dispatch_tick(self, epoch: int) -> bool:
        """Called once per REAL task dispatch on the coordinator; True
        exactly when this process should hard-exit (one-shot per process,
        mirroring ``worker_task_tick``). ``coordinator_crash_after_dispatches``
        fires in any epoch; the ``_takeover_`` variant only in a successor
        (epoch > 0), modelling a second control-plane crash landing while
        the first takeover is still settling."""
        cfg = self.config
        n_any = cfg.coordinator_crash_after_dispatches
        n_tko = cfg.coordinator_takeover_crash_after_dispatches
        if not n_any and not (n_tko and epoch > 0):
            return False
        with self._lock:
            n = self._counts.get(("coordinator_tick", ""), 0) + 1
            self._counts[("coordinator_tick", "")] = n
        if (n_any and n == n_any) or (n_tko and epoch > 0 and n == n_tko):
            self._count_injection("coordinator_crash", epoch=epoch)
            return True
        return False


# ----------------------------------------------------------------------
# process-level activation
# ----------------------------------------------------------------------

_lock = threading.Lock()
_active: Optional[FaultInjector] = None
#: (raw env string, injector built from it) — env parsing is cached per
#: value so the per-IO fast path is a dict lookup + string compare
_env_cache: tuple = (None, None)


def _coerce(config) -> FaultConfig:
    if isinstance(config, FaultConfig):
        return config
    if isinstance(config, dict):
        return FaultConfig.from_dict(config)
    raise TypeError(f"expected FaultConfig or dict, got {type(config).__name__}")


def activate(config, export_env: bool = False) -> FaultInjector:
    """Arm fault injection in this process (and, with ``export_env``, in
    every child process spawned afterwards)."""
    global _active
    cfg = _coerce(config)
    inj = FaultInjector(cfg)
    with _lock:
        _active = inj
    if export_env:
        os.environ[FAULTS_ENV_VAR] = cfg.to_env_json()
    return inj


def deactivate() -> None:
    """Disarm, including any env-var activation exported by this process."""
    global _active, _env_cache
    with _lock:
        _active = None
        _env_cache = (None, None)
    os.environ.pop(FAULTS_ENV_VAR, None)


def get_injector() -> Optional[FaultInjector]:
    """The active injector, or None (the common, fast case).

    Programmatic activation wins; otherwise the env var is consulted so
    spawned workers self-arm. A malformed env value raises loudly — silent
    no-fault chaos runs would be worse than an error.
    """
    global _env_cache
    if _active is not None:
        return _active
    raw = os.environ.get(FAULTS_ENV_VAR)
    if not raw:
        return None
    cached_raw, cached_inj = _env_cache
    if raw == cached_raw:
        return cached_inj
    cfg = FaultConfig.from_dict(json.loads(raw))
    inj = FaultInjector(cfg) if cfg.any_enabled else None
    with _lock:
        _env_cache = (raw, inj)
    return inj


def wire_config() -> Optional[str]:
    """The client's current arming state, serialized for task messages
    (``None`` = unarmed). The distributed coordinator attaches this to
    every task so fleet workers mirror the client exactly — workers that
    joined before arming still inject, and disarming propagates instead of
    leaving stale spawn-time env state behind."""
    inj = get_injector()
    return inj.config.to_env_json() if inj is not None else None


#: (raw wire string, injector) — the worker-side mirror persists across
#: tasks with the same config so occurrence counters advance
_wire_cache: tuple = (None, None)


def arm_from_wire(raw: Optional[str]) -> Optional[FaultInjector]:
    """Fleet-worker side: adopt the arming state a task message carried.

    ``None`` disarms (the client says no injection — overriding any stale
    env the worker process was spawned with)."""
    global _active, _wire_cache
    if raw is None:
        with _lock:
            _active = None
        return None
    cached_raw, cached_inj = _wire_cache
    if raw != cached_raw:
        cfg = FaultConfig.from_dict(json.loads(raw))
        cached_inj = FaultInjector(cfg) if cfg.any_enabled else None
    with _lock:
        _wire_cache = (raw, cached_inj)
        _active = cached_inj
    return cached_inj


class scoped:
    """Context manager arming injection for the duration of a ``with``
    block (used by ``Plan.execute`` for ``Spec(fault_injection=...)``).
    ``None`` config is a no-op, so callers need no conditional.

    Arming is process-global for that duration — it must be: tasks run on
    arbitrary pool threads, so a thread-local injector would never fire.
    Consequently a compute running CONCURRENTLY in the same process during
    an armed block sees the same injector (the same known limitation the
    process-global metrics registry has — see ``Plan.execute``); chaos
    testing and concurrent production computes don't mix in one process."""

    def __init__(self, config=None, export_env: bool = False):
        self._config = config
        self._export_env = export_env

    def __enter__(self):
        if self._config is None:
            return None
        self._prev = _active
        self._prev_env = os.environ.get(FAULTS_ENV_VAR)
        return activate(self._config, export_env=self._export_env)

    def __exit__(self, *exc) -> None:
        if self._config is None:
            return
        global _active
        with _lock:
            _active = self._prev
        if self._export_env:
            if self._prev_env is None:
                os.environ.pop(FAULTS_ENV_VAR, None)
            else:
                os.environ[FAULTS_ENV_VAR] = self._prev_env
