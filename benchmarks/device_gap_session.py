"""Gap-first resumable TPU session: measure ONLY what is still missing.

``device_session.py`` ran when the tunnel first revived (2026-07-31
01:03Z) and captured device numbers for the two addsum configs before the
tunnel wedged mid-``bench.py`` (the same multi-GB-HBM wedge signature as
round 3 — see BENCH_PROFILE.md).  This script is the follow-up that a
probe cadence fires on every subsequent revival:

- reads ``benchmarks/DEVICE_R5.jsonl`` and computes the set of workloads
  that already have a REAL device number (from any prior session), so a
  revival only spends tunnel-life on gaps;
- orders the gaps by information value per HBM byte: the matmul/MXU
  configs (~130 MB/operand, never measured on device) first, the ~4 GB
  addsum_scaled last;
- smoke-probes before every phase (appending to TUNNEL_LOG.jsonl) and
  exits the moment the tunnel dies — already-recorded phases survive, the
  next revival resumes where this one stopped;
- after the framework configs, fills the raw-JAX lower bounds
  (``raw_jax_bound.py --configs`` gap subset) and the threefry A/B, then
  recomputes the MXU fraction-of-peak summary.

Usage: ``python benchmarks/device_gap_session.py`` (inherited device
env).  Exit 0 = nothing missing or all gaps filled; 1 = tunnel dead.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)
sys.path.insert(0, HERE)

OUT = os.path.join(HERE, "DEVICE_R5.jsonl")

import bench  # noqa: E402  (repo root on path)
from device_session import THREEFRY_AB, V5E_BF16_PEAK_GFLOPS, record  # noqa: E402
from tunnel_probe import probe  # noqa: E402


def _parse_json_lines(text: str) -> list:
    """Every parseable JSON line in ``text`` — a truncated trailing line
    (crash/OOM mid-print) is skipped, never fatal."""
    out = []
    for ln in text.strip().splitlines():
        if ln.startswith("{"):
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
    return out


def run_json_phase(phase: str, script: str, timeout: int,
                   args: tuple = (), summary_leg: str | None = None) -> None:
    """One measurement subprocess -> one recorded phase row; the shared
    run/parse/record shape for raw bounds, mxu_sat, and tsqr."""
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(HERE, script), *args],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ), cwd=REPO,
        )
        lines = _parse_json_lines(out.stdout)
        row: dict = {"rc": out.returncode,
                     "stderr": out.stderr[-300:] if out.returncode else ""}
        if summary_leg is not None:
            row["legs"] = lines
            row["summary"] = next(
                (l for l in lines if l.get("leg") == summary_leg), None)
        else:
            row["bounds"] = lines
        record(phase, row)
    except subprocess.TimeoutExpired:
        record(phase, {"error": "timeout", "script": script,
                       "args": list(args)})

#: gap priority: smallest HBM footprint x highest information first.
#: (metric names mirror bench.CONFIGS; addsum/addsum_scaled landed in the
#: 01:03Z session but stay listed so a fresh DEVICE_R5.jsonl still works.)
PRIORITY = [
    "matmul", "matmul_bf16", "elemwise", "reduce", "vorticity_f32",
    "vorticity", "addsum", "addsum_scaled",
]

METRIC = {w: m for w, m, _, _, _ in bench.CONFIGS}
WORK = {w: (work, unit) for w, _, work, unit, _ in bench.CONFIGS}


def have_device_numbers() -> tuple[set, set]:
    """(workloads, raw-bound configs) already measured on device."""
    done, raw_done = set(), set()
    metric_to_workload = {m: w for w, m in METRIC.items()}
    try:
        rows = [json.loads(ln) for ln in open(OUT)]
    except OSError:
        return done, raw_done
    for r in rows:
        if r.get("phase") == "bench":
            for m in r.get("metrics", []):
                w = metric_to_workload.get(m.get("metric"))
                if w is not None:  # exact name == real device number
                    done.add(w)
        elif r.get("phase") == "device" and "value" in r:
            # error rows ({"error": "phase failed"}) do NOT count: the gap
            # must be retried on the next revival
            done.add(r["workload"])
        elif r.get("phase") == "raw":
            for b in r.get("bounds", []):
                if b.get("platform") == "tpu" and "rate" in b:
                    raw_done.add(b["config"])
        elif r.get("phase") == "threefry" and "elapsed_s" in r:
            raw_done.add(f"threefry_{r['partitionable']}")
    return done, raw_done


def main() -> int:
    done, raw_done = have_device_numbers()
    gaps = [w for w in PRIORITY if w not in done]
    raw_gaps = [
        c for c in ("matmul", "matmul_bf16", "reduce", "elemwise",
                    "vorticity", "vorticity_f32", "addsum")
        if c not in raw_done
    ]
    threefry_gaps = [
        f for f in (True, False) if f"threefry_{f}" not in raw_done
    ]
    try:
        _rows = [json.loads(ln) for ln in open(OUT)]
    except OSError:
        _rows = []
    mxu_sat_pending = not any(
        r.get("phase") == "mxu_sat" and r.get("summary") for r in _rows
    )
    tsqr_pending = not any(
        r.get("phase") == "tsqr" and r.get("summary") for r in _rows
    )
    print(f"gaps={gaps} raw_gaps={raw_gaps} threefry={threefry_gaps} "
          f"mxu_sat_pending={mxu_sat_pending} tsqr_pending={tsqr_pending}",
          flush=True)
    if not (gaps or raw_gaps or threefry_gaps or mxu_sat_pending
            or tsqr_pending):
        return 0

    baselines = bench.get_baselines()

    for workload in gaps:
        if not probe(75):
            return 1
        bench._T0 = time.monotonic()  # fresh per-phase budget
        res = bench.measure_device(
            workload, 300 if workload.startswith(("vorticity", "addsum_s"))
            else 150,
        )
        if res is None:
            # phase died with a live probe before it: either a wedge mid-
            # phase or a phase bug; record and let the next probe decide
            record("device", {"workload": workload, "error": "phase failed"})
            continue
        work, unit = WORK[workload]
        base = baselines.get(bench.BASELINE_KEY.get(workload, workload))
        record("device", {
            "workload": workload,
            "metric": METRIC[workload],
            "value": round(work / max(res["elapsed"], 1e-9) / 1e9, 3),
            "unit": unit,
            "vs_baseline": (
                round(base["elapsed"] / max(res["elapsed"], 1e-9), 3)
                if base else None
            ),
            "elapsed_s": round(res["elapsed"], 4),
        })

    # one subprocess PER config: the 03:19Z session lost all 7 bounds when
    # a single shared 600 s budget hit one slow f64-emulation compile.
    # Fast-compiling configs go first so a wedge costs the least info.
    RAW_ORDER = ["matmul_bf16", "elemwise", "reduce", "addsum",
                 "vorticity_f32", "matmul", "vorticity"]
    # configs not in the hard-coded order sort last (alphabetically) instead
    # of killing the whole gap session with a ValueError from .index
    for cfg in sorted(
        raw_gaps,
        key=lambda c: (
            RAW_ORDER.index(c) if c in RAW_ORDER else len(RAW_ORDER), c
        ),
    ):
        if not probe(75):
            return 1
        run_json_phase("raw", "raw_jax_bound.py", 300,
                       args=("--configs", cfg))

    for flag in threefry_gaps:
        if not probe(60):
            return 1
        try:
            out = subprocess.run(
                [sys.executable, "-c", THREEFRY_AB.format(partitionable=flag)],
                capture_output=True, text=True, timeout=300,
                env=dict(os.environ), cwd=REPO,
            )
            if out.returncode == 0:
                record("threefry",
                       json.loads(out.stdout.strip().splitlines()[-1]))
            else:
                record("threefry", {"partitionable": flag,
                                    "error": out.stderr[-400:]})
        except subprocess.TimeoutExpired:
            record("threefry", {"partitionable": flag, "error": "timeout"})

    # MXU saturation probe (16384^2 bf16, 8.8 TFLOP — the size where the
    # MXU rather than the dispatch floor is the bottleneck). Keyed off the
    # same mxu_sat_pending predicate as the early-exit so a failed run
    # (summary=null row) is retried on the next revival.
    if mxu_sat_pending:
        if not probe(75):
            return 1
        run_json_phase("mxu_sat", "mxu_saturation.py", 480,
                       summary_leg="summary")

    # TSQR device throughput (out-of-core QR, beyond-reference) — after
    # every baseline-config gap, once
    if tsqr_pending:
        if not probe(75):
            return 1
        run_json_phase("tsqr", "tsqr_device.py", 480, summary_leg="summary")

    # MXU fraction-of-peak summary over EVERYTHING recorded so far
    try:
        done, _ = have_device_numbers()
        rows = [json.loads(ln) for ln in open(OUT)]
        raw_by = {}
        for r in rows:
            if r.get("phase") == "raw":
                for b in r.get("bounds", []):
                    if b.get("platform") == "tpu" and "rate" in b:
                        raw_by[b["config"]] = b
        fw_by = {}
        for r in rows:
            if r.get("phase") == "device" and "value" in r:
                fw_by[r["workload"]] = r["value"]
            elif r.get("phase") == "bench":
                for m in r.get("metrics", []):
                    for w, metric in METRIC.items():
                        if m.get("metric") == metric:
                            fw_by[w] = m["value"]
        tbl = {}
        for cfg in ("matmul", "matmul_bf16"):
            raw_rate = raw_by.get(cfg, {}).get("rate")
            fw = fw_by.get(cfg)
            tbl[cfg] = {
                "framework_gflops": fw,
                "raw_jax_gflops": raw_rate,
                "fw_over_raw": round(fw / raw_rate, 3) if fw and raw_rate else None,
                "fraction_of_bf16_peak": (
                    round(fw / V5E_BF16_PEAK_GFLOPS, 4) if fw else None
                ),
            }
        if any(v["framework_gflops"] for v in tbl.values()):
            record("mxu", tbl)
    except Exception as e:
        record("mxu", {"error": str(e)[:300]})
    return 0


if __name__ == "__main__":
    sys.exit(main())
