"""The lazy whole-operation DAG.

Nodes alternate between *op* nodes (carrying a PrimitiveOperation) and *array*
nodes (carrying a Zarr target). Data never flows through the graph — each op
reads chunks of input arrays from shared storage (or, under the TPU executor,
from HBM-resident buffers) and writes chunks of one output array.

Reference parity: cubed/core/plan.py (behavioral; clean-room).
"""

from __future__ import annotations

import inspect
import logging
import os
import shutil
import tempfile
import time
import uuid
from functools import lru_cache
from typing import Any, Callable, Optional, Sequence

import networkx as nx

from ..primitive.types import CubedPipeline, PrimitiveOperation
from ..runtime.pipeline import (
    ResumeState,
    already_computed,
    iter_op_nodes,
    pending_mappable,
)
from ..runtime.types import (
    ComputeEndEvent,
    ComputeStartEvent,
    callbacks_on,
)
from ..storage.zarr import LazyZarrArray
from ..utils import (  # noqa: F401  (gensym re-exported for plan builders)
    StackSummary,
    extract_stack_summaries,
    gensym,
    join_path,
    memory_repr,
)

logger = logging.getLogger(__name__)

#: unique run id for this client process; work_dir data lives under it.
#: Overridable via CUBED_TPU_CONTEXT_ID: a resumable deployment (resume=True
#: across client restarts, or resume_from_journal after a coordinator
#: crash) must pin it so the restarted client resolves intermediate-array
#: paths to the SAME store locations the crashed run wrote
CONTEXT_ID = (
    os.environ.get("CUBED_TPU_CONTEXT_ID") or f"cubed-{uuid.uuid4().hex[:10]}"
)


def new_temp_path(name: str, spec=None) -> str:
    """A unique storage path for an intermediate array in the work_dir."""
    work_dir = spec.work_dir if spec is not None and spec.work_dir else tempfile.gettempdir()
    context_dir = join_path(work_dir, CONTEXT_ID)
    return join_path(context_dir, f"{name}.zarr")


class Plan:
    """A deferred computation constructed as a DAG of whole-array operations."""

    def __init__(self, dag: nx.MultiDiGraph):
        self.dag = dag

    # -- construction ------------------------------------------------------

    @classmethod
    def _new(
        cls,
        name: str,
        op_name: str,
        target,
        primitive_op: Optional[PrimitiveOperation] = None,
        hidden: bool = False,
        *source_arrays,
    ) -> "Plan":
        """Create a new plan adding an op (and its output array — or arrays,
        when ``name``/``target`` are lists for a multi-output op) to the
        union of the source arrays' plans."""
        dag = arrays_to_dag(*source_arrays)

        frame = inspect.currentframe()
        # skip this frame and internal callers
        stack_summaries = extract_stack_summaries(frame.f_back if frame else None)

        if isinstance(name, (list, tuple)):
            # multi-output op: one op node feeding N array nodes
            op_node = gensym(f"op-{op_name}")
            dag.add_node(
                op_node,
                name=op_node,
                type="op",
                op_display_name=f"{op_name}\n" + "\n".join(name),
                op_name=op_name,
                primitive_op=primitive_op,
                pipeline=primitive_op.pipeline if primitive_op else None,
                hidden=hidden,
                stack_summaries=stack_summaries,
            )
            for n, t in zip(name, target):
                dag.add_node(n, name=n, type="array", target=t, hidden=hidden)
                dag.add_edge(op_node, n)
            for x in source_arrays:
                dag.add_edge(x.name, op_node)
            return Plan(dag)

        if primitive_op is None:
            # op with no computation (e.g. wrapping an existing zarr array)
            op_node = gensym(f"op-{op_name}")
            dag.add_node(
                op_node,
                name=op_node,
                type="op",
                op_display_name=f"{op_name}\n{name}",
                op_name=op_name,
                primitive_op=None,
                hidden=hidden,
                stack_summaries=stack_summaries,
            )
            dag.add_node(name, name=name, type="array", target=target, hidden=hidden)
            dag.add_edge(op_node, name)
            for x in source_arrays:
                dag.add_edge(x.name, op_node)
        else:
            op_node = gensym(f"op-{op_name}")
            dag.add_node(
                op_node,
                name=op_node,
                type="op",
                op_display_name=f"{op_name}\n{name}",
                op_name=op_name,
                primitive_op=primitive_op,
                pipeline=primitive_op.pipeline,
                hidden=hidden,
                stack_summaries=stack_summaries,
            )
            dag.add_node(name, name=name, type="array", target=target, hidden=hidden)
            dag.add_edge(op_node, name)
            for x in source_arrays:
                dag.add_edge(x.name, op_node)
        return Plan(dag)

    @classmethod
    def arrays_to_plan(cls, *arrays) -> "Plan":
        return Plan(arrays_to_dag(*arrays))

    # -- finalization ------------------------------------------------------

    def _finalize(
        self,
        optimize_graph: bool = True,
        optimize_function: Optional[Callable] = None,
        array_names: Optional[tuple] = None,
    ) -> "FinalizedPlan":
        dag = self.optimize(optimize_function, array_names).dag if optimize_graph else self.dag
        dag = dag.copy()
        dag = self.create_lazy_zarr_arrays(dag)
        return FinalizedPlan(nx.freeze(dag))

    def optimize(
        self,
        optimize_function: Optional[Callable] = None,
        array_names: Optional[tuple] = None,
    ) -> "Plan":
        from .optimization import multiple_inputs_optimize_dag

        if optimize_function is None:
            optimize_function = multiple_inputs_optimize_dag
        dag = optimize_function(self.dag.copy(), array_names=array_names)
        return Plan(dag)

    def create_lazy_zarr_arrays(self, dag: nx.MultiDiGraph) -> nx.MultiDiGraph:
        """Inject a single first op that writes metadata for every lazy target."""
        lazy = [
            (name, data["target"])
            for name, data in dag.nodes(data=True)
            if data.get("type") == "array" and isinstance(data.get("target"), LazyZarrArray)
        ]
        if not lazy:
            return dag
        op_node = "create-arrays"
        targets = [t for _, t in lazy]
        pipeline = CubedPipeline(
            create_zarr_array, op_node, targets, None
        )
        primitive_op = PrimitiveOperation(
            pipeline=pipeline,
            source_array_names=[],
            target_array=None,
            projected_mem=0,
            allowed_mem=0,
            reserved_mem=0,
            num_tasks=len(targets),
            fusable=False,
        )
        dag.add_node(
            op_node,
            name=op_node,
            type="op",
            op_display_name=f"{op_node}\n{len(targets)} arrays",
            op_name=op_node,
            primitive_op=primitive_op,
            pipeline=pipeline,
            hidden=False,
            stack_summaries=[],
        )
        # run before every other op (reference: edges to all pipeline nodes,
        # cubed/core/plan.py:136-176)
        for name, _ in list(iter_op_nodes(dag)):
            if name != op_node:
                dag.add_edge(op_node, name)
        return dag

    # -- execution ---------------------------------------------------------

    def execute(
        self,
        executor=None,
        callbacks: Optional[Sequence] = None,
        optimize_graph: bool = True,
        optimize_function: Optional[Callable] = None,
        resume: Optional[bool] = None,
        resume_from_journal: Optional[str] = None,
        array_names: Optional[tuple] = None,
        spec=None,
        finalized: Optional["FinalizedPlan"] = None,
        deadline_s: Optional[float] = None,
        cancellation=None,
        **kwargs,
    ) -> None:
        if executor is None:
            from ..runtime.executors.python import PythonDagExecutor

            executor = PythonDagExecutor()

        # end-to-end time bound (runtime/cancellation.py): deadline_s
        # mints a per-compute CancellationToken (or tightens one the
        # caller passed — the service threads its own through here so
        # RequestHandle.cancel() reaches RUNNING computes); the token is
        # checked by every dispatch loop, carried on distributed task
        # messages, and enforced cooperatively inside task bodies
        from ..runtime import cancellation as cancel_mod

        cancel_token = cancellation
        if deadline_s is not None:
            if cancel_token is None:
                cancel_token = cancel_mod.CancellationToken()
            cancel_token.set_deadline(deadline_s)
        if cancel_token is not None:
            kwargs["cancellation"] = cancel_token

        if resume_from_journal is not None:
            # coordinator-crash recovery: the journal's completed-task set
            # intersects the chunk-integrity resume scan (the executors
            # build the ResumeState from this), so only tasks that BOTH
            # verify on disk AND were journaled complete are skipped
            from ..runtime.journal import load_journal

            resume = True
            kwargs["journal"] = load_journal(resume_from_journal)

        if finalized is None:
            finalized = self._finalize(
                optimize_graph, optimize_function, array_names
            )
        # else: a pre-finalized plan (the service's structural plan cache)
        # skips optimization + lazy-array creation entirely; the caller is
        # responsible for the fingerprint match that makes this sound
        dag = finalized.dag

        # every compute carries an aggregator: it folds per-task stats
        # (completion counts, storage bytes measured where each task ran)
        # into the process metrics registry and builds the per-op summary
        from ..observability import logs
        from ..observability.callback import _ComputeAggregator
        from ..observability.collect import TraceCollector
        from ..observability.flightrecorder import (
            FLIGHT_RECORDER_ENV_VAR,
            FlightRecorder,
        )
        from ..observability.metrics import get_registry

        #: correlates this compute's trace, structured logs, flight bundle
        compute_id = f"c-{uuid.uuid4().hex[:10]}"
        aggregator = _ComputeAggregator()
        all_callbacks = list(callbacks) if callbacks else []
        all_callbacks.append(aggregator)
        journal_path = getattr(spec, "journal", None)
        if journal_path:
            # durable compute journal (runtime/journal.py): compute
            # metadata, per-task dispatch/completion, and the decision ring
            # land in an append-only fsync'd JSONL beside the store — what
            # resume_from_journal rebuilds coordinator state from after a
            # client crash
            from ..runtime.journal import JournalCallback

            all_callbacks.append(JournalCallback(journal_path))
        # live telemetry (observability/export.py): env > Spec > off. When
        # armed, the process-global sampler/HTTP endpoint starts (or keeps
        # running — it outlives computes, like any scrape target) and this
        # compute reports live progress (tasks done/total -> task rate/ETA
        # on the /snapshot.json feed and `python -m cubed_tpu.top`)
        from ..observability import export as telemetry_export
        from ..observability.timeseries import ComputeProgressCallback

        if telemetry_export.maybe_start(spec) is not None:
            all_callbacks.append(ComputeProgressCallback())
        recorder_dir = os.environ.get(FLIGHT_RECORDER_ENV_VAR)
        if recorder_dir and not any(
            isinstance(cb, TraceCollector) for cb in all_callbacks
        ):
            # operator-armed post-mortems: every compute records, bundles
            # are only written on failure. Suppressed when the caller
            # already attached ANY collector (FlightRecorder included) —
            # two collectors would double-count the spans_dropped/
            # stragglers_detected counters and duplicate scheduler-lane
            # straggler instants; a caller who wants both a loose trace
            # AND bundles should attach one FlightRecorder and export from
            # it (observability/flightrecorder.py)
            all_callbacks.append(FlightRecorder(bundle_dir=recorder_dir))
        # durable run-history archive (observability/runhistory.py): a
        # compact record per compute — fingerprint, wall clock, analyze()
        # buckets, outcome — appended at completion. The bucket
        # decomposition needs the merged task spans, so arming run_history
        # attaches a TraceCollector when the caller (or the flight
        # recorder above) didn't already bring one; an existing collector
        # is reused, never doubled (same single-collector rule as the
        # operator flight recorder)
        run_history_dir = getattr(spec, "run_history", None)
        run_collector = None
        if run_history_dir:
            run_collector = next(
                (
                    cb for cb in all_callbacks
                    if isinstance(cb, TraceCollector)
                ),
                None,
            )
            if run_collector is None:
                run_collector = TraceCollector()
                all_callbacks.append(run_collector)
        run_started_at = time.monotonic()
        metrics_before = get_registry().snapshot()

        callbacks_on(
            all_callbacks, "on_compute_start",
            ComputeStartEvent(dag, resume, compute_id=compute_id),
        )
        if cancel_token is not None:
            # registered under the compute id for the compute's duration:
            # the coordinator reads it per task message, in-process chunk
            # IO checks it between reads/writes (unregistered in finally)
            cancel_mod.register_compute(compute_id, cancel_token)
        compute_error: Optional[BaseException] = None
        try:
            # Spec-level chaos config arms fault injection for this
            # compute's duration (exported to the env so spawned workers
            # inherit it); a None config makes this a no-op. Arming is
            # process-global while active — same caveat as the metrics
            # registry below: concurrent computes in one process share it
            from ..observability import accounting, dispatchprofile
            from ..runtime import faults, memory
            from ..storage import integrity

            with logs.compute_scope(
                # log-correlation context: every client/pool/fleet log line
                # emitted under this compute carries its id (the env export
                # is how spawned pool workers inherit it; fleet workers get
                # it from each task message)
                compute_id, export_env=True
            ), accounting.spans_scoped(
                # span recording is pay-for-what-you-watch: armed only while
                # a collector is attached to merge the spans (exported to
                # the env for pool spawns; fleet task messages mirror it).
                # None leaves an operator's CUBED_TPU_TASK_SPANS untouched
                True if any(
                    isinstance(cb, TraceCollector) for cb in all_callbacks
                ) else None,
                export_env=True,
            ), faults.scoped(
                getattr(spec, "fault_injection", None), export_env=True
            ), integrity.scoped(
                # Spec-level integrity mode, armed (and exported to the env,
                # so spawned pool/fleet workers inherit it) for this
                # compute's duration; None defers to env/default
                getattr(spec, "integrity", None), export_env=True
            ), memory.scoped(
                # runtime memory guard: the Spec's mode (default observe)
                # plus its allowed_mem, armed for the compute and exported
                # so pool workers measure against the same budget; an
                # operator CUBED_TPU_MEMORY_GUARD env var wins untouched.
                # No spec at all -> no budget to judge against -> no guard
                getattr(spec, "memory_guard", None),
                allowed_mem=getattr(spec, "allowed_mem", None),
                export_env=True,
            ), dispatchprofile.profile_scoped(
                # coordinator self-profiling (env > Spec > off): a true
                # no-op unless armed; the finished profile registers under
                # the compute id for bundles/diagnose/the trace lane
                spec, compute_id,
            ):
                executor.execute_dag(
                    dag,
                    callbacks=all_callbacks,
                    array_names=array_names,
                    resume=resume,
                    spec=spec,
                    **kwargs,
                )
        except BaseException as e:
            # captured for the end event (the flight recorder keys its
            # bundle assembly off it), then re-raised untouched
            compute_error = e
            raise
        finally:
            # on_compute_end fires even when the compute FAILS: that is when
            # a trace of the partial run (TracingCallback's trace.json) and
            # the stats gathered so far matter most. Stats assembly is
            # guarded so it can never mask the executor's own exception.
            #
            # executor_stats: the executor's own counters, overlaid with
            # this compute's metrics delta (task/retry/byte counters) and
            # the per-op wall-clock + projected-vs-measured summary.
            # Overlay order is deliberate: where an executor's lifetime
            # counter shares a name with a registry metric (a persistent
            # distributed fleet's task_timeouts/workers_lost), the
            # PER-COMPUTE windowed value wins — lifetime totals remain
            # available on executor.stats itself.
            #
            # Known limitation: the registry is process-global, so computes
            # running CONCURRENTLY in one process see each other's counter
            # increments in their windows (docs/observability.md). The
            # event-derived numbers (per_op, tasks/bytes via the
            # aggregator's own fold) are exact per compute either way.
            if cancel_token is not None:
                cancel_mod.unregister_compute(compute_id)
            stats: dict = {}
            try:
                executor_own = getattr(executor, "stats", None)
                if executor_own:
                    stats.update(dict(executor_own))
                stats.update(get_registry().snapshot_delta(metrics_before))
                stats.update(aggregator.summary())
            except Exception:
                logger.exception(
                    "failed to assemble executor_stats; reporting partial "
                    "stats (%d keys)", len(stats)
                )
            callbacks_on(
                all_callbacks,
                "on_compute_end",
                ComputeEndEvent(
                    dag,
                    executor_stats=stats or None,
                    compute_id=compute_id,
                    error=compute_error,
                ),
            )
            if run_history_dir:
                # after on_compute_end so the collector's trace is sealed;
                # the append itself never raises (archive discipline)
                from ..observability import runhistory

                # fingerprint the PRE-finalize dag: finalized lazy targets
                # carry per-build store paths that defeat the structural
                # masking, and the service fingerprints pre-finalize too —
                # archive records and plan-cache keys must agree
                runhistory.record_compute(
                    run_history_dir,
                    compute_id=compute_id,
                    dag=self.dag,
                    error=compute_error,
                    stats=stats,
                    collector=run_collector,
                    wall_clock_s=time.monotonic() - run_started_at,
                )

    # -- introspection -----------------------------------------------------

    def num_tasks(self, optimize_graph=True, optimize_function=None, resume=None) -> int:
        finalized = self._finalize(optimize_graph, optimize_function)
        return finalized.num_tasks(resume=resume)

    def num_arrays(self, optimize_graph=True, optimize_function=None) -> int:
        finalized = self._finalize(optimize_graph, optimize_function)
        return finalized.num_arrays()

    def max_projected_mem(self, optimize_graph=True, optimize_function=None, resume=None) -> int:
        finalized = self._finalize(optimize_graph, optimize_function)
        return finalized.max_projected_mem(resume=resume)

    def total_nbytes_written(self, optimize_graph=True, optimize_function=None) -> int:
        finalized = self._finalize(optimize_graph, optimize_function)
        return finalized.total_nbytes_written()

    def explain(
        self,
        spec=None,
        optimize_graph=True,
        optimize_function=None,
        array_names=None,
    ):
        """EXPLAIN this plan pre-execution: finalize it exactly like
        ``execute`` would and report per-op task counts, projected memory
        vs ``allowed_mem``, predicted bytes read/written (+ peer-eligible),
        the fusion outcome, and the scheduler/barrier decisions — an
        :class:`~cubed_tpu.observability.analytics.ExplainReport`
        (``print()`` it, ``.to_dict()`` it, or ``.save(path)`` for
        ``python -m cubed_tpu.explain``)."""
        from ..observability.analytics import explain as _explain

        return _explain(
            self,
            spec=spec,
            optimize_graph=optimize_graph,
            optimize_function=optimize_function,
            array_names=array_names,
        )

    def visualize(
        self,
        filename="cubed",
        format=None,
        rankdir="TB",
        optimize_graph=True,
        optimize_function=None,
        show_hidden=False,
    ):
        from .visualization import visualize_dag

        finalized = self._finalize(optimize_graph, optimize_function)
        return visualize_dag(
            finalized.dag,
            filename=filename,
            format=format,
            rankdir=rankdir,
            show_hidden=show_hidden,
        )


class FinalizedPlan:
    """A frozen, optimized DAG ready for execution."""

    def __init__(self, dag: nx.MultiDiGraph):
        self.dag = dag

    def num_tasks(self, resume=None) -> int:
        """Task count, chunk-granular under ``resume``: a partially-complete
        blockwise op contributes only its still-pending tasks — the same
        per-task skip the executors apply, so this number matches what a
        resumed compute actually runs. The scan is read-only (no
        quarantining, no metrics)."""
        nodes = dict(self.dag.nodes(data=True))
        state = ResumeState(count=False) if resume else None
        total = 0
        for name in nx.topological_sort(self.dag):
            if already_computed(name, self.dag, nodes, resume, state):
                continue
            node = nodes[name]
            if resume:
                _, skipped = pending_mappable(
                    name, node, resume, state, record=False
                )
                total += node["primitive_op"].num_tasks - skipped
            else:
                total += node["primitive_op"].num_tasks
        return total

    def num_arrays(self) -> int:
        return sum(1 for _, d in self.dag.nodes(data=True) if d.get("type") == "array")

    def num_ops(self) -> int:
        return sum(1 for _ in iter_op_nodes(self.dag))

    def max_projected_mem(self, resume=None) -> int:
        """Peak projected memory over the ops a compute would actually run;
        under ``resume`` an op skipped (all outputs checksum-valid) drops
        out, exactly mirroring the executors' skip decision."""
        nodes = dict(self.dag.nodes(data=True))
        state = ResumeState(count=False) if resume else None
        mems = [
            nodes[name]["primitive_op"].projected_mem
            for name in nx.topological_sort(self.dag)
            if not already_computed(name, self.dag, nodes, resume, state)
        ]
        return max(mems) if mems else 0

    def total_nbytes_written(self) -> int:
        return sum(
            d["target"].nbytes
            for _, d in self.dag.nodes(data=True)
            if d.get("type") == "array" and isinstance(d.get("target"), LazyZarrArray)
        )

    def explain(self, spec=None):
        """EXPLAIN this already-finalized plan (see ``Plan.explain``)."""
        from ..observability.analytics import explain_finalized

        return explain_finalized(self, spec=spec)


def arrays_to_dag(*arrays) -> nx.MultiDiGraph:
    """Union of the plans of the given arrays (sharing nodes by name)."""
    from .array import check_array_specs

    check_array_specs(arrays)
    dags = [a.plan.dag for a in arrays if hasattr(a, "plan")]
    if not dags:
        return nx.MultiDiGraph()
    return nx.compose_all(dags)


def arrays_to_plan(*arrays) -> Plan:
    return Plan(arrays_to_dag(*arrays))


def create_zarr_array(lazy_array: LazyZarrArray, config=None) -> None:
    """Task body of the create-arrays op."""
    lazy_array.create(mode="a")
