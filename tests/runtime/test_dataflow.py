"""Chunk-granular dataflow scheduler (``runtime/dataflow.py``).

Covers: mode resolution (env > Spec > default), chunk-graph construction
(1:1 elementwise edges, contraction fan-in, rechunk/create-arrays
barriers), dependency gating inside ``map_unordered`` (ordering + cycle
deadlock detection), the overlap proof (a downstream task STARTS before
its upstream op finishes), chaos-matrix bitwise correctness on every
async executor, corruption-RECOMPUTE repair mid-overlap, chunk-granular
resume consistency across the cross-op frontier, and the diagnose
overlap report.
"""

from __future__ import annotations

import concurrent.futures
import glob
import os
import threading
import time

import numpy as np
import pytest

import cubed_tpu as ct
import cubed_tpu.array_api as xp
from cubed_tpu.core.plan import arrays_to_plan
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime import faults
from cubed_tpu.runtime.dataflow import (
    DEFAULT_MODE,
    SCHEDULER_ENV_VAR,
    DataflowScheduler,
    build_chunk_graph,
    resolve_scheduler,
)
from cubed_tpu.runtime.executors.python_async import (
    AsyncPythonDagExecutor,
    map_unordered,
)
from cubed_tpu.runtime.pipeline import _task_chunk_key
from cubed_tpu.runtime.resilience import RetryPolicy
from cubed_tpu.runtime.types import Callback

from ..utils import TaskCounter


def _dataflow_spec(tmp_path, **kwargs):
    return ct.Spec(
        work_dir=str(tmp_path), allowed_mem="500MB", scheduler="dataflow",
        **kwargs,
    )


def _finalized_dag(arr):
    return arrays_to_plan(arr)._finalize(optimize_graph=False).dag


class _StatsCapture:
    stats: dict = {}

    def on_compute_end(self, event):
        self.stats = event.executor_stats or {}


# -- mode resolution -----------------------------------------------------


def test_resolve_scheduler_default_and_spec(tmp_path):
    # the dataflow scheduler is the default since rechunk stopped being a
    # barrier (ROADMAP item 5 first half); oplevel is the explicit escape
    # hatch
    assert resolve_scheduler(None) == DEFAULT_MODE == "dataflow"
    assert resolve_scheduler(
        ct.Spec(work_dir=str(tmp_path), scheduler="oplevel")
    ) == "oplevel"
    assert resolve_scheduler(_dataflow_spec(tmp_path)) == "dataflow"


def test_resolve_scheduler_env_wins(tmp_path, monkeypatch):
    monkeypatch.setenv(SCHEDULER_ENV_VAR, "oplevel")
    assert resolve_scheduler(_dataflow_spec(tmp_path)) == "oplevel"
    monkeypatch.setenv(SCHEDULER_ENV_VAR, "dataflow")
    assert resolve_scheduler(None) == "dataflow"


def test_resolve_scheduler_invalid_raises(monkeypatch):
    with pytest.raises(ValueError, match="invalid scheduler"):
        ct.Spec(scheduler="chunkwise")
    monkeypatch.setenv(SCHEDULER_ENV_VAR, "bogus")
    with pytest.raises(ValueError, match="invalid scheduler"):
        resolve_scheduler(None)


# -- chunk-graph construction --------------------------------------------


def test_chunk_graph_elementwise_one_to_one(tmp_path):
    """Each task of an elementwise consumer depends on exactly ONE task of
    its producer — the matching chunk — plus the create-arrays bootstrap."""
    spec = _dataflow_spec(tmp_path)
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = xp.multiply(xp.add(a, 1.0), 2.0)
    g = build_chunk_graph(_finalized_dag(c))

    assert g.op_order[0] == "create-arrays"
    op1, op2 = g.op_order[1], g.op_order[2]
    by_op = {}
    for idx, (name, m) in enumerate(g.items):
        by_op.setdefault(name, []).append(idx)
    create_idxs = set(by_op["create-arrays"])
    op1_key_to_idx = {
        _task_chunk_key(g.items[i][1]): i for i in by_op[op1]
    }
    assert len(by_op[op1]) == len(by_op[op2]) == 16
    for idx in by_op[op2]:
        deps = g.dependencies[idx]
        chunk_deps = deps - create_idxs
        key = _task_chunk_key(g.items[idx][1])
        assert chunk_deps == {op1_key_to_idx[key]}, (key, chunk_deps)
    # a pure elementwise chain has no conservative barriers beyond the
    # metadata bootstrap
    assert g.barrier_tasks == 0


def test_chunk_graph_reduction_fan_in(tmp_path):
    """A tree-reduce consumer fans in: its tasks depend on SEVERAL
    producer chunks each (streamed via iterator key structures), and no
    producer task is left unconsumed — every edge of the frontier exists."""
    spec = _dataflow_spec(tmp_path)
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = xp.add(a, 1.0)
    s = xp.sum(b)
    g = build_chunk_graph(_finalized_dag(s))

    by_op = {}
    for idx, (name, _m) in enumerate(g.items):
        by_op.setdefault(name, []).append(idx)
    create_idxs = set(by_op["create-arrays"])
    # somewhere in the reduce chain a stage must fan in: one task
    # consuming MANY producer chunks (the 64->4 partial_reduce round),
    # with the union of the stage's deps covering the producer entirely
    # (no dropped edges)
    fan_in_pairs = []
    for producer in g.op_order[1:]:
        p_idxs = set(by_op[producer])
        for consumer in g.op_order[2:]:
            if consumer == producer:
                continue
            per_task = [
                (g.dependencies.get(i, set()) - create_idxs) & p_idxs
                for i in by_op[consumer]
            ]
            consumed = set().union(*per_task) if per_task else set()
            if consumed and max(len(d) for d in per_task) >= 2:
                fan_in_pairs.append((producer, consumer, consumed == p_idxs))
    assert fan_in_pairs, g.op_order
    # at least one fan-in stage consumes its producer COMPLETELY
    assert any(complete for _, _, complete in fan_in_pairs), fan_in_pairs


def test_chunk_graph_rechunk_is_chunked(tmp_path):
    """Rechunk is no longer a barrier: every rechunk task depends on
    exactly the producer tasks whose chunks its region overlaps
    (``runtime/shuffle.py`` region math), its consumers depend on the
    covering rechunk task only, and the barrier metric stays zero."""
    from cubed_tpu.runtime import shuffle

    # tight allowed_mem so the rechunk write regions stay column strips
    # (several tasks) instead of consolidating into one whole-array copy
    spec = ct.Spec(
        work_dir=str(tmp_path), allowed_mem="700KB", scheduler="dataflow",
    )
    an = np.arange(128 * 128, dtype=np.float64).reshape(128, 128)
    a = ct.from_array(an, chunks=(32, 128), spec=spec)
    b = xp.add(a, 1.0)
    r = ct.rechunk(b, (128, 32))
    c = xp.add(r, 5.0)
    g = build_chunk_graph(_finalized_dag(c))

    by_op = {}
    for idx, (name, _m) in enumerate(g.items):
        by_op.setdefault(name, []).append(idx)
    rechunk_ops = [n for n, k in g.op_kind.items() if k == "rechunk"]
    assert rechunk_ops, g.op_kind
    assert g.barrier_tasks == 0
    assert g.barrier_ops == []
    add_op = g.op_order[1]
    create_idxs = set(by_op["create-arrays"])
    add_key_to_idx = {
        _task_chunk_key(g.items[i][1]): i for i in by_op[add_op]
    }
    first_rechunk = rechunk_ops[0]
    pipeline = g.pipelines[first_rechunk]
    assert len(by_op[first_rechunk]) > 1, "consolidated into one task"
    for idx in by_op[first_rechunk]:
        _, m = g.items[idx]
        expected = {
            add_key_to_idx[key]
            for _store, key in shuffle.rechunk_task_reads(m, pipeline.config)
        }
        assert g.dependencies[idx] - create_idxs == expected
        # locality: the graph recorded the exact source chunks this
        # shuffle task reads (what placement scores workers by)
        assert g.reads[idx], idx
    # the consumer of the rechunked array depends only on the rechunk
    # task(s) covering the chunks it reads — not on the whole stage
    last_rechunk = rechunk_ops[-1]
    rech_cover = {}
    for i in by_op[last_rechunk]:
        _, m = g.items[i]
        for key in shuffle.rechunk_task_writes(m, g.pipelines[last_rechunk].config):
            rech_cover[key] = i
    final_op = g.op_order[-1]
    for idx in by_op[final_op]:
        _, m = g.items[idx]
        deps = g.dependencies[idx] - create_idxs
        expected = {rech_cover[_task_chunk_key(m)]}
        assert deps == expected, (m, deps, expected)


def test_chunk_graph_resume_satisfies_deps(tmp_path):
    """A dependency on an already-valid chunk is born satisfied: after a
    full compute, deleting ONE final-output chunk leaves a one-task graph
    whose deps on the (complete) producer are empty."""
    spec = _dataflow_spec(tmp_path)
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = xp.multiply(xp.add(a, 1.0), 2.0)
    np.testing.assert_array_equal(
        c.compute(optimize_graph=False), (an + 1.0) * 2.0
    )
    stores = sorted(
        os.path.dirname(p)
        for p in glob.glob(f"{spec.work_dir}/*/*.zarr/.zarray")
    )
    assert len(stores) == 2  # intermediate + final
    # the final op's store is the one whose op comes last; deleting from
    # either proves the point — pick the one that still leaves its
    # consumer runnable (the final output)
    final_store = stores[-1]
    os.unlink(os.path.join(final_store, "3.3"))
    g = build_chunk_graph(_finalized_dag(c), resume=True)
    # create-arrays always re-runs (cheap metadata recreate, matching the
    # op-level resume path); beyond it, exactly ONE chunk task remains,
    # and its only deps are the bootstrap — the producer chunk it reads
    # is already valid, so that dependency was born satisfied
    chunk_items = [
        (i, name) for i, (name, _m) in enumerate(g.items)
        if name != "create-arrays"
    ]
    assert len(chunk_items) == 1, chunk_items
    idx, _name = chunk_items[0]
    create_idxs = {
        i for i, (name, _m) in enumerate(g.items) if name == "create-arrays"
    }
    assert g.dependencies.get(idx, set()) <= create_idxs


# -- map_unordered dependency gating -------------------------------------


def test_map_unordered_dependencies_enforce_order():
    order: list = []
    lock = threading.Lock()

    def fn(i):
        with lock:
            order.append(i)
        time.sleep(0.01)
        return i

    with concurrent.futures.ThreadPoolExecutor(max_workers=4) as pool:
        map_unordered(
            pool, fn, list(range(6)),
            dependencies={0: {4}, 1: {4}, 2: {4}, 4: {5}},
        )
    pos = {i: order.index(i) for i in range(6)}
    assert pos[5] < pos[4]
    assert all(pos[4] < pos[i] for i in (0, 1, 2))


def test_map_unordered_dependency_cycle_raises():
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        with pytest.raises(RuntimeError, match="dataflow deadlock"):
            map_unordered(
                pool, lambda i: i, [0, 1],
                dependencies={0: {1}, 1: {0}},
            )


def test_map_unordered_completed_inputs_resume():
    """A re-run over the same index space skips completed inputs and
    treats their dependency edges as satisfied — what the multiprocess
    pool-crash rebuild passes via the scheduler's live done-set."""
    ran: list = []
    lock = threading.Lock()

    def fn(i):
        with lock:
            ran.append(i)
        return i

    done_hook: list = []
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        map_unordered(
            pool, fn, list(range(4)),
            dependencies={3: {0, 1}},
            completed_inputs={0, 1},
            on_input_done=done_hook.append,
        )
    assert sorted(ran) == [2, 3]  # 0/1 never re-ran
    assert sorted(done_hook) == [2, 3]  # hooks fire only for fresh work


def test_map_unordered_dependencies_reject_batching():
    with concurrent.futures.ThreadPoolExecutor(max_workers=2) as pool:
        with pytest.raises(ValueError, match="mutually"):
            map_unordered(
                pool, lambda i: i, [0, 1], batch_size=1,
                dependencies={1: {0}},
            )


# -- the overlap proof ---------------------------------------------------


class _SlowBlock:
    """Deterministic straggler: block (0, 0) sleeps; everything else is
    instant. Picklable (multiprocess-safe)."""

    def __init__(self, delay_s: float):
        self.delay_s = delay_s

    def __call__(self, x, block_id=None):
        if block_id == (0, 0):
            time.sleep(self.delay_s)
        return x + 1.0


class _LifecycleWatch(Callback):
    """Wall-clock timestamps of task starts and op ends, per op."""

    def __init__(self):
        self.task_starts: dict = {}
        self.op_ends: dict = {}

    def on_task_start(self, event):
        self.task_starts.setdefault(event.array_name, []).append(time.time())

    def on_operation_end(self, event):
        self.op_ends[event.name] = time.time()


def test_dataflow_overlap_downstream_starts_before_upstream_ends(tmp_path):
    """The acceptance proof: with one straggler chunk in the upstream op,
    ≥1 downstream task STARTS while the upstream op is still running —
    impossible under the op barrier — and the result is bitwise-identical
    to the sequential oracle's."""
    spec = _dataflow_spec(tmp_path)
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.map_blocks(_SlowBlock(0.6), a, dtype=np.float64)
    c = xp.add(b, 1.0)

    watch = _LifecycleWatch()
    before = get_registry().snapshot()
    result = c.compute(
        executor=AsyncPythonDagExecutor(),
        callbacks=[watch],
        optimize_graph=False,
    )
    np.testing.assert_array_equal(result, an + 2.0)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("tasks_dispatched_early", 0) >= 1, delta

    ops = [op for op in watch.op_ends if op != "create-arrays"]
    assert len(ops) == 2
    upstream = min(ops, key=lambda op: min(watch.task_starts[op]))
    downstream = [op for op in ops if op != upstream][0]
    first_down = min(watch.task_starts[downstream])
    up_end = watch.op_ends[upstream]
    # the downstream op must have started well inside the straggler's
    # sleep window, not after the upstream op closed
    assert first_down < up_end - 0.2, (first_down, up_end)


def test_dataflow_env_var_drives_overlap(tmp_path, monkeypatch):
    """CUBED_TPU_SCHEDULER=dataflow arms the scheduler with no Spec knob."""
    monkeypatch.setenv(SCHEDULER_ENV_VAR, "dataflow")
    spec = ct.Spec(work_dir=str(tmp_path), allowed_mem="500MB")
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = xp.multiply(xp.add(a, 1.0), 2.0)
    before = get_registry().snapshot()
    result = c.compute(
        executor=AsyncPythonDagExecutor(), optimize_graph=False
    )
    np.testing.assert_array_equal(result, (an + 1.0) * 2.0)
    delta = get_registry().snapshot_delta(before)
    assert delta.get("tasks_dispatched_early", 0) >= 1, delta


# -- chaos matrix: bitwise-correct results under faults ------------------

CHAOS = dict(
    seed=42,
    storage_read_failure_rate=0.08,
    storage_write_failure_rate=0.12,
    task_failure_rate=0.08,
)


@pytest.mark.chaos
def test_dataflow_chaos_threaded_bitwise_correct(tmp_path):
    spec = _dataflow_spec(tmp_path, fault_injection=CHAOS)
    an = np.arange(400, dtype=np.float64).reshape(20, 20)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 100 chunks/op
    c = xp.multiply(xp.add(a, 1.0), 2.0)
    cap = _StatsCapture()
    result = c.compute(
        executor=AsyncPythonDagExecutor(
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0)
        ),
        callbacks=[cap],
        optimize_graph=False,
    )
    np.testing.assert_array_equal(result, (an + 1.0) * 2.0)
    assert cap.stats.get("faults_injected", 0) > 0, cap.stats
    assert cap.stats.get("task_retries", 0) > 0, cap.stats


class _CorruptFirstChunkTask(Callback):
    """Flips a byte in the chunk written by the FIRST completed chunk task
    (necessarily an upstream task — consumers cannot finish before their
    producer). Task-end callbacks fire BEFORE the completion loop releases
    dependents, so the consumer of this exact chunk has provably not read
    it yet: the corruption is always detected mid-compute."""

    def __init__(self, work_dir: str):
        self.work_dir = work_dir
        self.corrupted = None

    def on_task_end(self, event):
        import ast

        if self.corrupted is not None or event.array_name == "create-arrays":
            return
        try:
            key = ast.literal_eval(event.chunk_key)
        except (ValueError, SyntaxError):
            return
        name = ".".join(str(i) for i in key[1:])
        paths = glob.glob(f"{self.work_dir}/*/{key[0]}.zarr/{name}")
        if not paths:
            return
        with open(paths[0], "r+b") as f:
            data = bytearray(f.read())
            data[3] ^= 0xFF
            f.seek(0)
            f.write(data)
        self.corrupted = paths[0]


@pytest.mark.chaos
def test_dataflow_chaos_corruption_recompute_mid_overlap(tmp_path):
    """Corruption of an intermediate chunk detected WHILE the upstream op
    is still running (a straggler holds it open): the reader's
    ChunkIntegrityError triggers RECOMPUTE of exactly the producing task,
    the rest of the frontier keeps flowing, and the result is
    bitwise-correct."""
    spec = _dataflow_spec(tmp_path, integrity="verify")
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 64 chunks/op
    b = ct.map_blocks(_SlowBlock(0.5), a, dtype=np.float64)
    c = xp.multiply(b, 2.0)
    corruptor = _CorruptFirstChunkTask(str(tmp_path))
    cap = _StatsCapture()
    before = get_registry().snapshot()
    result = c.compute(
        executor=AsyncPythonDagExecutor(
            retry_policy=RetryPolicy(retries=4, backoff_base=0.01, seed=0)
        ),
        callbacks=[cap, corruptor],
        optimize_graph=False,
    )
    np.testing.assert_array_equal(result, (an + 1.0) * 2.0)
    assert corruptor.corrupted is not None
    delta = get_registry().snapshot_delta(before)
    assert delta.get("chunks_corrupt_detected", 0) >= 1, delta
    assert delta.get("chunks_recomputed", 0) >= 1, delta
    # ...and the repair happened in an overlapped frontier, not behind a
    # barrier: downstream tasks had already dispatched early
    assert delta.get("tasks_dispatched_early", 0) >= 1, delta


@pytest.mark.chaos
def test_dataflow_chaos_multiprocess_bitwise_correct(tmp_path, monkeypatch):
    monkeypatch.setenv(
        faults.FAULTS_ENV_VAR,
        faults.FaultConfig(
            seed=42, storage_write_failure_rate=0.15
        ).to_env_json(),
    )
    from cubed_tpu.runtime.executors.multiprocess import (
        MultiprocessDagExecutor,
    )

    spec = _dataflow_spec(tmp_path)
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 25 chunks/op
    c = xp.multiply(xp.add(a, 1.0), 3.0)
    cap = _StatsCapture()
    result = c.compute(
        executor=MultiprocessDagExecutor(
            max_workers=2,
            retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
        ),
        callbacks=[cap],
        optimize_graph=False,
    )
    np.testing.assert_array_equal(result, (an + 1.0) * 3.0)
    assert cap.stats.get("task_retries", 0) > 0, cap.stats


@pytest.mark.chaos
def test_dataflow_chaos_distributed_worker_crash_mid_overlap(
    tmp_path, monkeypatch
):
    """A worker hard-exits mid-compute while the cross-op frontier is in
    flight: its tasks requeue for free onto the survivor and the result
    stays bitwise-correct."""
    from cubed_tpu.runtime.executors.distributed import (
        DistributedDagExecutor,
    )

    monkeypatch.setenv(
        faults.FAULTS_ENV_VAR,
        faults.FaultConfig(
            seed=7,
            worker_crash_names=("local-0",),
            worker_crash_after_tasks=3,
        ).to_env_json(),
    )
    spec = _dataflow_spec(tmp_path)
    an = np.arange(256, dtype=np.float64).reshape(16, 16)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)  # 64 chunks/op
    c = xp.multiply(xp.add(a, 1.0), 2.0)
    before = get_registry().snapshot()
    ex = DistributedDagExecutor(
        n_local_workers=2,
        retry_policy=RetryPolicy(retries=6, backoff_base=0.01, seed=0),
    )
    try:
        ex._ensure_fleet()
        result = c.compute(executor=ex, optimize_graph=False)
        np.testing.assert_array_equal(result, (an + 1.0) * 2.0)
        assert ex._coordinator.stats["workers_lost"] >= 1
        delta = get_registry().snapshot_delta(before)
        assert delta.get("worker_loss_requeues", 0) >= 1, delta
    finally:
        ex.close()


# -- resume across the chunk-level frontier ------------------------------


def test_dataflow_resume_chunk_granular_frontier(tmp_path):
    """Chunk-granular resume composes with the dataflow frontier: delete
    one intermediate chunk and one (different) final chunk — the resumed
    compute runs only the producing tasks of the missing chunks, skips
    everything else, and matches the plan's own resume introspection."""
    spec = _dataflow_spec(tmp_path)
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = xp.multiply(xp.add(a, 1.0), 2.0)
    np.testing.assert_array_equal(
        c.compute(optimize_graph=False), (an + 1.0) * 2.0
    )
    inter_store, final_store = sorted(
        os.path.dirname(p)
        for p in glob.glob(f"{spec.work_dir}/*/*.zarr/.zarray")
    )
    # stores sort by gensym name, which is creation-ordered: first is the
    # intermediate (add), second the final (multiply)
    os.unlink(os.path.join(inter_store, "1.1"))
    os.unlink(os.path.join(final_store, "2.2"))

    plan_tasks = arrays_to_plan(c).num_tasks(
        optimize_graph=False, resume=True
    )
    before = get_registry().snapshot()
    counter = TaskCounter()
    result = c.compute(
        executor=AsyncPythonDagExecutor(),
        optimize_graph=False,
        resume=True,
        callbacks=[counter],
    )
    np.testing.assert_array_equal(result, (an + 1.0) * 2.0)
    delta = get_registry().snapshot_delta(before)
    # 25 tasks/op: intermediate re-runs 1 (chunk 1.1), final re-runs 1
    # (chunk 2.2, whose input chunk is still valid) — 48 skips
    assert delta.get("tasks_skipped_resume") == 48, delta
    # create-arrays (2 targets) + the two missing-chunk tasks — and the
    # executor ran exactly what the plan introspection promised
    assert counter.value == 4 == plan_tasks


def test_dataflow_resume_dependency_on_missing_upstream_chunk(tmp_path):
    """When the SAME chunk is missing in both stores, the final task must
    wait for the re-run of its producer (a live cross-op dependency in
    the resumed frontier) — order is enforced, result exact."""
    spec = _dataflow_spec(tmp_path)
    an = np.arange(100, dtype=np.float64).reshape(10, 10)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    c = xp.multiply(xp.add(a, 1.0), 2.0)
    np.testing.assert_array_equal(
        c.compute(optimize_graph=False), (an + 1.0) * 2.0
    )
    inter_store, final_store = sorted(
        os.path.dirname(p)
        for p in glob.glob(f"{spec.work_dir}/*/*.zarr/.zarray")
    )
    os.unlink(os.path.join(inter_store, "1.1"))
    os.unlink(os.path.join(final_store, "1.1"))

    g = build_chunk_graph(_finalized_dag(c), resume=True)
    chunk_items = [
        i for i, (name, _m) in enumerate(g.items)
        if name != "create-arrays"
    ]
    assert len(chunk_items) == 2, g.items
    up_idx, down_idx = chunk_items
    create_idxs = {
        i for i, (name, _m) in enumerate(g.items) if name == "create-arrays"
    }
    assert g.array_names[up_idx] != g.array_names[down_idx]
    # the live cross-op edge: the final task waits on the re-run producer
    assert g.dependencies.get(down_idx, set()) - create_idxs == {up_idx}

    counter = TaskCounter()
    result = c.compute(
        executor=AsyncPythonDagExecutor(),
        optimize_graph=False,
        resume=True,
        callbacks=[counter],
    )
    np.testing.assert_array_equal(result, (an + 1.0) * 2.0)
    assert counter.value == 4  # create-arrays x2 + the two chunk tasks


# -- diagnose: the overlap post-mortem -----------------------------------


def test_diagnose_op_overlap_rows():
    from cubed_tpu.diagnose import op_overlap_rows

    trace = {
        "traceEvents": [
            # op A: two tasks, 0-1s and 0-1s
            {"ph": "X", "cat": "task", "name": "op-a", "ts": 0.0,
             "dur": 1_000_000},
            {"ph": "X", "cat": "task", "name": "op-a", "ts": 0.0,
             "dur": 1_000_000},
            # op B: one task starting halfway through A
            {"ph": "X", "cat": "task", "name": "op-b", "ts": 500_000,
             "dur": 1_000_000},
            # non-task events are ignored
            {"ph": "i", "cat": "instant", "name": "noise", "ts": 0},
            {"ph": "X", "cat": "span", "name": "storage_read", "ts": 0,
             "dur": 10},
        ]
    }
    rows = op_overlap_rows(trace)
    assert [r["op"] for r in rows] == ["op-a", "op-b"]
    assert rows[0]["overlap_s"] == 0.0
    assert rows[1]["overlap_s"] == pytest.approx(0.5)
    assert rows[1]["busy_s"] == pytest.approx(1.0)


def test_diagnose_report_includes_overlap_section(tmp_path):
    """End-to-end: a dataflow compute's flight bundle renders a per-op
    overlap section naming the scheduler mode."""
    from cubed_tpu.diagnose import render_report
    from cubed_tpu.observability.flightrecorder import (
        FlightRecorder,
        load_bundle,
    )

    spec = _dataflow_spec(tmp_path)
    an = np.arange(64, dtype=np.float64).reshape(8, 8)
    a = ct.from_array(an, chunks=(2, 2), spec=spec)
    b = ct.map_blocks(_SlowBlock(0.4), a, dtype=np.float64)
    c = xp.add(b, 1.0)
    rec = FlightRecorder(bundle_dir=str(tmp_path), always=True)
    result = c.compute(
        executor=AsyncPythonDagExecutor(),
        callbacks=[rec],
        optimize_graph=False,
    )
    np.testing.assert_array_equal(result, an + 2.0)
    bundles = glob.glob(f"{tmp_path}/bundle-*")
    assert bundles, os.listdir(tmp_path)
    report = render_report(load_bundle(bundles[0]))
    assert "per-op overlap" in report
    assert "scheduler=dataflow" in report
    assert "ran concurrently with predecessors" in report
