"""Stage breakdown of the addsum_scaled CPU-fallback gap (VERDICT r4 #9).

Measures, on the CPU backend with a scrubbed environment (run it via
``python benchmarks/profile_addsum_scaled.py``; it re-executes itself in a
tunnel-free subprocess):

  1. framework warm compute of the bench config (16000x16000 f64,
     2000-chunks, JaxExecutor fallback path),
  2. a raw-JAX jit of the same math (generation + add + sum),
  3. the XLA threefry-f64 generation alone,
  4. numpy's Philox generation alone and the add+sum alone,
  5. the numpy-backend end-to-end equivalent (the recorded baseline's
     semantics).

Prints one JSON line per stage; the analysis lives in BENCH_PROFILE.md.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BODY = r"""
import json, sys, tempfile, time
sys.path.insert(0, %(repo)r)
import numpy as np

SHAPE, CHUNK = (16000, 16000), 2000
WORK = 2 * SHAPE[0] * SHAPE[1] * 8


def emit(stage, secs, note=""):
    print(json.dumps({
        "stage": stage, "seconds": round(secs, 3),
        "gbps": round(WORK / secs / 1e9, 3), "note": note,
    }), flush=True)


# ---- numpy side -----------------------------------------------------------
t0 = time.perf_counter()
rng = np.random.default_rng(0)
an = rng.random(SHAPE)
bn = rng.random(SHAPE)
t1 = time.perf_counter()
emit("numpy_philox_generate_2x2GB", t1 - t0)
t0 = time.perf_counter()
val = float(np.sum(np.add(an, bn)))
t1 = time.perf_counter()
emit("numpy_add_sum", t1 - t0)
del an, bn

# ---- jax side -------------------------------------------------------------
import jax
import jax.numpy as jnp

jax.config.update("jax_enable_x64", True)
jax.config.update("jax_threefry_partitionable", True)


def timed(fn, *args):
    fn(*args)  # warm (compile)
    t0 = time.perf_counter()
    r = fn(*args)
    jax.block_until_ready(r)
    return time.perf_counter() - t0


def _u(seed, salt):
    key = jax.random.fold_in(jax.random.key(0), seed * 7919 + salt)
    return jax.random.uniform(key, SHAPE, dtype=jnp.float64)


gen2 = jax.jit(lambda s: (_u(s, 1), _u(s, 2)))
emit("xla_threefry_f64_generate_2x2GB", timed(gen2, 3))

addsum_only = jax.jit(lambda a, b: jnp.sum(a + b))
a0, b0 = gen2(5)
emit("xla_add_sum", timed(addsum_only, a0, b0))
del a0, b0

raw = jax.jit(lambda s: jnp.sum(_u(s, 1) + _u(s, 2)))
emit("raw_jax_full", timed(raw, 7))

# ---- framework ------------------------------------------------------------
import cubed_tpu as ct
import cubed_tpu.array_api as xp
import cubed_tpu.random
from cubed_tpu.runtime.executors.jax import JaxExecutor

spec = ct.Spec(work_dir=tempfile.mkdtemp(), allowed_mem="4GB")


def build():
    a = cubed_tpu.random.random(SHAPE, chunks=CHUNK, spec=spec)
    b = cubed_tpu.random.random(SHAPE, chunks=CHUNK, spec=spec)
    return xp.sum(xp.add(a, b))

ex = JaxExecutor()
float(build().compute(executor=ex))  # warm: compile + trace caches
t0 = time.perf_counter()
float(build().compute(executor=ex))
t1 = time.perf_counter()
emit("framework_warm_compute", t1 - t0)

import cProfile, pstats, io
pr = cProfile.Profile()
pr.enable()
float(build().compute(executor=ex))
pr.disable()
s = io.StringIO()
pstats.Stats(pr, stream=s).sort_stats("cumulative").print_stats(14)
print(s.getvalue()[:3000], file=sys.stderr)
"""


def main() -> None:
    env = {
        k: v
        for k, v in os.environ.items()
        if not k.startswith(("PALLAS_AXON", "AXON_", "TPU_"))
    }
    env["JAX_PLATFORMS"] = "cpu"
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-c", BODY % {"repo": REPO}],
        env=env, text=True, capture_output=True, timeout=900,
    )
    sys.stdout.write(out.stdout)
    sys.stderr.write(out.stderr[-3500:])
    print(json.dumps({"stage": "total_wall", "seconds": round(time.time() - t0, 1)}))
    if out.returncode != 0:
        sys.exit(out.returncode)


if __name__ == "__main__":
    main()
