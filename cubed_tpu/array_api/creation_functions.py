"""Array-API creation functions. Creation of constant arrays is free (virtual
arrays); generated arrays (arange/linspace/eye) are per-block affine
computations keyed by ``block_id``. Reference parity:
cubed/array_api/creation_functions.py (322 LoC)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..backend_array_api import nxp
from ..chunks import normalize_chunks
from ..core.array import CoreArray
from ..core.ops import (
    blockwise,
    elemwise,
    from_array,
    map_blocks,
    new_array,
)
from ..core.plan import Plan, gensym
from ..spec import spec_from_config
from ..storage.virtual import (
    virtual_empty,
    virtual_full,
    virtual_in_memory,
    virtual_offsets,
)
from ..utils import to_chunksize


def _finalize_spec(spec):
    return spec_from_config(spec)


def arange(
    start, /, stop=None, step=1, *, dtype=None, device=None, chunks="auto", spec=None
):
    if stop is None:
        start, stop = 0, start
    num = int(max(np.ceil((stop - start) / step), 0))
    if dtype is None:
        dtype = np.arange(start, stop, step * num if num else step).dtype
    chunks = normalize_chunks(chunks, (num,), dtype=dtype)
    chunksize = chunks[0][0] if chunks[0] else 1

    def _arange_chunk(chunk, block_id=None, offset=None, numblocks=None):
        # offset path: block index arrives as device data (trace/vmap-safe)
        b0 = nxp.asarray(offset).ravel()[0] if offset is not None else block_id[0]
        bstart = start + b0 * chunksize * step
        blen = chunk.shape[0]
        return nxp.asarray(
            bstart + step * nxp.arange(blen), dtype=dtype
        )

    _arange_chunk.supports_offset = True
    return map_blocks(
        _arange_chunk,
        empty((num,), dtype=dtype, chunks=chunks, spec=spec),
        dtype=dtype,
    )


def asarray(obj, /, *, dtype=None, device=None, copy=None, chunks="auto", spec=None):
    if isinstance(obj, CoreArray):
        if dtype is not None and obj.dtype != np.dtype(dtype):
            from .data_type_functions import astype

            return astype(obj, dtype)
        return obj
    a = np.asarray(obj, dtype=dtype)
    if a.dtype == np.float16:
        raise NotImplementedError("float16 is not supported")
    spec = _finalize_spec(spec)
    outchunks = normalize_chunks(chunks, a.shape, dtype=a.dtype)
    target = virtual_in_memory(a, to_chunksize(outchunks) if a.shape else ())
    name = gensym("array")
    plan = Plan._new(name, "asarray", target)
    return new_array(name, target, spec, plan)


def empty(shape, *, dtype=None, device=None, chunks="auto", spec=None):
    if dtype is None:
        dtype = np.dtype(np.float64)
    return empty_virtual_array(shape, dtype=dtype, chunks=chunks, spec=spec, hidden=False)


def empty_like(x, /, *, dtype=None, device=None, chunks=None, spec=None):
    return empty(**_like_args(x, dtype, chunks, spec))


def empty_virtual_array(shape, *, dtype=None, device=None, chunks="auto", spec=None, hidden=True):
    if dtype is None:
        dtype = np.dtype(np.float64)
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    spec = _finalize_spec(spec)
    outchunks = normalize_chunks(chunks, shape, dtype=dtype)
    target = virtual_empty(shape, dtype=dtype, chunks=to_chunksize(outchunks) if shape else ())
    name = gensym("empty")
    plan = Plan._new(name, "empty", target, None, hidden)
    return new_array(name, target, spec, plan)


def eye(n_rows, n_cols=None, /, *, k=0, dtype=None, device=None, chunks="auto", spec=None):
    if n_cols is None:
        n_cols = n_rows
    if dtype is None:
        dtype = np.dtype(np.float64)
    shape = (n_rows, n_cols)
    chunks = normalize_chunks(chunks, shape, dtype=dtype)
    chunksize = to_chunksize(chunks)

    nb1 = len(chunks[1])

    def _eye_chunk(chunk, block_id=None, offset=None, numblocks=None):
        m, n = chunk.shape
        if offset is not None:
            # offset-native: the linear block offset may be a traced value,
            # so the diagonal predicate stays jit/vmap-safe (static-length
            # aranges + traced starts)
            off = nxp.asarray(offset).ravel()[0]
            b0, b1 = off // nb1, off % nb1
        else:
            b0, b1 = block_id
        ii = (b0 * chunksize[0] + nxp.arange(m))[:, None]
        jj = (b1 * chunksize[1] + nxp.arange(n))[None, :]
        return nxp.asarray(jj - ii == k, dtype=dtype)

    _eye_chunk.supports_offset = True

    return map_blocks(_eye_chunk, empty(shape, dtype=dtype, chunks=chunks, spec=spec), dtype=dtype)


def full(shape, fill_value, *, dtype=None, device=None, chunks="auto", spec=None):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = np.dtype(np.bool_)
        elif isinstance(fill_value, int):
            dtype = np.dtype(np.int64)
        elif isinstance(fill_value, float):
            dtype = np.dtype(np.float64)
        else:
            raise TypeError(f"Invalid input to full: {fill_value!r}")
    dtype = np.dtype(dtype)
    spec = _finalize_spec(spec)
    outchunks = normalize_chunks(chunks, shape, dtype=dtype)
    target = virtual_full(
        shape, fill_value, dtype=dtype, chunks=to_chunksize(outchunks) if shape else ()
    )
    name = gensym("full")
    plan = Plan._new(name, "full", target)
    return new_array(name, target, spec, plan)


def full_like(x, /, fill_value, *, dtype=None, device=None, chunks=None, spec=None):
    return full(fill_value=fill_value, **_like_args(x, dtype, chunks, spec))


def linspace(
    start, stop, /, num=50, *, dtype=None, device=None, endpoint=True,
    chunks="auto", spec=None,
):
    div = (num - 1) if endpoint else num
    div = div if div > 0 else 1
    step = float(stop - start) / div
    if dtype is None:
        dtype = np.dtype(np.float64)
    chunks = normalize_chunks(chunks, (num,), dtype=dtype)
    chunksize = chunks[0][0] if chunks[0] else 1

    def _linspace_chunk(chunk, block_id=None, offset=None, numblocks=None):
        b0 = nxp.asarray(offset).ravel()[0] if offset is not None else block_id[0]
        bstart = start + b0 * chunksize * step
        blen = chunk.shape[0]
        vals = bstart + step * nxp.arange(blen)
        if endpoint and num > 1:
            # pin the final element to `stop` exactly (numpy semantics): the
            # per-block affine accumulates one rounding step at the endpoint
            gidx = b0 * chunksize + nxp.arange(blen)
            vals = nxp.where(gidx == num - 1, stop, vals)
        return nxp.asarray(vals, dtype=dtype)

    _linspace_chunk.supports_offset = True
    return map_blocks(
        _linspace_chunk,
        empty((num,), dtype=dtype, chunks=chunks, spec=spec),
        dtype=dtype,
    )


def meshgrid(*arrays, indexing="xy"):
    if len({a.dtype for a in arrays}) > 1:
        raise ValueError("meshgrid inputs must all have the same dtype")
    from .manipulation_functions import broadcast_arrays, expand_dims

    if indexing == "xy" and len(arrays) > 1:
        arrays = (arrays[1], arrays[0]) + tuple(arrays[2:])
    n = len(arrays)
    grids = []
    for i, a in enumerate(arrays):
        g = a
        for j in range(0, i):
            g = expand_dims(g, axis=0)
        for j in range(i + 1, n):
            g = expand_dims(g, axis=g.ndim)
        grids.append(g)
    grids = list(broadcast_arrays(*grids))
    if indexing == "xy" and len(arrays) > 1:
        grids[0], grids[1] = grids[1], grids[0]
    return grids


def ones(shape, *, dtype=None, device=None, chunks="auto", spec=None):
    if dtype is None:
        dtype = np.dtype(np.float64)
    return full(shape, 1, dtype=dtype, chunks=chunks, spec=spec)


def ones_like(x, /, *, dtype=None, device=None, chunks=None, spec=None):
    return ones(**_like_args(x, dtype, chunks, spec))


def tril(x, /, *, k=0):
    from .dtypes import _numeric_dtypes

    if x.ndim < 2:
        raise ValueError("x must be at least 2-dimensional for tril")
    mask = _tri_mask(x, k)
    from .searching_functions import where

    return where(mask, x, zeros_like(x))


def triu(x, /, *, k=0):
    if x.ndim < 2:
        raise ValueError("x must be at least 2-dimensional for triu")
    mask = _tri_mask(x, k - 1)
    from .searching_functions import where

    return where(mask, zeros_like(x), x)


def _tri_mask(x, k):
    """Boolean mask (rows >= cols - k) matching x's trailing 2 dims & chunks."""
    m, n = x.shape[-2], x.shape[-1]
    cm = x.chunks[-2]
    cn = x.chunks[-1]

    def _mask_chunk(chunk, block_id=None):
        i0 = sum(cm[: block_id[0]])
        j0 = sum(cn[: block_id[1]])
        mm, nn = chunk.shape
        ii = nxp.arange(i0, i0 + mm)[:, None]
        jj = nxp.arange(j0, j0 + nn)[None, :]
        return ii >= (jj - k)

    mask2d = map_blocks(
        _mask_chunk,
        empty((m, n), dtype=np.bool_, chunks=(cm, cn), spec=x.spec),
        dtype=np.dtype(np.bool_),
    )
    return mask2d


def zeros(shape, *, dtype=None, device=None, chunks="auto", spec=None):
    if dtype is None:
        dtype = np.dtype(np.float64)
    return full(shape, 0, dtype=dtype, chunks=chunks, spec=spec)


def zeros_like(x, /, *, dtype=None, device=None, chunks=None, spec=None):
    return zeros(**_like_args(x, dtype, chunks, spec))


def offsets_virtual_array(numblocks, spec=None):
    """Hidden array feeding ``block_id`` to map_blocks tasks."""
    spec = _finalize_spec(spec)
    target = virtual_offsets(tuple(numblocks))
    name = gensym("block-ids")
    plan = Plan._new(name, "block_ids", target, None, True)
    return new_array(name, target, spec, plan)


def _like_args(x, dtype=None, chunks=None, spec=None):
    if dtype is None:
        dtype = x.dtype
    if chunks is None:
        chunks = x.chunks
    if spec is None:
        spec = x.spec
    return dict(shape=x.shape, dtype=dtype, chunks=chunks, spec=spec)


def from_dlpack(x, /, *, device=None, copy=None, chunks="auto", spec=None):
    """Construct a chunked array from any DLPack-exporting object (torch
    CPU tensors, jax arrays, numpy arrays, ...). The reference lists this
    as a known gap (reference api_status.md); here it lands as a host
    import through ``asarray``.

    The import always COPIES: a lazy plan may compute long after the
    exporter mutates its buffer, so aliasing semantics would corrupt
    results; ``copy=False`` is therefore rejected."""
    if not hasattr(x, "__dlpack__"):
        raise TypeError(
            f"from_dlpack requires an object with __dlpack__; got "
            f"{type(x).__name__}"
        )
    if copy is False:
        raise ValueError(
            "from_dlpack(copy=False) is not supported: chunked arrays "
            "always import host data by copy (the plan may compute after "
            "the exporter's buffer changes)"
        )
    if device is not None:
        raise ValueError(
            "from_dlpack(device=...) is not supported: arrays are placed "
            "by the executor at compute time"
        )
    try:
        host = np.from_dlpack(x)
    except BufferError:
        # some exporters refuse read-only buffers (DLPack cannot signal
        # readonly); the import copies unconditionally, so a plain host
        # conversion is just as safe — but only when numpy genuinely
        # converts (an object-dtype wrap means it could not)
        host = np.asarray(x)
        if host.dtype == object:
            raise
    return asarray(np.array(host, copy=True), chunks=chunks, spec=spec)
