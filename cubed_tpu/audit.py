"""``python -m cubed_tpu.audit`` — post-hoc invariant auditor CLI.

Thin entry point over :mod:`cubed_tpu.runtime.audit`; see that module for
the invariant catalogue and docs/reliability.md for the runbook.
"""

from .runtime.audit import main

if __name__ == "__main__":
    raise SystemExit(main())
