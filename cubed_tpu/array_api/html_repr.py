"""Notebook HTML repr with a chunk-grid SVG.

Reference parity: cubed's vendored dask SVG widgets
(cubed/vendor/dask/array/svg.py, array_object._repr_html_); reimplemented
minimally from scratch.
"""

from __future__ import annotations

from ..utils import memory_repr


def _grid_svg(chunks, max_px: int = 240) -> str:
    """Draw the chunk grid of the trailing (up to) 2 dims."""
    if len(chunks) == 0:
        return ""
    if len(chunks) == 1:
        rows, cols = (1,), chunks[0]
    else:
        rows, cols = chunks[-2], chunks[-1]
    total_h = sum(rows)
    total_w = sum(cols)
    if total_h == 0 or total_w == 0:
        return ""
    scale = max_px / max(total_h, total_w)
    h, w = total_h * scale, total_w * scale
    lines = [
        f'<svg width="{w + 2:.0f}" height="{h + 2:.0f}" '
        'style="stroke:#333;fill:#8fbcbb;fill-opacity:0.35">',
        f'<rect x="1" y="1" width="{w:.1f}" height="{h:.1f}" />',
    ]
    y = 0.0
    for r in rows[:-1]:
        y += r * scale
        lines.append(f'<line x1="1" y1="{y + 1:.1f}" x2="{w + 1:.1f}" y2="{y + 1:.1f}" />')
    x = 0.0
    for c in cols[:-1]:
        x += c * scale
        lines.append(f'<line x1="{x + 1:.1f}" y1="1" x2="{x + 1:.1f}" y2="{h + 1:.1f}" />')
    lines.append("</svg>")
    return "\n".join(lines)


def array_html_repr(arr) -> str:
    chunks = arr.chunks
    rows = [
        ("Array", f"{arr.shape}", f"{arr.chunksize}"),
        ("Bytes", memory_repr(arr.nbytes), memory_repr(arr.chunkmem)),
        ("Count", f"{arr.npartitions} chunks", f"dtype: {arr.dtype}"),
    ]
    table = "".join(
        f"<tr><th>{a}</th><td>{b}</td><td>{c}</td></tr>" for a, b, c in rows
    )
    return f"""
<div style="display:flex;align-items:center;gap:16px;font-family:monospace">
  <table>
    <tr><th></th><th>Array</th><th>Chunk</th></tr>
    {table}
  </table>
  {_grid_svg(chunks)}
</div>
"""
