"""Cross-run regression attribution: ``python -m cubed_tpu.regress``.

Reads the durable run archive (``runs.jsonl`` written under
``Spec(run_history=...)`` / the service's ``service_dir``), picks the
compute to explain (``--compute``, default: the latest compute record),
finds its baseline (``--baseline``, default: the most recent earlier OK
run with the SAME plan structural fingerprint), and prints the
bucket-by-bucket / per-op diff that names what got slower
(:func:`~cubed_tpu.observability.analytics.regression_diff`).

Exit codes are CI-gate friendly: ``0`` no regression, ``1`` the run
regressed past the 1.10x wall-clock threshold, ``2`` the diff could not
be made (no archive, no matching record, no comparable baseline).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from .observability.analytics import regression_diff, render_regression
from .observability.runhistory import find_baseline, load_runs

#: operator convenience: point the CLI at an archive once per shell
HISTORY_ENV_VAR = "CUBED_TPU_RUN_HISTORY"


def _pick_current(records: list, compute_id: Optional[str]) -> Optional[dict]:
    computes = [r for r in records if r.get("kind") == "compute"]
    if compute_id is not None:
        for rec in reversed(computes):
            if rec.get("compute_id") == compute_id:
                return rec
        return None
    # latest compute that carries a decomposition (diffable); fall back
    # to the latest compute at all so the error names what is missing
    for rec in reversed(computes):
        if rec.get("buckets"):
            return rec
    return computes[-1] if computes else None


def _pick_baseline(
    records: list, current: dict, baseline_id: Optional[str]
) -> Optional[dict]:
    if baseline_id is not None:
        for rec in reversed(records):
            if (
                rec.get("kind") == "compute"
                and rec.get("compute_id") == baseline_id
            ):
                return rec
        return None
    return find_baseline(
        records,
        current.get("fingerprint"),
        before_ts=current.get("ts"),
        exclude_compute_id=current.get("compute_id"),
    )


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cubed_tpu.regress", description=__doc__
    )
    parser.add_argument(
        "--history",
        default=os.environ.get(HISTORY_ENV_VAR),
        help="run-history directory holding runs.jsonl (default: "
        f"${HISTORY_ENV_VAR})",
    )
    parser.add_argument(
        "--compute", default=None,
        help="compute id to explain (default: latest archived compute)",
    )
    parser.add_argument(
        "--baseline", default=None,
        help="baseline compute id (default: most recent earlier OK run "
        "with the same plan fingerprint)",
    )
    parser.add_argument(
        "--json", action="store_true", dest="as_json",
        help="emit the regression diff as JSON instead of the report",
    )
    args = parser.parse_args(argv)

    if not args.history:
        print(
            "no run-history directory: pass --history or set "
            f"${HISTORY_ENV_VAR}",
            file=sys.stderr,
        )
        return 2
    records, bad = load_runs(args.history)
    if not records:
        print(
            f"no archive records under {args.history!r} "
            f"({bad} unreadable line(s))",
            file=sys.stderr,
        )
        return 2

    current = _pick_current(records, args.compute)
    if current is None:
        print(
            f"no compute record {args.compute!r} in the archive",
            file=sys.stderr,
        )
        return 2
    if not current.get("buckets"):
        print(
            f"compute {current.get('compute_id')!r} carries no bucket "
            "decomposition (it ran without a trace) — nothing to diff",
            file=sys.stderr,
        )
        return 2
    baseline = _pick_baseline(records, current, args.baseline)
    if baseline is None:
        print(
            "no comparable baseline (same fingerprint, earlier, OK, "
            "with a decomposition) for compute "
            f"{current.get('compute_id')!r}",
            file=sys.stderr,
        )
        return 2

    reg = regression_diff(baseline, current)
    if args.as_json:
        json.dump(reg, sys.stdout, indent=1, default=str)
        sys.stdout.write("\n")
    else:
        sys.stdout.write(render_regression(reg))
    return 1 if reg.get("regressed") else 0


if __name__ == "__main__":
    sys.exit(main())
