"""TraceCollector unit tests: clock alignment (handshake + latency
estimate), bounded span buffers, the live straggler watch, and the merged
export's lane/event structure — driven with synthetic events so every edge
is deterministic."""

from __future__ import annotations

import json
import os
import time

import networkx as nx
import pytest

from cubed_tpu.observability import accounting
from cubed_tpu.observability.accounting import task_scope
from cubed_tpu.observability.collect import (
    TraceCollector,
    decisions_since,
    record_decision,
    record_sample,
    samples_since,
)
from cubed_tpu.observability.metrics import get_registry
from cubed_tpu.runtime.types import (
    ComputeEndEvent,
    ComputeStartEvent,
    TaskEndEvent,
)


def _start_event(compute_id="c-test"):
    return ComputeStartEvent(nx.MultiDiGraph(), compute_id=compute_id)


def _task_event(op="op-a", chunk="0.0", start=None, end=None, pid=None,
                worker=None, spans=None, spans_dropped=None, result=None):
    now = time.time()
    return TaskEndEvent(
        array_name=op,
        chunk_key=chunk,
        function_start_tstamp=start if start is not None else now - 0.01,
        function_end_tstamp=end if end is not None else now,
        task_result_tstamp=result,
        pid=pid,
        worker=worker,
        spans=spans,
        spans_dropped=spans_dropped,
    )


def _events_by_lane(doc):
    meta = {e["tid"]: e["args"]["name"] for e in doc if e.get("ph") == "M"}
    out: dict = {}
    for e in doc:
        if e.get("ph") == "M":
            continue
        out.setdefault(meta.get(e.get("tid")), []).append(e)
    return out


# ---------------------------------------------------------------------------
# clock alignment
# ---------------------------------------------------------------------------


def test_handshake_offset_aligns_fleet_worker_spans(tmp_path):
    """Spans from a worker whose clock is 5s behind land on the client
    timeline when the executor stats carry its handshake offset."""
    col = TraceCollector(trace_dir=None)
    col.on_compute_start(_start_event())
    now = time.time()
    skew = -5.0  # the worker's clock reads 5s behind the client's
    span = {"name": "storage_read", "ts": now + skew - 0.008,
            "dur": 0.005, "cat": "storage"}
    col.on_task_end(
        _task_event(start=now + skew - 0.01, end=now + skew, pid=12345,
                    worker="w1", spans=[span], result=now)
    )
    col.on_compute_end(
        ComputeEndEvent(
            nx.MultiDiGraph(),
            executor_stats={
                "workers": {"w1": {"clock_offset": 5.0, "clock_rtt": 0.002}}
            },
        )
    )
    offs = col.clock_offsets()
    assert offs["w1"]["source"] == "handshake"
    assert offs["w1"]["offset"] == 5.0
    events = col.merged_tracer().events
    task = next(e for e in events if e["cat"] == "task")
    sub = next(e for e in events if e["cat"] == "storage")
    # aligned within the handshake's accuracy, not 5 seconds off
    assert abs(task["ts"] - (now - 0.01)) < 0.01
    assert abs(sub["ts"] - (now - 0.008)) < 0.01
    assert task["lane"] == "worker w1" and sub["lane"] == "worker w1"


def test_latency_estimate_aligns_unlabelled_remote_process():
    """With no handshake (multiprocess pool), the min result-shipping
    delta estimates the offset; a big skew is corrected."""
    col = TraceCollector(trace_dir=None)
    col.on_compute_start(_start_event())
    now = time.time()
    skew = -3.0
    for i in range(5):
        col.on_task_end(
            _task_event(chunk=str(i), start=now + skew - 0.01,
                        end=now + skew, pid=99999, result=now + 0.001 * i)
        )
    col.on_compute_end(ComputeEndEvent(nx.MultiDiGraph()))
    offs = col.clock_offsets()
    assert offs["pid-99999"]["source"] == "latency"
    assert offs["pid-99999"]["offset"] == pytest.approx(3.0, abs=0.05)


def test_same_clock_latency_noise_is_not_treated_as_skew():
    """Sub-threshold shipping latency (same-host pool) must not warp
    timestamps."""
    col = TraceCollector(trace_dir=None)
    col.on_compute_start(_start_event())
    now = time.time()
    col.on_task_end(
        _task_event(start=now - 0.01, end=now, pid=99999, result=now + 0.004)
    )
    col.on_compute_end(ComputeEndEvent(nx.MultiDiGraph()))
    assert col.clock_offsets()["pid-99999"] == {
        "offset": 0.0, "source": "local"
    }


def test_client_pid_needs_no_offset():
    col = TraceCollector(trace_dir=None)
    col.on_compute_start(_start_event())
    col.on_task_end(_task_event(pid=os.getpid()))
    assert col.clock_offsets()["client"]["offset"] == 0.0


def test_skewed_worker_spans_order_correctly_after_alignment():
    """Two workers skewed in opposite directions: after alignment their
    spans interleave in true execution order within ~1 RTT."""
    col = TraceCollector(trace_dir=None)
    col.on_compute_start(_start_event())
    now = time.time()
    rtt = 0.004
    # true order: w1's task ran 0-10ms, w2's ran 20-30ms; raw timestamps
    # would order them the other way around
    col.on_task_end(
        _task_event(chunk="a", start=now + 2.0, end=now + 2.01,
                    worker="w1", result=now + 0.012)
    )
    col.on_task_end(
        _task_event(chunk="b", start=now - 3.0 + 0.02, end=now - 3.0 + 0.03,
                    worker="w2", result=now + 0.032)
    )
    col.on_compute_end(
        ComputeEndEvent(
            nx.MultiDiGraph(),
            executor_stats={
                "workers": {
                    "w1": {"clock_offset": -2.0, "clock_rtt": rtt},
                    "w2": {"clock_offset": 3.0, "clock_rtt": rtt},
                }
            },
        )
    )
    events = [e for e in col.merged_tracer().events if e["cat"] == "task"]
    by_chunk = {e["args"]["chunk"]: e for e in events}
    # aligned: w1's span ends before w2's starts (modulo one RTT)
    assert (
        by_chunk["a"]["ts"] + by_chunk["a"]["dur"]
        <= by_chunk["b"]["ts"] + rtt
    )


# ---------------------------------------------------------------------------
# bounded buffers
# ---------------------------------------------------------------------------


def test_task_scope_span_buffer_is_bounded():
    with task_scope() as scope:
        for i in range(accounting.MAX_TASK_SPANS + 25):
            scope.add_span(f"s{i}", 0.0, 1.0)
    assert len(scope.spans) == accounting.MAX_TASK_SPANS
    assert scope.spans_dropped == 25
    stats = scope.stats()
    assert stats["spans_dropped"] == 25
    assert len(stats["spans"]) == accounting.MAX_TASK_SPANS


def test_spans_dropped_reaches_the_metrics_registry():
    before = get_registry().counter("spans_dropped").value
    col = TraceCollector(trace_dir=None)
    col.on_compute_start(_start_event())
    col.on_task_end(_task_event(spans_dropped=7))
    assert get_registry().counter("spans_dropped").value == before + 7


def test_task_record_retention_is_bounded_and_counted():
    col = TraceCollector(trace_dir=None, max_task_records=3)
    col.on_compute_start(_start_event())
    for i in range(5):
        col.on_task_end(_task_event(chunk=str(i)))
    assert len(col._records) == 3
    assert col.records_dropped == 2


def test_scope_span_records_error_and_noops_without_scope():
    with accounting.spans_scoped(True):
        # no scope: nothing recorded, nothing raised
        with accounting.scope_span("outside"):
            pass
        with task_scope() as scope:
            with pytest.raises(ValueError):
                with accounting.scope_span("fails", cat="storage"):
                    raise ValueError("boom")
    assert len(scope.spans) == 1
    span = scope.spans[0]
    assert span["name"] == "fails"
    assert span["attrs"]["error"] is True
    assert span["attrs"]["error_type"] == "ValueError"


def test_scope_span_records_nothing_while_disarmed():
    # recording is pay-for-what-you-watch: no collector armed it, so a
    # task scope buffers nothing and ships no span payload
    assert not accounting.spans_enabled()
    with task_scope() as scope:
        with accounting.scope_span("storage_read", cat="storage"):
            pass
    assert scope.spans == []
    assert scope.spans_dropped == 0
    with accounting.spans_scoped(True):
        assert accounting.spans_enabled()
        with task_scope() as scope:
            with accounting.scope_span("storage_read", cat="storage"):
                pass
        assert [s["name"] for s in scope.spans] == ["storage_read"]
    assert not accounting.spans_enabled()


def test_spans_env_var_wins_over_scoped_arming(monkeypatch):
    monkeypatch.setenv(accounting.SPANS_ENV_VAR, "1")
    assert accounting.spans_enabled()
    # wire mirroring reflects the effective state
    assert accounting.spans_wire() is True
    monkeypatch.setenv(accounting.SPANS_ENV_VAR, "0")
    with accounting.spans_scoped(True):
        # operator's explicit off wins over programmatic arming
        assert not accounting.spans_enabled()


# ---------------------------------------------------------------------------
# straggler watch
# ---------------------------------------------------------------------------


def test_live_straggler_watch_counts_and_records(caplog):
    before = get_registry().counter("stragglers_detected").value
    col = TraceCollector(trace_dir=None, straggler_factor=3.0,
                         straggler_min_s=0.05, straggler_min_tasks=5)
    col.on_compute_start(_start_event())
    now = time.time()
    for i in range(6):
        col.on_task_end(
            _task_event(chunk=str(i), start=now, end=now + 0.02)
        )
    import logging

    with caplog.at_level(logging.WARNING, logger="cubed_tpu"):
        col.on_task_end(
            _task_event(chunk="slow", start=now, end=now + 1.0)
        )
    assert get_registry().counter("stragglers_detected").value == before + 1
    assert any("straggler" in r.message for r in caplog.records)
    tail = decisions_since(now - 1)
    assert any(
        d["kind"] == "straggler" and d["chunk"] == "slow" for d in tail
    )
    # the post-hoc table agrees with the live flag
    rows = col.stragglers()
    assert rows and rows[0]["chunk"] == "slow"
    assert rows[0]["factor"] > 3.0


def test_fast_ops_produce_no_stragglers():
    before = get_registry().counter("stragglers_detected").value
    col = TraceCollector(trace_dir=None)
    col.on_compute_start(_start_event())
    now = time.time()
    for i in range(20):
        col.on_task_end(_task_event(chunk=str(i), start=now, end=now + 0.01))
    assert get_registry().counter("stragglers_detected").value == before
    assert col.stragglers() == []


# ---------------------------------------------------------------------------
# merged export
# ---------------------------------------------------------------------------


def test_export_merges_decisions_and_samples_and_is_loadable(tmp_path):
    col = TraceCollector(trace_dir=str(tmp_path))
    col.on_compute_start(_start_event("c-exp"))
    record_decision("retry", op="op-a", chunk="0.0", delay_s=0.1)
    record_sample(rss=123456789, pressure=1)
    col.on_task_end(
        _task_event(spans=[{"name": "kernel_apply", "ts": time.time(),
                            "dur": 0.001, "cat": "kernel"}])
    )
    col.on_compute_end(ComputeEndEvent(nx.MultiDiGraph()))
    assert col.trace_path == str(tmp_path / "trace-c-exp.json")
    doc = json.load(open(col.trace_path))
    lanes = _events_by_lane(doc["traceEvents"])
    assert any(e["name"] == "retry" for e in lanes.get("scheduler", []))
    assert any(
        e["ph"] == "C" and e["name"] == "rss_bytes"
        for e in lanes.get("memory", [])
    )
    client = lanes.get("client tasks", [])
    assert any(e.get("cat") == "kernel" for e in client)
    assert any(e.get("cat") == "task" for e in client)
    assert samples_since(0)  # the ring kept the sample


def test_execute_with_stats_ships_spans_pid_and_worker_label():
    from cubed_tpu.runtime.utils import execute_with_stats

    def body(m):
        with accounting.scope_span("storage_read", cat="storage", key="0.0"):
            pass
        return m

    accounting.set_process_label("test-worker")
    try:
        with accounting.spans_scoped(True):
            _, stats = execute_with_stats(body, ("op-x", 0, 0))
    finally:
        accounting.set_process_label(None)
    assert stats["pid"] == os.getpid()
    assert stats["worker"] == "test-worker"
    assert [s["name"] for s in stats["spans"]] == ["storage_read"]
    assert stats["spans_dropped"] == 0
    # the stats dict still builds a TaskEndEvent directly
    TaskEndEvent(array_name="op-x", **stats)


def test_failed_task_spans_ride_the_exception_to_the_trace(tmp_path):
    """A raising task's span buffer lands on the merged trace: the buffer
    rides the exception (surviving a pickle round-trip, like the pool and
    fleet channels give it) and record_failed_task merges it with
    error=True on the failing worker's lane."""
    import pickle

    from cubed_tpu.observability.collect import record_failed_task
    from cubed_tpu.runtime.utils import execute_with_stats

    def body(m):
        with accounting.scope_span("storage_read", cat="storage", key="0.0"):
            pass
        raise OSError("disk on fire")

    with accounting.spans_scoped(True):
        with pytest.raises(OSError) as excinfo:
            execute_with_stats(body, ("op-f", 0, 0))
    stats = excinfo.value.cubed_tpu_task_stats
    assert stats["error_type"] == "OSError"
    assert [s["name"] for s in stats["spans"]] == ["storage_read"]
    assert stats["function_end_tstamp"] >= stats["function_start_tstamp"]

    # the attribute survives pickling (how it crosses the pool boundary)
    exc = pickle.loads(pickle.dumps(excinfo.value))
    assert exc.cubed_tpu_task_stats["spans"]

    col = TraceCollector(trace_dir=str(tmp_path))
    col.on_compute_start(_start_event("c-fail"))
    record_failed_task("op-f", "(op-f, 0, 0)", 0, exc)
    col.on_compute_end(ComputeEndEvent(nx.MultiDiGraph()))
    doc = json.load(open(col.trace_path))
    lanes = _events_by_lane(doc["traceEvents"])
    client = lanes.get("client tasks", [])
    failed = [e for e in client if e.get("cat") == "task"
              and e["args"].get("error")]
    assert failed and failed[0]["args"]["error_type"] == "OSError"
    assert any(e["name"] == "storage_read" for e in client)


def test_failed_task_without_stats_is_a_noop():
    from cubed_tpu.observability.collect import (
        oob_tasks_since,
        record_failed_task,
    )

    t0 = time.time()
    record_failed_task("op", "0.0", 0, ValueError("no stats attached"))
    assert [t for t in oob_tasks_since(t0) if t["op"] == "op"] == []


def test_repair_spans_reach_the_merged_trace(tmp_path):
    from cubed_tpu.observability.collect import record_repair_spans

    col = TraceCollector(trace_dir=str(tmp_path))
    col.on_compute_start(_start_event("c-rep"))
    with accounting.spans_scoped(True):
        with task_scope() as scope:
            with accounting.scope_span(
                "recompute_repair", cat="repair", chunk="0.0"
            ):
                pass
    record_repair_spans("0.0", "/store/x", scope.stats())
    col.on_compute_end(ComputeEndEvent(nx.MultiDiGraph()))
    doc = json.load(open(col.trace_path))
    lanes = _events_by_lane(doc["traceEvents"])
    client = lanes.get("client tasks", [])
    assert any(
        e["name"] == "recompute_repair" and e.get("cat") == "repair"
        for e in client
    )
