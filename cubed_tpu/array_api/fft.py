"""Array-API ``fft`` extension namespace — beyond the reference (which has
no fft extension; its array-api surface stops at the core functions).

Chunked-transform semantics match dask's: the transform axis is rechunked
to a single chunk (the plan-time memory bound prices that chunk, so an
oversized axis fails loudly before anything runs) while every other axis
stays chunked; N-d transforms apply separably, one axis at a time, so at
most ONE axis is ever gathered per op. Per-block kernels are
``nxp.fft.*`` calls — on the TPU executor each is one XLA FFT op that
jits/vmaps and joins fused segments.
"""

from __future__ import annotations

import numpy as np

from ..backend_array_api import nxp
from ..core.ops import general_blockwise, rechunk
from .dtypes import (
    _complex_floating_dtypes,
    _floating_dtypes,
    _real_floating_dtypes,
    complex64,
    complex128,
    float32,
    float64,
)
from .manipulation_functions import roll

__all__ = [
    "fft", "ifft", "fftn", "ifftn", "rfft", "irfft", "rfftn", "irfftn",
    "hfft", "ihfft", "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = (None, "backward", "ortho", "forward")


def _complex_dtype_for(dt):
    return complex64 if dt in (float32, complex64) else complex128


def _real_dtype_for(dt):
    return float32 if dt in (float32, complex64) else float64


def _fft_axis_op(x, axis, out_len, out_dtype, kernel, op_name):
    """Apply a per-block 1-d transform along ``axis`` (gathered to one
    chunk); the output grid matches x's with ``axis`` re-sized."""
    axis = axis % x.ndim
    if len(x.chunks[axis]) > 1:
        x = rechunk(x, {axis: x.shape[axis]})
    out_shape = tuple(
        out_len if d == axis else s for d, s in enumerate(x.shape)
    )
    out_chunks = tuple(
        (out_len,) if d == axis else c for d, c in enumerate(x.chunks)
    )
    x_name = x.name

    def bf(out_key):
        return ((x_name, *out_key[1:]),)

    # fusable=False: XLA:CPU's fft thunk RET_CHECKs a dim0-major input
    # layout (fft_thunk.cc:167) and a fused producer (e.g. ifft(fft(x))
    # in one segment) can hand it a transposed layout — observed on a
    # 4-device virtual mesh. Standalone programs always see default
    # layouts; the transform is compute-dominated, so the lost
    # elementwise fusion is noise.
    return general_blockwise(
        kernel, bf, x,
        shape=out_shape,
        dtype=np.dtype(out_dtype),
        chunks=out_chunks,
        fusable=False,
        op_name=op_name,
    )


def _check(x, fname, real_ok=True, complex_ok=True):
    allowed = ()
    if real_ok:
        allowed += _real_floating_dtypes
    if complex_ok:
        allowed += _complex_floating_dtypes
    if x.dtype not in allowed:
        kinds = " or ".join(
            k for k, ok in (("real", real_ok), ("complex", complex_ok)) if ok
        )
        raise TypeError(f"{fname} requires a {kinds} floating-point dtype")
    if x.ndim == 0:
        raise ValueError(f"{fname} requires at least 1 dimension")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"invalid norm: {norm!r}")
    return norm or "backward"


def _check_axis(x, axis, fname):
    if not -x.ndim <= axis < x.ndim:
        raise IndexError(
            f"{fname}: axis {axis} is out of bounds for array of "
            f"dimension {x.ndim}"
        )
    return axis


def fft(x, /, *, n=None, axis=-1, norm="backward"):
    _check(x, "fft")
    _check_axis(x, axis, "fft")
    norm = _check_norm(norm)
    out_n = n if n is not None else x.shape[axis % x.ndim]
    dt = _complex_dtype_for(x.dtype)
    return _fft_axis_op(
        x, axis, out_n, dt,
        lambda a: nxp.fft.fft(a, n=out_n, axis=axis, norm=norm), "fft",
    )


def ifft(x, /, *, n=None, axis=-1, norm="backward"):
    _check(x, "ifft")
    _check_axis(x, axis, "ifft")
    norm = _check_norm(norm)
    out_n = n if n is not None else x.shape[axis % x.ndim]
    dt = _complex_dtype_for(x.dtype)
    return _fft_axis_op(
        x, axis, out_n, dt,
        lambda a: nxp.fft.ifft(a, n=out_n, axis=axis, norm=norm), "ifft",
    )


def rfft(x, /, *, n=None, axis=-1, norm="backward"):
    _check(x, "rfft", complex_ok=False)
    _check_axis(x, axis, "rfft")
    norm = _check_norm(norm)
    in_n = n if n is not None else x.shape[axis % x.ndim]
    out_n = in_n // 2 + 1
    dt = _complex_dtype_for(x.dtype)
    return _fft_axis_op(
        x, axis, out_n, dt,
        lambda a: nxp.fft.rfft(a, n=in_n, axis=axis, norm=norm), "rfft",
    )


def irfft(x, /, *, n=None, axis=-1, norm="backward"):
    _check(x, "irfft")
    _check_axis(x, axis, "irfft")
    norm = _check_norm(norm)
    out_n = n if n is not None else 2 * (x.shape[axis % x.ndim] - 1)
    dt = _real_dtype_for(x.dtype)
    return _fft_axis_op(
        x, axis, out_n, dt,
        lambda a: nxp.fft.irfft(a, n=out_n, axis=axis, norm=norm), "irfft",
    )


def hfft(x, /, *, n=None, axis=-1, norm="backward"):
    _check(x, "hfft")
    _check_axis(x, axis, "hfft")
    norm = _check_norm(norm)
    out_n = n if n is not None else 2 * (x.shape[axis % x.ndim] - 1)
    dt = _real_dtype_for(x.dtype)
    return _fft_axis_op(
        x, axis, out_n, dt,
        lambda a: nxp.fft.hfft(a, n=out_n, axis=axis, norm=norm), "hfft",
    )


def ihfft(x, /, *, n=None, axis=-1, norm="backward"):
    _check(x, "ihfft", complex_ok=False)
    _check_axis(x, axis, "ihfft")
    norm = _check_norm(norm)
    in_n = n if n is not None else x.shape[axis % x.ndim]
    out_n = in_n // 2 + 1
    dt = _complex_dtype_for(x.dtype)
    return _fft_axis_op(
        x, axis, out_n, dt,
        lambda a: nxp.fft.ihfft(a, n=in_n, axis=axis, norm=norm), "ihfft",
    )


def _resolve_axes(x, s, axes, fname):
    if axes is None:
        # numpy's convention: s without axes means the LAST len(s) axes,
        # expressed negatively so an over-long s lands out of bounds below
        axes = (
            tuple(range(x.ndim)) if s is None else tuple(range(-len(s), 0))
        )
    for a in axes:
        _check_axis(x, a, fname)
    axes = tuple(a % x.ndim for a in axes)
    if s is None:
        s = tuple(x.shape[a] for a in axes)
    if len(s) != len(axes):
        raise ValueError("s and axes must have the same length")
    return s, axes


def fftn(x, /, *, s=None, axes=None, norm="backward"):
    _check(x, "fftn")
    s, axes = _resolve_axes(x, s, axes, "fftn")
    out = x
    for n, a in zip(s, axes):  # separable: one gathered axis per op
        out = fft(out, n=n, axis=a, norm=norm)
    return out


def ifftn(x, /, *, s=None, axes=None, norm="backward"):
    _check(x, "ifftn")
    s, axes = _resolve_axes(x, s, axes, "ifftn")
    out = x
    for n, a in zip(s, axes):
        out = ifft(out, n=n, axis=a, norm=norm)
    return out


def rfftn(x, /, *, s=None, axes=None, norm="backward"):
    _check(x, "rfftn", complex_ok=False)
    s, axes = _resolve_axes(x, s, axes, "rfftn")
    out = rfft(x, n=s[-1], axis=axes[-1], norm=norm)
    for n, a in zip(s[:-1], axes[:-1]):
        out = fft(out, n=n, axis=a, norm=norm)
    return out


def irfftn(x, /, *, s=None, axes=None, norm="backward"):
    _check(x, "irfftn")
    s_given = s is not None
    s, axes = _resolve_axes(x, s, axes, "irfftn")
    if not s_given:
        # default s: the last transformed axis inverts to 2*(m-1)
        s = s[:-1] + (2 * (x.shape[axes[-1]] - 1),)
    out = x
    for n, a in zip(s[:-1], axes[:-1]):
        out = ifft(out, n=n, axis=a, norm=norm)
    return irfft(out, n=s[-1], axis=axes[-1], norm=norm)


def fftfreq(n, /, *, d=1.0, dtype=None, device=None, spec=None):
    """Sample frequencies: [0, 1, ..., (n-1)//2, -(n//2), ..., -1]/(n·d),
    composed from chunked arange + where (no host-side materialization)."""
    from .creation_functions import arange, asarray
    from .elementwise_functions import divide, less, subtract
    from .searching_functions import where

    dt = np.dtype(dtype) if dtype is not None else np.dtype(np.float64)
    if dt not in _real_floating_dtypes:
        raise ValueError("fftfreq requires a real floating-point dtype")
    i = arange(n, dtype=dt, spec=spec)
    folded = where(
        less(i, asarray((n + 1) // 2, dtype=dt, spec=spec)),
        i,
        subtract(i, asarray(n, dtype=dt, spec=spec)),
    )
    return divide(folded, asarray(n * d, dtype=dt, spec=spec))


def rfftfreq(n, /, *, d=1.0, dtype=None, device=None, spec=None):
    from .creation_functions import arange, asarray
    from .elementwise_functions import divide

    dt = np.dtype(dtype) if dtype is not None else np.dtype(np.float64)
    if dt not in _real_floating_dtypes:
        raise ValueError("rfftfreq requires a real floating-point dtype")
    i = arange(n // 2 + 1, dtype=dt, spec=spec)
    return divide(i, asarray(n * d, dtype=dt, spec=spec))


def fftshift(x, /, *, axes=None):
    if x.dtype not in _floating_dtypes:
        raise TypeError("fftshift requires a floating-point dtype")
    if axes is None:
        axes = tuple(range(x.ndim))
    elif isinstance(axes, int):
        axes = (axes,)
    out = x
    for a in axes:
        _check_axis(x, a, "fftshift")
        out = roll(out, x.shape[a % x.ndim] // 2, axis=a % x.ndim)
    return out


def ifftshift(x, /, *, axes=None):
    if x.dtype not in _floating_dtypes:
        raise TypeError("ifftshift requires a floating-point dtype")
    if axes is None:
        axes = tuple(range(x.ndim))
    elif isinstance(axes, int):
        axes = (axes,)
    out = x
    for a in axes:
        _check_axis(x, a, "ifftshift")
        out = roll(out, -(x.shape[a % x.ndim] // 2), axis=a % x.ndim)
    return out
