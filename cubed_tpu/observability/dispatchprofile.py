"""Control-plane self-observation: the coordinator dispatch profiler and
the instrumented hot-lock wrapper.

The runtime's single-threaded dispatch path (``map_unordered``'s loop plus,
on the distributed executor, ``Coordinator.submit`` running inline on it)
is the one shared component every task crosses — ``measure_fleet_scaling``
shows it saturating long before the fleet does. Task-side instrumentation
(spans, task stats) cannot see it: the coordinator's time is spent *between*
tasks, pickling/sending/releasing. This module watches the control plane
itself:

- :class:`DispatchProfiler` — a bounded ``sys._current_frames()`` sampling
  profiler (~75 Hz) over the client/coordinator threads for the life of a
  compute. Aggregates folded stacks (flamegraph-ready, hard entry cap with
  an overflow counter), keeps a bounded reservoir of leaf samples for a
  Perfetto ``dispatch profile`` lane, and exports collapsed stacks as
  ``profile-<compute_id>.folded`` in the flight-recorder bundle. **Off by
  default** and a true no-op when off (no thread, no sampling): armed via
  ``Spec(dispatch_profile=True)`` or ``CUBED_TPU_DISPATCH_PROFILE=1``
  (env wins, same precedence as every other arming knob).

- :class:`TimedLock` — a drop-in ``threading.Lock`` wrapper that measures
  contended-acquire wait time (``dispatch_lock_wait_s``) with a per-thread
  accumulator the dispatch ledger reads per submit. The uncontended path
  costs one extra try-acquire. Works under ``threading.Condition`` (the
  coordinator's ``_worker_joined``) via the generic acquire/release
  fallbacks.

Not to be confused with ``observability/profiler.py`` — the JAX **device**
profiler (device traces + per-op device memory); this module profiles the
host-side control plane.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import List, Optional, Tuple

from .metrics import get_registry

#: operator override ("1" forces the profiler on for every compute)
PROFILE_ENV_VAR = "CUBED_TPU_DISPATCH_PROFILE"

#: sampling rate: high enough to resolve per-task dispatch costs at
#: hundreds of tasks/sec, low enough that the sampler itself stays well
#: under the <5% armed-overhead budget the bench gate enforces
DEFAULT_HZ = 75.0

#: hard cap on distinct folded stacks retained — a pathological compute
#: (deep recursion, churning threads) must not grow the dict unboundedly;
#: overflow is counted (``dispatch_profile_overflow``), never silent
MAX_FOLDED_STACKS = 2000

#: frames walked per stack before truncation
MAX_STACK_DEPTH = 48

#: leaf samples retained for the Perfetto "dispatch profile" lane
MAX_LANE_SAMPLES = 1024

#: finished profiles retained for bundles/diagnose, newest-kept
MAX_KEPT_PROFILES = 4

#: thread-name prefixes the sampler skips: task-executing pool threads and
#: the telemetry/profiler machinery itself are not the control plane
EXCLUDE_THREAD_PREFIXES = (
    "ThreadPoolExecutor",  # task bodies on the threads executor
    "telemetry",           # the ~1s telemetry sampler
    "dispatch-profile",    # this profiler's own thread
    "chunk-repair",        # the recompute side pool
)


def profile_enabled(spec=None) -> bool:
    """Whether the dispatch profiler arms for a compute (env > spec > off)."""
    env = os.environ.get(PROFILE_ENV_VAR)
    if env:
        return env == "1"
    if spec is not None:
        armed = getattr(spec, "dispatch_profile", None)
        if armed is not None:
            return bool(armed)
    return False


class DispatchProfiler:
    """Bounded sampling profiler over this process's control-plane threads.

    ``start()`` spawns one daemon thread sampling ``sys._current_frames()``
    at ``hz``; ``stop()`` joins it. Results: :meth:`folded_lines` (collapsed
    stacks, one ``stack count`` line each — feed to any flamegraph tool),
    :meth:`top_stacks` (ranked summary for ``diagnose``), and
    :meth:`lane_samples` (bounded ``(ts, leaf)`` reservoir for the Perfetto
    lane). All aggregation happens on the sampler thread; readers take the
    lock only at export time.
    """

    def __init__(self, hz: float = DEFAULT_HZ):
        self.hz = max(1.0, min(200.0, float(hz)))
        self.samples = 0
        self.overflow = 0
        self.started_at: Optional[float] = None
        self.stopped_at: Optional[float] = None
        self._folded: dict = {}
        self._lane: deque = deque(maxlen=MAX_LANE_SAMPLES)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "DispatchProfiler":
        if self._thread is not None:
            return self
        self.started_at = time.time()
        self._thread = threading.Thread(
            target=self._run, name="dispatch-profiler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> "DispatchProfiler":
        if self._thread is None:
            return self
        self._stop.set()
        self._thread.join(timeout=2.0)
        self._thread = None
        self.stopped_at = time.time()
        return self

    def _run(self) -> None:
        interval = 1.0 / self.hz
        own_tid = threading.get_ident()
        while not self._stop.wait(interval):
            try:
                self._sample_once(own_tid)
            except Exception:
                # the profiler must never take the compute down with it
                pass

    # -- sampling ------------------------------------------------------

    def _sample_once(self, own_tid: int) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        ts = time.time()
        for tid, frame in frames.items():
            if tid == own_tid:
                continue
            name = names.get(tid) or f"thread-{tid}"
            if name.startswith(EXCLUDE_THREAD_PREFIXES):
                continue
            stack: List[str] = []
            f = frame
            while f is not None and len(stack) < MAX_STACK_DEPTH:
                code = f.f_code
                stack.append(
                    f"{os.path.basename(code.co_filename)}:{code.co_name}"
                )
                f = f.f_back
            stack.reverse()  # root-first, the folded convention
            key = name + ";" + ";".join(stack)
            leaf = stack[-1] if stack else name
            with self._lock:
                self.samples += 1
                if key in self._folded:
                    self._folded[key] += 1
                elif len(self._folded) < MAX_FOLDED_STACKS:
                    self._folded[key] = 1
                else:
                    self.overflow += 1
                    get_registry().counter(
                        "dispatch_profile_overflow"
                    ).inc()
                self._lane.append((ts, f"{name}: {leaf}"))

    # -- export --------------------------------------------------------

    def folded(self) -> dict:
        with self._lock:
            return dict(self._folded)

    def folded_lines(self) -> List[str]:
        """Collapsed stacks, one ``stack count`` line each (the format
        ``flamegraph.pl`` / speedscope / inferno all consume)."""
        with self._lock:
            items = sorted(self._folded.items(), key=lambda kv: -kv[1])
        return [f"{stack} {count}" for stack, count in items]

    def top_stacks(self, n: int = 8) -> List[dict]:
        """The ``n`` hottest stacks, leaf-labelled, with sample fractions."""
        with self._lock:
            total = sum(self._folded.values()) or 1
            items = sorted(self._folded.items(), key=lambda kv: -kv[1])[:n]
        out = []
        for stack, count in items:
            parts = stack.split(";")
            out.append({
                "thread": parts[0],
                "leaf": parts[-1] if len(parts) > 1 else parts[0],
                "stack": stack,
                "count": count,
                "fraction": round(count / total, 4),
            })
        return out

    def lane_samples(self) -> List[Tuple[float, str]]:
        """Bounded ``(ts, "thread: leaf")`` reservoir for the trace lane."""
        with self._lock:
            return list(self._lane)

    def summary(self) -> dict:
        """The manifest block bundles/diagnose render."""
        return {
            "samples": self.samples,
            "overflow": self.overflow,
            "distinct_stacks": len(self._folded),
            "hz": self.hz,
            "duration_s": (
                round((self.stopped_at or time.time())
                      - self.started_at, 3)
                if self.started_at else None
            ),
            "top_stacks": self.top_stacks(),
        }


#: finished profiles by compute id (bounded, newest kept) — how the flight
#: recorder and ``diagnose`` find the profile after the compute ended
_profiles: "OrderedDict[str, DispatchProfiler]" = OrderedDict()
_profiles_lock = threading.Lock()


def register_profile(compute_id: str, profiler: DispatchProfiler) -> None:
    with _profiles_lock:
        _profiles[compute_id] = profiler
        _profiles.move_to_end(compute_id)
        while len(_profiles) > MAX_KEPT_PROFILES:
            _profiles.popitem(last=False)


def profile_for(compute_id: Optional[str]) -> Optional[DispatchProfiler]:
    """The finished (or live) profiler for a compute id, or None."""
    if compute_id is None:
        return None
    with _profiles_lock:
        return _profiles.get(compute_id)


class profile_scoped:
    """Arm the dispatch profiler for one compute (``Plan.execute`` enters
    this around ``execute_dag``). A true no-op — no thread, no sampling, no
    allocation beyond this object — unless :func:`profile_enabled` says the
    compute asked for it. The finished profiler is registered under the
    compute id so the flight recorder and ``diagnose`` can find it."""

    def __init__(self, spec=None, compute_id: Optional[str] = None):
        self._spec = spec
        self._compute_id = compute_id
        self.profiler: Optional[DispatchProfiler] = None

    def __enter__(self) -> Optional[DispatchProfiler]:
        if profile_enabled(self._spec):
            self.profiler = DispatchProfiler().start()
            if self._compute_id:
                # registered at START so a mid-compute dump sees the live
                # profiler (bundles on failure, diagnose on a hung compute)
                register_profile(self._compute_id, self.profiler)
        return self.profiler

    def __exit__(self, *exc) -> None:
        if self.profiler is not None:
            self.profiler.stop()


class TimedLock:
    """``threading.Lock`` with contended-wait measurement.

    The dispatch ledger needs "how long did THIS submit wait on the
    coordinator's hot lock": :meth:`reset_thread_wait` zeroes a per-thread
    accumulator, every contended ``acquire`` on that thread adds its wait,
    :meth:`thread_wait_s` reads it back. Cumulative wait also lands on the
    ``dispatch_lock_wait_s`` registry counter so the live surfaces see lock
    pressure without a ledger in flight.

    Implements ``acquire``/``release``/context-manager/``locked``, so
    ``threading.Condition(TimedLock())`` works through the stdlib's generic
    fallbacks — waits during a Condition ``wait_for`` (e.g. the
    coordinator's no-live-worker backfill wait) count as lock wait, which
    is the honest reading: the dispatch path was blocked either way.
    """

    __slots__ = ("_lock", "_tls", "_counter")

    def __init__(self, metric: str = "dispatch_lock_wait_s"):
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._counter = get_registry().counter(metric)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(False):
            return True
        if not blocking:
            return False
        t0 = time.perf_counter()
        ok = self._lock.acquire(True, timeout)
        wait = time.perf_counter() - t0
        self._tls.acc = getattr(self._tls, "acc", 0.0) + wait
        self._counter.inc(wait)
        return ok

    def release(self) -> None:
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def reset_thread_wait(self) -> None:
        self._tls.acc = 0.0

    def thread_wait_s(self) -> float:
        return getattr(self._tls, "acc", 0.0)
