"""General utilities: byte-string parsing, chunk math, nested-structure helpers.

Semantics follow the reference (cubed/utils.py) but are reimplemented from scratch
for a TPU-first stack: memory accounting models HBM tiles rather than worker RSS.
Reference parity: cubed/utils.py:92-312.
"""

from __future__ import annotations

import itertools
import platform
import re
import sys
import threading
from dataclasses import dataclass
from math import prod
from operator import add
from pathlib import Path
from posixpath import join as _urljoin
from resource import RUSAGE_SELF, getrusage
from typing import Any, Iterable, Iterator, Sequence
from urllib.parse import urlsplit, urlunsplit

import numpy as np

# ---------------------------------------------------------------------------
# Plan-node name generation
# ---------------------------------------------------------------------------

#: process-global counter shared by every gensym'd plan identifier
sym_counter = itertools.count()

#: serializes draws from ``sym_counter``: plans are now built concurrently
#: (the multi-tenant compute service accepts submissions from many client
#: threads), and while CPython's ``next()`` on an ``itertools.count`` is
#: atomic today, tests legitimately REASSIGN ``sym_counter`` to pin plan
#: names — a read-swap racing a concurrent draw could mint a duplicate
#: identifier, which would silently alias two arrays' store paths
_sym_lock = threading.Lock()


def gensym(name: str = "op") -> str:
    """A unique plan-node identifier with a FIXED-WIDTH counter.

    Fixed width matters beyond cosmetics: the JAX executor's structural
    cache key canonicalizes these names inside the pickled payload BYTE
    stream, where a name-length change (op-999 vs op-1000) also changes
    pickle length-prefix bytes the rewrite can't see — so two structurally
    identical plans built across a digit boundary would hash differently
    and miss the cache. Nine digits pushes the first boundary past 10^9
    plan nodes per process. One shared helper/counter so op and array node
    name formats can never desynchronize.
    """
    with _sym_lock:
        return f"{name}-{next(sym_counter):09d}"


# ---------------------------------------------------------------------------
# Byte-size parsing and formatting
# ---------------------------------------------------------------------------

_BYTE_UNITS = {
    "": 1,
    "B": 1,
    "KB": 10**3,
    "MB": 10**6,
    "GB": 10**9,
    "TB": 10**12,
    "PB": 10**15,
    "KIB": 2**10,
    "MIB": 2**20,
    "GIB": 2**30,
    "TIB": 2**40,
    "PIB": 2**50,
    # single-letter suffixes are binary, matching common usage ("100M")
    "K": 2**10,
    "M": 2**20,
    "G": 2**30,
    "T": 2**40,
    "P": 2**50,
}

_BYTES_RE = re.compile(r"^\s*([0-9]*\.?[0-9]+)\s*([A-Za-z]*)\s*$")


def convert_to_bytes(value: int | float | str | None) -> int | None:
    """Parse a human byte string (``"2GB"``, ``"100MiB"``, ``"1_000"``) to an int.

    Ints/floats pass through (floats must be integral). Reference parity:
    cubed/utils.py:201-258.
    """
    if value is None:
        return None
    if isinstance(value, (int, np.integer)):
        if value < 0:
            raise ValueError(f"Invalid byte value: {value!r} (negative)")
        return int(value)
    if isinstance(value, float):
        if not value.is_integer() or value < 0:
            raise ValueError(f"Invalid byte value: {value!r}")
        return int(value)
    if isinstance(value, str):
        m = _BYTES_RE.match(value.replace("_", ""))
        if not m:
            raise ValueError(f"Invalid byte string: {value!r}")
        number, unit = m.groups()
        unit = unit.upper()
        if unit not in _BYTE_UNITS:
            raise ValueError(f"Invalid byte unit {unit!r} in {value!r}")
        result = float(number) * _BYTE_UNITS[unit]
        if not float(result).is_integer():
            raise ValueError(f"Byte string {value!r} is not an integral byte count")
        return int(result)
    raise TypeError(f"Cannot convert {type(value)} to bytes")


def memory_repr(num: int | float) -> str:
    """Render a byte count human-readably (``1.5 GB``)."""
    if num < 1000:
        return f"{int(num)} bytes"
    for unit in ("KB", "MB", "GB", "TB", "PB"):
        num /= 1000.0
        if num < 1000.0:
            return f"{num:3.1f} {unit}"
    return f"{num:3.1f} EB"


# ---------------------------------------------------------------------------
# Chunk math
# ---------------------------------------------------------------------------


def itemsize(dtype) -> int:
    """Bytes per element for a dtype (numpy or jax)."""
    return np.dtype(dtype).itemsize


def chunk_memory(dtype, chunksize: Sequence[int]) -> int:
    """Bytes of memory for one chunk of the given dtype and shape."""
    return itemsize(dtype) * prod(int(c) for c in chunksize)


def array_memory(dtype, shape: Sequence[int]) -> int:
    return itemsize(dtype) * prod(int(s) for s in shape)


def to_chunksize(chunkset: tuple[tuple[int, ...], ...]) -> tuple[int, ...]:
    """Collapse a per-dim tuple-of-block-sizes to a single chunk shape.

    Requires regular chunking: in each dimension all blocks equal except a
    possibly-smaller final block. Reference parity: cubed/utils.py (to_chunksize).
    """
    if not _check_regular_chunks(chunkset):
        raise ValueError(f"Array must have regular chunks, but found chunks={chunkset}")
    return tuple(c[0] if len(c) > 0 else 1 for c in chunkset)


def _check_regular_chunks(chunkset: tuple[tuple[int, ...], ...]) -> bool:
    """True if every dim's blocks are uniform except a possibly-smaller last block."""
    for chunks in chunkset:
        if len(chunks) == 0:
            continue
        if len(chunks) == 1:
            continue
        if len(set(chunks[:-1])) > 1:
            return False
        if chunks[-1] > chunks[0]:
            return False
    return True


def get_item(chunks: tuple[tuple[int, ...], ...], idx: tuple[int, ...]) -> tuple[slice, ...]:
    """Convert a block index into the tuple of slices selecting that block."""
    starts = tuple(tuple(accumulate_prepend_zero(c)) for c in chunks)
    return tuple(
        slice(start[i], start[i] + c[i]) for c, start, i in zip(chunks, starts, idx)
    )


def accumulate_prepend_zero(seq: Sequence[int]) -> list[int]:
    out = [0]
    for s in seq:
        out.append(out[-1] + s)
    return out[:-1]


def offset_to_block_id(offset: int, numblocks: Sequence[int]) -> tuple[int, ...]:
    """Linear offset -> nd block index (C order)."""
    return tuple(int(i) for i in np.unravel_index(offset, tuple(numblocks)))


def block_id_to_offset(block_id: Sequence[int], numblocks: Sequence[int]) -> int:
    """nd block index -> linear offset (C order)."""
    return int(np.ravel_multi_index(tuple(block_id), tuple(numblocks)))


def chunk_starts(chunks_1d: Sequence[int]) -> list[int]:
    return accumulate_prepend_zero(chunks_1d)


# ---------------------------------------------------------------------------
# Paths
# ---------------------------------------------------------------------------


def join_path(dir_url: str, child_path: str) -> str:
    """Join a path to a directory that may be a filesystem path or a URL."""
    parts = urlsplit(str(dir_url))
    if parts.scheme in ("", "file"):
        p = Path(str(dir_url).replace("file://", "")) / child_path
        return str(p)
    return urlunsplit(
        (parts.scheme, parts.netloc, _urljoin(parts.path, child_path), parts.query, parts.fragment)
    )


# ---------------------------------------------------------------------------
# Host memory measurement (for the CPU oracle executor; TPU path uses HBM stats)
# ---------------------------------------------------------------------------


def peak_measured_mem() -> int:
    """Peak RSS of this process in bytes.

    On Linux this reads VmHWM from ``/proc/self/status``, NOT
    ``getrusage(RUSAGE_SELF).ru_maxrss``: ru_maxrss survives ``execve``,
    so any worker subprocess spawned from a fat parent (a long test run, a
    big application) inherits the parent's peak as its own floor and the
    measured-memory guarantee reads gigabytes of phantom usage (measured:
    a 3.2 GB parent makes a fresh child report ru_maxrss 3.2 GB while its
    true VmHWM is 167 MB). VmHWM belongs to the mm struct, which exec
    replaces, so it reflects only this program's own footprint."""
    if platform.system() == "Linux":
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmHWM:"):
                        return int(line.split()[1]) * 1024
        except OSError:
            pass
    ru_maxrss = getrusage(RUSAGE_SELF).ru_maxrss
    # ru_maxrss is KiB on Linux, bytes on macOS
    if platform.system() == "Darwin":
        return ru_maxrss
    return ru_maxrss * 1024


def current_measured_mem() -> int | None:
    """Current RSS of this process in bytes, or None when unmeasurable.

    The runtime memory guard (runtime/memory.py) samples this to attribute
    RSS *growth* to running tasks. Like :func:`peak_measured_mem` it reads
    ``/proc/self/status`` (VmRSS) rather than anything rusage-derived —
    there is no instantaneous-RSS rusage field at all, and the guard must
    never inherit a fork/exec parent's footprint as its own. Platforms
    without ``/proc`` return None and the guard stays inactive (tests
    needing it carry the ``mem`` marker and auto-skip there)."""
    if platform.system() != "Linux":
        return None
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


def host_available_mem() -> int | None:
    """``MemAvailable`` from ``/proc/meminfo`` in bytes, or None.

    The memory guard's host-pressure floor: when the whole machine is
    nearly out of memory, per-process accounting is moot — back off."""
    if platform.system() != "Linux":
        return None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return None


# ---------------------------------------------------------------------------
# Nested-structure helpers
# ---------------------------------------------------------------------------


def split_into(iterable: Iterable, sizes: Iterable[int]) -> Iterator[list]:
    """Split *iterable* into sublists of the given sizes; ``None`` = the rest."""
    it = iter(iterable)
    for size in sizes:
        if size is None:
            yield list(it)
            return
        yield list(itertools.islice(it, size))


def map_nested(func, seq):
    """Apply *func* to every non-list element of an arbitrarily nested list."""
    if isinstance(seq, list):
        return [map_nested(func, item) for item in seq]
    return func(seq)


def flatten_nested(seq) -> Iterator:
    if isinstance(seq, (list, tuple)):
        for item in seq:
            yield from flatten_nested(item)
    else:
        yield seq


# ---------------------------------------------------------------------------
# Broadcast trick: constant-chunk arrays with zero storage
# ---------------------------------------------------------------------------


def broadcast_trick(func):
    """Wrap a numpy creation function so the result is a stride-0 broadcast.

    ``ones((1000,1000))`` allocates one element and broadcasts it, so virtual
    full/empty arrays cost no memory until written to. Reference parity:
    cubed/utils.py:296-312.
    """

    def wrapper(shape, *args, **kwargs):
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        meta = func((), *args, **kwargs)
        return np.broadcast_to(meta, shape)

    wrapper.__name__ = getattr(func, "__name__", "broadcast_trick")
    return wrapper


# ---------------------------------------------------------------------------
# Caller-stack provenance for plan nodes
# ---------------------------------------------------------------------------


@dataclass
class StackSummary:
    """A lightweight record of one frame of the user call stack."""

    filename: str
    lineno: int
    name: str
    array_names_to_variable_names: dict[str, str]

    def is_cubed(self) -> bool:
        normalized = self.filename.replace("\\", "/")
        return "/cubed_tpu/" in normalized or normalized.endswith("cubed_tpu")


def extract_stack_summaries(frame, limit: int = 10) -> list[StackSummary]:
    """Walk the caller stack, mapping internal array names to user variable names.

    Inspects each frame's locals for framework arrays so ``visualize()`` can label
    op nodes with the user's own variable names. Reference parity:
    cubed/utils.py:128-198.
    """
    summaries: list[StackSummary] = []
    while frame is not None and len(summaries) < limit:
        name_map = {}
        try:
            for var, val in frame.f_locals.items():
                nm = getattr(val, "name", None)
                if nm is not None and type(nm) is str and hasattr(val, "zarray_maybe_lazy"):
                    name_map[nm] = var
        except Exception:
            pass
        summaries.append(
            StackSummary(
                filename=frame.f_code.co_filename,
                lineno=frame.f_lineno,
                name=frame.f_code.co_name,
                array_names_to_variable_names=name_map,
            )
        )
        frame = frame.f_back
    summaries.reverse()
    return summaries
